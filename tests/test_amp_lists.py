"""Shipped amp op-classification defaults (amp.lists + amp.F).

Mirrors the reference's cast tests
(ref: tests/L0/run_amp/test_basic_casts.py run_layer_test — whitelist
ops are ALWAYS_HALF/ALWAYS_BFLOAT16, blacklist ALWAYS_FLOAT, banned BCE
raises with guidance) against the policy-consulting functional
namespace, plus the out-of-box O1 training claim: a ported reference
model trains under O1 with zero manual registration.
"""

import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.amp import _amp_state
from apex_tpu.amp.lists import (
    BANNED_FUNCS,
    COMPUTE_FUNCS,
    FP32_FUNCS,
    MATCH_INPUT_FUNCS,
    PROMOTE_FUNCS,
    SEQUENCE_CASTS,
    register_defaults,
)

F = amp.F


@pytest.fixture(autouse=True)
def _clean_policy():
    """amp.initialize activates a process-global policy; never leak it
    across tests."""
    prev, prev_banned = _amp_state.get_active(), _amp_state.allow_banned
    yield
    _amp_state.set_active(prev)
    _amp_state.allow_banned = prev_banned


def _o1():
    return amp.OPT_LEVELS["O1"]


def _o4():
    return amp.OPT_LEVELS["O4"]


IN_DTYPES = (jnp.float16, jnp.float32)


class TestBasicCasts:
    """Every classified op, both input dtypes, O1 and O4 — the
    run_layer_test cross product."""

    def _whitelist_cases(self):
        h, b = 8, 4
        x = jnp.ones((b, h))
        w = jnp.ones((h, h)) * 0.1
        k = jnp.ones((3, 3, 2, 2)) * 0.1  # OIHW after transpose below
        img = jnp.ones((2, 3, 8, 8))
        return [
            ("linear", lambda dt: F.linear(x.astype(dt), w.astype(dt))),
            ("matmul", lambda dt: F.matmul(x.astype(dt), w.astype(dt))),
            ("bmm", lambda dt: F.bmm(
                jnp.ones((2, 4, 4), dt), jnp.ones((2, 4, 4), dt))),
            ("einsum", lambda dt: F.einsum(
                "bi,ij->bj", x.astype(dt), w.astype(dt))),
            ("dot", lambda dt: F.dot(x.astype(dt), w.astype(dt))),
            ("conv2d", lambda dt: F.conv2d(
                img.astype(dt), jnp.ones((4, 3, 3, 3), dt) * 0.1)),
            ("conv1d", lambda dt: F.conv1d(
                jnp.ones((2, 3, 16), dt), jnp.ones((4, 3, 3), dt))),
            ("conv_transpose2d", lambda dt: F.conv_transpose2d(
                img.astype(dt), jnp.ones((3, 4, 3, 3), dt), stride=2)),
            ("conv_transpose2d_tuplepad", lambda dt: F.conv_transpose2d(
                img.astype(dt), jnp.ones((3, 4, 3, 3), dt), stride=2,
                padding=(1, 1))),
        ]

    @pytest.mark.parametrize("props,expect", [("O1", jnp.float16),
                                              ("O4", jnp.bfloat16)])
    def test_whitelist_always_compute_dtype(self, props, expect):
        with amp.policy_scope(amp.OPT_LEVELS[props]):
            for name, fn in self._whitelist_cases():
                for dt in IN_DTYPES:
                    out = fn(dt)
                    assert out.dtype == expect, (name, dt, out.dtype)

    def test_blacklist_always_float(self):
        h, b = 8, 4
        x2 = jnp.ones((b, h))
        img = jnp.ones((2, 4, 8, 8))
        tgt = jnp.zeros((b,), jnp.int32)
        cases = [
            ("softmax", lambda dt: F.softmax(x2.astype(dt))),
            ("log_softmax", lambda dt: F.log_softmax(x2.astype(dt))),
            ("softplus", lambda dt: F.softplus(x2.astype(dt))),
            ("gelu", lambda dt: F.gelu(x2.astype(dt))),
            ("logsumexp", lambda dt: F.logsumexp(x2.astype(dt), axis=-1)),
            ("layer_norm", lambda dt: F.layer_norm(x2.astype(dt), h)),
            ("rms_norm", lambda dt: F.rms_norm(x2.astype(dt))),
            ("group_norm", lambda dt: F.group_norm(img.astype(dt), 2)),
            ("batch_norm", lambda dt: F.batch_norm(
                img.astype(dt), training=True)),
            ("normalize", lambda dt: F.normalize(x2.astype(dt))),
            ("cosine_similarity", lambda dt: F.cosine_similarity(
                x2.astype(dt), x2.astype(dt))),
            ("norm", lambda dt: F.norm(x2.astype(dt))),
            ("var", lambda dt: F.var(x2.astype(dt))),
            ("std", lambda dt: F.std(x2.astype(dt))),
            ("cumsum", lambda dt: F.cumsum(x2.astype(dt), axis=0)),
            ("mse_loss", lambda dt: F.mse_loss(
                x2.astype(dt), x2.astype(dt))),
            ("l1_loss", lambda dt: F.l1_loss(
                x2.astype(dt), x2.astype(dt))),
            ("smooth_l1_loss", lambda dt: F.smooth_l1_loss(
                x2.astype(dt), x2.astype(dt))),
            ("cross_entropy", lambda dt: F.cross_entropy(
                x2.astype(dt), tgt)),
            ("nll_loss", lambda dt: F.nll_loss(
                F.log_softmax(x2).astype(dt), tgt)),
            ("kl_div", lambda dt: F.kl_div(
                F.log_softmax(x2).astype(dt), F.softmax(x2).astype(dt))),
            ("binary_cross_entropy_with_logits",
             lambda dt: F.binary_cross_entropy_with_logits(
                 x2.astype(dt), jnp.zeros_like(x2, dt))),
        ]
        for level in ("O1", "O4"):
            with amp.policy_scope(amp.OPT_LEVELS[level]):
                for name, fn in cases:
                    for dt in IN_DTYPES:
                        out = fn(dt)
                        assert out.dtype == jnp.float32, (level, name, dt)

    def test_match_input_ops_preserve_dtype(self):
        with amp.policy_scope(_o1()):
            for name in MATCH_INPUT_FUNCS:
                fn = getattr(F, name)
                for dt in IN_DTYPES:
                    assert fn(jnp.ones((4,), dt)).dtype == dt, name

    def test_promote_widest(self):
        with amp.policy_scope(_o1()):
            a16 = jnp.ones((4,), jnp.float16)
            a32 = jnp.ones((4,), jnp.float32)
            for name in PROMOTE_FUNCS:
                out = getattr(F, name)(a16, a32)
                assert out.dtype == jnp.float32, name
                out = getattr(F, name)(a16, a16)
                assert out.dtype == jnp.float16, name
            assert F.cat([a16, a32]).dtype == jnp.float32
            assert F.stack([a16, a16]).dtype == jnp.float16

    def test_no_policy_is_passthrough(self):
        _amp_state.set_active(None)
        x = jnp.ones((4, 8), jnp.float16)
        assert F.linear(x, jnp.ones((8, 8), jnp.float16)).dtype == jnp.float16
        assert F.softmax(x).dtype == jnp.float16
        # O0 (no compute dtype) is also a passthrough
        with amp.policy_scope(amp.OPT_LEVELS["O0"]):
            assert F.softmax(x).dtype == jnp.float16

    def test_disable_casts_suspends(self):
        with amp.policy_scope(_o1()):
            x = jnp.ones((4, 8), jnp.float32)
            w = jnp.ones((8, 8), jnp.float32)
            assert F.linear(x, w).dtype == jnp.float16
            with amp.disable_casts():
                assert F.linear(x, w).dtype == jnp.float32
            assert F.linear(x, w).dtype == jnp.float16


class TestBanned:
    def test_bce_raises_with_guidance(self):
        with amp.policy_scope(_o1()):
            p = jnp.full((4,), 0.5)
            t = jnp.zeros((4,))
            with pytest.raises(RuntimeError,
                               match="binary_cross_entropy_with_logits"):
                F.binary_cross_entropy(p, t)

    def test_bce_allowed_when_opted_in(self):
        with amp.policy_scope(_o1()):
            _amp_state.allow_banned = True
            p = jnp.full((4,), 0.5)
            out = F.binary_cross_entropy(p, jnp.zeros((4,)))
            np.testing.assert_allclose(
                float(out), -np.log(0.5), rtol=1e-5)

    def test_bce_fine_without_amp(self):
        _amp_state.set_active(None)
        out = F.binary_cross_entropy(jnp.full((4,), 0.5), jnp.zeros((4,)))
        assert np.isfinite(float(out))

    def test_banned_table_entry(self):
        assert BANNED_FUNCS[0][0] == "binary_cross_entropy"
        assert "binary_cross_entropy_with_logits" in BANNED_FUNCS[0][1]


class TestRegisterDefaults:
    def test_applies_tables_to_user_module(self):
        ns = types.SimpleNamespace(
            linear=lambda x, w: x @ w,
            softmax=lambda x: jax.nn.softmax(x),
            add=lambda a, b: a + b,
            unrelated="leave me",
        )
        n = register_defaults(ns, compute_dtype="float16")
        assert n == 3
        x32 = jnp.ones((4, 8), jnp.float32)
        # static decorators: active regardless of policy state
        assert ns.linear(x32, jnp.ones((8, 8))).dtype == jnp.float16
        assert ns.softmax(jnp.ones((4,), jnp.float16)).dtype == jnp.float32
        assert ns.add(jnp.ones((8,), jnp.float16), x32[0]).dtype == jnp.float32
        assert ns.unrelated == "leave me"

    def test_repeated_registration_is_idempotent(self):
        """A second register_defaults (e.g. amp.initialize called
        twice) must not stack a second cast wrapper — wrapped functions
        carry a marker and are skipped; the dense alias of linear gets
        its own single wrapper too."""
        base = lambda x, w: x @ w  # noqa: E731
        ns = types.SimpleNamespace(
            linear=base, dense=base,
            softmax=lambda x: jax.nn.softmax(x),
        )
        n1 = register_defaults(ns, compute_dtype="float16")
        assert n1 == 3                       # linear, dense, softmax
        wrapped_linear, wrapped_dense = ns.linear, ns.dense
        n2 = register_defaults(ns, compute_dtype="float16")
        assert n2 == 0                       # nothing newly rebound
        assert ns.linear is wrapped_linear   # same single wrapper
        assert ns.dense is wrapped_dense
        # behavior unchanged: one cast, fp16 out
        out = ns.linear(jnp.ones((4, 8), jnp.float32), jnp.ones((8, 8)))
        assert out.dtype == jnp.float16

    def test_tables_cover_reference_judgment(self):
        # the reference's core classification must be present
        for name in ("linear", "conv2d", "matmul"):
            assert name in COMPUTE_FUNCS
        for name in ("softmax", "layer_norm", "cross_entropy",
                     "binary_cross_entropy_with_logits"):
            assert name in FP32_FUNCS
        assert "cat" in SEQUENCE_CASTS


class TestO1TrainsOutOfBox:
    def test_ported_model_trains_under_o1(self):
        """A reference-style model written against amp.F trains under
        O1 with no manual registration: whitelist matmuls run fp16,
        losses fp32, loss decreases, grads finite."""
        rng = np.random.RandomState(0)
        Xn = rng.randn(128, 16).astype(np.float32)
        X = jnp.asarray(Xn)
        Y = jnp.asarray((Xn @ rng.randn(16) > 0).astype(np.int64))
        params = {
            "w1": jnp.asarray(rng.randn(32, 16).astype(np.float32) * 0.2),
            "b1": jnp.zeros((32,)),
            "w2": jnp.asarray(rng.randn(2, 32).astype(np.float32) * 0.2),
            "b2": jnp.zeros((2,)),
        }
        params, amp_state = amp.initialize(params, opt_level="O1")

        def model(p, x):
            h = F.relu(F.linear(x, p["w1"], p["b1"]))
            assert h.dtype == jnp.float16   # whitelist took effect
            return F.linear(h, p["w2"], p["b2"])

        def loss_fn(p, x, y):
            loss = F.cross_entropy(model(p, x), y)
            assert loss.dtype == jnp.float32  # blacklist took effect
            return loss

        @jax.jit
        def step(p, scaler_state):
            loss, g = jax.value_and_grad(
                lambda p_: loss_fn(p_, X, Y))(p)
            p = jax.tree.map(lambda a, b: a - 0.3 * b.astype(a.dtype), p, g)
            return p, loss

        l0 = float(loss_fn(params, X, Y))
        for _ in range(40):
            params, loss = step(params, amp_state.scalers[0])
        lf = float(loss)
        assert np.isfinite(lf)
        assert lf < l0 * 0.7, (l0, lf)

"""profiler surface: the nvtx-parity ``range`` alias must never shadow
the builtin (module-scope binding removed; served via ``__getattr__``),
plus the trace/annotate helpers."""

import builtins

import jax
import jax.numpy as jnp

from apex_tpu import profiler


class TestRangeShadowRegression:
    def test_range_never_in_module_dict(self):
        # the shadow bug: `range = jax.named_scope` at module scope
        # meant any code added to profiler.py silently lost the
        # builtin. The alias now lives ONLY in __getattr__.
        assert "range" not in vars(profiler)
        assert "range" not in profiler.__all__

    def test_profiler_range_attribute_works(self):
        # attribute access keeps nvtx-name parity...
        assert profiler.range is jax.named_scope
        with profiler.range("unit_region"):
            x = jnp.ones((4,)) + 1.0
        assert float(x.sum()) == 8.0
        # ...and the decorator form too
        @profiler.range("deco_region")
        def f(y):
            return y * 2

        assert float(f(jnp.float32(3.0))) == 6.0

    def test_from_import_still_resolves(self):
        # module __getattr__ serves `from apex_tpu.profiler import range`
        from apex_tpu.profiler import range as prof_range

        assert prof_range is jax.named_scope

    def test_builtin_range_is_the_builtin(self):
        # calling BOTH in one scope: the builtin is untouched by the
        # alias (the original regression: intra-module/star-import
        # code picking up jax.named_scope as `range`)
        assert list(range(3)) == [0, 1, 2]
        assert range is builtins.range
        with profiler.range("both"):
            assert [i for i in range(2)] == [0, 1]

    def test_star_import_does_not_shadow(self):
        ns = {}
        exec("from apex_tpu.profiler import *\n"
             "out = list(range(3))", ns)
        assert ns["out"] == [0, 1, 2]
        assert ns.get("range") is None or ns["range"] is builtins.range

    def test_unknown_attribute_raises(self):
        try:
            profiler.definitely_not_here
        except AttributeError as e:
            assert "definitely_not_here" in str(e)
        else:
            raise AssertionError("expected AttributeError")

    def test_mark_range_and_annotate_still_exported(self):
        assert profiler.mark_range is jax.named_scope
        @profiler.annotate("ann")
        def g(x):
            return x + 1

        assert g(1) == 2

    def test_cache_stats_passthrough(self):
        stats = profiler.optimizer_step_cache_stats()
        for key in ("factory_hits", "factory_misses",
                    "layout_hits", "layout_misses"):
            assert key in stats

"""Distributed consistency guard (apex_tpu/resilience/guard.py):
bitwise state fingerprints, cross-replica divergence detection +
majority repair, no-quorum fallback, and preemption-safe shutdown.

Acceptance bar (ISSUE 3): an injected one-replica bit-flip
(APEX_TPU_FAULTS ``bit_flip`` site) is detected within
``fingerprint_every`` steps, localized to the correct parameter leaf +
replica in the structured ``resilience`` record, and after majority
repair the run is bitwise-identical to an uninjected run from the next
fingerprint boundary on; SIGTERM mid-step produces a final checkpoint
a fresh process auto-resumes from.

Replica sets are simulated with ``LocalCollective`` — one thread per
"host", every thread running the same loop code a real host would,
barrier-synchronized inside the collective ops (the threaded analog of
the repo's simulated 8-device CPU mesh).
"""

import os
import signal
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import records
from apex_tpu.multi_tensor.ops import per_tensor_l2norm
from apex_tpu.multi_tensor.segmented import segmented_per_leaf_checksum
from apex_tpu.optimizers import FusedAdam, FusedLAMB
from apex_tpu.optimizers.train_step import make_train_step
from apex_tpu.resilience import (
    CheckpointManager,
    ConsistencyGuard,
    DivergenceError,
    FaultInjector,
    LocalCollective,
    NullCollective,
    PreemptionHandler,
    compare_fingerprints,
    faults,
    graceful_shutdown,
    install_preemption_handler,
    state_fingerprint,
)
from apex_tpu.resilience.guard import fingerprint_buffer_names


def _params(seed=0):
    r = np.random.RandomState(seed)
    return {"b": jnp.zeros((6,), jnp.float32),
            "w1": jnp.asarray(r.randn(32, 6), jnp.float32),
            "w2": jnp.asarray(r.randn(6, 6), jnp.float32)}


@pytest.fixture
def records_dir(tmp_path, monkeypatch):
    path = tmp_path / "records"
    monkeypatch.setattr(records, "RECORDS_DIR", str(path))
    return path


def _flip_one_bit(buf, idx, bit=12):
    word = jax.lax.bitcast_convert_type(buf[idx], jnp.uint32)
    val = jax.lax.bitcast_convert_type(word ^ jnp.uint32(1 << bit),
                                       jnp.float32)
    return buf.at[idx].set(val)


class TestChecksum:
    def test_segmented_matches_plain_routing(self):
        opt = FusedLAMB(lr=1e-3, impl="xla", segmented=True)
        st = opt.init(_params())
        r = np.random.RandomState(0)
        gtree = {k: jnp.asarray(r.randn(*v.shape), jnp.float32)
                 for k, v in _params().items()}
        buf = st.space.pack(gtree, dtype=jnp.float32)
        seg = np.asarray(segmented_per_leaf_checksum(buf, st.space,
                                                     st.seg_meta))
        plain = np.asarray(segmented_per_leaf_checksum(buf, st.space, None))
        assert seg.dtype == np.uint32
        np.testing.assert_array_equal(seg, plain)

    def test_single_bit_flip_changes_exactly_its_leaf(self):
        opt = FusedLAMB(lr=1e-3, impl="xla", segmented=True)
        st = opt.init(_params())
        base = np.asarray(segmented_per_leaf_checksum(
            st.master, st.space, st.seg_meta))
        flipped = _flip_one_bit(st.master, st.space.offsets[1] + 3)
        after = np.asarray(segmented_per_leaf_checksum(
            flipped, st.space, st.seg_meta))
        diff = np.nonzero(after != base)[0]
        np.testing.assert_array_equal(diff, [1])       # only 'w1'

    def test_checksum_is_value_blind_but_bit_exact(self):
        # two buffers equal as floats but different bits (0.0 vs -0.0)
        # MUST fingerprint differently: the guard is bitwise, not
        # numeric
        opt = FusedAdam(lr=1e-3, impl="xla")
        st = opt.init(_params())
        buf = st.space.zeros()
        neg = buf.at[0].set(-0.0)
        a = np.asarray(segmented_per_leaf_checksum(buf, st.space, None))
        b = np.asarray(segmented_per_leaf_checksum(neg, st.space, None))
        assert np.asarray(buf[0]) == np.asarray(neg[0])   # numerically ==
        assert not np.array_equal(a, b)                   # bitwise !=

    def test_state_fingerprint_covers_master_and_slots(self):
        opt = FusedAdam(lr=1e-3, impl="xla")
        st = opt.init(_params())
        fp = state_fingerprint(st)
        assert fp.names == ("master", "slot:m", "slot:v")
        assert fp.names == fingerprint_buffer_names(st)
        assert fp.sums.shape == (3, st.space.num_leaves)
        # a flip in a SLOT buffer is caught too (SDC doesn't pick
        # polite targets)
        st2 = st._replace(slots={**st.slots,
                                 "m": _flip_one_bit(st.slots["m"], 0)})
        fp2 = state_fingerprint(st2)
        assert not np.array_equal(fp.sums[1], fp2.sums[1])
        np.testing.assert_array_equal(fp.sums[0], fp2.sums[0])


class TestCompare:
    def test_identical_is_clean(self):
        a = np.arange(6, dtype=np.uint32).reshape(2, 3)
        rep = compare_fingerprints(np.stack([a, a, a]))
        assert not rep.divergent and rep.has_quorum

    def test_majority_localizes_minority(self):
        a = np.zeros((2, 3), np.uint32)
        b = a.copy()
        b[1, 2] = 7
        rep = compare_fingerprints(np.stack([a, b, a]))
        assert rep.divergent and rep.has_quorum
        assert rep.majority_replica == 0
        assert rep.minority_replicas == (1,)
        assert rep.sites == ((1, 1, 2),)

    def test_one_vs_one_has_no_quorum(self):
        a = np.zeros((1, 2), np.uint32)
        b = a + 1
        rep = compare_fingerprints(np.stack([a, b]))
        assert rep.divergent and not rep.has_quorum
        assert rep.majority_replica is None

    def test_three_way_split_has_no_quorum(self):
        a = np.zeros((1, 1), np.uint32)
        rep = compare_fingerprints(np.stack([a, a + 1, a + 2]))
        assert rep.divergent and not rep.has_quorum


class TestFingerprintOption:
    def test_aux_fingerprint_at_boundaries_only(self):
        opt = FusedAdam(lr=1e-2, impl="xla")
        st = opt.init(_params())
        step = make_train_step(opt, fingerprint_every=3)
        assert step.options["fingerprint_every"] == 3
        r = np.random.RandomState(0)
        g = jnp.asarray(r.randn(st.space.total).astype(np.float32) * 0.01)
        for _ in range(6):
            st, aux = step(st, g)
            fp = np.asarray(aux.state_fingerprint)
            if int(st.count) % 3 == 0:
                np.testing.assert_array_equal(fp, state_fingerprint(st).sums)
            else:
                assert not fp.any()       # gated off-boundary

    def test_with_options_builds_fingerprint_sibling(self):
        opt = FusedAdam(lr=1e-2, impl="xla")
        base = make_train_step(opt)
        assert base.options["fingerprint_every"] is None
        sib = base.with_options(fingerprint_every=4)
        assert sib.options["fingerprint_every"] == 4
        assert sib is base.with_options(fingerprint_every=4)   # cached
        with pytest.raises(ValueError, match="positive"):
            make_train_step(opt, fingerprint_every=0)

    def test_guard_requires_an_interval(self):
        opt = FusedAdam(lr=1e-2, impl="xla")
        step = make_train_step(opt)
        with pytest.raises(ValueError, match="fingerprint_every"):
            ConsistencyGuard(step, collective=NullCollective())


# ---------------------------------------------------------------------------
# The acceptance scenario: one-replica bit flip -> detect, localize,
# majority-repair, bitwise-identical from the next boundary on
# ---------------------------------------------------------------------------


FP_EVERY = 2
STEPS = 8


class _Fleet:
    """N simulated hosts in lockstep threads, identical per-step grads
    (the post-all-reduce data-parallel contract), each running the
    same guard-wrapped loop a real host would."""

    def __init__(self, n, step, opt, *, managers=None, events=None):
        self.n = n
        self.step = step
        self.opt = opt
        self.group = LocalCollective(n)
        self.handles = self.group.handles()
        self.managers = managers or [None] * n
        self.events = events if events is not None else []
        self.probes = [dict() for _ in range(n)]
        self.states = [None] * n
        self.errors = [None] * n

    def grads(self, i, space):
        r = np.random.RandomState(1000 + i)
        return jnp.asarray(r.randn(space.total).astype(np.float32) * 0.01)

    def run(self, steps=STEPS, mutate=None, ckpt_every=None):
        def loop(rid):
            try:
                st = self.opt.init(_params())
                guard = ConsistencyGuard(
                    self.step, collective=self.handles[rid],
                    manager=self.managers[rid],
                    on_event=self.events.append)
                for i in range(steps):
                    if mutate is not None:
                        st = mutate(rid, i, st)
                    st, aux = guard(st, self.grads(i, st.space))
                    self.probes[rid][i] = np.asarray(st.master).copy()
                    # one writer per shared single-host directory (the
                    # multi-WRITER protocol is checkpoint.py's quorum
                    # mode, tests/test_quorum_checkpoint.py)
                    if (rid == 0 and self.managers[0] is not None
                            and ckpt_every
                            and (i + 1) % ckpt_every == 0):
                        self.managers[0].save(i + 1, st)
                self.states[rid] = st
            except BaseException as e:  # noqa: BLE001 — surfaced below
                self.errors[rid] = e

        ts = [threading.Thread(target=loop, args=(r,), daemon=True)
              for r in range(self.n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(120)
        return self


def _golden(opt, step, steps=STEPS):
    st = opt.init(_params())
    probes = {}
    for i in range(steps):
        r = np.random.RandomState(1000 + i)
        g = jnp.asarray(r.randn(st.space.total).astype(np.float32) * 0.01)
        st, _ = step(st, g)
        probes[i] = np.asarray(st.master).copy()
    return st, probes


class TestMajorityRepair:
    FLIP_STEP = 3          # strictly inside a fingerprint window
    FLIP_LEAF = 2          # 'w2'

    def _fleet_run(self, records_dir, monkeypatch):
        monkeypatch.setenv(
            faults.ENV_KNOB,
            f"bit_flip={self.FLIP_STEP};bit_flip_replica=1;"
            f"bit_flip_leaf={self.FLIP_LEAF}")
        faults.install(None)
        opt = FusedAdam(lr=1e-2, impl="xla")
        step = make_train_step(opt, fingerprint_every=FP_EVERY)
        golden_state, golden_probes = _golden(opt, step)

        def mutate(rid, i, st):
            return st._replace(master=faults.flip_bits(
                st.master, i, replica=rid, space=st.space))

        fleet = _Fleet(3, step, opt).run(mutate=mutate)
        assert fleet.errors == [None, None, None]
        return fleet, golden_state, golden_probes

    def test_bit_flip_detected_localized_repaired_bitwise(
            self, records_dir, monkeypatch):
        fleet, golden_state, golden_probes = self._fleet_run(
            records_dir, monkeypatch)

        # every replica reported the same event: replica 1, 'w2', with
        # a quorum, repaired from the majority
        assert len(fleet.events) == 3
        for ev in fleet.events:
            assert ev["event"] == "replica_divergence"
            assert ev["has_quorum"] is True
            assert ev["action"] == "majority_repair"
            assert ev["minority_replicas"] == [1]
            assert {(s["replica"], s["name"]) for s in ev["sites"]} \
                == {(1, "['w2']")}
            # detected within fingerprint_every steps of the flip
            assert ev["count"] - self.FLIP_STEP <= FP_EVERY
        rec = records.latest_record("resilience", require_backend=None)
        assert rec["payload"]["event"] == "replica_divergence"
        assert rec["payload"]["sites"][0]["name"] == "['w2']"
        assert rec["payload"]["sites"][0]["replica"] == 1

        # from the first fingerprint boundary after the flip on, every
        # replica's trajectory is BITWISE the uninjected golden run
        boundary = fleet.events[0]["count"]
        for rid in range(3):
            for i in range(boundary - 1, STEPS):
                np.testing.assert_array_equal(
                    fleet.probes[rid][i], golden_probes[i],
                    err_msg=f"replica {rid} step {i}")
            np.testing.assert_array_equal(
                np.asarray(fleet.states[rid].master),
                np.asarray(golden_state.master))
            for k in golden_state.slots:
                np.testing.assert_array_equal(
                    np.asarray(fleet.states[rid].slots[k]),
                    np.asarray(golden_state.slots[k]))
            assert int(fleet.states[rid].count) == int(golden_state.count)

    def test_clean_fleet_reports_nothing(self, records_dir):
        opt = FusedAdam(lr=1e-2, impl="xla")
        step = make_train_step(opt, fingerprint_every=FP_EVERY)
        fleet = _Fleet(3, step, opt).run()
        assert fleet.errors == [None, None, None]
        assert fleet.events == []
        assert records.latest_record("resilience",
                                     require_backend=None) is None


class TestNoQuorum:
    def _mutator(self):
        inj = FaultInjector(bit_flip_steps=frozenset({1}),
                            bit_flip_replica=1, bit_flip_leaf=0)

        def mutate(rid, i, st):
            return st._replace(master=inj.flip_bits(
                st.master, i, replica=rid, space=st.space))
        return mutate

    def test_two_replicas_roll_back_to_checkpoint(self, tmp_path,
                                                  records_dir):
        opt = FusedAdam(lr=1e-2, impl="xla")
        step = make_train_step(opt, fingerprint_every=FP_EVERY)
        # both replicas share one checkpoint directory (the shared-FS
        # contract); single-host managers here — quorum checkpoints
        # have their own suite (tests/test_quorum_checkpoint.py)
        mgrs = [CheckpointManager(tmp_path / "ckpt", keep=3)
                for _ in range(2)]
        fleet = _Fleet(2, step, opt, managers=mgrs).run(
            mutate=self._mutator(), ckpt_every=1)
        assert fleet.errors == [None, None]
        assert len(fleet.events) == 2
        for ev in fleet.events:
            assert ev["has_quorum"] is False
            assert ev["action"] == "rollback"
        # both replicas restored the same checkpoint -> bit-identical
        np.testing.assert_array_equal(
            np.asarray(fleet.states[0].master),
            np.asarray(fleet.states[1].master))

    def test_no_manager_raises_divergence_error(self, records_dir):
        opt = FusedAdam(lr=1e-2, impl="xla")
        step = make_train_step(opt, fingerprint_every=FP_EVERY)
        fleet = _Fleet(2, step, opt).run(mutate=self._mutator())
        for err in fleet.errors:
            assert isinstance(err, DivergenceError)
            assert "no agreeing majority" in str(err)
            assert err.report is not None and not err.report.has_quorum


class TestLostLockstep:
    def test_mismatched_counts_raise(self):
        group = LocalCollective(2)
        handles = group.handles()
        opt = FusedAdam(lr=1e-2, impl="xla")
        step = make_train_step(opt, fingerprint_every=1)
        errors = [None, None]

        def loop(rid):
            try:
                st = opt.init(_params())
                guard = ConsistencyGuard(step, collective=handles[rid])
                r = np.random.RandomState(0)
                g = jnp.asarray(
                    r.randn(st.space.total).astype(np.float32) * 0.01)
                if rid == 1:               # replica 1 sneaks an extra step
                    st, _ = step(st, g)
                guard(st, g)
            except BaseException as e:  # noqa: BLE001
                errors[rid] = e

        ts = [threading.Thread(target=loop, args=(r,), daemon=True)
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        for err in errors:
            assert isinstance(err, DivergenceError)
            assert "different step counts" in str(err)


# ---------------------------------------------------------------------------
# Preemption-safe shutdown
# ---------------------------------------------------------------------------


class TestPreemption:
    def test_handler_sets_flag_only(self):
        with PreemptionHandler(signals=(signal.SIGTERM,)) as h:
            assert not h.should_stop()
            os.kill(os.getpid(), signal.SIGTERM)
            assert h.requested and h.signum == signal.SIGTERM
            assert h.should_stop()
        # uninstalled: the default disposition is restored
        assert signal.getsignal(signal.SIGTERM) != h._handle

    def test_faults_sigterm_site_drives_the_real_signal(self):
        with PreemptionHandler(signals=(signal.SIGTERM,)) as h:
            with faults.inject(sigterm_steps=frozenset({2})):
                faults.maybe_sigterm(1)
                assert not h.requested
                faults.maybe_sigterm(2)
                assert h.requested

    def test_agreement_any_flagged_host_stops_the_fleet(self):
        group = LocalCollective(3)
        handles = group.handles()
        out = [None] * 3

        def loop(rid):
            h = PreemptionHandler()
            h.requested = rid == 1          # only one host got the signal
            out[rid] = h.should_stop(handles[rid])

        ts = [threading.Thread(target=loop, args=(r,), daemon=True)
              for r in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert out == [True, True, True]

    def test_sigterm_mid_step_checkpoint_resumes_bitwise(
            self, tmp_path, records_dir):
        opt = FusedAdam(lr=1e-2, impl="xla")
        step = make_train_step(opt)
        golden_state, golden_probes = _golden(opt, step)

        mgr = CheckpointManager(tmp_path / "ckpt", keep=3)
        handler = install_preemption_handler(signals=(signal.SIGTERM,))
        try:
            st = opt.init(_params())
            stopped_at = None
            with faults.inject(sigterm_steps=frozenset({4})):
                for i in range(STEPS):
                    faults.maybe_sigterm(i)   # "the scheduler's notice"
                    st, _ = step(st, _Fleet(1, step, opt).grads(
                        i, st.space))
                    if handler.should_stop():
                        # drain: finish the in-flight step, then the
                        # priority final checkpoint names the NEXT step
                        graceful_shutdown(mgr, i + 1, st, handler=handler)
                        stopped_at = i + 1
                        break
            assert stopped_at == 5
        finally:
            handler.uninstall()
        rec = records.latest_record("resilience", require_backend=None)
        assert rec["payload"]["event"] == "preemption_checkpoint"
        assert rec["payload"]["step"] == 5
        assert rec["payload"]["signum"] == signal.SIGTERM

        # "fresh process": auto-resume from latest_valid, replay
        # bitwise to the uninterrupted run
        restored = mgr.restore(template=opt.init(_params(seed=1)))
        assert restored.step == stopped_at
        st2 = restored.opt_state
        for i in range(restored.step, STEPS):
            st2, _ = step(st2, _Fleet(1, step, opt).grads(i, st2.space))
            np.testing.assert_array_equal(np.asarray(st2.master[:16]),
                                          golden_probes[i][:16])
        np.testing.assert_array_equal(np.asarray(st2.master),
                                      np.asarray(golden_state.master))

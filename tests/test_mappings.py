"""TP/SP mapping-op tests (mirrors ref tests/L0/run_transformer/test_mapping.py).

Forward semantics and Megatron-exact VJPs, on a real shard_map over the
simulated 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.tensor_parallel import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)

TP = 4


@pytest.fixture(autouse=True)
def mesh():
    m = ps.initialize_model_parallel(TP, 1)
    yield m
    ps.destroy_model_parallel()


def run_tp(fn, x, in_spec, out_spec, mesh):
    """Run fn under shard_map over the tensor axis only."""
    return jax.jit(
        shard_map(
            fn, mesh=mesh,
            in_specs=(in_spec,), out_specs=out_spec,
            check_vma=False,
        )
    )(x)


class TestForwardSemantics:
    def test_scatter_then_gather_last_dim(self, mesh, rng):
        x = jnp.asarray(rng.randn(6, 8 * TP), jnp.float32)

        def f(x):
            return gather_from_tensor_model_parallel_region(
                scatter_to_tensor_model_parallel_region(x)
            )

        out = run_tp(f, x, P(), P(), mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)

    def test_scatter_takes_rank_chunk(self, mesh, rng):
        x = jnp.asarray(rng.randn(2, 8 * TP), jnp.float32)

        def f(x):
            return scatter_to_tensor_model_parallel_region(x)

        # out_spec P(None, "tensor"): each rank's chunk concatenated back
        out = run_tp(f, x, P(), P(None, "tensor"), mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)

    def test_reduce_sums_over_ranks(self, mesh):
        # input sharded over tensor axis: each rank holds ones
        x = jnp.ones((TP, 4), jnp.float32)

        def f(x):
            return reduce_from_tensor_model_parallel_region(x)

        out = run_tp(f, x, P("tensor", None), P(None), mesh)
        # psum of ones over 4 ranks = 4 (out replicated; any copy works)
        np.testing.assert_allclose(np.asarray(out), 4.0 * np.ones((1, 4)), rtol=1e-6)

    def test_sequence_scatter_gather(self, mesh, rng):
        x = jnp.asarray(rng.randn(8 * TP, 6), jnp.float32)

        def f(x):
            return gather_from_sequence_parallel_region(
                scatter_to_sequence_parallel_region(x),
                tensor_parallel_output_grad=False,
            )

        out = run_tp(f, x, P(), P(), mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)

    def test_reduce_scatter_sequence(self, mesh):
        x = jnp.ones((4 * TP, 2), jnp.float32)

        def f(x):
            return reduce_scatter_to_sequence_parallel_region(x)

        out = run_tp(f, x, P(), P("tensor", None), mesh)
        # every rank contributed identical full-length ones; rs sums them
        np.testing.assert_allclose(np.asarray(out), TP * np.ones((4 * TP, 2)), rtol=1e-6)


class TestBackwardSemantics:
    def test_copy_bwd_allreduces(self, mesh):
        """copy: id fwd / psum bwd — the column-parallel entry. The VJP
        is probed *inside* shard_map (device-local activation flow, the
        op's intended position) so shard_map's own boundary-replication
        transpose doesn't stack on top of the op's psum."""

        def f(x):
            y, vjp = jax.vjp(copy_to_tensor_model_parallel_region, x)
            r = ps.get_tensor_model_parallel_rank().astype(jnp.float32)
            (gx,) = vjp((r + 1.0) * jnp.ones_like(y))   # per-rank partial grad
            return gx[None]

        gx = jax.jit(
            shard_map(f, mesh=mesh, in_specs=(P(),), out_specs=P("tensor"),
                      check_vma=False)
        )(jnp.ones((4,), jnp.float32))
        # every rank's dL/dx = sum_r (r+1) = 10 per element
        np.testing.assert_allclose(
            np.asarray(gx), 10.0 * np.ones((TP, 4)), rtol=1e-6
        )

    def test_reduce_bwd_identity(self, mesh):
        def f(x):
            y = reduce_from_tensor_model_parallel_region(x)
            # y is replicated; take mean over ranks to keep loss scalar-consistent
            return jnp.sum(y) / TP

        x = jnp.ones((TP, 4), jnp.float32)  # sharded input
        g = run_tp(jax.grad(f), x, P("tensor", None), P("tensor", None), mesh)
        # d(sum(psum(x))/TP)/dx = 1/TP * ... identity bwd: each shard gets g of y
        np.testing.assert_allclose(np.asarray(g), np.ones((TP, 4)) / TP, rtol=1e-6)

    def test_gather_bwd_splits(self, mesh, rng):
        w = jnp.asarray(rng.randn(8 * TP), jnp.float32)

        def f(x):
            y = gather_from_tensor_model_parallel_region(x)  # (8*TP,)
            return jnp.sum(y * w) / 1.0

        x = jnp.ones((8 * TP,), jnp.float32)  # replicated-in per rank: local (8,)? no:
        # give each rank its own chunk via sharded input
        def g_fn(x):
            return jax.grad(f)(x)

        g = run_tp(g_fn, x, P("tensor"), P("tensor"), mesh)
        # bwd split: each rank receives its chunk of w
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=1e-6)

    def test_scatter_bwd_gathers(self, mesh, rng):
        w = jnp.asarray(rng.randn(8 * TP), jnp.float32)

        def f(x):
            y, vjp = jax.vjp(scatter_to_tensor_model_parallel_region, x)
            chunk = 8
            r = ps.get_tensor_model_parallel_rank()
            wl = jax.lax.dynamic_slice_in_dim(w, r * chunk, chunk, 0)
            (gx,) = vjp(wl)   # cotangent = this rank's chunk of w
            return gx[None]

        gx = jax.jit(
            shard_map(f, mesh=mesh, in_specs=(P(),), out_specs=P("tensor"),
                      check_vma=False)
        )(jnp.ones((8 * TP,), jnp.float32))
        # bwd all-gathers chunk cotangents: every rank sees the full w
        np.testing.assert_allclose(
            np.asarray(gx), np.tile(np.asarray(w), (TP, 1)), rtol=1e-6
        )

    def test_gather_seq_bwd_reduce_scatter(self, mesh):
        """gather_from_sequence_parallel w/ tensor_parallel_output_grad:
        bwd reduce-scatters partial grads (ref mappings.py:223-242)."""

        def partials(x):
            y = gather_from_sequence_parallel_region(
                x, tensor_parallel_output_grad=True
            )
            r = ps.get_tensor_model_parallel_rank().astype(jnp.float32)
            return ((r + 1.0) * jnp.sum(y))[None]

        sharded = shard_map(
            partials, mesh=mesh,
            in_specs=(P("tensor", None),), out_specs=P("tensor"),
            check_vma=False,
        )

        def loss(x):
            return jnp.sum(sharded(x))

        x = jnp.ones((4 * TP, 2), jnp.float32)
        g = jax.jit(jax.grad(loss))(x)
        # each rank's partial grad is (r+1); reduce-scatter sums to 10 everywhere
        np.testing.assert_allclose(np.asarray(g), 10.0 * np.ones((4 * TP, 2)), rtol=1e-6)

    def test_rs_seq_bwd_gathers(self, mesh):
        def partials(x):
            y = reduce_scatter_to_sequence_parallel_region(x)
            return jnp.sum(y)[None]

        sharded = shard_map(
            partials, mesh=mesh, in_specs=(P(),), out_specs=P("tensor"),
            check_vma=False,
        )

        def loss(x):
            # sum of per-rank rs outputs = TP * mean contribution; normalize
            return jnp.sum(sharded(x)) / TP

        x = jnp.ones((4 * TP, 2), jnp.float32)
        g = jax.jit(jax.grad(loss))(x)
        np.testing.assert_allclose(np.asarray(g), np.ones((4 * TP, 2)), rtol=1e-6)

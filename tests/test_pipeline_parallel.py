"""Pipeline-parallel tests.

Mirrors ref tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py,
test_p2p_comm.py, test_microbatches.py — on the simulated mesh: the
pipelined loss/grads must equal the single-device sequential model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.pipeline_parallel import (
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    get_kth_microbatch,
    get_ltor_masks_and_position_ids,
    send_forward_recv_forward,
    spmd_pipeline,
)

PP = 4


@pytest.fixture
def pp_mesh():
    m = ps.initialize_model_parallel(1, PP)  # dp=2, pp=4
    yield m
    ps.destroy_model_parallel()


class TestP2P:
    def test_ring_shift(self, pp_mesh):
        def f(x):
            r = jax.lax.axis_index("pipe").astype(jnp.float32)
            y = send_forward_recv_forward(x + r)
            return y[None]

        out = jax.jit(
            shard_map(
                f, mesh=pp_mesh, in_specs=(P(),), out_specs=P(None, "pipe"),
                check_vma=False,
            )
        )(jnp.zeros((2,)))
        # stage s receives from s-1: row s = (s-1) mod PP
        got = np.asarray(out).reshape(2, PP).T[0] if False else None
        arr = np.asarray(out)  # (1*? ...) shape (1? ...)
        # out shape: (1, PP*2)? out_specs P(None, "pipe") concat on dim1
        vals = arr.reshape(1, PP, 2)[0, :, 0]
        np.testing.assert_array_equal(vals, [(s - 1) % PP for s in range(PP)])


class TestSpmdPipeline:
    def _stacked_params(self, rng, n_layers, width):
        # one linear layer per pp stage: stage s applies W_s
        return jnp.asarray(rng.randn(n_layers, width, width) * 0.3, jnp.float32)

    def test_matches_sequential(self, pp_mesh, rng):
        width, m, mb = 8, 6, 2
        ws = self._stacked_params(rng, PP, width)
        x = jnp.asarray(rng.randn(m, mb, width), jnp.float32)

        def stage_fn(w_local, h):
            return jnp.tanh(h @ w_local[0])

        out = jax.jit(
            shard_map(
                lambda w, x: spmd_pipeline(stage_fn, w, x),
                mesh=pp_mesh,
                in_specs=(P("pipe", None, None), P()),
                out_specs=P(),
                check_vma=False,
            )
        )(ws, x)

        # sequential reference
        h = np.asarray(x)
        for s in range(PP):
            h = np.tanh(h @ np.asarray(ws[s]))
        # outputs valid on last stage; out_specs P() takes one replica —
        # with check_vma off this is rank 0's buffer, which only matches
        # on the last stage. Broadcast via psum-mask inside instead:
        def run(w, x):
            from apex_tpu.transformer.pipeline_parallel import last_stage_value
            y = spmd_pipeline(stage_fn, w, x)
            return last_stage_value(y)

        out2 = jax.jit(
            shard_map(
                run, mesh=pp_mesh,
                in_specs=(P("pipe", None, None), P()),
                out_specs=P(), check_vma=False,
            )
        )(ws, x)
        np.testing.assert_allclose(np.asarray(out2), h, rtol=1e-4, atol=1e-5)

    def test_grads_match_sequential(self, pp_mesh, rng):
        width, m, mb = 8, 4, 2
        ws = self._stacked_params(rng, PP, width)
        x = jnp.asarray(rng.randn(m, mb, width), jnp.float32)
        t = jnp.asarray(rng.randn(m, mb, width), jnp.float32)

        def stage_fn(w_local, h):
            return jnp.tanh(h @ w_local[0])

        def pipeline_loss(w, x):
            from apex_tpu.transformer.pipeline_parallel import last_stage_value
            y = spmd_pipeline(stage_fn, w, x)
            loss = jnp.sum((y - t) ** 2)
            return last_stage_value(loss)

        fn = shard_map(
            pipeline_loss, mesh=pp_mesh,
            in_specs=(P("pipe", None, None), P()),
            out_specs=P(), check_vma=False,
        )
        g1 = jax.jit(jax.grad(lambda w: fn(w, x)))(ws)

        def seq_loss(ws):
            h = x
            for s in range(PP):
                h = jnp.tanh(h @ ws[s])
            return jnp.sum((h - t) ** 2)

        g2 = jax.grad(seq_loss)(ws)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-4, atol=1e-4)


class TestSchedules:
    def test_no_pipelining_grad_accumulation(self, rng):
        w = jnp.asarray(rng.randn(8, 4), jnp.float32)
        batch = jnp.asarray(rng.randn(16, 8), jnp.float32)

        def step(params, mb):
            return jnp.mean((mb @ params) ** 2)

        loss, grads = forward_backward_no_pipelining(
            step, batch, w, num_microbatches=4
        )
        # reference: mean over microbatches == full-batch loss here
        full_loss = float(step(w, batch))
        np.testing.assert_allclose(float(loss), full_loss, rtol=1e-5)
        g_full = jax.grad(step)(w, batch)
        np.testing.assert_allclose(np.asarray(grads), np.asarray(g_full), rtol=1e-4, atol=1e-5)

    def test_no_pipelining_forward_only(self, rng):
        w = jnp.asarray(rng.randn(8, 4), jnp.float32)
        batch = jnp.asarray(rng.randn(8, 8), jnp.float32)
        loss, grads = forward_backward_no_pipelining(
            lambda p, b: jnp.mean((b @ p) ** 2), batch, w,
            num_microbatches=2, forward_only=True,
        )
        assert grads is None

    def test_pipelining_without_interleaving(self, pp_mesh, rng):
        width, m = 8, 8
        ws = jnp.asarray(rng.randn(PP, width, width) * 0.3, jnp.float32)
        emb = jnp.asarray(rng.randn(width, width) * 0.3, jnp.float32)
        batch = jnp.asarray(rng.randn(m * 2, width), jnp.float32)
        t = 1.5

        def pre_fn(params, mb):
            return mb @ params["emb"]

        def stage_fn(params, h):
            return jnp.tanh(h @ params["stages"][0])

        def loss_fn(y, mb):
            return jnp.mean((y - t) ** 2)

        params = {"emb": emb, "stages": ws}

        fn = shard_map(
            lambda p, b: forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, pre_fn, p, b, num_microbatches=m
            ),
            mesh=pp_mesh,
            in_specs=({"emb": P(), "stages": P("pipe", None, None)}, P()),
            out_specs=(P(), {"emb": P(), "stages": P("pipe", None, None)}),
            check_vma=False,
        )
        loss, grads = jax.jit(fn)(params, batch)

        def seq_loss(params):
            h = batch.reshape(m, 2, width) @ params["emb"]
            for s in range(PP):
                h = jnp.tanh(h @ params["stages"][s])
            return jnp.mean(jax.vmap(lambda y: jnp.mean((y - t) ** 2))(h))

        ref_loss, ref_grads = jax.value_and_grad(seq_loss)(params)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(grads["stages"]), np.asarray(ref_grads["stages"]),
            rtol=1e-3, atol=1e-4,
        )

    def test_pipelining_with_interleaving(self, pp_mesh, rng):
        """2 model chunks x 4 stages = 8 virtual stages; equals an
        8-layer sequential model."""
        width, m, vpp = 8, 4, 2
        # chunk c on stage s holds layer index c*PP + s
        ws = jnp.asarray(rng.randn(PP, vpp, width, width) * 0.2, jnp.float32)
        batch = jnp.asarray(rng.randn(m * 2, width), jnp.float32)

        def stage_fn(params, h, chunk_id):
            return jnp.tanh(h @ params[0, chunk_id])

        def loss_fn(y, mb):
            return jnp.mean(y ** 2)

        fn = shard_map(
            lambda p, b: forward_backward_pipelining_with_interleaving(
                stage_fn, loss_fn, None, p, b,
                num_microbatches=m, num_model_chunks=vpp,
            ),
            mesh=pp_mesh,
            in_specs=(P("pipe", None, None, None), P()),
            out_specs=(P(), P("pipe", None, None, None)),
            check_vma=False,
        )
        loss, grads = jax.jit(fn)(ws, batch)

        def seq_loss(ws):
            h = batch.reshape(m, 2, width)
            for c in range(vpp):
                for s in range(PP):
                    h = jnp.tanh(h @ ws[s, c])
            return jnp.mean(h ** 2)

        ref_loss, ref_grads = jax.value_and_grad(seq_loss)(ws)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(grads), np.asarray(ref_grads), rtol=1e-3, atol=1e-4
        )

    def test_interleaved_forward_only(self, pp_mesh, rng):
        """forward_only=True returns (loss, None) and the loss equals
        the grad-producing run's."""
        width, m, vpp = 8, 4, 2
        ws = jnp.asarray(rng.randn(PP, vpp, width, width) * 0.2, jnp.float32)
        batch = jnp.asarray(rng.randn(m * 2, width), jnp.float32)

        def stage_fn(params, h, chunk_id):
            return jnp.tanh(h @ params[0, chunk_id])

        def loss_fn(y, mb):
            return jnp.mean(y ** 2)

        grads_seen = []

        def call(p, b, forward_only):
            loss, grads = forward_backward_pipelining_with_interleaving(
                stage_fn, loss_fn, None, p, b,
                num_microbatches=m, num_model_chunks=vpp,
                forward_only=forward_only)
            grads_seen.append(grads)
            return loss

        def run(forward_only):
            fn = shard_map(
                lambda p, b: call(p, b, forward_only),
                mesh=pp_mesh,
                in_specs=(P("pipe", None, None, None), P()),
                out_specs=P(), check_vma=False,
            )
            return float(np.ravel(jax.jit(fn)(ws, batch))[0])

        loss_fwd_only = run(True)
        assert grads_seen[0] is None        # forward_only returns no grads
        np.testing.assert_allclose(loss_fwd_only, run(False), rtol=1e-6)

    def test_get_forward_backward_func(self):
        assert get_forward_backward_func(None, 1) is forward_backward_no_pipelining
        assert (
            get_forward_backward_func(None, 4)
            is forward_backward_pipelining_without_interleaving
        )
        assert (
            get_forward_backward_func(2, 4)
            is forward_backward_pipelining_with_interleaving
        )


class TestMicrobatches:
    def test_constant(self):
        c = ConstantNumMicroBatches(64, 4, 2)
        assert c.get() == 8
        assert c.get_current_global_batch_size() == 64

    def test_constant_indivisible_raises(self):
        with pytest.raises(ValueError):
            ConstantNumMicroBatches(65, 4, 2)

    def test_rampup(self):
        r = RampupBatchsizeNumMicroBatches(
            start_batch_size=16, batch_size_increment=16, ramup_samples=1000,
            global_batch_size=64, micro_batch_size=4, data_parallel_size=2,
        )
        assert r.get_current_global_batch_size() == 16
        r.update(500, False)  # 500/(1000/3) -> 1 increment
        assert r.get_current_global_batch_size() == 32
        r.update(2000, False)
        assert r.get_current_global_batch_size() == 64
        assert r.get() == 8

    def test_kth_microbatch(self, rng):
        batch = {"x": jnp.asarray(rng.randn(12, 3), jnp.float32)}
        mb = get_kth_microbatch(batch, 2, 4)
        np.testing.assert_allclose(
            np.asarray(mb["x"]), np.asarray(batch["x"][8:12])
        )


class TestLtorMasks:
    def test_causal_mask(self):
        data = jnp.asarray([[5, 3, 7, 1]], jnp.int32)
        mask, loss_mask, pos = get_ltor_masks_and_position_ids(data)
        assert mask.shape == (1, 1, 4, 4)
        m = np.asarray(mask[0, 0])
        assert not m[2, 1] and m[1, 2]  # can attend backward, not forward
        np.testing.assert_array_equal(np.asarray(pos[0]), [0, 1, 2, 3])
        np.testing.assert_array_equal(np.asarray(loss_mask[0]), [1, 1, 1, 1])

    def test_eod_resets(self):
        data = jnp.asarray([[5, 0, 7, 1]], jnp.int32)  # EOD token = 0
        mask, loss_mask, pos = get_ltor_masks_and_position_ids(
            data, eod_token=0, reset_position_ids=True,
            reset_attention_mask=True, eod_mask_loss=True,
        )
        np.testing.assert_array_equal(np.asarray(loss_mask[0]), [1, 0, 1, 1])
        # positions restart after EOD (EOD belongs to first segment)
        np.testing.assert_array_equal(np.asarray(pos[0]), [0, 1, 0, 1])
        m = np.asarray(mask[0, 0])
        assert m[2, 0]  # token 2 (new doc) cannot see token 0


def test_pipeline_memory_scales_with_depth(pp_mesh, rng):
    """VERDICT #6 acceptance: compiled peak temp memory of the 1F1B
    schedule grows ~O(pipeline depth), not O(num_microbatches) — the
    chunk-checkpointed scan stores one ring buffer per chunk boundary
    plus one transiently recomputed chunk (ref 1F1B bounds in-flight
    activations to the depth, fwd_bwd_pipelining_without_
    interleaving.py:228-489)."""
    width, mbsz = 64, 4

    def stage_fn(params, h):
        for i in range(2):
            h = jnp.tanh(h @ params[0, i])
        return h

    def loss_fn(y, mb):
        return jnp.mean(y ** 2)

    def temp_bytes(m):
        ws = jnp.asarray(rng.randn(PP, 2, width, width) * 0.2, jnp.float32)
        batch = jnp.asarray(rng.randn(m * mbsz, width), jnp.float32)
        fn = shard_map(
            lambda p, b: forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, None, p, b, num_microbatches=m,
            ),
            mesh=pp_mesh,
            in_specs=(P("pipe", None, None, None), P()),
            out_specs=(P(), P("pipe", None, None, None)),
            check_vma=False,
        )
        compiled = jax.jit(fn).lower(ws, batch).compile()
        ma = compiled.memory_analysis()
        if ma is None:
            pytest.skip("backend reports no memory analysis")
        return ma.temp_size_in_bytes

    t8 = temp_bytes(8)
    t32 = temp_bytes(32)
    # O(M) saved state would grow ~4x going 8 -> 32 microbatches; the
    # chunked schedule's transient chunk is fixed-size, so the growth
    # must stay well under 2x (some O(M) terms remain: the raw input
    # microbatches and per-chunk boundary carries)
    assert t32 < 2.0 * t8, (t8, t32)


def test_interleaved_pipeline_memory_scales_with_depth(pp_mesh, rng):
    """Interleaved analog of the depth-memory bound (round-2 VERDICT
    weak#4): the single-rotating-buffer tick scan must keep compiled
    peak temp memory ~O(depth), never the (M, ...) boundary-activation
    stack of the old per-chunk ring formulation."""
    width, mbsz, vpp = 64, 4, 2

    def stage_fn(params, h, chunk_id):
        return jnp.tanh(h @ params[0, chunk_id])

    def loss_fn(y, mb):
        return jnp.mean(y ** 2)

    def temp_bytes(m):
        ws = jnp.asarray(rng.randn(PP, vpp, width, width) * 0.2,
                         jnp.float32)
        batch = jnp.asarray(rng.randn(m * mbsz, width), jnp.float32)
        fn = shard_map(
            lambda p, b: forward_backward_pipelining_with_interleaving(
                stage_fn, loss_fn, None, p, b, num_microbatches=m,
                num_model_chunks=vpp,
            ),
            mesh=pp_mesh,
            in_specs=(P("pipe", None, None, None), P()),
            out_specs=(P(), P("pipe", None, None, None)),
            check_vma=False,
        )
        compiled = jax.jit(fn).lower(ws, batch).compile()
        ma = compiled.memory_analysis()
        if ma is None:
            pytest.skip("backend reports no memory analysis")
        return ma.temp_size_in_bytes

    t8 = temp_bytes(8)
    t32 = temp_bytes(32)
    assert t32 < 2.0 * t8, (t8, t32)

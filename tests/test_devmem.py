"""Memory-plane observability (apex_tpu/telemetry/devmem.py):
memory_analysis normalization, the polled device-memory ledger with
watermark tracking, the explicit null-with-reason degradation on
backends without stats (the mfu_reason contract), and the
tools/telemetry_dump.py compile/devmem sections + Prometheus
coverage."""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import pytest

from apex_tpu import telemetry
from apex_tpu.optimizers import FusedAdam
from apex_tpu.optimizers.train_step import clear_step_cache, make_train_step
from apex_tpu.telemetry import devmem


@pytest.fixture(autouse=True)
def fresh():
    telemetry.reset()
    clear_step_cache()
    yield
    telemetry.reset()
    clear_step_cache()


def _load_dump_tool():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "telemetry_dump.py")
    spec = importlib.util.spec_from_file_location("telemetry_dump", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _FakeDevice:
    device_kind = "TPU v99-fake"

    def __init__(self, in_use=1000, limit=10_000):
        self.in_use = in_use
        self.limit = limit

    def memory_stats(self):
        return {"bytes_in_use": self.in_use,
                "peak_bytes_in_use": self.in_use + 500,
                "bytes_limit": self.limit,
                "num_allocs": 3}


class _StatlessDevice:
    device_kind = "statless"

    def memory_stats(self):
        return None


class TestCompiledMemory:
    def test_normalizes_real_compiled(self):
        c = jax.jit(lambda x: x * 2 + 1).lower(
            jnp.ones((16,), jnp.float32)).compile()
        mem = devmem.compiled_memory(c)
        assert mem["argument_bytes"] == 64
        assert mem["output_bytes"] == 64
        assert mem["total_footprint_bytes"] >= 128
        for key in ("temp_bytes", "alias_bytes", "generated_code_bytes",
                    "peak_bytes"):
            assert key in mem       # fixed key set, value-or-null

    def test_garbage_object_degrades_to_none(self):
        assert devmem.compiled_memory(object()) is None
        assert devmem.normalize_memory_analysis(None) is None
        assert devmem.normalize_memory_analysis(object()) is None

    def test_train_step_memory(self):
        opt = FusedAdam(lr=1e-3, impl="xla")
        state = opt.init({"w": jnp.zeros((64,), jnp.float32)})
        g = jnp.zeros((state.space.total,), jnp.float32)
        step = make_train_step(opt)
        mem = devmem.train_step_memory(step, state, g)
        assert mem["argument_bytes"] > 0
        # lower() passthrough: nothing was donated, the state is usable
        state, _ = step(state, g)

    def test_jitted_memory(self):
        fn = jax.jit(lambda x: jnp.sum(x * x))
        mem = devmem.jitted_memory(fn, jnp.ones((32,), jnp.float32))
        assert mem["argument_bytes"] == 128

    def test_publish_memory_gauges(self):
        devmem.publish_memory({"argument_bytes": 100, "peak_bytes": None,
                               "temp_bytes": 7}, fn="f")
        gauges = telemetry.snapshot()["gauges"]
        assert gauges['devmem_compiled_bytes{fn="f",part="argument"}'] == 100
        assert gauges['devmem_compiled_bytes{fn="f",part="temp"}'] == 7
        # null parts publish nothing
        assert not any("peak" in k for k in gauges)
        devmem.publish_memory(None)     # no-op, never raises


class TestDeviceMemoryStats:
    def test_cpu_is_null_with_reason(self):
        st = devmem.device_memory_stats()       # the test backend: CPU
        assert st["bytes_in_use"] is None
        assert st["peak_bytes_in_use"] is None
        assert "memory_stats" in st["devmem_reason"]
        assert st["device_kind"]                # named, not guessed

    def test_fake_device_values(self):
        st = devmem.device_memory_stats(_FakeDevice(in_use=123))
        assert st["bytes_in_use"] == 123
        assert st["peak_bytes_in_use"] == 623
        assert st["bytes_limit"] == 10_000
        assert st["devmem_reason"] is None


class TestLedger:
    def test_null_reason_path_publishes_info_not_gauges(self):
        led = devmem.DeviceMemoryLedger(device=_StatlessDevice())
        st = led.poll()
        snap = telemetry.snapshot()
        assert "devmem_bytes_in_use" not in snap["gauges"]
        assert "statless" in snap["info"]["devmem_reason"]
        det = telemetry.snapshot_detail()
        assert det["devmem"] is None
        assert "statless" in det["devmem_reason"]
        assert st["devmem_reason"]

    def test_gauges_and_watermark_high_water(self):
        dev = _FakeDevice(in_use=1000)
        led = devmem.DeviceMemoryLedger(device=dev)
        led.poll()
        dev.in_use = 5000
        led.poll()
        dev.in_use = 2000
        led.poll()
        gauges = telemetry.snapshot()["gauges"]
        assert gauges["devmem_bytes_in_use"] == 2000
        assert gauges["devmem_watermark_bytes"] == 5000    # high-water
        assert gauges["devmem_bytes_limit"] == 10_000
        det = telemetry.snapshot_detail()
        assert det["devmem"]["bytes_in_use"] == 2000
        assert det["devmem"]["watermark_bytes"] == 5000
        assert "devmem_reason" not in det
        s = led.summary()
        assert s["polls"] == 3 and s["watermark_bytes"] == 5000
        assert s["last"]["bytes_in_use"] == 2000

    def test_no_poll_detail_says_why(self):
        det = telemetry.snapshot_detail()
        assert det["devmem"] is None
        assert "no device-memory poll" in det["devmem_reason"]

    def test_global_ledger_lifecycle(self):
        led = devmem.enable(device=_FakeDevice())
        assert devmem.get_ledger() is led
        devmem.disable()
        assert devmem.get_ledger() is None
        devmem.enable(device=_FakeDevice())
        telemetry.reset()               # reset disarms the global ledger
        assert devmem.get_ledger() is None


class TestPromCoverage:
    def test_prometheus_text_covers_both_planes(self):
        devmem.DeviceMemoryLedger(device=_FakeDevice()).poll()
        from apex_tpu.telemetry import compiled

        tr = compiled.enable()
        try:
            tr.record_compile("x", 0.01)
            tr.observe("x", {"a": 1})
            tr.observe("x", {"a": 2})
        finally:
            compiled.disable()
        text = telemetry.to_prometheus_text()
        assert "devmem_bytes_in_use 1000" in text
        assert "devmem_watermark_bytes 1000" in text
        assert 'compile_count{fn="x"} 1' in text
        assert 'compile_seconds_bucket{fn="x",le="0.01"} 1' in text
        assert 'recompile_count{fn="x"} 1' in text


class TestDumpSections:
    def _snap(self):
        devmem.DeviceMemoryLedger(device=_FakeDevice()).poll()
        from apex_tpu.telemetry import compiled

        tr = compiled.enable()
        try:
            tr.record_compile("train_step", 0.02)
            tr.observe("f", {"a": 1})
            tr.observe("f", {"a": 2})
        finally:
            compiled.disable()
        return telemetry.snapshot()

    def test_sections_extracted(self):
        dump = _load_dump_tool()
        snap = self._snap()
        comp = dump.compile_section(snap)
        assert 'compile_count{fn="train_step"}' in comp["counters"]
        assert 'recompile_count{fn="f"}' in comp["counters"]
        assert 'compile_ms{fn="train_step"}' in comp["gauges"]
        dm = dump.devmem_section(snap)
        assert dm["gauges"]["devmem_bytes_in_use"] == 1000
        assert "devmem_reason" not in dm

    def test_devmem_section_null_reason(self):
        dump = _load_dump_tool()
        snap = telemetry.snapshot()         # nothing polled
        dm = dump.devmem_section(snap)
        assert "devmem_reason" in dm

    def test_json_output_carries_sections(self, capsys, tmp_path):
        dump = _load_dump_tool()
        rec = {"payload": {"telemetry": {"registry": self._snap()}}}
        path = tmp_path / "flightrec_x.json"
        path.write_text(json.dumps(rec))
        assert dump.main([str(path), "--format", "json"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert 'compile_count{fn="train_step"}' in out["compile"]["counters"]
        assert out["devmem"]["gauges"]["devmem_bytes_in_use"] == 1000
        # the registry sections themselves are still in place
        assert "counters" in out and "gauges" in out

    def test_prom_output_carries_plane_comments(self, capsys, tmp_path):
        dump = _load_dump_tool()
        path = tmp_path / "snap.json"
        path.write_text(json.dumps(self._snap()))
        assert dump.main([str(path)]) == 0
        out = capsys.readouterr().out
        assert "# compile plane: 1 compiles, 1 recompiles, 0 storms" in out
        assert "# devmem: bytes_in_use=1000" in out

    def test_prom_comment_names_missing_devmem(self, capsys, tmp_path):
        dump = _load_dump_tool()
        path = tmp_path / "snap.json"
        path.write_text(json.dumps(telemetry.snapshot()))
        assert dump.main([str(path)]) == 0
        out = capsys.readouterr().out
        assert "# devmem: unavailable" in out

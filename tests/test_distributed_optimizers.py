"""ZeRO-style sharded optimizer tests.

Mirrors ref apex/contrib/test/optimizers/test_distributed_fused_adam.py
and test_dist_fused_lamb.py strategy: the sharded optimizer over N
(simulated) devices must match the *unsharded* fused optimizer run on
the globally-reduced gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.optimizers import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.optimizers import FusedAdam, FusedLAMB
from apex_tpu.transformer import parallel_state as ps


@pytest.fixture(autouse=True)
def mesh():
    m = ps.initialize_model_parallel(1, 1)  # dp=8
    yield m
    ps.destroy_model_parallel()


def make_params(rng):
    return {
        "w1": jnp.asarray(rng.randn(33, 17), jnp.float32),
        "b1": jnp.asarray(rng.randn(17), jnp.float32),
        "w2": jnp.asarray(rng.randn(17, 5), jnp.float32),
    }


def make_grad_shards(rng, params, world=8):
    """world congruent grad pytrees (one per device) + their mean."""
    shards = []
    for _ in range(world):
        shards.append(
            jax.tree.map(lambda p: jnp.asarray(rng.randn(*p.shape), jnp.float32), params)
        )
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *shards)
    mean = jax.tree.map(lambda s: jnp.mean(s, axis=0), stacked)
    return stacked, mean


def run_sharded(mesh, opt, params, grad_stack, n_steps=3, **step_kw):
    """Init + n steps entirely inside shard_map over the data axis."""

    def body(params, gstack):
        g = jax.tree.map(lambda s: s[0], gstack)  # this device's grads
        state = opt.init(params)
        p = params
        for _ in range(n_steps):
            p, state = opt.step(state, g, **step_kw)
        return p, state.count, state.found_inf

    return jax.jit(
        shard_map(
            body, mesh=mesh,
            in_specs=(P(), P("data")),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
    )(params, grad_stack)


class TestDistributedFusedAdam:
    def test_matches_unsharded(self, mesh, rng):
        params = make_params(rng)
        gstack, gmean = make_grad_shards(rng, params)

        p_dist, count, _ = run_sharded(
            mesh, DistributedFusedAdam(lr=1e-2, weight_decay=0.01), params, gstack
        )

        ref_opt = FusedAdam(lr=1e-2, weight_decay=0.01)
        state = ref_opt.init(params)
        p_ref = params
        for _ in range(3):
            p_ref, state = ref_opt.step(state, gmean)

        for k in params:
            np.testing.assert_allclose(
                np.asarray(p_dist[k]), np.asarray(p_ref[k]), rtol=2e-5, atol=2e-6
            )
        assert int(count) == 3

    def test_sum_mode(self, mesh, rng):
        """average_grad_sync=False reduces with sum (ref
        distributed_fused_adam.py average_grad_sync arg)."""
        params = make_params(rng)
        gstack, gmean = make_grad_shards(rng, params)
        gsum = jax.tree.map(lambda m: m * 8.0, gmean)

        p_dist, _, _ = run_sharded(
            mesh, DistributedFusedAdam(lr=1e-3, average_grad_sync=False),
            params, gstack,
        )
        ref_opt = FusedAdam(lr=1e-3)
        state = ref_opt.init(params)
        p_ref = params
        for _ in range(3):
            p_ref, state = ref_opt.step(state, gsum)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p_dist[k]), np.asarray(p_ref[k]), rtol=2e-5, atol=2e-6
            )

    def test_overflow_skips_all_shards(self, mesh, rng):
        """An inf in one shard's grads must skip the step on every shard
        (ref: found_inf allreduce semantics)."""
        params = make_params(rng)
        gstack, _ = make_grad_shards(rng, params)
        # poison only device 3's grads for w2
        g = np.array(gstack["w2"])
        g[3, 0, 0] = np.inf
        gstack = dict(gstack, w2=jnp.asarray(g))

        p_dist, count, found = run_sharded(
            mesh, DistributedFusedAdam(lr=1e-2), params, gstack,
            n_steps=1, skip_if_nonfinite=True,
        )
        assert float(np.unique(np.asarray(found))[0]) == 1.0
        assert int(np.unique(np.asarray(count))[0]) == 0
        for k in params:
            np.testing.assert_array_equal(np.asarray(p_dist[k]), np.asarray(params[k]))

    def test_grad_sync_dtype_bf16(self, mesh, rng):
        """bf16 grad reduce-scatter stays close to fp32 (ref
        grad_sync_dtype arg, distributed_fused_adam.py:55-57)."""
        params = make_params(rng)
        gstack, gmean = make_grad_shards(rng, params)
        p_dist, _, _ = run_sharded(
            mesh,
            DistributedFusedAdam(lr=1e-2, grad_sync_dtype=jnp.bfloat16),
            params, gstack,
        )
        ref_opt = FusedAdam(lr=1e-2)
        state = ref_opt.init(params)
        p_ref = params
        for _ in range(3):
            p_ref, state = ref_opt.step(state, gmean)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p_dist[k]), np.asarray(p_ref[k]), rtol=0.05, atol=0.05
            )


class TestDistributedFusedLAMB:
    def test_matches_unsharded(self, mesh, rng):
        params = make_params(rng)
        gstack, gmean = make_grad_shards(rng, params)

        opt = DistributedFusedLAMB(
            lr=1e-2, weight_decay=0.01, max_grad_norm=1.0
        )
        p_dist, count, _ = run_sharded(mesh, opt, params, gstack)

        ref_opt = FusedLAMB(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
        state = ref_opt.init(params)
        p_ref = params
        for _ in range(3):
            p_ref, state = ref_opt.step(state, gmean)

        for k in params:
            np.testing.assert_allclose(
                np.asarray(p_dist[k]), np.asarray(p_ref[k]), rtol=2e-5, atol=2e-6
            )
        assert int(count) == 3

    def test_nvlamb_no_decay_groups(self, mesh, rng):
        params = make_params(rng)
        gstack, gmean = make_grad_shards(rng, params)
        opt = DistributedFusedLAMB(
            lr=1e-2, weight_decay=0.0, use_nvlamb=True, max_grad_norm=0.0
        )
        p_dist, _, _ = run_sharded(mesh, opt, params, gstack)
        ref_opt = FusedLAMB(
            lr=1e-2, weight_decay=0.0, use_nvlamb=True, max_grad_norm=0.0
        )
        state = ref_opt.init(params)
        p_ref = params
        for _ in range(3):
            p_ref, state = ref_opt.step(state, gmean)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p_dist[k]), np.asarray(p_ref[k]), rtol=2e-5, atol=2e-6
            )

    def test_e5m2_allgather_roundtrip(self, mesh, rng):
        """e5m2-compressed param allgather runs and stays within e5m2
        quantization error (ref distributed_fused_lamb.py:91)."""
        params = make_params(rng)
        gstack, _ = make_grad_shards(rng, params)
        opt = DistributedFusedLAMB(lr=1e-3, e5m2_allgather=True)
        p_dist, _, _ = run_sharded(mesh, opt, params, gstack, n_steps=1)
        # e5m2 has 2 mantissa bits -> ~12.5% relative error bound
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p_dist[k]), np.asarray(params[k]), rtol=0.3, atol=0.3
            )

    def test_clip_before_ar(self, mesh, rng):
        """clip_after_ar=False clips by the max over ranks of the local
        (pre-reduction) grad norms (ref distributed_fused_lamb.py:626-634)."""
        from apex_tpu.multi_tensor import FlatSpace, fused_lamb_update

        params = make_params(rng)
        gstack, gmean = make_grad_shards(rng, params)
        opt = DistributedFusedLAMB(
            lr=1e-2, weight_decay=0.01, max_grad_norm=0.5, clip_after_ar=False
        )
        p_dist, count, _ = run_sharded(mesh, opt, params, gstack)
        assert int(count) == 3

        # reference: unsharded LAMB on the mean grads, with the clip
        # norm forced to max_d ||g_d|| (each device's local grad norm)
        local_norms = [
            float(np.sqrt(sum(np.sum(np.asarray(gstack[k])[d] ** 2) for k in params)))
            for d in range(8)
        ]
        expected_norm = max(local_norms)
        space = FlatSpace.create(params)
        master = space.pack(params, dtype=jnp.float32)
        m = jnp.zeros_like(master)
        v = jnp.zeros_like(master)
        g = space.pack(gmean, dtype=jnp.float32)
        for step in range(1, 4):
            master, m, v, _ = fused_lamb_update(
                master, m, v, g, space, lr=1e-2, weight_decay=0.01,
                max_grad_norm=0.5, step=step,
                global_grad_norm=jnp.float32(expected_norm),
            )
        p_ref = space.unpack(master)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p_dist[k]), np.asarray(p_ref[k]), rtol=2e-5, atol=2e-6
            )

    def test_clip_before_ar_rejects_pre_synced(self, mesh, rng):
        params = make_params(rng)
        gstack, _ = make_grad_shards(rng, params)
        opt = DistributedFusedLAMB(lr=1e-2, clip_after_ar=False)
        with pytest.raises(ValueError, match="grads_pre_synced"):
            run_sharded(mesh, opt, params, gstack, n_steps=1,
                        grads_pre_synced=True)


class TestDistributedStochasticRounding:
    """bf16 SR shards: master-free ZeRO (bf16 analog of the reference's
    e5m2-compressed allgather, distributed_fused_lamb.py:91)."""

    @pytest.mark.parametrize("opt_cls", [DistributedFusedAdam,
                                         DistributedFusedLAMB])
    def test_bf16_sr_tracks_fp32(self, mesh, rng, opt_cls):
        """A few steps of the bf16+SR sharded optimizer stay within
        bf16-resolution of the fp32 sharded run on the same grads."""
        params = jax.tree.map(lambda x: x.astype(jnp.bfloat16),
                              make_params(rng))
        gstack, _ = make_grad_shards(rng, make_params(rng))
        kw = dict(lr=0.01, impl="xla")
        p32, cnt, found = run_sharded(
            mesh, opt_cls(**kw),
            jax.tree.map(lambda x: x.astype(jnp.float32), params), gstack)
        psr, cnt2, found2 = run_sharded(
            mesh, opt_cls(**kw, master_dtype=jnp.bfloat16,
                          stochastic_rounding=True), params, gstack)
        assert int(np.ravel(cnt2)[0]) == int(np.ravel(cnt)[0])
        assert float(np.ravel(found2)[0]) == 0.0
        for a, b in zip(jax.tree.leaves(psr), jax.tree.leaves(p32)):
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            # within ~a bf16 ulp of the fp32 trajectory after 3 steps
            assert np.max(np.abs(a - b) / (1.0 + np.abs(b))) < 2.0 ** -6

    def test_sr_streams_differ_across_shards(self, mesh, rng):
        """Each shard must round with its own stream: with identical
        values on every shard, the rounding patterns still differ."""
        from jax import lax

        # Adam's normalized update is ~1, so lr=2^-9 leaves params at
        # ~1 - 2^-9: dead-center between the two bf16 neighbours of 1,
        # a fair rounding coin on every element
        opt = DistributedFusedAdam(lr=2.0 ** -9, weight_decay=0.0,
                                   master_dtype=jnp.bfloat16,
                                   stochastic_rounding=True, impl="xla")
        n = 2048 * 8
        params = {"w": jnp.full((n,), 1.0, jnp.bfloat16)}
        gstack = {"w": jnp.full((8, n), 2.0 ** -9, jnp.float32)}

        def body(pp, gstack):
            g = jax.tree.map(lambda s: s[0], gstack)
            st = opt.init(pp)
            p2, st = opt.step(st, g)
            # the LOCAL master shard, stacked for inspection
            return lax.all_gather(st.master, "data")

        shards = jax.jit(
            shard_map(body, mesh=mesh, in_specs=(P(), P("data")),
                      out_specs=P(), check_vma=False)
        )(params, gstack)
        shards = np.asarray(shards, np.float32)  # (8, shard)
        # every shard saw the same values; identical rounding across all
        # 8 shards would mean a shared stream
        assert not all(
            (shards[i] == shards[0]).all() for i in range(1, 8))

    def test_rejects_mixed_leaves(self, mesh, rng):
        opt = DistributedFusedAdam(lr=1e-3, master_dtype=jnp.bfloat16,
                                   stochastic_rounding=True, impl="xla")
        params = {"w": jnp.ones((64,), jnp.bfloat16),
                  "ln": jnp.ones((8,), jnp.float32)}

        def body(pp):
            return opt.init(pp).count

        with pytest.raises(ValueError, match="float32"):
            jax.jit(shard_map(body, mesh=mesh, in_specs=(P(),),
                              out_specs=P(), check_vma=False))(params)

"""NonfiniteWatchdog: skip counting, per-parameter NaN localization
(riding the segmented layout's per-segment slot machinery), structured
``resilience`` records, rollback with a re-initialized loss scale, and
the give-up-loudly rollback limit (apex_tpu/resilience/watchdog.py).

Acceptance bar (ISSUE 2): injected persistent-NaN grads trigger
segment localization naming the poisoned parameter, a structured
``resilience`` record, and rollback, while a single transient NaN step
stays a plain skip (no rollback, no record).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import records
from apex_tpu.amp.scaler import LossScaler
from apex_tpu.multi_tensor.ops import per_tensor_l2norm
from apex_tpu.multi_tensor.segmented import segmented_per_leaf_sumsq
from apex_tpu.optimizers import FusedAdam, FusedLAMB
from apex_tpu.optimizers.train_step import make_train_step
from apex_tpu.resilience import (
    CheckpointManager,
    FaultInjector,
    NonfiniteWatchdog,
    RollbackLimitExceeded,
    RollbackUnavailable,
    leaf_names,
    localize_nonfinite,
)


def _params(seed=0):
    r = np.random.RandomState(seed)
    return {"b": jnp.zeros((6,), jnp.float32),
            "w1": jnp.asarray(r.randn(32, 6), jnp.float32),
            "w2": jnp.asarray(r.randn(6, 6), jnp.float32)}


@pytest.fixture
def records_dir(tmp_path, monkeypatch):
    path = tmp_path / "records"
    monkeypatch.setattr(records, "RECORDS_DIR", str(path))
    return path


class _Rig:
    """Watchdog test rig: fused step + checkpoint manager + a
    deterministic NaN injector poisoning one named leaf."""

    def __init__(self, tmp_path, threshold=2, poison_leaf=2, opt=None,
                 **wd_kwargs):
        self.opt = opt if opt is not None else FusedAdam(lr=1e-2, impl="xla")
        self.scaler = LossScaler(init_scale=2.0 ** 8, scale_window=100)
        self.step = make_train_step(self.opt, scaler=self.scaler)
        self.state = self.opt.init(_params())
        self.sstate = self.scaler.init()
        self.mgr = CheckpointManager(tmp_path / "ckpt", keep=3)
        self.wd = NonfiniteWatchdog(self.step, manager=self.mgr,
                                    threshold=threshold, **wd_kwargs)
        self.inj = FaultInjector(nan_grad_steps=frozenset(),
                                 nan_leaf=poison_leaf)
        r = np.random.RandomState(42)
        self.g = jnp.asarray(
            r.randn(self.state.space.total).astype(np.float32) * 0.01)

    def drive(self, i, poisoned=False):
        g = self.g
        if poisoned:
            self.inj.nan_grad_steps = frozenset({i})
            g = self.inj.poison_grads(g, i, space=self.state.space)
        self.state, self.sstate, aux = self.wd(self.state, g, self.sstate)
        return aux


class TestPlainSkip:
    def test_single_transient_nan_is_a_skip_not_a_rollback(
            self, tmp_path, records_dir):
        rig = _Rig(tmp_path, threshold=2)
        rig.drive(0)
        rig.mgr.save(1, rig.state, scaler_state=rig.sstate)
        scale = float(rig.sstate.loss_scale)
        aux = rig.drive(1, poisoned=True)          # one bad step
        assert float(aux.found_inf) == 1.0
        assert rig.wd.consecutive_skips == 1
        # the amp contract, untouched: scale halved, update skipped
        assert float(rig.sstate.loss_scale) == scale / 2
        rig.drive(2)                               # clean step resets
        assert rig.wd.consecutive_skips == 0
        assert rig.wd.escalations == 0 and rig.wd.last_event is None
        assert records.latest_record("resilience",
                                     require_backend=None) is None

    def test_good_steps_update_params(self, tmp_path, records_dir):
        rig = _Rig(tmp_path)
        before = np.asarray(rig.state.master).copy()
        rig.drive(0)
        assert not np.array_equal(np.asarray(rig.state.master), before)


class TestEscalation:
    def test_persistent_nan_localizes_records_and_rolls_back(
            self, tmp_path, records_dir):
        rig = _Rig(tmp_path, threshold=3, poison_leaf=2)
        rig.drive(0)
        rig.mgr.save(1, rig.state, scaler_state=rig.sstate)
        ckpt_master = np.asarray(rig.state.master).copy()
        rig.drive(1)                               # diverge past the ckpt
        post_master = np.asarray(rig.state.master).copy()
        assert not np.array_equal(post_master, ckpt_master)

        for i in range(2, 5):                      # 3 consecutive NaN steps
            rig.drive(i, poisoned=True)

        event = rig.wd.last_event
        assert event is not None
        assert event["action"] == "rollback"
        assert event["consecutive_skips"] == 3
        # localization names EXACTLY the poisoned parameter
        assert [s["name"] for s in event["suspects"]] == ["['w2']"]
        assert event["restored_step"] == 1
        # rolled back to the checkpointed master, not the diverged one
        np.testing.assert_array_equal(np.asarray(rig.state.master),
                                      ckpt_master)
        # loss scale RE-INITIALIZED, not the ground-down one
        assert float(rig.sstate.loss_scale) == 2.0 ** 8
        # each NaN step halved the scale inside the compiled step
        assert event["loss_scale_before"] == 2.0 ** 8 / 8
        rec = records.latest_record("resilience", require_backend=None)
        assert rec["payload"]["event"] == "nonfinite_escalation"
        assert rec["payload"]["suspects"] == event["suspects"]
        # training continues cleanly after rollback
        rig.drive(5)
        assert rig.wd.consecutive_skips == 0

    def test_no_manager_resets_scaler_only(self, tmp_path, records_dir):
        rig = _Rig(tmp_path, threshold=2)
        rig.wd.manager = None
        rig.drive(0)
        for i in range(1, 3):
            rig.drive(i, poisoned=True)
        assert rig.wd.last_event["action"] == "scaler_reset"
        assert float(rig.sstate.loss_scale) == 2.0 ** 8

    def test_rollback_limit_raises_with_suspects(self, tmp_path,
                                                 records_dir):
        rig = _Rig(tmp_path, threshold=1, max_rollbacks=1, poison_leaf=0)
        rig.drive(0)
        rig.mgr.save(1, rig.state, scaler_state=rig.sstate)
        rig.drive(1, poisoned=True)                # escalation 1: rollback
        assert rig.wd.escalations == 1
        with pytest.raises(RollbackLimitExceeded) as ei:
            rig.drive(2, poisoned=True)            # escalation 2: give up
        assert [s["name"] for s in ei.value.suspects] == ["['b']"]

    def test_on_event_callback_fires(self, tmp_path, records_dir):
        seen = []
        rig = _Rig(tmp_path, threshold=1, on_event=seen.append)
        rig.drive(0)
        rig.mgr.save(1, rig.state, scaler_state=rig.sstate)
        rig.drive(1, poisoned=True)
        assert len(seen) == 1 and seen[0]["event"] == "nonfinite_escalation"

    def test_cold_start_empty_directory_raises_clear_error(
            self, tmp_path, records_dir):
        # a manager is attached but its directory holds NO checkpoint
        # (cold start / wrong path): escalation must raise a
        # RollbackLimitExceeded-subclass NAMING the directory, not loop
        # scaler resets or die on an internal error
        rig = _Rig(tmp_path, threshold=2)
        with pytest.raises(RollbackUnavailable) as ei:
            for i in range(4):
                rig.drive(i, poisoned=True)
        msg = str(ei.value)
        assert str(rig.mgr.directory) in msg
        assert "no valid checkpoint" in msg
        assert [s["name"] for s in ei.value.suspects] == ["['w2']"]
        assert isinstance(ei.value, RollbackLimitExceeded)  # catchable as

    def test_cold_start_absent_directory_raises_clear_error(
            self, tmp_path, records_dir):
        import shutil

        rig = _Rig(tmp_path, threshold=1)
        shutil.rmtree(rig.mgr.directory)        # directory vanished
        with pytest.raises(RollbackUnavailable, match="no valid checkpoint"):
            rig.drive(0, poisoned=True)


class TestLocalization:
    def test_segmented_sumsq_matches_subtile_path_on_finite_data(self):
        opt = FusedLAMB(lr=1e-3, impl="xla", segmented=True)
        st = opt.init(_params())
        r = np.random.RandomState(0)
        # pack a gradient TREE so padding regions are zero, like a real
        # grad buffer (the two reductions bill inter-leaf padding to
        # different owners; on real buffers the padding is always zero)
        gtree = {k: jnp.asarray(r.randn(*v.shape), jnp.float32)
                 for k, v in _params().items()}
        g = st.space.pack(gtree, dtype=jnp.float32)
        seg = np.sqrt(np.asarray(
            segmented_per_leaf_sumsq(g, st.space, st.seg_meta)))
        ref = np.asarray(per_tensor_l2norm(g, st.space, impl="xla"))
        np.testing.assert_allclose(seg, ref, rtol=1e-5)

    def test_nan_flags_only_the_poisoned_leaf(self):
        opt = FusedLAMB(lr=1e-3, impl="xla", segmented=True)
        st = opt.init(_params())
        g = st.space.zeros() + 1.0
        off = st.space.offsets[1]                  # 'w1'
        g = g.at[off + 3].set(jnp.nan)
        sumsq = np.asarray(segmented_per_leaf_sumsq(g, st.space,
                                                    st.seg_meta))
        assert not np.isfinite(sumsq[1])
        assert np.isfinite(np.delete(sumsq, 1)).all()
        suspects = localize_nonfinite(st.space, g, seg_meta=st.seg_meta)
        assert [s["leaf"] for s in suspects] == [1]
        assert suspects[0]["name"] == "['w1']"

    def test_leaf_names_follow_flat_order(self):
        opt = FusedAdam(lr=1e-3, impl="xla")
        st = opt.init(_params())
        assert leaf_names(st.space) == ["['b']", "['w1']", "['w2']"]

    def test_with_grad_norm_variant_feeds_aux_norms(self, tmp_path,
                                                    records_dir):
        # the zero-extra-pass monitoring path: a with_grad_norm LAMB
        # step reports per-tensor norms in its aux (segmented phase-0
        # accumulators on kernel impls), and the watchdog localizes
        # from them without touching the grads again
        opt = FusedLAMB(lr=1e-3, impl="xla", segmented=True)
        scaler = LossScaler(init_scale=2.0 ** 8, scale_window=100)
        base = make_train_step(opt, scaler=scaler)
        step = base.with_options(with_grad_norm=True)
        assert step is base.with_options(with_grad_norm=True)  # cached
        assert step.options["with_grad_norm"] is True
        state = opt.init(_params())
        sstate = scaler.init()
        wd = NonfiniteWatchdog(step, threshold=1)
        g = state.space.zeros() + 1e-3
        g = g.at[state.space.offsets[2]].set(jnp.inf)          # 'w2'
        state, sstate, aux = wd(state, g, sstate)
        assert aux.grad_norm_per_tensor is not None
        assert [s["name"] for s in wd.last_event["suspects"]] == ["['w2']"]

    def test_donated_grads_localize_from_aux_only(self, tmp_path,
                                                  records_dir):
        opt = FusedLAMB(lr=1e-3, impl="xla", segmented=True)
        scaler = LossScaler(init_scale=2.0 ** 8, scale_window=100)
        step = make_train_step(opt, scaler=scaler, donate_grads=True,
                               with_grad_norm=True)
        state = opt.init(_params())
        sstate = scaler.init()
        wd = NonfiniteWatchdog(step, threshold=1)
        g = state.space.zeros() + 1e-3
        g = g.at[state.space.offsets[0]].set(jnp.nan)          # 'b'
        state, sstate, aux = wd(state, g, sstate)
        assert [s["name"] for s in wd.last_event["suspects"]] == ["['b']"]


class TestNoScalerWatchdog:
    def test_two_tuple_signature_and_rollback(self, tmp_path, records_dir):
        opt = FusedAdam(lr=1e-2, impl="xla")
        step = make_train_step(opt, skip_if_nonfinite=True)
        state = opt.init(_params())
        mgr = CheckpointManager(tmp_path / "ckpt")
        wd = NonfiniteWatchdog(step, manager=mgr, threshold=1)
        r = np.random.RandomState(0)
        g = jnp.asarray(r.randn(state.space.total).astype(np.float32))
        state, aux = wd(state, g)
        mgr.save(1, state)
        ckpt = np.asarray(state.master).copy()
        state, aux = wd(state, g)                  # diverge
        state, aux = wd(state, g.at[0].set(jnp.nan))
        assert wd.last_event["action"] == "rollback"
        assert wd.last_event["loss_scale_before"] is None
        np.testing.assert_array_equal(np.asarray(state.master), ckpt)

"""Resilience subsystem: retry policy, fault injection, atomic
checkpointing, kill-and-resume, and the prefetch pipeline's transfer
fault tolerance (apex_tpu/resilience, docs/resilience.md).

The acceptance bar (ISSUE 2): a run killed mid-training by an injected
fault auto-resumes from ``latest_valid()`` and replays a
bitwise-identical trajectory vs. the uninterrupted run; with the
newest checkpoint fault-injected to be truncated, resume falls back to
the previous valid checkpoint and a corrupt-checkpoint event is
recorded.
"""

import json
import os
import random

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import records
from apex_tpu.amp.scaler import LossScaler
from apex_tpu.optimizers import FusedAdam
from apex_tpu.optimizers.train_step import make_train_step
from apex_tpu.resilience import (
    CheckpointError,
    CheckpointManager,
    FaultInjector,
    SimulatedCrash,
    backoff_delays,
    faults,
    retry_call,
)
from apex_tpu.runtime import PrefetchLoader


class TestRetry:
    def test_success_after_transient(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "ok"

        slept = []
        assert retry_call(flaky, retries=4, base_delay=0.1, jitter=0.0,
                          sleep=slept.append) == "ok"
        assert calls["n"] == 3
        assert slept == [0.1, 0.2]        # exponential, no jitter

    def test_exhaustion_reraises_original(self):
        def dead():
            raise OSError("dead disk")

        with pytest.raises(OSError, match="dead disk"):
            retry_call(dead, retries=2, base_delay=0.0, sleep=lambda d: None)

    def test_retry_on_filters(self):
        def typed():
            raise ValueError("not retryable")

        calls = {"n": 0}

        def count():
            calls["n"] += 1
            raise ValueError("boom")

        with pytest.raises(ValueError):
            retry_call(typed, retries=3, retry_on=(OSError,),
                       sleep=lambda d: None)
        with pytest.raises(ValueError):
            retry_call(count, retries=3, retry_on=(ValueError,),
                       base_delay=0.0, sleep=lambda d: None)
        assert calls["n"] == 4            # retried when listed

    def test_deadline_bounds_total_time(self):
        clock = {"t": 0.0}

        def monotonic():
            return clock["t"]

        def sleep(d):
            clock["t"] += d

        calls = {"n": 0}

        def dead():
            calls["n"] += 1
            clock["t"] += 0.4             # each attempt costs 0.4s
            raise OSError("down")

        with pytest.raises(OSError):
            retry_call(dead, retries=50, base_delay=0.1, factor=1.0,
                       jitter=0.0, deadline=1.0, sleep=sleep,
                       monotonic=monotonic)
        # attempts stop once the 1s budget is gone — nowhere near 51
        assert calls["n"] <= 3
        assert clock["t"] <= 1.5

    def test_jitter_is_deterministic_with_seeded_rng(self):
        a = backoff_delays(4, jitter=0.5, rng=random.Random(7))
        b = backoff_delays(4, jitter=0.5, rng=random.Random(7))
        c = backoff_delays(4, jitter=0.5, rng=random.Random(8))
        assert a == b and a != c

    def test_on_retry_callback(self):
        seen = []

        def flaky():
            if len(seen) < 2:
                raise OSError("x")
            return 1

        retry_call(flaky, retries=3, base_delay=0.01, jitter=0.0,
                   on_retry=lambda i, e, d: seen.append((i, str(e), d)),
                   sleep=lambda d: None)
        assert [s[0] for s in seen] == [0, 1]

    def test_give_up_on_passes_through_immediately(self):
        from apex_tpu.resilience import CheckpointError

        calls = {"n": 0}

        def validation_failure():
            calls["n"] += 1
            raise CheckpointError("sha256 mismatch")

        slept = []
        # CheckpointError matches the broad retry_on, but the allowlist
        # wins: ONE attempt, zero sleeps, original exception unchanged
        with pytest.raises(CheckpointError, match="sha256"):
            retry_call(validation_failure, retries=5, base_delay=0.1,
                       retry_on=(RuntimeError,),
                       give_up_on=(CheckpointError,),
                       sleep=slept.append)
        assert calls["n"] == 1 and slept == []

    def test_give_up_on_does_not_shadow_retryable_siblings(self):
        from apex_tpu.resilience import CheckpointError

        calls = {"n": 0}

        def transient():
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("transient")
            return "ok"

        # sibling RuntimeErrors NOT in the allowlist keep retrying
        assert retry_call(transient, retries=5, base_delay=0.0,
                          retry_on=(RuntimeError,),
                          give_up_on=(CheckpointError,),
                          sleep=lambda d: None) == "ok"
        assert calls["n"] == 3

    def test_named_site_publishes_attempts_and_terminals(
            self, monkeypatch):
        from apex_tpu import telemetry
        from apex_tpu.telemetry import metrics as tmetrics

        reg = telemetry.MetricsRegistry()
        sink = telemetry.InMemorySink()
        reg.add_sink(sink)
        monkeypatch.setattr(tmetrics, "_REGISTRY", reg)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "ok"

        assert retry_call(flaky, retries=4, base_delay=0.0, jitter=0.0,
                          sleep=lambda d: None, site="disk") == "ok"
        # one counter bump + one flight-ring event per SLEEP, labelled
        # by site, with the attempt index and the error on the event
        assert reg.counter("retry_attempts").value(site="disk") == 2
        evs = [e for e in sink.events if e["event"] == "retry"]
        assert [e["attempt"] for e in evs] == [0, 1]
        assert all(e["site"] == "disk" for e in evs)
        assert all("transient" in e["error"] for e in evs)
        # exhaustion: terminal counter + event, original exception kept
        with pytest.raises(OSError, match="dead"):
            retry_call(lambda: (_ for _ in ()).throw(OSError("dead")),
                       retries=1, base_delay=0.0,
                       sleep=lambda d: None, site="disk")
        assert reg.counter("retry_exhausted").value(site="disk") == 1
        assert "retry_exhausted" in [e["event"] for e in sink.events]
        # give-up pass-through: its own terminal, zero extra attempts
        def fatal():
            raise CheckpointError("bad bytes")

        with pytest.raises(CheckpointError):
            retry_call(fatal, retries=3, retry_on=(Exception,),
                       give_up_on=(CheckpointError,), base_delay=0.0,
                       sleep=lambda d: None, site="ckpt")
        assert reg.counter("retry_give_up").value(site="ckpt") == 1
        assert reg.counter("retry_attempts").value(site="ckpt") == 0

    def test_siteless_calls_publish_nothing(self, monkeypatch):
        from apex_tpu import telemetry
        from apex_tpu.telemetry import metrics as tmetrics

        reg = telemetry.MetricsRegistry()
        monkeypatch.setattr(tmetrics, "_REGISTRY", reg)
        with pytest.raises(OSError):
            retry_call(lambda: (_ for _ in ()).throw(OSError("x")),
                       retries=1, base_delay=0.0, sleep=lambda d: None)
        snap = reg.snapshot()
        assert not any(n.startswith("retry")
                       for n in snap.get("counters", {}))

    def test_keyboard_interrupt_never_retried(self):
        from apex_tpu.resilience.retry import NON_RETRYABLE

        assert KeyboardInterrupt in NON_RETRYABLE
        calls = {"n": 0}

        def interrupted():
            calls["n"] += 1
            raise KeyboardInterrupt

        # even a catch-all retry_on cannot make ctrl-C burn the deadline
        with pytest.raises(KeyboardInterrupt):
            retry_call(interrupted, retries=5, base_delay=0.0,
                       retry_on=(BaseException,), sleep=lambda d: None)
        assert calls["n"] == 1


class TestFaults:
    def test_env_grammar_roundtrip(self):
        inj = FaultInjector.from_env(
            "nan_grads=3,4;nan_leaf=2;io:device_put=0,1;"
            "io_permanent:record_write=5;truncate=12;crash=7")
        assert inj.nan_grad_steps == frozenset({3, 4})
        assert inj.nan_leaf == 2
        assert inj.io_errors["device_put"] == frozenset({0, 1})
        assert inj.io_permanent_from["record_write"] == 5
        assert inj.should_truncate(12) and not inj.should_truncate(11)
        with pytest.raises(SimulatedCrash):
            inj.maybe_crash(7)
        inj.maybe_crash(6)                # no-op
        with pytest.raises(ValueError, match="unknown"):
            FaultInjector.from_env("frobnicate=1")

    def test_site_counters_are_deterministic(self):
        inj = FaultInjector(io_errors={"s": frozenset({1})},
                            io_permanent_from={"p": 2})
        inj.check("s")                    # idx 0: ok
        with pytest.raises(faults.FaultError):
            inj.check("s")                # idx 1: transient
        inj.check("s")                    # idx 2: ok again
        inj.check("p"), inj.check("p")    # 0, 1 ok
        for _ in range(3):
            with pytest.raises(faults.FaultError):
                inj.check("p")            # 2.. permanent

    def test_poison_grads_targets_leaf(self):
        opt = FusedAdam(lr=1e-3, impl="xla")
        st = opt.init({"a": jnp.zeros((16,)), "b": jnp.zeros((4, 4))})
        inj = FaultInjector(nan_grad_steps=frozenset({5}), nan_leaf=1)
        g = st.space.zeros()
        assert np.isfinite(np.asarray(inj.poison_grads(g, 4,
                                                       space=st.space))).all()
        bad = np.asarray(inj.poison_grads(g, 5, space=st.space))
        off = st.space.offsets[1]
        assert np.isnan(bad[off])
        assert np.isfinite(bad[:off]).all()   # other leaf untouched

    def test_env_knob_activates(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_KNOB, "nan_grads=2")
        faults.install(None)
        inj = faults.active()
        assert inj is not None and inj.should_poison(2)
        monkeypatch.delenv(faults.ENV_KNOB)
        assert faults.active() is None

    def test_inject_restores_previous(self):
        assert faults.active() is None
        with faults.inject(crash_steps=frozenset({1})):
            assert faults.active() is not None
        assert faults.active() is None


def _params(seed=0, n=48, d=6):
    r = np.random.RandomState(seed)
    return {"w": jnp.asarray(r.randn(n, d), jnp.float32),
            "b": jnp.zeros((d,), jnp.float32)}


class TestCheckpointManager:
    def _state(self, seed=0):
        opt = FusedAdam(lr=1e-2, impl="xla")
        return opt, opt.init(_params(seed))

    def test_roundtrip_bitwise(self, tmp_path):
        opt, st = self._state()
        scaler = LossScaler()
        ss = scaler.update(scaler.init(), jnp.asarray(1.0))
        rng = np.random.RandomState(3)
        rng.randn(5)
        mgr = CheckpointManager(tmp_path)
        mgr.save(7, st, scaler_state=ss, rng_state=rng,
                 extra={"epoch": 2})
        r = mgr.restore(template=self._state(seed=1)[1])
        assert r.step == 7 and r.extra == {"epoch": 2}
        np.testing.assert_array_equal(np.asarray(r.opt_state.master),
                                      np.asarray(st.master))
        for k in st.slots:
            np.testing.assert_array_equal(np.asarray(r.opt_state.slots[k]),
                                          np.asarray(st.slots[k]))
        assert int(r.opt_state.count) == int(st.count)
        assert float(r.scaler_state.loss_scale) == float(ss.loss_scale)
        assert float(r.scaler_state.found_inf) == 1.0
        # host RNG stream continues exactly where the original left off
        np.testing.assert_array_equal(r.rng_state.randn(4), rng.randn(4))

    def test_retention_keeps_last_k(self, tmp_path):
        _, st = self._state()
        mgr = CheckpointManager(tmp_path, keep=2)
        for step in (1, 2, 3, 4):
            mgr.save(step, st)
        assert mgr.all_steps() == [3, 4]

    def test_latest_valid_skips_truncated_and_records_event(
            self, tmp_path, monkeypatch):
        monkeypatch.setattr(records, "RECORDS_DIR",
                            str(tmp_path / "records"))
        _, st = self._state()
        mgr = CheckpointManager(tmp_path / "ckpt", keep=3)
        mgr.save(1, st)
        with faults.inject(truncate_steps=frozenset({2})):
            mgr.save(2, st)               # finalized, then corrupted
        ok, reason = mgr.validate(mgr.path_for(2))
        assert not ok and "truncated" in reason
        assert mgr.latest_valid() == mgr.path_for(1)
        rec = records.latest_record("resilience", require_backend=None)
        assert rec["payload"]["event"] == "corrupt_checkpoint"
        assert rec["payload"]["step"] == 2

    def test_latest_valid_skips_corrupt_manifest_and_bitrot(self, tmp_path):
        _, st = self._state()
        mgr = CheckpointManager(tmp_path, keep=4)
        mgr.save(1, st), mgr.save(2, st), mgr.save(3, st)
        with open(os.path.join(mgr.path_for(3), "manifest.json"), "w") as f:
            f.write("{not json")
        # same-size bit flip: only the sha catches it
        ppath = os.path.join(mgr.path_for(2), "payload.bin")
        with open(ppath, "r+b") as f:
            f.seek(8)
            b = f.read(1)
            f.seek(8)
            f.write(bytes([b[0] ^ 0xFF]))
        assert mgr.latest_valid(record_events=False) == mgr.path_for(1)
        assert mgr.validate(mgr.path_for(2))[1] == "sha256 mismatch"

    def test_failed_write_leaves_no_partial_checkpoint(self, tmp_path):
        _, st = self._state()
        mgr = CheckpointManager(tmp_path, keep=3)
        with faults.inject(io_permanent_from={"checkpoint_write": 0}):
            with pytest.raises(OSError):
                mgr.save(1, st)
        assert mgr.all_steps() == []
        assert not [n for n in os.listdir(tmp_path) if ".tmp-" in n]
        # transient write errors are absorbed by the retry
        with faults.inject(io_errors={"checkpoint_write": frozenset({0})}):
            mgr.save(2, st)
        assert mgr.latest_valid(record_events=False) == mgr.path_for(2)

    def test_stale_tmp_dirs_swept_at_startup(self, tmp_path):
        os.makedirs(tmp_path / "step_000000000009.tmp-123-456")
        CheckpointManager(tmp_path)
        assert not [n for n in os.listdir(tmp_path) if ".tmp-" in n]

    def test_bf16_compressed_master(self, tmp_path):
        import ml_dtypes

        _, st = self._state()
        mgr = CheckpointManager(tmp_path, compress_master=True)
        mgr.save(1, st)
        manifest = mgr.read_manifest(mgr.path_for(1))
        assert manifest["master_compressed"] is True
        assert manifest["arrays"][0]["dtype"] == "bfloat16"
        r = mgr.restore(template=st)
        # bf16 round-trip: exact at bf16 resolution, fp32 dtype back
        assert r.opt_state.master.dtype == jnp.float32
        np.testing.assert_array_equal(
            np.asarray(r.opt_state.master),
            np.asarray(st.master).astype(ml_dtypes.bfloat16).astype(
                np.float32))

    def test_async_save_overlaps_and_wait_raises(self, tmp_path):
        _, st = self._state()
        mgr = CheckpointManager(tmp_path, async_save=True)
        path = mgr.save(1, st)
        mgr.wait()
        assert mgr.validate(path)[0]
        with faults.inject(io_permanent_from={"checkpoint_write": 0}):
            mgr.save(2, st)
            with pytest.raises(OSError):
                mgr.wait()
        # a failed async save must not poison the next one
        mgr.save(3, st)
        mgr.wait()
        assert mgr.latest_valid(record_events=False) == mgr.path_for(3)

    def test_restore_rejects_layout_mismatch(self, tmp_path):
        opt, st = self._state()
        mgr = CheckpointManager(tmp_path)
        mgr.save(1, st)
        other = FusedAdam(lr=1e-2, impl="xla").init(
            {"w": jnp.zeros((4, 4), jnp.float32)})
        with pytest.raises(CheckpointError, match="different parameter"):
            mgr.restore(template=other)

    def test_restore_without_any_checkpoint(self, tmp_path):
        _, st = self._state()
        with pytest.raises(CheckpointError, match="no valid checkpoint"):
            CheckpointManager(tmp_path).restore(template=st)


# ---------------------------------------------------------------------------
# Kill-and-resume (the acceptance scenario)
# ---------------------------------------------------------------------------


class _Trainer:
    """Deterministic fused-step training harness: per-step gradients are
    a pure function of the step index, so two runs over the same steps
    are comparable bitwise."""

    def __init__(self):
        self.opt = FusedAdam(lr=1e-2, impl="xla")
        self.scaler = LossScaler(init_scale=2.0 ** 10, scale_window=3)
        self.step = make_train_step(self.opt, scaler=self.scaler)
        self.state = self.opt.init(_params())
        self.sstate = self.scaler.init()

    def grad(self, i):
        r = np.random.RandomState(1000 + i)
        return jnp.asarray(
            r.randn(self.state.space.total).astype(np.float32) * 0.01)

    def run(self, start, stop, mgr=None, ckpt_every=2):
        probes = {}
        for i in range(start, stop):
            faults.maybe_crash(i)
            self.state, self.sstate, _ = self.step(
                self.state, self.grad(i), self.sstate)
            probes[i] = np.asarray(self.state.master[:16]).copy()
            if mgr is not None and (i + 1) % ckpt_every == 0:
                # manifest step = the next step to run on resume
                mgr.save(i + 1, self.state, scaler_state=self.sstate)
        return probes

    def resume_from(self, mgr):
        restored = mgr.restore(template=self.state)
        self.state = restored.opt_state
        self.sstate = restored.scaler_state
        return restored.step


class TestKillAndResume:
    STEPS = 9

    def test_resume_replays_bitwise(self, tmp_path, monkeypatch):
        monkeypatch.setattr(records, "RECORDS_DIR",
                            str(tmp_path / "records"))
        golden = _Trainer()
        ref = golden.run(0, self.STEPS)

        mgr = CheckpointManager(tmp_path / "ckpt", keep=3)
        victim = _Trainer()
        with faults.inject(crash_steps=frozenset({5})):
            with pytest.raises(SimulatedCrash):
                victim.run(0, self.STEPS, mgr=mgr)

        # "new process": fresh optimizer/step/state, auto-resume
        revived = _Trainer()
        start = revived.resume_from(mgr)
        assert start == 4                 # newest checkpoint before the kill
        probes = revived.run(start, self.STEPS, mgr=mgr)
        for i in range(start, self.STEPS):
            np.testing.assert_array_equal(probes[i], ref[i])
        np.testing.assert_array_equal(np.asarray(revived.state.master),
                                      np.asarray(golden.state.master))
        assert float(revived.sstate.loss_scale) == float(
            golden.sstate.loss_scale)
        assert int(revived.state.count) == int(golden.state.count)

    def test_corrupt_newest_falls_back_to_previous(self, tmp_path,
                                                   monkeypatch):
        monkeypatch.setattr(records, "RECORDS_DIR",
                            str(tmp_path / "records"))
        golden = _Trainer()
        ref = golden.run(0, self.STEPS)

        mgr = CheckpointManager(tmp_path / "ckpt", keep=3)
        victim = _Trainer()
        # checkpoint written at step 6 is truncated ON DISK after
        # finalize, and the run is killed right after
        with faults.inject(crash_steps=frozenset({7}),
                           truncate_steps=frozenset({6})):
            with pytest.raises(SimulatedCrash):
                victim.run(0, self.STEPS, mgr=mgr)

        revived = _Trainer()
        start = revived.resume_from(mgr)
        assert start == 4                 # fell PAST the corrupt step-6 ckpt
        rec = records.latest_record("resilience", require_backend=None)
        assert rec["payload"]["event"] == "corrupt_checkpoint"
        assert rec["payload"]["step"] == 6
        probes = revived.run(start, self.STEPS)
        for i in range(start, self.STEPS):
            np.testing.assert_array_equal(probes[i], ref[i])


class TestPrefetchTransferFaults:
    def _batches(self, n=5):
        return [np.full((3,), i, np.float32) for i in range(n)]

    def test_transient_failures_retried_in_order(self):
        with faults.inject(io_errors={"device_put": frozenset({0, 2})}):
            loader = PrefetchLoader(iter(self._batches()), depth=2,
                                    retry_base_delay=0.001)
            out = list(loader)
        assert len(out) == 5 and not loader.degraded
        for i, b in enumerate(out):
            np.testing.assert_array_equal(np.asarray(b),
                                          np.full((3,), i, np.float32))

    def test_repeated_deaths_degrade_to_synchronous(self):
        # retries=1 -> 2 tries/attempt; restarts=1 -> 2 workers die on
        # batch 0 (injected calls 0..3), then the synchronous fallback
        # finishes the epoch — no batch lost, order preserved
        with faults.inject(io_errors={"device_put": frozenset({0, 1, 2, 3})}):
            loader = PrefetchLoader(iter(self._batches(4)), depth=2,
                                    transfer_retries=1,
                                    max_worker_restarts=1,
                                    retry_base_delay=0.001)
            out = list(loader)
        assert loader.degraded and loader.worker_deaths == 2
        assert len(out) == 4
        for i, b in enumerate(out):
            np.testing.assert_array_equal(np.asarray(b),
                                          np.full((3,), i, np.float32))

    def test_transform_runs_once_per_batch_across_restarts(self):
        seen = []

        def transform(b):
            seen.append(int(b[0]))
            return b * 2

        with faults.inject(io_errors={"device_put": frozenset({0, 1})}):
            loader = PrefetchLoader(iter(self._batches(3)), depth=2,
                                    transfer_retries=0,
                                    max_worker_restarts=2,
                                    transform=transform,
                                    retry_base_delay=0.001)
            out = list(loader)
        assert sorted(seen) == [0, 1, 2]          # no double-transform
        np.testing.assert_array_equal(np.asarray(out[0]),
                                      np.zeros((3,), np.float32))

    def test_source_errors_never_retried(self):
        def gen():
            yield np.zeros((1,), np.float32)
            raise ValueError("boom")

        loader = PrefetchLoader(gen(), depth=2, transfer_retries=5)
        with pytest.raises(ValueError, match="boom"):
            list(loader)
        assert loader.worker_deaths == 0


class TestRecordWriteFaults:
    def test_transient_disk_error_absorbed(self, tmp_path, monkeypatch):
        monkeypatch.setattr(records, "RECORDS_DIR", str(tmp_path))
        with faults.inject(io_errors={"record_write": frozenset({0})}):
            path = records.write_record("resil_unit", {"x": 1})
        assert path is not None and os.path.exists(path)
        with open(path) as f:
            assert json.load(f)["payload"] == {"x": 1}

    def test_permanent_disk_error_returns_none(self, tmp_path, monkeypatch):
        monkeypatch.setattr(records, "RECORDS_DIR", str(tmp_path))
        with faults.inject(io_permanent_from={"record_write": 0}):
            assert records.write_record("resil_unit", {"x": 1}) is None
        assert os.listdir(tmp_path) == []

"""Test harness config.

Mirrors the reference's "multi-process on one node, no cluster needed"
strategy (ref: apex/transformer/testing/distributed_test_base.py:30-103)
the TPU way: a simulated 8-device CPU mesh via
``--xla_force_host_platform_device_count`` (SURVEY.md §4 "TPU translation").
Must run before jax initializes its backend, hence module-level in conftest.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon TPU-tunnel plugin (injected via sitecustomize at interpreter
# start) hooks jax backend lookup and blocks CPU-only runs on tunnel
# availability. Tests are CPU-only by design — unregister it.
sys.path = [p for p in sys.path if ".axon_site" not in p]
os.environ.pop("PYTHONPATH", None)

import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
_hook = _xb._get_backend_uncached
if getattr(_hook, "__name__", "") == "_axon_get_backend_uncached":
    for _cell in _hook.__closure__ or ():
        if callable(_cell.cell_contents):
            _xb._get_backend_uncached = _cell.cell_contents
jax.config.update("jax_platforms", "cpu")
os.environ["JAX_PLATFORMS"] = "cpu"

jax.config.update("jax_threefry_partitionable", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)


@pytest.fixture(params=["xla", "interpret"])
def impl(request):
    """Every fused op runs both the XLA reference path and the Pallas
    kernel (interpreter mode on CPU), mirroring the reference's
    kernel-vs-reference test style (ref: tests/L0/run_amp/test_multi_tensor_scale.py)."""
    return request.param


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "l1: cross-product integration tier (ref tests/L1/cross_product)")

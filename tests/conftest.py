"""Test harness config.

Mirrors the reference's "multi-process on one node, no cluster needed"
strategy (ref: apex/transformer/testing/distributed_test_base.py:30-103)
the TPU way: a simulated 8-device CPU mesh via
``--xla_force_host_platform_device_count`` (SURVEY.md §4 "TPU translation").
Must run before jax initializes its backend, hence module-level in conftest.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
# The tier-1 budget is wall-clock-bound and the suite is dominated by
# XLA:CPU compile time (~1000 programs); the tests assert numerics and
# program structure, not generated-code quality, so skip the backend
# optimization pipeline. Callers who want optimized code (perf smokes)
# can pre-set the flag themselves.
if "xla_backend_optimization_level" not in flags:
    flags = (flags + " --xla_backend_optimization_level=0").strip()
os.environ["XLA_FLAGS"] = flags

# The axon TPU-tunnel plugin (injected via sitecustomize at interpreter
# start) hooks jax backend lookup and blocks CPU-only runs on tunnel
# availability. Tests are CPU-only by design — unregister it.
sys.path = [p for p in sys.path if ".axon_site" not in p]
os.environ.pop("PYTHONPATH", None)

import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
_hook = _xb._get_backend_uncached
if getattr(_hook, "__name__", "") == "_axon_get_backend_uncached":
    for _cell in _hook.__closure__ or ():
        if callable(_cell.cell_contents):
            _xb._get_backend_uncached = _cell.cell_contents
jax.config.update("jax_platforms", "cpu")
os.environ["JAX_PLATFORMS"] = "cpu"

jax.config.update("jax_threefry_partitionable", True)

# Older jax runtimes ship shard_map under jax.experimental with the
# check_rep spelling of check_vma; tests are written against the modern
# surface (`from jax import shard_map`, check_vma=...). Install the
# package's compat wrapper as the top-level name so every test module
# runs on both runtimes (same shim apex_tpu._compat uses internally).
if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _compat_shard_map(f, *, mesh, in_specs, out_specs,
                          check_vma=None, **kw):
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)

    jax.shard_map = _compat_shard_map

import gc  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Keep the cyclic-GC young: the suite compiles thousands of programs, and
# the jaxpr/executable graphs the jit caches keep alive push the gen-2
# heap into the millions of objects — every full collection then scans
# all of them, and by mid-suite each test runs ~3x slower than it does
# standalone (the tier-1 budget is wall-clock-bound on 1-core CPU
# runners). Freeze the import graph out of collection now, and have the
# module-scope fixture below drop each module's compiled programs and
# re-freeze the survivors, so gen-2 scans stay proportional to one
# module's allocations rather than the whole session's.
gc.freeze()


@pytest.fixture(autouse=True, scope="module")
def _jax_cache_hygiene():
    yield
    jax.clear_caches()
    gc.collect()
    gc.freeze()


@pytest.fixture
def rng():
    return np.random.RandomState(42)


@pytest.fixture(params=["xla", "interpret"])
def impl(request):
    """Every fused op runs both the XLA reference path and the Pallas
    kernel (interpreter mode on CPU), mirroring the reference's
    kernel-vs-reference test style (ref: tests/L0/run_amp/test_multi_tensor_scale.py)."""
    return request.param


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "l1: cross-product integration tier (ref tests/L1/cross_product)")
    config.addinivalue_line(
        "markers",
        "slow: long-running integration tests excluded from the tier-1 "
        "budget (-m 'not slow'); run with -m slow before release")

"""L1 cross-product consistency tier.

One small model trained end-to-end under every mixed-precision opt
level x loss-scale combination; the per-iteration loss trajectories
must agree across configurations (ref: tests/L1/common/main_amp.py
dumps per-iteration loss, tests/L1/cross_product/run.sh runs the
opt-level x loss-scale grid, tests/L1/common/compare.py asserts
run-to-run agreement).

The reference compares full-dataset imagenet runs; here the workload is
a deterministic tanh-MLP regression (same synthetic data for every
config) so the whole grid runs in seconds on the CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.amp.frontend import OPT_LEVELS, make_scaler
from apex_tpu.optimizers import FusedSGD

STEPS = 40
LR = 0.05


def _data(rng):
    x = jnp.asarray(rng.randn(256, 16).astype(np.float32))
    w_true = jnp.asarray(rng.randn(16, 4).astype(np.float32) * 0.5)
    y = jnp.tanh(x @ w_true)
    return x, y


def _init_params(rng):
    return {
        "w1": jnp.asarray(rng.randn(16, 32).astype(np.float32) * 0.3),
        "b1": jnp.zeros((32,), jnp.float32),
        "w2": jnp.asarray(rng.randn(32, 4).astype(np.float32) * 0.3),
        "b2": jnp.zeros((4,), jnp.float32),
    }


def _forward(params, x, compute_dtype):
    """Patch-style levels run matmuls in compute_dtype (the whitelist
    cast); cast-style levels pass already-cast params."""
    if compute_dtype is not None:
        params = jax.tree.map(lambda p: p.astype(compute_dtype), params)
        x = x.astype(compute_dtype)
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    out = h @ params["w2"] + params["b2"]
    return out.astype(jnp.float32)


def _train(opt_level, loss_scale, rng_seed=0):
    """Train the fixture under one (opt_level, loss_scale) config and
    return the per-step loss trajectory (the compare.py artifact)."""
    rng = np.random.RandomState(rng_seed)
    x, y = _data(rng)
    params0 = _init_params(rng)

    opt = FusedSGD(lr=LR, momentum=0.9, impl="xla")
    cast_params, opt_state, amp_state = amp.initialize(
        params0, optimizers=opt, opt_level=opt_level, loss_scale=loss_scale)
    props = amp_state.properties
    scaler = make_scaler(props)
    sst = amp_state.scalers[0]

    @jax.jit
    def step(model_params, opt_state, sst):
        def loss_fn(p):
            pred = _forward(p, x, props.compute_dtype)
            return jnp.mean((pred - y) ** 2)

        # loss/grads on the MODEL params (cast dtype for O2/O3/O5),
        # scaled by the carried loss scale
        loss = loss_fn(model_params)
        grads = jax.grad(
            lambda p: scaler.scale_loss(loss_fn(p), sst))(model_params)
        # fused optimizer: unscale + inf-check + update one kernel pass;
        # the fp32 master lives in opt_state, step returns fp32 params
        new_params, opt_state = opt.step(
            opt_state, grads, grad_scale=sst.loss_scale,
            skip_if_nonfinite=True)
        sst2 = scaler.update(sst, opt_state.found_inf)
        # master -> model copy (the reference's post-step
        # master_params_to_model_params)
        if props.cast_model_type is not None:
            new_params = jax.tree.map(
                lambda p, m: p.astype(m.dtype), new_params, model_params)
        return loss, new_params, opt_state, sst2

    losses = []
    model_params = cast_params
    for _ in range(STEPS):
        loss, model_params, opt_state, sst = step(
            model_params, opt_state, sst)
        losses.append(float(loss))
    return np.asarray(losses)


GRID = [
    ("O0", None),
    ("O1", None),          # dynamic (level default)
    ("O1", 128.0),         # static
    ("O2", None),
    ("O2", 128.0),
    ("O3", 128.0),         # pure fp16 wants a static scale
    ("O4", None),          # bf16, no scaling
    ("O5", None),
]


@pytest.mark.l1
class TestCrossProduct:
    @pytest.fixture(scope="class")
    def trajectories(self):
        return {cfg: _train(*cfg) for cfg in GRID}

    def test_all_configs_learn(self, trajectories):
        for cfg, tr in trajectories.items():
            assert np.isfinite(tr).all(), cfg
            assert tr[-1] < tr[0] / 3.0, (cfg, tr[0], tr[-1])

    def test_trajectories_match_fp32(self, trajectories):
        """Every mixed config tracks the O0 fp32 trajectory (loose: the
        compute dtype rounds every matmul)."""
        ref = trajectories[("O0", None)]
        for cfg, tr in trajectories.items():
            np.testing.assert_allclose(
                tr, ref, rtol=0.15, atol=2e-3,
                err_msg=f"{cfg} diverged from fp32 baseline")

    def test_loss_scale_invariance(self, trajectories):
        """Same level, different loss scale: trajectories agree tightly
        (scaling must be numerically transparent, ref compare.py's
        run-to-run assertion)."""
        np.testing.assert_allclose(
            trajectories[("O1", None)], trajectories[("O1", 128.0)],
            rtol=2e-2, atol=1e-4)
        np.testing.assert_allclose(
            trajectories[("O2", None)], trajectories[("O2", 128.0)],
            rtol=2e-2, atol=1e-4)

    def test_patch_vs_cast_agreement(self, trajectories):
        """O1 ~ O2 (both fp16 math) and O4 ~ O5 (both bf16 math)."""
        np.testing.assert_allclose(
            trajectories[("O1", None)], trajectories[("O2", None)],
            rtol=5e-2, atol=5e-4)
        np.testing.assert_allclose(
            trajectories[("O4", None)], trajectories[("O5", None)],
            rtol=5e-2, atol=5e-4)

    def _train_dp(self, opt_level, loss_scale, n_dev=8):
        """The same workload dp-sharded over the simulated mesh: batch
        split over the data axis, grads psum-averaged (the apex-DDP
        gradient_average semantics), optimizer step replicated
        (ref: tests/L1/cross_product_distributed/ repeats the grid
        under DDP)."""
        import functools

        from jax import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        rng = np.random.RandomState(0)
        x, y = _data(rng)
        params0 = _init_params(rng)
        mesh = Mesh(np.asarray(jax.devices()[:n_dev]), ("data",))

        opt = FusedSGD(lr=LR, momentum=0.9, impl="xla")
        cast_params, opt_state, amp_state = amp.initialize(
            params0, optimizers=opt, opt_level=opt_level,
            loss_scale=loss_scale)
        props = amp_state.properties
        scaler = make_scaler(props)
        sst = amp_state.scalers[0]

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(), jax.tree.map(lambda _: P(), opt_state),
                      jax.tree.map(lambda _: P(), sst),
                      P("data"), P("data")),
            out_specs=(P(), P(), jax.tree.map(lambda _: P(), opt_state),
                       jax.tree.map(lambda _: P(), sst)),
            check_vma=False,
        )
        def step(model_params, opt_state, sst, xs, ys):
            def loss_fn(p):
                pred = _forward(p, xs, props.compute_dtype)
                return jnp.mean((pred - ys) ** 2)

            local_loss = loss_fn(model_params)
            grads = jax.grad(
                lambda p: scaler.scale_loss(loss_fn(p), sst))(model_params)
            # DDP: average grads (and the reported loss) over the
            # data axis — every rank then steps identically
            grads = jax.tree.map(
                lambda g: jax.lax.pmean(g, "data"), grads)
            loss = jax.lax.pmean(local_loss, "data")
            new_params, opt_state = opt.step(
                opt_state, grads, grad_scale=sst.loss_scale,
                skip_if_nonfinite=True)
            sst2 = scaler.update(sst, opt_state.found_inf)
            if props.cast_model_type is not None:
                new_params = jax.tree.map(
                    lambda p, m: p.astype(m.dtype), new_params,
                    model_params)
            return loss, new_params, opt_state, sst2

        losses = []
        model_params = cast_params
        for _ in range(STEPS):
            loss, model_params, opt_state, sst = step(
                model_params, opt_state, sst, x, y)
            losses.append(float(loss))
        return np.asarray(losses)

    @pytest.mark.parametrize("cfg", [("O0", None), ("O2", 128.0),
                                     ("O5", None)])
    def test_dp_sharded_matches_single_device(self, trajectories, cfg):
        """dp-sharded run reproduces the single-device trajectory: the
        psum-mean of per-shard grads equals the full-batch grad, so the
        whole training curve must agree to fp tolerance (the
        cross_product_distributed acceptance). Dynamic-scale configs
        are excluded from the elementwise check: per-shard fp16 grads
        are scaled BEFORE the allreduce (reference DDP semantics), so
        the overflow-skip schedule can differ by a step or two — see
        test_dp_dynamic_scale_converges."""
        tr_dp = self._train_dp(*cfg)
        # half-precision configs round each shard's grads before the
        # pmean, so mean-of-shard-means wobbles in the last bf16/fp16
        # digit vs the full-batch mean
        rtol = 2e-3 if cfg[0] == "O0" else 6e-3
        np.testing.assert_allclose(
            tr_dp, trajectories[cfg], rtol=rtol, atol=1e-5,
            err_msg=f"{cfg} dp trajectory diverged from single-device")

    def test_dp_dynamic_scale_converges(self, trajectories):
        """O2 + dynamic scale under dp: early steps may skip while the
        scale backs off (per-shard scaled fp16 grads overflow sooner
        than the full batch's), but the run must land on the same
        solution — final loss matches the single-device run."""
        tr_dp = self._train_dp("O2", None)
        assert np.isfinite(tr_dp).all()
        ref = trajectories[("O2", None)]
        np.testing.assert_allclose(tr_dp[-1], ref[-1], rtol=0.05,
                                   atol=1e-3)

    def test_dynamic_scaler_stayed_sane(self):
        """A dynamic-scale run's scaler must not collapse (no spurious
        overflow spiral) on a well-conditioned problem."""
        rng = np.random.RandomState(0)
        x, y = _data(rng)
        params = _init_params(rng)
        opt = FusedSGD(lr=LR, momentum=0.9, impl="xla")
        cast_params, opt_state, amp_state = amp.initialize(
            params, optimizers=opt, opt_level="O2")
        scaler = make_scaler(amp_state.properties)
        sst = amp_state.scalers[0]
        model_params = cast_params
        for _ in range(10):
            def loss_fn(p):
                pred = _forward(p, x, None)
                return jnp.mean((pred - y) ** 2)
            grads = jax.grad(
                lambda p: scaler.scale_loss(loss_fn(p), sst))(model_params)
            new_params, opt_state = opt.step(
                opt_state, grads, grad_scale=sst.loss_scale,
                skip_if_nonfinite=True)
            sst = scaler.update(sst, opt_state.found_inf)
            model_params = jax.tree.map(
                lambda p, m: p.astype(m.dtype), new_params, model_params)
        assert float(sst.loss_scale) >= 2.0 ** 13, float(sst.loss_scale)

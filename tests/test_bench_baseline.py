"""bench.py round-over-round baselines (ISSUE 5 satellite): records
whose bench computed no in-run ratio no longer emit
``"vs_baseline": null`` — the value is compared against the newest
PRIOR run of the same metric (bench_records entry, else a repo-root
``BENCH_r*.json`` round artifact), and a ``bench_regression``
telemetry event fires when the headline worsened past the threshold.
"""

import json

import pytest

import bench
from apex_tpu import records, telemetry


@pytest.fixture(autouse=True)
def fresh(tmp_path, monkeypatch):
    telemetry.reset()
    monkeypatch.setattr(records, "RECORDS_DIR", str(tmp_path / "records"))
    yield
    telemetry.reset()


def write_prior(kind, metric, value, utc="20260101T000000Z",
                backend="tpu"):
    import os

    os.makedirs(records.RECORDS_DIR, exist_ok=True)
    name = f"{kind}_{utc}_cafe.json"
    with open(os.path.join(records.RECORDS_DIR, name), "w") as f:
        json.dump({"kind": kind, "utc": utc, "git_sha": "cafe",
                   "backend": backend, "captured": True,
                   "payload": {"metric": metric, "value": value}}, f)
    return name


class TestPriorMeasurement:
    def test_newest_matching_record_wins(self):
        write_prior("fleet", "agg_ms", 2.0, utc="20260101T000000Z")
        write_prior("fleet", "agg_ms", 3.0, utc="20260102T000000Z")
        prior = bench.prior_measurement("agg_ms", "fleet")
        assert prior["value"] == 3.0
        assert prior["utc"] == "20260102T000000Z"
        assert prior["run"].startswith("fleet_20260102")

    def test_metric_must_match_within_kind(self):
        # error records share the kind but carry a different metric
        write_prior("fleet", "bench_fleet_error", 1.0,
                    utc="20260103T000000Z")
        write_prior("fleet", "agg_ms", 2.0, utc="20260101T000000Z")
        prior = bench.prior_measurement("agg_ms", "fleet")
        assert prior["value"] == 2.0

    def test_null_value_records_skipped(self):
        write_prior("fleet", "agg_ms", None, utc="20260104T000000Z")
        assert bench.prior_measurement("agg_ms", "fleet") is None

    def test_bench_round_artifacts_are_the_fallback(self, tmp_path):
        root = tmp_path / "root"
        root.mkdir()
        line = json.dumps({"metric": "agg_ms", "value": 4.0,
                           "unit": "ms", "vs_baseline": None})
        (root / "BENCH_r03.json").write_text(json.dumps(
            {"n": 3, "rc": 0, "tail": f"# noise\n{line}\n"}))
        (root / "BENCH_r02.json").write_text(json.dumps(
            {"n": 2, "rc": 0,
             "tail": json.dumps({"metric": "agg_ms", "value": 9.0})}))
        prior = bench.prior_measurement("agg_ms", "fleet",
                                        root=str(root))
        # highest round wins; bench_records (empty here) would beat it
        assert prior == {"value": 4.0, "run": "BENCH_r03.json"}
        write_prior("fleet", "agg_ms", 2.0)
        assert bench.prior_measurement(
            "agg_ms", "fleet", root=str(root))["value"] == 2.0

    def test_real_repo_artifacts_parse(self):
        # the actual BENCH_r*.json at the repo root: the headline
        # metric is extractable (its value may be null on CPU rounds —
        # then the scan keeps looking and may legitimately find none)
        bench.prior_measurement("fused_lamb_step_time_vs_optax",
                                "headline")       # must not raise


class TestFillVsBaseline:
    def test_populates_ratio_and_source(self):
        write_prior("fleet", "agg_ms", 2.0)
        rec = {"metric": "agg_ms", "value": 1.0, "unit": "ms (lower is "
               "better)", "vs_baseline": None, "detail": {}}
        bench._fill_vs_baseline(rec, "fleet")
        assert rec["vs_baseline"] == 0.5
        assert rec["detail"]["baseline_source"]["value"] == 2.0
        assert "regression" not in rec["detail"]

    def test_existing_in_run_baseline_untouched(self):
        write_prior("fleet", "agg_ms", 2.0)
        rec = {"metric": "agg_ms", "value": 1.0, "vs_baseline": 0.9,
               "detail": {}}
        bench._fill_vs_baseline(rec, "fleet")
        assert rec["vs_baseline"] == 0.9
        assert "baseline_source" not in rec["detail"]

    def test_no_prior_leaves_null_with_note(self):
        rec = {"metric": "agg_ms", "value": 1.0, "vs_baseline": None,
               "detail": {}}
        bench._fill_vs_baseline(rec, "fleet")
        assert rec["vs_baseline"] is None
        assert "no prior" in rec["detail"]["vs_baseline_note"]

    def test_null_value_stays_null(self):
        write_prior("fleet", "agg_ms", 2.0)
        rec = {"metric": "agg_ms", "value": None, "vs_baseline": None,
               "detail": {}}
        bench._fill_vs_baseline(rec, "fleet")
        assert rec["vs_baseline"] is None

    def test_regression_event_lower_is_better(self):
        write_prior("fleet", "agg_ms", 1.0)
        rec = {"metric": "agg_ms", "value": 1.5,
               "unit": "ms (lower is better)", "vs_baseline": None,
               "detail": {}}
        bench._fill_vs_baseline(rec, "fleet")       # 1.5x > 1.1: worse
        assert rec["vs_baseline"] == 1.5
        assert rec["detail"]["regression"] is True
        reg = telemetry.registry()
        assert reg.counter("telemetry_events").value(
            event="bench_regression") == 1.0

    def test_regression_event_higher_is_better(self):
        write_prior("gpt", "tok_s", 1000.0)
        rec = {"metric": "tok_s", "value": 800.0,
               "unit": "tokens/sec", "vs_baseline": None, "detail": {}}
        bench._fill_vs_baseline(rec, "gpt")         # 0.8 < 1/1.1: worse
        assert rec["detail"]["regression"] is True
        # and a mild wobble inside the threshold does NOT fire
        rec2 = {"metric": "tok_s", "value": 950.0,
                "unit": "tokens/sec", "vs_baseline": None, "detail": {}}
        bench._fill_vs_baseline(rec2, "gpt")
        assert "regression" not in rec2["detail"]
        assert telemetry.registry().counter("telemetry_events").value(
            event="bench_regression") == 1.0

    def test_threshold_env_knob(self, monkeypatch):
        write_prior("fleet", "agg_ms", 1.0)
        monkeypatch.setenv("APEX_TPU_BENCH_REGRESSION_THRESHOLD", "2.0")
        rec = {"metric": "agg_ms", "value": 1.5,
               "unit": "ms (lower is better)", "vs_baseline": None,
               "detail": {}}
        bench._fill_vs_baseline(rec, "fleet")       # 1.5 < 2.0: fine
        assert "regression" not in rec["detail"]


class TestHeadlineRepeats:
    def test_default_is_median_of_at_least_five(self, monkeypatch):
        monkeypatch.delenv("APEX_TPU_BENCH_REPEATS", raising=False)
        assert bench._headline_repeats() >= 5

    def test_env_override_and_floor(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_BENCH_REPEATS", "2")
        assert bench._headline_repeats() == 2
        monkeypatch.setenv("APEX_TPU_BENCH_REPEATS", "0")
        assert bench._headline_repeats() == 1          # floor, not zero
        monkeypatch.setenv("APEX_TPU_BENCH_REPEATS", "bogus")
        assert bench._headline_repeats() == 5


class TestHeadlineLedger:
    def test_headline_record_measured_vs_analytic(self, monkeypatch,
                                                  capsys):
        # a tiny-shape headline run: the record must carry BOTH sides
        # of the HBM ledger per impl, the repeat spread, and route the
        # default impl through the segmented one-pass schedule
        monkeypatch.setenv("APEX_TPU_BENCH_REPEATS", "1")
        monkeypatch.setattr(
            bench, "bert_large_shapes",
            lambda **kw: [(64, 8), (64,), (32, 8), (16,)])
        bench.main()
        lines = [l for l in capsys.readouterr().out.splitlines()
                 if l.startswith("{")]
        rec = json.loads(lines[-1])
        d = rec["detail"]
        assert d["repeats"] == 1
        assert d["headline_stat"] == "median of 1"
        assert d["impl"] == "fused_step"
        mb = d["measured_bytes_per_element"]
        ana = d["hbm_accesses_per_element"]
        # measured next to analytic, for the baseline AND every impl
        assert set(mb) >= {"optax", "fused_step"}
        assert set(mb) >= set(d["fused_ms_by_impl"])
        assert set(ana) >= set(d["fused_ms_by_impl"]) | {"optax"}
        # CPU has a cost model: the measured side is real numbers here
        assert mb["fused_step"] > 0 and mb["optax"] > 0
        # spread recorded per impl (one repeat -> one sample each)
        assert all(len(v) == 1 for v in d["fused_ms_spread"].values())
        # memory plane: the compiled step's footprint + the devmem
        # null-with-reason contract on the CPU smoke backend
        tdet = d["telemetry"]
        assert tdet["memory_analysis"]["argument_bytes"] > 0
        assert tdet["devmem"] is None and tdet["devmem_reason"]


class TestEmitEndToEnd:
    def test_emit_fills_vs_baseline_from_prior_run(self, capsys):
        write_prior("fleet", "agg_ms", 2.0)
        bench.emit({"metric": "agg_ms", "value": 3.0,
                    "unit": "ms (lower is better)", "vs_baseline": None,
                    "detail": {"backend": "cpu"}}, "fleet")
        out = json.loads(capsys.readouterr().out.strip())
        assert out["vs_baseline"] == 1.5
        assert out["detail"]["baseline_source"]["value"] == 2.0
        assert out["detail"]["regression"] is True
        # the bench_regression event fired BEFORE the telemetry fold,
        # so the emitted record's own snapshot carries it
        counters = out["detail"]["telemetry"]["registry"]["counters"]
        assert counters['telemetry_events{event="bench_regression"}'] == 1.0

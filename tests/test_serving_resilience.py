"""Serving-tier resilience (apex_tpu/serving/resilience.py +
scheduler integration, docs/serving.md "Failure modes & recovery").

Anchors:

- deadlines: queued + in-flight TTL reap at the top of the step —
  BEFORE admission and decode — with outcome ``deadline_exceeded``;
- quarantine: ``decode_nonfinite`` isolates exactly the poisoned lane
  (the rest of the batch keeps its tokens, compared against a clean
  run); a sequence-bound exception localizes by binary split; a
  transient ``io:decode_step`` index is absorbed with ZERO quarantines;
- drain: a preemption flag commits an atomic serving snapshot, a fresh
  engine resumes it, and the merged token streams match the
  uninterrupted run exactly; corrupt snapshots are refused;
- hot swap: staged install at a step boundary with old/new digests,
  structured rejection on signature mismatch (and the
  ``weight_swap_mismatch`` clause), fingerprint-manifest validation;
- ``submit()`` is thread-safe under concurrent stepping.
"""

import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from apex_tpu import serving, telemetry  # noqa: E402
from apex_tpu.models.gpt import GPTConfig, GPTModel  # noqa: E402
from apex_tpu.resilience import faults  # noqa: E402
from apex_tpu.resilience.guard import PreemptionHandler  # noqa: E402
from apex_tpu.serving import resilience as sresil  # noqa: E402
from apex_tpu.serving.kv_cache import KVCache  # noqa: E402

VOCAB, SEQ, HID, LAYERS, HEADS, KV = 64, 64, 32, 2, 4, 2
BLOCKS, BS = 24, 4


def tiny_config(**kw):
    base = dict(vocab_size=VOCAB, max_seq_len=SEQ, hidden_size=HID,
                num_layers=LAYERS, num_heads=HEADS, num_kv_heads=KV,
                dtype=jnp.float32, param_dtype=jnp.float32)
    base.update(kw)
    return GPTConfig(**base)


def fresh_cache(num_blocks=BLOCKS, block_size=BS):
    return KVCache(LAYERS, KV, HID // HEADS, num_blocks=num_blocks,
                   block_size=block_size, dtype=jnp.float32)


@pytest.fixture(scope="module")
def model_and_params():
    model = GPTModel(tiny_config())
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, VOCAB, (1, 8)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks)
    return model, params


@pytest.fixture(scope="module")
def step_fn(model_and_params):
    model, _ = model_and_params
    return serving.make_decode_step(model, fresh_cache())


def make_batcher(model, params, step_fn, cache, **kw):
    reg = telemetry.MetricsRegistry()
    sink = telemetry.InMemorySink()
    reg.add_sink(sink)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_prefill_batch", 4)
    b = serving.ContinuousBatcher(model, params, cache, step_fn=step_fn,
                                  registry=reg, **kw)
    return b, reg, sink


def run_clean(model, params, step_fn, requests):
    """Token streams per id from an uninterrupted, fault-free run."""
    cache = fresh_cache()
    eng, _, _ = make_batcher(model, params, step_fn, cache)
    _, results = serving.serve_loop(eng, cache.init_state(), requests)
    return {r.id: r.tokens for r in results}


def mk_requests(n, rng, **kw):
    return [serving.Request(
        id=i, prompt=rng.randint(0, VOCAB, (int(rng.randint(2, 9)),)),
        max_new_tokens=int(rng.randint(3, 7)), **kw) for i in range(n)]


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------


class TestDeadlines:
    def test_queued_deadline_reaps_before_admission(
            self, model_and_params, step_fn):
        model, params = model_and_params
        cache = fresh_cache()
        t = [0.0]
        eng, reg, sink = make_batcher(model, params, step_fn, cache,
                                      clock=lambda: t[0])
        state = cache.init_state()
        eng.submit(serving.Request(id="late", prompt=[1] * 4,
                                   max_new_tokens=4, deadline_ms=50.0))
        t[0] = 0.2                       # 200ms later: TTL long gone
        state, rep = eng.step(state)
        assert rep["expired"] == ["late"]
        assert rep["admitted"] == []
        res = eng.drain()
        assert len(res) == 1
        assert res[0].finish_reason == "deadline_exceeded"
        assert res[0].reason == "deadline_queued"
        assert res[0].tokens == []
        assert reg.counter("serving_deadline_exceeded").value(
            where="queued") == 1
        assert "serving_deadline_exceeded" in [
            e["event"] for e in sink.events]
        assert cache.blocks_in_use == 0

    def test_inflight_deadline_reaps_before_decode(
            self, model_and_params, step_fn):
        model, params = model_and_params
        cache = fresh_cache()
        t = [0.0]
        eng, reg, _ = make_batcher(model, params, step_fn, cache,
                                   clock=lambda: t[0])
        state = cache.init_state()
        eng.submit(serving.Request(id="ttl", prompt=[1] * 4,
                                   max_new_tokens=8, deadline_ms=100.0))
        eng.submit(serving.Request(id="ok", prompt=[2] * 4,
                                   max_new_tokens=8))
        state, rep = eng.step(state)     # both admitted, 2 tokens each
        assert rep["decoded"] == ["ttl", "ok"]
        n_before = len(eng.running[0].generated)
        t[0] = 0.5                       # past ttl's deadline
        state, rep = eng.step(state)
        # the reap happened BEFORE decode: ttl never bought this
        # step's decode slot and its token count did not grow
        assert rep["expired"] == ["ttl"]
        assert "ttl" not in rep["decoded"]
        assert rep["decoded"] == ["ok"]
        res = {r.id: r for r in eng.drain()}
        assert res["ttl"].finish_reason == "deadline_exceeded"
        assert res["ttl"].reason == "deadline_in_flight"
        assert len(res["ttl"].tokens) == n_before
        assert reg.counter("serving_deadline_exceeded").value(
            where="in_flight") == 1
        # the survivor runs to completion; its blocks were untouched
        while not eng.idle():
            state, _ = eng.step(state)
        out = eng.drain()
        assert out[0].id == "ok" and out[0].finish_reason == "length"
        assert out[0].reason is None
        assert cache.blocks_in_use == 0

    def test_prefilling_deadline_reaps_mid_chunks(self, model_and_params,
                                                  step_fn):
        # a chunked long prompt expiring BETWEEN chunks reaps from the
        # prefilling list with its own reason code — routers can tell
        # "never admitted" from "died mid-prefill" from "died decoding"
        model, params = model_and_params
        cache = fresh_cache()
        t = [0.0]
        eng, reg, _ = make_batcher(model, params, step_fn, cache,
                                   clock=lambda: t[0], prefill_chunk=4)
        state = cache.init_state()
        eng.submit(serving.Request(id="slow", prompt=[1] * 16,
                                   max_new_tokens=4, deadline_ms=100.0))
        state, rep = eng.step(state)     # first chunk in; 3 to go
        assert rep["admitted"] == ["slow"]
        assert not eng.idle()
        t[0] = 0.5                       # expires mid-prefill
        state, rep = eng.step(state)
        assert rep["expired"] == ["slow"]
        res = eng.drain()
        assert res[0].finish_reason == "deadline_exceeded"
        assert res[0].reason == "deadline_prefilling"
        assert res[0].tokens == []       # never reached decode
        assert reg.counter("serving_deadline_exceeded").value(
            where="prefilling") == 1
        assert cache.blocks_in_use == 0

    def test_no_deadline_never_expires(self, model_and_params, step_fn):
        model, params = model_and_params
        cache = fresh_cache()
        t = [0.0]
        eng, _, _ = make_batcher(model, params, step_fn, cache,
                                 clock=lambda: t[0])
        state = cache.init_state()
        eng.submit(serving.Request(id=0, prompt=[3] * 4,
                                   max_new_tokens=3))
        t[0] = 1e6
        while not eng.idle():
            state, _ = eng.step(state)
        assert eng.drain()[0].finish_reason == "length"


# ---------------------------------------------------------------------------
# quarantine: nonfinite localization + binary-split isolation
# ---------------------------------------------------------------------------


class _PoisonDecode:
    """step_fn wrapper whose decode raises whenever the batch's block
    tables touch a poisoned sequence's blocks — a SEQUENCE-bound fault
    (unlike the step-indexed clause), which is exactly what the binary
    split must localize."""

    def __init__(self, inner):
        self.inner = inner
        self.poison_blocks = set()
        self.decode_calls = 0

    def prefill(self, *a, **kw):
        return self.inner.prefill(*a, **kw)

    def prefill_chunk(self, *a, **kw):
        return self.inner.prefill_chunk(*a, **kw)

    def decode(self, params, state, tokens, positions, tables, **kw):
        self.decode_calls += 1
        if self.poison_blocks & set(np.asarray(tables).ravel().tolist()):
            raise faults.FaultError("poisoned sequence in batch")
        return self.inner.decode(params, state, tokens, positions,
                                 tables, **kw)


class TestQuarantine:
    def test_nonfinite_lane_quarantined_others_bitwise(
            self, model_and_params, step_fn, tmp_path, monkeypatch):
        from apex_tpu import records
        from apex_tpu.telemetry import flight

        monkeypatch.setattr(records, "RECORDS_DIR", str(tmp_path))
        model, params = model_and_params
        rng = np.random.RandomState(11)
        reqs = mk_requests(3, rng)
        clean = run_clean(model, params, step_fn, reqs)
        cache = fresh_cache()
        eng, reg, sink = make_batcher(model, params, step_fn, cache)
        state = cache.init_state()
        flight.enable()
        try:
            with faults.inject(decode_nonfinite_steps=frozenset({1}),
                               decode_nonfinite_lane=1):
                for r in mk_requests(3, np.random.RandomState(11)):
                    eng.submit(r)
                state, rep0 = eng.step(state)
                assert rep0["decoded"] == [0, 1, 2]
                state, rep1 = eng.step(state)
                # ONLY lane 1 quarantined; the others kept this step's
                # tokens
                assert rep1["quarantined"] == [1]
                assert rep1["decoded"] == [0, 2]
            while not eng.idle():
                state, _ = eng.step(state)
        finally:
            flight.disable()
        res = {r.id: r for r in eng.drain()}
        assert res[1].finish_reason == "error"
        assert res[1].reason == "quarantined"
        assert "nonfinite" in res[1].error
        assert res[1].tokens == clean[1][:len(res[1].tokens)]
        # the survivors' full streams match the fault-free run exactly
        assert res[0].tokens == clean[0]
        assert res[2].tokens == clean[2]
        assert reg.counter("serving_quarantined").value(
            reason="nonfinite") == 1
        assert cache.blocks_in_use == 0
        rec = records.latest_record(flight.FLIGHT_KIND,
                                    require_backend=None)
        assert rec["payload"]["trigger"] == "serving_quarantine"
        assert "1" in str(rec["payload"]["extra"]["requests"])

    def test_binary_split_isolates_raising_sequence(
            self, model_and_params):
        model, params = model_and_params
        rng = np.random.RandomState(12)
        reqs = mk_requests(4, rng)
        cache0 = fresh_cache()
        base_step = serving.make_decode_step(model, cache0)
        clean = run_clean(model, params, base_step, reqs)

        cache = fresh_cache()
        wrapped = _PoisonDecode(serving.make_decode_step(model, cache))
        eng, reg, _ = make_batcher(model, params, wrapped, cache)
        state = cache.init_state()
        for r in mk_requests(4, np.random.RandomState(12)):
            eng.submit(r)
        state, rep = eng.step(state)     # all admitted, first decode ok
        assert rep["decoded"] == [0, 1, 2, 3]
        # poison request 1 by its block table, then keep stepping: the
        # full-batch dispatch fails, the split exonerates everyone else
        victim = next(f for f in eng.running if f.req.id == 1)
        wrapped.poison_blocks = set(cache.table(victim.seq_id))
        calls_before = wrapped.decode_calls
        state, rep = eng.step(state)
        assert rep["quarantined"] == [1]
        assert sorted(rep["decoded"]) == [0, 2, 3]
        # the split really retried: full batch + halves + singletons
        assert wrapped.decode_calls > calls_before + 1
        while not eng.idle():
            state, _ = eng.step(state)
        res = {r.id: r for r in eng.drain()}
        assert res[1].finish_reason == "error"
        assert res[1].reason == "quarantined"
        assert "poisoned sequence" in res[1].error
        for i in (0, 2, 3):
            assert res[i].finish_reason == "length"
            assert res[i].reason is None
            assert res[i].tokens == clean[i]
        assert reg.counter("serving_quarantined").value(
            reason="exception") == 1
        assert cache.blocks_in_use == 0

    def test_transient_decode_fault_absorbed_zero_quarantines(
            self, model_and_params, step_fn):
        model, params = model_and_params
        rng = np.random.RandomState(13)
        reqs = mk_requests(2, rng)
        clean = run_clean(model, params, step_fn, reqs)
        cache = fresh_cache()
        eng, reg, _ = make_batcher(model, params, step_fn, cache)
        state = cache.init_state()
        # call index 1 = engine step 1's FULL-batch dispatch; the
        # binary-split halves (indices 2, 3) succeed
        with faults.inject(io_errors={"decode_step": frozenset({1})}):
            for r in mk_requests(2, np.random.RandomState(13)):
                eng.submit(r)
            while not eng.idle():
                state, _ = eng.step(state)
        res = {r.id: r for r in eng.drain()}
        assert {r.finish_reason for r in res.values()} == {"length"}
        assert res[0].tokens == clean[0]
        assert res[1].tokens == clean[1]
        assert reg.counter("serving_quarantined").value() == 0


# ---------------------------------------------------------------------------
# drain snapshots + resume
# ---------------------------------------------------------------------------


class TestDrainResume:
    def test_snapshot_resume_replays_bitwise(self, model_and_params,
                                             step_fn, tmp_path):
        model, params = model_and_params
        rng = np.random.RandomState(21)
        reqs = mk_requests(6, rng)
        clean = run_clean(model, params, step_fn, reqs)

        handler = PreemptionHandler()        # not installed: flag only
        cache = fresh_cache()
        eng, _, sink = make_batcher(
            model, params, step_fn, cache, max_batch=3,
            preemption=handler, snapshot_dir=str(tmp_path))
        state = cache.init_state()
        for r in mk_requests(6, np.random.RandomState(21)):
            eng.submit(r)
        state, _ = eng.step(state)
        state, _ = eng.step(state)           # some tokens in flight
        handler.requested = True             # the SIGTERM flag
        state, rep = eng.step(state)
        assert rep["drained"] is True
        assert rep["snapshot"] is not None
        assert eng.draining and not eng.running
        assert cache.blocks_in_use == 0
        phase1 = eng.drain()
        done_ids = {r.id for r in phase1}
        # a draining engine refuses new work loudly
        eng.submit(serving.Request(id="late", prompt=[1], max_new_tokens=1))
        late = eng.drain()
        assert late[0].finish_reason == "error"
        assert late[0].reason == "draining"
        assert "draining" in late[0].error

        path = sresil.latest_snapshot(str(tmp_path))
        assert path == rep["snapshot"]
        snap = sresil.load_snapshot(path)
        snap_ids = {e["id"] for e in snap["requests"]}
        # zero silently dropped: finished + snapshotted == submitted
        assert done_ids | snap_ids == set(range(6))
        assert done_ids.isdisjoint(snap_ids)
        assert any(e["state"] == "in_flight" and e["generated"]
                   for e in snap["requests"])

        resumed, prior = sresil.resume_requests(snap)
        cache2 = fresh_cache()
        eng2, _, _ = make_batcher(model, params, step_fn, cache2,
                                  max_batch=3)
        _, results = serving.serve_loop(eng2, cache2.init_state(),
                                        resumed)
        merged = sresil.merge_results(results, prior)
        got = {r.id: r.tokens for r in merged}
        got.update({r.id: r.tokens for r in phase1})
        # the replayed streams are identical to the uninterrupted run
        assert got == clean
        assert "serving_drain" in [e["event"] for e in sink.events]

    def test_drain_without_snapshot_dir_finishes_inflight(
            self, model_and_params, step_fn):
        model, params = model_and_params
        handler = PreemptionHandler()
        cache = fresh_cache()
        eng, reg, _ = make_batcher(model, params, step_fn, cache,
                                   max_batch=2, preemption=handler)
        state = cache.init_state()
        for i in range(4):
            eng.submit(serving.Request(id=i, prompt=[1 + i] * 4,
                                       max_new_tokens=4))
        state, _ = eng.step(state)           # 0, 1 in flight; 2, 3 queued
        handler.requested = True
        state, rep = eng.step(state)
        assert rep["drained"] is True and rep["snapshot"] is None
        # queued work fails LOUDLY, in-flight work keeps decoding
        res = {r.id: r for r in eng.drain()}
        assert {2, 3} <= set(res)
        assert all("preempted" in res[i].error for i in (2, 3))
        while eng.running:
            state, _ = eng.step(state)
        res = {r.id: r for r in eng.drain()}
        assert res[0].finish_reason == "length"
        assert res[1].finish_reason == "length"
        assert cache.blocks_in_use == 0

    def test_drain_results_in_completion_order(self, model_and_params,
                                               step_fn):
        model, params = model_and_params
        cache = fresh_cache()
        eng, _, _ = make_batcher(model, params, step_fn, cache)
        state = cache.init_state()
        for i, n in enumerate([3, 1, 2]):
            eng.submit(serving.Request(id=i, prompt=[1 + i] * 4,
                                       max_new_tokens=n))
        while not eng.idle():
            state, _ = eng.step(state)
        assert [r.id for r in eng.drain()] == [1, 2, 0]

    def test_corrupt_snapshot_refused(self, model_and_params, step_fn,
                                      tmp_path):
        model, params = model_and_params
        handler = PreemptionHandler()
        cache = fresh_cache()
        eng, _, _ = make_batcher(model, params, step_fn, cache,
                                 preemption=handler,
                                 snapshot_dir=str(tmp_path))
        state = cache.init_state()
        eng.submit(serving.Request(id=0, prompt=[5] * 4,
                                   max_new_tokens=8))
        state, _ = eng.step(state)
        with faults.inject(snapshot_corrupt_indices=frozenset({0})):
            handler.requested = True
            state, rep = eng.step(state)
        path = rep["snapshot"]
        assert path is not None
        ok, reason = sresil.validate_snapshot(path)
        assert not ok and "truncated" in reason
        with pytest.raises(sresil.SnapshotError, match="truncated"):
            sresil.load_snapshot(path)
        # latest_snapshot skips the rotten one
        assert sresil.latest_snapshot(str(tmp_path)) is None

    def test_latest_snapshot_falls_back_to_older_valid(
            self, model_and_params, step_fn, tmp_path):
        model, params = model_and_params
        cache = fresh_cache()
        eng, _, _ = make_batcher(model, params, step_fn, cache)
        eng.submit(serving.Request(id="q", prompt=[2] * 4,
                                   max_new_tokens=2))
        good = sresil.save_snapshot(eng, str(tmp_path), step=5)
        with faults.inject(snapshot_corrupt_indices=frozenset({1})):
            sresil.save_snapshot(eng, str(tmp_path), step=9)
        assert sresil.latest_snapshot(str(tmp_path)) == good
        snap = sresil.load_snapshot(good)
        assert snap["requests"][0]["id"] == "q"
        assert snap["requests"][0]["state"] == "queued"


# ---------------------------------------------------------------------------
# live weight hot-swap
# ---------------------------------------------------------------------------


class TestWeightSwap:
    def test_swap_installs_at_step_boundary(self, model_and_params,
                                            step_fn):
        model, params = model_and_params
        cache = fresh_cache()
        eng, reg, sink = make_batcher(model, params, step_fn, cache)
        state = cache.init_state()
        eng.submit(serving.Request(id=0, prompt=[7] * 5,
                                   max_new_tokens=6))
        state, _ = eng.step(state)
        new_params = jax.tree_util.tree_map(lambda x: x * 1.5, params)
        info = serving.swap_weights(eng, new_params)
        assert info["old_digest"] != info["new_digest"]
        assert eng.params is params      # staged, not yet installed
        state, _ = eng.step(state)       # the boundary installs it
        assert eng.params is new_params
        while not eng.idle():
            state, _ = eng.step(state)
        # no request dropped across the swap
        res = eng.drain()[0]
        assert res.finish_reason == "length" and len(res.tokens) == 6
        events = [e for e in sink.events
                  if e["event"] == "serving_weight_swap"]
        assert events and events[0]["new_digest"] == info["new_digest"]
        assert reg.counter("serving_weight_swaps").value() == 1
        assert cache.blocks_in_use == 0

    def test_swap_rejects_shape_mismatch_structured(
            self, model_and_params, step_fn, tmp_path, monkeypatch):
        from apex_tpu import records

        monkeypatch.setattr(records, "RECORDS_DIR", str(tmp_path))
        model, params = model_and_params
        cache = fresh_cache()
        eng, reg, _ = make_batcher(model, params, step_fn, cache)
        bad = jax.tree_util.tree_map(lambda x: x, params)
        leaves, treedef = jax.tree_util.tree_flatten(bad)
        leaves[0] = jnp.zeros(np.asarray(leaves[0]).shape + (2,))
        bad = jax.tree_util.tree_unflatten(treedef, leaves)
        with pytest.raises(serving.WeightSwapError) as ei:
            serving.swap_weights(eng, bad)
        assert ei.value.mismatches
        assert any("expected" in m for m in ei.value.mismatches)
        assert eng.params is params
        assert eng._pending_swap is None
        assert reg.counter("serving_weight_swap_rejected").value() == 1

    def test_weight_swap_mismatch_clause(self, model_and_params,
                                         step_fn):
        model, params = model_and_params
        cache = fresh_cache()
        eng, _, _ = make_batcher(model, params, step_fn, cache)
        with faults.inject(weight_swap_mismatch_indices=frozenset({0})):
            with pytest.raises(serving.WeightSwapError,
                               match="signature mismatch"):
                serving.swap_weights(eng, params)
        # the next swap (index 1) is off-plan and goes through
        serving.swap_weights(eng, params)
        assert eng._pending_swap is not None

    def test_fingerprint_manifest_validation(self, model_and_params,
                                             step_fn):
        model, params = model_and_params
        cache = fresh_cache()
        eng, _, _ = make_batcher(model, params, step_fn, cache)
        fp = serving.params_fingerprint(params)
        serving.swap_weights(eng, params, expect_fingerprint=fp)
        wrong = fp.copy()
        wrong[0] ^= 1
        with pytest.raises(serving.WeightSwapError,
                           match="signature mismatch"):
            serving.swap_weights(eng, params, expect_fingerprint=wrong)


# ---------------------------------------------------------------------------
# thread-safe submission
# ---------------------------------------------------------------------------


class TestThreadSafety:
    def test_concurrent_submit_loses_nothing(self, model_and_params,
                                             step_fn):
        model, params = model_and_params
        cache = fresh_cache(num_blocks=32)
        eng, _, _ = make_batcher(model, params, step_fn, cache)
        state = cache.init_state()
        n_threads, per = 4, 8

        def client(t):
            for i in range(per):
                eng.submit(serving.Request(
                    id=(t, i), prompt=[1 + t] * 3, max_new_tokens=2))

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(n_threads)]
        for th in threads:
            th.start()
        results = []
        for _ in range(500):
            for th in threads:
                th.join(timeout=0.001)
            state, _ = eng.step(state)
            results.extend(eng.drain())
            if (all(not th.is_alive() for th in threads)
                    and eng.idle()):
                break
        results.extend(eng.drain())
        assert len(results) == n_threads * per
        assert {tuple(r.id) for r in results} == {
            (t, i) for t in range(n_threads) for i in range(per)}
        assert all(r.finish_reason == "length" for r in results)
        assert cache.blocks_in_use == 0


# ---------------------------------------------------------------------------
# grammar
# ---------------------------------------------------------------------------


class TestGrammar:
    def test_new_serving_clauses(self):
        inj = faults.FaultInjector.from_env(
            "decode_nonfinite=2,4;decode_nonfinite_lane=1;"
            "serving_snapshot_corrupt=0;weight_swap_mismatch=3")
        assert inj.nonfinite_lane_at(2) == 1
        assert inj.nonfinite_lane_at(4) == 1
        assert inj.nonfinite_lane_at(3) is None
        assert inj.should_snapshot_corrupt(0)
        assert not inj.should_snapshot_corrupt(1)
        assert inj.should_weight_swap_mismatch(3)
        assert not inj.should_weight_swap_mismatch(0)

    def test_deadline_validation(self):
        with pytest.raises(ValueError, match="deadline_ms"):
            serving.Request(id=0, prompt=[1], deadline_ms=0)
        serving.Request(id=0, prompt=[1], deadline_ms=5.0)

"""amp engine tests.

Mirrors ref tests/L0/run_amp (test_basic_casts.py, test_promotion.py,
test_checkpointing.py) behaviorally: policy casting, dynamic-scale
schedule, skip-step integration, state (de)serialization.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.amp import LossScaler
from apex_tpu.optimizers import FusedSGD


def make_params():
    return {
        "dense": {"kernel": jnp.ones((8, 8), jnp.float32), "bias": jnp.zeros((8,), jnp.float32)},
        "BatchNorm_0": {"scale": jnp.ones((8,), jnp.float32), "bias": jnp.zeros((8,), jnp.float32)},
    }


class TestOptLevels:
    def test_O0_identity(self):
        p, state = amp.initialize(make_params(), opt_level="O0")
        assert state.properties.cast_model_type is None
        assert p["dense"]["kernel"].dtype == jnp.float32

    def test_O2_casts_model_keeps_bn(self):
        p, state = amp.initialize(make_params(), opt_level="O2")
        assert p["dense"]["kernel"].dtype == jnp.float16
        assert p["BatchNorm_0"]["scale"].dtype == jnp.float32
        assert state.properties.loss_scale == "dynamic"

    def test_O3_pure_half(self):
        p, _ = amp.initialize(make_params(), opt_level="O3")
        assert p["dense"]["kernel"].dtype == jnp.float16
        assert p["BatchNorm_0"]["scale"].dtype == jnp.float16

    def test_O5_bf16_master(self):
        p, state = amp.initialize(make_params(), opt_level="O5")
        assert p["dense"]["kernel"].dtype == jnp.bfloat16
        assert p["BatchNorm_0"]["scale"].dtype == jnp.float32
        assert state.properties.master_weights
        assert state.properties.loss_scale is None

    def test_O1_O4_compute_dtype(self):
        _, s1 = amp.initialize(make_params(), opt_level="O1")
        _, s4 = amp.initialize(make_params(), opt_level="O4")
        assert s1.properties.compute_dtype == jnp.float16
        assert s4.properties.compute_dtype == jnp.bfloat16
        assert s4.properties.loss_scale is None

    def test_override(self):
        p, state = amp.initialize(
            make_params(), opt_level="O2", keep_batchnorm_fp32=False,
            loss_scale=128.0,
        )
        assert p["BatchNorm_0"]["scale"].dtype == jnp.float16
        assert state.properties.loss_scale == 128.0

    def test_with_optimizer_master_from_fp32(self):
        opt = FusedSGD(lr=0.1, momentum=0.9)
        params = make_params()
        cast_params, opt_state, state = amp.initialize(
            params, opt, opt_level="O2"
        )
        # master weights are fp32 copies of original params
        assert opt_state.master.dtype == jnp.float32
        master = opt.master_params(opt_state)
        np.testing.assert_array_equal(
            np.asarray(master["dense"]["kernel"]),
            np.asarray(params["dense"]["kernel"]),
        )


class TestLossScaler:
    def test_static(self):
        s = LossScaler(loss_scale=128.0)
        st = s.init()
        assert float(st.loss_scale) == 128.0
        scaled = s.scale_loss(jnp.asarray(2.0), st)
        assert float(scaled) == 256.0
        st = s.update(st, jnp.asarray(1.0))
        assert float(st.loss_scale) == 128.0  # static never changes

    def test_dynamic_backoff_and_growth(self):
        s = LossScaler(loss_scale="dynamic", scale_window=4)
        st = s.init()
        assert float(st.loss_scale) == 2.0 ** 16
        st = s.update(st, jnp.asarray(1.0))  # overflow
        assert float(st.loss_scale) == 2.0 ** 15
        assert int(st.unskipped) == 0
        for _ in range(3):
            st = s.update(st, jnp.asarray(0.0))
        assert float(st.loss_scale) == 2.0 ** 15
        st = s.update(st, jnp.asarray(0.0))  # 4th good step -> grow
        assert float(st.loss_scale) == 2.0 ** 16
        assert int(st.unskipped) == 0

    def test_dynamic_max_clamp(self):
        s = LossScaler(loss_scale="dynamic", scale_window=1, max_loss_scale=2.0 ** 17)
        st = s.init()
        for _ in range(5):
            st = s.update(st, jnp.asarray(0.0))
        assert float(st.loss_scale) == 2.0 ** 17

    def test_dynamic_min_clamp(self):
        s = LossScaler(loss_scale="dynamic", min_loss_scale=2.0 ** 15)
        st = s.init()
        for _ in range(5):
            st = s.update(st, jnp.asarray(1.0))
        assert float(st.loss_scale) == 2.0 ** 15

    def test_unscale_reports_inf(self):
        s = LossScaler()
        st = s.init()
        grads = {"a": jnp.ones((16,)) * st.loss_scale, "b": jnp.ones((4, 4))}
        un, found = s.unscale(grads, st)
        np.testing.assert_allclose(np.asarray(un["a"]), np.ones(16), rtol=1e-6)
        assert float(found) == 0.0
        grads["a"] = grads["a"].at[3].set(jnp.nan)
        _, found = s.unscale(grads, st)
        assert float(found) == 1.0

    def test_update_inside_jit(self):
        s = LossScaler(scale_window=2)

        @jax.jit
        def step(st, found):
            return s.update(st, found)

        st = s.init()
        st = step(st, jnp.asarray(0.0))
        st = step(st, jnp.asarray(0.0))
        assert float(st.loss_scale) == 2.0 ** 17

    def test_state_dict_roundtrip(self):
        s = LossScaler()
        st = s.update(s.init(), jnp.asarray(1.0))
        d = s.state_dict(st)
        st2 = s.load_state_dict(d)
        assert float(st2.loss_scale) == float(st.loss_scale)
        assert int(st2.unskipped) == int(st.unskipped)
        # full state: the overflow that just happened survives the trip
        assert d["found_inf"] == 1.0
        assert float(st2.found_inf) == 1.0
        # pre-found_inf checkpoints load as "last step clean"
        legacy = {"loss_scale": 2.0 ** 12, "unskipped": 3}
        st3 = s.load_state_dict(legacy)
        assert float(st3.found_inf) == 0.0


class TestLossScalerScheduleEdges:
    """The dynamic-schedule corner cases (ref apex/amp/scaler.py:206-226):
    min floor under repeated overflow, max cap under sustained growth,
    and overflow landing on the exact would-grow step."""

    def test_min_floor_repeated_overflow_then_regrow(self):
        s = LossScaler(min_loss_scale=2.0 ** 14, scale_window=2)
        st = s.init()
        for _ in range(6):                      # far past the floor
            st = s.update(st, jnp.asarray(1.0))
            assert int(st.unskipped) == 0       # overflow always resets
        assert float(st.loss_scale) == 2.0 ** 14   # floored, not 2^10
        # the floor is not a trap: a clean window regrows
        st = s.update(st, jnp.asarray(0.0))
        assert float(st.loss_scale) == 2.0 ** 14
        st = s.update(st, jnp.asarray(0.0))
        assert float(st.loss_scale) == 2.0 ** 15

    def test_max_cap_holds_and_window_keeps_resetting(self):
        s = LossScaler(scale_window=1, max_loss_scale=2.0 ** 18)
        st = s.init()
        for _ in range(8):
            st = s.update(st, jnp.asarray(0.0))
            # every grow step resets the window counter, capped or not
            assert int(st.unskipped) == 0
        assert float(st.loss_scale) == 2.0 ** 18
        # one overflow still backs off from the cap
        st = s.update(st, jnp.asarray(1.0))
        assert float(st.loss_scale) == 2.0 ** 17

    def test_overflow_on_exact_grow_step_backs_off_and_resets_window(self):
        s = LossScaler(scale_window=3)
        st = s.init()
        st = s.update(st, jnp.asarray(0.0))
        st = s.update(st, jnp.asarray(0.0))
        assert int(st.unskipped) == 2
        # this step WOULD grow (3rd good step) — but it overflows:
        # overflow wins, the scale halves, and the window restarts
        st = s.update(st, jnp.asarray(1.0))
        assert float(st.loss_scale) == 2.0 ** 15
        assert int(st.unskipped) == 0
        # a full fresh window is required before growing again
        st = s.update(st, jnp.asarray(0.0))
        st = s.update(st, jnp.asarray(0.0))
        assert float(st.loss_scale) == 2.0 ** 15
        st = s.update(st, jnp.asarray(0.0))
        assert float(st.loss_scale) == 2.0 ** 16
        assert int(st.unskipped) == 0

    def test_amp_state_dict_roundtrip(self):
        params, state = amp.initialize(make_params(), opt_level="O2", num_losses=3)
        d = amp.state_dict(state)
        assert set(d) == {"loss_scaler0", "loss_scaler1", "loss_scaler2"}
        state2 = amp.load_state_dict(state, d)
        assert len(state2.scalers) == 3


class TestSkipStepIntegration:
    def test_overflow_skips_update(self):
        """End-to-end O2-style loop: overflow grads leave params+count
        untouched and halve the scale (ref: apex/amp/handle.py:127-154)."""
        params = {"w": jnp.ones((32,), jnp.float32)}
        opt = FusedSGD(lr=0.1, momentum=0.0, impl="xla")
        scaler = LossScaler()
        ost = opt.init(params)
        sst = scaler.init()

        good = {"w": jnp.ones((32,), jnp.float32) * float(sst.loss_scale)}
        bad = {"w": good["w"].at[0].set(jnp.inf)}

        # good step
        p1, ost = opt.step(ost, good, grad_scale=sst.loss_scale,
                           skip_if_nonfinite=True)
        sst = scaler.update(sst, ost.found_inf)
        np.testing.assert_allclose(np.asarray(p1["w"]), 0.9 * np.ones(32), rtol=1e-6)
        assert int(ost.count) == 1

        # overflow step
        p2, ost = opt.step(ost, bad, grad_scale=sst.loss_scale,
                           skip_if_nonfinite=True)
        sst = scaler.update(sst, ost.found_inf)
        np.testing.assert_allclose(np.asarray(p2["w"]), np.asarray(p1["w"]))
        assert int(ost.count) == 1
        assert float(sst.loss_scale) == 2.0 ** 15


class TestScaleLossContextManager:
    """The reference's `with amp.scale_loss(...)` surface
    (ref apex/amp/handle.py:16-158) as a functional handle."""

    def _amp_state(self, **kw):
        _, state = amp.initialize(make_params(), opt_level="O2", **kw)
        return state

    def test_scales_and_unscales(self):
        state = self._amp_state()
        loss = jnp.float32(2.0)
        grads_scaled = {"w": jnp.full((4,), 3.0 * 65536.0)}
        with amp.scale_loss(loss, state) as scaled:
            np.testing.assert_allclose(float(scaled.loss), 2.0 * 65536.0)
            scaled.grads = grads_scaled
        np.testing.assert_allclose(np.asarray(scaled.grads["w"]), 3.0,
                                   rtol=1e-6)
        assert float(scaled.skip) == 0.0
        # one clean step counted toward the growth window
        assert int(scaled.amp_state.scalers[0].unskipped) == 1

    def test_overflow_halves_scale_and_sets_skip(self):
        state = self._amp_state()
        with amp.scale_loss(jnp.float32(1.0), state) as scaled:
            scaled.grads = {"w": jnp.asarray([jnp.inf, 1.0])}
        assert float(scaled.skip) == 1.0
        assert float(scaled.amp_state.scalers[0].loss_scale) == 65536.0 / 2

    def test_delay_unscale_leaves_state(self):
        state = self._amp_state()
        with amp.scale_loss(jnp.float32(1.0), state,
                            delay_unscale=True) as scaled:
            scaled.grads = {"w": jnp.full((2,), 65536.0)}
        # grads still scaled, scaler untouched (accumulation step)
        np.testing.assert_allclose(np.asarray(scaled.grads["w"]), 65536.0)
        assert scaled.amp_state is state

    def test_multiple_losses(self):
        state = self._amp_state(num_losses=2)
        with amp.scale_loss(jnp.float32(1.0), state, loss_id=1) as scaled:
            scaled.grads = {"w": jnp.asarray([jnp.nan])}
        assert float(scaled.amp_state.scalers[1].loss_scale) == 65536.0 / 2
        assert float(scaled.amp_state.scalers[0].loss_scale) == 65536.0
        with pytest.raises(ValueError, match="loss_id"):
            with amp.scale_loss(jnp.float32(1.0), state, loss_id=2):
                pass

    def test_traces_under_jit(self):
        state = self._amp_state()

        @jax.jit
        def step(state, x):
            def loss_fn(w):
                with amp.scale_loss(jnp.sum(w * x), state) as scaled:
                    pass
                return scaled.loss

            w = jnp.ones((4,), jnp.float32)
            with amp.scale_loss(jnp.sum(w * x), state) as scaled:
                scaled.grads = jax.grad(loss_fn)(w)
            return scaled.grads, scaled.amp_state, scaled.skip

        grads, new_state, skip = step(state, jnp.arange(4.0))
        np.testing.assert_allclose(np.asarray(grads), np.arange(4.0),
                                   rtol=1e-6)
        assert float(skip) == 0.0


class TestFunctionCasts:
    def test_half_and_float_function(self):
        @amp.half_function
        def f(x):
            return x

        assert f(jnp.ones((4,), jnp.float32)).dtype == jnp.float16

        @amp.float_function
        def g(x):
            return x

        assert g(jnp.ones((4,), jnp.float16)).dtype == jnp.float32

    def test_bfloat16_function(self):
        @amp.bfloat16_function
        def f(x):
            return x

        assert f(jnp.ones((4,), jnp.float32)).dtype == jnp.bfloat16

    def test_promote_function(self):
        @amp.promote_function
        def add(x, y):
            return x + y

        out = add(jnp.ones((4,), jnp.float16), jnp.ones((4,), jnp.float32))
        assert out.dtype == jnp.float32

    def test_compute_cast_roundtrip(self):
        def f(x):
            assert x.dtype == jnp.bfloat16
            return x * 2

        g = amp.compute_cast(f, jnp.bfloat16)
        out = g(jnp.ones((4,), jnp.float32))
        assert out.dtype == jnp.float32

    def test_int_args_untouched(self):
        @amp.half_function
        def f(x, n):
            return x, n

        x, n = f(jnp.ones((4,), jnp.float32), jnp.arange(4))
        assert x.dtype == jnp.float16
        assert n.dtype == jnp.int32


class TestRegisterFunctions:
    """ref apex/amp/amp.py:48-71 user registries — here the rebind is
    immediate (no deferred amp.init patch pass)."""

    def test_register_half_and_float(self):
        import types

        from apex_tpu import amp

        mod = types.SimpleNamespace(
            f=lambda x: x.dtype, g=lambda x: x.dtype)
        amp.register_half_function(mod, "f")
        amp.register_float_function(mod, "g")
        x = jnp.ones((4,), jnp.float32)
        assert mod.f(x) == jnp.float16
        assert mod.g(x.astype(jnp.float16)) == jnp.float32

    def test_register_promote(self):
        import types

        from apex_tpu import amp

        mod = types.SimpleNamespace(add=lambda a, b: (a + b).dtype)
        amp.register_promote_function(mod, "add")
        out = mod.add(jnp.ones((2,), jnp.float16), jnp.ones((2,), jnp.float32))
        assert out == jnp.float32

    def test_master_params_iterator(self, rng):
        from apex_tpu import amp
        from apex_tpu.optimizers import FusedAdam

        params = {"w": jnp.asarray(rng.randn(8, 2), jnp.bfloat16),
                  "b": jnp.zeros((2,), jnp.bfloat16)}
        opt = FusedAdam(lr=1e-3, impl="xla")
        state = opt.init(params)
        masters = list(amp.master_params(opt, state))
        assert len(masters) == 2
        assert all(m.dtype == jnp.float32 for m in masters)

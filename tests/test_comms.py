"""Comms & sharding plane (apex_tpu/telemetry/comms.py, sharding.py,
the fleet merged-trace path in telemetry/fleet.py): collective tracing
across the Collective impls, the measured-vs-analytic bandwidth
ledger, the EWMA slow-op escalation latch, the collective fault
clauses, clock-offset estimation under injected skew, merged-trace
well-formedness, and the sharding introspection null-with-reason
contract on CPU.

Replica sets are simulated with ``LocalCollective`` threads (pattern
of tests/test_fleet.py); the real-process KVStoreCollective analog is
``tools/fleet_drill.py``'s comms phase.
"""

import json
import threading
import time

import numpy as np
import pytest

from apex_tpu import telemetry
from apex_tpu.resilience import faults
from apex_tpu.resilience.guard import LocalCollective, NullCollective
from apex_tpu.telemetry import comms
from apex_tpu.telemetry import metrics as tmetrics
from apex_tpu.telemetry import sharding as tsharding
from apex_tpu.telemetry.fleet import (
    estimate_clock_offsets,
    export_fleet_trace,
)


@pytest.fixture(autouse=True)
def fresh_telemetry():
    telemetry.reset()
    faults.install(None)
    yield
    faults.install(None)
    telemetry.reset()


def run_fleet(n, fn):
    """``fn(rid, handle)`` on one thread per simulated host; returns
    per-host results, surfacing any thread's error."""
    group = LocalCollective(n)
    handles = group.handles()
    out = [None] * n
    errs = [None] * n

    def loop(r):
        try:
            out[r] = fn(r, handles[r])
        except BaseException as e:  # noqa: BLE001
            errs[r] = e

    ts = [threading.Thread(target=loop, args=(r,), daemon=True)
          for r in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    for e in errs:
        if e is not None:
            raise e
    return out


def private_tracer(**kw):
    return comms.CommsTracer(registry=tmetrics.MetricsRegistry(),
                             timeline=telemetry.StepTimeline(capacity=256),
                             **kw)


class TestInstrumentIdentity:
    def test_disabled_returns_the_exact_object(self):
        col = NullCollective()
        assert comms.instrument(col) is col
        assert not comms.enabled()

    def test_none_stays_none(self):
        assert comms.instrument(None) is None

    def test_enable_wraps_and_rewrap_is_idempotent(self):
        comms.enable()
        col = NullCollective()
        wrapped = comms.instrument(col)
        assert isinstance(wrapped, comms.InstrumentedCollective)
        assert wrapped.inner is col
        assert comms.instrument(wrapped) is wrapped
        assert wrapped.n_replicas == 1 and wrapped.replica_id == 0

    def test_rewrap_with_new_tracer_swaps_not_nests(self):
        t1, t2 = private_tracer(), private_tracer()
        w1 = comms.instrument(NullCollective(), tracer=t1)
        w2 = comms.instrument(w1, tracer=t2)
        assert w2 is not w1 and w2.inner is w1.inner
        assert w2.tracer is t2

    def test_reset_disarms(self):
        comms.enable()
        assert comms.enabled()
        telemetry.reset()
        assert not comms.enabled()
        assert comms.section()["enabled"] is False
        assert "reason" in comms.section()

    def test_env_knob_arms(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_COMMS", "1")
        comms.disable()
        try:
            assert comms.enabled()
            assert isinstance(comms.instrument(NullCollective()),
                              comms.InstrumentedCollective)
        finally:
            monkeypatch.delenv("APEX_TPU_COMMS")
            comms.disable()

    def test_results_byte_identical_to_raw(self):
        tr = private_tracer()
        col = comms.instrument(NullCollective(), tracer=tr)
        x = np.arange(32, dtype=np.float32)
        raw = NullCollective().all_gather(x)
        traced = col.all_gather(x)
        assert np.array_equal(np.asarray(traced), np.asarray(raw))
        assert col.agree_any(True) is True
        assert col.agree_any(False) is False


class TestOpAccounting:
    def test_null_collective_ops_and_bytes(self):
        tr = private_tracer()
        col = comms.instrument(NullCollective(), tracer=tr)
        x = np.ones(256, np.float32)               # 1024 bytes
        col.all_gather(x)
        col.broadcast_from(0, [x, x])
        col.barrier()
        col.agree_any(False)
        c = tr.registry.snapshot()["counters"]
        for op in comms.COLLECTIVE_OPS:
            key = f'collective_ops{{impl="NullCollective",op="{op}"}}'
            assert c.get(key) == 1.0, (op, c)
        st = tr.op_stats()
        assert st["all_gather"]["payload_bytes"] == 1024
        assert st["broadcast_from"]["payload_bytes"] == 2048
        assert st["barrier"]["payload_bytes"] == 0
        assert st["agree_any"]["payload_bytes"] == 4
        # timeline spans landed, one per op, category "collective"
        spans = tr.timeline.spans()
        assert sorted(s.name for s in spans) == sorted(
            f"collective:{op}" for op in comms.COLLECTIVE_OPS)
        assert all(s.category == "collective" for s in spans)
        # bytes attribution rides the span into every exported trace
        by_name = {s.name: s.args for s in spans}
        assert by_name["collective:all_gather"]["payload_bytes"] == 1024
        assert by_name["collective:all_gather"]["wire_bytes"] == 1024
        assert by_name["collective:barrier"]["payload_bytes"] == 0
        trace = tr.timeline.export_trace()
        gather_ev = [e for e in trace["traceEvents"]
                     if e.get("name") == "collective:all_gather"]
        assert gather_ev[0]["args"]["payload_bytes"] == 1024

    def test_local_collective_threaded_per_host_accounting(self):
        def host(r, handle):
            tr = private_tracer()
            col = comms.instrument(handle, tracer=tr)
            assert col.impl_name() == "LocalCollective"
            got = col.all_gather(np.full(64, r, np.float32))
            col.barrier()
            assert col.agree_any(r == 1) is True   # any host voting True
            return np.asarray(got), tr

        outs = run_fleet(3, host)
        for got, tr in outs:
            assert got.shape[0] == 3
            assert [float(row[0]) for row in got] == [0.0, 1.0, 2.0]
            st = tr.op_stats()
            assert st["all_gather"]["calls"] == 1
            # analytic wire bytes: payload x n for the gather
            assert st["all_gather"]["wire_bytes"] == 64 * 4 * 3
            assert st["agree_any"]["wire_bytes"] == 4 * 3
            c = tr.registry.snapshot()["counters"]
            key = ('collective_ops{impl="LocalCollective",'
                   'op="all_gather"}')
            assert c.get(key) == 1.0

    def test_histograms_observe_bytes_and_ms(self):
        tr = private_tracer()
        col = comms.instrument(NullCollective(), tracer=tr)
        col.all_gather(np.ones(1024, np.float32))
        h = tr.registry.snapshot()["histograms"]
        b = h['collective_bytes{op="all_gather"}']
        assert b["count"] == 1 and b["sum"] == 4096.0
        m = h['collective_ms{op="all_gather"}']
        assert m["count"] == 1 and m["sum"] >= 0.0
        # barrier carries no payload: no bytes observation
        col.barrier()
        h = tr.registry.snapshot()["histograms"]
        assert 'collective_bytes{op="barrier"}' not in h
        assert h['collective_ms{op="barrier"}']["count"] == 1


class TestWireBytes:
    def test_analytic_model(self):
        assert comms.wire_bytes("all_gather", 1000, 4) == 4000
        assert comms.wire_bytes("broadcast_from", 1000, 4) == 1000
        assert comms.wire_bytes("barrier", 0, 4) == 0
        assert comms.wire_bytes("agree_any", 4, 4) == 16

    def test_all_to_all(self):
        # MoE dispatch/combine (docs/moe.md): each host keeps its own
        # 1/n shard and ships the other (n-1)/n of its payload
        assert comms.wire_bytes("all_to_all", 1000, 4) == 750
        assert comms.wire_bytes("all_to_all", 1000, 2) == 500
        assert comms.wire_bytes("all_to_all", 1024, 8) == 896
        assert comms.wire_bytes("all_to_all", 1000, 1) == 0

    def test_degenerate_world(self):
        assert comms.wire_bytes("all_gather", 100, 0) == 100


class TestLedger:
    def test_measured_column_math(self):
        tr = private_tracer()
        # 2 gathers x 1 MB payload on a 4-host set, 10 ms each:
        # wire = 2 x 4 MB over 20 ms -> 400 MB/s
        for _ in range(2):
            tr.record("all_gather", "X", 1_000_000,
                      comms.wire_bytes("all_gather", 1_000_000, 4),
                      t0=0.0, dur_s=0.010)
        [row] = tr.ledger()
        assert row["op"] == "all_gather" and row["calls"] == 2
        assert row["payload_bytes"] == 2_000_000
        assert row["wire_bytes"] == 8_000_000
        assert row["wall_ms"] == pytest.approx(20.0)
        assert row["mean_ms"] == pytest.approx(10.0)
        assert row["measured_mbps"] == pytest.approx(400.0)

    def test_analytic_column_null_with_reason_without_link(self):
        tr = private_tracer()
        tr.record("barrier", "X", 0, 0, t0=0.0, dur_s=0.001)
        [row] = tr.ledger()
        assert row["analytic_ms"] is None
        assert "link_gbps" in row["analytic_reason"]
        assert row["measured_mbps"] is None      # zero wire bytes

    def test_analytic_column_with_link(self):
        tr = private_tracer(link_gbps=8.0)       # 1 GB/s
        # 4 MB wire at 1 GB/s -> 4 ms analytic; measured 8 ms -> 2.0x
        tr.record("all_gather", "X", 1_000_000, 4_000_000,
                  t0=0.0, dur_s=0.008)
        [row] = tr.ledger()
        assert row["analytic_ms"] == pytest.approx(4.0)
        assert row["measured_over_analytic"] == pytest.approx(2.0)
        assert "analytic_reason" not in row

    def test_summary_carries_the_whole_story(self):
        tr = private_tracer()
        tr.record("barrier", "X", 0, 0, t0=0.0, dur_s=0.001)
        s = tr.summary()
        assert set(s) >= {"ops", "ledger", "clock_offsets",
                          "slow_factor", "min_samples"}
        assert s["clock_offsets"] is None
        tr.note_clock_offsets({"offsets_ms": {"0": 0.0}, "spread_ms": 0.0,
                               "rounds": 3, "rtt_ms": 0.1, "junk": 1})
        s = tr.summary()
        assert s["clock_offsets"]["rounds"] == 3
        assert "junk" not in s["clock_offsets"]
        json.dumps(s)

    def test_knob_validation(self):
        with pytest.raises(ValueError, match="slow_factor"):
            comms.CommsTracer(registry=tmetrics.MetricsRegistry(),
                              slow_factor=1.0)
        with pytest.raises(ValueError, match="ewma_alpha"):
            comms.CommsTracer(registry=tmetrics.MetricsRegistry(),
                              ewma_alpha=0.0)


class TestSlowEscalation:
    def drive(self, tr, op, ms_seq):
        for ms in ms_seq:
            tr.record(op, "X", 0, 0, t0=0.0, dur_s=ms / 1e3)

    def test_latch_one_event_per_excursion(self):
        tr = private_tracer(slow_factor=4.0, min_samples=5)
        sink = tmetrics.InMemorySink()
        tr.registry.add_sink(sink)
        # 6 healthy samples warm the EWMA; a 3-op slow excursion must
        # raise ONE event; recovery unlatches; a second excursion
        # raises the second
        self.drive(tr, "barrier", [1.0] * 6)
        assert tr.op_stats()["barrier"]["slow_events"] == 0
        self.drive(tr, "barrier", [50.0, 50.0, 50.0])
        assert tr.op_stats()["barrier"]["slow_events"] == 1
        self.drive(tr, "barrier", [1.0, 1.0])      # healthy: unlatch
        self.drive(tr, "barrier", [50.0])
        st = tr.op_stats()["barrier"]
        assert st["slow_events"] == 2
        evs = [e for e in sink.events if e["event"] == "collective_slow"]
        assert len(evs) == 2
        assert evs[0]["op"] == "barrier" and evs[0]["ms"] >= 50.0
        c = tr.registry.snapshot()["counters"]
        assert c['collective_slow_total{op="barrier"}'] == 2.0

    def test_slow_sample_never_raises_its_own_bar(self):
        tr = private_tracer(slow_factor=4.0, min_samples=2, ewma_alpha=1.0)
        self.drive(tr, "barrier", [1.0, 1.0, 1.0])
        ewma_before = tr.op_stats()["barrier"]["ewma_ms"]
        self.drive(tr, "barrier", [100.0, 100.0])
        st = tr.op_stats()["barrier"]
        assert st["ewma_ms"] == pytest.approx(ewma_before)
        assert st["slow_events"] == 1

    def test_no_escalation_inside_warmup(self):
        tr = private_tracer(slow_factor=4.0, min_samples=10)
        self.drive(tr, "barrier", [1.0] * 5 + [500.0])
        assert tr.op_stats()["barrier"]["slow_events"] == 0

    def test_per_op_state_is_independent(self):
        tr = private_tracer(min_samples=2)
        self.drive(tr, "barrier", [1.0] * 3 + [50.0])
        self.drive(tr, "all_gather", [50.0] * 4)   # uniformly slow: fine
        assert tr.op_stats()["barrier"]["slow_events"] == 1
        assert tr.op_stats()["all_gather"]["slow_events"] == 0


class TestFaultClauses:
    def test_from_env_grammar(self):
        inj = faults.FaultInjector.from_env(
            "collective_slow=25;collective_slow_at=2,4;"
            "collective_payload_corrupt=1")
        assert inj.collective_slow_ms == 25.0
        assert inj.collective_slow_at == frozenset({2, 4})
        assert inj.collective_corrupt_indices == frozenset({1})

    def test_delay_applies_at_planned_indices_only(self):
        inj = faults.FaultInjector.from_env(
            "collective_slow=40;collective_slow_at=1")
        assert inj.collective_delay_s() == 0.0         # op 0
        assert inj.collective_delay_s() == pytest.approx(0.040)
        assert inj.collective_delay_s() == 0.0         # op 2

    def test_empty_at_set_means_every_op(self):
        inj = faults.FaultInjector.from_env("collective_slow=10")
        assert all(inj.collective_delay_s() == pytest.approx(0.010)
                   for _ in range(3))

    def test_injected_delay_lands_in_the_measured_ms(self):
        tr = private_tracer()
        col = comms.instrument(NullCollective(), tracer=tr)
        with faults.inject(collective_slow_ms=30.0):
            col.barrier()
        assert tr.op_stats()["barrier"]["last_ms"] >= 30.0

    def test_io_collective_raises_out_of_the_op(self):
        tr = private_tracer()
        col = comms.instrument(NullCollective(), tracer=tr)
        faults.install(faults.FaultInjector.from_env("io:collective=1"))
        col.barrier()                                   # call 0: fine
        with pytest.raises(faults.FaultError, match="collective"):
            col.barrier()                               # call 1: planned
        # the failed op never reached the tracer
        assert tr.op_stats()["barrier"]["calls"] == 1

    def test_corrupt_flips_one_byte_and_events(self):
        tr = private_tracer()
        sink = tmetrics.InMemorySink()
        tr.registry.add_sink(sink)
        col = comms.instrument(NullCollective(), tracer=tr)
        x = np.ones(16, np.float32)
        faults.install(faults.FaultInjector.from_env(
            "collective_payload_corrupt=1"))
        clean = np.asarray(col.all_gather(x))           # payload op 0
        assert np.array_equal(clean[0], x)
        bad = np.asarray(col.all_gather(x))             # payload op 1
        assert not np.array_equal(bad[0], x)
        # exactly ONE byte differs
        diff = (np.asarray(bad).view(np.uint8).reshape(-1)
                != np.asarray(clean).view(np.uint8).reshape(-1))
        assert int(diff.sum()) == 1
        evs = [e for e in sink.events
               if e["event"] == "collective_payload_corrupt"]
        assert len(evs) == 1 and evs[0]["op"] == "all_gather"

    def test_barrier_never_corruptible(self):
        tr = private_tracer()
        col = comms.instrument(NullCollective(), tracer=tr)
        faults.install(faults.FaultInjector.from_env(
            "collective_payload_corrupt=0"))
        col.barrier()          # consumes no payload-op index
        bad = np.asarray(col.all_gather(np.ones(8, np.float32)))
        assert not np.array_equal(bad[0], np.ones(8, np.float32))


class TestClockOffsets:
    def test_single_host_short_circuits(self):
        out = estimate_clock_offsets(NullCollective())
        assert out["n_hosts"] == 1 and out["rounds"] == 0
        assert out["offsets_ms"] == {"0": 0.0}
        assert out["spread_ms"] == 0.0

    def test_recovers_injected_skew(self):
        skew = [0.0, 0.25, -0.1]                  # seconds vs host 0

        def host(r, handle):
            reg = tmetrics.MetricsRegistry()
            return estimate_clock_offsets(
                handle, rounds=5, registry=reg,
                clock=lambda: time.perf_counter() + skew[r]), reg

        outs = run_fleet(3, host)
        for r, (out, reg) in enumerate(outs):
            assert out["n_hosts"] == 3
            for h in range(3):
                want = (skew[h] - skew[0]) * 1e3
                got = out["offsets_ms"][str(h)]
                assert got == pytest.approx(want, abs=10.0), (h, got)
            assert out["local_offset_ms"] == out["offsets_ms"][str(r)]
            assert out["spread_ms"] == pytest.approx(350.0, abs=20.0)
            assert out["rtt_ms"] >= 0.0
            g = reg.snapshot()["gauges"]
            assert g['fleet_clock_offset_ms{host="1"}'] == pytest.approx(
                250.0, abs=10.0)
            assert "fleet_clock_offset_spread_ms" in g

    def test_deposits_into_armed_tracer(self):
        comms.enable()

        def host(r, handle):
            return estimate_clock_offsets(
                handle, rounds=2, registry=tmetrics.MetricsRegistry())

        run_fleet(2, host)
        offs = comms.get_tracer().clock_offsets
        assert offs is not None and offs["rounds"] == 2


class TestMergedTrace:
    def test_merged_trace_well_formed(self):
        def host(r, handle):
            tl = telemetry.StepTimeline(capacity=64)
            tr = comms.CommsTracer(registry=tmetrics.MetricsRegistry(),
                                   timeline=tl)
            col = comms.instrument(handle, tracer=tr)
            with tl.phase("work"):
                col.barrier()
            instants = [{"event": "collective_slow",
                         "wall_time": time.time(), "op": "barrier",
                         "host": r}]
            return export_fleet_trace(col, timeline=tl,
                                      instant_events=instants)

        outs = run_fleet(2, host)
        for trace in outs:
            json.dumps(trace)
            evs = trace["traceEvents"]
            x_pids = {e["pid"] for e in evs if e.get("ph") == "X"}
            assert x_pids == {0, 1}
            for r in (0, 1):
                barriers = [e for e in evs if e.get("ph") == "X"
                            and e["pid"] == r
                            and e["name"] == "collective:barrier"]
                assert barriers
                # bytes/ms attribution survives the merge
                assert barriers[0]["args"]["payload_bytes"] == 0
                assert barriers[0]["dur"] >= 0
                assert any(e.get("ph") == "M"
                           and e["name"] == "process_name"
                           and e.get("pid") == r for e in evs)
            instants = [e for e in evs if e.get("ph") == "i"]
            assert {e["pid"] for e in instants} == {0, 1}
            assert all(e["name"] == "collective_slow" for e in instants)
            assert all(e["ts"] >= 0 for e in evs if "ts" in e)
            od = trace["otherData"]
            assert od["n_hosts"] == 2
            assert set(od["clock_offsets_ms"]) == {"0", "1"}

    def test_offset_correction_aligns_shared_instant(self):
        # hosts with skewed clocks time the SAME barrier; after the
        # offset shift the merged spans must land together (well under
        # the injected skew)
        skew = [0.0, 0.2]

        def host(r, handle):
            clk = lambda: time.perf_counter() + skew[r]   # noqa: E731
            tl = telemetry.StepTimeline(capacity=64, clock=clk)
            tr = comms.CommsTracer(registry=tmetrics.MetricsRegistry(),
                                   timeline=tl, clock=clk)
            col = comms.instrument(handle, tracer=tr)
            off = estimate_clock_offsets(
                col, rounds=5, clock=clk,
                registry=tmetrics.MetricsRegistry())
            col.barrier()                      # one shared fleet instant
            return export_fleet_trace(col, timeline=tl, offsets=off)

        outs = run_fleet(2, host)
        evs = outs[0]["traceEvents"]
        # the LAST collective:barrier span per host is the shared one
        last = {}
        for e in evs:
            if e.get("ph") == "X" and e["name"] == "collective:barrier":
                last[e["pid"]] = e["ts"]
        assert set(last) == {0, 1}
        assert abs(last[0] - last[1]) < 50e3   # < 50 ms, vs 200 ms skew

    def test_disabled_timeline_host_contributes_metadata_only(self):
        def host(r, handle):
            tl = telemetry.StepTimeline(capacity=8, enabled=(r == 0))
            if r == 0:
                tl.record_span("step", tl.clock(), 0.001)
            return export_fleet_trace(handle, timeline=tl,
                                      instant_events=[])

        outs = run_fleet(2, host)
        evs = outs[0]["traceEvents"]
        assert all(e["pid"] == 0 for e in evs if e.get("ph") == "X")
        assert any(e.get("ph") == "M" and e.get("pid") == 1 for e in evs)


class TestShardingIntrospection:
    def test_fixed_keys_on_cpu_with_reason(self):
        import jax
        import jax.numpy as jnp

        info = tsharding.jitted_shardings(
            jax.jit(lambda x: x * 2.0), jnp.ones((8, 4), jnp.float32),
            fn="double")
        assert set(info) == set(tsharding.SHARDING_KEYS)
        assert info["fn"] == "double"
        assert info["inputs"] and info["outputs"]
        # single-device CPU: no mesh, and the reason says so
        if info["mesh"] is None:
            assert info["sharding_reason"] is not None
            assert "single-device" in info["sharding_reason"]
        # per-device bytes are real: 8x4 f32 = 128 bytes each way
        assert info["input_bytes_per_device"] == 128
        assert info["output_bytes_per_device"] == 128
        json.dumps(info)

    def test_normalize_never_raises_on_junk(self):
        out = tsharding.normalize_sharding(object())
        assert out["kind"] == "object" and out["n_devices"] == 1
        assert out["mesh"] is None

    def test_executable_without_surface_gets_reason(self):
        info = tsharding.executable_shardings(object(), fn="junk")
        assert set(info) == set(tsharding.SHARDING_KEYS)
        assert "no shardings" in info["sharding_reason"]

    def test_lower_failure_gets_reason(self):
        info = tsharding.jitted_shardings(object(), fn="junk")
        assert "lower/compile failed" in info["sharding_reason"]

    def test_publish_folds_into_snapshot_detail(self):
        import jax
        import jax.numpy as jnp

        info = tsharding.jitted_shardings(
            jax.jit(lambda x: x + 1.0), jnp.ones((4,), jnp.float32),
            fn="inc")
        tsharding.publish_shardings(info)
        g = telemetry.registry().snapshot()["gauges"]
        assert g['sharding_devices{fn="inc"}'] == 1.0
        assert g['sharding_bytes_per_device{dir="input",fn="inc"}'] == 16.0
        detail = telemetry.snapshot_detail()
        assert detail["sharding"]["inc"]["fn"] == "inc"

    def test_snapshot_detail_null_with_reason_when_unpublished(self):
        detail = telemetry.snapshot_detail()
        assert detail["sharding"] is None
        assert "publish_shardings" in detail["sharding_reason"]


class TestSection:
    def test_disabled_marker(self):
        s = comms.section()
        assert s["enabled"] is False and "APEX_TPU_COMMS" in s["reason"]

    def test_armed_summary(self):
        comms.enable()
        col = comms.instrument(NullCollective())
        col.barrier()
        s = comms.section()
        assert s["enabled"] is True
        assert s["ops"]["barrier"]["calls"] == 1

"""Fleet telemetry aggregation (apex_tpu/telemetry/fleet.py): the
variable-length snapshot gather over the Collective abstraction, the
merge semantics (counters summed, gauges per-host + stats, histograms
bucket-merged, timelines side by side), and EWMA straggler detection.

Replica sets are simulated with ``LocalCollective`` threads, exactly
like tests/test_guard.py; the real-process analog is
``tools/fleet_drill.py`` (driven by tools/check_observability.sh).
"""

import json
import threading

import numpy as np
import pytest

from apex_tpu import telemetry
from apex_tpu.resilience.guard import LocalCollective, NullCollective
from apex_tpu.telemetry import metrics as tmetrics
from apex_tpu.telemetry.fleet import (
    DEFAULT_SNAPSHOT_CAP_BYTES,
    FleetAggregator,
    gather_snapshots,
    merge_snapshots,
    phase_means_by_host,
)


@pytest.fixture(autouse=True)
def fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()


def host_snapshot(r, *, steps=4, step_ms=10.0, timeline=True):
    """One synthetic host's ``snapshot_detail``: private registry +
    timeline the way a real host's process-global ones would look."""
    reg = tmetrics.MetricsRegistry()
    reg.counter("steps").inc(steps)
    reg.counter("skips").inc(r, kind="nonfinite")
    reg.gauge("queue_depth").set(float(r))
    reg.histogram("save_s", buckets=(0.1, 1.0)).observe(0.05 + r)
    tl_summary = None
    if timeline:
        tl = telemetry.StepTimeline(capacity=64)
        for i in range(steps):
            tl.record_span("step", i * 0.02, step_ms / 1e3, step=i)
            tl.record_span("data_wait", i * 0.02, 0.002, step=i)
        tl_summary = tl.summary()
    return {"registry": reg.snapshot(), "step_timeline": tl_summary,
            "mfu": None}


def run_fleet(n, fn):
    """Run ``fn(rid, handle)`` on one thread per simulated host;
    returns the per-host results, surfacing any thread's error."""
    group = LocalCollective(n)
    handles = group.handles()
    out = [None] * n
    errs = [None] * n

    def loop(r):
        try:
            out[r] = fn(r, handles[r])
        except BaseException as e:  # noqa: BLE001
            errs[r] = e

    ts = [threading.Thread(target=loop, args=(r,), daemon=True)
          for r in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    for e in errs:
        if e is not None:
            raise e
    return out


class TestGather:
    def test_every_host_sees_all_snapshots_in_order(self):
        outs = run_fleet(3, lambda r, h: gather_snapshots(
            h, {"host": r, "blob": "x" * (10 * (r + 1))}))
        for got in outs:
            assert [s["host"] for s in got] == [0, 1, 2]
            # variable-length payloads survive the padded transport
            assert [len(s["blob"]) for s in got] == [10, 20, 30]

    def test_null_collective_and_none_are_local(self):
        snap = {"host": 0}
        assert gather_snapshots(NullCollective(), snap) == [snap]
        assert gather_snapshots(None, snap) == [snap]

    def test_default_snapshot_is_process_detail(self):
        telemetry.registry().counter("c").inc(5)
        [got] = gather_snapshots(None)
        assert got["registry"]["counters"]["c"] == 5.0

    def test_oversized_snapshot_rides_as_stub_not_silence(self):
        """The gather cap regression: one host past ``max_bytes`` must
        gather as a structured stub + ONE fleet_snapshot_truncated
        event on that host — the other hosts' views stay intact and
        the merge still works."""
        regs = [tmetrics.MetricsRegistry() for _ in range(3)]

        def host(r, handle):
            snap = host_snapshot(r)
            if r == 1:
                snap["blob"] = "x" * 4096          # past the tiny cap
            return gather_snapshots(handle, snap, max_bytes=1024,
                                    registry=regs[r])

        outs = run_fleet(3, host)
        for got in outs:
            # hosts 0/2 intact, host 1 a valid-shaped marked stub
            assert got[0]["registry"]["counters"]["steps"] == 4.0
            assert got[2]["registry"]["counters"]["steps"] == 4.0
            stub = got[1]
            assert stub["truncated"] is True
            assert stub["replica_id"] == 1
            assert stub["max_bytes"] == 1024
            assert stub["original_bytes"] > 1024
            assert stub["step_timeline"] is None
            # the merge never chokes on the stub
            fleet = merge_snapshots(got)
            assert fleet["counters"]["steps"] == 8.0
        # the event + counter landed on the oversized host ONLY
        c1 = regs[1].snapshot()["counters"]
        assert c1["fleet_snapshot_truncated_total"] == 1.0
        assert c1['telemetry_events{event="fleet_snapshot_truncated"}'] \
            == 1.0
        for r in (0, 2):
            assert "fleet_snapshot_truncated_total" \
                not in regs[r].snapshot()["counters"]

    def test_default_cap_admits_normal_snapshots(self):
        outs = run_fleet(2, lambda r, h: gather_snapshots(
            h, host_snapshot(r)))
        assert all("truncated" not in s for got in outs for s in got)
        assert DEFAULT_SNAPSHOT_CAP_BYTES == 4 << 20


class TestMerge:
    def test_counters_sum_gauges_stat_histograms_bucket_merge(self):
        fleet = merge_snapshots([host_snapshot(r) for r in range(3)])
        assert fleet["n_hosts"] == 3
        # counters (incl. labeled series) SUM across hosts
        assert fleet["counters"]["steps"] == 12.0
        assert fleet["counters"]['skips{kind="nonfinite"}'] == 3.0
        # gauges stay per-host with min/max/mean — summing a
        # last-write-wins value would lie
        g = fleet["gauges"]["queue_depth"]
        assert g["per_host"] == {"0": 0.0, "1": 1.0, "2": 2.0}
        assert g["min"] == 0.0 and g["max"] == 2.0 and g["mean"] == 1.0
        # histograms: cumulative counts at the same le add
        h = fleet["histograms"]["save_s"]
        assert h["count"] == 3
        assert h["buckets"]["0.1"] == 1          # only host 0's 0.05
        assert h["buckets"]["+Inf"] == 3
        assert h["sum"] == pytest.approx(0.15 + 1 + 2)
        # per-host step-phase summaries side by side
        assert set(fleet["step_timelines"]) == {"0", "1", "2"}
        assert fleet["step_timelines"]["1"]["phases"]["step"]["count"] == 4
        json.dumps(fleet)                        # one JSON-able dict

    def test_disabled_timeline_host_merges_as_none(self):
        fleet = merge_snapshots([host_snapshot(0),
                                 host_snapshot(1, timeline=False)])
        assert fleet["step_timelines"]["1"] is None
        assert fleet["counters"]["steps"] == 8.0
        # and the straggler derivation skips the blind host
        means = phase_means_by_host(
            [host_snapshot(0), host_snapshot(1, timeline=False)], "step")
        assert list(means) == [0]

    def test_empty_registry_host(self):
        fleet = merge_snapshots([
            {"registry": {"counters": {}, "gauges": {}, "histograms": {}},
             "step_timeline": None, "mfu": None},
            host_snapshot(1)])
        assert fleet["counters"]["steps"] == 4.0


class TestStraggler:
    def test_slow_host_flagged_and_published(self):
        agg = FleetAggregator(None, straggler_factor=2.0)
        per_host = [host_snapshot(0), host_snapshot(1),
                    host_snapshot(2, step_ms=50.0)]
        rep = agg.straggler_report(per_host)
        step = rep["phases"]["step"]
        assert step["median_ms"] == pytest.approx(10.0)
        assert step["spread"] == pytest.approx(5.0)
        assert [s["host"] for s in step["stragglers"]] == ["2"]
        assert step["stragglers"][0]["ratio_to_median"] == pytest.approx(5.0)
        # publish path: gauges + one event per flagged (host, phase)
        agg._publish(rep)
        reg = telemetry.registry()
        assert reg.gauge("fleet_straggler_spread").value(
            phase="step") == pytest.approx(5.0)
        assert reg.gauge("fleet_stragglers").value() == 1.0
        assert reg.gauge("fleet_phase_ms").value(
            phase="step", host="2") == pytest.approx(50.0)
        assert reg.counter("telemetry_events").value(
            event="fleet_straggler") == 1.0

    def test_clean_fleet_flags_nobody(self):
        agg = FleetAggregator(None)
        rep = agg.straggler_report([host_snapshot(r) for r in range(3)])
        assert rep["n_stragglers"] == 0
        assert rep["phases"]["step"]["stragglers"] == []
        assert rep["phases"]["step"]["spread"] == pytest.approx(1.0)

    def test_ewma_converges_not_jumps(self):
        # one noisy window must not flag a host; a persistent slowdown
        # converges toward the new level
        agg = FleetAggregator(None, straggler_factor=3.0, ewma_alpha=0.5)
        agg.straggler_report([host_snapshot(r) for r in range(2)])
        rep = agg.straggler_report([host_snapshot(0),
                                    host_snapshot(1, step_ms=90.0)])
        e1 = float(rep["phases"]["step"]["per_host_ewma_ms"]["1"])
        assert e1 == pytest.approx(0.5 * 10 + 0.5 * 90)     # not 90
        rep = agg.straggler_report([host_snapshot(0),
                                    host_snapshot(1, step_ms=90.0)])
        e2 = float(rep["phases"]["step"]["per_host_ewma_ms"]["1"])
        assert e2 > e1                                      # converging

    def test_single_host_never_flags(self):
        agg = FleetAggregator(None)
        rep = agg.straggler_report([host_snapshot(0, step_ms=500.0)])
        assert rep["n_stragglers"] == 0

    def test_knob_validation(self):
        with pytest.raises(ValueError, match="straggler_factor"):
            FleetAggregator(None, straggler_factor=1.0)
        with pytest.raises(ValueError, match="ewma_alpha"):
            FleetAggregator(None, ewma_alpha=0.0)


class TestAggregate:
    def test_threaded_fleet_aggregates_identically(self):
        def host(r, handle):
            agg = FleetAggregator(handle)
            return agg.aggregate(host_snapshot(r, step_ms=10.0 * (r + 1)),
                                 publish=False)

        outs = run_fleet(3, host)
        # every host derived the identical fleet view from the
        # identical gather
        for fleet in outs:
            assert fleet["counters"]["steps"] == 12.0
            strag = fleet["straggler"]["phases"]["step"]
            assert strag["spread"] == pytest.approx(3.0)
            assert fleet["aggregation_ms"] >= 0.0
        a, b = (dict(o, aggregation_ms=None) for o in outs[:2])
        assert json.dumps(a, sort_keys=True) == json.dumps(b,
                                                           sort_keys=True)

    def test_single_host_aggregate_uses_local_snapshot(self):
        telemetry.registry().counter("c").inc(2)
        fleet = FleetAggregator(NullCollective()).aggregate()
        assert fleet["n_hosts"] == 1
        assert fleet["counters"]["c"] == 2.0

    def test_multiproc_fleet_aggregator_single_host(self):
        from apex_tpu.parallel import multiproc

        agg = multiproc.fleet_aggregator(straggler_factor=4.0)
        assert isinstance(agg.collective, NullCollective)
        assert agg.straggler_factor == 4.0

"""Fused optimizer tests — fused-vs-reference equivalence.

Mirrors ref tests/L0/run_optimizers/test_fused_optimizer.py,
test_lamb.py, test_fused_novograd.py: each fused optimizer against an
independent reference (optax or hand-rolled numpy), plus master-weight
dtype behavior and jit stability.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.optimizers import (
    FusedAdagrad,
    FusedAdam,
    FusedLAMB,
    FusedLARS,
    FusedNovoGrad,
    FusedSGD,
    as_optax,
)


def make_params(rng, dtype=jnp.float32):
    return {
        "layer1": {
            "kernel": jnp.asarray(rng.randn(17, 33), dtype),
            "bias": jnp.asarray(rng.randn(33), dtype),
        },
        "layer2": {"kernel": jnp.asarray(rng.randn(33, 5), dtype)},
    }


def make_grads(rng, params):
    return jax.tree.map(lambda p: jnp.asarray(rng.randn(*p.shape) * 0.1, jnp.float32), params)


class TestFusedAdamVsOptax:
    def test_matches_adamw(self, rng):
        params = make_params(rng)
        opt = FusedAdam(lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                        weight_decay=0.01, adam_w_mode=True, impl="xla")
        state = opt.init(params)
        ref = optax.adamw(1e-3, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01)
        ref_state = ref.init(params)
        ref_params = params
        for i in range(5):
            grads = make_grads(np.random.RandomState(i), params)
            params, state = opt.step(state, grads)
            updates, ref_state = ref.update(grads, ref_state, ref_params)
            ref_params = optax.apply_updates(ref_params, updates)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            ),
            params, ref_params,
        )

    def test_matches_adam_l2(self, rng):
        params = make_params(rng)
        opt = FusedAdam(lr=1e-3, weight_decay=0.0, adam_w_mode=False, impl="xla")
        state = opt.init(params)
        ref = optax.adam(1e-3)
        ref_state = ref.init(params)
        ref_params = params
        for i in range(3):
            grads = make_grads(np.random.RandomState(i), params)
            params, state = opt.step(state, grads)
            updates, ref_state = ref.update(grads, ref_state, ref_params)
            ref_params = optax.apply_updates(ref_params, updates)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            ),
            params, ref_params,
        )


class TestFusedSGDVsOptax:
    @pytest.mark.parametrize("momentum,nesterov", [(0.0, False), (0.9, False), (0.9, True)])
    def test_matches_optax_sgd(self, rng, momentum, nesterov):
        params = make_params(rng)
        opt = FusedSGD(lr=0.1, momentum=momentum, nesterov=nesterov, impl="xla")
        state = opt.init(params)
        ref = optax.sgd(0.1, momentum=momentum if momentum else None,
                        nesterov=nesterov)
        ref_state = ref.init(params)
        ref_params = params
        for i in range(4):
            grads = make_grads(np.random.RandomState(i), params)
            params, state = opt.step(state, grads)
            updates, ref_state = ref.update(grads, ref_state, ref_params)
            ref_params = optax.apply_updates(ref_params, updates)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            params, ref_params,
        )


class TestFusedLAMB:
    def test_decreases_quadratic_loss(self, rng):
        params = {"w": jnp.asarray(rng.randn(256), jnp.float32)}
        target = jnp.asarray(rng.randn(256), jnp.float32)
        opt = FusedLAMB(lr=0.05, weight_decay=0.01, impl="xla")
        state = opt.init(params)

        def loss_fn(p):
            return jnp.sum((p["w"] - target) ** 2)

        losses = []
        for _ in range(60):
            loss, grads = jax.value_and_grad(loss_fn)(params)
            params, state = opt.step(state, grads)
            losses.append(float(loss))
        assert losses[-1] < 0.1 * losses[0]

    def test_jit_step_stable(self, rng):
        params = make_params(rng)
        opt = FusedLAMB(lr=0.01, impl="xla")
        state = opt.init(params)

        @jax.jit
        def step(state, grads):
            return opt.step(state, grads)

        for i in range(3):
            grads = make_grads(np.random.RandomState(i), params)
            params, state = step(state, grads)
        assert int(state.count) == 3


class TestMasterWeights:
    def test_bf16_params_fp32_master(self, rng):
        """O5-style flow: bf16 model params, fp32 master inside optimizer
        (ref: apex/amp/_process_optimizer.py:28-90)."""
        params32 = make_params(rng)
        params16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params32)
        opt = FusedSGD(lr=0.01, momentum=0.9, impl="xla")
        state = opt.init(params16)
        assert state.master.dtype == jnp.float32
        grads = make_grads(rng, params32)
        new_params, state = opt.step(state, grads)
        # returned params keep the model dtype
        assert new_params["layer1"]["kernel"].dtype == jnp.bfloat16
        # master keeps full precision across steps (no bf16 round-trip drift)
        tiny = jax.tree.map(lambda g: g * 1e-6, grads)
        m0 = np.asarray(state.master)
        _, state2 = opt.step(state, tiny)
        assert not np.array_equal(np.asarray(state2.master), m0)


class TestNovoGradLARS:
    def test_novograd_converges(self, rng):
        # NovoGrad normalizes grads per-tensor, so the effective per-element
        # step is ~lr/sqrt(n); size lr accordingly
        params = {"w": jnp.asarray(rng.randn(64), jnp.float32)}
        opt = FusedNovoGrad(lr=0.5, impl="xla")
        state = opt.init(params)

        def loss_fn(p):
            return jnp.sum(p["w"] ** 2)

        l0 = float(loss_fn(params))
        for _ in range(100):
            grads = jax.grad(loss_fn)(params)
            params, state = opt.step(state, grads)
        assert float(loss_fn(params)) < 0.2 * l0

    def test_lars_converges(self, rng):
        params = {"w": jnp.asarray(rng.randn(512), jnp.float32)}
        opt = FusedLARS(lr=0.5, momentum=0.9, impl="xla")
        state = opt.init(params)

        def loss_fn(p):
            return jnp.sum(p["w"] ** 2)

        l0 = float(loss_fn(params))
        for _ in range(30):
            grads = jax.grad(loss_fn)(params)
            params, state = opt.step(state, grads)
        assert float(loss_fn(params)) < 0.2 * l0


class TestAdagrad:
    def test_matches_optax(self, rng):
        params = make_params(rng)
        opt = FusedAdagrad(lr=0.01, eps=1e-10, impl="xla")
        state = opt.init(params)
        ref = optax.adagrad(0.01, initial_accumulator_value=0.0, eps=1e-10)
        ref_state = ref.init(params)
        ref_params = params
        for i in range(4):
            grads = make_grads(np.random.RandomState(i), params)
            params, state = opt.step(state, grads)
            updates, ref_state = ref.update(grads, ref_state, ref_params)
            ref_params = optax.apply_updates(ref_params, updates)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            ),
            params, ref_params,
        )


class TestOptaxAdapter:
    def test_as_optax(self, rng):
        params = make_params(rng)
        opt = as_optax(FusedAdam(lr=1e-3, impl="xla"))
        state = opt.init(params)
        grads = make_grads(rng, params)
        updates, state = opt.update(grads, state, params=params)
        new_params = optax.apply_updates(params, updates)
        direct = FusedAdam(lr=1e-3, impl="xla")
        dstate = direct.init(params)
        expected, _ = direct.step(dstate, grads)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
            ),
            new_params, expected,
        )

    def test_scheduled_lr(self, rng):
        sched = lambda count: 0.1 / (1.0 + count.astype(jnp.float32))
        params = {"w": jnp.ones((64,), jnp.float32)}
        opt = FusedSGD(lr=sched, momentum=0.0, impl="xla")
        state = opt.init(params)
        g = {"w": jnp.ones((64,), jnp.float32)}
        p1, state = opt.step(state, g)       # lr = 0.1
        np.testing.assert_allclose(np.asarray(p1["w"]), 0.9 * np.ones(64), rtol=1e-6)
        p2, state = opt.step(state, g)       # lr = 0.05
        np.testing.assert_allclose(np.asarray(p2["w"]), 0.85 * np.ones(64), rtol=1e-6)


class TestFusedMixedPrecisionLamb:
    """ref: apex/optimizers/fused_mixed_precision_lamb.py — bf16 model
    weights with fp32 masters, fp32 params updated directly."""

    def test_mixed_tree_dtypes_roundtrip(self, rng):
        from apex_tpu.optimizers import FusedMixedPrecisionLamb

        params = {
            "w_bf16": jnp.asarray(rng.randn(128, 64), jnp.bfloat16),
            "w_fp32": jnp.asarray(rng.randn(64), jnp.float32),
        }
        opt = FusedMixedPrecisionLamb(
            lr=0.01, reduced_precision_dtype=jnp.bfloat16, impl="xla")
        state = opt.init(params)
        grads = jax.tree.map(lambda p: jnp.ones_like(p, jnp.float32), params)
        new_params, state = opt.step(state, grads)
        assert new_params["w_bf16"].dtype == jnp.bfloat16
        assert new_params["w_fp32"].dtype == jnp.float32
        # masters stay fp32 for every leaf
        masters = opt.master_params(state)
        assert all(m.dtype == jnp.float32 for m in jax.tree.leaves(masters))

    def test_rejects_undeclared_dtype(self, rng):
        from apex_tpu.optimizers import FusedMixedPrecisionLamb

        params = {"w": jnp.asarray(rng.randn(8), jnp.float16)}
        opt = FusedMixedPrecisionLamb(
            lr=0.01, reduced_precision_dtype=jnp.bfloat16, impl="xla")
        with pytest.raises(ValueError, match="float32 or"):
            opt.init(params)

    def test_matches_fused_lamb_on_fp32(self, rng):
        from apex_tpu.optimizers import FusedLAMB, FusedMixedPrecisionLamb

        params = {"w": jnp.asarray(rng.randn(256), jnp.float32)}
        grads = {"w": jnp.asarray(rng.randn(256).astype(np.float32) * 0.1)}
        a = FusedLAMB(lr=0.01, impl="xla")
        b = FusedMixedPrecisionLamb(lr=0.01, impl="xla")
        pa, _ = a.step(a.init(params), grads)
        pb, _ = b.step(b.init(params), grads)
        np.testing.assert_array_equal(np.asarray(pa["w"]), np.asarray(pb["w"]))


class TestStochasticRoundingMaster:
    """Master-free bf16 training (master_dtype=bf16 + SR): the
    TPU-native replacement for the fp32-master discipline."""

    def test_bf16_master_state_memory(self, rng):
        opt = FusedAdam(lr=1e-3, master_dtype=jnp.bfloat16,
                        stochastic_rounding=True, impl="xla")
        params = make_params(rng, jnp.bfloat16)
        state = opt.init(params)
        assert state.master.dtype == jnp.bfloat16
        # slot EMAs stay fp32 (bf16 quantization bias hits m/v hardest)
        assert state.slots["m"].dtype == jnp.float32
        assert state.slots["v"].dtype == jnp.float32

    def test_requires_bf16_and_sr_together(self):
        with pytest.raises(ValueError, match="bfloat16"):
            FusedAdam(master_dtype=jnp.float16, stochastic_rounding=True)
        with pytest.raises(ValueError, match="stochastic_rounding"):
            FusedAdam(master_dtype=jnp.bfloat16)

    def test_rejects_wider_leaves(self, rng):
        """A reduced master must not silently quantize fp32 leaves
        (e.g. layernorm scales) at init — explicit cast required."""
        opt = FusedAdam(master_dtype=jnp.bfloat16,
                        stochastic_rounding=True, impl="xla")
        params = {"w": jnp.asarray(rng.randn(64), jnp.bfloat16),
                  "ln": jnp.asarray(rng.randn(8), jnp.float32)}
        with pytest.raises(ValueError, match="float32"):
            opt.init(params)

    @pytest.mark.parametrize(
        "opt_cls", [FusedAdam, FusedLAMB, FusedSGD, FusedNovoGrad])
    def test_trains_close_to_fp32(self, rng, impl, opt_cls):
        """bf16+SR reaches a loss in the same regime as the fp32-master
        run on a small regression (the reference-style convergence
        check, ref tests/L0/run_optimizers/test_fused_optimizer.py)."""
        W = jnp.asarray(rng.randn(16, 16) * 0.7, jnp.float32)
        X = jnp.asarray(rng.randn(512, 16), jnp.float32)
        Y = jnp.tanh(X @ W)

        def loss_fn(pt):
            h = jnp.tanh(X @ pt["w1"].astype(jnp.float32))
            return jnp.mean((h @ pt["w2"].astype(jnp.float32) - Y) ** 2)

        def train(dtype, **kw):
            params = {
                "w1": jnp.asarray(rng.randn(16, 32) * 0.3, dtype),
                "w2": jnp.asarray(rng.randn(32, 16) * 0.3, dtype),
            }
            kwargs = dict(lr=0.03) if opt_cls is not FusedSGD else dict(lr=0.3)
            opt = opt_cls(**kwargs, impl=impl, **kw)
            state = opt.init(params)

            @jax.jit
            def step(pp, st):
                l, gr = jax.value_and_grad(loss_fn)(pp)
                pp2, st2 = opt.step(st, gr)
                return pp2, st2, l

            for _ in range(80):
                params, state, l = step(params, state)
            return float(l)

        rng_state = rng.get_state()
        l_fp32 = train(jnp.float32)
        rng.set_state(rng_state)            # identical init
        l_sr = train(jnp.bfloat16, master_dtype=jnp.bfloat16,
                     stochastic_rounding=True)
        assert l_sr < max(3.0 * l_fp32, 5e-3), (l_sr, l_fp32)

    @pytest.mark.l1
    def test_long_horizon_trajectory_quality(self, rng):
        """>= 500-step trajectory: master-free bf16+SR must track the
        fp32-master run's loss curve, not just its 80-step regime
        (VERDICT r3 #4 — the claim is drift-free ACCUMULATION, which
        only a long horizon exercises). Uses the XLA SR emulation
        (same math as the in-kernel pltpu.stochastic_round path)."""
        W = jnp.asarray(rng.randn(24, 24) * 0.6, jnp.float32)
        X = jnp.asarray(rng.randn(256, 24), jnp.float32)
        Y = jnp.tanh(X @ W)

        def loss_fn(pt):
            h = jnp.tanh(X @ pt["w1"].astype(jnp.float32))
            return jnp.mean((h @ pt["w2"].astype(jnp.float32) - Y) ** 2)

        def train(dtype, steps=500, **kw):
            params = {
                "w1": jnp.asarray(rng.randn(24, 48) * 0.3, dtype),
                "w2": jnp.asarray(rng.randn(48, 24) * 0.3, dtype),
            }
            opt = FusedLAMB(lr=0.06, weight_decay=0.0, max_grad_norm=0.0,
                            impl="xla", **kw)
            state = opt.init(params)

            @jax.jit
            def k_steps(pp, st):
                def body(_, c):
                    pp, st, _ = c
                    l, gr = jax.value_and_grad(loss_fn)(pp)
                    pp2, st2 = opt.step(st, gr)
                    return pp2, st2, l
                return jax.lax.fori_loop(
                    0, 50, body, (pp, st, jnp.float32(0.0)))

            l_init = float(loss_fn(params))
            curve = []
            for _ in range(steps // 50):
                params, state, l = k_steps(params, state)
                curve.append(float(l))
            return [l_init] + curve

        rng_state = rng.get_state()
        c_fp32 = train(jnp.float32)
        rng.set_state(rng_state)            # identical init draw
        c_sr = train(jnp.bfloat16, master_dtype=jnp.bfloat16,
                     stochastic_rounding=True)
        # both converge substantially...
        assert c_fp32[-1] < c_fp32[0] / 10
        assert c_sr[-1] < c_sr[0] / 10
        # ...and SR never drifts away from the fp32 curve late in
        # training (the failure mode of nearest-rounded bf16 masters)
        assert c_sr[-1] < max(3.0 * c_fp32[-1], 1e-3), (c_sr, c_fp32)

    def test_sr_seed_advances_with_count(self, rng):
        """Two consecutive steps must use different SR streams (seeded
        by the unskipped-step counter), and resume from a checkpointed
        state must reproduce the same stream."""
        opt = FusedSGD(lr=1.0, master_dtype=jnp.bfloat16,
                       stochastic_rounding=True, impl="xla")
        params = {"w": jnp.full((4096,), 1.0, jnp.bfloat16)}
        g = {"w": jnp.full((4096,), 2.0 ** -9, jnp.float32)}
        s0 = opt.init(params)
        p1, s1 = opt.step(s0, g)
        p2, s2 = opt.step(s1, g)
        # different steps -> different rounding pattern
        a1 = np.asarray(p1["w"], np.float32)
        d2 = np.asarray(p2["w"], np.float32) - a1
        assert (np.unique(a1).size > 1) and (np.unique(d2).size > 1)
        # replay step 2 from the same state: bitwise identical
        p2r, _ = opt.step(s1, g)
        np.testing.assert_array_equal(np.asarray(p2["w"]),
                                      np.asarray(p2r["w"]))


class TestStepFlat:
    """step_flat consumes grads already in the flat space — bitwise the
    same update as step(pack(tree)), and the layout jax.grad produces
    when the loss differentiates through space.unpack(master)."""

    def test_step_flat_matches_step(self):
        from apex_tpu.optimizers import FusedAdam

        rng = np.random.RandomState(0)
        params = {"a": jnp.asarray(rng.randn(64, 32).astype(np.float32)),
                  "b": jnp.asarray(rng.randn(17).astype(np.float32))}
        grads = jax.tree.map(
            lambda p: jnp.asarray(
                rng.randn(*p.shape).astype(np.float32) * 1e-2), params)
        opt = FusedAdam(lr=1e-3, weight_decay=0.01)
        s0 = opt.init(params)

        p_tree, s_tree = opt.step(s0, grads)
        flat = s0.space.pack(grads, dtype=jnp.float32)
        p_flat, s_flat = opt.step_flat(opt.init(params), flat)
        np.testing.assert_array_equal(np.asarray(s_tree.master),
                                      np.asarray(s_flat.master))
        for a, b in zip(jax.tree.leaves(p_tree), jax.tree.leaves(p_flat)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_grad_through_unpack_is_flat(self):
        """The flat-native loop: jax.grad w.r.t. the master buffer
        yields flat grads step_flat accepts, and the resulting training
        trajectory matches the tree-grad path."""
        from apex_tpu.optimizers import FusedAdam

        rng = np.random.RandomState(1)
        params = {"w": jnp.asarray(rng.randn(8, 4).astype(np.float32)),
                  "b": jnp.asarray(np.zeros(4, np.float32))}
        x = jnp.asarray(rng.randn(16, 8).astype(np.float32))
        y = jnp.asarray(rng.randn(16, 4).astype(np.float32))

        def loss_tree(p):
            return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

        opt = FusedAdam(lr=1e-2)
        s_a = opt.init(params)
        s_b = opt.init(params)
        for _ in range(3):
            p_a = s_a.space.unpack(s_a.master)
            _, s_a = opt.step(s_a, jax.grad(loss_tree)(p_a))
            gflat = jax.grad(
                lambda mm: loss_tree(s_b.space.unpack(mm)))(s_b.master)
            _, s_b = opt.step_flat(s_b, gflat)
        np.testing.assert_allclose(np.asarray(s_a.master),
                                   np.asarray(s_b.master), rtol=1e-6)

    def test_step_flat_shape_mismatch(self):
        from apex_tpu.optimizers import FusedAdam

        params = {"a": jnp.zeros((32,), jnp.float32)}
        opt = FusedAdam(lr=1e-3)
        s0 = opt.init(params)
        with pytest.raises(ValueError, match="flat_grads shape"):
            opt.step_flat(s0, jnp.zeros((s0.master.shape[0] + 1,)))

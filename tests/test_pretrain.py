"""Full-parallel pretrain composition tests (DP x TP x PP on the GSPMD
mesh) + driver entry points.

PR-16: `make_gpt_pretrain_step` is a thin composition over the mesh
substrate — plain :class:`MeshTrainStep` at pipe=1, a
:class:`MeshPipelineTrainStep` schedule at pipe>1, same standard param
tree either way. Schedule mechanics themselves are pinned by
tests/test_mesh_pipeline.py; this file pins the composition surface.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import mesh as gmesh
from apex_tpu.models.gpt import GPTConfig
from apex_tpu.models.pretrain import (
    init_gpt_pretrain_params,
    make_gpt_pretrain_step,
)
from apex_tpu.optimizers import FusedAdam


@pytest.fixture(autouse=True)
def clean():
    gmesh.destroy_mesh()
    yield
    gmesh.destroy_mesh()


class TestPretrainStep:
    # one TP+PP config stays in tier-1; the rest of the grid (~10s per
    # config of simulated-mesh compute) runs in the slow tier
    @pytest.mark.parametrize("tp,pp,vpp,schedule", [
        (2, 2, 1, "1f1b"),
        pytest.param(2, 2, 1, "gpipe", marks=pytest.mark.slow),
        pytest.param(4, 2, 1, "1f1b", marks=pytest.mark.slow),
        pytest.param(1, 4, 1, "1f1b", marks=pytest.mark.slow),
        # interleaved schedule composed with TP: the vpp chunk rows
        # must interoperate with the TP collectives inside each chunk
        pytest.param(2, 2, 2, "interleaved_1f1b", marks=pytest.mark.slow),
        pytest.param(1, 2, 2, "interleaved_1f1b", marks=pytest.mark.slow),
    ])
    def test_step_runs_and_loss_decreases(self, rng, tp, pp, vpp, schedule):
        gmesh.initialize_mesh(model=tp, pipe=pp)
        dp = 8 // (tp * pp)
        layers = max(pp * vpp, 2)
        cfg = GPTConfig(
            vocab_size=128, max_seq_len=32, hidden_size=64,
            num_layers=layers, num_heads=4, dtype=jnp.float32,
        )
        params = init_gpt_pretrain_params(cfg, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=2e-3, impl="xla")
        step, state = make_gpt_pretrain_step(
            cfg, opt, schedule=schedule, num_microbatches=2,
            num_model_chunks=vpp)(params)
        toks = jnp.asarray(rng.randint(0, 128, (4 * dp, 33)), jnp.int32)
        x, y = toks[:, :-1], toks[:, 1:]
        losses = []
        for _ in range(5):
            state, loss = step(state, x, y)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    @pytest.mark.slow
    def test_sr_bf16_master_free(self, rng):
        """The full parallel pretrain stack composes with the
        master-free bf16 stochastic-rounding optimizer mode: params and
        optimizer master live in bf16 end to end, loss still drops."""
        gmesh.initialize_mesh(model=2, pipe=2)
        cfg = GPTConfig(
            vocab_size=128, max_seq_len=32, hidden_size=64,
            num_layers=2, num_heads=4, dtype=jnp.bfloat16,
        )
        params = init_gpt_pretrain_params(cfg, jax.random.PRNGKey(0))
        params = jax.tree.map(lambda l: l.astype(jnp.bfloat16), params)
        opt = FusedAdam(lr=2e-3, impl="xla", master_dtype=jnp.bfloat16,
                        stochastic_rounding=True)
        step, state = make_gpt_pretrain_step(
            cfg, opt, num_microbatches=2)(params)
        toks = jnp.asarray(rng.randint(0, 128, (8, 33)), jnp.int32)
        x, y = toks[:, :-1], toks[:, 1:]
        losses = []
        for _ in range(6):
            state, loss = step(state, x, y)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
        assert state.flat.dtype == jnp.bfloat16

    def test_matches_single_device(self, rng):
        """Pipelined parallel pretrain loss == dense sequential model
        loss on the same params."""
        gmesh.initialize_mesh(model=2, pipe=2)
        cfg = GPTConfig(
            vocab_size=64, max_seq_len=16, hidden_size=32, num_layers=2,
            num_heads=4, dtype=jnp.float32,
        )
        params = init_gpt_pretrain_params(cfg, jax.random.PRNGKey(1))
        opt = FusedAdam(lr=1e-3, impl="xla")
        step, state = make_gpt_pretrain_step(
            cfg, opt, num_microbatches=2)(params)
        toks = jnp.asarray(rng.randint(0, 64, (4, 17)), jnp.int32)
        x, y = toks[:, :-1], toks[:, 1:]
        _, loss = step(state, x, y)

        # dense reference: same params applied sequentially
        from apex_tpu.models.gpt import GPTLayer
        from apex_tpu.normalization import FusedLayerNorm

        def dense_loss(variables):
            params = variables["params"]
            table = params["embedding"]["embedding"]
            h = table[x] + params["position_embedding"][:16][None]
            h = h.transpose(1, 0, 2)
            layer = GPTLayer(cfg)
            for i in range(cfg.num_layers):
                lp = jax.tree.map(lambda l: l[i],
                                  params["layers"]["layer"])
                h = layer.apply({"params": lp}, h)
            h = FusedLayerNorm(cfg.hidden_size).apply(
                {"params": params["final_norm"]}, h
            )
            logits = jnp.einsum("sbh,vh->sbv", h, table)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(
                logits, y.transpose(1, 0)[..., None], -1
            )[..., 0]
            return jnp.mean(lse - tgt)

        np.testing.assert_allclose(float(loss), float(dense_loss(params)),
                                   rtol=2e-4)

    def test_no_mesh_identity_fallback(self, rng):
        """With no mesh armed, the build degenerates to the 1-device
        identity plan — same code path, plain MeshTrainStep."""
        cfg = GPTConfig(
            vocab_size=64, max_seq_len=16, hidden_size=32, num_layers=2,
            num_heads=4, dtype=jnp.float32,
        )
        params = init_gpt_pretrain_params(cfg, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=2e-3, impl="xla")
        step, state = make_gpt_pretrain_step(cfg, opt)(params)
        assert not isinstance(step, gmesh.MeshPipelineTrainStep)
        toks = jnp.asarray(rng.randint(0, 64, (2, 17)), jnp.int32)
        state, loss = step(state, toks[:, :-1], toks[:, 1:])
        assert np.isfinite(float(loss))


class TestGraftEntry:
    def test_entry_compiles(self):
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape[0] == 256

    @pytest.mark.slow
    def test_dryrun_multichip(self):
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as g

        g.dryrun_multichip(8)

"""Full-parallel pretrain composition tests (DP x TP x SP x PP) +
driver entry points."""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt import GPTConfig
from apex_tpu.models.pretrain import (
    init_gpt_pretrain_params,
    make_gpt_pretrain_step,
)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state as ps


@pytest.fixture(autouse=True)
def clean():
    ps.destroy_model_parallel()
    yield
    ps.destroy_model_parallel()


class TestPretrainStep:
    # one TP+SP config stays in tier-1; the rest of the grid (~10s per
    # config of simulated-mesh compute) runs in the slow tier
    @pytest.mark.parametrize("tp,pp,sp,vpp", [
        (2, 2, True, 1),
        pytest.param(2, 2, False, 1, marks=pytest.mark.slow),
        pytest.param(4, 2, True, 1, marks=pytest.mark.slow),
        pytest.param(1, 4, False, 1, marks=pytest.mark.slow),
        # interleaved schedule composed with TP(+SP): the vpp tick scan
        # must interoperate with the TP collectives inside each chunk
        pytest.param(2, 2, True, 2, marks=pytest.mark.slow),
        pytest.param(2, 2, False, 2, marks=pytest.mark.slow),
    ])
    def test_step_runs_and_loss_decreases(self, rng, tp, pp, sp, vpp):
        mesh = ps.initialize_model_parallel(tp, pp)
        dp = 8 // (tp * pp)
        layers = max(pp * vpp, 2)
        cfg = GPTConfig(
            vocab_size=128, max_seq_len=32, hidden_size=64,
            num_layers=layers, num_heads=4,
            dtype=jnp.float32, sequence_parallel=sp,
        )
        params = init_gpt_pretrain_params(cfg, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=2e-3, impl="xla")
        build = make_gpt_pretrain_step(cfg, mesh, opt, num_microbatches=2,
                                       num_model_chunks=vpp)
        init_opt, step_fn, _ = build(params)
        opt_state = init_opt(params)
        toks = jnp.asarray(rng.randint(0, 128, (4 * dp, 33)), jnp.int32)
        x, y = toks[:, :-1], toks[:, 1:]
        losses = []
        for _ in range(5):
            params, opt_state, loss = step_fn(params, opt_state, x, y)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    @pytest.mark.slow
    def test_sr_bf16_master_free(self, rng):
        """The full parallel pretrain stack composes with the
        master-free bf16 stochastic-rounding optimizer mode: params and
        optimizer master live in bf16 end to end, loss still drops."""
        mesh = ps.initialize_model_parallel(2, 2)
        cfg = GPTConfig(
            vocab_size=128, max_seq_len=32, hidden_size=64,
            num_layers=2, num_heads=4,
            dtype=jnp.bfloat16, sequence_parallel=True,
        )
        params = init_gpt_pretrain_params(cfg, jax.random.PRNGKey(0))
        params = jax.tree.map(lambda l: l.astype(jnp.bfloat16), params)
        opt = FusedAdam(lr=2e-3, impl="xla", master_dtype=jnp.bfloat16,
                        stochastic_rounding=True)
        build = make_gpt_pretrain_step(cfg, mesh, opt, num_microbatches=2)
        init_opt, step_fn, _ = build(params)
        opt_state = init_opt(params)
        assert jax.tree.leaves(opt_state)[0].dtype in (jnp.bfloat16,
                                                       jnp.int32,
                                                       jnp.float32)
        toks = jnp.asarray(rng.randint(0, 128, (8, 33)), jnp.int32)
        x, y = toks[:, :-1], toks[:, 1:]
        losses = []
        for _ in range(6):
            params, opt_state, loss = step_fn(params, opt_state, x, y)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
        assert all(l.dtype == jnp.bfloat16
                   for l in jax.tree.leaves(params))

    def test_matches_single_device(self, rng):
        """Parallel pretrain loss == dense sequential model loss."""
        mesh = ps.initialize_model_parallel(2, 2)
        cfg = GPTConfig(
            vocab_size=64, max_seq_len=16, hidden_size=32, num_layers=2,
            num_heads=4, dtype=jnp.float32,
        )
        params = init_gpt_pretrain_params(cfg, jax.random.PRNGKey(1))
        opt = FusedAdam(lr=1e-3, impl="xla")
        build = make_gpt_pretrain_step(cfg, mesh, opt, num_microbatches=1)
        init_opt, step_fn, _ = build(params)
        opt_state = init_opt(params)
        toks = jnp.asarray(rng.randint(0, 64, (2, 17)), jnp.int32)
        x, y = toks[:, :-1], toks[:, 1:]
        _, _, loss = step_fn(params, opt_state, x, y)

        # dense reference: same params applied sequentially
        from apex_tpu.models.gpt import GPTLayer
        from apex_tpu.normalization import FusedLayerNorm

        def dense_loss(params):
            table = params["embedding"]["embedding"]
            h = table[x] + params["position_embedding"][:16][None]
            h = h.transpose(1, 0, 2)
            layer = GPTLayer(cfg)
            for i in range(cfg.num_layers):
                lp = jax.tree.map(lambda l: l[i], params["layers"])
                h = layer.apply({"params": lp}, h)
            h = FusedLayerNorm(cfg.hidden_size).apply(
                {"params": params["final_norm"]}, h
            )
            logits = jnp.einsum("sbh,vh->sbv", h, table)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(
                logits, y.transpose(1, 0)[..., None], -1
            )[..., 0]
            return jnp.mean(lse - tgt)

        np.testing.assert_allclose(float(loss), float(dense_loss(params)),
                                   rtol=2e-4)

    @pytest.mark.slow
    def test_interleaved_matches_non_interleaved(self, rng):
        """vpp=2 pretrain step computes the same loss as the vpp=1 step
        on semantically-identical params: stacking the layers in the
        interleaved_layer_permutation order makes rank/chunk layout
        reproduce the same global layer sequence."""
        from apex_tpu.models.pretrain import interleaved_layer_permutation

        mesh = ps.initialize_model_parallel(1, 2)   # pp=2, dp=4
        pp, vpp = 2, 2
        cfg = GPTConfig(
            vocab_size=64, max_seq_len=16, hidden_size=32,
            num_layers=4, num_heads=4, dtype=jnp.float32,
        )
        params = init_gpt_pretrain_params(cfg, jax.random.PRNGKey(2))
        opt = FusedAdam(lr=1e-3, impl="xla")
        toks = jnp.asarray(rng.randint(0, 64, (8, 17)), jnp.int32)
        x, y = toks[:, :-1], toks[:, 1:]

        build1 = make_gpt_pretrain_step(cfg, mesh, opt, num_microbatches=2)
        init1, step1, _ = build1(params)
        _, _, loss1 = step1(params, init1(params), x, y)

        perm = interleaved_layer_permutation(cfg.num_layers, pp, vpp)
        params_v = dict(params)
        params_v["layers"] = jax.tree.map(
            lambda l: l[jnp.asarray(perm)], params["layers"])
        build2 = make_gpt_pretrain_step(
            cfg, mesh, opt, num_microbatches=2, num_model_chunks=vpp)
        init2, step2, _ = build2(params_v)
        params_out, _, loss2 = step2(params_v, init2(params_v), x, y)

        np.testing.assert_allclose(float(loss1), float(loss2), rtol=2e-4)
        # grads flowed everywhere: one step changed every layer leaf
        diff = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))),
            params_v["layers"], params_out["layers"])
        assert all(d > 0 for d in jax.tree.leaves(diff))

    def test_interleaved_permutation_roundtrip(self):
        from apex_tpu.models.pretrain import interleaved_layer_permutation

        perm = interleaved_layer_permutation(8, 2, 2)
        # rank 0 hosts virtual stages 0 and 2 -> layers [0,1] and [4,5]
        assert list(perm[:4]) == [0, 1, 4, 5]
        # rank 1 hosts virtual stages 1 and 3 -> layers [2,3] and [6,7]
        assert list(perm[4:]) == [2, 3, 6, 7]


class TestGraftEntry:
    def test_entry_compiles(self):
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape[0] == 256

    @pytest.mark.slow
    def test_dryrun_multichip(self):
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as g

        g.dryrun_multichip(8)

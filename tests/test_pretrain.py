"""Full-parallel pretrain composition tests (DP x TP x SP x PP) +
driver entry points."""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt import GPTConfig
from apex_tpu.models.pretrain import (
    init_gpt_pretrain_params,
    make_gpt_pretrain_step,
)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state as ps


@pytest.fixture(autouse=True)
def clean():
    ps.destroy_model_parallel()
    yield
    ps.destroy_model_parallel()


class TestPretrainStep:
    @pytest.mark.parametrize("tp,pp,sp", [(2, 2, True), (2, 2, False),
                                          (4, 2, True), (1, 4, False)])
    def test_step_runs_and_loss_decreases(self, rng, tp, pp, sp):
        mesh = ps.initialize_model_parallel(tp, pp)
        dp = 8 // (tp * pp)
        cfg = GPTConfig(
            vocab_size=128, max_seq_len=32, hidden_size=64,
            num_layers=max(pp, 2) if pp <= 2 else pp, num_heads=4,
            dtype=jnp.float32, sequence_parallel=sp,
        )
        params = init_gpt_pretrain_params(cfg, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=2e-3, impl="xla")
        build = make_gpt_pretrain_step(cfg, mesh, opt, num_microbatches=2)
        init_opt, step_fn, _ = build(params)
        opt_state = init_opt(params)
        toks = jnp.asarray(rng.randint(0, 128, (4 * dp, 33)), jnp.int32)
        x, y = toks[:, :-1], toks[:, 1:]
        losses = []
        for _ in range(5):
            params, opt_state, loss = step_fn(params, opt_state, x, y)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_matches_single_device(self, rng):
        """Parallel pretrain loss == dense sequential model loss."""
        mesh = ps.initialize_model_parallel(2, 2)
        cfg = GPTConfig(
            vocab_size=64, max_seq_len=16, hidden_size=32, num_layers=2,
            num_heads=4, dtype=jnp.float32,
        )
        params = init_gpt_pretrain_params(cfg, jax.random.PRNGKey(1))
        opt = FusedAdam(lr=1e-3, impl="xla")
        build = make_gpt_pretrain_step(cfg, mesh, opt, num_microbatches=1)
        init_opt, step_fn, _ = build(params)
        opt_state = init_opt(params)
        toks = jnp.asarray(rng.randint(0, 64, (2, 17)), jnp.int32)
        x, y = toks[:, :-1], toks[:, 1:]
        _, _, loss = step_fn(params, opt_state, x, y)

        # dense reference: same params applied sequentially
        from apex_tpu.models.gpt import GPTLayer
        from apex_tpu.normalization import FusedLayerNorm

        def dense_loss(params):
            table = params["embedding"]["embedding"]
            h = table[x] + params["position_embedding"][:16][None]
            h = h.transpose(1, 0, 2)
            layer = GPTLayer(cfg)
            for i in range(cfg.num_layers):
                lp = jax.tree.map(lambda l: l[i], params["layers"])
                h = layer.apply({"params": lp}, h)
            h = FusedLayerNorm(cfg.hidden_size).apply(
                {"params": params["final_norm"]}, h
            )
            logits = jnp.einsum("sbh,vh->sbv", h, table)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(
                logits, y.transpose(1, 0)[..., None], -1
            )[..., 0]
            return jnp.mean(lse - tgt)

        np.testing.assert_allclose(float(loss), float(dense_loss(params)),
                                   rtol=2e-4)


class TestGraftEntry:
    def test_entry_compiles(self):
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape[0] == 256

    def test_dryrun_multichip(self):
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as g

        g.dryrun_multichip(8)

"""ASP 2:4 sparsity tests (mirrors ref apex/contrib/test/ and the
sparse_masklib semantics: every group of 4 keeps its 2 largest)."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.contrib.sparsity import (
    ASP,
    create_mask,
    m4n2_1d,
    m4n2_2d_best,
    search_input_permutation,
)
from apex_tpu.optimizers import FusedAdam


class TestMaskCalculators:
    def test_m4n2_1d_keeps_top2(self, rng):
        m = jnp.asarray(rng.randn(8, 16), jnp.float32)
        mask = m4n2_1d(m)
        a = np.abs(np.asarray(m)).reshape(-1, 4)
        mk = np.asarray(mask).reshape(-1, 4)
        assert (mk.sum(-1) == 2).all()
        # kept entries are the two largest |w| of each group
        for g in range(a.shape[0]):
            kept = set(np.flatnonzero(mk[g]))
            top2 = set(np.argsort(-a[g])[:2])
            assert kept == top2, (g, a[g], mk[g])

    def test_m4n2_1d_remainder_dense(self, rng):
        m = jnp.asarray(rng.randn(2, 10), jnp.float32)
        mask = np.asarray(m4n2_1d(m))
        assert (mask[:, 8:] == 1).all()
        assert (mask[:, :8].reshape(-1, 4).sum(-1) == 2).all()

    def test_m4n2_2d_rows_and_cols(self, rng):
        m = jnp.asarray(rng.randn(8, 8), jnp.float32)
        mask = np.asarray(m4n2_2d_best(m))
        blocks = mask.reshape(2, 4, 2, 4).transpose(0, 2, 1, 3)
        for b in blocks.reshape(-1, 4, 4):
            assert (b.sum(0) == 2).all() and (b.sum(1) == 2).all()

    def test_create_mask_flax_layout(self, rng):
        # (in=8, out=6) kernel: groups along axis 0
        k = jnp.asarray(rng.randn(8, 6), jnp.float32)
        mask = np.asarray(create_mask(k))
        assert mask.shape == (8, 6)
        assert (mask.T.reshape(-1, 4).sum(-1) == 2).all()

    def test_create_mask_conv_kernel_hwio(self, rng):
        # flax HWIO layout (kh, kw, in, out): groups along the in axis
        k = jnp.asarray(rng.randn(3, 3, 8, 6), jnp.float32)
        mask = np.asarray(create_mask(k))
        assert mask.shape == k.shape
        fibers = mask.transpose(0, 1, 3, 2).reshape(-1, 4)
        assert (fibers.sum(-1) == 2).all()

    def test_asp_prunes_hwio_conv(self, rng):
        p = {"conv": {"kernel": jnp.asarray(rng.randn(3, 3, 8, 6),
                                            jnp.float32)}}
        masks = ASP.init_model_for_pruning(p)
        masks = ASP.compute_sparse_masks(p, masks)
        mk = np.asarray(masks["conv"]["kernel"])
        assert (mk.transpose(0, 1, 3, 2).reshape(-1, 4).sum(-1) == 2).all()


class TestPermutationSearch:
    def test_search_improves_or_keeps(self, rng):
        w = jnp.asarray(rng.randn(8, 16), jnp.float32)
        from apex_tpu.contrib.sparsity import permutation_retained_magnitude
        base = permutation_retained_magnitude(w, np.arange(16))
        perm = search_input_permutation(w, num_rounds=50)
        assert sorted(perm) == list(range(16))
        assert permutation_retained_magnitude(w, perm) >= base - 1e-6


class TestASPWorkflow:
    def _params(self, rng):
        return {
            "dense1": {"kernel": jnp.asarray(rng.randn(8, 16), jnp.float32),
                       "bias": jnp.asarray(rng.randn(16), jnp.float32)},
            "norm": {"scale": jnp.asarray(rng.randn(16), jnp.float32)},
        }

    def test_masks_and_apply(self, rng):
        p = self._params(rng)
        masks = ASP.init_model_for_pruning(p)
        masks = ASP.compute_sparse_masks(p, masks)
        pruned = ASP.apply_masks(p, masks)
        kmask = np.asarray(masks["dense1"]["kernel"])
        assert (kmask.T.reshape(-1, 4).sum(-1) == 2).all()
        np.testing.assert_array_equal(np.asarray(masks["dense1"]["bias"]), 1)
        nz = np.asarray(pruned["dense1"]["kernel"]) != 0
        np.testing.assert_array_equal(nz, kmask > 0)

    def test_optimizer_keeps_sparsity(self, rng):
        p = self._params(rng)
        pruned, masks, opt = ASP.prune_trained_model(
            p, FusedAdam(lr=1e-2, impl="xla"))
        state = opt.init(pruned)
        g = jax.tree.map(lambda l: jnp.ones_like(l), pruned)
        params2, state = opt.step(state, g)
        nz = np.asarray(params2["dense1"]["kernel"]) != 0
        np.testing.assert_array_equal(
            nz, np.asarray(masks["dense1"]["kernel"]) > 0)
        # non-eligible leaves updated densely
        assert (np.asarray(params2["dense1"]["bias"])
                != np.asarray(pruned["dense1"]["bias"])).all()

    def test_restore(self, rng):
        p = self._params(rng)
        masks = ASP.init_model_for_pruning(p)
        masks = ASP.compute_sparse_masks(p, masks)
        pruned = ASP.apply_masks(p, masks)
        restored = ASP.restore_pruned_weights(pruned, p, masks)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b)), restored, p)

    def test_disallowed_names(self, rng):
        p = self._params(rng)
        masks = ASP.init_model_for_pruning(
            p, disallowed_layer_names=["dense1"])
        masks = ASP.compute_sparse_masks(p, masks)
        np.testing.assert_array_equal(
            np.asarray(masks["dense1"]["kernel"]), 1)

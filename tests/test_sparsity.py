"""ASP 2:4 sparsity tests (mirrors ref apex/contrib/test/ and the
sparse_masklib semantics: every group of 4 keeps its 2 largest)."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.contrib.sparsity import (
    ASP,
    create_mask,
    m4n2_1d,
    m4n2_2d_best,
    search_input_permutation,
)
from apex_tpu.optimizers import FusedAdam


class TestMaskCalculators:
    def test_m4n2_1d_keeps_top2(self, rng):
        m = jnp.asarray(rng.randn(8, 16), jnp.float32)
        mask = m4n2_1d(m)
        a = np.abs(np.asarray(m)).reshape(-1, 4)
        mk = np.asarray(mask).reshape(-1, 4)
        assert (mk.sum(-1) == 2).all()
        # kept entries are the two largest |w| of each group
        for g in range(a.shape[0]):
            kept = set(np.flatnonzero(mk[g]))
            top2 = set(np.argsort(-a[g])[:2])
            assert kept == top2, (g, a[g], mk[g])

    def test_m4n2_1d_remainder_dense(self, rng):
        m = jnp.asarray(rng.randn(2, 10), jnp.float32)
        mask = np.asarray(m4n2_1d(m))
        assert (mask[:, 8:] == 1).all()
        assert (mask[:, :8].reshape(-1, 4).sum(-1) == 2).all()

    def test_m4n2_2d_rows_and_cols(self, rng):
        m = jnp.asarray(rng.randn(8, 8), jnp.float32)
        mask = np.asarray(m4n2_2d_best(m))
        blocks = mask.reshape(2, 4, 2, 4).transpose(0, 2, 1, 3)
        for b in blocks.reshape(-1, 4, 4):
            assert (b.sum(0) == 2).all() and (b.sum(1) == 2).all()

    def test_create_mask_flax_layout(self, rng):
        # (in=8, out=6) kernel: groups along axis 0
        k = jnp.asarray(rng.randn(8, 6), jnp.float32)
        mask = np.asarray(create_mask(k))
        assert mask.shape == (8, 6)
        assert (mask.T.reshape(-1, 4).sum(-1) == 2).all()

    def test_create_mask_conv_kernel_hwio(self, rng):
        # flax HWIO layout (kh, kw, in, out): groups along the in axis
        k = jnp.asarray(rng.randn(3, 3, 8, 6), jnp.float32)
        mask = np.asarray(create_mask(k))
        assert mask.shape == k.shape
        fibers = mask.transpose(0, 1, 3, 2).reshape(-1, 4)
        assert (fibers.sum(-1) == 2).all()

    def test_asp_prunes_hwio_conv(self, rng):
        p = {"conv": {"kernel": jnp.asarray(rng.randn(3, 3, 8, 6),
                                            jnp.float32)}}
        masks = ASP.init_model_for_pruning(p)
        masks = ASP.compute_sparse_masks(p, masks)
        mk = np.asarray(masks["conv"]["kernel"])
        assert (mk.transpose(0, 1, 3, 2).reshape(-1, 4).sum(-1) == 2).all()


class TestPermutationSearch:
    def test_search_improves_or_keeps(self, rng):
        w = jnp.asarray(rng.randn(8, 16), jnp.float32)
        from apex_tpu.contrib.sparsity import permutation_retained_magnitude
        base = permutation_retained_magnitude(w, np.arange(16))
        perm = search_input_permutation(w, num_rounds=50)
        assert sorted(perm) == list(range(16))
        assert permutation_retained_magnitude(w, perm) >= base - 1e-6

    def test_exhaustive_degrade_warns_with_fallback_name(self, rng):
        """Production-sized layers trip max_stripe_groups and degrade
        to the hill-climb; that quality cliff must be named, not
        silent, for method='exhaustive'/'auto' callers."""
        import warnings

        from apex_tpu.contrib.sparsity import exhaustive_search

        w = jnp.asarray(rng.randn(4, 1024), jnp.float32)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            perm = exhaustive_search(np.asarray(w), max_iters=1,
                                     escape_attempts=0)
        assert sorted(perm) == list(range(1024))
        msgs = [str(c.message) for c in caught
                if issubclass(c.category, RuntimeWarning)]
        assert any("hill-climb" in m and "max_stripe_groups" in m
                   for m in msgs), msgs
        # small shapes that the table covers stay silent
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            exhaustive_search(rng.randn(4, 16).astype(np.float32),
                              max_iters=1, escape_attempts=0)
        assert not [c for c in caught
                    if issubclass(c.category, RuntimeWarning)]

    def test_partition_tables_match_reference_counts(self):
        """Canonical-unique window permutations: 35 for 8 columns,
        5775 for 12 (ref exhaustive_search.py
        predict_unique_combinations: C! / ((M!)^G * G!))."""
        from apex_tpu.contrib.sparsity import _unique_partitions_np

        assert _unique_partitions_np(8).shape == (35, 2)
        assert _unique_partitions_np(12).shape == (5775, 3)

    def test_exhaustive_beats_identity_on_adversarial(self, rng):
        """Columns grouped so same-magnitude channels share stripes:
        2:4 on the identity layout throws away half the large-magnitude
        entries; the search must recover them by mixing stripes (the
        accuracy-retention mechanism of ref permutation_lib.py)."""
        from apex_tpu.contrib.sparsity import (
            _hill_climb_permutation,
            exhaustive_search,
            permutation_retained_magnitude,
        )

        mags = np.repeat([5.0, 5.0, 0.1, 0.1], 4).astype(np.float32)
        w = rng.randn(64, 16).astype(np.float32) * mags
        base = permutation_retained_magnitude(w, np.arange(16))
        perm = exhaustive_search(w, window_cols=8, seed=0)
        score = permutation_retained_magnitude(w, perm)
        assert sorted(perm) == list(range(16))
        # the improvement is structural, not epsilon: >10% retained
        assert score > base * 1.1, (base, score)
        # and at least as good as the old hill-climb at its budget
        hc = permutation_retained_magnitude(
            w, _hill_climb_permutation(w, 100, 0))
        assert score >= hc - 1e-4

    def test_window12_at_least_window8(self, rng):
        from apex_tpu.contrib.sparsity import (
            exhaustive_search,
            permutation_retained_magnitude,
        )

        mags = np.repeat([5.0, 5.0, 0.1, 0.1], 4).astype(np.float32)
        w = rng.randn(32, 16).astype(np.float32) * mags
        s8 = permutation_retained_magnitude(
            w, exhaustive_search(w, window_cols=8, seed=0))
        s12 = permutation_retained_magnitude(
            w, exhaustive_search(w, window_cols=12, seed=0))
        assert s12 >= s8 - 1e-3

    def test_escape_attempts_help_or_keep(self, rng):
        from apex_tpu.contrib.sparsity import (
            exhaustive_search,
            permutation_retained_magnitude,
        )

        w = rng.randn(32, 24).astype(np.float32) * np.repeat(
            rng.uniform(0.1, 5.0, 6), 4).astype(np.float32)
        s0 = permutation_retained_magnitude(
            w, exhaustive_search(w, window_cols=8, escape_attempts=0,
                                 seed=0))
        s10 = permutation_retained_magnitude(
            w, exhaustive_search(w, window_cols=8, escape_attempts=10,
                                 seed=0))
        assert s10 >= s0 - 1e-4

    def test_permuted_mask_preserves_toy_model_quality(self, rng):
        """End-to-end accuracy retention: prune a linear regressor's
        input channels 2:4 with and without the searched permutation;
        the permuted pruning must lose less test error (the claim the
        reference's whole permutation subsystem exists to make)."""
        from apex_tpu.contrib.sparsity import (
            exhaustive_search,
            mn_1d_best,
        )

        # teacher weights with adversarially-striped importance
        mags = np.repeat([4.0, 4.0, 0.05, 0.05], 4).astype(np.float32)
        W = (rng.randn(16, 8).astype(np.float32)
             * mags[:, None])                       # (in=16, out=8)
        X = rng.randn(512, 16).astype(np.float32)
        Y = X @ W

        def pruned_err(perm):
            Wp = W[perm]                            # permute input rows
            mask = np.asarray(mn_1d_best(jnp.asarray(Wp.T), 4, 2)).T
            Wmasked = Wp * mask
            # un-permute back to original channel order
            inv = np.argsort(perm)
            pred = X @ Wmasked[inv]
            return float(np.mean((pred - Y) ** 2))

        err_id = pruned_err(np.arange(16))
        perm = exhaustive_search(W.T, window_cols=8, seed=0)
        err_perm = pruned_err(np.asarray(perm))
        assert err_perm < err_id * 0.9, (err_id, err_perm)


class TestASPWorkflow:
    def _params(self, rng):
        return {
            "dense1": {"kernel": jnp.asarray(rng.randn(8, 16), jnp.float32),
                       "bias": jnp.asarray(rng.randn(16), jnp.float32)},
            "norm": {"scale": jnp.asarray(rng.randn(16), jnp.float32)},
        }

    def test_masks_and_apply(self, rng):
        p = self._params(rng)
        masks = ASP.init_model_for_pruning(p)
        masks = ASP.compute_sparse_masks(p, masks)
        pruned = ASP.apply_masks(p, masks)
        kmask = np.asarray(masks["dense1"]["kernel"])
        assert (kmask.T.reshape(-1, 4).sum(-1) == 2).all()
        np.testing.assert_array_equal(np.asarray(masks["dense1"]["bias"]), 1)
        nz = np.asarray(pruned["dense1"]["kernel"]) != 0
        np.testing.assert_array_equal(nz, kmask > 0)

    def test_optimizer_keeps_sparsity(self, rng):
        p = self._params(rng)
        pruned, masks, opt = ASP.prune_trained_model(
            p, FusedAdam(lr=1e-2, impl="xla"))
        state = opt.init(pruned)
        g = jax.tree.map(lambda l: jnp.ones_like(l), pruned)
        params2, state = opt.step(state, g)
        nz = np.asarray(params2["dense1"]["kernel"]) != 0
        np.testing.assert_array_equal(
            nz, np.asarray(masks["dense1"]["kernel"]) > 0)
        # non-eligible leaves updated densely
        assert (np.asarray(params2["dense1"]["bias"])
                != np.asarray(pruned["dense1"]["bias"])).all()

    def test_restore(self, rng):
        p = self._params(rng)
        masks = ASP.init_model_for_pruning(p)
        masks = ASP.compute_sparse_masks(p, masks)
        pruned = ASP.apply_masks(p, masks)
        restored = ASP.restore_pruned_weights(pruned, p, masks)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b)), restored, p)

    def test_disallowed_names(self, rng):
        p = self._params(rng)
        masks = ASP.init_model_for_pruning(
            p, disallowed_layer_names=["dense1"])
        masks = ASP.compute_sparse_masks(p, masks)
        np.testing.assert_array_equal(
            np.asarray(masks["dense1"]["kernel"]), 1)

"""Quorum (multi-host) checkpoints (apex_tpu/resilience/checkpoint.py
multi-host mode): per-host shards under the same atomic protocol, a
coordinator commit manifest recorded only after every host's shard
verifies, `latest_valid()` refusing any partial host-set, and restore
from any committed host's copy (shrunken-slice resume).

Acceptance bar (ISSUE 3): kill-one-host-before-commit resumes from the
last *quorum* checkpoint — never a partial host-set.
"""

import json
import os
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import records
from apex_tpu.optimizers import FusedAdam
from apex_tpu.optimizers.train_step import make_train_step
from apex_tpu.resilience import (
    CheckpointError,
    CheckpointManager,
    SimulatedCrash,
    faults,
)
from apex_tpu.resilience.checkpoint import COMMIT, host_dirname


def _params(seed=0, n=48, d=6):
    r = np.random.RandomState(seed)
    return {"w": jnp.asarray(r.randn(n, d), jnp.float32),
            "b": jnp.zeros((d,), jnp.float32)}


@pytest.fixture
def records_dir(tmp_path, monkeypatch):
    path = tmp_path / "records"
    monkeypatch.setattr(records, "RECORDS_DIR", str(path))
    return path


def _state(seed=0):
    opt = FusedAdam(lr=1e-2, impl="xla")
    return opt, opt.init(_params(seed))


def _managers(directory, n_hosts, **kw):
    kw.setdefault("quorum_timeout", 20.0)
    return [CheckpointManager(directory, process_id=h, n_processes=n_hosts,
                              **kw) for h in range(n_hosts)]


def _save_all(mgrs, step, state, skip=(), plans=None, errors=None):
    """Every host saves concurrently (the real fleet shape: each
    process writes its shard; the coordinator blocks until all land,
    then commits). ``skip`` hosts never save; ``plans`` maps host ->
    fault plan installed around ITS save only (the per-process env
    knob of a real fleet)."""
    errors = errors if errors is not None else {}

    def save(h):
        try:
            if plans and h in plans:
                with faults.inject(**plans[h]):
                    mgrs[h].save(step, state)
            else:
                mgrs[h].save(step, state)
        except BaseException as e:  # noqa: BLE001 — asserted by callers
            errors[h] = e

    ts = [threading.Thread(target=save, args=(h,), daemon=True)
          for h in range(len(mgrs)) if h not in skip]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    return errors


class TestQuorumRoundtrip:
    def test_shards_commit_and_restore_bitwise(self, tmp_path, records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 3)
        assert mgrs[0].multihost and mgrs[0].is_coordinator
        assert not mgrs[1].is_coordinator
        errors = _save_all(mgrs, 4, st)
        assert errors == {}
        path = mgrs[0].path_for(4)
        assert mgrs[0].all_steps() == [4]
        ok, reason = mgrs[0].validate(path)
        assert ok, reason
        commit = mgrs[0].read_commit(path)
        assert commit["n_hosts"] == 3
        assert sorted(commit["hosts"]) == [host_dirname(h) for h in range(3)]
        # every host restores its OWN shard, bitwise
        for h, mgr in enumerate(mgrs):
            r = mgr.restore(template=_state(seed=1)[1])
            assert r.step == 4
            np.testing.assert_array_equal(np.asarray(r.opt_state.master),
                                          np.asarray(st.master))
            manifest_host = mgr.read_manifest(
                os.path.join(path, host_dirname(h)))
            assert manifest_host["process_id"] == h
            assert manifest_host["n_processes"] == 3

    def test_shrunken_slice_restores_any_copy(self, tmp_path, records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        assert _save_all(mgrs, 2, st) == {}
        # a later SINGLE-process run (slice shrank) still resumes: the
        # state is data-parallel replicated, any committed shard works
        solo = CheckpointManager(tmp_path / "ckpt")
        path = solo.latest_valid()
        assert path == solo.path_for(2)
        r = solo.restore(path, template=_state(seed=1)[1])
        np.testing.assert_array_equal(np.asarray(r.opt_state.master),
                                      np.asarray(st.master))
        # a 4-host manager restoring a 2-host checkpoint: its own id
        # has no shard, so it falls back to a committed one
        big = CheckpointManager(tmp_path / "ckpt", process_id=3,
                                n_processes=4)
        r2 = big.restore(path, template=_state(seed=1)[1])
        np.testing.assert_array_equal(np.asarray(r2.opt_state.master),
                                      np.asarray(st.master))
        # ... and pinning a shard that is not in the commit raises
        with pytest.raises(CheckpointError, match="host_0007"):
            solo.restore(path, template=_state(seed=1)[1], host=7)

    def test_single_host_layout_is_unchanged(self, tmp_path):
        _, st = _state()
        mgr = CheckpointManager(tmp_path / "ckpt")
        mgr.save(3, st)
        path = mgr.path_for(3)
        names = sorted(os.listdir(path))
        assert names == ["manifest.json", "payload.bin"]   # no shards
        assert not mgr._is_multihost_layout(path)


class TestPartialHostSet:
    def test_missing_shard_times_out_and_commits_nothing(
            self, tmp_path, records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2, quorum_timeout=0.5)
        assert _save_all(mgrs, 2, st) == {}            # quorum at step 2
        # host 1 never saves step 4: the coordinator must time out,
        # refuse the commit, and name the missing shard
        errors = _save_all(mgrs, 4, st, skip={1})
        assert isinstance(errors[0], CheckpointError)
        assert "quorum timeout" in str(errors[0])
        assert "host_0001" in str(errors[0])
        assert not os.path.exists(os.path.join(mgrs[0].path_for(4), COMMIT))

    def test_kill_one_host_before_commit_resumes_from_last_quorum(
            self, tmp_path, records_dir):
        # the acceptance drill, in-process: host 1 dies inside its
        # step-4 save (shard never lands); resume must come from the
        # step-2 QUORUM checkpoint, never the partial step-4 set
        opt, st0 = _state()
        scaler_free_step = make_train_step(opt)
        mgrs = _managers(tmp_path / "ckpt", 2, quorum_timeout=0.5)
        assert _save_all(mgrs, 2, st0) == {}

        r = np.random.RandomState(7)
        g = jnp.asarray(r.randn(st0.space.total).astype(np.float32) * 0.01)
        ref_master = np.asarray(st0.master).copy()
        st4, _ = scaler_free_step(st0, g)      # donates st0's buffers
        # host 1 dies first (in a real fleet the fault plan is that
        # process's own APEX_TPU_FAULTS; sequencing keeps the
        # process-wide injector from leaking into host 0's save)
        with faults.inject(crash_before_commit_steps=frozenset({4})):
            with pytest.raises(SimulatedCrash):
                mgrs[1].save(4, st4)           # the dead host
        with pytest.raises(CheckpointError, match="quorum timeout"):
            mgrs[0].save(4, st4)               # coordinator times out
        ok, reason = mgrs[0].validate(mgrs[0].path_for(4))
        assert not ok and "commit" in reason
        # the dead host's shard never landed at all
        assert not os.path.exists(
            os.path.join(mgrs[0].path_for(4), host_dirname(1)))

        for mgr in mgrs:
            assert mgr.latest_valid() == mgr.path_for(2)
            restored = mgr.restore(template=_state(seed=1)[1])
            assert restored.step == 2
            np.testing.assert_array_equal(
                np.asarray(restored.opt_state.master), ref_master)
        rec = records.latest_record("resilience", require_backend=None)
        assert rec["payload"]["event"] == "corrupt_checkpoint"
        assert rec["payload"]["step"] == 4
        assert "commit" in rec["payload"]["reason"]

    def test_committed_shard_corruption_invalidates_whole_step(
            self, tmp_path, records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        assert _save_all(mgrs, 2, st) == {}
        assert _save_all(mgrs, 4, st) == {}
        # bit-rot inside ONE host's committed shard: the whole step is
        # out (a quorum restore must never mix a good shard with a
        # rotten host-set), and resume falls back to the previous one
        ppath = os.path.join(mgrs[0].path_for(4), host_dirname(1),
                             "payload.bin")
        with open(ppath, "r+b") as f:
            f.seek(4)
            b = f.read(1)
            f.seek(4)
            f.write(bytes([b[0] ^ 0xFF]))
        ok, reason = mgrs[0].validate(mgrs[0].path_for(4))
        assert not ok and "host_0001" in reason
        assert mgrs[0].latest_valid() == mgrs[0].path_for(2)

    def test_commit_sha_mismatch_rejected(self, tmp_path, records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        assert _save_all(mgrs, 2, st) == {}
        path = mgrs[0].path_for(2)
        cpath = os.path.join(path, COMMIT)
        with open(cpath) as f:
            commit = json.load(f)
        commit["hosts"][host_dirname(0)] = "0" * 64   # swapped shard
        with open(cpath, "w") as f:
            json.dump(commit, f)
        ok, reason = mgrs[0].validate(path)
        assert not ok and "sha256 differs" in reason


class TestPreElasticCompat:
    def test_pre_elastic_commit_restores_via_legacy_path(
            self, tmp_path, records_dir):
        # backward compat (ISSUE 7): a quorum bundle written BEFORE the
        # elastic layer — COMMIT.json with no layout manifest — still
        # restores on the same topology through the legacy full-copy
        # path, under both manager classes
        from apex_tpu.resilience import ElasticCheckpointManager

        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        assert _save_all(mgrs, 2, st) == {}
        commit = mgrs[0].read_commit(mgrs[0].path_for(2))
        assert "layout" not in commit          # the pre-elastic format
        for h in range(2):
            el = ElasticCheckpointManager(tmp_path / "ckpt",
                                          process_id=h, n_processes=2)
            assert el.latest_valid() == el.path_for(2)
            r = el.restore(template=_state(seed=1)[1])
            assert r.step == 2
            np.testing.assert_array_equal(np.asarray(r.opt_state.master),
                                          np.asarray(st.master))
            assert not hasattr(r, "fingerprint")   # legacy RestoredState


class TestCommitFaults:
    def test_transient_commit_write_fault_absorbed(self, tmp_path,
                                                   records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        errors = _save_all(
            mgrs, 2, st,
            plans={0: dict(io_errors={"quorum_commit": frozenset({0})})})
        assert errors == {}
        assert mgrs[0].latest_valid() == mgrs[0].path_for(2)

    def test_dead_disk_at_commit_surfaces_and_commits_nothing(
            self, tmp_path, records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        errors = _save_all(
            mgrs, 2, st,
            plans={0: dict(io_permanent_from={"quorum_commit": 0})})
        assert isinstance(errors.get(0), OSError)
        assert not os.path.exists(os.path.join(mgrs[0].path_for(2), COMMIT))
        assert mgrs[0].latest_valid(record_events=False) is None

    def test_stale_shard_tmp_dirs_swept_at_startup(self, tmp_path):
        step_dir = tmp_path / "ckpt" / "step_000000000002"
        os.makedirs(step_dir / "host_0001.tmp-9-9")
        CheckpointManager(tmp_path / "ckpt", process_id=0, n_processes=2)
        assert not [n for n in os.listdir(step_dir) if ".tmp-" in n]

    def test_bad_process_id_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="process_id"):
            CheckpointManager(tmp_path, process_id=2, n_processes=2)

"""RNN family + fp16_utils legacy API tests
(mirrors ref tests/L0/run_amp/test_rnn.py and run_fp16util/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.fp16_utils import (
    FP16_Optimizer,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
    tofp16,
)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.rnn import GRU, LSTM, RNN, ReLU, Tanh, mLSTM


class TestRNN:
    @pytest.mark.parametrize("ctor", [LSTM, GRU, ReLU, Tanh, mLSTM])
    def test_shapes_all_cells(self, rng, ctor):
        model = ctor(input_size=6, hidden_size=8, num_layers=2)
        x = jnp.asarray(rng.randn(5, 3, 6), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), x)
        out, finals = model.apply(params, x)
        assert out.shape == (5, 3, 8)
        assert len(finals) == 2

    def test_lstm_vs_manual_recurrence(self, rng):
        """Single-layer LSTM scan equals a hand-rolled per-step loop."""
        model = LSTM(input_size=4, hidden_size=4, num_layers=1, bias=True)
        x = jnp.asarray(rng.randn(6, 2, 4), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), x)
        out, _ = model.apply(params, x)

        from apex_tpu.rnn import lstm_cell
        p = {k.split("l0d0_")[1]: v
             for k, v in params["params"].items()}
        h = (jnp.zeros((2, 4)), jnp.zeros((2, 4)))
        for t in range(6):
            h, o = lstm_cell(p, x[t], h)
            np.testing.assert_allclose(np.asarray(out[t]), np.asarray(o),
                                       rtol=1e-5, atol=1e-6)

    def test_bidirectional_concat(self, rng):
        model = LSTM(input_size=4, hidden_size=3, num_layers=1,
                     bidirectional=True)
        x = jnp.asarray(rng.randn(5, 2, 4), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), x)
        out, finals = model.apply(params, x)
        assert out.shape == (5, 2, 6)
        # reverse direction's final state corresponds to t=0 output half
        np.testing.assert_allclose(
            np.asarray(out[0, :, 3:]), np.asarray(finals[0][1][0]),
            rtol=1e-6)

    def test_batch_first(self, rng):
        model = GRU(input_size=4, hidden_size=5, num_layers=1,
                    batch_first=True)
        x = jnp.asarray(rng.randn(2, 7, 4), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), x)
        out, _ = model.apply(params, x)
        assert out.shape == (2, 7, 5)

    def test_lstm_learns(self, rng):
        """Tiny sequence-sum regression converges (the reference's RNN
        tests are train-smoke tests under amp)."""
        model = LSTM(input_size=2, hidden_size=16, num_layers=1)
        x = jnp.asarray(rng.randn(8, 16, 2), jnp.float32)
        y = jnp.cumsum(x[..., 0], axis=0)[..., None]
        head = jnp.asarray(rng.randn(16, 1) * 0.1, jnp.float32)
        params = {"rnn": model.init(jax.random.PRNGKey(0), x),
                  "head": head}
        opt = FusedAdam(lr=1e-2, impl="xla")
        state = opt.init(params)

        @jax.jit
        def step(params, state):
            def loss_fn(p):
                out, _ = model.apply(p["rnn"], x)
                return jnp.mean((out @ p["head"] - y) ** 2)
            loss, g = jax.value_and_grad(loss_fn)(params)
            params, state = opt.step(state, g)
            return params, state, loss

        losses = []
        for _ in range(60):
            params, state, loss = step(params, state)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, losses[::20]

    def test_initial_states_tbptt(self, rng):
        """Carrying finals across segments == one long scan (truncated
        BPTT contract)."""
        model = LSTM(input_size=3, hidden_size=4, num_layers=2)
        x = jnp.asarray(rng.randn(10, 2, 3), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), x)
        full, _ = model.apply(params, x)
        o1, s1 = model.apply(params, x[:5])
        o2, _ = model.apply(params, x[5:], s1)
        np.testing.assert_allclose(
            np.asarray(jnp.concatenate([o1, o2])), np.asarray(full),
            rtol=1e-5, atol=1e-6)

    def test_mlstm_multiplicative_path(self, rng):
        """mLSTM differs from LSTM given identical shared weights."""
        x = jnp.asarray(rng.randn(4, 2, 8), jnp.float32)
        m1 = LSTM(input_size=8, hidden_size=8, num_layers=1)
        m2 = mLSTM(input_size=8, hidden_size=8, num_layers=1)
        p1 = m1.init(jax.random.PRNGKey(0), x)
        p2 = m2.init(jax.random.PRNGKey(0), x)
        o1, _ = m1.apply(p1, x)
        o2, _ = m2.apply(p2, x)
        assert o2.shape == o1.shape
        assert "l0d0_w_mih" in p2["params"]


class TestFP16Util:
    def _params(self, rng):
        return {"dense": {"kernel": jnp.asarray(rng.randn(4, 4), jnp.float32),
                          "bias": jnp.zeros((4,), jnp.float32)},
                "batch_norm": {"scale": jnp.ones((4,), jnp.float32)}}

    def test_network_to_half_keeps_norms(self, rng):
        """Only batch/group norms stay fp32 (ref BN_convert_float
        converts _BatchNorm modules only — dense biases and layer norms
        go fp16 like everything else)."""
        p = network_to_half(self._params(rng))
        assert p["dense"]["kernel"].dtype == jnp.float16
        assert p["dense"]["bias"].dtype == jnp.float16
        assert p["batch_norm"]["scale"].dtype == jnp.float32

    def test_tofp16_all(self, rng):
        p = tofp16(self._params(rng))
        assert all(l.dtype == jnp.float16 for l in jax.tree.leaves(p))

    def test_prep_and_copy_roundtrip(self, rng):
        model_p = tofp16(self._params(rng))
        model_p, master_p = prep_param_lists(model_p)
        assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(master_p))
        back = master_params_to_model_params(master_p, model_p)
        assert all(l.dtype == jnp.float16 for l in jax.tree.leaves(back))
        g32 = model_grads_to_master_grads(tofp16(self._params(rng)))
        assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(g32))


class TestFP16Optimizer:
    def test_static_scale_training(self, rng):
        x = jnp.asarray(rng.randn(32, 8), jnp.float16)
        w_t = jnp.asarray(rng.randn(8, 4), jnp.float32)
        y = (np.asarray(x, np.float32) @ np.asarray(w_t)).astype(np.float32)
        y = jnp.asarray(y)
        params = {"w": jnp.asarray(rng.randn(8, 4) * 0.1, jnp.float16)}
        opt = FP16_Optimizer(FusedAdam(lr=5e-2, impl="xla"),
                             static_loss_scale=128.0)
        state = opt.init(params)

        @jax.jit
        def step(params, state):
            def loss_fn(p):
                pred = x.astype(jnp.float32) @ p["w"].astype(jnp.float32)
                return jnp.mean((pred - y) ** 2)
            loss, g = jax.value_and_grad(
                lambda p: opt.scale_loss(loss_fn(p), state))(params)
            params, state = opt.step(state, g)
            return params, state, loss

        losses = []
        for _ in range(60):
            params, state, loss = step(params, state)
            losses.append(float(loss) / 128.0)
        assert params["w"].dtype == jnp.float16
        assert losses[-1] < losses[0] * 0.2, losses[::20]

    def test_dynamic_scale_recovers_from_inf(self, rng):
        params = {"w": jnp.ones((4,), jnp.float16)}
        opt = FP16_Optimizer(FusedAdam(lr=1e-2, impl="xla"),
                             dynamic_loss_scale=True)
        state = opt.init(params)
        scale0 = float(state.scaler_state.loss_scale)

        bad = {"w": jnp.asarray([jnp.inf, 1, 1, 1], jnp.float16)}
        params2, state = opt.step(state, bad)
        # skipped: params unchanged, scale halved
        np.testing.assert_allclose(
            np.asarray(params2["w"], np.float32),
            np.asarray(params["w"], np.float32))
        assert float(state.scaler_state.loss_scale) == scale0 / 2

        good = {"w": jnp.ones((4,), jnp.float16)}
        params3, state = opt.step(state, good)
        assert (np.asarray(params3["w"], np.float32)
                != np.asarray(params2["w"], np.float32)).any()

    def test_state_dict_roundtrip(self, rng):
        params = {"w": jnp.ones((4,), jnp.float16)}
        opt = FP16_Optimizer(FusedAdam(lr=1e-2, impl="xla"),
                             dynamic_loss_scale=True)
        state = opt.init(params)
        d = opt.state_dict(state)
        state2 = opt.load_state_dict(state, d)
        assert float(state2.scaler_state.loss_scale) == float(
            state.scaler_state.loss_scale)
        np.testing.assert_array_equal(
            np.asarray(state2.opt_state.master),
            np.asarray(state.opt_state.master))
        assert float(opt.loss_scale(state2)) == float(opt.loss_scale(state))


class TestOutputProjection:
    """ref RNNBackend.py:258-262,361-363 — recurrent projection: h is
    projected hidden->output after every step; the projected h is the
    recurrent input and the emitted output; LSTM cell state stays
    hidden-size."""

    def test_lstm_projection_shapes_and_recurrence(self, rng):
        from apex_tpu.rnn import LSTM

        s, b, d_in, hid, out = 5, 3, 8, 16, 6
        m = LSTM(d_in, hid, num_layers=2, output_size=out)
        x = jnp.asarray(rng.randn(s, b, d_in).astype(np.float32))
        params = m.init(jax.random.PRNGKey(0), x)
        y, finals = m.apply(params, x)
        assert y.shape == (s, b, out)
        h_f, c_f = finals[0][0]
        assert h_f.shape == (b, out)           # carried h is projected
        assert c_f.shape == (b, hid)           # cell state stays hidden
        # layer-1 recurrent weight consumes the projected width
        p0 = params["params"]
        assert p0["l0d0_w_hh"].shape[0] == out
        assert p0["l0d0_w_ho"].shape == (hid, out)
        # second layer's input is the first layer's projected output
        assert p0["l1d0_w_ih"].shape[0] == out

    def test_projection_matches_manual_scan(self, rng):
        from apex_tpu.rnn import RNN

        s, b, d_in, hid, out = 4, 2, 5, 7, 3
        m = RNN(cell_type="tanh", input_size=d_in, hidden_size=hid,
                output_size=out, num_layers=1, bias=False)
        x = jnp.asarray(rng.randn(s, b, d_in).astype(np.float32))
        params = m.init(jax.random.PRNGKey(1), x)
        y, _ = m.apply(params, x)
        p = params["params"]
        w_ih, w_hh, w_ho = (np.asarray(p["l0d0_w_ih"]),
                            np.asarray(p["l0d0_w_hh"]),
                            np.asarray(p["l0d0_w_ho"]))
        h = np.zeros((b, out), np.float32)
        want = []
        for t in range(s):
            h_raw = np.tanh(np.asarray(x[t]) @ w_ih + h @ w_hh)
            h = h_raw @ w_ho
            want.append(h)
        np.testing.assert_allclose(np.asarray(y), np.stack(want),
                                   rtol=1e-5, atol=1e-5)

    def test_no_projection_param_when_sizes_equal(self, rng):
        from apex_tpu.rnn import GRU

        m = GRU(4, 8, num_layers=1, output_size=8)
        x = jnp.asarray(rng.randn(3, 2, 4).astype(np.float32))
        params = m.init(jax.random.PRNGKey(0), x)
        assert "l0d0_w_ho" not in params["params"]

    def test_mlstm_projection(self, rng):
        """ref cells.py mLSTMRNNCell: multiplicative path is
        output_size-wide (w_mih (out,in), w_mhh (out,out))."""
        from apex_tpu.rnn import mLSTM

        s, b, d_in, hid, out = 4, 2, 5, 8, 3
        m = mLSTM(d_in, hid, num_layers=1, output_size=out)
        x = jnp.asarray(rng.randn(s, b, d_in).astype(np.float32))
        params = m.init(jax.random.PRNGKey(0), x)
        p = params["params"]
        assert p["l0d0_w_mih"].shape == (d_in, out)
        assert p["l0d0_w_mhh"].shape == (out, out)
        assert p["l0d0_w_hh"].shape[0] == out
        y, finals = m.apply(params, x)
        assert y.shape == (s, b, out)
        assert np.all(np.isfinite(np.asarray(y)))

    def test_gru_projection_rejected(self, rng):
        """The GRU recurrence mixes gate-width and carry-width tensors
        under projection (the reference's own path crashes there); we
        reject it with a clear error instead."""
        from apex_tpu.rnn import GRU

        m = GRU(4, 8, num_layers=1, output_size=6)
        x = jnp.asarray(rng.randn(3, 2, 4).astype(np.float32))
        with pytest.raises(NotImplementedError, match="GRU"):
            m.init(jax.random.PRNGKey(0), x)

    def test_output_size_zero_rejected(self, rng):
        from apex_tpu.rnn import LSTM

        m = LSTM(4, 8, num_layers=1, output_size=0)
        x = jnp.asarray(rng.randn(3, 2, 4).astype(np.float32))
        with pytest.raises(ValueError, match="positive"):
            m.init(jax.random.PRNGKey(0), x)

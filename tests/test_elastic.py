"""Elastic resharding (apex_tpu/resilience/elastic.py): quorum
checkpoints written as logically-indexed range shards, restored on a
DIFFERENT host count — the planner re-partitions the committed ranges
onto the live world, missing ranges travel over the Collective, and
the reassembled state is verified bitwise against the layout
manifest's per-leaf fingerprint.

Acceptance bar (ISSUE 7): kill an N-process run and resume on N−1 and
N+1 processes with the restored state bitwise-identical to an
uninterrupted run — the single-process ``LocalCollective`` simulation
of the two-process ``tools/elastic_drill.py``.
"""

import os
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import records
from apex_tpu.optimizers import FusedAdam
from apex_tpu.optimizers.train_step import make_train_step
from apex_tpu.resilience import (
    CheckpointError,
    CheckpointManager,
    ConsistencyGuard,
    DivergenceError,
    ElasticCheckpointManager,
    ElasticLayoutError,
    ElasticRestoreError,
    ElasticRestorePlanner,
    LocalCollective,
    NullCollective,
    faults,
    graceful_shutdown,
    partition_ranges,
)
from apex_tpu.resilience.elastic import space_signature
from apex_tpu.telemetry import flight
from apex_tpu.telemetry import metrics as telemetry_metrics


def _params(seed=0, n=48, d=6):
    r = np.random.RandomState(seed)
    return {"w": jnp.asarray(r.randn(n, d), jnp.float32),
            "b": jnp.zeros((d,), jnp.float32)}


def _state(seed=0):
    opt = FusedAdam(lr=1e-2, impl="xla")
    return opt, opt.init(_params(seed))


def _grad(space, i):
    r = np.random.RandomState(1000 + i)
    return jnp.asarray(r.randn(space.total).astype(np.float32) * 0.01)


@pytest.fixture
def records_dir(tmp_path, monkeypatch):
    path = tmp_path / "records"
    monkeypatch.setattr(records, "RECORDS_DIR", str(path))
    return path


def _managers(directory, n_hosts, cls=ElasticCheckpointManager, **kw):
    kw.setdefault("quorum_timeout", 20.0)
    return [cls(directory, process_id=h, n_processes=n_hosts, **kw)
            for h in range(n_hosts)]


def _save_all(mgrs, step, state, plans=None):
    """Every host saves concurrently (the real fleet shape)."""
    errors = {}

    def save(h):
        try:
            if plans and h in plans:
                with faults.inject(**plans[h]):
                    mgrs[h].save(step, state)
            else:
                mgrs[h].save(step, state)
        except BaseException as e:  # noqa: BLE001 — asserted by callers
            errors[h] = e

    ts = [threading.Thread(target=save, args=(h,), daemon=True)
          for h in range(len(mgrs))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    return errors


def _restore_world(directory, n_new, template_fn, **kw):
    """Every host of a NEW world restores concurrently over a
    LocalCollective; returns {host: ElasticRestoredState}."""
    group = LocalCollective(n_new)
    handles = group.handles()
    outs, errors = {}, {}

    def restore(h):
        try:
            mgr = ElasticCheckpointManager(directory, process_id=h,
                                           n_processes=n_new)
            outs[h] = mgr.restore(template=template_fn(),
                                  collective=handles[h], **kw)
        except BaseException as e:  # noqa: BLE001
            errors[h] = e

    ts = [threading.Thread(target=restore, args=(h,), daemon=True)
          for h in range(n_new)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    assert errors == {}, errors
    return outs


def _assert_bitwise(restored, state):
    np.testing.assert_array_equal(np.asarray(restored.opt_state.master),
                                  np.asarray(state.master))
    for k in state.slots:
        np.testing.assert_array_equal(
            np.asarray(restored.opt_state.slots[k]),
            np.asarray(state.slots[k]))
    assert int(restored.opt_state.count) == int(state.count)


class TestPartitionRanges:
    def test_tiles_exactly_and_aligned(self):
        for total, n, align in [(8192, 2, 2048), (10240, 3, 2048),
                                (4096, 5, 2048), (2048, 1, 2048)]:
            ranges = partition_ranges(total, n, align)
            assert len(ranges) == n
            cur = 0
            for lo, hi in ranges:
                assert lo == cur and hi >= lo
                assert lo % align == 0 and hi % align == 0
                cur = hi
            assert cur == total

    def test_more_hosts_than_units_yields_empty_tails(self):
        ranges = partition_ranges(4096, 5, 2048)
        assert ranges[0] == (0, 2048) and ranges[1] == (2048, 4096)
        assert all(lo == hi for lo, hi in ranges[2:])

    def test_unaligned_total_rejected(self):
        with pytest.raises(ValueError, match="multiple"):
            partition_ranges(100, 2, 2048)


class TestElasticSave:
    def test_commit_carries_layout_manifest(self, tmp_path, records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        assert _save_all(mgrs, 4, st) == {}
        commit = mgrs[0].read_commit(mgrs[0].path_for(4))
        lay = commit["layout"]
        assert lay["world"] == 2
        assert lay["total"] == st.space.total
        assert lay["tree_sig"] == space_signature(st.space)
        ranges = sorted(lay["ranges"].values())
        assert ranges[0][0] == 0 and ranges[-1][1] == st.space.total
        assert [b["name"] for b in lay["buffers"]] == \
            ["master"] + [f"slot:{k}" for k in sorted(st.slots)]
        fp = np.asarray(lay["fingerprint"], np.uint32)
        assert fp.shape == (1 + len(st.slots), st.space.num_leaves)

    def test_shards_hold_ranges_not_copies(self, tmp_path, records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        assert _save_all(mgrs, 2, st) == {}
        path = mgrs[0].path_for(2)
        full = CheckpointManager(str(tmp_path / "full"))
        full.save(2, st)
        full_bytes = os.path.getsize(
            os.path.join(full.path_for(2), "payload.bin"))
        for h in range(2):
            shard = os.path.getsize(
                os.path.join(path, f"host_{h:04d}", "payload.bin"))
            assert shard < full_bytes  # each host writes ~1/N, not 1/1

    def test_compress_master_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="compress_master"):
            ElasticCheckpointManager(tmp_path, compress_master=True)

    def test_diverged_replicas_refuse_commit(self, tmp_path, records_dir):
        # host 1 saves DIFFERENT bits: its save-time fingerprint
        # disagrees, and the coordinator must abort — diverged replicas
        # must never become a checkpoint
        opt, st = _state()
        _, st_other = _state(seed=9)
        mgrs = _managers(tmp_path / "ckpt", 2, quorum_timeout=5.0)
        errors = {}

        def save(h, s):
            try:
                mgrs[h].save(2, s)
            except BaseException as e:  # noqa: BLE001
                errors[h] = e

        ts = [threading.Thread(target=save, args=(0, st), daemon=True),
              threading.Thread(target=save, args=(1, st_other),
                               daemon=True)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert isinstance(errors.get(0), CheckpointError)
        assert "fingerprint disagrees" in str(errors[0])
        assert mgrs[0].latest_valid(record_events=False) is None


class TestElasticRestore:
    def test_same_world_roundtrip_bitwise(self, tmp_path, records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        assert _save_all(mgrs, 4, st) == {}
        outs = _restore_world(tmp_path / "ckpt", 2,
                              lambda: _state(seed=1)[1])
        for h in range(2):
            assert outs[h].step == 4
            _assert_bitwise(outs[h], st)

    def test_shrink_to_one_reads_all_from_disk(self, tmp_path,
                                               records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 3)
        assert _save_all(mgrs, 6, st) == {}
        solo = ElasticCheckpointManager(tmp_path / "ckpt")
        r = solo.restore(template=_state(seed=1)[1])
        _assert_bitwise(r, st)
        assert r.plan["saved_world"] == 3 and r.plan["new_world"] == 1
        # nothing to fetch: every range came straight off the platter
        assert all(s["source"] == "disk" for s in r.plan["ranges"]
                   if "source" in s)

    def test_grow_fetches_ranges_over_collective(self, tmp_path,
                                                 records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        assert _save_all(mgrs, 4, st) == {}
        reg = telemetry_metrics.registry()
        fetched0 = reg.counter("elastic_ranges_fetched").value()
        outs = _restore_world(tmp_path / "ckpt", 3,
                              lambda: _state(seed=1)[1])
        for h in range(3):
            _assert_bitwise(outs[h], st)
            np.testing.assert_array_equal(outs[h].fingerprint,
                                          outs[0].fingerprint)
        fetched = sum(1 for h in range(3)
                      for s in outs[h].plan["ranges"]
                      if str(s.get("source", "")).startswith("peer_"))
        assert fetched > 0
        assert reg.counter("elastic_ranges_fetched").value() \
            == fetched0 + fetched
        assert reg.counter("elastic_bytes_remapped").value() > 0

    def test_kill_and_resume_on_new_world_matches_golden(
            self, tmp_path, records_dir):
        # THE acceptance sim: train on 2, "die" at step 4, resume on 3
        # (and on 1) — the replayed trajectory is bitwise identical to
        # an uninterrupted run
        opt, st0 = _state()
        step = make_train_step(opt)
        mgrs = _managers(tmp_path / "ckpt", 2)

        state = st0
        for i in range(4):
            state, _ = step(state, _grad(state.space, i))
        assert _save_all(mgrs, 4, state) == {}
        golden = state
        for i in range(4, 8):
            golden, _ = step(golden, _grad(golden.space, i))

        for n_new in (1, 3):
            outs = _restore_world(tmp_path / "ckpt", n_new,
                                  lambda: _state(seed=1)[1])
            resumed = outs[0].opt_state
            assert outs[0].step == 4
            for i in range(4, 8):
                resumed, _ = step(resumed, _grad(resumed.space, i))
            np.testing.assert_array_equal(np.asarray(resumed.master),
                                          np.asarray(golden.master))

    def test_wrong_template_tree_rejected(self, tmp_path, records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        assert _save_all(mgrs, 2, st) == {}
        other_opt = FusedAdam(lr=1e-2, impl="xla")
        other = other_opt.init({"w": jnp.zeros((8, 4), jnp.float32)})
        solo = ElasticCheckpointManager(tmp_path / "ckpt")
        with pytest.raises(CheckpointError, match="different parameter"):
            solo.restore(template=other)


class TestElasticFaults:
    def test_world_mismatch_detected_with_flight_bundle(
            self, tmp_path, records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        errors = _save_all(
            mgrs, 4, st,
            plans={0: dict(world_mismatch_steps=frozenset({4}))})
        assert errors == {}
        solo = ElasticCheckpointManager(tmp_path / "ckpt")
        flight.enable()
        try:
            with pytest.raises(ElasticLayoutError,
                               match="world 3 but commits 2"):
                solo.restore(solo.path_for(4),
                             template=_state(seed=1)[1])
            rec = flight.get_recorder()
            assert rec.dumps == 1
            assert rec.last_trigger == "elastic_restore_error"
        finally:
            flight.disable()
        bundle = records.latest_record("flightrec", require_backend=None)
        assert bundle["payload"]["trigger"] == "elastic_restore_error"
        extra = bundle["payload"]["extra"]
        assert extra["layout"]["world"] == 3        # the manifest as found
        assert "ranges" in extra                    # per-range status

    def test_shard_truncate_refused_and_skipped(self, tmp_path,
                                                records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        assert _save_all(mgrs, 2, st) == {}
        errors = _save_all(
            mgrs, 4, st,
            plans={0: dict(shard_truncate_steps=frozenset({4}),
                           shard_truncate_host=1)})
        assert errors == {}                 # commit landed, THEN the rot
        solo = ElasticCheckpointManager(tmp_path / "ckpt")
        ok, reason = solo.validate(solo.path_for(4))
        assert not ok and "host_0001" in reason
        # latest_valid falls back to the previous elastic quorum step
        assert solo.latest_valid() == solo.path_for(2)
        with pytest.raises(ElasticRestoreError):
            solo.restore(solo.path_for(4), template=_state(seed=1)[1])

    def test_range_fetch_timeout_falls_back_to_disk(self, tmp_path,
                                                    records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        assert _save_all(mgrs, 4, st) == {}
        with faults.inject(range_fetch_timeout=frozenset({0})):
            outs = _restore_world(tmp_path / "ckpt", 2,
                                  lambda: _state(seed=1)[1])
        for h in range(2):
            _assert_bitwise(outs[h], st)
            fallbacks = [s for s in outs[h].plan["ranges"]
                         if s.get("source") == "disk_fallback"]
            assert len(fallbacks) == 1
            assert fallbacks[0]["status"] == "range_fetch_timeout"


class TestLegacyInterop:
    def test_legacy_manager_reports_elastic_candidate(self, tmp_path,
                                                      records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        assert _save_all(mgrs, 4, st) == {}
        legacy = CheckpointManager(tmp_path / "ckpt")
        # resumable-but-mismatched: named, not silently "not found"
        assert legacy.latest_valid() is None
        rec = records.latest_record("resilience", require_backend=None)
        assert rec["payload"]["event"] == "elastic_candidate"
        assert rec["payload"]["step"] == 4
        assert rec["payload"]["layout"]["world"] == 2
        with pytest.raises(CheckpointError, match="[Ee]lastic"):
            legacy.restore(legacy.path_for(4),
                           template=_state(seed=1)[1])

    def test_legacy_scan_still_finds_older_legacy_step(self, tmp_path,
                                                       records_dir):
        opt, st = _state()
        legacy_mgrs = _managers(tmp_path / "ckpt", 2,
                                cls=CheckpointManager)
        assert _save_all(legacy_mgrs, 2, st) == {}
        mgrs = _managers(tmp_path / "ckpt", 2)
        assert _save_all(mgrs, 4, st) == {}
        legacy = CheckpointManager(tmp_path / "ckpt")
        assert legacy.latest_valid() == legacy.path_for(2)
        # the elastic manager prefers the newer elastic bundle
        elastic = ElasticCheckpointManager(tmp_path / "ckpt")
        assert elastic.latest_valid() == elastic.path_for(4)


class TestGuardBaseline:
    def test_verify_restore_accepts_matching_baseline(self, tmp_path,
                                                      records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        assert _save_all(mgrs, 4, st) == {}
        solo = ElasticCheckpointManager(tmp_path / "ckpt")
        r = solo.restore(template=_state(seed=1)[1])
        step = make_train_step(opt)
        guard = ConsistencyGuard(step, collective=NullCollective(),
                                 fingerprint_every=2)
        sums = guard.verify_restore(r.opt_state, baseline=r.fingerprint)
        np.testing.assert_array_equal(sums, np.asarray(r.fingerprint))

    def test_verify_restore_rejects_wrong_baseline(self, tmp_path,
                                                   records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        assert _save_all(mgrs, 4, st) == {}
        solo = ElasticCheckpointManager(tmp_path / "ckpt")
        r = solo.restore(template=_state(seed=1)[1])
        step = make_train_step(opt)
        guard = ConsistencyGuard(step, collective=NullCollective(),
                                 fingerprint_every=2)
        bad = np.array(r.fingerprint, np.uint32)
        bad[0, 0] ^= 1
        with pytest.raises(DivergenceError, match="baseline"):
            guard.verify_restore(r.opt_state, baseline=bad)
        rec = records.latest_record("resilience", require_backend=None)
        assert rec["payload"]["event"] == "restore_baseline_mismatch"

    def test_verify_restore_crossreplica_divergence(self, tmp_path,
                                                    records_dir):
        # replica 1 restored DIFFERENT bits: the gather must refuse
        opt, st = _state()
        _, st_other = _state(seed=9)
        step = make_train_step(opt)
        group = LocalCollective(2)
        handles = group.handles()
        errors = {}

        def verify(h, s):
            guard = ConsistencyGuard(step, collective=handles[h],
                                     fingerprint_every=2)
            try:
                guard.verify_restore(s)
            except BaseException as e:  # noqa: BLE001
                errors[h] = e

        ts = [threading.Thread(target=verify, args=(0, st), daemon=True),
              threading.Thread(target=verify, args=(1, st_other),
                               daemon=True)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert len(errors) == 2
        assert all(isinstance(e, DivergenceError)
                   for e in errors.values())


class TestGracefulShutdownElastic:
    def test_graceful_shutdown_commits_elastic_bundle(self, tmp_path,
                                                      records_dir):
        opt, st = _state()
        mgrs = _managers(tmp_path / "ckpt", 2)
        group = LocalCollective(2)
        handles = group.handles()
        errors = {}

        def drain(h):
            try:
                graceful_shutdown(mgrs[h], 7, st,
                                  collective=handles[h])
            except BaseException as e:  # noqa: BLE001
                errors[h] = e

        ts = [threading.Thread(target=drain, args=(h,), daemon=True)
              for h in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert errors == {}
        commit = mgrs[0].read_commit(mgrs[0].path_for(7))
        assert commit["layout"]["world"] == 2
        # a preemption bundle resumes on a different world
        outs = _restore_world(tmp_path / "ckpt", 3,
                              lambda: _state(seed=1)[1])
        for h in range(3):
            _assert_bitwise(outs[h], st)


class TestPlanner:
    def test_reads_cover_assignments_minimally(self):
        layout = {"format": 1, "world": 2, "total": 8192, "align": 2048,
                  "ranges": {"host_0000": [0, 4096],
                             "host_0001": [4096, 8192]}}
        p = ElasticRestorePlanner(layout, 3)
        seen = []
        for h in range(3):
            lo, hi = p.assignments[h]
            reads = p.reads_for(h)
            assert sum(b - a for _, _, a, b in reads) == hi - lo
            seen.extend((a, b) for _, _, a, b in reads)
        # the union of all hosts' reads is the whole space, no overlap
        seen.sort()
        cur = 0
        for a, b in seen:
            assert a == cur
            cur = b
        assert cur == 8192

    def test_gap_in_ranges_rejected(self):
        layout = {"format": 1, "world": 2, "total": 8192, "align": 2048,
                  "ranges": {"host_0000": [0, 2048],
                             "host_0001": [4096, 8192]}}
        with pytest.raises(ElasticLayoutError, match="tile"):
            ElasticRestorePlanner(layout, 2)

    def test_describe_is_json_ready(self, tmp_path):
        import json

        layout = {"format": 1, "world": 1, "total": 2048, "align": 2048,
                  "ranges": {"host_0000": [0, 2048]}}
        p = ElasticRestorePlanner(layout, 2)
        json.dumps(p.describe(1))

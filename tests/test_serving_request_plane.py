"""Serving request plane (apex_tpu/serving/tracing.py +
apex_tpu/telemetry/slo.py + scheduler integration,
docs/observability.md "Request plane").

Anchors:

- per-request traces: trace id minted at ``submit()``, spans at every
  state transition (queued / admitted / prefill / ``prefill_chunk[i]``
  / a coalesced decode window / finished), keep-last-k ring, perfetto
  export with ONE TRACK PER REQUEST;
- trace continuity across drain -> resume: the trace id survives the
  snapshot bitwise, the resumed engine CONTINUES the same trace with a
  ``resumed_from`` annotation, and the ``slo_violation`` bundle embeds
  complete traces;
- the SLO monitor: exact sliding-window quantiles, multi-window
  burn-rate gauges, one latched ``slo_alert`` per violation episode,
  a clean run stays silent, and ``should_shed()`` gates admission
  (``serving_slo_shed``);
- the ``serving_prefill_chunk_tokens`` regression: token counts land
  in finite token-count buckets, never all in +Inf, and the registry
  refuses a silently conflicting bucket grid;
- ``introspect()`` + tools/serving_top.py + the telemetry_dump
  ``serving`` section.
"""

import json
import os
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from apex_tpu import records, serving, telemetry  # noqa: E402
from apex_tpu.models.gpt import GPTConfig, GPTModel  # noqa: E402
from apex_tpu.resilience.guard import PreemptionHandler  # noqa: E402
from apex_tpu.serving import resilience as sresil  # noqa: E402
from apex_tpu.serving.kv_cache import KVCache  # noqa: E402
from apex_tpu.serving.tracing import RequestTracer  # noqa: E402
from apex_tpu.telemetry import flight  # noqa: E402
from apex_tpu.telemetry.slo import (  # noqa: E402
    SLOMonitor,
    SLOTarget,
    SlidingWindowQuantile,
)

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))

VOCAB, SEQ, HID, HEADS, KV, LAYERS = 64, 64, 32, 4, 2, 2


def tiny_config():
    return GPTConfig(vocab_size=VOCAB, max_seq_len=SEQ,
                     hidden_size=HID, num_layers=LAYERS,
                     num_heads=HEADS, num_kv_heads=KV,
                     dtype=jnp.float32, param_dtype=jnp.float32)


def fresh_cache(num_blocks=32, block_size=4):
    return KVCache(LAYERS, KV, HID // HEADS, num_blocks=num_blocks,
                   block_size=block_size, dtype=jnp.float32)


@pytest.fixture(scope="module")
def model_and_params():
    model = GPTModel(tiny_config())
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, VOCAB, (1, 8)), jnp.int32)
    return model, model.init(jax.random.PRNGKey(0), toks)


@pytest.fixture(scope="module")
def step_fn(model_and_params):
    model, _ = model_and_params
    return serving.make_decode_step(model, fresh_cache())


@pytest.fixture()
def records_dir(tmp_path, monkeypatch):
    path = tmp_path / "records"
    monkeypatch.setattr(records, "RECORDS_DIR", str(path))
    return path


def make_engine(model, params, step_fn, cache, **kw):
    reg = kw.pop("registry", None) or telemetry.MetricsRegistry()
    sink = telemetry.InMemorySink()
    reg.add_sink(sink)
    kw.setdefault("max_batch", 4)
    eng = serving.ContinuousBatcher(model, params, cache,
                                    step_fn=step_fn, registry=reg,
                                    **kw)
    return eng, reg, sink


def mk_requests(n, rng, **kw):
    return [serving.Request(
        id=i, prompt=rng.randint(0, VOCAB, (int(rng.randint(3, 9)),)),
        max_new_tokens=int(rng.randint(3, 6)), **kw) for i in range(n)]


# ---------------------------------------------------------------------------
# RequestTracer
# ---------------------------------------------------------------------------


class TestTracer:
    def test_trace_minted_at_submit_and_spans_at_transitions(
            self, model_and_params, step_fn):
        model, params = model_and_params
        cache = fresh_cache()
        tracer = RequestTracer()
        eng, _, _ = make_engine(model, params, step_fn, cache,
                                tracer=tracer)
        req = serving.Request(id="r0", prompt=[1] * 5,
                              max_new_tokens=3)
        assert req.trace_id is None
        eng.submit(req)
        assert req.trace_id is not None          # minted at submit()
        assert tracer.summary()["live"] == 1
        state = cache.init_state()
        while not eng.idle():
            state, _ = eng.step(state)
        (res,) = eng.drain()
        assert res.finish_reason == "length"
        (trace,) = tracer.trace_dicts()
        assert trace["trace_id"] == req.trace_id
        assert trace["outcome"] == "length"
        names = [s["name"] for s in trace["spans"]]
        assert "queued" in names and "prefill" in names
        assert "decode" in names                 # the coalesced window
        decode = next(s for s in trace["spans"] if s["name"] == "decode")
        assert decode["args"]["tokens"] == 2     # 3 total, 1 at prefill
        marks = [m["name"] for m in trace["marks"]]
        assert marks[:2] == ["admitted", "first_token"]
        assert marks[-1] == "finished"

    def test_chunked_prefill_gets_per_chunk_spans(
            self, model_and_params, step_fn):
        model, params = model_and_params
        cache = fresh_cache()
        tracer = RequestTracer()
        eng, _, _ = make_engine(model, params, step_fn, cache,
                                tracer=tracer, prefill_chunk=4)
        eng.submit(serving.Request(id="long", prompt=[2] * 11,
                                   max_new_tokens=2))
        state = cache.init_state()
        while not eng.idle():
            state, _ = eng.step(state)
        (trace,) = tracer.trace_dicts()
        chunk_names = [s["name"] for s in trace["spans"]
                       if s["name"].startswith("prefill_chunk")]
        # 11 tokens / chunk 4 -> chunks of 4, 4, 3 with ordinals
        assert chunk_names == ["prefill_chunk[0]", "prefill_chunk[1]",
                               "prefill_chunk[2]"]
        toks = [s["args"]["tokens"] for s in trace["spans"]
                if s["name"].startswith("prefill_chunk")]
        assert sum(toks) == 11

    def test_perfetto_export_one_track_per_request(
            self, model_and_params, step_fn, tmp_path):
        model, params = model_and_params
        cache = fresh_cache()
        tracer = RequestTracer()
        eng, _, _ = make_engine(model, params, step_fn, cache,
                                tracer=tracer)
        reqs = mk_requests(5, np.random.RandomState(3))
        state, _ = serving.serve_loop(eng, cache.init_state(), reqs)
        path = tmp_path / "requests.json"
        trace = tracer.export_trace(str(path))
        on_disk = json.loads(path.read_text())
        assert on_disk == trace
        meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
        assert len(meta) == 5                    # one track per request
        tids = {e["tid"] for e in meta}
        assert len(tids) == 5
        # every complete event carries µs ts/dur and its trace id —
        # the StepTimeline.export_trace event format
        for e in trace["traceEvents"]:
            if e["ph"] == "X":
                assert {"name", "cat", "ts", "dur", "pid", "tid",
                        "args"} <= set(e)
                assert "trace_id" in e["args"]

    def test_completed_ring_is_bounded(self, model_and_params,
                                       step_fn):
        model, params = model_and_params
        cache = fresh_cache()
        tracer = RequestTracer(keep=3)
        eng, _, _ = make_engine(model, params, step_fn, cache,
                                tracer=tracer)
        reqs = mk_requests(8, np.random.RandomState(5))
        serving.serve_loop(eng, cache.init_state(), reqs)
        assert len(tracer.completed()) == 3
        assert tracer.summary()["finished"] == 8

    def test_untraced_engine_leaves_requests_untouched(
            self, model_and_params, step_fn):
        model, params = model_and_params
        cache = fresh_cache()
        eng, _, _ = make_engine(model, params, step_fn, cache)
        req = serving.Request(id=0, prompt=[1] * 4, max_new_tokens=2)
        eng.submit(req)
        state = cache.init_state()
        while not eng.idle():
            state, _ = eng.step(state)
        assert req.trace_id is None              # disabled is step

    def test_quarantine_marks_and_outcome(self, model_and_params,
                                          step_fn, monkeypatch):
        model, params = model_and_params
        cache = fresh_cache()
        tracer = RequestTracer()
        eng, _, _ = make_engine(model, params, step_fn, cache,
                                tracer=tracer)
        monkeypatch.setenv("APEX_TPU_FAULTS",
                           "decode_nonfinite=1;decode_nonfinite_lane=0")
        for i in range(2):
            eng.submit(serving.Request(id=i, prompt=[1 + i] * 4,
                                       max_new_tokens=4))
        state = cache.init_state()
        state, _ = eng.step(state)
        state, rep = eng.step(state)
        assert rep["quarantined"] == [0]
        traces = {t["request_id"]: t for t in tracer.trace_dicts()}
        bad = traces["0"]
        assert bad["outcome"] == "error"
        assert any(m["name"] == "quarantine" for m in bad["marks"])


# ---------------------------------------------------------------------------
# drain -> resume trace continuity
# ---------------------------------------------------------------------------


class TestTraceContinuity:
    def test_trace_id_survives_snapshot_and_resume_continues(
            self, model_and_params, step_fn, tmp_path):
        model, params = model_and_params
        handler = PreemptionHandler()        # not installed: flag only
        cache = fresh_cache()
        tracer = RequestTracer()
        eng, _, _ = make_engine(model, params, step_fn, cache,
                                max_batch=2, tracer=tracer,
                                preemption=handler,
                                snapshot_dir=str(tmp_path))
        state = cache.init_state()
        for r in mk_requests(5, np.random.RandomState(11)):
            eng.submit(r)
        state, _ = eng.step(state)
        state, _ = eng.step(state)
        handler.requested = True
        state, rep = eng.step(state)
        assert rep["snapshot"] is not None

        snap = sresil.load_snapshot(rep["snapshot"])
        # every snapshotted entry carries its trace id, bitwise
        by_id = {e["id"]: e for e in snap["requests"]}
        drained = {t["request_id"]: t for t in tracer.trace_dicts()
                   if t["outcome"] == "drained"}
        assert set(drained) == {str(i) for i in by_id}
        for rid, e in by_id.items():
            assert e["trace_id"] == drained[str(rid)]["trace_id"]

        resumed, _prior = sresil.resume_requests(snap)
        origin = f"serving_{snap['step']:012d}"
        assert all(r.resumed_from == origin for r in resumed)
        assert all(r.trace_id == by_id[r.id]["trace_id"]
                   for r in resumed)

        cache2 = fresh_cache()
        tracer2 = RequestTracer()
        eng2, _, _ = make_engine(model, params, step_fn, cache2,
                                 max_batch=2, tracer=tracer2)
        serving.serve_loop(eng2, cache2.init_state(), resumed)
        cont = {t["request_id"]: t for t in tracer2.trace_dicts()}
        for r in resumed:
            t = cont[str(r.id)]
            # SAME trace id on the resumed side, resumed_from set and
            # marked, and the continuation ends normally
            assert t["trace_id"] == by_id[r.id]["trace_id"]
            assert t["resumed_from"] == origin
            assert any(m["name"] == "resumed" and
                       m["args"]["resumed_from"] == origin
                       for m in t["marks"])
            assert t["outcome"] in ("length", "eos")
        # the perfetto track label carries the resumed_from annotation
        meta = [e for e in tracer2.export_trace()["traceEvents"]
                if e["ph"] == "M"]
        assert all(origin in e["args"]["name"] for e in meta)


# ---------------------------------------------------------------------------
# SLO monitor
# ---------------------------------------------------------------------------


class TestSlidingWindowQuantile:
    def test_exact_quantiles_and_pruning(self):
        est = SlidingWindowQuantile(10.0)
        for i, v in enumerate([1.0, 2.0, 3.0, 4.0]):
            est.observe(v, t=float(i))
        assert est.quantile(0.0, now=4.0) == 1.0
        assert est.quantile(1.0, now=4.0) == 4.0
        assert est.quantile(0.5, now=4.0) == pytest.approx(2.5)
        # samples age out of the window (cutoff now - 10s)
        assert est.quantile(0.0, now=11.5) == 3.0
        assert est.count(now=12.5) == 1
        assert est.quantile(0.5, now=100.0) is None

    def test_capacity_bounds_memory(self):
        est = SlidingWindowQuantile(1e9, capacity=4)
        for i in range(100):
            est.observe(float(i), t=float(i))
        assert est.count(now=100.0) == 4
        assert est.quantile(0.0, now=100.0) == 96.0


class TestSLOMonitor:
    def mk(self, reg, **kw):
        kw.setdefault("windows", ((10.0, 2.0, 2.0),))
        kw.setdefault("min_samples", 2)
        kw.setdefault("check_every", 1)
        return SLOMonitor([SLOTarget("ttft_p99", 0.1, budget=0.1)],
                          registry=reg, **kw)

    def test_clean_run_stays_silent(self, records_dir):
        reg = telemetry.MetricsRegistry()
        sink = telemetry.InMemorySink()
        reg.add_sink(sink)
        mon = self.mk(reg)
        for i in range(20):
            mon.observe("ttft_p99", 0.01, t=i * 0.1)
        out = mon.check(now=2.0)
        assert out["alerting"] == []
        assert not mon.should_shed()
        assert all(e["event"] != "slo_alert" for e in sink.events)
        assert reg.gauge("slo_burn_rate").value(
            slo="ttft_p99", window="10s") == 0.0

    def test_burn_rate_alert_latches_once_and_recovers(self):
        reg = telemetry.MetricsRegistry()
        sink = telemetry.InMemorySink()
        reg.add_sink(sink)
        mon = self.mk(reg)
        for i in range(10):
            mon.observe("ttft_p99", 5.0, t=float(i) * 0.2,
                        request_id=f"r{i}")
        out = mon.check(now=2.0)
        assert out["alerting"] == ["ttft_p99"]
        assert mon.should_shed()
        # burn = bad_frac (1.0) / budget (0.1) = 10x
        assert reg.gauge("slo_burn_rate").value(
            slo="ttft_p99", window="10s") == pytest.approx(10.0)
        mon.check(now=2.5)                   # still violating: latched
        alerts = [e for e in sink.events if e["event"] == "slo_alert"]
        assert len(alerts) == 1
        assert alerts[0]["requests"]         # offenders named
        # the short window empties -> recovery event, gauge drops
        out = mon.check(now=60.0)
        assert out["alerting"] == []
        assert not mon.should_shed()
        assert [e["event"] for e in sink.events].count(
            "slo_recovered") == 1
        assert reg.gauge("slo_alert_active").value(slo="ttft_p99") == 0

    def test_min_samples_guards_single_bad_request(self):
        reg = telemetry.MetricsRegistry()
        mon = self.mk(reg)
        mon.observe("ttft_p99", 99.0, t=1.9)
        out = mon.check(now=2.0)
        assert out["alerting"] == []         # one sample never alerts

    def test_summary_mirrored_into_info(self):
        reg = telemetry.MetricsRegistry()
        mon = self.mk(reg)
        mon.observe("ttft_p99", 0.01, t=0.0)
        mon.check(now=1.0)
        info = reg.snapshot()["info"]["slo_window"]
        assert "ttft_p99" in info["targets"]
        json.dumps(info)                     # JSON-able end to end

    def test_unconfigured_target_is_noop(self):
        mon = self.mk(telemetry.MetricsRegistry())
        mon.observe("nonexistent", 1.0, t=0.0)   # must not raise

    def test_should_shed_gates_admission(self, model_and_params,
                                          step_fn):
        model, params = model_and_params
        cache = fresh_cache()
        t = [0.0]
        reg = telemetry.MetricsRegistry()
        sink = telemetry.InMemorySink()
        reg.add_sink(sink)
        mon = SLOMonitor([SLOTarget("tpot_p99", 1e-6, budget=0.1)],
                         windows=((8.0, 4.0, 2.0),), min_samples=1,
                         check_every=1, registry=reg,
                         clock=lambda: t[0])
        eng, _, _ = make_engine(model, params, step_fn, cache,
                                registry=reg, slo=mon,
                                clock=lambda: t[0])
        eng._registry = reg
        state = cache.init_state()
        eng.submit(serving.Request(id=0, prompt=[1] * 4,
                                   max_new_tokens=3))
        while not eng.idle():
            t[0] += 0.5
            state, _ = eng.step(state)       # finishes -> violating tpot
        assert mon.should_shed()
        eng.submit(serving.Request(id=1, prompt=[2] * 4,
                                   max_new_tokens=2))
        t[0] += 0.5
        state, rep = eng.step(state)
        assert rep["admitted"] == []         # shed: stays queued
        assert rep["queued"] == 1
        assert reg.counter("serving_slo_shed").value() >= 1
        assert "serving_slo_shed" in [e["event"] for e in sink.events]
        # the violating samples age out; the end-of-step check clears
        # the latch, so the step AFTER the recovery check admits
        t[0] += 30.0
        state, _ = eng.step(state)
        assert not mon.should_shed()
        state, rep = eng.step(state)
        assert rep["admitted"] == [1]

    def test_violation_bundle_embeds_traces_and_introspect(
            self, model_and_params, step_fn, records_dir):
        model, params = model_and_params
        cache = fresh_cache()
        tracer = RequestTracer()
        reg = telemetry.MetricsRegistry()
        mon = SLOMonitor([SLOTarget("tpot_p99", 1e-9)],
                         windows=((5.0, 0.5, 1.0),), min_samples=1,
                         check_every=1, registry=reg)
        rec = flight.enable(keep=3)
        try:
            eng, _, _ = make_engine(model, params, step_fn, cache,
                                    registry=reg, tracer=tracer,
                                    slo=mon)
            reqs = mk_requests(3, np.random.RandomState(9))
            serving.serve_loop(eng, cache.init_state(), reqs)
            assert rec.dumps == 1
            assert rec.last_trigger == "slo_violation"
            with open(rec.last_dump) as f:
                bundle = json.load(f)["payload"]
            extra = bundle["extra"]
            assert extra["slo"] == "tpot_p99"
            assert extra["requests"]
            traces = {t["request_id"]: t for t in extra["traces"]}
            for rid in extra["requests"]:
                # COMPLETE traces: terminal outcome, decode span,
                # perfetto-exportable span payloads
                t = traces[str(rid)]
                assert t["outcome"] is not None
                assert any(s["name"] == "decode" for s in t["spans"])
            assert extra["introspect"]["slo"]["alerting"] == [
                "tpot_p99"]
        finally:
            flight.disable()


# ---------------------------------------------------------------------------
# serving_prefill_chunk_tokens regression (ISSUE 11 satellite)
# ---------------------------------------------------------------------------


class TestChunkTokensHistogram:
    def test_chunk_tokens_land_in_finite_buckets(
            self, model_and_params, step_fn):
        """Token COUNTS must never observe into the seconds-scale
        DEFAULT_BUCKETS grid (every ~40-token chunk would land in
        +Inf and the histogram reads as one useless spike)."""
        model, params = model_and_params
        cache = fresh_cache()
        eng, reg, _ = make_engine(model, params, step_fn, cache,
                                  prefill_chunk=4)
        eng.submit(serving.Request(id=0, prompt=[3] * 14,
                                   max_new_tokens=2))
        state = cache.init_state()
        while not eng.idle():
            state, _ = eng.step(state)
        h = reg.histogram("serving_prefill_chunk_tokens").series()[
            "serving_prefill_chunk_tokens"]
        assert h["count"] == 4               # chunks of 4,4,4,2
        finite = [le for le in h["buckets"] if le != "+Inf"]
        top = max(finite, key=float)
        # ALL mass sits below +Inf: the grid is token-count scale
        assert h["buckets"][top] == h["count"]
        assert float(top) >= 4096            # TOKEN_COUNT_BUCKETS

    def test_registry_refuses_conflicting_bucket_grid(self):
        reg = telemetry.MetricsRegistry()
        reg.histogram("toks", buckets=(8, 64, 512))
        # a reader with no opinion gets the existing instrument
        assert reg.histogram("toks").buckets == (8.0, 64.0, 512.0)
        with pytest.raises(ValueError, match="mis-bucket"):
            reg.histogram("toks", buckets=(0.1, 1.0))

    def test_token_count_buckets_exported(self):
        assert telemetry.TOKEN_COUNT_BUCKETS[0] == 1
        assert telemetry.TOKEN_COUNT_BUCKETS[-1] >= 4096


# ---------------------------------------------------------------------------
# introspection + serving_top + telemetry_dump serving section
# ---------------------------------------------------------------------------


class TestIntrospection:
    def test_introspect_reports_all_states(self, model_and_params,
                                           step_fn):
        model, params = model_and_params
        cache = fresh_cache()
        tracer = RequestTracer()
        eng, _, _ = make_engine(model, params, step_fn, cache,
                                max_batch=2, max_prefill_batch=1,
                                prefill_chunk=4, tracer=tracer)
        state = cache.init_state()
        eng.submit(serving.Request(id="short", prompt=[1] * 4,
                                   max_new_tokens=8,
                                   deadline_ms=60000.0))
        eng.submit(serving.Request(id="long", prompt=[2] * 12,
                                   max_new_tokens=4))
        eng.submit(serving.Request(id="waiting", prompt=[3] * 4,
                                   max_new_tokens=2))
        state, _ = eng.step(state)
        state, _ = eng.step(state)
        intro = eng.introspect()
        json.dumps(intro)                    # JSON-able end to end
        by_id = {r["id"]: r for r in intro["requests"]}
        assert by_id["short"]["state"] == "decoding"
        assert by_id["short"]["generated"] >= 1
        assert by_id["short"]["deadline_left_ms"] is not None
        assert by_id["long"]["state"] == "prefilling"
        assert 0 < by_id["long"]["prefilled"] < 12
        assert by_id["waiting"]["state"] == "queued"
        assert by_id["short"]["trace_id"] is not None
        assert intro["pool"]["blocks_in_use"] > 0
        assert intro["traces"]["live"] == 3

    def test_serving_top_renders_live_and_bundle(
            self, model_and_params, step_fn):
        import serving_top

        model, params = model_and_params
        cache = fresh_cache()
        tracer = RequestTracer()
        eng, _, _ = make_engine(model, params, step_fn, cache,
                                max_batch=2, prefill_chunk=4,
                                tracer=tracer)
        state = cache.init_state()
        eng.submit(serving.Request(id="alpha", prompt=[1] * 4,
                                   max_new_tokens=6))
        state, _ = eng.step(state)
        text = serving_top.render_live(eng)
        assert "alpha" in text and "decoding" in text
        assert "kv pool" in text
        bundle = {"trigger": "slo_violation",
                  "error": "RuntimeError: SLO ...", "pid": 1,
                  "extra": {"slo": "tpot_p99", "requests": ["alpha"],
                            "traces": tracer.trace_dicts(),
                            "introspect": eng.introspect()}}
        out = serving_top.render_bundle(bundle)
        assert "slo_violation" in out
        assert "alpha" in out

    def test_serving_top_cli_resolves_shapes(self, model_and_params,
                                             step_fn, tmp_path,
                                             capsys):
        import serving_top

        model, params = model_and_params
        cache = fresh_cache()
        eng, _, _ = make_engine(model, params, step_fn, cache)
        intro = tmp_path / "intro.json"
        intro.write_text(json.dumps(eng.introspect()))
        assert serving_top.main([str(intro)]) == 0
        assert "serving engine" in capsys.readouterr().out
        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        assert serving_top.main([str(bad)]) == 2

    def test_telemetry_dump_serving_section(self, model_and_params,
                                            step_fn):
        import telemetry_dump

        model, params = model_and_params
        cache = fresh_cache()
        reg = telemetry.MetricsRegistry()
        mon = SLOMonitor([SLOTarget("ttft_p99", 10.0)],
                         windows=((10.0, 1.0, 2.0),), check_every=1,
                         registry=reg)
        eng, _, _ = make_engine(model, params, step_fn, cache,
                                registry=reg, slo=mon)
        reqs = mk_requests(2, np.random.RandomState(1))
        serving.serve_loop(eng, cache.init_state(), reqs)
        snap = reg.snapshot()
        sec = telemetry_dump.serving_section(snap)
        assert any(k.startswith("serving_requests")
                   for k in sec["counters"])
        assert any(k.startswith("slo_burn_rate")
                   for k in sec["gauges"])
        assert sec["prefix_cache_hit_rate"] is not None
        assert sec["slo_window"]["targets"]["ttft_p99"]
        comments = telemetry_dump.plane_comments(snap)
        assert "# serving:" in comments
        assert "alerting=none" in comments
        # no serving series -> the section stays null-with-reason and
        # the comment line is omitted
        empty = telemetry.MetricsRegistry().snapshot()
        sec2 = telemetry_dump.serving_section(empty)
        assert sec2["slo_reason"]
        assert "# serving:" not in telemetry_dump.plane_comments(empty)

"""GSPMD mesh substrate tests (apex_tpu/mesh, docs/mesh.md).

The conftest forces 8 simulated CPU devices, so every test here runs
on a real (8-way) mesh. The heavier end-to-end guarantees — dp=8 loss
parity vs 1 device and model-sharded decode token identity — are ALSO
proven by tools/check_mesh.sh in fresh processes; the in-suite copies
here are the tier-1 regression net.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as gmesh
from apex_tpu.mesh import annotate
from apex_tpu.models.gpt import GPTConfig, GPTModel, gpt_loss_fn


@pytest.fixture(autouse=True)
def clean_mesh():
    gmesh.destroy_mesh()
    yield
    gmesh.destroy_mesh()


def tiny_cfg(**kw):
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("param_dtype", jnp.float32)
    return GPTConfig(**kw)


class TestMeshLifecycle:
    def test_default_is_degenerate(self):
        assert not gmesh.mesh_initialized()
        assert gmesh.mesh_size() == 1
        assert gmesh.axis_sizes() == {"batch": 1, "pipe": 1, "model": 1}
        with pytest.raises(RuntimeError):
            gmesh.current_mesh()

    def test_initialize_defaults_batch(self):
        mesh = gmesh.initialize_mesh(model=2)
        n = len(jax.devices())
        assert mesh.axis_names == ("batch", "pipe", "model")
        assert gmesh.axis_sizes() == {"batch": n // 2, "pipe": 1,
                                      "model": 2}
        assert gmesh.mesh_size() == n

    def test_one_device_mesh_is_legal(self):
        gmesh.initialize_mesh(batch=1, model=1, pipe=1,
                              devices=jax.devices()[:1])
        assert gmesh.mesh_initialized()
        assert gmesh.mesh_size() == 1

    def test_bad_factorization_raises(self):
        with pytest.raises(ValueError):
            gmesh.initialize_mesh(model=3)
        with pytest.raises(ValueError):
            gmesh.initialize_mesh(batch=2, model=2, pipe=3)

    def test_destroy(self):
        gmesh.initialize_mesh()
        gmesh.destroy_mesh()
        assert not gmesh.mesh_initialized()
        assert gmesh.mesh_size() == 1


class TestShardingPlan:
    def test_identity_on_one_device(self):
        """Every shard_* entry point returns THE SAME OBJECT on a
        1-device mesh — the byte-identity guarantee existing
        single-chip paths rely on."""
        gmesh.initialize_mesh(batch=1, devices=jax.devices()[:1])
        cfg = tiny_cfg()
        model = GPTModel(cfg)
        toks = jnp.zeros((2, 8), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), toks)
        plan = gmesh.plan_gpt(params)
        assert plan.is_identity()
        assert plan.shard_params(params) is params
        assert plan.shard_batch(toks) is toks
        state = {"anything": jnp.ones((3,))}
        assert plan.shard_state(state) is state

    def test_gpt_plan_shards_tensor_dims_on_model_axis(self):
        gmesh.initialize_mesh(model=2)
        cfg = tiny_cfg()
        model = GPTModel(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((2, 8), jnp.int32))
        plan = gmesh.plan_gpt(params)
        specs = plan.param_specs
        leaves = jax.tree.leaves(
            specs, is_leaf=lambda x: isinstance(x, P))
        axes = {a for s in leaves for a in s if a is not None}
        assert axes == {"model"}         # only the model axis appears
        assert any(any(a == "model" for a in s) for s in leaves)

    def test_shard_params_and_batch_commit(self):
        gmesh.initialize_mesh(model=2)
        cfg = tiny_cfg()
        model = GPTModel(cfg)
        toks = jnp.zeros((8, 8), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), toks)
        plan = gmesh.plan_gpt(params)
        sharded = plan.shard_params(params)
        chex_leaf = jax.tree.leaves(sharded)[0]
        assert len(chex_leaf.sharding.device_set) == 8
        batch = plan.shard_batch(toks)
        assert str(tuple(batch.sharding.spec)) == "('batch',)"
        d = plan.detail()
        assert d["n_devices"] == 8
        assert d["param_leaves_sharded"] > 0


class TestAnnotate:
    def test_constrain_identity_without_mesh(self):
        x = jnp.ones((4, 4))
        assert annotate.constrain(x, None, "model") is x
        assert not annotate.mesh_active()

    def test_constrain_identity_on_one_device_mesh(self):
        gmesh.initialize_mesh(batch=1, devices=jax.devices()[:1])
        x = jnp.ones((4, 4))
        assert annotate.constrain_hidden(x) is x

    def test_constrain_applies_on_real_mesh(self):
        gmesh.initialize_mesh(model=2)
        assert annotate.mesh_active()

        @jax.jit
        def f(x):
            return annotate.constrain(x, "batch", None) * 2.0

        y = f(jnp.ones((8, 4)))
        np.testing.assert_allclose(np.asarray(y), 2.0)

    def test_shard_kv_pool_identity_without_mesh(self):
        state = {"k": jnp.zeros((2, 3, 4, 2, 8))}
        assert annotate.shard_kv_pool(state) is state


class TestMeshTrainStep:
    def _data(self, cfg, batch=8, seq=16):
        rng = np.random.RandomState(0)
        toks = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)),
                           jnp.int32)
        labels = jnp.asarray(
            rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
        return toks, labels

    def _run(self, n_steps=3):
        from apex_tpu.optimizers import FusedAdam

        cfg = tiny_cfg()
        model = GPTModel(cfg)
        toks, labels = self._data(cfg)
        params = model.init(jax.random.PRNGKey(0), toks)
        plan = gmesh.plan_gpt(params) if gmesh.mesh_initialized() else \
            gmesh.plan_gpt(params, mesh=_single_mesh())
        step = gmesh.make_mesh_train_step(
            model, FusedAdam(lr=1e-3, impl="xla"), plan)
        state = step.init(params)
        losses = []
        for _ in range(n_steps):
            state, loss = step(state, toks, labels)
            losses.append(float(loss))
        return losses

    def test_dp8_matches_single_device(self):
        """The acceptance guarantee: the SAME model code, 1-device vs
        dp=8 GSPMD, loss-identical to fp32 tolerance."""
        ref = self._run()                  # no mesh -> identity plan
        gmesh.initialize_mesh()            # pure dp over all devices
        assert gmesh.axis_sizes()["batch"] == len(jax.devices())
        dp = self._run()
        np.testing.assert_allclose(dp, ref, rtol=2e-5, atol=2e-5)

    def test_tp2_matches_single_device(self):
        ref = self._run()
        gmesh.initialize_mesh(model=2)
        tp = self._run()
        np.testing.assert_allclose(tp, ref, rtol=2e-5, atol=2e-5)

    def test_observes_compile_and_publishes_shardings(self):
        from apex_tpu import telemetry
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.telemetry import compiled as tcompiled
        from apex_tpu.telemetry import metrics as tmetrics

        telemetry.reset()
        try:
            gmesh.initialize_mesh()
            cfg = tiny_cfg()
            model = GPTModel(cfg)
            toks, labels = self._data(cfg)
            params = model.init(jax.random.PRNGKey(0), toks)
            step = gmesh.make_mesh_train_step(
                model, FusedAdam(lr=1e-3, impl="xla"),
                gmesh.plan_gpt(params))
            tracker = tcompiled.enable()
            state = step.init(params)
            state, _ = step(state, toks, labels)   # compile
            state, _ = step(state, toks, labels)   # hot
            state, _ = step(state, toks, labels)   # hot
            s = tracker.summary()
            # one observed signature, zero hot-loop recompiles
            assert s["signatures"].get("mesh_train_step") == 1
            assert s["recompiles"] == 0
            g = tmetrics.registry().snapshot()["gauges"]
            assert g.get('sharding_devices{fn="mesh_train_step"}') == \
                len(jax.devices())
            detail = telemetry.snapshot_detail()
            assert "mesh_train_step" in (detail["sharding"] or {})
        finally:
            telemetry.reset()


def _single_mesh():
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1, 1),
                gmesh.MESH_AXES)


class TestServingSharded:
    def test_model_sharded_decode_token_identical(self):
        """A model-sharded checkpoint + kv_heads-sharded paged pool
        through the REAL serving DecodeStep produces the same greedy
        stream as the unsharded engine."""
        from apex_tpu.serving import KVCache, make_decode_step

        cfg = tiny_cfg(num_heads=4, num_kv_heads=2)
        model = GPTModel(cfg)
        prompt = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 8)),
            jnp.int32)
        params = model.init(jax.random.PRNGKey(0), prompt)

        def stream(params, cache_state_sharder):
            cache = KVCache.for_config(cfg, num_blocks=16, block_size=8)
            state = cache_state_sharder(cache.init_state())
            step = make_decode_step(model, cache)
            for i in range(2):
                cache.allocate(i, 8 + 4)
            tables = cache.table_array([0, 1], width=4)
            lengths = np.asarray([8, 8], np.int32)
            out = step.prefill(params, state, prompt, lengths, tables)
            state, tok = out.cache, out.next_token
            toks = [np.asarray(tok)]
            pos = lengths.copy()
            for _ in range(3):
                out = step.decode(params, state, np.asarray(tok), pos,
                                  tables)
                state, tok = out.cache, out.next_token
                pos = pos + 1
                toks.append(np.asarray(tok))
            return np.stack(toks)

        ref = stream(params, lambda s: s)
        gmesh.initialize_mesh(model=2)
        sharded = stream(annotate.shard_params_for_serving(params),
                         annotate.shard_kv_pool)
        np.testing.assert_array_equal(sharded, ref)

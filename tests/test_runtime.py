"""Native host runtime tests: C++ flatten/unflatten vs numpy, bf16
casts vs ml_dtypes, prefetch pipeline ordering."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.runtime import (
    HostFlatSpace,
    PrefetchLoader,
    cast_bf16_f32,
    cast_f32_bf16,
    native_available,
)


def test_native_library_builds():
    """g++ is in the image; the native path must actually be exercised
    by this test run, not silently fall back."""
    assert native_available()


class TestHostFlatSpace:
    def _arrays(self, rng):
        return [rng.randn(17, 5).astype(np.float32),
                rng.randn(3).astype(np.float16),
                (rng.randn(2, 2, 2) * 100).astype(np.int32),
                rng.randn(1000, 33).astype(np.float32)]

    def test_roundtrip(self, rng):
        arrays = self._arrays(rng)
        space = HostFlatSpace.for_arrays(arrays)
        buf = space.flatten(arrays)
        assert buf.dtype == np.uint8 and buf.size == space.total_bytes
        back = space.unflatten(buf)
        for a, b in zip(arrays, back):
            assert a.dtype == b.dtype and a.shape == b.shape
            np.testing.assert_array_equal(a, b)

    def test_alignment(self, rng):
        space = HostFlatSpace([(3,), (5,)], [np.float32, np.float32],
                              align=128)
        assert space.offsets == [0, 128]
        assert space.total_bytes == 256

    def test_matches_numpy_fallback(self, rng, monkeypatch):
        arrays = self._arrays(rng)
        space = HostFlatSpace.for_arrays(arrays)
        native = space.flatten(arrays)
        import apex_tpu.runtime as rt
        monkeypatch.setattr(rt, "_lib", None)
        monkeypatch.setattr(rt, "_lib_tried", True)
        fallback = space.flatten(arrays)
        np.testing.assert_array_equal(native, fallback)
        for a, b in zip(space.unflatten(native), arrays):
            np.testing.assert_array_equal(a, b)

    def test_large_parallel_path(self, rng):
        """> 1 MiB total triggers the thread-pool branch."""
        arrays = [rng.randn(1 << 18).astype(np.float32) for _ in range(4)]
        space = HostFlatSpace.for_arrays(arrays)
        back = space.unflatten(space.flatten(arrays))
        for a, b in zip(arrays, back):
            np.testing.assert_array_equal(a, b)


class TestCasts:
    def test_bf16_roundtrip_exact(self, rng):
        import ml_dtypes
        x = rng.randn(4096).astype(np.float32)
        bf = cast_f32_bf16(x)
        ref = x.astype(ml_dtypes.bfloat16)
        np.testing.assert_array_equal(bf.view(np.uint16),
                                      ref.view(np.uint16))
        back = cast_bf16_f32(bf)
        np.testing.assert_array_equal(back, ref.astype(np.float32))

    def test_bf16_nan_inf(self):
        import ml_dtypes
        x = np.array([np.nan, np.inf, -np.inf, 0.0, -0.0], np.float32)
        bf = cast_f32_bf16(x)
        ref = x.astype(ml_dtypes.bfloat16)
        assert np.isnan(bf.astype(np.float32)[0])
        np.testing.assert_array_equal(bf.view(np.uint16)[1:],
                                      ref.view(np.uint16)[1:])

    def test_large_parallel_cast(self, rng):
        import ml_dtypes
        x = rng.randn(1 << 19).astype(np.float32)
        np.testing.assert_array_equal(
            cast_f32_bf16(x).view(np.uint16),
            x.astype(ml_dtypes.bfloat16).view(np.uint16))


class TestPrefetchLoader:
    def test_order_and_content(self, rng):
        batches = [{"x": np.full((4,), i, np.float32)} for i in range(10)]
        out = list(PrefetchLoader(iter(batches), depth=3))
        assert len(out) == 10
        for i, b in enumerate(out):
            np.testing.assert_array_equal(np.asarray(b["x"]), batches[i]["x"])

    def test_transform_runs_on_worker(self, rng):
        batches = [np.ones((2,), np.float32) * i for i in range(5)]
        out = list(PrefetchLoader(iter(batches), depth=2,
                                  transform=lambda b: b * 2))
        for i, b in enumerate(out):
            np.testing.assert_array_equal(np.asarray(b), batches[i] * 2)

    def test_worker_exception_propagates(self):
        def gen():
            yield np.zeros((1,), np.float32)
            raise ValueError("boom")

        it = iter(PrefetchLoader(gen(), depth=2))
        next(it)
        with pytest.raises(ValueError, match="boom"):
            list(it)

    def test_abandoned_consumer_releases_worker(self):
        def gen():
            while True:
                yield np.zeros((1,), np.float32)

        import threading
        before = threading.active_count()
        it = iter(PrefetchLoader(gen(), depth=2))
        next(it)
        it.close()  # abandon mid-stream -> finally stops the worker
        import time
        time.sleep(0.5)
        assert threading.active_count() <= before + 1

    def test_single_pass_guard(self):
        loader = PrefetchLoader(iter([np.zeros((1,), np.float32)]))
        list(loader)
        with pytest.raises(RuntimeError, match="single-pass"):
            iter(loader)

    def test_flatten_validates_layout(self, rng):
        space = HostFlatSpace([(4,)], [np.float32])
        with pytest.raises(ValueError):
            space.flatten([rng.randn(5).astype(np.float32)])
        with pytest.raises(ValueError):
            space.unflatten(np.zeros(7, np.uint8))

    def test_scalar_leaf_fallback(self, monkeypatch):
        import apex_tpu.runtime as rt
        monkeypatch.setattr(rt, "_lib", None)
        monkeypatch.setattr(rt, "_lib_tried", True)
        space = HostFlatSpace([()], [np.float32])
        buf = space.flatten([np.float32(3.5)])
        assert float(space.unflatten(buf)[0]) == 3.5

    def test_overlap(self):
        """The loader stages ahead: after consuming item 0, at least
        one further batch is already produced without being requested."""
        import time
        produced = []

        def gen():
            for i in range(4):
                produced.append(i)
                yield np.zeros((1,), np.float32)

        it = iter(PrefetchLoader(gen(), depth=2))
        next(it)
        time.sleep(0.5)
        assert len(produced) >= 2
        list(it)


class TestProfiler:
    """SURVEY §5 tracing hooks (ref nvtx ranges / --prof windows)."""

    def test_named_range_and_annotate(self):
        from apex_tpu import profiler

        @profiler.annotate("my_op")
        def f(x):
            with profiler.range("inner"):
                return x * 2

        out = jax.jit(f)(jnp.ones((4,)))
        np.testing.assert_array_equal(np.asarray(out), 2 * np.ones(4))

    def test_trace_capture(self, tmp_path):
        from apex_tpu import profiler

        with profiler.trace(str(tmp_path), enabled=True):
            jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
        # a TensorBoard-loadable trace directory was produced
        assert any(tmp_path.rglob("*.pb")) or any(tmp_path.rglob("*.json.gz"))

    def test_trace_disabled_noop(self, tmp_path):
        from apex_tpu import profiler

        with profiler.trace(str(tmp_path / "off"), enabled=False):
            pass
        assert not (tmp_path / "off").exists()

    def test_ddp_prof_flag(self, rng):
        from apex_tpu.parallel import DistributedDataParallel
        from apex_tpu.transformer import parallel_state as ps

        ps.destroy_model_parallel()
        mesh = ps.initialize_model_parallel()
        try:
            import functools

            from jax import shard_map
            from jax.sharding import PartitionSpec as P

            ddp = DistributedDataParallel(prof=True)
            x = jnp.asarray(rng.randn(8, 4).astype(np.float32))

            run = functools.partial(
                shard_map, mesh=mesh,
                in_specs=(P(ps.DATA_AXIS, None),), out_specs=P(),
                check_vma=False)
            out = jax.jit(run(lambda g: ddp.allreduce_grads(g)))(x)
            np.testing.assert_allclose(
                np.asarray(out), np.mean(np.asarray(x).reshape(8, -1, 4), 0),
                rtol=1e-6)
        finally:
            ps.destroy_model_parallel()


class TestBackendProbe:
    """Runtime Mosaic probe (the reference's multi_tensor_applier.available
    analog): a working backend reports available; the default degrades to
    xla rather than erroring when kernels can't compile."""

    def test_probe_runs_and_caches(self):
        from apex_tpu import _backend

        _backend.pallas_available.cache_clear()
        try:
            # CPU: interpret=False pallas lowers via the CPU backend in
            # current jax — either outcome is valid, but it must not raise
            # and must be memoized
            r1 = _backend.pallas_available()
            r2 = _backend.pallas_available()
            assert isinstance(r1, bool) and r1 == r2
            assert _backend.pallas_available.cache_info().hits == 1
        finally:
            _backend.pallas_available.cache_clear()

    def test_default_impl_env_override_skips_probe(self, monkeypatch):
        from apex_tpu import _backend

        def boom():
            raise AssertionError("probe must not run under env override")

        monkeypatch.setenv("APEX_TPU_IMPL", "xla")
        monkeypatch.setattr(_backend, "pallas_available", boom)
        _backend.default_impl.cache_clear()
        try:
            assert _backend.default_impl() == "xla"
        finally:
            _backend.default_impl.cache_clear()

"""backend_guard: the defensive bring-up layer every driver entry point
and bench run depends on (probe-with-timeout, retry budget, CPU
fallback, single-slot lock, MFU peak table)."""

import multiprocessing
import os
import time

import pytest

import apex_tpu.backend_guard as bg


class TestChipPeaks:
    @pytest.mark.parametrize("kind,peak", [
        ("TPU v5p", 459.0),
        ("TPU v5 lite", 197.0),
        ("TPU v5e", 197.0),
        ("TPU v4", 275.0),
        ("TPU v6 lite", 918.0),
        ("TPU v3", 123.0),
    ])
    def test_known_chips(self, kind, peak):
        assert bg.chip_peak_tflops(kind) == peak

    def test_unknown_is_none_not_a_guess(self):
        # mfu must be null for unknown chips, never a made-up denominator
        assert bg.chip_peak_tflops("cpu") is None
        assert bg.chip_peak_tflops("TPU v99") is None


class TestSlotLock:
    def test_acquire_and_reenter(self, tmp_path, monkeypatch):
        monkeypatch.setenv("APEX_TPU_SLOT_LOCK", str(tmp_path / "l"))
        with bg.tpu_slot_lock(timeout=5) as got:
            assert got
            # reentrant within the process: no deadlock, reports held
            with bg.tpu_slot_lock(timeout=5) as got2:
                assert got2

    def test_contention_times_out_not_hangs(self, tmp_path, monkeypatch):
        path = str(tmp_path / "l")
        monkeypatch.setenv("APEX_TPU_SLOT_LOCK", path)

        def hold(path, ev):
            import fcntl
            fd = os.open(path, os.O_CREAT | os.O_RDWR)
            fcntl.flock(fd, fcntl.LOCK_EX)
            ev.set()
            time.sleep(30)

        ev = multiprocessing.Event()
        proc = multiprocessing.Process(target=hold, args=(path, ev),
                                       daemon=True)
        proc.start()
        assert ev.wait(10)
        t0 = time.monotonic()
        try:
            with bg.tpu_slot_lock(timeout=1) as got:
                assert not got          # fails OPEN (advisory), not hang
            assert time.monotonic() - t0 < 15
        finally:
            proc.terminate()
            proc.join()
        # lock released by the dead process: next acquisition succeeds
        with bg.tpu_slot_lock(timeout=10) as got:
            assert got

    def test_unopenable_path_fails_open(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_SLOT_LOCK",
                           "/nonexistent-dir-xyz/lock")
        with bg.tpu_slot_lock(timeout=1) as got:
            assert not got              # warns + proceeds, never raises


class TestEnsureBackend:
    def test_initialized_backend_short_circuits(self):
        # the test process already runs the simulated CPU mesh
        report = bg.ensure_backend(min_devices=1)
        assert not report.fallback
        assert report.n_devices >= 1
        assert "backend" in report.as_detail()

    @staticmethod
    def _isolate_probe_cache(monkeypatch, tmp_path):
        monkeypatch.setenv("APEX_TPU_BACKEND_PROBE_CACHE",
                           str(tmp_path / "probe_cache.json"))
        monkeypatch.setattr(bg, "_PROBE_VERDICT", None)

    def test_retry_budget_retries_probe(self, monkeypatch, tmp_path):
        import jax._src.xla_bridge as xb

        self._isolate_probe_cache(monkeypatch, tmp_path)
        calls = []

        def fake_probe(timeout=None):
            calls.append(1)
            return {"ok": False, "error": "tunnel down"}

        monkeypatch.setattr(bg, "probe_default_backend", fake_probe)
        monkeypatch.setattr(bg, "_RETRY_SLEEP", 0.05)
        monkeypatch.setattr(xb, "backends_are_initialized", lambda: False)
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        report = bg.ensure_backend(min_devices=1, retry_budget=0.2)
        assert report.fallback
        assert len(calls) >= 2          # retried, not one-shot
        assert "after" in report.note   # attempt count recorded

    def test_zero_budget_single_probe(self, monkeypatch, tmp_path):
        import jax._src.xla_bridge as xb

        self._isolate_probe_cache(monkeypatch, tmp_path)
        calls = []
        monkeypatch.setattr(
            bg, "probe_default_backend",
            lambda timeout=None: (calls.append(1)
                                  or {"ok": False, "error": "down"}))
        monkeypatch.setattr(xb, "backends_are_initialized", lambda: False)
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        report = bg.ensure_backend(min_devices=1, retry_budget=0.0)
        assert report.fallback and len(calls) == 1
        assert report.as_detail()["backend_fallback"] == "down"

    def test_failed_verdict_cached_across_invocations(self, monkeypatch,
                                                      tmp_path):
        # invocation 1 burns the probe honestly; invocation 2 (fresh
        # "process": in-process verdict cleared, disk cache kept) must
        # reuse the failure verdict instead of re-probing 4x120s
        import jax._src.xla_bridge as xb

        self._isolate_probe_cache(monkeypatch, tmp_path)
        calls = []
        monkeypatch.setattr(
            bg, "probe_default_backend",
            lambda timeout=None: (calls.append(1)
                                  or {"ok": False, "error": "probe timed "
                                      "out after 120s"}))
        monkeypatch.setattr(xb, "backends_are_initialized", lambda: False)
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        r1 = bg.ensure_backend(min_devices=1, retry_budget=0.0)
        assert r1.fallback and len(calls) == 1

        monkeypatch.setattr(bg, "_PROBE_VERDICT", None)  # "new process"
        # force_cpu_backend pinned JAX_PLATFORMS; a fresh invocation
        # starts without the pin
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        r2 = bg.ensure_backend(min_devices=1, retry_budget=0.0)
        assert r2.fallback and len(calls) == 1           # no new probe
        assert r2.probe.get("cached") is True
        assert "cached probe verdict" in r2.note
        # the cached verdict flows into the bench-record detail
        d = r2.as_detail()
        assert d["backend_probe"]["cached"] is True
        assert "age_s" in d["backend_probe"]

    def test_timeout_verdict_suppresses_in_budget_reprobes(
            self, monkeypatch, tmp_path):
        # BENCH_r05's failure mode: ONE invocation with a retry budget
        # re-burned the 120 s probe timeout 4x on a dead tunnel. A
        # timeout verdict is now honored for the cache TTL inside the
        # loop too: with the default TTL (300 s) dwarfing this budget,
        # exactly one probe runs and the note says why
        import jax._src.xla_bridge as xb

        self._isolate_probe_cache(monkeypatch, tmp_path)
        calls = []
        monkeypatch.setattr(
            bg, "probe_default_backend",
            lambda timeout=None: (calls.append(1)
                                  or {"ok": False, "error": "probe timed "
                                      "out after 120s"}))
        monkeypatch.setattr(bg, "_RETRY_SLEEP", 0.05)
        monkeypatch.setattr(xb, "backends_are_initialized", lambda: False)
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        report = bg.ensure_backend(min_devices=1, retry_budget=2.0)
        assert report.fallback
        assert len(calls) == 1          # no in-budget re-burn
        assert "re-probes suppressed" in report.note

    def test_timeout_verdict_reprobes_after_ttl_expiry(
            self, monkeypatch, tmp_path):
        # a budget LONGER than the TTL still re-probes — once the
        # cached verdict expires, the tunnel may have recovered
        import jax._src.xla_bridge as xb

        self._isolate_probe_cache(monkeypatch, tmp_path)
        monkeypatch.setenv("APEX_TPU_BACKEND_PROBE_CACHE_TTL", "0.05")
        calls = []
        monkeypatch.setattr(
            bg, "probe_default_backend",
            lambda timeout=None: (calls.append(1)
                                  or {"ok": False, "error": "probe timed "
                                      "out after 120s"}))
        monkeypatch.setattr(bg, "_RETRY_SLEEP", 0.05)
        monkeypatch.setattr(xb, "backends_are_initialized", lambda: False)
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        report = bg.ensure_backend(min_devices=1, retry_budget=0.5)
        assert report.fallback
        assert len(calls) >= 2          # waited out the TTL, then re-probed

    def test_cheap_failures_keep_the_short_retry_cadence(
            self, monkeypatch, tmp_path):
        # non-timeout failures (fast rc != 0) cost seconds, not the
        # probe window — the original retry cadence is right for them
        import jax._src.xla_bridge as xb

        self._isolate_probe_cache(monkeypatch, tmp_path)
        calls = []
        monkeypatch.setattr(
            bg, "probe_default_backend",
            lambda timeout=None: (calls.append(1)
                                  or {"ok": False, "error": "probe rc=1: "
                                      "plugin exploded"}))
        monkeypatch.setattr(bg, "_RETRY_SLEEP", 0.05)
        monkeypatch.setattr(xb, "backends_are_initialized", lambda: False)
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        report = bg.ensure_backend(min_devices=1, retry_budget=0.3)
        assert report.fallback and len(calls) >= 2
        assert "suppressed" not in report.note

    def test_cache_ttl_zero_disables(self, monkeypatch, tmp_path):
        import jax._src.xla_bridge as xb

        self._isolate_probe_cache(monkeypatch, tmp_path)
        monkeypatch.setenv("APEX_TPU_BACKEND_PROBE_CACHE_TTL", "0")
        calls = []
        monkeypatch.setattr(
            bg, "probe_default_backend",
            lambda timeout=None: (calls.append(1)
                                  or {"ok": False, "error": "down"}))
        monkeypatch.setattr(xb, "backends_are_initialized", lambda: False)
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        bg.ensure_backend(min_devices=1, retry_budget=0.0)
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        bg.ensure_backend(min_devices=1, retry_budget=0.0)
        assert len(calls) == 2           # every invocation probes fresh

    def test_stale_verdict_ignored(self, monkeypatch, tmp_path):
        self._isolate_probe_cache(monkeypatch, tmp_path)
        bg.store_probe_verdict({"ok": False, "error": "old news"})
        monkeypatch.setattr(bg, "_PROBE_VERDICT", None)
        import json as _json
        path = tmp_path / "probe_cache.json"
        rec = _json.loads(path.read_text())
        rec["wall_time"] -= 10_000.0     # far beyond any sane TTL
        path.write_text(_json.dumps(rec))
        assert bg.cached_probe_verdict() is None

"""backend_guard: the defensive bring-up layer every driver entry point
and bench run depends on (probe-with-timeout, retry budget, CPU
fallback, single-slot lock, MFU peak table)."""

import multiprocessing
import os
import time

import pytest

import apex_tpu.backend_guard as bg


class TestChipPeaks:
    @pytest.mark.parametrize("kind,peak", [
        ("TPU v5p", 459.0),
        ("TPU v5 lite", 197.0),
        ("TPU v5e", 197.0),
        ("TPU v4", 275.0),
        ("TPU v6 lite", 918.0),
        ("TPU v3", 123.0),
    ])
    def test_known_chips(self, kind, peak):
        assert bg.chip_peak_tflops(kind) == peak

    def test_unknown_is_none_not_a_guess(self):
        # mfu must be null for unknown chips, never a made-up denominator
        assert bg.chip_peak_tflops("cpu") is None
        assert bg.chip_peak_tflops("TPU v99") is None


class TestSlotLock:
    def test_acquire_and_reenter(self, tmp_path, monkeypatch):
        monkeypatch.setenv("APEX_TPU_SLOT_LOCK", str(tmp_path / "l"))
        with bg.tpu_slot_lock(timeout=5) as got:
            assert got
            # reentrant within the process: no deadlock, reports held
            with bg.tpu_slot_lock(timeout=5) as got2:
                assert got2

    def test_contention_times_out_not_hangs(self, tmp_path, monkeypatch):
        path = str(tmp_path / "l")
        monkeypatch.setenv("APEX_TPU_SLOT_LOCK", path)

        def hold(path, ev):
            import fcntl
            fd = os.open(path, os.O_CREAT | os.O_RDWR)
            fcntl.flock(fd, fcntl.LOCK_EX)
            ev.set()
            time.sleep(30)

        ev = multiprocessing.Event()
        proc = multiprocessing.Process(target=hold, args=(path, ev),
                                       daemon=True)
        proc.start()
        assert ev.wait(10)
        t0 = time.monotonic()
        try:
            with bg.tpu_slot_lock(timeout=1) as got:
                assert not got          # fails OPEN (advisory), not hang
            assert time.monotonic() - t0 < 15
        finally:
            proc.terminate()
            proc.join()
        # lock released by the dead process: next acquisition succeeds
        with bg.tpu_slot_lock(timeout=10) as got:
            assert got

    def test_unopenable_path_fails_open(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_SLOT_LOCK",
                           "/nonexistent-dir-xyz/lock")
        with bg.tpu_slot_lock(timeout=1) as got:
            assert not got              # warns + proceeds, never raises


class TestEnsureBackend:
    def test_initialized_backend_short_circuits(self):
        # the test process already runs the simulated CPU mesh
        report = bg.ensure_backend(min_devices=1)
        assert not report.fallback
        assert report.n_devices >= 1
        assert "backend" in report.as_detail()

    def test_retry_budget_retries_probe(self, monkeypatch):
        import jax._src.xla_bridge as xb

        calls = []

        def fake_probe(timeout=None):
            calls.append(1)
            return {"ok": False, "error": "tunnel down"}

        monkeypatch.setattr(bg, "probe_default_backend", fake_probe)
        monkeypatch.setattr(bg, "_RETRY_SLEEP", 0.05)
        monkeypatch.setattr(xb, "backends_are_initialized", lambda: False)
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        report = bg.ensure_backend(min_devices=1, retry_budget=0.2)
        assert report.fallback
        assert len(calls) >= 2          # retried, not one-shot
        assert "after" in report.note   # attempt count recorded

    def test_zero_budget_single_probe(self, monkeypatch):
        import jax._src.xla_bridge as xb

        calls = []
        monkeypatch.setattr(
            bg, "probe_default_backend",
            lambda timeout=None: (calls.append(1)
                                  or {"ok": False, "error": "down"}))
        monkeypatch.setattr(xb, "backends_are_initialized", lambda: False)
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        report = bg.ensure_backend(min_devices=1, retry_budget=0.0)
        assert report.fallback and len(calls) == 1
        assert report.as_detail()["backend_fallback"] == "down"

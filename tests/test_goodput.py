"""Run ledger: goodput attribution, restart continuity, the step-series
anomaly plane, and the satellites that ride the same PR — timeline
ring-wraparound accounting, fault-grammar stalls, windowed MFU, fleet
merge, and the report CLI (docs/observability.md "Run ledger &
goodput"; end-to-end kill-and-resume lives in
tools/check_observability.sh)."""

import importlib.util
import os

import pytest

from apex_tpu import telemetry
from apex_tpu.telemetry import cost as tcost
from apex_tpu.telemetry import fleet as tfleet
from apex_tpu.telemetry import goodput
from apex_tpu.telemetry import metrics as tmetrics
from apex_tpu.telemetry import timeline as ttimeline
from apex_tpu.telemetry.goodput import CAUSES, GoodputLedger, StepSeries
from apex_tpu.telemetry.timeline import Span, StepTimeline


@pytest.fixture(autouse=True)
def fresh_telemetry():
    """Every test sees a clean registry, disarmed ledger, and disabled
    global timeline."""
    telemetry.reset()
    yield
    telemetry.reset()


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _span(name, dur, *, category="phase", args=None, step=0):
    return Span(name, 0.0, float(dur), step, category, args)


def _ledger(clock, **kw):
    kw.setdefault("publish_every", 0)
    return GoodputLedger(clock=clock, **kw)


# ---------------------------------------------------------------------------
# Attribution identity + span routing
# ---------------------------------------------------------------------------


class TestAttribution:
    def test_identity_sums_to_wall(self):
        """The pinned identity: attributed + unattributed == wall, with
        every feed path exercised at once."""
        clk = FakeClock()
        led = _ledger(clk)
        led.observe_span(_span("compile", 0.5, category="compile"))
        led.observe_span(_span("step", 2.0, category="train_step"))
        led.observe_span(_span("data_wait", 0.3, category="data"))
        led.observe_span(_span("checkpoint", 0.4,
                               args={"kind": "save"}))
        led.observe_span(_span("checkpoint", 0.2,
                               args={"kind": "restore"}))
        led.note_rollback(1.0, restore_seconds=0.2)
        led.note_drain(0.7, save_seconds=0.4)
        led.note_straggler_wait(0.15)
        clk.advance(10.0)
        s = led.summary()
        attributed = sum(s["seconds"][c] for c in CAUSES)
        assert s["attributed_seconds"] == pytest.approx(attributed)
        assert (attributed + s["unattributed_seconds"]
                == pytest.approx(s["wall_seconds"]))
        assert s["overlap_seconds"] == 0.0
        assert s["seconds"]["unattributed"] == s["unattributed_seconds"]
        # each feed landed in its own bucket
        assert s["seconds"]["compile"] == pytest.approx(0.5)
        assert s["seconds"]["productive"] == pytest.approx(1.5)  # net
        assert s["seconds"]["data_wait"] == pytest.approx(0.3)
        assert s["seconds"]["checkpoint_save"] == pytest.approx(0.4)
        assert s["seconds"]["checkpoint_restore"] == pytest.approx(0.2)
        assert s["seconds"]["rollback"] == pytest.approx(0.8)  # net
        assert s["seconds"]["drain_shutdown"] == pytest.approx(0.3)  # net
        assert s["seconds"]["straggler_wait"] == pytest.approx(0.15)
        assert s["goodput_fraction"] == pytest.approx(1.5 / 10.0)

    def test_overlap_surfaced_not_hidden(self):
        """Buckets past wall (async saves) surface as overlap_seconds;
        unattributed clamps at zero rather than going negative."""
        clk = FakeClock()
        led = _ledger(clk)
        led.observe_span(_span("checkpoint", 5.0, args={"kind": "save"}))
        clk.advance(1.0)
        s = led.summary()
        assert s["unattributed_seconds"] == 0.0
        assert s["overlap_seconds"] == pytest.approx(4.0)

    def test_compile_nets_out_of_next_step_only(self):
        clk = FakeClock()
        led = _ledger(clk)
        led.observe_span(_span("compile", 1.5, category="compile"))
        led.observe_span(_span("step", 2.0))
        led.observe_span(_span("step", 2.0))
        s = led.summary()
        assert s["seconds"]["compile"] == pytest.approx(1.5)
        assert s["seconds"]["productive"] == pytest.approx(0.5 + 2.0)

    def test_checkpoint_kind_defaults_to_save(self):
        clk = FakeClock()
        led = _ledger(clk)
        led.observe_span(_span("checkpoint", 0.25))
        assert led.summary()["seconds"]["checkpoint_save"] == (
            pytest.approx(0.25))

    def test_pipeline_stages_ride_outside_identity(self):
        """Per-stage spans overlap the step wall — they show up as a
        diagnostic, never in the identity buckets."""
        clk = FakeClock()
        led = _ledger(clk)
        led.observe_span(_span("pipeline:stage0", 0.5,
                               category="pipeline"))
        led.observe_span(_span("pipeline:stage0", 0.5,
                               category="pipeline"))
        clk.advance(2.0)
        s = led.summary()
        assert s["stages"] == {"pipeline:stage0": pytest.approx(1.0)}
        assert sum(s["seconds"][c] for c in CAUSES) == 0.0

    def test_unknown_spans_stay_unattributed(self):
        clk = FakeClock()
        led = _ledger(clk)
        led.observe_span(_span("h2d", 0.5))
        led.observe_span(_span("host_step", 1.0, category="step"))
        clk.advance(2.0)
        s = led.summary()
        assert sum(s["seconds"][c] for c in CAUSES) == 0.0
        assert s["unattributed_seconds"] == pytest.approx(2.0)

    def test_span_feed_is_authoritative_over_step_s(self):
        """Once any timeline "step" span has been seen, observe_step's
        step_s never credits buckets (no double counting) — but steps
        and tokens still count."""
        clk = FakeClock()
        led = _ledger(clk)
        led.observe_span(_span("step", 1.0))
        led.observe_step(step=0, tokens=128, step_s=9.0)
        s = led.summary()
        assert s["seconds"]["productive"] == pytest.approx(1.0)
        assert s["steps"] == 1
        assert s["tokens_trained_total"] == 128.0

    def test_step_s_feeds_buckets_without_spans(self):
        clk = FakeClock()
        led = _ledger(clk)
        led.observe_step(step=0, tokens=64, step_s=0.5)
        led.observe_step(step=1, tokens=64, step_s=0.5)
        s = led.summary()
        assert s["seconds"]["productive"] == pytest.approx(1.0)
        assert s["median_step_s"] == pytest.approx(0.5)

    def test_live_span_observer_wiring(self):
        """enable() installs the observer on the timeline module so
        every recorded span — global timeline included — reaches the
        ledger; disable() removes it."""
        led = goodput.enable(publish_every=0)
        assert ttimeline._SPAN_OBSERVER is not None
        ttimeline.record_global_span("data_wait", 0.0, 0.25,
                                     category="data")
        assert led.summary()["seconds"]["data_wait"] == (
            pytest.approx(0.25))
        goodput.disable()
        assert ttimeline._SPAN_OBSERVER is None


# ---------------------------------------------------------------------------
# Restart survival: pack / absorb continuity
# ---------------------------------------------------------------------------


class TestRestartContinuity:
    def _run(self, led, clk, n, dur=0.5, start=0):
        for i in range(start, start + n):
            led.observe_span(_span("step", dur, step=i))
            led.observe_step(step=i, loss=1.0, tokens=100,
                             step_s=dur)
            clk.advance(dur)

    def test_kill_and_resume_carries_cumulative_state(self):
        """A resumed ledger is cumulative across the restart: seconds,
        wall, tokens, steps carry; restarts increments; the replayed
        range re-attributes to rework."""
        clk_a = FakeClock()
        a = _ledger(clk_a)
        self._run(a, clk_a, 10)           # steps 0..9, high water 9
        packed = a.pack(step=9)
        assert packed["step_high_water"] == 9
        assert packed["restarts"] == 0

        clk_b = FakeClock(5000.0)
        b = _ledger(clk_b)
        # checkpoint was at step 4 → steps 5..9 replay as rework
        b.absorb(packed, restored_step=4)
        self._run(b, clk_b, 5, start=5)   # the replay
        self._run(b, clk_b, 3, start=10)  # fresh ground
        s = b.summary()
        assert s["restarts"] == 1
        assert s["rework_steps"] == 5
        assert s["replay_remaining"] == 0
        assert s["seconds"]["rework"] == pytest.approx(5 * 0.5)
        # prior productive (10 steps) + fresh (3 steps)
        assert s["seconds"]["productive"] == pytest.approx(13 * 0.5)
        assert s["steps"] == 18           # 10 prior + 8 this life
        assert s["tokens_trained_total"] == pytest.approx(1800.0)
        # wall is cumulative: prior incarnation's + this one's
        assert s["wall_seconds"] == pytest.approx(
            packed["wall_seconds"] + 8 * 0.5)
        # the identity still holds on the merged ledger
        attributed = sum(s["seconds"][c] for c in CAUSES)
        assert (attributed + s["unattributed_seconds"]
                == pytest.approx(max(s["wall_seconds"], attributed)))

    def test_same_incarnation_absorb_is_replay_bookkeeping_only(self):
        """An in-process rollback restores its own checkpoint: the
        live state must not double-count, only the rework window
        arms."""
        clk = FakeClock()
        led = _ledger(clk)
        self._run(led, clk, 6)
        packed = led.pack(step=5)
        led.absorb(packed, restored_step=2)
        s = led.summary()
        assert s["restarts"] == 0
        assert s["steps"] == 6            # not 12
        assert s["replay_remaining"] == 3
        self._run(led, clk, 3, start=3)
        assert led.summary()["rework_steps"] == 3

    def test_double_absorb_guard(self):
        clk_a = FakeClock()
        a = _ledger(clk_a)
        self._run(a, clk_a, 4)
        packed = a.pack(step=3)
        b = _ledger(FakeClock())
        b.absorb(packed, restored_step=3)
        b.absorb(packed, restored_step=3)
        s = b.summary()
        assert s["restarts"] == 1
        assert s["steps"] == 4            # absorbed once, not twice

    def test_restart_chain_counts_every_kill(self):
        a = _ledger(FakeClock())
        b = _ledger(FakeClock())
        b.absorb(a.pack(step=0))
        c = _ledger(FakeClock())
        c.absorb(b.pack(step=0))
        assert c.summary()["restarts"] == 2

    def test_anomaly_episodes_carry_across_restart(self):
        a = _ledger(FakeClock())
        a.series.episodes["loss_spike"] = 2
        b = _ledger(FakeClock())
        b.absorb(a.pack(step=0))
        assert b.series.episodes["loss_spike"] == 2

    def test_merge_into_extra_and_note_restored_roundtrip(self):
        """The module-level checkpoint hooks: disarmed is identity,
        armed folds the pack in (never clobbering a caller's key), and
        note_restored absorbs it back."""
        extra = {"mine": 1}
        assert goodput.merge_into_extra(extra, step=5) is extra

        led = goodput.enable(publish_every=0)
        out = goodput.merge_into_extra(None, step=5)
        assert out["goodput"]["incarnation"] == led.incarnation
        out2 = goodput.merge_into_extra({"mine": 1}, step=5)
        assert out2["mine"] == 1 and "goodput" in out2
        taken = {"goodput": "caller-owned"}
        assert goodput.merge_into_extra(taken) is taken

        pack = dict(out["goodput"])
        pack["incarnation"] = "prior-process"
        pack["steps"] = 7
        goodput.note_restored({"goodput": pack}, restored_step=5)
        s = led.summary()
        assert s["restarts"] == 1 and s["steps"] == 7
        # disarmed / malformed never raise
        goodput.disable()
        goodput.note_restored({"goodput": pack}, restored_step=5)
        goodput.note_restored(None)


# ---------------------------------------------------------------------------
# Anomaly plane: StepSeries latches + ledger gauge/event surface
# ---------------------------------------------------------------------------


def _warm(series, n=24, base=1.0):
    for i in range(n):
        # deterministic non-flat noise so the IQR scale is positive
        series.push(step=i, loss=base + 0.01 * ((i * 7) % 13),
                    tokens_per_s=1000.0)


class TestStepSeries:
    def test_loss_spike_latches_once_then_rearms(self):
        sr = StepSeries(min_samples=16, window=32, loss_z=6.0)
        _warm(sr)
        fired = sr.push(step=24, loss=50.0)
        assert [(k, p) for k, p, _ in fired] == [("loss_spike", "latch")]
        assert sr.episodes["loss_spike"] == 1
        # still high: latched, no re-fire
        assert sr.push(step=25, loss=50.0) == []
        assert sr.episodes["loss_spike"] == 1
        # recovery re-arms
        fired = sr.push(step=26, loss=1.0)
        assert [(k, p) for k, p, _ in fired] == [("loss_spike",
                                                  "recover")]
        assert not sr.active["loss_spike"]
        # window still carries the two 50.0 outliers, so re-warm until
        # they age out before the second episode
        _warm(sr, n=40)
        fired = sr.push(step=99, loss=50.0)
        assert [(k, p) for k, p, _ in fired] == [("loss_spike", "latch")]
        assert sr.episodes["loss_spike"] == 2

    def test_needs_min_samples_before_scoring(self):
        sr = StepSeries(min_samples=16, window=32, loss_z=6.0)
        for i in range(15):
            assert sr.push(step=i, loss=1000.0 * i) == []

    def test_flat_window_spikes_up_never_down(self):
        sr = StepSeries(min_samples=8, window=16, loss_z=6.0)
        for i in range(10):
            sr.push(step=i, loss=2.0)
        assert sr.push(step=10, loss=0.5) == []     # downward: never
        fired = sr.push(step=11, loss=2.1)
        assert [(k, p) for k, p, _ in fired] == [("loss_spike", "latch")]

    def test_throughput_regression_needs_sustain(self):
        sr = StepSeries(min_samples=8, throughput_drop=0.3, sustain=3,
                        fast_alpha=0.9, slow_alpha=0.0)
        for i in range(10):
            sr.push(step=i, tokens_per_s=1000.0)
        fired = []
        for i in range(10, 16):
            fired += sr.push(step=i, tokens_per_s=100.0)
            if i < 12:
                assert sr.episodes["throughput_regression"] == 0
        assert sr.episodes["throughput_regression"] == 1
        assert [f for f in fired if f[1] == "latch"][0][0] == (
            "throughput_regression")
        # recovery re-arms
        rec = []
        for i in range(16, 22):
            rec += sr.push(step=i, tokens_per_s=1000.0)
        assert ("throughput_regression", "recover") in [
            (k, p) for k, p, _ in rec]

    def test_window_is_flight_bundle_sized(self):
        sr = StepSeries(capacity=64)
        for i in range(100):
            sr.push(step=i, loss=1.0)
        w = sr.window(32)
        assert len(w) == 32 and w[-1]["step"] == 99
        assert sr.summary()["samples"] == 64

    def test_nonfinite_samples_never_poison_the_window(self):
        sr = StepSeries(min_samples=4, window=8)
        for i in range(6):
            sr.push(step=i, loss=1.0 + 0.1 * i)
        sr.push(step=6, loss=float("nan"))
        sr.push(step=7, loss=float("inf"))
        assert all(s["loss"] is not None or s["step"] >= 6
                   for s in sr.window(8))
        # scoring continues on the finite prior window
        fired = sr.push(step=8, loss=500.0)
        assert [(k, p) for k, p, _ in fired] == [("loss_spike", "latch")]


class TestLedgerAnomalySurface:
    def test_latch_flips_gauge_and_emits_event_and_recovers(self):
        reg = tmetrics.registry()
        led = goodput.enable(publish_every=0, min_samples=8, window=16,
                             loss_z=6.0)
        for i in range(12):
            led.observe_step(step=i, loss=1.0 + 0.01 * ((i * 7) % 13))
        led.observe_step(step=12, loss=80.0)
        g = reg.gauge("goodput_anomaly_active")
        assert g.value(kind="loss_spike") == 1.0
        assert reg.counter("telemetry_events").value(
            event="loss_spike") == 1.0
        led.observe_step(step=13, loss=1.0)
        assert g.value(kind="loss_spike") == 0.0
        assert reg.counter("telemetry_events").value(
            event="loss_spike_recovered") == 1.0
        assert led.summary()["anomalies"]["episodes"]["loss_spike"] == 1


# ---------------------------------------------------------------------------
# Publish surface: gauges + info blob + windowed MFU
# ---------------------------------------------------------------------------


class TestPublish:
    def test_publish_mirrors_summary_into_registry(self):
        reg = tmetrics.registry()
        clk = FakeClock()
        led = _ledger(clk)
        led.observe_span(_span("step", 1.0))
        led.observe_step(step=0, tokens=512)
        clk.advance(2.0)
        summ = led.publish(reg)
        g = reg.gauge("goodput_seconds")
        assert g.value(cause="productive") == pytest.approx(1.0)
        assert g.value(cause="unattributed") == pytest.approx(1.0)
        assert reg.gauge("goodput_fraction").value() == pytest.approx(0.5)
        assert reg.gauge("tokens_trained_total").value() == 512.0
        assert reg.gauge("effective_tokens_per_sec").value() == (
            pytest.approx(256.0))
        assert reg.get_info("goodput")["wall_seconds"] == (
            summ["wall_seconds"])

    def test_publish_every_cadence(self):
        reg = tmetrics.registry()
        led = goodput.enable(publish_every=5)
        for i in range(4):
            led.observe_step(step=i, step_s=0.1)
        assert reg.get_info("goodput") is None
        led.observe_step(step=4, step_s=0.1)
        assert reg.get_info("goodput")["steps"] == 5

    def test_publish_folds_mfu_from_step_cost(self):
        """When a step cost was published, publish() refreshes the
        mfu_ewma window from the productive-step median."""
        reg = tmetrics.registry()
        reg.gauge("step_flops", "").set(275e12)
        clk = FakeClock()
        led = _ledger(clk)
        led.observe_span(_span("step", 1.0))
        summ = led.publish(reg)
        # chip kind resolution is host-dependent; the contract is the
        # key exists and, on CPU hosts, the null reason is published
        assert "mfu_ewma" in summ


class TestMfuWindow:
    def test_seeds_then_folds_ewma(self):
        reg = tmetrics.registry()
        est = tcost.publish_mfu_window({"flops": 275e12}, 1.0,
                                       kind="v4", registry=reg)
        assert est["mfu"] == pytest.approx(1.0)
        assert reg.gauge("mfu_ewma").value() == pytest.approx(1.0)
        est = tcost.publish_mfu_window({"flops": 137.5e12}, 1.0,
                                       kind="v4", alpha=0.2,
                                       registry=reg)
        assert est["mfu_ewma"] == pytest.approx(0.9)
        assert reg.gauge("mfu_ewma").value() == pytest.approx(0.9)

    def test_null_estimate_leaves_gauge_and_names_reason(self):
        reg = tmetrics.registry()
        est = tcost.publish_mfu_window(None, 1.0, kind="v4",
                                       registry=reg)
        assert est["mfu_ewma"] is None
        assert "cost model" in reg.get_info("mfu_reason")
        assert reg.gauge("mfu_ewma").value() == 0.0  # untouched default
        est = tcost.publish_mfu_window({"flops": 1e12}, 0.0, kind="v4",
                                       registry=reg)
        assert est["mfu"] is None
        assert "non-positive" in reg.get_info("mfu_reason")


# ---------------------------------------------------------------------------
# Fleet merge goldens
# ---------------------------------------------------------------------------


def _host_summary(fraction, wall, *, straggler=0.0, tokens=0.0,
                  restarts=0):
    seconds = {c: 0.0 for c in CAUSES}
    seconds["productive"] = round(fraction * wall, 6)
    seconds["straggler_wait"] = straggler
    return {"enabled": True, "goodput_fraction": fraction,
            "wall_seconds": wall, "seconds": seconds,
            "tokens_trained_total": tokens, "restarts": restarts}


class TestFleetMerge:
    def test_merge_goodput_golden(self):
        snaps = [
            {"registry": {}, "goodput": _host_summary(
                0.8, 100.0, straggler=2.0, tokens=1000.0, restarts=1)},
            {"registry": {}, "goodput": _host_summary(
                0.6, 100.0, straggler=5.0, tokens=500.0)},
            {"registry": {}},                        # disarmed host
            {"registry": {}, "goodput": {"enabled": False}},
        ]
        merged = tfleet.merge_snapshots(snaps)
        gp = merged["goodput"]
        assert gp["n_hosts"] == 2                    # disarmed drop out
        assert set(gp["per_host"]) == {"0", "1"}
        assert gp["per_host"]["0"] == {
            "goodput_fraction": 0.8, "wall_seconds": 100.0,
            "straggler_wait_seconds": 2.0, "restarts": 1}
        assert gp["fraction_min"] == 0.6
        assert gp["fraction_max"] == 0.8
        assert gp["fraction_mean"] == pytest.approx(0.7)
        assert gp["seconds_total"]["productive"] == pytest.approx(140.0)
        assert gp["straggler_wait_seconds_total"] == pytest.approx(7.0)
        assert gp["tokens_trained_total"] == pytest.approx(1500.0)

    def test_no_goodput_key_when_fleet_disarmed(self):
        merged = tfleet.merge_snapshots([{"registry": {}},
                                         {"registry": {}}])
        assert "goodput" not in merged


# ---------------------------------------------------------------------------
# Timeline ring wraparound (satellite regression)
# ---------------------------------------------------------------------------


class TestTimelineWraparound:
    def test_dropped_seconds_and_counter_delta(self):
        """Ring eviction is accounted, not silent: dropped_seconds
        totals the evicted durations, summary surfaces them, and
        publish() bumps the counter by the delta exactly once."""
        reg = tmetrics.registry()
        tl = StepTimeline(capacity=4, enabled=True)
        for i in range(10):
            tl.record_span(f"p{i}", 0.0, 1.0)
        assert tl.dropped_seconds == pytest.approx(6.0)
        s = tl.summary()
        assert s["dropped_spans"] == 6
        assert s["dropped_span_seconds"] == pytest.approx(6.0)
        tl.publish(reg)
        c = reg.counter("timeline_dropped_spans_total")
        assert c.value() == 6.0
        tl.publish(reg)                   # no new evictions: no delta
        assert c.value() == 6.0
        for i in range(2):
            tl.record_span("q", 0.0, 0.5)
        tl.publish(reg)
        assert c.value() == 8.0
        # the evicted spans (dur 1.0 each) are what is totaled, not
        # the newly recorded ones
        assert tl.dropped_seconds == pytest.approx(8.0)

    def test_under_capacity_drops_nothing(self):
        tl = StepTimeline(capacity=8, enabled=True)
        for i in range(8):
            tl.record_span("p", 0.0, 1.0)
        assert tl.dropped_seconds == 0.0
        assert tl.summary()["dropped_spans"] == 0

    def test_ledger_surfaces_global_timeline_drops(self):
        ttimeline.enable(capacity=2)
        led = goodput.enable(publish_every=0)
        for i in range(5):
            ttimeline.record_global_span("h2d", 0.0, 0.25)
        assert led.summary()["timeline_dropped_span_seconds"] == (
            pytest.approx(0.75))

    def test_reset_clears_drop_accounting(self):
        tl = StepTimeline(capacity=2, enabled=True)
        for i in range(5):
            tl.record_span("p", 0.0, 1.0)
        tl.reset()
        assert tl.dropped_seconds == 0.0


# ---------------------------------------------------------------------------
# Faults grammar: stall clauses (satellite)
# ---------------------------------------------------------------------------


class TestFaultStalls:
    def test_grammar_parses_stall_clauses(self):
        from apex_tpu.resilience.faults import FaultInjector

        inj = FaultInjector.from_env("data_stall_ms=7;ckpt_stall_ms=2.5")
        assert inj.data_stall_ms == 7.0
        assert inj.ckpt_stall_ms == 2.5
        assert inj.data_stall_s() == pytest.approx(0.007)
        assert inj.ckpt_stall_s() == pytest.approx(0.0025)

    def test_negative_stall_clamps_to_zero(self):
        from apex_tpu.resilience.faults import FaultInjector

        inj = FaultInjector(data_stall_ms=-5.0, ckpt_stall_ms=-1.0)
        assert inj.data_stall_s() == 0.0
        assert inj.ckpt_stall_s() == 0.0

    def test_module_helpers_default_to_zero_without_injector(self):
        from apex_tpu.resilience import faults

        if faults.active() is None:
            assert faults.data_stall_s() == 0.0
            assert faults.ckpt_stall_s() == 0.0


# ---------------------------------------------------------------------------
# Disarmed contract + report CLI
# ---------------------------------------------------------------------------


class TestDisarmed:
    def test_section_reports_reason(self):
        sec = goodput.section()
        assert sec["enabled"] is False
        assert "not armed" in sec["goodput_reason"]

    def test_module_feeds_are_noops(self):
        goodput.observe_step(step=0, loss=1.0, tokens=10, step_s=0.1)
        goodput.note_rollback(1.0)
        goodput.note_drain(1.0)
        goodput.note_straggler_wait(1.0)
        assert goodput.get_ledger() is None
        assert goodput.enabled() is False

    def test_snapshot_detail_carries_reason(self):
        snap = telemetry.snapshot_detail()
        assert snap["goodput"] is None
        assert "not armed" in snap["goodput_reason"]

    def test_snapshot_detail_carries_summary_when_armed(self):
        goodput.enable(publish_every=0)
        snap = telemetry.snapshot_detail()
        assert snap["goodput"]["enabled"] is True
        assert set(CAUSES) <= set(snap["goodput"]["seconds"])


def _load_report_tool():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "goodput_report.py")
    spec = importlib.util.spec_from_file_location("goodput_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestReportTool:
    def test_normalize_rederives_from_pack(self):
        """A manifest pack has no derived fields; the report re-derives
        fraction / unattributed / effective tok/s from the raw
        buckets."""
        rpt = _load_report_tool()
        clk = FakeClock()
        led = _ledger(clk)
        led.observe_span(_span("step", 2.0))
        led.observe_step(step=0, tokens=1000)
        clk.advance(4.0)
        summ = rpt.normalize(led.pack(step=0))
        assert summ["goodput_fraction"] == pytest.approx(0.5)
        assert summ["unattributed_seconds"] == pytest.approx(2.0)
        assert summ["effective_tokens_per_sec"] == pytest.approx(250.0)
        with pytest.raises(ValueError):
            rpt.normalize({"not": "a pack"})

    def test_extract_finds_nested_payloads(self):
        rpt = _load_report_tool()
        led = _ledger(FakeClock())
        pack = led.pack(step=0)
        for wrap in (pack,
                     {"goodput": pack},
                     {"extra": {"goodput": pack}},
                     {"payload": {"telemetry": {"goodput": pack}}}):
            got = rpt.extract(wrap)
            assert got["incarnation"] == led.incarnation
        assert rpt.extract({"unrelated": 1}) is None
        assert rpt.extract("not a dict") is None

    def test_render_shows_restarts_and_table(self):
        rpt = _load_report_tool()
        clk = FakeClock()
        led = _ledger(clk)
        led.observe_span(_span("step", 1.0))
        clk.advance(2.0)
        led.absorb({"incarnation": "prior", "restarts": 0,
                    "seconds": {}, "wall_seconds": 1.0})
        text = rpt.render(rpt.normalize(led.pack(step=0)))
        assert "== goodput report ==" in text
        assert "restarts    1" in text
        for cause in (*CAUSES, "unattributed"):
            assert cause in text

"""Encoder-decoder fixture tests (mirrors the reference's enc-dec
coverage in standalone_transformer_lm + pipeline split-rank math)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.t5 import (
    T5Config,
    T5Model,
    encoder_decoder_stage_layout,
    t5_loss_fn,
    t5_param_specs,
)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state as ps

TINY = T5Config(
    vocab_size=96, max_seq_len=32, hidden_size=48,
    num_encoder_layers=2, num_decoder_layers=2, num_heads=4,
    dtype=jnp.float32,
)


def synth_batch(rng, b, s_enc, s_dec, vocab):
    enc = rng.randint(0, vocab, (b, s_enc))
    mask = np.ones((b, s_enc), np.int32)
    mask[:, s_enc - 2:] = 0
    dec = rng.randint(0, vocab, (b, s_dec + 1))
    lmask = np.ones((b, s_dec), np.int32)
    return (jnp.asarray(enc, jnp.int32), jnp.asarray(mask),
            jnp.asarray(dec[:, :-1], jnp.int32),
            jnp.asarray(dec[:, 1:], jnp.int32), jnp.asarray(lmask))


def test_stage_layout():
    layout = encoder_decoder_stage_layout(12, 12, 4, 2)
    assert layout == (("encoder", 6), ("encoder", 6),
                      ("decoder", 6), ("decoder", 6))
    layout = encoder_decoder_stage_layout(12, 4, 4, 3)
    assert layout == (("encoder", 4),) * 3 + (("decoder", 4),)
    with pytest.raises(ValueError):
        encoder_decoder_stage_layout(12, 12, 4, 0)
    with pytest.raises(ValueError):
        encoder_decoder_stage_layout(10, 12, 4, 3)


class TestSingleDevice:
    def test_forward_shapes(self, rng):
        model = T5Model(TINY)
        enc, mask, dec, _, _ = synth_batch(rng, 2, 16, 12, TINY.vocab_size)
        params = model.init(jax.random.PRNGKey(0), enc, mask, dec)
        logits = model.apply(params, enc, mask, dec)
        assert logits.shape == (12, 2, TINY.vocab_size)

    def test_encoder_mask_blocks_padding(self, rng):
        """Changing a masked-out encoder token must not change logits."""
        model = T5Model(TINY)
        enc, mask, dec, _, _ = synth_batch(rng, 1, 16, 8, TINY.vocab_size)
        params = model.init(jax.random.PRNGKey(0), enc, mask, dec)
        out1 = model.apply(params, enc, mask, dec)
        enc2 = enc.at[0, 15].set((int(enc[0, 15]) + 1) % TINY.vocab_size)
        out2 = model.apply(params, enc2, mask, dec)
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                                   atol=1e-5)

    def test_decoder_causality(self, rng):
        """Changing a future decoder token must not change past logits."""
        model = T5Model(TINY)
        enc, mask, dec, _, _ = synth_batch(rng, 1, 8, 10, TINY.vocab_size)
        params = model.init(jax.random.PRNGKey(0), enc, mask, dec)
        out1 = model.apply(params, enc, mask, dec)
        dec2 = dec.at[0, 7].set((int(dec[0, 7]) + 1) % TINY.vocab_size)
        out2 = model.apply(params, enc, mask, dec2)
        np.testing.assert_allclose(np.asarray(out1[:7]),
                                   np.asarray(out2[:7]), atol=1e-5)
        assert not np.allclose(np.asarray(out1[7:]), np.asarray(out2[7:]))

    @pytest.mark.slow
    def test_tiny_convergence(self, rng):
        model = T5Model(TINY)
        enc, mask, dec, labels, lmask = synth_batch(
            rng, 4, 12, 10, TINY.vocab_size)
        params = model.init(jax.random.PRNGKey(0), enc, mask, dec)
        opt = FusedAdam(lr=2e-3, impl="xla")
        state = opt.init(params)

        @jax.jit
        def step(params, state):
            loss, grads = jax.value_and_grad(
                lambda p: t5_loss_fn(model.apply(p, enc, mask, dec),
                                     labels, lmask))(params)
            params, state = opt.step(state, grads)
            return params, state, loss

        losses = []
        for _ in range(40):
            params, state, loss = step(params, state)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, losses[::10]


class TestTensorParallel:
    @pytest.fixture(autouse=True)
    def mesh(self):
        m = ps.initialize_model_parallel(4, 1)
        yield m
        ps.destroy_model_parallel()

    def test_tp_matches_dense(self, mesh, rng):
        cfg = T5Config(
            vocab_size=64, max_seq_len=16, hidden_size=32,
            num_encoder_layers=1, num_decoder_layers=1, num_heads=4,
            dtype=jnp.float32,
        )
        model = T5Model(cfg)
        enc, mask, dec, labels, lmask = synth_batch(
            rng, 2, 12, 8, cfg.vocab_size)
        params = model.init(jax.random.PRNGKey(0), enc, mask, dec)

        def loss_fn(p, *args):
            return t5_loss_fn(model.apply(p, *args[:3]), args[3], args[4])

        dense = loss_fn(params, enc, mask, dec, labels, lmask)
        specs = t5_param_specs(params)
        loss = jax.jit(shard_map(
            loss_fn, mesh=mesh,
            in_specs=(specs, P(), P(), P(), P(), P()),
            out_specs=P(), check_vma=False,
        ))(params, enc, mask, dec, labels, lmask)
        np.testing.assert_allclose(float(loss), float(dense), rtol=2e-4)

    @pytest.mark.slow
    def test_tp_grads_match_dense(self, mesh, rng):
        cfg = T5Config(
            vocab_size=64, max_seq_len=16, hidden_size=32,
            num_encoder_layers=1, num_decoder_layers=1, num_heads=4,
            dtype=jnp.float32,
        )
        model = T5Model(cfg)
        enc, mask, dec, labels, lmask = synth_batch(
            rng, 2, 12, 8, cfg.vocab_size)
        params = model.init(jax.random.PRNGKey(0), enc, mask, dec)
        specs = t5_param_specs(params)

        def loss_fn(p, *args):
            return t5_loss_fn(model.apply(p, *args[:3]), args[3], args[4])

        step = shard_map(
            lambda p, *a: jax.value_and_grad(loss_fn)(p, *a),
            mesh=mesh, in_specs=(specs, P(), P(), P(), P(), P()),
            out_specs=(P(), specs), check_vma=False,
        )
        loss_tp, g_tp = jax.jit(step)(params, enc, mask, dec, labels, lmask)
        g_dense = jax.grad(
            lambda p: loss_fn(p, enc, mask, dec, labels, lmask))(params)
        np.testing.assert_allclose(
            float(loss_tp),
            float(loss_fn(params, enc, mask, dec, labels, lmask)),
            rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5),
            g_tp, g_dense)


class TestT5FlashBackend:
    """T5 on the Pallas kernel: encoder padding as segment ids, causal
    decoder, key-side-masked cross attention."""

    @pytest.mark.slow
    def test_flash_matches_softmax(self, rng):
        base = dict(vocab_size=256, max_seq_len=64, hidden_size=64,
                    num_encoder_layers=2, num_decoder_layers=2,
                    num_heads=4, dtype=jnp.float32,
                    softmax_impl="interpret")
        enc = jnp.asarray(rng.randint(0, 256, (2, 48)), jnp.int32)
        mask = jnp.ones((2, 48), jnp.int32).at[:, 41:].set(0)
        dec = jnp.asarray(rng.randint(0, 256, (2, 32)), jnp.int32)
        outs = {}
        for backend in ("softmax", "flash"):
            cfg = T5Config(attention_backend=backend, **base)
            model = T5Model(cfg)
            params = model.init(jax.random.PRNGKey(0), enc, mask, dec)
            outs[backend] = np.asarray(
                model.apply(params, enc, mask, dec))
        # decoder logits must agree: encoder pad ROWS differ between
        # masking conventions but are excluded as cross-attn keys under
        # both, so nothing downstream sees them
        np.testing.assert_allclose(outs["flash"], outs["softmax"],
                                   rtol=2e-4, atol=2e-4)

    def test_backend_validated(self):
        with pytest.raises(ValueError, match="attention_backend"):
            T5Config(attention_backend="Flash")

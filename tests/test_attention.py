"""Flash attention kernel + MHA module tests.

Mirrors the reference's contrib attention tests
(ref: apex/contrib/test/multihead_attn/test_self_multihead_attn.py,
test_encdec_multihead_attn.py, apex/contrib/test/fmha/test_fmha.py):
fused kernel vs pure reference implementation, fwd and bwd.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.fmha import fmha, segment_ids_from_cu_seqlens
from apex_tpu.contrib.multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)
from apex_tpu.ops.attention import flash_attention


def naive_attention(q, k, v, bias=None, causal=False, scale=None):
    scale = scale or q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if bias is not None:
        s = s + bias
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        row = np.arange(sq)[:, None]
        col = np.arange(sk)[None, :]
        s = jnp.where(jnp.asarray(col > row + (sk - sq)), -1e30, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@pytest.fixture
def qkv(rng):
    b, h, s, d = 2, 4, 128, 64
    return [jnp.asarray(rng.randn(b, h, s, d).astype(np.float32)) * 0.3
            for _ in range(3)]


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_naive(qkv, causal, impl):
    q, k, v = qkv
    out = flash_attention(q, k, v, causal=causal, impl=impl)
    ref = naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-3, rtol=1e-3)


def test_flash_bias(qkv, rng, impl):
    q, k, v = qkv
    bias = jnp.asarray(rng.randn(1, q.shape[1], q.shape[2],
                                 k.shape[2]).astype(np.float32))
    out = flash_attention(q, k, v, bias=bias, impl=impl)
    ref = naive_attention(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-3, rtol=1e-3)


def test_flash_grads_match_xla(qkv, rng):
    q, k, v = qkv
    bias = jnp.asarray(rng.randn(1, 4, 128, 128).astype(np.float32)) * 0.1

    def mk(impl):
        def f(q, k, v, bias):
            o = flash_attention(q, k, v, bias=bias, causal=True, impl=impl)
            return jnp.sum(o * o)
        return jax.grad(f, argnums=(0, 1, 2, 3))

    gi = mk("interpret")(q, k, v, bias)
    gx = mk("xla")(q, k, v, bias)
    for a, b in zip(gi, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-2, rtol=1e-2)


def test_flash_segment_ids_isolate(rng, impl):
    """Packed sequences must not attend across segment boundaries: the
    packed result equals per-segment attention computed separately."""
    b, h, s, d = 1, 2, 128, 32
    q, k, v = [jnp.asarray(rng.randn(b, h, s, d).astype(np.float32)) * 0.5
               for _ in range(3)]
    seg = jnp.asarray(np.repeat([0, 1], s // 2)[None], jnp.int32)
    out = flash_attention(q, k, v, segment_ids=seg, causal=True, impl=impl)
    half = s // 2
    ref0 = naive_attention(q[:, :, :half], k[:, :, :half], v[:, :, :half],
                           causal=True)
    ref1 = naive_attention(q[:, :, half:], k[:, :, half:], v[:, :, half:],
                           causal=True)
    np.testing.assert_allclose(np.asarray(out[:, :, :half]), np.asarray(ref0),
                               atol=5e-3, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(out[:, :, half:]), np.asarray(ref1),
                               atol=5e-3, rtol=1e-3)


def test_fmha_packed_varlen(rng, impl):
    lens = [48, 80, 128]
    total = sum(lens)
    nh, d = 4, 32
    qkv_packed = jnp.asarray(rng.randn(total, 3, nh, d).astype(np.float32)) * 0.4
    cu = jnp.asarray(np.cumsum([0] + lens), jnp.int32)
    out = fmha(qkv_packed, cu, impl=impl)
    assert out.shape == (total, nh, d)
    # compare each sequence against standalone attention
    off = 0
    for ln in lens:
        chunk = qkv_packed[off:off + ln]
        q, k, v = (chunk[:, i].transpose(1, 0, 2)[None] for i in range(3))
        ref = naive_attention(q, k, v)[0].transpose(1, 0, 2)
        np.testing.assert_allclose(np.asarray(out[off:off + ln]),
                                   np.asarray(ref), atol=5e-3, rtol=1e-3)
        off += ln


def test_segment_ids_from_cu_seqlens():
    cu = jnp.asarray([0, 3, 5], jnp.int32)
    seg = segment_ids_from_cu_seqlens(cu, 7)
    np.testing.assert_array_equal(np.asarray(seg), [0, 0, 0, 1, 1, 2, 2])


@pytest.mark.parametrize("norm_add", [False, True])
def test_self_multihead_attn(rng, norm_add):
    s, b, e, h = 64, 2, 128, 4
    x = jnp.asarray(rng.randn(s, b, e).astype(np.float32)) * 0.5
    mod = SelfMultiheadAttn(embed_dim=e, num_heads=h, bias=True,
                            include_norm_add=norm_add, impl="interpret")
    params = mod.init(jax.random.PRNGKey(0), x)
    out, _ = mod.apply(params, x)
    assert out.shape == (s, b, e)
    ref = SelfMultiheadAttn(embed_dim=e, num_heads=h, bias=True,
                            include_norm_add=norm_add, impl="default")
    out_ref, _ = ref.apply(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=5e-3, rtol=1e-3)


def test_self_multihead_attn_padding_mask(rng):
    s, b, e, h = 64, 2, 64, 4
    x = jnp.asarray(rng.randn(s, b, e).astype(np.float32)) * 0.5
    pad = jnp.asarray(np.arange(s)[None] >= 48).repeat(b, 0)  # (b, s)
    mod = SelfMultiheadAttn(embed_dim=e, num_heads=h, impl="interpret")
    params = mod.init(jax.random.PRNGKey(0), x)
    out, _ = mod.apply(params, x, key_padding_mask=pad)
    ref = SelfMultiheadAttn(embed_dim=e, num_heads=h, impl="default")
    out_ref, _ = ref.apply(params, x, key_padding_mask=pad)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=5e-3, rtol=1e-3)


def test_encdec_multihead_attn(rng):
    sq, sk, b, e, h = 32, 64, 2, 64, 4
    q = jnp.asarray(rng.randn(sq, b, e).astype(np.float32)) * 0.5
    kv = jnp.asarray(rng.randn(sk, b, e).astype(np.float32)) * 0.5
    mod = EncdecMultiheadAttn(embed_dim=e, num_heads=h, impl="interpret")
    params = mod.init(jax.random.PRNGKey(0), q, kv)
    out, _ = mod.apply(params, q, kv)
    assert out.shape == (sq, b, e)
    ref = EncdecMultiheadAttn(embed_dim=e, num_heads=h, impl="default")
    out_ref, _ = ref.apply(params, q, kv)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=5e-3, rtol=1e-3)


def test_mha_dropout_stays_on_kernel_and_grads_match_xla(rng):
    """dropout>0 must NOT silently downgrade the fast impl to the XLA
    path (round-2 VERDICT weak#3): the kernel's counter-based dropout
    generates the identical mask across impls, so outputs AND grads of
    the kernel path match the XLA path exactly for the same rng."""
    s, b, e, h = 32, 2, 64, 4
    x = jnp.asarray(rng.randn(s, b, e).astype(np.float32)) * 0.5
    kern = SelfMultiheadAttn(embed_dim=e, num_heads=h, dropout=0.3,
                             impl="interpret")
    xla = SelfMultiheadAttn(embed_dim=e, num_heads=h, dropout=0.3,
                            impl="default")
    params = kern.init(jax.random.PRNGKey(0), x, is_training=False)

    # the fast module must actually call the kernel impl under dropout
    calls = []
    import apex_tpu.contrib.multihead_attn as mha_mod
    orig = mha_mod.flash_attention

    def spy(*a, **kw):
        calls.append(kw.get("impl"))
        return orig(*a, **kw)

    mha_mod.flash_attention = spy
    try:
        kern.apply(params, x, is_training=True,
                   rngs={"dropout": jax.random.PRNGKey(7)})
    finally:
        mha_mod.flash_attention = orig
    assert calls == ["interpret"], calls

    def loss(mod, p):
        out, _ = mod.apply(p, x, is_training=True,
                           rngs={"dropout": jax.random.PRNGKey(7)})
        return jnp.sum(out ** 2)

    lk, gk = jax.value_and_grad(lambda p: loss(kern, p))(params)
    lx, gx = jax.value_and_grad(lambda p: loss(xla, p))(params)
    np.testing.assert_allclose(float(lk), float(lx), rtol=1e-4)
    for leaf_k, leaf_x in zip(jax.tree.leaves(gk), jax.tree.leaves(gx)):
        np.testing.assert_allclose(np.asarray(leaf_k), np.asarray(leaf_x),
                                   atol=5e-3, rtol=1e-3)


def test_mha_dropout_deterministic_under_key(rng):
    s, b, e, h = 32, 2, 64, 4
    x = jnp.asarray(rng.randn(s, b, e).astype(np.float32))
    mod = SelfMultiheadAttn(embed_dim=e, num_heads=h, dropout=0.5,
                            impl="default")
    params = mod.init(jax.random.PRNGKey(0), x, is_training=False)
    o1, _ = mod.apply(params, x, is_training=True,
                      rngs={"dropout": jax.random.PRNGKey(7)})
    o2, _ = mod.apply(params, x, is_training=True,
                      rngs={"dropout": jax.random.PRNGKey(7)})
    o3, _ = mod.apply(params, x, is_training=False)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    assert np.abs(np.asarray(o1) - np.asarray(o3)).max() > 1e-3


def test_fully_masked_q_segment_zero_output_and_grads(rng):
    """A q segment with no matching kv segment must emit 0 with zero
    gradients — on both impls (code-review regression)."""
    b, h, s, d = 1, 2, 128, 32
    q, k, v = [jnp.asarray(rng.randn(b, h, s, d).astype(np.float32)) * 0.5
               for _ in range(3)]
    q_seg = jnp.asarray(np.repeat([0, 7], s // 2)[None], jnp.int32)
    k_seg = jnp.zeros((b, s), jnp.int32)  # segment 7 queries match nothing

    outs, grads = {}, {}
    for impl in ("xla", "interpret"):
        def f(q, k, v):
            o = flash_attention(q, k, v, segment_ids=q_seg,
                                kv_segment_ids=k_seg, impl=impl)
            return jnp.sum(o * o), o
        (_, o), g = jax.value_and_grad(f, argnums=(0, 1, 2),
                                       has_aux=True)(q, k, v)
        outs[impl], grads[impl] = o, g

    for impl in ("xla", "interpret"):
        np.testing.assert_array_equal(
            np.asarray(outs[impl][:, :, s // 2:]), 0.0)
        np.testing.assert_array_equal(
            np.asarray(grads[impl][0][:, :, s // 2:]), 0.0)
    for a, b_ in zip(grads["xla"], grads["interpret"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-2, rtol=1e-2)


def test_kv_segment_ids_only(rng, impl):
    """kv_segment_ids without segment_ids masks padded keys
    (code-review regression: used to be silently ignored)."""
    b, h, s, d = 2, 2, 128, 32
    q, k, v = [jnp.asarray(rng.randn(b, h, s, d).astype(np.float32)) * 0.5
               for _ in range(3)]
    valid = 96
    kv_seg = jnp.asarray((np.arange(s) >= valid)[None].repeat(b, 0),
                         jnp.int32)
    out = flash_attention(q, k, v, kv_segment_ids=kv_seg, impl=impl)
    ref = naive_attention(q[:, :, :, :], k[:, :, :valid], v[:, :, :valid])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-3, rtol=1e-3)


@pytest.mark.slow
def test_flash_bias_grad_broadcast_shapes(rng):
    """dbias must come back in the bias's own (broadcast) shape and match
    the XLA path (code-review regression for the chunked recompute)."""
    b, h, s, d = 2, 2, 64, 32
    q, k, v = [jnp.asarray(rng.randn(b, h, s, d).astype(np.float32)) * 0.5
               for _ in range(3)]
    for shape in [(1, 1, s, s), (1, h, s, s), (b, h, s, s),
                  (b, 1, 1, s), (1, h, s, 1)]:
        bias = jnp.asarray(rng.randn(*shape).astype(np.float32)) * 0.1

        def mk(impl):
            def f(bias):
                o = flash_attention(q, k, v, bias=bias, causal=True,
                                    impl=impl)
                return jnp.sum(o * o)
            return jax.grad(f)
        gi = mk("interpret")(bias)
        gx = mk("xla")(bias)
        assert gi.shape == shape
        np.testing.assert_allclose(np.asarray(gi), np.asarray(gx),
                                   atol=2e-2, rtol=1e-2)


def test_mask_additive_fast_impl(rng):
    """mask_additive builds a (b,1,1,sk) bias; the Pallas path must accept
    it (code-review regression: size-1 sq/sk dims used to crash)."""
    s, b, e, h = 64, 2, 64, 4
    x = jnp.asarray(rng.randn(s, b, e).astype(np.float32)) * 0.5
    add_mask = jnp.where(jnp.asarray(np.arange(s)[None] >= 48).repeat(b, 0),
                         -10000.0, 0.0)
    fast = SelfMultiheadAttn(embed_dim=e, num_heads=h, mask_additive=True,
                             impl="interpret")
    params = fast.init(jax.random.PRNGKey(0), x)
    out, _ = fast.apply(params, x, key_padding_mask=add_mask)
    ref = SelfMultiheadAttn(embed_dim=e, num_heads=h, mask_additive=True,
                            impl="default")
    out_ref, _ = ref.apply(params, x, key_padding_mask=add_mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=5e-3, rtol=1e-3)


class TestSlidingWindow:
    """window_size: local attention band (beyond the reference) — each
    query sees its last w keys up to the diagonal; out-of-band blocks
    are skipped in the kernel."""

    def _manual(self, q, k, v, w):
        b, h, s, d = q.shape
        scores = np.einsum("bhqd,bhkd->bhqk",
                           np.asarray(q, np.float32) * d ** -0.5,
                           np.asarray(k, np.float32))
        row = np.arange(s)[:, None]
        col = np.arange(s)[None, :]
        mask = (col > row) | (col <= row - w)
        scores = np.where(mask, -1e30, scores)
        e = np.exp(scores - scores.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        return np.einsum("bhqk,bhkd->bhqd", p, np.asarray(v, np.float32))

    @pytest.mark.parametrize("w", [1, 16, 64, 1000])
    def test_matches_manual(self, rng, impl, w):
        from apex_tpu.ops.attention import flash_attention

        b, h, s, d = 2, 2, 128, 32
        q, k, v = (jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * 0.3)
                   for _ in range(3))
        out = flash_attention(q, k, v, causal=True, window_size=w,
                              block_q=32, block_k=32, impl=impl)
        np.testing.assert_allclose(np.asarray(out), self._manual(q, k, v, w),
                                   rtol=2e-4, atol=2e-4)

    def test_grads_match_xla(self, rng, impl):
        from apex_tpu.ops.attention import flash_attention

        b, h, s, d = 1, 2, 64, 16
        q, k, v = (jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * 0.3)
                   for _ in range(3))

        def loss(q, k, v, im):
            o = flash_attention(q, k, v, causal=True, window_size=8,
                                block_q=16, block_k=16, impl=im)
            return jnp.sum(o ** 2)

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, impl)
        g_ref = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, "xla")
        for a, b_ in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-4, atol=1e-4)

    def test_window_requires_causal(self, rng):
        from apex_tpu.ops.attention import flash_attention

        q = jnp.zeros((1, 1, 8, 8))
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, q, q, window_size=4)


class TestGQA:
    """Grouped-query attention: kv heads shared across query-head groups
    (beyond the reference). Forward reads shared kv blocks via the index
    map; backward repeats kv and group-sums dk/dv."""

    def _ref(self, q, k, v, causal):
        group = q.shape[1] // k.shape[1]
        kf = np.repeat(np.asarray(k), group, axis=1)
        vf = np.repeat(np.asarray(v), group, axis=1)
        d = q.shape[-1]
        s = np.einsum("bhqd,bhkd->bhqk",
                      np.asarray(q, np.float32) * d ** -0.5,
                      kf.astype(np.float32))
        if causal:
            sq, sk = s.shape[-2:]
            mask = np.arange(sk)[None, :] > np.arange(sq)[:, None]
            s = np.where(mask, -1e30, s)
        e = np.exp(s - s.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        return np.einsum("bhqk,bhkd->bhqd", p, vf.astype(np.float32))

    @pytest.mark.parametrize("hq,hk", [(8, 2), (4, 1), (4, 4)])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_manual(self, rng, impl, hq, hk, causal):
        from apex_tpu.ops.attention import flash_attention

        b, s, d = 2, 64, 16
        q = jnp.asarray(rng.randn(b, hq, s, d).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(b, hk, s, d).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(b, hk, s, d).astype(np.float32) * 0.3)
        out = flash_attention(q, k, v, causal=causal, block_q=32,
                              block_k=32, impl=impl)
        np.testing.assert_allclose(np.asarray(out),
                                   self._ref(q, k, v, causal),
                                   rtol=2e-4, atol=2e-4)

    def test_grads_match_xla(self, rng, impl):
        from apex_tpu.ops.attention import flash_attention

        b, hq, hk, s, d = 1, 4, 2, 32, 16
        q = jnp.asarray(rng.randn(b, hq, s, d).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(b, hk, s, d).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(b, hk, s, d).astype(np.float32) * 0.3)

        def loss(q, k, v, im):
            o = flash_attention(q, k, v, causal=True, block_q=16,
                                block_k=16, impl=im)
            return jnp.sum(o ** 2)

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, impl)
        g_ref = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, "xla")
        assert g[1].shape == k.shape and g[2].shape == v.shape
        for a, b_ in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-4, atol=1e-4)

    def test_bad_head_counts_rejected(self):
        from apex_tpu.ops.attention import flash_attention

        q = jnp.zeros((1, 4, 8, 8))
        k = jnp.zeros((1, 3, 8, 8))
        with pytest.raises(ValueError, match="kv heads"):
            flash_attention(q, k, k)

    def test_xla_fallback_never_materializes_repeated_kv(self, rng):
        """The XLA reference path must compute GQA per kv-head group —
        a materialized repeat of K/V to (b, hq, sk, d) is an hq/hk x
        HBM spike on the path every CPU test and Mosaic-fallback run
        takes (round-2 VERDICT weak#6)."""
        from apex_tpu.ops.attention import flash_attention

        # sq != sk so the repeated-KV shape (b, hq, sk, d) is distinct
        # from every legitimate q-shaped buffer (q, dq, out, dout)
        b, hq, hk, sq, sk, d = 1, 8, 2, 32, 64, 16
        q = jnp.asarray(rng.randn(b, hq, sq, d).astype(np.float32))
        k = jnp.asarray(rng.randn(b, hk, sk, d).astype(np.float32))
        v = jnp.asarray(rng.randn(b, hk, sk, d).astype(np.float32))

        def fwd_bwd(q, k, v):
            def loss(q, k, v):
                o = flash_attention(q, k, v, impl="xla")
                return jnp.sum(o ** 2)
            return jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)

        repeated_kv = (b, hq, sk, d)
        for eqn in jax.make_jaxpr(fwd_bwd)(q, k, v).jaxpr.eqns:
            for var in eqn.outvars:
                shape = getattr(var.aval, "shape", ())
                assert tuple(shape) != repeated_kv, (
                    f"{eqn.primitive} materializes a repeated-KV-shaped "
                    f"array {shape}")


def test_window_with_distinct_bwd_blocks(rng):
    """Sliding-window attention with backward blocks different from the
    forward's: the banded-grid math must derive from the backward's own
    block sizes, not the forward's."""
    b, h, s, d = 1, 2, 128, 16
    q = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * 0.3)
    k = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * 0.3)

    def loss(q, k, v, im):
        o = flash_attention(q, k, v, causal=True, window_size=48,
                            block_q=64, block_k=64,
                            bwd_block_q=32, bwd_block_k=32, impl=im)
        return jnp.sum(o ** 2)

    g_kern = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, "interpret")
    g_xla = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, "xla")
    for a, b_ in zip(g_kern, g_xla):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_fp32_backward_tight_tolerance(rng):
    """The backward casts dS/P to the INPUT dtype before its matmuls
    (bf16 MXU fast path); with fp32 inputs that cast is the identity,
    so the kernel backward must match the XLA path to near machine
    precision — the tight-tolerance regression pinning the fp32 path
    against any future down-cast (round-2 ADVICE #2)."""
    from apex_tpu.ops.attention import flash_attention

    b, h, s, d = 2, 2, 64, 16
    q = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * 0.3)
    k = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * 0.3)

    def loss(q, k, v, im):
        o = flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                            impl=im)
        return jnp.sum(o ** 2)

    g_kern = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, "interpret")
    g_xla = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, "xla")
    for a, b_ in zip(g_kern, g_xla):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-5, atol=2e-5)


def test_dropout_with_positions_rejected(rng):
    """dropout's counter mask is keyed on block-local indices, so a
    chunked-with-positions call would sample a different mask than the
    unchunked equivalent; the combination must be rejected loudly
    (round-2 ADVICE #1)."""
    from apex_tpu.ops.attention import flash_attention

    b, h, s, d = 1, 2, 16, 8
    q = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
    pos = jnp.arange(s, dtype=jnp.int32)[None].repeat(b, 0)
    with pytest.raises(ValueError, match="dropout.*positions|positions"):
        flash_attention(q, q, q, causal=True, dropout_rate=0.1,
                        dropout_rng=jax.random.PRNGKey(0),
                        q_positions=pos, kv_positions=pos)


def test_gqa_bias_and_segments_grads(rng, impl):
    """Covers the GQA bias-grad recompute (k[ib, ih // group]) and
    the GQA + packed-varlen (segment ids) path."""
    from apex_tpu.ops.attention import flash_attention

    b, hq, hk, s, d = 2, 4, 2, 32, 16
    q = jnp.asarray(rng.randn(b, hq, s, d).astype(np.float32) * 0.3)
    k = jnp.asarray(rng.randn(b, hk, s, d).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.randn(b, hk, s, d).astype(np.float32) * 0.3)
    bias = jnp.asarray(rng.randn(1, hq, s, s).astype(np.float32) * 0.1)
    seg = jnp.asarray(
        np.repeat(np.arange(2), s // 2)[None, :].repeat(b, 0), jnp.int32)

    def loss(q, k, v, bias, im):
        o = flash_attention(q, k, v, bias=bias, segment_ids=seg,
                            block_q=16, block_k=16, impl=im)
        return jnp.sum(o ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2, 3))(q, k, v, bias, impl)
    g_ref = jax.grad(loss, argnums=(0, 1, 2, 3))(q, k, v, bias, "xla")
    assert g[3].shape == bias.shape
    for a, b_ in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


class TestDropout:
    """Fused softmax+dropout inside the flash kernel (ref: the
    softmax+dropout fusion in apex/contrib/csrc/multihead_attn/)."""

    def test_keep_rate_and_scaling(self, rng, impl):
        b, h, s, d = 2, 4, 128, 64
        q, k, v = (jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * .3)
                   for _ in range(3))
        rate = 0.3
        out = flash_attention(q, k, v, dropout_rate=rate,
                              dropout_rng=jax.random.PRNGKey(0), impl=impl)
        ref = flash_attention(q, k, v, impl=impl)
        # dropped outputs are unbiased: E[out] = ref; mean over many
        # independent (row, head) masks converges
        np.testing.assert_allclose(float(jnp.mean(out)), float(jnp.mean(ref)),
                                   atol=5e-3)
        assert not np.allclose(np.asarray(out), np.asarray(ref))

    def test_grads_match_xla_same_mask(self, rng, impl):
        """Same seed -> bit-identical mask across impls, so grads agree
        to kernel tolerance (the VERDICT 'grads match XLA-with-same-mask'
        acceptance)."""
        b, h, s, d = 2, 4, 64, 32
        q, k, v = (jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * .3)
                   for _ in range(3))
        key = jax.random.PRNGKey(42)

        def loss(q, k, v, im):
            o = flash_attention(q, k, v, causal=True, dropout_rate=0.2,
                                dropout_rng=key, block_q=32, block_k=32,
                                impl=im)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        l_k = loss(q, k, v, impl)
        l_x = loss(q, k, v, "xla")
        np.testing.assert_allclose(float(l_k), float(l_x), rtol=1e-4)
        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, impl)
        g_ref = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, "xla")
        for a, b_ in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-4, atol=2e-4)

    def test_deterministic_per_seed(self, rng, impl):
        b, h, s, d = 1, 2, 64, 32
        q, k, v = (jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * .3)
                   for _ in range(3))
        f = lambda key: flash_attention(  # noqa: E731
            q, k, v, dropout_rate=0.5, dropout_rng=key, impl=impl)
        a1 = f(jax.random.PRNGKey(1))
        a2 = f(jax.random.PRNGKey(1))
        b2 = f(jax.random.PRNGKey(2))
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
        assert not np.allclose(np.asarray(a1), np.asarray(b2))

    def test_gqa_dropout_grads(self, rng, impl):
        """Dropout mask uses the flat q-head index in the grouped dkv
        grid — GQA must agree with the (repeated-kv) XLA path."""
        b, hq, hk, s, d = 2, 4, 2, 64, 32
        q = jnp.asarray(rng.randn(b, hq, s, d).astype(np.float32) * .3)
        k = jnp.asarray(rng.randn(b, hk, s, d).astype(np.float32) * .3)
        v = jnp.asarray(rng.randn(b, hk, s, d).astype(np.float32) * .3)
        key = jax.random.PRNGKey(3)

        def loss(q, k, v, im):
            o = flash_attention(q, k, v, causal=True, dropout_rate=0.15,
                                dropout_rng=key, block_q=32, block_k=32,
                                impl=im)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, impl)
        g_ref = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, "xla")
        for a, b_ in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-4, atol=2e-4)


class TestReturnLse:
    """lse as a differentiable second output — the merge signal for
    ring/blockwise attention (chunk pairs combine via logaddexp)."""

    def test_lse_matches_xla(self, rng, impl):
        b, h, s, d = 2, 4, 128, 64
        q, k, v = (jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * .3)
                   for _ in range(3))
        o_k, lse_k = flash_attention(q, k, v, causal=True, impl=impl,
                                     return_lse=True, block_q=64, block_k=64)
        o_x, lse_x = flash_attention(q, k, v, causal=True, impl="xla",
                                     return_lse=True)
        np.testing.assert_allclose(np.asarray(o_k), np.asarray(o_x),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(lse_k), np.asarray(lse_x),
                                   rtol=1e-4, atol=1e-4)

    def test_lse_grads_match_xla(self, rng, impl):
        """A loss using BOTH outputs exercises the extended VJP
        (ds += p * g_lse)."""
        b, h, s, d = 2, 2, 64, 32
        q, k, v = (jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * .3)
                   for _ in range(3))

        def loss(q, k, v, im):
            o, lse = flash_attention(q, k, v, causal=True, impl=im,
                                     return_lse=True, block_q=32,
                                     block_k=32)
            return jnp.sum(o.astype(jnp.float32) ** 2) + jnp.sum(
                jnp.sin(lse))

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, impl)
        g_ref = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, "xla")
        for a, b_ in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-4, atol=2e-4)

    def test_chunked_merge_equals_full(self, rng, impl):
        """Split KV into chunks, attend per chunk with return_lse, merge
        with logaddexp: must equal full attention — the ring-attention
        combine identity."""
        b, h, s, d = 1, 2, 128, 32
        q, k, v = (jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * .3)
                   for _ in range(3))
        full = flash_attention(q, k, v, impl=impl, block_q=32, block_k=32)
        halves = [(flash_attention(q, k[:, :, i:i + 64], v[:, :, i:i + 64],
                                   impl=impl, return_lse=True,
                                   block_q=32, block_k=32))
                  for i in (0, 64)]
        (o1, l1), (o2, l2) = halves
        lse = jnp.logaddexp(l1, l2)
        merged = (o1.astype(jnp.float32) * jnp.exp(l1 - lse)[..., None]
                  + o2.astype(jnp.float32) * jnp.exp(l2 - lse)[..., None])
        np.testing.assert_allclose(np.asarray(merged), np.asarray(full),
                                   rtol=2e-4, atol=2e-4)

    def test_masked_rows_lse_neg_inf(self, rng, impl):
        """Fully-masked rows carry lse=NEG_INF — zero mass under the
        merge — and grads stay finite."""
        from apex_tpu.ops.attention import NEG_INF

        b, h, s, d = 1, 2, 64, 32
        q, k, v = (jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * .3)
                   for _ in range(3))
        seg = jnp.zeros((b, s), jnp.int32).at[:, :32].set(1)
        kseg = jnp.ones((b, s), jnp.int32) * 2    # no kv matches any q
        o, lse = flash_attention(q, k, v, segment_ids=seg,
                                 kv_segment_ids=kseg, impl=impl,
                                 return_lse=True, block_q=32, block_k=32)
        assert np.all(np.asarray(lse) <= NEG_INF * 0.5)
        assert np.all(np.asarray(o) == 0.0)
        g = jax.grad(lambda q: jnp.sum(flash_attention(
            q, k, v, segment_ids=seg, kv_segment_ids=kseg, impl=impl,
            return_lse=True, block_q=32, block_k=32)[0] ** 2))(q)
        assert np.isfinite(np.asarray(g)).all()


class TestPositions:
    """Dynamic global positions for chunked causal masking — the mask
    basis for ring/blockwise attention chunks."""

    def test_positions_equal_static_causal(self, rng, impl):
        b, h, s, d = 1, 2, 64, 32
        q, k, v = (jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * .3)
                   for _ in range(3))
        pos = jnp.arange(s, dtype=jnp.int32)
        o_pos = flash_attention(q, k, v, causal=True, q_positions=pos,
                                kv_positions=pos, impl=impl,
                                block_q=32, block_k=32)
        o_stat = flash_attention(q, k, v, causal=True, impl=impl,
                                 block_q=32, block_k=32)
        np.testing.assert_allclose(np.asarray(o_pos), np.asarray(o_stat),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.slow
    def test_chunked_causal_merge(self, rng, impl):
        """KV chunks attended with global positions + lse merge must
        equal full causal attention — including grads through the
        positions-masked backward."""
        b, h, s, d = 1, 2, 128, 32
        q, k, v = (jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * .3)
                   for _ in range(3))
        pos = jnp.arange(s, dtype=jnp.int32)

        def merged(q, k, v, im):
            outs = []
            for i in (0, 64):
                o, l = flash_attention(
                    q, k[:, :, i:i + 64], v[:, :, i:i + 64], causal=True,
                    q_positions=pos, kv_positions=pos[i:i + 64],
                    return_lse=True, impl=im, block_q=32, block_k=32)
                outs.append((o.astype(jnp.float32), l))
            (o1, l1), (o2, l2) = outs
            lse = jnp.logaddexp(l1, l2)
            return (o1 * jnp.exp(l1 - lse)[..., None]
                    + o2 * jnp.exp(l2 - lse)[..., None])

        full = flash_attention(q, k, v, causal=True, impl=impl,
                               block_q=32, block_k=32)
        np.testing.assert_allclose(
            np.asarray(merged(q, k, v, impl)), np.asarray(full),
            rtol=2e-4, atol=2e-4)

        g = jax.grad(lambda q: jnp.sum(merged(q, k, v, impl) ** 2))(q)
        g_ref = jax.grad(lambda q: jnp.sum(flash_attention(
            q, k, v, causal=True, impl=impl, block_q=32, block_k=32
        ).astype(jnp.float32) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=2e-4, atol=2e-4)

    def test_positions_validation(self, rng):
        q = jnp.zeros((1, 2, 16, 8))
        pos = jnp.arange(16, dtype=jnp.int32)
        with pytest.raises(ValueError, match="together"):
            flash_attention(q, q, q, causal=True, q_positions=pos)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, q, q, q_positions=pos, kv_positions=pos)

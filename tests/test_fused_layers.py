"""Fused layer tests — kernel-vs-reference parity incl. gradients.

Mirrors ref tests/L0/run_fused_layer_norm/test_fused_layer_norm.py,
tests/L0/run_transformer/test_fused_softmax.py, test_fused_rope.py,
apex/contrib/test/xentropy, fused_dense, mlp.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.fused_dense import FusedDense, FusedDenseGeluDense
from apex_tpu.mlp import MLP
from apex_tpu.normalization import FusedLayerNorm, FusedRMSNorm
from apex_tpu.ops import (
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_2d,
    fused_apply_rotary_pos_emb_cached,
    fused_apply_rotary_pos_emb_thd,
    fused_layer_norm,
    fused_rms_norm,
    generic_scaled_masked_softmax,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
    softmax_cross_entropy_loss,
)


# ---------------------------------------------------------------------------
# layer norm / rms norm
# ---------------------------------------------------------------------------


def ref_layer_norm(x, w, b, eps):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    y = (x - mu) / np.sqrt(var + eps)
    if w is not None:
        y = y * w
    if b is not None:
        y = y + b
    return y


class TestFusedLayerNorm:
    @pytest.mark.parametrize("affine,bias", [(True, True), (True, False), (False, False)])
    def test_forward(self, rng, impl, affine, bias):
        x = rng.randn(12, 256).astype(np.float32)
        w = rng.randn(256).astype(np.float32) if affine else None
        b = rng.randn(256).astype(np.float32) if bias else None
        y = fused_layer_norm(
            jnp.asarray(x),
            jnp.asarray(w) if affine else None,
            jnp.asarray(b) if bias else None,
            eps=1e-5, impl=impl,
        )
        np.testing.assert_allclose(
            np.asarray(y), ref_layer_norm(x, w, b, 1e-5), rtol=2e-5, atol=1e-5
        )

    def test_grad_matches_xla_autodiff(self, rng, impl):
        x = jnp.asarray(rng.randn(8, 128), jnp.float32)
        w = jnp.asarray(rng.randn(128), jnp.float32)
        b = jnp.asarray(rng.randn(128), jnp.float32)

        def fused_loss(x, w, b):
            return jnp.sum(fused_layer_norm(x, w, b, impl=impl) ** 2)

        def ref_loss(x, w, b):
            mu = x.mean(-1, keepdims=True)
            var = jnp.mean((x - mu) ** 2, -1, keepdims=True)
            return jnp.sum(((x - mu) * jax.lax.rsqrt(var + 1e-5) * w + b) ** 2)

        g1 = jax.grad(fused_loss, argnums=(0, 1, 2))(x, w, b)
        g2 = jax.grad(ref_loss, argnums=(0, 1, 2))(x, w, b)
        for a, e in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-4)

    def test_mixed_dtype_bf16_in_fp32_params(self, rng, impl):
        x = jnp.asarray(rng.randn(16, 128), jnp.bfloat16)
        w = jnp.ones((128,), jnp.float32)
        b = jnp.zeros((128,), jnp.float32)
        y = fused_layer_norm(x, w, b, impl=impl)
        assert y.dtype == jnp.bfloat16

    def test_multidim_normalized_shape(self, rng, impl):
        x = rng.randn(6, 4, 32).astype(np.float32)
        w = rng.randn(4, 32).astype(np.float32)
        y = fused_layer_norm(jnp.asarray(x), jnp.asarray(w), None, impl=impl)
        flat = x.reshape(6, -1)
        expected = ref_layer_norm(flat, w.reshape(-1), None, 1e-5).reshape(6, 4, 32)
        np.testing.assert_allclose(np.asarray(y), expected, rtol=2e-5, atol=1e-5)

    def test_module(self, rng):
        mod = FusedLayerNorm(normalized_shape=64, impl="xla")
        x = jnp.asarray(rng.randn(4, 64), jnp.float32)
        params = mod.init(jax.random.PRNGKey(0), x)
        y = mod.apply(params, x)
        assert y.shape == (4, 64)
        assert params["params"]["scale"].shape == (64,)


class TestFusedRMSNorm:
    def test_forward(self, rng, impl):
        x = rng.randn(10, 192).astype(np.float32)
        w = rng.randn(192).astype(np.float32)
        y = fused_rms_norm(jnp.asarray(x), jnp.asarray(w), eps=1e-6, impl=impl)
        rms = np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(np.asarray(y), x / rms * w, rtol=2e-5, atol=1e-5)

    def test_grad(self, rng, impl):
        x = jnp.asarray(rng.randn(8, 128), jnp.float32)
        w = jnp.asarray(rng.randn(128), jnp.float32)

        def fused_loss(x, w):
            return jnp.sum(fused_rms_norm(x, w, eps=1e-6, impl=impl) ** 2)

        def ref_loss(x, w):
            rms = jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6)
            return jnp.sum((x * rms * w) ** 2)

        g1 = jax.grad(fused_loss, argnums=(0, 1))(x, w)
        g2 = jax.grad(ref_loss, argnums=(0, 1))(x, w)
        for a, e in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-4)

    def test_module(self, rng):
        mod = FusedRMSNorm(normalized_shape=64, impl="xla")
        x = jnp.asarray(rng.randn(4, 64), jnp.float32)
        params = mod.init(jax.random.PRNGKey(0), x)
        assert mod.apply(params, x).shape == (4, 64)


# ---------------------------------------------------------------------------
# fused softmax family
# ---------------------------------------------------------------------------


def np_softmax(x, axis=-1):
    m = x.max(axis=axis, keepdims=True)
    e = np.exp(x - m)
    return e / e.sum(axis=axis, keepdims=True)


class TestFusedSoftmax:
    def test_scaled(self, rng, impl):
        x = rng.randn(2, 4, 16, 128).astype(np.float32)
        y = scaled_softmax(jnp.asarray(x), 0.5, impl)
        np.testing.assert_allclose(np.asarray(y), np_softmax(0.5 * x), rtol=1e-5, atol=1e-6)

    def test_causal(self, rng, impl):
        x = rng.randn(6, 32, 32).astype(np.float32)
        y = scaled_upper_triang_masked_softmax(jnp.asarray(x), 2.0, impl)
        mask = np.triu(np.ones((32, 32), bool), k=1)
        ref = np_softmax(np.where(mask, -1e30, 2.0 * x))
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-6)
        # strictly-upper entries are exactly zero
        assert float(np.abs(np.asarray(y)[:, 0, 1:]).max()) == 0.0

    def test_masked(self, rng, impl):
        x = rng.randn(2, 4, 16, 64).astype(np.float32)
        mask = rng.rand(2, 1, 16, 64) > 0.7
        y = scaled_masked_softmax(jnp.asarray(x), jnp.asarray(mask), 0.8, impl)
        ref = np_softmax(0.8 * x + np.where(mask, -10000.0, 0.0))
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-6)

    def test_masked_broadcast_batch1(self, rng, impl):
        x = rng.randn(4, 2, 8, 64).astype(np.float32)
        mask = rng.rand(1, 1, 8, 64) > 0.5
        y = scaled_masked_softmax(jnp.asarray(x), jnp.asarray(mask), 1.0, impl)
        ref = np_softmax(x + np.where(mask, -10000.0, 0.0))
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-6)

    def test_generic_fallback(self, rng):
        x = rng.randn(2, 3, 8, 32).astype(np.float32)
        mask = rng.rand(2, 3, 8, 32) > 0.5  # full-head mask -> generic path
        y = generic_scaled_masked_softmax(jnp.asarray(x), jnp.asarray(mask), 1.0)
        ref = np_softmax(x + np.where(mask, -10000.0, 0.0))
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-6)

    def test_causal_grad(self, rng, impl):
        x = jnp.asarray(rng.randn(2, 16, 16), jnp.float32)
        t = jnp.asarray(rng.randn(2, 16, 16), jnp.float32)

        def fused_loss(x):
            return jnp.sum(scaled_upper_triang_masked_softmax(x, 1.3, impl) * t)

        def ref_loss(x):
            row = jax.lax.broadcasted_iota(jnp.int32, (1, 16, 16), 1)
            col = jax.lax.broadcasted_iota(jnp.int32, (1, 16, 16), 2)
            s = jnp.where(col > row, -1e30, 1.3 * x)
            return jnp.sum(jax.nn.softmax(s, axis=-1) * t)

        g1 = jax.grad(fused_loss)(x)
        g2 = jax.grad(ref_loss)(x)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-4, atol=1e-5)

    def test_scaled_grad(self, rng, impl):
        x = jnp.asarray(rng.randn(4, 8, 64), jnp.float32)
        t = jnp.asarray(rng.randn(4, 8, 64), jnp.float32)

        def fused_loss(x):
            return jnp.sum(scaled_softmax(x, 0.7, impl) * t)

        def ref_loss(x):
            return jnp.sum(jax.nn.softmax(0.7 * x, axis=-1) * t)

        np.testing.assert_allclose(
            np.asarray(jax.grad(fused_loss)(x)),
            np.asarray(jax.grad(ref_loss)(x)),
            rtol=1e-4, atol=1e-5,
        )


# ---------------------------------------------------------------------------
# RoPE
# ---------------------------------------------------------------------------


def np_rope(t, freqs):
    rot = freqs.shape[-1]
    cos, sin = np.cos(freqs), np.sin(freqs)
    tr, tp = t[..., :rot], t[..., rot:]
    half = rot // 2
    rh = np.concatenate([-tr[..., half:], tr[..., :half]], -1)
    return np.concatenate([tr * cos + rh * sin, tp], -1)


class TestFusedRope:
    def test_sbhd(self, rng, impl):
        s, b, h, d = 16, 2, 4, 32
        t = rng.randn(s, b, h, d).astype(np.float32)
        freqs = rng.randn(s, 1, 1, 24).astype(np.float32)
        y = fused_apply_rotary_pos_emb(jnp.asarray(t), jnp.asarray(freqs),
                                       impl=impl)
        np.testing.assert_allclose(np.asarray(y), np_rope(t, freqs), rtol=1e-5, atol=1e-5)

    def test_sbhd_grad(self, rng, impl):
        # bwd = fwd with -sin in both impls (ref fused_rope.py backward)
        s, b, h, d = 8, 2, 2, 32
        t = jnp.asarray(rng.randn(s, b, h, d).astype(np.float32))
        freqs = jnp.asarray(rng.randn(s, 1, 1, d).astype(np.float32))

        def loss(t_, im):
            return jnp.sum(fused_apply_rotary_pos_emb(t_, freqs, impl=im) ** 2)

        g = jax.grad(lambda t_: loss(t_, impl))(t)
        g_ref = jax.grad(lambda t_: loss(t_, "xla"))(t)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=1e-5, atol=1e-5)

    def test_cached(self, rng, impl):
        s, b, h, d = 8, 2, 2, 16
        t = rng.randn(s, b, h, d).astype(np.float32)
        freqs = rng.randn(s, 1, 1, d).astype(np.float32)
        y1 = fused_apply_rotary_pos_emb(jnp.asarray(t), jnp.asarray(freqs),
                                        impl=impl)
        y2 = fused_apply_rotary_pos_emb_cached(
            jnp.asarray(t), jnp.cos(jnp.asarray(freqs)), jnp.sin(jnp.asarray(freqs)),
            impl=impl,
        )
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=1e-6)

    def test_cached_per_batch_cos(self, rng, impl):
        # cos/sin with non-unit interior dims can't use the row-tiled
        # kernel; every impl must broadcast through the XLA path
        s, b, h, d = 4, 2, 3, 8
        t = rng.randn(s, b, h, d).astype(np.float32)
        freqs = rng.randn(s, b, 1, d).astype(np.float32)
        y = fused_apply_rotary_pos_emb_cached(
            jnp.asarray(t), jnp.cos(jnp.asarray(freqs)),
            jnp.sin(jnp.asarray(freqs)), impl=impl)
        np.testing.assert_allclose(np.asarray(y), np_rope(t, freqs),
                                   rtol=1e-5, atol=1e-5)

    def test_thd_restarts_positions(self, rng, impl):
        # two sequences of length 6 and 10 packed; positions restart
        d = 8
        freqs = rng.randn(16, 1, 1, d).astype(np.float32)
        t = rng.randn(16, 2, d).astype(np.float32)
        cu = jnp.asarray([0, 6, 16], jnp.int32)
        y = fused_apply_rotary_pos_emb_thd(jnp.asarray(t), cu, jnp.asarray(freqs),
                                           impl=impl)
        # sequence 0: positions 0..5 ; sequence 1: positions 0..9
        t_sbhd0 = t[:6][:, None]          # (6, 1, 2, d)
        t_sbhd1 = t[6:][:, None]
        e0 = np_rope(t_sbhd0, freqs[:6])
        e1 = np_rope(t_sbhd1, freqs[:10])
        np.testing.assert_allclose(np.asarray(y[:6]), e0[:, 0], rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(y[6:]), e1[:, 0], rtol=1e-5, atol=1e-5)

    def test_2d(self, rng):
        b, H, W, h, d = 2, 4, 4, 2, 16
        t = rng.randn(b, H * W, h, d).astype(np.float32)
        half = d // 2
        fh = rng.randn(H, half).astype(np.float32)
        fw = rng.randn(W, half).astype(np.float32)
        y = fused_apply_rotary_pos_emb_2d(
            jnp.asarray(t), H, W,
            jnp.cos(jnp.asarray(fh)), jnp.sin(jnp.asarray(fh)),
            jnp.cos(jnp.asarray(fw)), jnp.sin(jnp.asarray(fw)),
        )
        tt = t.reshape(b, H, W, h, d)
        eh = np_rope(tt[..., :half], fh[None, :, None, None, :])
        ew = np_rope(tt[..., half:], fw[None, None, :, None, :])
        expected = np.concatenate([eh, ew], -1).reshape(b, H * W, h, d)
        np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-5, atol=1e-5)

    def test_grad_is_inverse_rotation(self, rng):
        # standard RoPE: freqs are the same angle duplicated across the
        # two halves (Megatron cat((freqs, freqs), -1)) — the reference
        # kernel's backward-via-neg-sin identity assumes this layout
        s, b, h, d = 8, 1, 2, 16
        t = jnp.asarray(rng.randn(s, b, h, d), jnp.float32)
        half = rng.randn(s, 1, 1, d // 2).astype(np.float32)
        freqs = jnp.asarray(np.concatenate([half, half], -1), jnp.float32)

        def fused_loss(t):
            return jnp.sum(fused_apply_rotary_pos_emb(t, freqs) ** 2)

        def ref_loss(t):
            cos, sin = jnp.cos(freqs), jnp.sin(freqs)
            half = d // 2
            rh = jnp.concatenate([-t[..., half:], t[..., :half]], -1)
            return jnp.sum((t * cos + rh * sin) ** 2)

        np.testing.assert_allclose(
            np.asarray(jax.grad(fused_loss)(t)),
            np.asarray(jax.grad(ref_loss)(t)),
            rtol=1e-4, atol=1e-5,
        )


# ---------------------------------------------------------------------------
# xentropy
# ---------------------------------------------------------------------------


class TestXentropy:
    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_forward(self, rng, impl, smoothing):
        logits = rng.randn(16, 512).astype(np.float32)
        labels = rng.randint(0, 512, (16,))
        loss = softmax_cross_entropy_loss(
            jnp.asarray(logits), jnp.asarray(labels), smoothing, impl
        )
        lse = np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1)) + logits.max(-1)
        nll = lse - logits[np.arange(16), labels]
        expected = (1 - smoothing) * nll + smoothing * (lse - logits.mean(-1))
        np.testing.assert_allclose(np.asarray(loss), expected, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("smoothing", [0.0, 0.2])
    def test_grad(self, rng, impl, smoothing):
        logits = jnp.asarray(rng.randn(8, 256), jnp.float32)
        labels = jnp.asarray(rng.randint(0, 256, (8,)), jnp.int32)

        def fused_loss(l):
            return jnp.sum(softmax_cross_entropy_loss(l, labels, smoothing, impl))

        def ref_loss(l):
            lse = jax.scipy.special.logsumexp(l, axis=-1)
            nll = lse - jnp.take_along_axis(l, labels[:, None], 1)[:, 0]
            smooth = lse - jnp.mean(l, -1)
            return jnp.sum((1 - smoothing) * nll + smoothing * smooth)

        np.testing.assert_allclose(
            np.asarray(jax.grad(fused_loss)(logits)),
            np.asarray(jax.grad(ref_loss)(logits)),
            rtol=1e-4, atol=1e-5,
        )

    def test_multidim(self, rng, impl):
        logits = jnp.asarray(rng.randn(2, 8, 128), jnp.float32)
        labels = jnp.asarray(rng.randint(0, 128, (2, 8)), jnp.int32)
        loss = softmax_cross_entropy_loss(logits, labels, 0.0, impl)
        assert loss.shape == (2, 8)


# ---------------------------------------------------------------------------
# fused dense / MLP
# ---------------------------------------------------------------------------


class TestFusedDenseMLP:
    def test_fused_dense(self, rng):
        mod = FusedDense(features=32)
        x = jnp.asarray(rng.randn(4, 16), jnp.float32)
        params = mod.init(jax.random.PRNGKey(0), x)
        y = mod.apply(params, x)
        w = params["params"]["kernel"]
        b = params["params"]["bias"]
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(x) @ np.asarray(w).T + np.asarray(b),
            rtol=1e-5, atol=1e-5,
        )

    def test_gelu_dense(self, rng):
        mod = FusedDenseGeluDense(intermediate_features=64, out_features=16)
        x = jnp.asarray(rng.randn(4, 16), jnp.float32)
        params = mod.init(jax.random.PRNGKey(0), x)
        assert mod.apply(params, x).shape == (4, 16)

    def test_mlp_matches_manual(self, rng):
        mod = MLP(mlp_sizes=(16, 32, 8), activation="relu")
        x = jnp.asarray(rng.randn(4, 16), jnp.float32)
        params = mod.init(jax.random.PRNGKey(0), x)
        y = mod.apply(params, x)
        p = params["params"]
        h = np.maximum(
            np.asarray(x) @ np.asarray(p["kernel_0"]).T + np.asarray(p["bias_0"]), 0
        )
        expected = h @ np.asarray(p["kernel_1"]).T + np.asarray(p["bias_1"])
        np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-5, atol=1e-5)

    def test_mlp_sigmoid_nobias(self, rng):
        mod = MLP(mlp_sizes=(8, 16, 4), activation="sigmoid", use_bias=False)
        x = jnp.asarray(rng.randn(2, 8), jnp.float32)
        params = mod.init(jax.random.PRNGKey(0), x)
        assert mod.apply(params, x).shape == (2, 4)

    def test_mlp_grads_flow(self, rng):
        mod = MLP(mlp_sizes=(8, 16, 4))
        x = jnp.asarray(rng.randn(2, 8), jnp.float32)
        params = mod.init(jax.random.PRNGKey(0), x)

        def loss(p):
            return jnp.sum(mod.apply(p, x) ** 2)

        g = jax.grad(loss)(params)
        assert float(jnp.abs(g["params"]["kernel_0"]).sum()) > 0


class TestFastLayerNormShim:
    """ref apex/contrib/layer_norm — name surface over the same kernels."""

    def test_fast_layer_norm_shim(self, rng):
        from apex_tpu.contrib.layer_norm import FastLayerNorm

        ln = FastLayerNorm(64, eps=1e-5)
        x = jnp.asarray(rng.randn(4, 64).astype(np.float32))
        params = ln.init(jax.random.PRNGKey(0), x)
        y = ln.apply(params, x)
        ref = (x - x.mean(-1, keepdims=True)) / jnp.sqrt(
            x.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)


class TestSoftmaxTiling:
    """Mosaic-legality guard (same class as the xentropy fix): ragged
    row counts and huge trailing dims must fall back to XLA instead of
    emitting sub-8 row tiles."""

    @pytest.mark.parametrize("shape", [(7, 12, 512), (2, 3, 1001, 260)])
    def test_awkward_shapes_match_xla(self, rng, impl, shape):
        from apex_tpu.ops import (
            scaled_softmax,
            scaled_upper_triang_masked_softmax,
        )

        x = jnp.asarray(rng.randn(*shape).astype(np.float32))
        got = scaled_softmax(x, 0.7, impl=impl)
        want = scaled_softmax(x, 0.7, impl="xla")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
        g = jax.grad(lambda x: jnp.sum(
            scaled_softmax(x, 0.7, impl=impl) ** 2))(x)
        assert np.isfinite(np.asarray(g)).all()
        if len(shape) == 3:
            got = scaled_upper_triang_masked_softmax(x, 0.7, impl=impl)
            want = scaled_upper_triang_masked_softmax(x, 0.7, impl="xla")
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-5, atol=1e-6)

"""Tensor-parallel layer tests.

Mirrors ref tests/L0/run_transformer/test_layers.py (TP layers vs dense
reference), test_cross_entropy.py (sharded CE vs full CE),
test_random.py (RNG tracker).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    RngStatesTracker,
    VocabParallelEmbedding,
    checkpoint_wrapper,
    column_bias_spec,
    column_kernel_spec,
    model_parallel_rng_key,
    row_bias_spec,
    row_kernel_spec,
    split_tensor_along_last_dim,
    vocab_embedding_spec,
    vocab_parallel_cross_entropy,
)

TP = 4


@pytest.fixture(autouse=True)
def mesh():
    m = ps.initialize_model_parallel(TP, 1)
    yield m
    ps.destroy_model_parallel()


class TestColumnParallelLinear:
    def test_matches_dense(self, mesh, rng):
        layer = ColumnParallelLinear(output_size=32, gather_output=True)
        x = jnp.asarray(rng.randn(6, 16), jnp.float32)
        params = layer.init(jax.random.PRNGKey(0), x)
        dense = layer.apply(params, x)  # outside shard_map: plain dense
        assert params["params"]["kernel"].shape == (32, 16)

        sharded = jax.jit(
            shard_map(
                lambda p, x: layer.apply(p, x),
                mesh=mesh,
                in_specs=(
                    {"params": {"kernel": column_kernel_spec(),
                                "bias": column_bias_spec()}},
                    P(),
                ),
                out_specs=P(),
                check_vma=False,
            )
        )(params, x)
        np.testing.assert_allclose(
            np.asarray(sharded), np.asarray(dense), rtol=1e-5, atol=1e-5
        )

    def test_no_gather_keeps_shard(self, mesh, rng):
        layer = ColumnParallelLinear(output_size=32, gather_output=False)
        x = jnp.asarray(rng.randn(6, 16), jnp.float32)
        params = layer.init(jax.random.PRNGKey(0), x)
        out = jax.jit(
            shard_map(
                lambda p, x: layer.apply(p, x),
                mesh=mesh,
                in_specs=(
                    {"params": {"kernel": column_kernel_spec(),
                                "bias": column_bias_spec()}},
                    P(),
                ),
                out_specs=P(None, "tensor"),
                check_vma=False,
            )
        )(params, x)
        dense = layer.apply(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense), rtol=1e-5, atol=1e-5)

    def test_grads_match_dense(self, mesh, rng):
        layer = ColumnParallelLinear(output_size=32, gather_output=True)
        x = jnp.asarray(rng.randn(6, 16), jnp.float32)
        params = layer.init(jax.random.PRNGKey(0), x)
        t = jnp.asarray(rng.randn(6, 32), jnp.float32)

        def dense_loss(p):
            return jnp.sum(layer.apply(p, x) * t)

        # per-rank partial-loss convention: each rank's (identical) loss
        # copy emitted and summed so every rank's cotangent is 1 — the
        # boundary form under which sharded-param grads equal Megatron's
        def per_rank(p, x):
            return jnp.sum(layer.apply(p, x) * t)[None]

        inner = shard_map(
            per_rank, mesh=mesh,
            in_specs=(
                {"params": {"kernel": column_kernel_spec(),
                            "bias": column_bias_spec()}},
                P(),
            ),
            out_specs=P("tensor"),
            check_vma=False,
        )

        def sharded_loss(p, x):
            # summing TP identical copies seeds cotangent 1 on every
            # rank; sharded-param grads then equal the dense grads of
            # ONE loss (the gather VJP routes each rank its own chunk)
            return jnp.sum(inner(p, x))

        g1 = jax.jit(jax.grad(lambda p: sharded_loss(p, x)))(params)
        g2 = jax.grad(dense_loss)(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            ),
            g1, g2,
        )


class TestRowParallelLinear:
    def test_matches_dense(self, mesh, rng):
        layer = RowParallelLinear(output_size=24, input_is_parallel=False)
        x = jnp.asarray(rng.randn(6, 32), jnp.float32)
        params = layer.init(jax.random.PRNGKey(0), x)
        dense = layer.apply(params, x)
        assert params["params"]["kernel"].shape == (24, 32)

        sharded = jax.jit(
            shard_map(
                lambda p, x: layer.apply(p, x),
                mesh=mesh,
                in_specs=(
                    {"params": {"kernel": row_kernel_spec(),
                                "bias": row_bias_spec()}},
                    P(),
                ),
                out_specs=P(),
                check_vma=False,
            )
        )(params, x)
        np.testing.assert_allclose(
            np.asarray(sharded), np.asarray(dense), rtol=1e-5, atol=1e-5
        )

    def test_input_parallel_path(self, mesh, rng):
        layer = RowParallelLinear(output_size=24, input_is_parallel=True)
        x = jnp.asarray(rng.randn(6, 32), jnp.float32)
        # init with a LOCAL-width input but full weight comes from config?
        # kernel width derives from local x width * tp inside shard_map;
        # init outside with full x gives full kernel (in_full = 32 * 1)
        params = layer.init(jax.random.PRNGKey(0), x)
        sharded = jax.jit(
            shard_map(
                lambda p, x: layer.apply(p, x),
                mesh=mesh,
                in_specs=(
                    {"params": {"kernel": row_kernel_spec(),
                                "bias": row_bias_spec()}},
                    P(None, "tensor"),   # input arrives already split
                ),
                out_specs=P(),
                check_vma=False,
            )
        )(params, x)
        dense = layer.apply(params, x)
        np.testing.assert_allclose(
            np.asarray(sharded), np.asarray(dense), rtol=1e-5, atol=1e-5
        )


class TestRowParallelReducePrecision:
    def test_row_parallel_fp32_reduce(self, rng):
        """Pins the TP-reduce precision decision (VERDICT r1 weak #8):
        partial sums cross the psum in fp32 by default and are rounded
        to bf16 once, after the collective. At tp=8 this must be
        measurably closer to the fp64 ground truth than reducing
        bf16-rounded partials (the reference's behavior,
        reduce_in_fp32=False)."""
        ps.destroy_model_parallel()
        mesh8 = ps.initialize_model_parallel(8, 1)
        try:
            n, d_in, d_out = 64, 512, 32
            x64 = rng.randn(n, d_in)
            w64 = rng.randn(d_out, d_in) / np.sqrt(d_in)
            truth = x64 @ w64.T
            x = jnp.asarray(x64, jnp.bfloat16)

            def run(reduce_in_fp32):
                layer = RowParallelLinear(
                    output_size=d_out, input_is_parallel=False,
                    use_bias=False, reduce_in_fp32=reduce_in_fp32,
                    param_dtype=jnp.bfloat16,
                )
                params = {"params": {"kernel": jnp.asarray(w64, jnp.bfloat16)}}
                out = jax.jit(
                    shard_map(
                        lambda p, x: layer.apply(p, x),
                        mesh=mesh8,
                        in_specs=({"params": {"kernel": row_kernel_spec()}},
                                  P()),
                        out_specs=P(), check_vma=False,
                    )
                )(params, x)
                return np.asarray(out, np.float64)

            err_fp32 = np.abs(run(True) - truth).mean()
            err_bf16 = np.abs(run(False) - truth).mean()
            # same inputs, so both errors are dominated by the bf16
            # inputs; the fp32 reduction must not ADD rounding on top
            # (strictly better on average at tp=8) ...
            assert err_fp32 < err_bf16, (err_fp32, err_bf16)
            # ... and must match the round-once dense fp32 computation
            # to bf16 resolution
            dense = (jnp.asarray(x64, jnp.bfloat16).astype(jnp.float32)
                     @ jnp.asarray(w64, jnp.bfloat16).astype(jnp.float32).T)
            np.testing.assert_allclose(
                run(True), np.asarray(dense.astype(jnp.bfloat16), np.float64),
                rtol=0.02, atol=0.02)
        finally:
            ps.destroy_model_parallel()


class TestColumnRowComposition:
    def test_mlp_block(self, mesh, rng):
        """Column(no-gather) -> gelu -> Row(input-parallel): the Megatron
        MLP pattern with exactly one allreduce (ref test_layers.py)."""
        col = ColumnParallelLinear(output_size=64, gather_output=False)
        row = RowParallelLinear(output_size=16, input_is_parallel=True)
        x = jnp.asarray(rng.randn(6, 16), jnp.float32)
        pc = col.init(jax.random.PRNGKey(0), x)
        h_full = col.apply(pc, x)
        pr = row.init(jax.random.PRNGKey(1), jax.nn.gelu(h_full))
        expected = row.apply(pr, jax.nn.gelu(h_full))

        def block(pc, pr, x):
            h = col.apply(pc, x)
            return row.apply(pr, jax.nn.gelu(h))

        out = jax.jit(
            shard_map(
                block, mesh=mesh,
                in_specs=(
                    {"params": {"kernel": column_kernel_spec(),
                                "bias": column_bias_spec()}},
                    {"params": {"kernel": row_kernel_spec(),
                                "bias": row_bias_spec()}},
                    P(),
                ),
                out_specs=P(),
                check_vma=False,
            )
        )(pc, pr, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=1e-4, atol=1e-4
        )

    def test_sequence_parallel_roundtrip(self, mesh, rng):
        """SP: Column gathers seq, Row reduce-scatters seq — output stays
        sequence-sharded (ref layers.py:293-306,355-363)."""
        col = ColumnParallelLinear(
            output_size=64, gather_output=False, sequence_parallel_enabled=True
        )
        row = RowParallelLinear(
            output_size=16, input_is_parallel=True,
            sequence_parallel_enabled=True,
        )
        seq = 8 * TP
        x = jnp.asarray(rng.randn(seq, 16), jnp.float32)
        pc = col.init(jax.random.PRNGKey(0), x)
        pr = row.init(
            jax.random.PRNGKey(1),
            jax.nn.gelu(col.apply(pc, x)),
        )
        expected = row.apply(pr, jax.nn.gelu(col.apply(pc, x)))

        def block(pc, pr, x):
            h = col.apply(pc, x)
            return row.apply(pr, jax.nn.gelu(h))

        out = jax.jit(
            shard_map(
                block, mesh=mesh,
                in_specs=(
                    {"params": {"kernel": column_kernel_spec(),
                                "bias": column_bias_spec()}},
                    {"params": {"kernel": row_kernel_spec(),
                                "bias": row_bias_spec()}},
                    P("tensor", None),   # sequence-sharded activations
                ),
                out_specs=P("tensor", None),
                check_vma=False,
            )
        )(pc, pr, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=1e-4, atol=1e-4
        )


class TestVocabParallelEmbedding:
    def test_matches_dense(self, mesh, rng):
        emb = VocabParallelEmbedding(num_embeddings=64, embedding_dim=16)
        ids = jnp.asarray(rng.randint(0, 64, (4, 10)), jnp.int32)
        params = emb.init(jax.random.PRNGKey(0), ids)
        dense = emb.apply(params, ids)
        assert params["params"]["embedding"].shape == (64, 16)

        sharded = jax.jit(
            shard_map(
                lambda p, i: emb.apply(p, i),
                mesh=mesh,
                in_specs=(
                    {"params": {"embedding": vocab_embedding_spec()}},
                    P(),
                ),
                out_specs=P(),
                check_vma=False,
            )
        )(params, ids)
        np.testing.assert_allclose(
            np.asarray(sharded), np.asarray(dense), rtol=1e-5, atol=1e-5
        )

    def test_grads_match_dense(self, mesh, rng):
        emb = VocabParallelEmbedding(num_embeddings=32, embedding_dim=8)
        ids = jnp.asarray(rng.randint(0, 32, (12,)), jnp.int32)
        params = emb.init(jax.random.PRNGKey(0), ids)
        t = jnp.asarray(rng.randn(12, 8), jnp.float32)

        def dense_loss(p):
            return jnp.sum(emb.apply(p, ids) * t)

        fn = shard_map(
            lambda p, i: jnp.sum(emb.apply(p, i) * t)[None],
            mesh=mesh,
            in_specs=(
                {"params": {"embedding": vocab_embedding_spec()}}, P(),
            ),
            out_specs=P("tensor"),
            check_vma=False,
        )

        g1 = jax.jit(jax.grad(lambda p: jnp.sum(fn(p, ids))))(params)
        g2 = jax.grad(dense_loss)(params)
        np.testing.assert_allclose(
            np.asarray(g1["params"]["embedding"]),
            np.asarray(g2["params"]["embedding"]),
            rtol=1e-4, atol=1e-5,
        )


class TestVocabParallelCrossEntropy:
    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_matches_full_ce(self, mesh, rng, smoothing):
        vocab = 64
        logits = jnp.asarray(rng.randn(4, 10, vocab), jnp.float32)
        target = jnp.asarray(rng.randint(0, vocab, (4, 10)), jnp.int32)

        loss = jax.jit(
            shard_map(
                lambda l, t: vocab_parallel_cross_entropy(l, t, smoothing),
                mesh=mesh,
                in_specs=(P(None, None, "tensor"), P()),
                out_specs=P(),
                check_vma=False,
            )
        )(logits, target)

        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, target[..., None], -1)[..., 0]
        expected = lse - tgt
        if smoothing > 0:
            sm = smoothing * vocab / (vocab - 1)
            mean_log_prob = jnp.mean(logits, -1) - lse
            expected = (1 - sm) * expected - sm * mean_log_prob
        np.testing.assert_allclose(
            np.asarray(loss), np.asarray(expected), rtol=1e-4, atol=1e-5
        )

    def test_grads_match_full_ce(self, mesh, rng):
        vocab = 32
        logits = jnp.asarray(rng.randn(6, vocab), jnp.float32)
        target = jnp.asarray(rng.randint(0, vocab, (6,)), jnp.int32)

        # train-step pattern: grad inside shard_map, sharded in/out
        step = shard_map(
            lambda l, t: jax.grad(
                lambda l_: jnp.sum(vocab_parallel_cross_entropy(l_, t))
            )(l),
            mesh=mesh,
            in_specs=(P(None, "tensor"), P()),
            out_specs=P(None, "tensor"),
            check_vma=False,
        )

        def full_loss(l):
            lse = jax.scipy.special.logsumexp(l, axis=-1)
            tgt = jnp.take_along_axis(l, target[:, None], -1)[:, 0]
            return jnp.sum(lse - tgt)

        g1 = jax.jit(step)(logits, target)
        g2 = jax.grad(full_loss)(logits)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-4, atol=1e-5)


class TestRandom:
    def test_tracker_fork_advances(self):
        tr = RngStatesTracker()
        tr.add("model-parallel-rng", 123)
        k1 = tr.fork("model-parallel-rng")
        k2 = tr.fork("model-parallel-rng")
        assert not np.array_equal(np.asarray(k1), np.asarray(k2))

    def test_tracker_duplicate_raises(self):
        tr = RngStatesTracker()
        tr.add("a", 1)
        with pytest.raises(ValueError):
            tr.add("a", 2)
        with pytest.raises(ValueError):
            tr.fork("missing")

    def test_state_save_restore(self):
        tr = RngStatesTracker()
        tr.add("s", 7)
        saved = tr.get_states()
        k1 = tr.fork("s")
        tr.set_states(saved)
        k2 = tr.fork("s")
        np.testing.assert_array_equal(np.asarray(k1), np.asarray(k2))

    def test_model_parallel_key_differs_per_rank(self, mesh):
        def f():
            k = model_parallel_rng_key(jax.random.PRNGKey(0))
            return jax.random.uniform(k, (1,))

        out = jax.jit(
            shard_map(f, mesh=mesh, in_specs=(), out_specs=P("tensor"),
                      check_vma=False)
        )()
        vals = np.asarray(out)
        assert len(np.unique(vals)) == TP  # distinct dropout per TP rank

    def test_checkpoint_wrapper_preserves_values_and_grads(self, rng):
        w = jnp.asarray(rng.randn(16, 16), jnp.float32)
        x = jnp.asarray(rng.randn(4, 16), jnp.float32)

        def block(w, x):
            return jnp.sum(jnp.tanh(x @ w) ** 2)

        ck = checkpoint_wrapper(block)
        np.testing.assert_allclose(float(ck(w, x)), float(block(w, x)), rtol=1e-6)
        g1 = jax.grad(ck)(w, x)
        g2 = jax.grad(block)(w, x)
        # remat recomputes the forward inside the backward; XLA may
        # reassociate the recomputed chain, so grads match to float
        # noise (observed ~2e-4 rel on ~1e-7-magnitude elements), not
        # bitwise
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-5, atol=1e-6)


class TestSplitUtil:
    def test_split_tensor_along_last_dim(self, rng):
        x = jnp.asarray(rng.randn(4, 12), jnp.float32)
        parts = split_tensor_along_last_dim(x, 3)
        assert len(parts) == 3
        np.testing.assert_allclose(
            np.asarray(jnp.concatenate(parts, -1)), np.asarray(x)
        )

"""Mesh topology tests (mirrors ref tests/L0/run_transformer/test_parallel_state.py)."""

import jax
import numpy as np
import pytest

from apex_tpu.transformer import parallel_state as ps


@pytest.fixture(autouse=True)
def clean_state():
    ps.destroy_model_parallel()
    yield
    ps.destroy_model_parallel()


class TestInitializeModelParallel:
    @pytest.mark.parametrize("tp,pp", [(1, 1), (2, 1), (1, 2), (2, 2), (4, 2), (8, 1)])
    def test_shapes(self, tp, pp):
        mesh = ps.initialize_model_parallel(tp, pp)
        world = len(jax.devices())
        assert ps.get_tensor_model_parallel_world_size() == tp
        assert ps.get_pipeline_model_parallel_world_size() == pp
        assert ps.get_data_parallel_world_size() == world // (tp * pp)
        assert ps.get_world_size() == world
        assert mesh.axis_names == (
            "data", "expert", "pipe", "context", "tensor")

    def test_indivisible_raises(self):
        with pytest.raises(RuntimeError):
            ps.initialize_model_parallel(3, 1)

    def test_not_initialized_raises(self):
        with pytest.raises(RuntimeError):
            ps.get_mesh()
        assert not ps.model_parallel_is_initialized()

    def test_destroy(self):
        ps.initialize_model_parallel(2, 2)
        assert ps.model_parallel_is_initialized()
        ps.destroy_model_parallel()
        assert not ps.model_parallel_is_initialized()

    def test_tp_is_innermost(self):
        """TP ranks must be adjacent devices (ref parallel_state.py:196-221
        makes TP ranks consecutive)."""
        mesh = ps.initialize_model_parallel(4, 2)
        devs = np.asarray(mesh.devices)
        # along tensor axis, device ids are consecutive
        ids = np.vectorize(lambda d: d.id)(devs)
        row = ids[0, 0, 0, 0, :]
        np.testing.assert_array_equal(row, np.arange(row[0], row[0] + 4))

    def test_mesh_covers_all_devices_once(self):
        """Topology-aware assignment may permute devices but must remain
        a bijection onto the device set."""
        mesh = ps.initialize_model_parallel(2, 2, context_parallel_size=2)
        ids = sorted(d.id for d in np.asarray(mesh.devices).ravel())
        assert ids == sorted(d.id for d in jax.devices())

    def test_explicit_devices_bypass_topology(self):
        """Caller-supplied devices keep the caller's exact order (the
        topology-aware path only applies to the default device set)."""
        devs = list(jax.devices())[::-1]        # deliberately reversed
        mesh = ps.initialize_model_parallel(2, 1, devices=devs)
        got = [d.id for d in np.asarray(mesh.devices).ravel()]
        assert got == [d.id for d in devs]

    def test_expert_parallel(self):
        ps.initialize_model_parallel(2, 1, expert_model_parallel_size=2)
        assert ps.get_expert_model_parallel_world_size() == 2
        assert ps.get_data_parallel_world_size() == 2

    def test_virtual_pp_param_retired(self):
        """PR-16: the interleaved schedule is a mesh.pipeline
        PipelineSpec property, not topology state — the old
        virtual-pp kwarg is gone from the signature."""
        with pytest.raises(TypeError):
            ps.initialize_model_parallel(
                1, 4, virtual_pipeline_model_parallel_size=2
            )


class TestSubstrateCoexistence:
    """PR-16 retired the exclusivity contract (SubstrateConflictError):
    with pipeline execution on the GSPMD mesh, the legacy mesh is just
    a trace-scoped shard_map tool (cp/ep kernels) and may coexist with
    a live GSPMD mesh."""

    def test_conflict_error_retired(self):
        from apex_tpu import mesh as gmesh

        assert not hasattr(gmesh, "SubstrateConflictError")
        assert not hasattr(gmesh, "check_substrate_conflict")

    def test_both_substrates_live(self):
        from apex_tpu import mesh as gmesh

        gmesh.initialize_mesh(model=2)
        try:
            mesh = ps.initialize_model_parallel(2, 1)
            assert ps.model_parallel_is_initialized()
            assert gmesh.mesh_initialized()
            assert mesh.axis_names == (
                "data", "expert", "pipe", "context", "tensor")
            assert gmesh.axis_sizes()["model"] == 2
        finally:
            gmesh.destroy_mesh()


class TestRankQueriesInShardMap:
    def test_axis_index(self):
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = ps.initialize_model_parallel(4, 1)

        def f():
            return (
                ps.get_tensor_model_parallel_rank()[None],
                ps.get_data_parallel_rank()[None],
            )

        tp_ranks, dp_ranks = jax.jit(
            shard_map(
                f, mesh=mesh, in_specs=(),
                out_specs=(P("tensor"), P("data")),
            )
        )()
        np.testing.assert_array_equal(np.asarray(tp_ranks), np.arange(4))
        np.testing.assert_array_equal(np.asarray(dp_ranks), np.arange(2))

"""Data-parallel runtime tests.

Mirrors ref tests/distributed/ (DDP correctness, synced_batchnorm
single-vs-multi device equivalence, BN groups) on the simulated mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import (
    LARC,
    DistributedDataParallel,
    Reducer,
    SyncBatchNorm,
    create_syncbn_group_assignment,
    larc_transform,
)
from apex_tpu.transformer import parallel_state as ps


@pytest.fixture(autouse=True)
def mesh():
    m = ps.initialize_model_parallel(1, 1)  # dp=8
    yield m
    ps.destroy_model_parallel()


class TestDistributedDataParallel:
    def test_grad_average_matches_global_batch(self, mesh, rng):
        """DDP-parity: per-shard grads averaged over dp == grads of the
        global batch (ref tests/distributed/DDP)."""
        w = jnp.asarray(rng.randn(16, 4), jnp.float32)
        x = jnp.asarray(rng.randn(32, 16), jnp.float32)
        y = jnp.asarray(rng.randn(32, 4), jnp.float32)

        def loss(w, x, y):
            return jnp.mean((x @ w - y) ** 2)

        ddp = DistributedDataParallel()

        def sharded_step(w, x, y):
            g = jax.grad(loss)(w, x, y)
            return ddp.allreduce_grads(g)

        g_dist = jax.jit(
            shard_map(
                sharded_step, mesh=mesh,
                in_specs=(P(), P("data", None), P("data", None)),
                out_specs=P(),
                check_vma=False,
            )
        )(w, x, y)
        g_ref = jax.grad(loss)(w, x, y)
        np.testing.assert_allclose(np.asarray(g_dist), np.asarray(g_ref), rtol=1e-5, atol=1e-6)

    def test_predivide_factor(self, mesh):
        ddp = DistributedDataParallel(gradient_predivide_factor=4.0)
        g = {"w": jnp.ones((8,), jnp.float32)}

        out = jax.jit(
            shard_map(
                lambda g: ddp.allreduce_grads(g), mesh=mesh,
                in_specs=(P(),), out_specs=P(), check_vma=False,
            )
        )(g)
        # mean of identical ones = 1 regardless of predivide path
        np.testing.assert_allclose(np.asarray(out["w"]), np.ones(8), rtol=1e-6)

    def test_no_average_sums(self, mesh):
        ddp = DistributedDataParallel(gradient_average=False)
        g = {"w": jnp.ones((8,), jnp.float32)}
        out = jax.jit(
            shard_map(
                lambda g: ddp.allreduce_grads(g), mesh=mesh,
                in_specs=(P(),), out_specs=P(), check_vma=False,
            )
        )(g)
        np.testing.assert_allclose(np.asarray(out["w"]), 8.0 * np.ones(8), rtol=1e-6)

    def test_always_fp32_preserves_dtype(self, mesh):
        ddp = DistributedDataParallel(allreduce_always_fp32=True)
        g = {"w": jnp.ones((8,), jnp.bfloat16)}
        out = jax.jit(
            shard_map(
                lambda g: ddp.allreduce_grads(g), mesh=mesh,
                in_specs=(P(),), out_specs=P(), check_vma=False,
            )
        )(g)
        assert out["w"].dtype == jnp.bfloat16

    def test_reducer(self, mesh):
        red = Reducer()

        def f(x):
            r = jax.lax.axis_index("data").astype(jnp.float32)
            return red.reduce({"v": x + r})

        out = jax.jit(
            shard_map(f, mesh=mesh, in_specs=(P(),), out_specs=P(),
                      check_vma=False)
        )({"v": jnp.zeros((4,))}["v"])
        # mean of ranks 0..7 = 3.5
        np.testing.assert_allclose(np.asarray(out["v"]), 3.5 * np.ones(4), rtol=1e-6)


class TestSyncBatchNorm:
    def _dist_stats(self, mesh, x_global, groups=None):
        """Run SyncBN across dp shards; return output + running stats."""
        bn = SyncBatchNorm(num_features=x_global.shape[-1],
                           axis_index_groups=groups)
        params = bn.init(jax.random.PRNGKey(0), x_global[:1])

        def f(x):
            y, updates = bn.apply(params, x, mutable=["batch_stats"])
            return y, updates["batch_stats"]

        y, stats = jax.jit(
            shard_map(
                f, mesh=mesh,
                in_specs=(P("data", None),),
                out_specs=(P("data", None), P()),
                check_vma=False,
            )
        )(x_global)
        return y, stats

    def test_matches_global_batchnorm(self, mesh, rng):
        """Sync BN over shards == BN over the global batch
        (ref tests/distributed/synced_batchnorm/two_gpu_unit_test.py)."""
        x = jnp.asarray(rng.randn(32, 8), jnp.float32)
        y, stats = self._dist_stats(mesh, x)
        xn = np.asarray(x)
        mean = xn.mean(0)
        var = xn.var(0)
        expected = (xn - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-4, atol=1e-5)
        # running stats: momentum 0.1 from (0, 1) init, unbiased var
        np.testing.assert_allclose(np.asarray(stats["mean"]), 0.1 * mean, rtol=1e-4, atol=1e-5)
        unbiased = var * 32 / 31
        np.testing.assert_allclose(
            np.asarray(stats["var"]), 0.9 * 1.0 + 0.1 * unbiased, rtol=1e-4, atol=1e-5
        )

    def test_bn_groups(self, mesh, rng):
        """BN groups of 4: stats shared within each half of the dp axis
        (ref tests/distributed/synced_batchnorm/test_groups.py)."""
        groups = create_syncbn_group_assignment(8, 4)
        x = jnp.asarray(rng.randn(32, 8), jnp.float32)  # 4 rows per device
        y, _ = self._dist_stats(mesh, x, groups=groups)
        xn = np.asarray(x)
        out = np.empty_like(xn)
        for half in (slice(0, 16), slice(16, 32)):
            mean = xn[half].mean(0)
            var = xn[half].var(0)
            out[half] = (xn[half] - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(np.asarray(y), out, rtol=1e-4, atol=1e-5)

    def test_grad_matches_global(self, mesh, rng):
        """SyncBN backward == global-batch BN backward (the reference
        needed welford bwd kernels; here AD through psum'd stats)."""
        x = jnp.asarray(rng.randn(16, 4), jnp.float32)
        t = jnp.asarray(rng.randn(16, 4), jnp.float32)
        bn = SyncBatchNorm(num_features=4, track_running_stats=False)
        params = bn.init(jax.random.PRNGKey(0), x[:1])

        def dist_loss(x):
            def f(x, t):
                y = bn.apply(params, x)
                return jnp.sum(y * t)[None]

            parts = shard_map(
                f, mesh=mesh,
                in_specs=(P("data", None), P("data", None)),
                out_specs=P("data"), check_vma=False,
            )(x, t)
            return jnp.sum(parts)

        def global_loss(x):
            y = bn.apply(params, x)
            return jnp.sum(y * t)

        g1 = jax.jit(jax.grad(dist_loss))(x)
        g2 = jax.grad(global_loss)(x)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-4, atol=1e-5)

    def test_eval_uses_running_stats(self, rng):
        bn = SyncBatchNorm(num_features=4, axis_name=None)
        x = jnp.asarray(rng.randn(8, 4), jnp.float32)
        params = bn.init(jax.random.PRNGKey(0), x)
        y = bn.apply(params, x, True)  # use_running_stats with (0,1) stats
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(x) / np.sqrt(1 + 1e-5), rtol=1e-5
        )

    def test_fuse_relu(self, rng):
        bn = SyncBatchNorm(num_features=4, axis_name=None, fuse_relu=True)
        x = jnp.asarray(rng.randn(8, 4), jnp.float32)
        params = bn.init(jax.random.PRNGKey(0), x)
        y, _ = bn.apply(params, x, mutable=["batch_stats"])
        assert float(jnp.min(y)) >= 0.0


class TestLARC:
    def test_clip_mode_caps_effective_lr(self, rng):
        params = {"w": jnp.asarray(rng.randn(256) * 100, jnp.float32)}  # big ||p||
        opt = LARC(FusedSGD(lr=0.1, momentum=0.0, impl="xla"))
        state = opt.init(params)
        g = {"w": jnp.asarray(rng.randn(256) * 0.01, jnp.float32)}
        p2, state = opt.step(state, g)
        # adaptive lr would exceed base lr; clip mode caps ratio at 1
        np.testing.assert_allclose(
            np.asarray(p2["w"]),
            np.asarray(params["w"]) - 0.1 * np.asarray(g["w"]),
            rtol=1e-5,
        )

    def test_scale_mode_scales_down(self, rng):
        params = {"w": jnp.asarray(rng.randn(256) * 0.001, jnp.float32)}  # tiny ||p||
        opt = LARC(FusedSGD(lr=0.1, momentum=0.0, impl="xla"),
                   trust_coefficient=0.02, clip=False)
        state = opt.init(params)
        g = {"w": jnp.asarray(rng.randn(256), jnp.float32)}
        p2, _ = opt.step(state, g)
        delta = np.abs(np.asarray(p2["w"]) - np.asarray(params["w"]))
        full = np.abs(0.1 * np.asarray(g["w"]))
        assert np.all(delta < full)  # effective lr far below base

    def test_optax_transform(self, rng):
        import optax

        params = {"w": jnp.asarray(rng.randn(64) * 100, jnp.float32)}
        tx = optax.chain(
            larc_transform(0.1, trust_coefficient=0.02, clip=True),
            optax.sgd(0.1),
        )
        state = tx.init(params)
        g = {"w": jnp.asarray(rng.randn(64) * 0.01, jnp.float32)}
        updates, state = tx.update(g, state, params)
        new = optax.apply_updates(params, updates)
        assert new["w"].shape == (64,)


class TestSyncDeviation:
    """SPMD analog of the reference's DDP epilogue asserts + race test
    (ref distributed.py:336-349, tests/distributed/DDP/
    ddp_race_condition_test.py): reduced grads must be replicated."""

    def test_zero_after_allreduce_nonzero_before(self, mesh):
        from apex_tpu.parallel import DistributedDataParallel
        from apex_tpu.parallel.distributed import sync_deviation

        ddp = DistributedDataParallel(axis_name="data")

        def f(x):
            g = {"w": x * (1.0 + jax.lax.axis_index("data"))}  # rank-dependent
            before = sync_deviation(g, "data")
            g = ddp.allreduce_grads(g)
            after = sync_deviation(g, "data")
            return before, after

        before, after = jax.jit(shard_map(
            f, mesh=mesh, in_specs=P("data"), out_specs=(P(), P()),
            check_vma=False,
        ))(jnp.ones((8, 4)))
        assert float(np.ravel(before)[0]) > 0.0
        assert float(np.ravel(after)[0]) == 0.0

    def test_check_synchronized_detects_bypass(self, mesh):
        """check_synchronized on the tree the optimizer consumes flags
        a leaf that bypassed the reduction (torch DDP check_reduction)."""
        from apex_tpu.parallel import DistributedDataParallel

        ddp = DistributedDataParallel(axis_name="data",
                                      check_reduction=True)

        def f(x):
            synced = ddp.allreduce_grads({"w": x})
            # "forgot" to reduce a second tree — rank-dependent
            bad = {"w": synced["w"], "extra": x * 1.0}
            return ddp.check_synchronized(synced), ddp.check_synchronized(bad)

        rank_dep = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)
        good, bad = jax.jit(shard_map(
            f, mesh=mesh, in_specs=P("data"), out_specs=(P(), P()),
            check_vma=False,
        ))(rank_dep)
        assert float(np.ravel(good)[0]) == 0.0
        assert float(np.ravel(bad)[0]) > 0.0

    def test_sync_deviation_nan_propagates(self, mesh):
        """inf/NaN anywhere must not be reported as 'in sync'."""
        from apex_tpu.parallel.distributed import sync_deviation

        def f(x):
            bad = jnp.where(jax.lax.axis_index("data") == 1,
                            jnp.inf, 0.0) + x
            return sync_deviation({"w": bad}, "data")

        dev = jax.jit(shard_map(
            f, mesh=mesh, in_specs=P("data"), out_specs=P(),
            check_vma=False,
        ))(jnp.ones((8, 4)))
        assert not (float(np.ravel(dev)[0]) <= 0.0)

"""Fused update engine tests — kernel-vs-reference parity.

Mirrors ref tests/L0/run_amp/test_multi_tensor_scale.py,
test_multi_tensor_axpby.py, test_multi_tensor_l2norm.py and
tests/L0/run_optimizers fused-vs-reference equivalence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.multi_tensor import (
    FlatSpace,
    fused_adagrad_update,
    fused_adam_update,
    fused_lamb_update,
    fused_lars_update,
    fused_novograd_update,
    fused_sgd_update,
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_scale,
    per_tensor_l2norm,
)


def make_tree(rng, scale=1.0):
    return {
        "w1": jnp.asarray(rng.randn(33, 65) * scale, jnp.float32),
        "b1": jnp.asarray(rng.randn(65) * scale, jnp.float32),
        "w2": jnp.asarray(rng.randn(129, 257) * scale, jnp.float32),
        "scalar": jnp.asarray(rng.randn() * scale, jnp.float32),
    }


class TestFlatSpace:
    def test_roundtrip(self, rng):
        tree = make_tree(rng)
        space = FlatSpace.create(tree)
        buf = space.pack(tree)
        assert buf.ndim == 1 and buf.shape[0] == space.total
        assert space.total % space.align == 0
        out = space.unpack(buf)
        jax.tree.map(np.testing.assert_array_equal, tree, out)

    def test_cast_roundtrip(self, rng):
        tree = jax.tree.map(lambda x: x.astype(jnp.bfloat16), make_tree(rng))
        space = FlatSpace.create(tree)
        buf = space.pack(tree, dtype=jnp.float32)
        assert buf.dtype == jnp.float32
        out = space.unpack(buf)
        assert all(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(out))

    def test_tile_ids(self, rng):
        tree = make_tree(rng)
        space = FlatSpace.create(tree)
        ids = space.tile_leaf_ids(2048)
        assert ids.shape[0] == space.total // 2048
        # each leaf owns padded_size/2048 consecutive tiles
        counts = np.bincount(ids, minlength=space.num_leaves)
        np.testing.assert_array_equal(
            counts, np.asarray(space.padded_sizes) // 2048
        )

    def test_padding_is_zero(self, rng):
        tree = make_tree(rng)
        space = FlatSpace.create(tree)
        buf = np.asarray(space.pack(tree))
        for off, size, psize in zip(space.offsets, space.sizes, space.padded_sizes):
            assert np.all(buf[off + size : off + psize] == 0)


class TestScaleAxpbyL2norm:
    def test_scale(self, rng, impl):
        tree = make_tree(rng)
        space = FlatSpace.create(tree)
        buf = space.pack(tree)
        out, found = multi_tensor_scale(buf, 4.0, impl=impl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(buf) * 4.0, rtol=1e-6)
        assert float(found) == 0.0

    def test_scale_found_inf(self, rng, impl):
        buf = jnp.asarray(rng.randn(4096), jnp.float32).at[17].set(jnp.inf)
        _, found = multi_tensor_scale(buf, 1.0, impl=impl)
        assert float(found) == 1.0
        buf = jnp.asarray(rng.randn(4096), jnp.float32).at[100].set(jnp.nan)
        _, found = multi_tensor_scale(buf, 0.5, impl=impl)
        assert float(found) == 1.0

    def test_scale_overflow_detected_post_scale(self, impl):
        # scaling can overflow even finite inputs — reference flags the output
        buf = jnp.full((2048,), 3e38, jnp.float32)
        _, found = multi_tensor_scale(buf, 10.0, impl=impl)
        assert float(found) == 1.0

    def test_axpby(self, rng, impl):
        x = jnp.asarray(rng.randn(5000), jnp.float32)
        y = jnp.asarray(rng.randn(5000), jnp.float32)
        out, found = multi_tensor_axpby(x, y, 2.0, -3.0, impl=impl)
        np.testing.assert_allclose(
            np.asarray(out), 2.0 * np.asarray(x) - 3.0 * np.asarray(y), rtol=1e-6
        )
        assert float(found) == 0.0

    @pytest.mark.parametrize("arg_to_check,bad_x,expect", [
        (-1, True, 1.0), (-1, False, 1.0), (0, True, 1.0),
        (0, False, 0.0), (1, False, 1.0), (1, True, 0.0),
    ])
    def test_axpby_arg_to_check(self, rng, impl, arg_to_check, bad_x, expect):
        x = jnp.asarray(rng.randn(3000), jnp.float32)
        y = jnp.asarray(rng.randn(3000), jnp.float32)
        if bad_x:
            x = x.at[5].set(jnp.nan)
        else:
            y = y.at[5].set(jnp.nan)
        _, found = multi_tensor_axpby(x, y, 1.0, 1.0, arg_to_check=arg_to_check, impl=impl)
        assert float(found) == expect

    def test_l2norm_global(self, rng, impl):
        tree = make_tree(rng)
        space = FlatSpace.create(tree)
        buf = space.pack(tree)
        norm, _ = multi_tensor_l2norm(buf, impl=impl)
        np.testing.assert_allclose(
            float(norm), float(np.linalg.norm(np.asarray(buf))), rtol=1e-5
        )

    def test_l2norm_per_tensor(self, rng, impl):
        tree = make_tree(rng)
        space = FlatSpace.create(tree)
        buf = space.pack(tree)
        norm, pt = multi_tensor_l2norm(buf, space, per_tensor=True, impl=impl)
        leaves = jax.tree.leaves(tree)
        expected = np.array([np.linalg.norm(np.asarray(l)) for l in leaves])
        np.testing.assert_allclose(np.asarray(pt), expected, rtol=1e-5)
        np.testing.assert_allclose(
            float(norm), float(np.linalg.norm(np.asarray(buf))), rtol=1e-5
        )

    def test_sumsq_subtiles_fused_into_update(self, rng, impl):
        """The engine's in-pass per-subtile sumsq partials (the fusion
        that folds LAMB's ||p||/||update|| passes into stage 1) must
        reproduce per_tensor_l2norm exactly, for both an input and an
        output buffer, at the DEFAULT (non-per-tensor) tile size."""
        from apex_tpu.multi_tensor.engine import fused_elementwise
        from apex_tpu.multi_tensor.ops import _norms_from_subtile_partials

        tree = make_tree(rng)
        space = FlatSpace.create(tree)
        buf = space.pack(tree)
        other = space.pack(jax.tree.map(
            lambda v: jnp.asarray(np.asarray(
                np.random.RandomState(1).standard_normal(v.shape),
                np.float32)),
            tree))

        def fn(ins, s, t):
            a, b = [x.astype(jnp.float32) for x in ins]
            return [a * 2.0 + b]

        (out, a_part, o_part), _ = fused_elementwise(
            fn, [buf, other], num_outputs=1, out_dtypes=[jnp.float32],
            impl=impl, sumsq_subtiles=(("in", 0), ("out", 0)))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(buf) * 2.0 + np.asarray(other),
            rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(_norms_from_subtile_partials(a_part, space)),
            np.asarray(per_tensor_l2norm(buf, space, impl="xla")),
            rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(_norms_from_subtile_partials(o_part, space)),
            np.asarray(per_tensor_l2norm(out, space, impl="xla")),
            rtol=1e-5)

        with pytest.raises(ValueError, match="sumsq_subtiles"):
            fused_elementwise(fn, [buf, other], num_outputs=1,
                              out_dtypes=[jnp.float32], impl=impl,
                              sumsq_subtiles=(("out", 3),))

    @pytest.mark.parametrize("tile_rows", [16, 128, 512])
    def test_per_tensor_values_any_tile_size(self, rng, impl, tile_rows):
        """Subtile-granular tile_ids give identical per-tensor semantics
        at every sweep tile size: sub=1 (the documented Mosaic
        mitigation path), sub=8, and the default sub=32."""
        from apex_tpu.multi_tensor.engine import fused_elementwise
        from apex_tpu.multi_tensor.ops import _PT_TILE

        tree = make_tree(rng)
        space = FlatSpace.create(tree)
        buf = space.pack(tree)
        per_leaf = jnp.arange(space.num_leaves, dtype=jnp.float32) + 2.0

        def fn(ins, s, t):
            (x,) = [i.astype(jnp.float32) for i in ins]
            (r,) = t
            return [x * r]

        (out,), _ = fused_elementwise(
            fn, [buf], per_tensor=[per_leaf],
            tile_ids=space.tile_leaf_ids(_PT_TILE),
            num_outputs=1, out_dtypes=[jnp.float32], impl=impl,
            tile_rows=tile_rows)
        want = buf * space.elementwise_leaf_values(per_leaf)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-6)

    def test_sumsq_subtiles_pad_clean(self, rng, impl):
        """fn's image of the zero tail-pad (fn(0) != 0 here) must never
        leak into the partials: summing ALL partials equals the exact
        global sum of squares of the real output, on every impl."""
        from apex_tpu.multi_tensor.engine import fused_elementwise

        n = 70000    # not a multiple of the 65536-element default tile
        x = jnp.asarray(np.asarray(rng.standard_normal(n), np.float32))

        def fn(ins, s, t):
            return [ins[0].astype(jnp.float32) + 1.0]   # fn(0) = 1

        (out, part), _ = fused_elementwise(
            fn, [x], num_outputs=1, out_dtypes=[jnp.float32], impl=impl,
            sumsq_subtiles=(("out", 0),))
        got = float(jnp.sum(part))
        want = float(jnp.sum(out.astype(jnp.float32) ** 2))
        np.testing.assert_allclose(got, want, rtol=1e-6)


def _np_adam(p, m, v, g, lr, b1, b2, eps, step, wd, adam_w):
    if not adam_w:
        g = g + wd * p
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    mhat = m / (1 - b1**step)
    vhat = v / (1 - b2**step)
    upd = mhat / (np.sqrt(vhat) + eps)
    if adam_w:
        upd = upd + wd * p
    return p - lr * upd, m, v


class TestFusedOptimizerOps:
    @pytest.mark.parametrize("adam_w", [True, False])
    def test_adam(self, rng, impl, adam_w):
        n = 6000
        p, g = rng.randn(n).astype(np.float32), rng.randn(n).astype(np.float32)
        m, v = rng.randn(n).astype(np.float32), np.abs(rng.randn(n)).astype(np.float32)
        for step in (1, 2, 3):
            p2, m2, v2, found = fused_adam_update(
                jnp.asarray(p), jnp.asarray(m), jnp.asarray(v), jnp.asarray(g),
                lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, step=step,
                weight_decay=0.01, adam_w_mode=adam_w, impl=impl,
            )
            pe, me, ve = _np_adam(p, m, v, g, 1e-3, 0.9, 0.999, 1e-8, step, 0.01, adam_w)
            np.testing.assert_allclose(np.asarray(p2), pe, rtol=2e-5, atol=1e-6)
            np.testing.assert_allclose(np.asarray(m2), me, rtol=2e-5, atol=1e-6)
            np.testing.assert_allclose(np.asarray(v2), ve, rtol=2e-5, atol=1e-6)
            assert float(found) == 0.0
            p, m, v = np.asarray(p2), np.asarray(m2), np.asarray(v2)

    def test_adam_grad_scale(self, rng, impl):
        n = 3000
        p, m, v = (rng.randn(n).astype(np.float32) for _ in range(3))
        v = np.abs(v)
        g = rng.randn(n).astype(np.float32)
        p2a, *_ = fused_adam_update(
            jnp.asarray(p), jnp.asarray(m), jnp.asarray(v), jnp.asarray(g * 128.0),
            lr=1e-3, step=1, grad_scale=128.0, impl=impl,
        )
        p2b, *_ = fused_adam_update(
            jnp.asarray(p), jnp.asarray(m), jnp.asarray(v), jnp.asarray(g),
            lr=1e-3, step=1, impl=impl,
        )
        np.testing.assert_allclose(np.asarray(p2a), np.asarray(p2b), rtol=1e-5, atol=1e-7)

    def test_adam_found_inf(self, rng, impl):
        n = 3000
        p, m, v, g = (rng.randn(n).astype(np.float32) for _ in range(4))
        g[7] = np.inf
        _, _, _, found = fused_adam_update(
            jnp.asarray(p), jnp.asarray(m), jnp.asarray(v), jnp.asarray(g),
            lr=1e-3, step=1, impl=impl,
        )
        assert float(found) == 1.0

    @pytest.mark.parametrize("nesterov", [False, True])
    def test_sgd(self, rng, impl, nesterov):
        n = 4000
        p = rng.randn(n).astype(np.float32)
        g = rng.randn(n).astype(np.float32)
        mom = np.zeros(n, np.float32)
        lr, mu, wd = 0.1, 0.9, 1e-4
        pj, mj = jnp.asarray(p), jnp.asarray(mom)
        for step in range(3):
            pj, mj, found = fused_sgd_update(
                pj, mj, jnp.asarray(g), lr=lr, momentum=mu, weight_decay=wd,
                nesterov=nesterov, first_run=(step == 0), impl=impl,
            )
            ge = g + wd * p
            mom = ge if step == 0 else mu * mom + ge
            upd = ge + mu * mom if nesterov else mom
            p = p - lr * upd
            np.testing.assert_allclose(np.asarray(pj), p, rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(np.asarray(mj), mom, rtol=1e-5, atol=1e-6)
            assert float(found) == 0.0

    def test_sgd_no_momentum(self, rng, impl):
        n = 2048
        p = rng.randn(n).astype(np.float32)
        g = rng.randn(n).astype(np.float32)
        p2, m2, _ = fused_sgd_update(
            jnp.asarray(p), jnp.zeros(n, jnp.float32), jnp.asarray(g),
            lr=0.5, momentum=0.0, impl=impl,
        )
        np.testing.assert_allclose(np.asarray(p2), p - 0.5 * g, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(m2), np.zeros(n), atol=0)

    def test_adagrad(self, rng, impl):
        n = 3000
        p = rng.randn(n).astype(np.float32)
        g = rng.randn(n).astype(np.float32)
        h = np.abs(rng.randn(n)).astype(np.float32)
        p2, h2, found = fused_adagrad_update(
            jnp.asarray(p), jnp.asarray(h), jnp.asarray(g),
            lr=0.01, eps=1e-10, weight_decay=1e-4, impl=impl,
        )
        ge = g + 1e-4 * p
        he = h + ge * ge
        pe = p - 0.01 * ge / (np.sqrt(he) + 1e-10)
        np.testing.assert_allclose(np.asarray(h2), he, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(p2), pe, rtol=1e-5, atol=1e-6)
        assert float(found) == 0.0

    def test_lamb_matches_manual(self, rng, impl):
        tree = make_tree(rng)
        space = FlatSpace.create(tree)
        p = space.pack(tree)
        g = space.pack(jax.tree.map(lambda x: x * 0.1, tree))
        m = jnp.zeros_like(p)
        v = jnp.zeros_like(p)
        lr, b1, b2, eps, wd, step = 0.01, 0.9, 0.999, 1e-6, 0.01, 1
        p2, m2, v2, found = fused_lamb_update(
            p, m, v, g, space, lr=lr, beta1=b1, beta2=b2, eps=eps, step=step,
            weight_decay=wd, max_grad_norm=0.0, impl=impl,
        )
        # manual per-tensor reference
        pn, gn = np.asarray(p), np.asarray(g)
        me = (1 - b1) * gn
        ve = (1 - b2) * gn * gn
        upd = (me / (1 - b1**step)) / (np.sqrt(ve / (1 - b2**step)) + eps) + wd * pn
        pe = np.array(pn)
        for off, psize in zip(space.offsets, space.padded_sizes):
            sl = slice(off, off + psize)
            wn = np.linalg.norm(pn[sl])
            un = np.linalg.norm(upd[sl])
            ratio = wn / un if (wn > 0 and un > 0) else 1.0
            pe[sl] = pn[sl] - lr * ratio * upd[sl]
        np.testing.assert_allclose(np.asarray(m2), me, rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(np.asarray(v2), ve, rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(np.asarray(p2), pe, rtol=1e-4, atol=1e-6)
        assert float(found) == 0.0

    def test_lamb_grad_clipping(self, rng, impl):
        tree = make_tree(rng, scale=100.0)
        space = FlatSpace.create(tree)
        p = space.pack(make_tree(rng))
        g = space.pack(tree)  # huge grads
        m = jnp.zeros_like(p)
        v = jnp.zeros_like(p)
        p_clip, *_ = fused_lamb_update(
            p, m, v, g, space, lr=0.01, step=1, max_grad_norm=1.0, impl=impl,
        )
        gnorm = float(jnp.linalg.norm(g))
        p_manual, *_ = fused_lamb_update(
            p, m, v, g / gnorm, space, lr=0.01, step=1, max_grad_norm=0.0, impl=impl,
        )
        np.testing.assert_allclose(
            np.asarray(p_clip), np.asarray(p_manual), rtol=1e-4, atol=1e-6
        )

    def test_novograd(self, rng, impl):
        tree = make_tree(rng)
        space = FlatSpace.create(tree)
        p = space.pack(tree)
        g = space.pack(jax.tree.map(lambda x: x * 0.1, tree))
        m = jnp.zeros_like(p)
        v = jnp.zeros((space.num_leaves,), jnp.float32)
        p2, m2, v2, found = fused_novograd_update(
            p, m, v, g, space, lr=0.01, beta1=0.95, beta2=0.98, step=1,
            weight_decay=0.001, impl=impl,
        )
        gn = np.asarray(g)
        pn = np.asarray(p)
        # step 1: v = ||g||^2 per tensor
        expected_v = []
        pe, me = np.array(pn), np.zeros_like(pn)
        for off, psize in zip(space.offsets, space.padded_sizes):
            sl = slice(off, off + psize)
            gnorm = np.linalg.norm(gn[sl])
            expected_v.append(gnorm**2)
            denom = gnorm + 1e-8
            gg = gn[sl] / denom + 0.001 * pn[sl]
            me[sl] = 0.05 * gg
            pe[sl] = pn[sl] - 0.01 * me[sl]
        np.testing.assert_allclose(np.asarray(v2), expected_v, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(m2), me, rtol=1e-4, atol=1e-7)
        np.testing.assert_allclose(np.asarray(p2), pe, rtol=1e-4, atol=1e-7)
        assert float(found) == 0.0

    def test_lars(self, rng, impl):
        tree = make_tree(rng)
        space = FlatSpace.create(tree)
        p = space.pack(tree)
        g = space.pack(jax.tree.map(lambda x: x * 0.01, tree))
        mom = jnp.zeros_like(p)
        p2, mom2, found = fused_lars_update(
            p, mom, g, space, lr=0.1, momentum=0.9, weight_decay=1e-4,
            trust_coefficient=0.02, first_run=True, impl=impl,
        )
        pn, gn = np.asarray(p), np.asarray(g)
        pe = np.array(pn)
        for off, psize in zip(space.offsets, space.padded_sizes):
            sl = slice(off, off + psize)
            wn, gnorm = np.linalg.norm(pn[sl]), np.linalg.norm(gn[sl])
            ratio = 0.02 * wn / (gnorm + 1e-4 * wn + 1e-8)
            ratio = min(ratio, 1.0) if (wn > 0 and gnorm > 0) else 1.0
            ge = (gn[sl] + 1e-4 * pn[sl]) * ratio
            pe[sl] = pn[sl] - 0.1 * ge
        np.testing.assert_allclose(np.asarray(p2), pe, rtol=1e-4, atol=1e-7)
        assert float(found) == 0.0


class TestJitAndDonation:
    def test_adam_jits(self, rng):
        n = 4096
        p, m, v, g = (jnp.asarray(rng.randn(n), jnp.float32) for _ in range(4))

        @jax.jit
        def step(p, m, v, g):
            return fused_adam_update(p, m, v, g, lr=1e-3, step=1, impl="xla")

        p2, m2, v2, found = step(p, m, v, g)
        assert p2.shape == (n,)
        assert float(found) == 0.0


class TestStochasticRounding:
    """bf16 master-free updates: E[stored] == fp32 value, so sub-ulp
    updates accumulate in expectation (engine sr_outputs/sr_seed;
    ref analog: mixed param dtypes in csrc/multi_tensor_lamb_mp.cu)."""

    def test_sr_statistics(self, impl):
        # p = 1.0, update 2^-9: bf16 ulp(1.0) = 2^-8, so nearest
        # rounding returns exactly 1.0 every time; SR must round up to
        # 1+2^-8 with probability 1/2 and keep the mean at 1+2^-9
        n = 1 << 14
        p = jnp.full((n,), 1.0, jnp.bfloat16)
        g = jnp.full((n,), 2.0 ** -9, jnp.float32)
        p2, _, found = fused_sgd_update(
            p, jnp.zeros((n,), jnp.float32), g, lr=1.0, momentum=0.0,
            impl=impl, sr_seed=7)
        assert p2.dtype == jnp.bfloat16
        vals = np.asarray(p2, np.float32)
        lo, hi = 1.0 - 2.0 ** -8, 1.0 - 0.0
        # every value is one of the two bf16 neighbours of 1 - 2^-9
        assert set(np.unique(vals)) <= {np.float32(lo), np.float32(hi)}
        frac_hi = (vals == hi).mean()
        assert abs(frac_hi - 0.5) < 0.05, frac_hi
        assert abs(vals.mean() - (1.0 - 2.0 ** -9)) < 2e-4
        assert float(found) == 0.0

    def test_sr_deterministic_per_seed(self, impl):
        n = 4096
        p = jnp.full((n,), 1.0, jnp.bfloat16)
        g = jnp.full((n,), 2.0 ** -9, jnp.float32)

        def run(seed):
            out, _, _ = fused_sgd_update(
                p, jnp.zeros((n,), jnp.float32), g, lr=1.0, impl=impl,
                sr_seed=seed)
            return np.asarray(out, np.float32)

        np.testing.assert_array_equal(run(3), run(3))
        assert (run(3) != run(4)).any()

    def test_sr_nonfinite_passthrough(self, impl):
        if impl == "interpret":
            # interpret SR casts outside the kernel; xla covers the
            # emulation's finite guard (same code path)
            pytest.skip("finite guard lives in the shared emulation")
        p = jnp.full((256,), 1.0, jnp.bfloat16)
        g = np.zeros((256,), np.float32)
        g[3] = np.inf
        p2, _, found = fused_sgd_update(
            p, jnp.zeros((256,), jnp.float32), g, lr=1.0, impl="xla",
            sr_seed=0)
        assert float(found) == 1.0
        assert np.isinf(np.asarray(p2, np.float32)[3])

    def test_sr_requires_bf16(self):
        p = jnp.ones((256,), jnp.float32)
        with pytest.raises(ValueError, match="bfloat16"):
            fused_sgd_update(p, jnp.zeros_like(p), p, lr=1.0, impl="xla",
                             sr_seed=1)

    def test_sr_drift_accumulates(self, impl):
        # 64 steps of +2^-11: nearest rounding stalls at exactly 1.0;
        # SR accumulates ~64 * 2^-11 = 2^-5 in expectation
        n = 8192
        p = jnp.full((n,), 1.0, jnp.bfloat16)
        g = jnp.full((n,), -(2.0 ** -11), jnp.float32)
        mom = jnp.zeros((n,), jnp.float32)
        for step in range(64):
            p, _, _ = fused_sgd_update(p, mom, g, lr=1.0, momentum=0.0,
                                       impl=impl, sr_seed=step)
        drift = float(np.asarray(p, np.float32).mean()) - 1.0
        assert abs(drift - 2.0 ** -5) < 0.2 * 2.0 ** -5, drift
        # nearest rounding comparison: the same updates vanish
        p_nr = jnp.full((n,), 1.0, jnp.bfloat16)
        for _ in range(4):
            p2f = p_nr.astype(jnp.float32) + 2.0 ** -11
            p_nr = p2f.astype(jnp.bfloat16)
        assert float(np.asarray(p_nr, np.float32).mean()) == 1.0

    @pytest.mark.parametrize("opt_name", ["adam", "lamb"])
    def test_sr_per_tensor_ops(self, rng, impl, opt_name):
        tree = make_tree(rng, scale=0.5)
        tree = jax.tree.map(lambda x: x.astype(jnp.bfloat16), tree)
        space = FlatSpace.create(tree)
        p = space.pack(tree)                      # bf16 flat buffer
        g = space.pack(jax.tree.map(
            lambda v: jnp.asarray(rng.randn(*v.shape) * 0.01, jnp.float32),
            tree), dtype=jnp.float32)
        m = jnp.zeros(p.shape, jnp.float32)
        v = jnp.zeros(p.shape, jnp.float32)
        if opt_name == "adam":
            p2, *_ , found = fused_adam_update(
                p, m, v, g, lr=1e-3, step=1, impl=impl, sr_seed=11)
        else:
            p2, *_, found = fused_lamb_update(
                p, m, v, g, space, lr=1e-3, step=1, impl=impl, sr_seed=11)
        assert p2.dtype == jnp.bfloat16
        assert float(found) == 0.0
        # stored bf16 values sit within one ulp of the fp32 update
        p2f_ref, *_ , _ = (
            fused_adam_update(p, m, v, g.astype(jnp.float32), lr=1e-3,
                              step=1, impl="xla")
            if opt_name == "adam" else
            fused_lamb_update(p, m, v, g, space, lr=1e-3, step=1,
                              impl="xla"))
        diff = np.abs(np.asarray(p2, np.float32)
                      - np.asarray(p2f_ref, np.float32))
        scale = 1.0 + np.abs(np.asarray(p2f_ref, np.float32))
        assert (diff / scale).max() < 2.0 ** -7, (diff / scale).max()


class TestSegmentedLamb:
    """Single-pass segment-resident LAMB (multi_tensor/segmented.py)
    vs the two-stage reference on the SAME segmented layout. The
    interpret impl runs the real kernel schedule, so these pin the
    phase/revisit logic, the one-hot slot reductions, and the
    large-leaf fallback — not just the driver glue."""

    def _tree(self, rng, with_large, seg):
        tree = {
            "a": jnp.asarray(rng.randn(1000).astype(np.float32)),
            "b": jnp.asarray(rng.randn(300, 70).astype(np.float32)),
            "c": jnp.asarray(rng.randn(5).astype(np.float32)),
            "d": jnp.asarray(rng.randn(128, 128).astype(np.float32)),
        }
        if with_large:
            tree["big"] = jnp.asarray(
                rng.randn(2 * seg + 777).astype(np.float32))
        return tree

    @pytest.mark.parametrize("with_large", [False, True])
    @pytest.mark.parametrize("use_nvlamb,wd", [(True, 0.01), (False, 0.0),
                                               (False, 0.01)])
    def test_matches_two_stage(self, rng, with_large, use_nvlamb, wd):
        from apex_tpu.multi_tensor.flat_buffer import segmented_space
        from apex_tpu.multi_tensor.segmented import (
            CHUNK, fused_lamb_segmented_update)
        from apex_tpu.multi_tensor.ops import fused_lamb_update

        seg = 2 * CHUNK
        tree = self._tree(rng, with_large, seg)
        space, meta = segmented_space(tree, seg_elems=seg)
        pk = lambda t: space.pack(t, dtype=jnp.float32)  # noqa: E731
        p = pk(tree)
        g = pk(jax.tree.map(
            lambda x: jnp.asarray(
                rng.randn(*x.shape).astype(np.float32) * 1e-2), tree))
        m = pk(jax.tree.map(
            lambda x: jnp.asarray(
                rng.randn(*x.shape).astype(np.float32) * 1e-3), tree))
        v = pk(jax.tree.map(
            lambda x: jnp.abs(jnp.asarray(
                rng.randn(*x.shape).astype(np.float32) * 1e-4)), tree))
        kw = dict(lr=1e-2, weight_decay=wd, use_nvlamb=use_nvlamb,
                  step=3, max_grad_norm=0.0)
        got = fused_lamb_segmented_update(
            p, m, v, g, space, meta, impl="interpret", **kw)
        want = fused_lamb_update(p, m, v, g, space, impl="xla", **kw)
        for name, a, b in zip(("p2", "m2", "v2"), got, want):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-6, rtol=1e-5,
                err_msg=name)
        assert float(got[3]) == float(want[3]) == 0.0

    def test_found_inf(self, rng):
        from apex_tpu.multi_tensor.flat_buffer import segmented_space
        from apex_tpu.multi_tensor.segmented import (
            CHUNK, fused_lamb_segmented_update)

        seg = CHUNK
        tree = self._tree(rng, False, seg)
        space, meta = segmented_space(tree, seg_elems=seg)
        p = space.pack(tree, dtype=jnp.float32)
        g = jnp.zeros_like(p).at[3].set(jnp.inf)
        m = jnp.zeros_like(p)
        v = jnp.zeros_like(p)
        *_, found = fused_lamb_segmented_update(
            p, m, v, g, space, meta, impl="interpret",
            lr=1e-2, max_grad_norm=0.0)
        assert float(found) == 1.0

    @pytest.mark.parametrize("with_large", [False, True])
    def test_stream_p_matches_two_stage(self, rng, with_large):
        """stash_p=False re-streams p in phase 1 (half the scratch, 8
        HBM accesses/elem) — must be bitwise the same math."""
        from apex_tpu.multi_tensor.flat_buffer import segmented_space
        from apex_tpu.multi_tensor.segmented import (
            CHUNK, fused_lamb_segmented_update)
        from apex_tpu.multi_tensor.ops import fused_lamb_update

        seg = 2 * CHUNK
        tree = self._tree(rng, with_large, seg)
        space, meta = segmented_space(tree, seg_elems=seg)
        pk = lambda t: space.pack(t, dtype=jnp.float32)  # noqa: E731
        p = pk(tree)
        g = pk(jax.tree.map(
            lambda x: jnp.asarray(
                rng.randn(*x.shape).astype(np.float32) * 1e-2), tree))
        m = jnp.zeros_like(p)
        v = jnp.zeros_like(p)
        kw = dict(lr=1e-2, weight_decay=0.01, use_nvlamb=True, step=1,
                  max_grad_norm=0.0)
        got = fused_lamb_segmented_update(
            p, m, v, g, space, meta, impl="interpret", stash_p=False,
            **kw)
        want = fused_lamb_update(p, m, v, g, space, impl="xla", **kw)
        for name, a, b in zip(("p2", "m2", "v2"), got, want):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-6, rtol=1e-5,
                err_msg=name)

    def test_bf16_u_stash_close(self, rng):
        """u_dtype=bfloat16 halves the stash; the update-term is O(1)
        so the perturbation on p2 is ~lr*2^-9 — loose-tol parity."""
        from apex_tpu.multi_tensor.flat_buffer import segmented_space
        from apex_tpu.multi_tensor.segmented import (
            CHUNK, fused_lamb_segmented_update)
        from apex_tpu.multi_tensor.ops import fused_lamb_update

        seg = 2 * CHUNK
        tree = self._tree(rng, False, seg)
        space, meta = segmented_space(tree, seg_elems=seg)
        pk = lambda t: space.pack(t, dtype=jnp.float32)  # noqa: E731
        p = pk(tree)
        g = pk(jax.tree.map(
            lambda x: jnp.asarray(
                rng.randn(*x.shape).astype(np.float32) * 1e-2), tree))
        m = jnp.zeros_like(p)
        v = jnp.zeros_like(p)
        kw = dict(lr=1e-2, weight_decay=0.01, use_nvlamb=True, step=1,
                  max_grad_norm=0.0)
        got = fused_lamb_segmented_update(
            p, m, v, g, space, meta, impl="interpret", stash_p=False,
            u_dtype=jnp.bfloat16, **kw)
        want = fused_lamb_update(p, m, v, g, space, impl="xla", **kw)
        # p2 differs only through the bf16-rounded u: |dp2| <= lr*r*2^-8|u|
        np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]),
                                   atol=1e-2 * 2.0 ** -7, rtol=0)
        # m2/v2 are written in phase 0, before any stash: exact
        for name, a, b in zip(("m2", "v2"), got[1:], want[1:]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-6, rtol=1e-5,
                err_msg=name)

    def test_reinit_does_not_poison_old_state(self, rng):
        """ADVICE r3: SegmentMeta rides in the STATE — a second init()
        over a different tree must not change how an earlier state
        steps."""
        from apex_tpu.optimizers import FusedLAMB

        params_a = {"w": jnp.asarray(rng.randn(40, 12).astype(np.float32))}
        params_b = {f"x{i}": jnp.asarray(rng.randn(7 + i).astype(np.float32))
                    for i in range(5)}
        g_a = jax.tree.map(
            lambda l: jnp.asarray(
                rng.randn(*l.shape).astype(np.float32) * 1e-2), params_a)

        opt = FusedLAMB(lr=1e-2, weight_decay=0.01, use_nvlamb=True,
                        max_grad_norm=0.0)
        st_a = opt.init(params_a)
        want, _ = opt.step(st_a, g_a)
        _ = opt.init(params_b)          # different tree, fresh layout
        got, _ = opt.step(st_a, g_a)    # old state must be unaffected
        for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_mismatched_meta_raises(self, rng):
        from apex_tpu.multi_tensor.flat_buffer import segmented_space
        from apex_tpu.multi_tensor.segmented import (
            CHUNK, fused_lamb_segmented_update)

        tree = self._tree(rng, False, CHUNK)
        space, _ = segmented_space(tree, seg_elems=CHUNK)
        other = {"z": jnp.zeros((5 * CHUNK,), jnp.float32)}
        _, foreign_meta = segmented_space(other, seg_elems=CHUNK)
        p = space.pack(tree, dtype=jnp.float32)
        with pytest.raises(ValueError, match="does not cover"):
            fused_lamb_segmented_update(
                p, jnp.zeros_like(p), jnp.zeros_like(p), jnp.zeros_like(p),
                space, foreign_meta, impl="interpret", lr=1e-2)

    def test_optimizer_trajectory_matches(self, rng):
        """FusedLAMB(segmented=True) == FusedLAMB(segmented=False)
        over several steps of a real loop (different flat layouts,
        same math)."""
        from apex_tpu.optimizers import FusedLAMB

        params = {"w": jnp.asarray(rng.randn(40, 12).astype(np.float32)),
                  "b": jnp.asarray(np.zeros(12, np.float32))}
        x = jnp.asarray(rng.randn(64, 40).astype(np.float32))
        y = jnp.asarray(rng.randn(64, 12).astype(np.float32))

        def loss(p):
            return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

        outs = {}
        for segmented in (False, True):
            opt = FusedLAMB(lr=1e-2, weight_decay=0.01, use_nvlamb=True,
                            max_grad_norm=1.0, segmented=segmented)
            st = opt.init(params)
            for _ in range(4):
                pt = st.space.unpack(st.master)
                new_params, st = opt.step(st, jax.grad(loss)(pt))
            outs[segmented] = new_params
        for a, b in zip(jax.tree.leaves(outs[False]),
                        jax.tree.leaves(outs[True])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-6, atol=2e-6)


class TestSegmentedLambSR:
    """Segmented one-pass LAMB + in-kernel stochastic rounding.

    The SR bits are a counter hash in plain uint32 ops (segmented.py),
    so the interpret schedule runs the EXACT stream the chip runs —
    this class is the off-chip correctness witness VERDICT r4 flagged
    as missing (the combination previously fell back even in
    interpret)."""

    def _const_setup(self, n_seg=2):
        from apex_tpu.multi_tensor.flat_buffer import segmented_space
        from apex_tpu.multi_tensor.segmented import CHUNK

        tree = {"w": jnp.full((n_seg * CHUNK,), 1.0, jnp.bfloat16)}
        space, meta = segmented_space(tree, seg_elems=n_seg * CHUNK)
        p = space.pack(tree, dtype=jnp.bfloat16)
        g = jnp.full((space.total,), 1.0, jnp.float32)
        return space, meta, p, g

    def test_sr_unbiased_below_ulp(self):
        """A constant update far below one bf16 ulp must survive in
        expectation: mean == 1 - lr (bias_correction=True, step 1 =>
        u = 1/(1+eps)), values land on the two bf16 neighbors. This is
        the exact check tools/tpu_smoke.py gates the chip on."""
        from apex_tpu.multi_tensor.segmented import (
            fused_lamb_segmented_update)

        space, meta, p, g = self._const_setup()
        m = jnp.zeros((space.total,), jnp.float32)
        v = jnp.zeros((space.total,), jnp.float32)
        lr = 2.0 ** -11
        p2, *_ = jax.jit(lambda p_, m_, v_, g_: fused_lamb_segmented_update(
            p_, m_, v_, g_, space, meta, lr=lr, weight_decay=0.0,
            use_nvlamb=False, step=1, max_grad_norm=0.0,
            bias_correction=True, impl="interpret", sr_seed=11))(p, m, v, g)
        vals = np.asarray(jax.device_get(p2), np.float32)
        exp = 1.0 - lr
        assert abs(float(vals.mean()) - exp) < 2e-4
        uniq = np.unique(vals)
        assert 1 < uniq.size <= 3, uniq

    def test_sr_stream_deterministic_and_seed_sensitive(self):
        from apex_tpu.multi_tensor.segmented import (
            fused_lamb_segmented_update)

        space, meta, p, g = self._const_setup()
        m = jnp.zeros((space.total,), jnp.float32)
        v = jnp.zeros((space.total,), jnp.float32)

        def run(seed):
            p2, *_ = fused_lamb_segmented_update(
                p, m, v, g, space, meta, lr=2.0 ** -11, weight_decay=0.0,
                use_nvlamb=False, step=1, max_grad_norm=0.0,
                bias_correction=True, impl="interpret", sr_seed=seed)
            return np.asarray(jax.device_get(p2), np.float32)

        a, b, c = run(7), run(7), run(8)
        np.testing.assert_array_equal(a, b)       # same seed: same stream
        assert (a != c).any()                     # new seed: new stream

    def test_sr_scratch_modes_also_lower(self):
        """SR composes with the VMEM-budget variants (p-stream and the
        bf16 u-stash) in the real kernel schedule."""
        from apex_tpu.multi_tensor.segmented import (
            fused_lamb_segmented_update)

        space, meta, p, g = self._const_setup()
        m = jnp.zeros((space.total,), jnp.float32)
        v = jnp.zeros((space.total,), jnp.float32)
        for kw in ({"stash_p": False},
                   {"stash_p": False, "u_dtype": jnp.bfloat16}):
            p2, *_ = fused_lamb_segmented_update(
                p, m, v, g, space, meta, lr=2.0 ** -11, weight_decay=0.0,
                use_nvlamb=False, step=1, max_grad_norm=0.0,
                bias_correction=True, impl="interpret", sr_seed=3, **kw)
            vals = np.asarray(jax.device_get(p2), np.float32)
            assert abs(float(vals.mean()) - (1.0 - 2.0 ** -11)) < 3e-4, kw

    @pytest.mark.slow
    def test_sr_trajectory_tracks_fp32_master(self, ):
        """Master-free bf16+SR training stays close to the fp32-master
        trajectory on a toy regression — the accuracy story behind the
        ~half param-side HBM traffic (ref csrc/multi_tensor_lamb_mp.cu
        mixed-dtype discipline)."""
        from apex_tpu.optimizers import FusedLAMB

        rng = np.random.RandomState(0)
        Xn = rng.randn(128, 24).astype(np.float32)
        W_t = rng.randn(24, 8).astype(np.float32)
        Y = jnp.asarray(Xn @ W_t)
        X = jnp.asarray(Xn)
        p0 = {"w": jnp.asarray(rng.randn(24, 8).astype(np.float32) * 0.2)}

        def loss(p):
            return jnp.mean((X @ p["w"].astype(jnp.float32) - Y) ** 2)

        finals = {}
        for mode in ("fp32", "sr"):
            if mode == "fp32":
                opt = FusedLAMB(lr=2e-2, weight_decay=0.0,
                                max_grad_norm=0.0, segmented=True,
                                impl="interpret")
                params = dict(p0)
            else:
                opt = FusedLAMB(lr=2e-2, weight_decay=0.0,
                                max_grad_norm=0.0, segmented=True,
                                impl="interpret",
                                master_dtype=jnp.bfloat16,
                                stochastic_rounding=True)
                params = jax.tree.map(
                    lambda l: l.astype(jnp.bfloat16), p0)
            st = opt.init(params)
            for _ in range(60):
                pt = st.space.unpack(st.master)
                _, st = opt.step(st, jax.grad(loss)(pt))
            finals[mode] = float(loss(st.space.unpack(st.master)))
        l0 = float(loss(p0))
        # trust-ratio pacing: assert real progress, not an absolute
        # floor (LAMB normalizes per-leaf update magnitude)
        assert finals["fp32"] < 0.2 * l0, (l0, finals)
        # SR must track fp32 closely (not stall at bf16 ulps): within
        # 50% of the master trajectory's final loss
        assert finals["sr"] < 1.5 * finals["fp32"] + 1e-3, finals

    def test_sharded_bf16_sr_step_under_shard_map(self):
        """ZeRO-style witness for the exact config the TPU bench runs:
        every device steps its own shard with the segmented kernel
        (interpret schedule), bf16 master + in-kernel SR, found_inf
        psum'd across the mesh (ref
        apex/contrib/optimizers/distributed_fused_lamb.py:83-120).

        The shard index is folded into ``sr_seed`` so each
        data-parallel shard draws its OWN rounding bit-stream: with a
        shared seed every replica rounds identically and the rounding
        bias no longer averages out across the fleet. Shards here get
        IDENTICAL (p, m, v, g) so decorrelation is directly visible in
        the outputs."""
        from jax import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu.multi_tensor.flat_buffer import segmented_space
        from apex_tpu.multi_tensor.segmented import (
            CHUNK, fused_lamb_segmented_update)

        ndev = len(jax.devices())
        tree = {"w": jnp.zeros((CHUNK,), jnp.bfloat16)}
        space, meta = segmented_space(tree, seg_elems=CHUNK)
        rng = np.random.RandomState(0)
        row_p = rng.randn(space.total).astype(np.float32)
        row_g = rng.randn(space.total).astype(np.float32) * 1e-2
        p = jnp.asarray(np.tile(row_p, (ndev, 1))).astype(jnp.bfloat16)
        g = jnp.asarray(np.tile(row_g, (ndev, 1)))
        m = jnp.zeros((ndev, space.total), jnp.float32)
        v = jnp.zeros((ndev, space.total), jnp.float32)
        mesh = Mesh(np.asarray(jax.devices()), ("dev",))

        def shard_step(p_, m_, v_, g_):
            p_, m_, v_, g_ = (x[0] for x in (p_, m_, v_, g_))
            # per-shard SR stream: fold the data-parallel shard index
            # into the seed (same discipline as per-step count folding)
            seed = 5 + jax.lax.axis_index("dev")
            p2, m2, v2, found = fused_lamb_segmented_update(
                p_, m_, v_, g_, space, meta, lr=1e-3, weight_decay=0.01,
                use_nvlamb=True, step=1, max_grad_norm=0.0,
                impl="interpret", sr_seed=seed)
            found = jax.lax.psum(found, "dev")
            return (p2[None], m2[None], v2[None],
                    jnp.broadcast_to(found, (1,)))

        p2, m2, v2, found = jax.jit(shard_map(
            shard_step, mesh=mesh,
            in_specs=(P("dev"), P("dev"), P("dev"), P("dev")),
            out_specs=(P("dev"), P("dev"), P("dev"), P("dev")),
            check_vma=False))(p, m, v, g)
        assert p2.shape == p.shape and p2.dtype == jnp.bfloat16
        assert float(np.asarray(found)[0]) == 0.0
        # every shard actually moved, and moments are finite
        moved = np.asarray(
            (p2.astype(jnp.float32) != p.astype(jnp.float32)).any(axis=1))
        assert moved.all()
        assert np.isfinite(np.asarray(m2)).all()
        # identical inputs, per-shard seeds: the fp32 moment updates
        # must agree bit-for-bit across shards while the SR-rounded
        # params differ somewhere (decorrelated rounding streams)
        m2_np = np.asarray(m2)
        np.testing.assert_array_equal(m2_np, np.tile(m2_np[0], (ndev, 1)))
        if ndev > 1:
            p2_np = np.asarray(p2.astype(jnp.float32))
            assert any((p2_np[i] != p2_np[0]).any()
                       for i in range(1, ndev)), (
                "all shards drew an identical rounding bit-stream")

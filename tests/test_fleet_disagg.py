"""Disaggregated prefill/decode (apex_tpu/serving/fleet.py,
docs/serving.md "Disaggregated prefill/decode").

Anchors:

- fault grammar: the ``kv_transfer_corrupt`` / ``kv_transfer_timeout``
  / ``kv_transfer_partial`` / ``handoff_orphan`` clauses (+
  ``io:kv_handoff``) parse from the env grammar and sequence
  deterministically per transfer attempt / per handoff;
- roles are POLICY, not capability: a prefill-only fleet decodes its
  own streams, a decode-only fleet admits — zero drops outranks the
  split, always bitwise vs a colocated run;
- the clean split: prefill engines run admission + prefill then hand
  the KV blocks to a decode engine over the manifested wire — every
  stream bitwise, a ``handoff`` span per shipped request, ONE perfetto
  track end to end;
- verify-before-install: corrupt / timed-out / partial wire payloads
  are refused against the sha256 manifest and re-sent (idempotent,
  same root hash); the stream never sees a poisoned block;
- the failure ladder: exhausted retries keep the stream decoding on
  the SOURCE; repeated failures latch colocated fallback
  (``reason="handoff_degraded"``, a ``kv_handoff_failed`` bundle with
  the manifest + per-block verify log) and a clean health probe
  unlatches it;
- orphaned exports free dirty (scrub-before-reuse) and replay on the
  same trace id; a decode engine dying MID-HANDOFF is fenced and the
  victim re-prefills on a survivor — still bitwise.
"""

import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from apex_tpu import serving, telemetry  # noqa: E402
from apex_tpu.models.gpt import GPTConfig, GPTModel  # noqa: E402
from apex_tpu.resilience import faults  # noqa: E402
from apex_tpu.serving.kv_cache import KVCache  # noqa: E402

VOCAB, SEQ, HID, LAYERS, HEADS, KV = 64, 64, 32, 2, 4, 2
BLOCKS, BS = 24, 4


def tiny_config(**kw):
    base = dict(vocab_size=VOCAB, max_seq_len=SEQ, hidden_size=HID,
                num_layers=LAYERS, num_heads=HEADS, num_kv_heads=KV,
                dtype=jnp.float32, param_dtype=jnp.float32)
    base.update(kw)
    return GPTConfig(**base)


def fresh_cache(num_blocks=BLOCKS, block_size=BS):
    return KVCache(LAYERS, KV, HID // HEADS, num_blocks=num_blocks,
                   block_size=block_size, dtype=jnp.float32)


@pytest.fixture(scope="module")
def model_and_params():
    model = GPTModel(tiny_config())
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, VOCAB, (1, 8)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks)
    return model, params


@pytest.fixture(scope="module")
def step_fn(model_and_params):
    model, _ = model_and_params
    return serving.make_decode_step(model, fresh_cache())


def make_engine(model, params, step_fn, reg):
    cache = fresh_cache()
    b = serving.ContinuousBatcher(model, params, cache, step_fn=step_fn,
                                  registry=reg, max_batch=4,
                                  max_prefill_batch=4)
    return b, cache


def make_fleet(model, params, step_fn, roles, **router_kw):
    router_kw.setdefault("stall_after_s", 30.0)
    router_kw.setdefault("retry_base_delay", 0.0)
    reg = telemetry.MetricsRegistry()
    sink = telemetry.InMemorySink()
    reg.add_sink(sink)
    tracer = serving.RequestTracer()
    router = serving.FleetRouter(registry=reg, tracer=tracer,
                                 **router_kw)
    for i, role in enumerate(roles):
        b, cache = make_engine(model, params, step_fn, reg)
        router.add_engine(f"e{i}", b, cache.init_state(), role=role)
    return router, reg, sink, tracer


def drive(router):
    out = []
    n = 0
    while not router.idle():
        router.step()
        out.extend(router.merge_results())
        n += 1
        assert n < 500, "fleet did not converge"
    out.extend(router.merge_results())
    return out


def mk_requests(n, rng, **kw):
    return [serving.Request(
        id=i, prompt=rng.randint(0, VOCAB, (int(rng.randint(2, 9)),)),
        max_new_tokens=int(rng.randint(3, 7)), **kw) for i in range(n)]


def run_clean(model, params, step_fn, requests):
    """Token streams per id from an uninterrupted single-engine run."""
    reg = telemetry.MetricsRegistry()
    eng, cache = make_engine(model, params, step_fn, reg)
    _, results = serving.serve_loop(eng, cache.init_state(), requests)
    return {r.id: r.tokens for r in results}


def run_disagg(model, params, step_fn, roles, requests, **router_kw):
    router, reg, sink, tracer = make_fleet(model, params, step_fn,
                                           roles, **router_kw)
    for r in requests:
        router.submit(serving.Request(id=r.id, prompt=r.prompt,
                                      max_new_tokens=r.max_new_tokens))
    results = drive(router)
    return {r.id: r.tokens for r in results}, router, reg, sink, tracer


# ---------------------------------------------------------------------------
# fault grammar
# ---------------------------------------------------------------------------


class TestHandoffGrammar:
    def test_env_grammar_parses_handoff_clauses(self):
        inj = faults.FaultInjector.from_env(
            "kv_transfer_corrupt=0,3;kv_transfer_timeout=1;"
            "kv_transfer_partial=5;handoff_orphan=2;io:kv_handoff=4")
        assert inj.kv_transfer_corrupt == frozenset({0, 3})
        assert inj.kv_transfer_timeout == frozenset({1})
        assert inj.kv_transfer_partial == frozenset({5})
        assert inj.handoff_orphan == frozenset({2})
        assert inj.io_errors["kv_handoff"] == frozenset({4})

    def test_kv_transfer_fault_sequences_per_attempt(self):
        inj = faults.FaultInjector(
            kv_transfer_corrupt=frozenset({0}),
            kv_transfer_timeout=frozenset({1}),
            kv_transfer_partial=frozenset({2}))
        # 0-based global attempt counter: one draw per wire transfer
        assert inj.kv_transfer_fault() == "corrupt"
        assert inj.kv_transfer_fault() == "timeout"
        assert inj.kv_transfer_fault() == "partial"
        assert inj.kv_transfer_fault() is None

    def test_orphan_sequences_per_handoff(self):
        inj = faults.FaultInjector(handoff_orphan=frozenset({1}))
        assert not inj.should_orphan_handoff()
        assert inj.should_orphan_handoff()
        assert not inj.should_orphan_handoff()

    def test_role_is_validated(self, model_and_params, step_fn):
        model, params = model_and_params
        assert serving.ENGINE_ROLES == ("prefill", "decode", "colocated")
        router, reg, _, _ = make_fleet(model, params, step_fn, [])
        b, cache = make_engine(model, params, step_fn, reg)
        with pytest.raises(ValueError, match="role"):
            router.add_engine("bad", b, cache.init_state(),
                              role="prefetcher")


# ---------------------------------------------------------------------------
# the clean split: roles as policy, bitwise streams, one track
# ---------------------------------------------------------------------------


class TestDisaggClean:
    def test_split_is_bitwise_with_handoff_spans(self, model_and_params,
                                                 step_fn):
        model, params = model_and_params
        reqs = mk_requests(12, np.random.RandomState(7))
        clean = run_clean(model, params, step_fn, reqs)
        got, router, reg, _, tracer = run_disagg(
            model, params, step_fn, ["prefill", "decode", "decode"],
            reqs)
        assert got == clean
        intro = router.introspect()
        ho = intro["handoff"]
        assert ho["ok"] > 0, "no handoffs in a prefill/decode split"
        assert ho["failed"] == 0 and ho["orphan"] == 0
        assert ho["bytes"] > 0
        assert not ho["fallback"]["latched"]
        assert intro["engines"]["e0"]["role"] == "prefill"
        assert intro["engines"]["e1"]["role"] == "decode"
        assert intro["engines"]["e0"]["handoffs_out"] == ho["ok"]
        assert (intro["engines"]["e1"]["handoffs_in"]
                + intro["engines"]["e2"]["handoffs_in"]) == ho["ok"]
        assert reg.counter("fleet_handoffs").value(outcome="ok") \
            == ho["ok"]
        assert reg.counter("fleet_handoff_bytes").value() == ho["bytes"]
        # a `handoff` span per shipped request, on a SINGLE live
        # segment — the perfetto export stays one track per request
        done = tracer.completed()
        spans = [s for t in done for s in t.spans
                 if s["name"] == "handoff"]
        assert len(spans) == ho["ok"]
        assert all(s["args"]["src"] == "e0" for s in spans)
        trace = tracer.export_trace()
        metas = [e for e in trace["traceEvents"] if e.get("ph") == "M"]
        assert len(metas) == len(reqs)

    def test_prefill_only_fleet_decodes_locally(self, model_and_params,
                                                step_fn):
        # no decode seat anywhere: the role is routing policy, not a
        # capability — the prefill engine IS the colocated floor
        model, params = model_and_params
        reqs = mk_requests(6, np.random.RandomState(8))
        clean = run_clean(model, params, step_fn, reqs)
        got, router, _, _, _ = run_disagg(
            model, params, step_fn, ["prefill"], reqs)
        assert got == clean
        assert router.introspect()["handoff"]["ok"] == 0

    def test_decode_only_fleet_admits(self, model_and_params, step_fn):
        # admission prefers non-decode seats but never refuses for
        # role purity: a decode-only fleet still serves everything
        model, params = model_and_params
        reqs = mk_requests(6, np.random.RandomState(9))
        clean = run_clean(model, params, step_fn, reqs)
        got, _, _, _, _ = run_disagg(
            model, params, step_fn, ["decode", "decode"], reqs)
        assert got == clean


# ---------------------------------------------------------------------------
# the failure ladder
# ---------------------------------------------------------------------------


class TestHandoffFaults:
    def test_transient_wire_faults_absorbed_bitwise(
            self, model_and_params, step_fn):
        model, params = model_and_params
        reqs = mk_requests(12, np.random.RandomState(7))
        clean = run_clean(model, params, step_fn, reqs)
        with faults.inject(kv_transfer_corrupt=frozenset({0, 3}),
                           kv_transfer_timeout=frozenset({1}),
                           kv_transfer_partial=frozenset({5})):
            got, router, reg, _, _ = run_disagg(
                model, params, step_fn, ["prefill", "decode"], reqs)
        # every refused payload was re-sent under the same manifest
        # root; nothing corrupt ever installed
        assert got == clean
        ho = router.introspect()["handoff"]
        assert ho["retries"] > 0
        assert ho["failed"] == 0
        assert reg.counter("fleet_handoff_retries").value() \
            == ho["retries"]

    def test_persistent_corrupt_latches_with_bundle(
            self, model_and_params, step_fn, tmp_path, monkeypatch):
        from apex_tpu import records
        from apex_tpu.telemetry import flight

        monkeypatch.setattr(records, "RECORDS_DIR", str(tmp_path / "r"))
        model, params = model_and_params
        reqs = mk_requests(12, np.random.RandomState(7))
        clean = run_clean(model, params, step_fn, reqs)
        flight.enable()
        try:
            with faults.inject(
                    kv_transfer_corrupt=frozenset(range(10000))):
                got, router, reg, sink, _ = run_disagg(
                    model, params, step_fn, ["prefill", "decode"],
                    reqs, fallback_after=2)
        finally:
            flight.disable()
        # zero dropped: every stream decoded locally on the source,
        # bitwise — degraded mode costs the split, never a request
        assert got == clean
        ho = router.introspect()["handoff"]
        assert ho["ok"] == 0 and ho["failed"] >= 2
        assert ho["fallback"]["latched"]
        assert ho["fallback"]["consecutive_failures"] >= 2
        assert reg.gauge("fleet_colocated_fallback_latched").value() == 1
        assert reg.counter("fleet_colocated_fallback").value(
            transition="latched") == 1
        assert reg.counter("fleet_requests_rerouted").value(
            cause="handoff_degraded") == ho["failed"]
        evs = [e for e in sink.events
               if e.get("event") == "fleet_colocated_fallback"]
        assert any(e.get("transition") == "latched"
                   and e.get("reason") == "handoff_degraded"
                   for e in evs)
        assert "kv_handoff_failed" in [e["event"] for e in sink.events]
        # the bundle embeds the manifest + the last attempt's verify
        rec = records.latest_record(flight.FLIGHT_KIND,
                                    require_backend=None)
        assert rec["payload"]["trigger"] == "kv_handoff_failed"
        extra = rec["payload"]["extra"]
        assert extra["src"] == "e0" and extra["dst"] == "e1"
        assert extra["attempts"] == 3          # handoff_retries=2 + 1
        assert len(extra["manifest"]["root"]) == 64
        assert extra["manifest"]["blocks"]
        assert any(not v["ok"] for v in extra["verify"])

    def test_clean_probe_unlatches(self, model_and_params, step_fn):
        model, params = model_and_params
        reqs = mk_requests(6, np.random.RandomState(7))
        clean = run_clean(model, params, step_fn, reqs)
        # the first four transfers corrupt, then the wire heals: with
        # fallback_after=1 and no retries the first handoff latches,
        # later probes fail until attempt 4, then one clean probe
        # reopens the split for the remaining work
        with faults.inject(kv_transfer_corrupt=frozenset(range(4))):
            got, router, reg, sink, _ = run_disagg(
                model, params, step_fn, ["prefill", "decode"], reqs,
                fallback_after=1, handoff_retries=0)
        assert {i: got[i] for i in got} == {i: clean[i] for i in got}
        lat = [e.get("transition") for e in sink.events
               if e.get("event") == "fleet_colocated_fallback"]
        assert "latched" in lat and "unlatched" in lat
        assert not router.introspect()["handoff"]["fallback"]["latched"]
        assert reg.counter("fleet_handoff_probes").value(
            outcome="ok") >= 1
        assert reg.counter("fleet_handoff_probes").value(
            outcome="failed") >= 1
        assert reg.gauge("fleet_colocated_fallback_latched").value() == 0

    def test_orphaned_export_scrubbed_and_replayed(
            self, model_and_params, step_fn):
        model, params = model_and_params
        reqs = mk_requests(12, np.random.RandomState(7))
        clean = run_clean(model, params, step_fn, reqs)
        with faults.inject(handoff_orphan=frozenset({0})):
            got, router, reg, sink, tracer = run_disagg(
                model, params, step_fn, ["prefill", "decode"], reqs)
        assert got == clean
        ho = router.introspect()["handoff"]
        assert ho["orphan"] == 1
        assert reg.counter("fleet_handoffs").value(outcome="orphan") == 1
        assert reg.counter("fleet_requests_rerouted").value(
            cause="handoff_orphan") == 1
        assert "fleet_handoff_orphan" in [e["event"] for e in sink.events]
        # the orphan's stream replayed under its ORIGINAL trace id
        done = tracer.completed()
        resumed = [t for t in done
                   if t.resumed_from
                   and t.resumed_from.startswith("handoff_")]
        assert len(resumed) == 1
        rerouted = [t for t in done if t.outcome == "rerouted"
                    and t.trace_id == resumed[0].trace_id]
        assert rerouted

    def test_decode_crash_mid_handoff_replays_bitwise(
            self, model_and_params, step_fn):
        model, params = model_and_params
        reqs = mk_requests(12, np.random.RandomState(7))
        clean = run_clean(model, params, step_fn, reqs)
        # at router step 0 the decode seat is still idle — the crash
        # fires inside the transfer attempt, not the engine loop
        with faults.inject(engine_crash_steps=frozenset({0}),
                           engine_crash_engine=1):
            got, router, reg, _, tracer = run_disagg(
                model, params, step_fn,
                ["prefill", "decode", "decode"], reqs)
        assert got == clean
        ho = router.introspect()["handoff"]
        assert ho["dst_crash"] >= 1
        assert router.failovers and router.failovers[0]["cause"] == "crash"
        [h1] = [h for h in router.engines() if h.name == "e1"]
        assert h1.status == "fenced"
        assert reg.counter("fleet_requests_rerouted").value(
            cause="handoff_dst_crash") >= 1
        # still one perfetto track per request, crash and all
        trace = tracer.export_trace()
        by_req = {}
        for ev in trace["traceEvents"]:
            if ev.get("ph") == "M":
                continue
            rid = ev["args"].get("trace_id")
            by_req.setdefault(rid, set()).add(ev["tid"])
        assert by_req and all(len(t) == 1 for t in by_req.values())


# ---------------------------------------------------------------------------
# take_queued under concurrent submit (the withdraw/hedge edge)
# ---------------------------------------------------------------------------


class TestTakeQueuedRace:
    def test_take_queued_races_concurrent_submit(self, model_and_params,
                                                 step_fn):
        # the router withdraws queued work (hedges, recovery) while the
        # frontend keeps submitting: every request ends up EXACTLY once
        # — either withdrawn or still queued, never both, never lost
        model, params = model_and_params
        reg = telemetry.MetricsRegistry()
        eng, _ = make_engine(model, params, step_fn, reg)
        N = 200
        taken = []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                taken.extend(eng.take_queued(2))

        t = threading.Thread(target=hammer)
        t.start()
        try:
            for i in range(N):
                eng.submit(serving.Request(id=i, prompt=[1, 2],
                                           max_new_tokens=1))
        finally:
            stop.set()
            t.join()
        taken.extend(eng.take_queued())
        left = [r.id for r, _ in eng.queue]
        got = sorted([r.id for r, _ in taken] + left)
        assert got == list(range(N))

"""Batch samplers, arguments harness, ResNet, and example smoke runs
(mirrors ref tests/L0 microbatches tests + L1 example cross-products,
shrunk to CPU-mesh scale)."""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.resnet import ResNet, ResNetConfig, cross_entropy_logits
from apex_tpu.transformer._data import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)
from apex_tpu.transformer.testing.arguments import parse_args
from apex_tpu.transformer.testing import global_vars


class TestSamplers:
    def test_sequential_disjoint_ranks(self):
        got = []
        for rank in range(2):
            s = MegatronPretrainingSampler(
                total_samples=20, consumed_samples=0,
                local_minibatch_size=3, data_parallel_rank=rank,
                data_parallel_size=2)
            got.append(list(s))
        # batches align step-wise; ranks see disjoint, contiguous spans
        assert got[0][0] == [0, 1, 2] and got[1][0] == [3, 4, 5]
        assert got[0][1] == [6, 7, 8] and got[1][1] == [9, 10, 11]
        flat = [i for b in got[0] + got[1] for i in b]
        assert len(set(flat)) == len(flat)

    def test_sequential_resume(self):
        s = MegatronPretrainingSampler(
            total_samples=20, consumed_samples=6,
            local_minibatch_size=3, data_parallel_rank=0,
            data_parallel_size=2)
        assert list(s)[0] == [6, 7, 8]

    def test_sequential_drop_last(self):
        s = MegatronPretrainingSampler(
            total_samples=10, consumed_samples=0,
            local_minibatch_size=3, data_parallel_rank=0,
            data_parallel_size=2, drop_last=False)
        batches = list(s)
        assert batches[-1] == [6, 7, 8]  # partial tail, rank-0 span

    def test_sequential_validation(self):
        with pytest.raises(RuntimeError):
            MegatronPretrainingSampler(0, 0, 1, 0, 1)
        with pytest.raises(RuntimeError):
            MegatronPretrainingSampler(10, 10, 1, 0, 1)
        with pytest.raises(RuntimeError):
            MegatronPretrainingSampler(10, 0, 1, 3, 2)

    def test_random_deterministic_and_disjoint(self):
        a0 = list(MegatronPretrainingRandomSampler(
            total_samples=32, consumed_samples=0, local_minibatch_size=4,
            data_parallel_rank=0, data_parallel_size=2))
        a0b = list(MegatronPretrainingRandomSampler(
            total_samples=32, consumed_samples=0, local_minibatch_size=4,
            data_parallel_rank=0, data_parallel_size=2))
        a1 = list(MegatronPretrainingRandomSampler(
            total_samples=32, consumed_samples=0, local_minibatch_size=4,
            data_parallel_rank=1, data_parallel_size=2))
        assert a0 == a0b  # deterministic
        flat0 = {i for b in a0 for i in b}
        flat1 = {i for b in a1 for i in b}
        assert not (flat0 & flat1)
        assert flat0 | flat1 == set(range(32))

    def test_random_epoch_reshuffles(self):
        e0 = list(MegatronPretrainingRandomSampler(
            total_samples=32, consumed_samples=0, local_minibatch_size=4,
            data_parallel_rank=0, data_parallel_size=1))
        e1 = list(MegatronPretrainingRandomSampler(
            total_samples=32, consumed_samples=32, local_minibatch_size=4,
            data_parallel_rank=0, data_parallel_size=1))
        assert e0 != e1


class TestArguments:
    def test_defaults_and_derived(self):
        ns = parse_args(args=[])
        assert ns.ffn_hidden_size == 4 * ns.hidden_size
        assert ns.global_batch_size == ns.micro_batch_size
        assert ns.params_dtype == "float32"

    def test_mesh_args(self):
        ns = parse_args(args=[
            "--tensor-model-parallel-size", "2",
            "--context-parallel-size", "4", "--sequence-parallel", "--bf16"])
        assert ns.tensor_model_parallel_size == 2
        assert ns.context_parallel_size == 4
        assert ns.sequence_parallel
        assert ns.params_dtype == "bfloat16"

    def test_fp16_bf16_exclusive(self):
        with pytest.raises(ValueError):
            parse_args(args=["--fp16", "--bf16"])

    def test_reference_flag_surface(self):
        """The flag groups the reference fixtures drive (ref
        arguments.py): kv-channels derivation, virtual-pp from
        layers-per-virtual-stage, recompute knobs, precision extras."""
        ns = parse_args(args=[
            "--num-layers", "8", "--hidden-size", "128",
            "--num-attention-heads", "8",
            "--pipeline-model-parallel-size", "2",
            "--num-layers-per-virtual-pipeline-stage", "2",
            "--adam-beta2", "0.95", "--init-method-std", "0.01",
            "--lr-decay-style", "cosine", "--lr-warmup-iters", "5",
            "--attention-softmax-in-fp32",
            "--accumulate-allreduce-grads-in-fp32",
            "--recompute-granularity", "full",
            "--make-vocab-size-divisible-by", "64",
            "--eval-iters", "7", "--mask-prob", "0.2",
            "--bert-no-binary-head",
        ])
        assert ns.kv_channels == 16
        assert ns.virtual_pipeline_model_parallel_size == 2
        assert ns.adam_beta2 == 0.95
        assert ns.attention_softmax_in_fp32
        assert ns.checkpoint_activations       # implied by recompute
        assert not ns.bert_binary_head
        assert ns.eval_iters == 7 and ns.mask_prob == 0.2

    def test_validation_errors(self):
        with pytest.raises(ValueError, match="not divisible"):
            parse_args(args=["--num-layers", "5",
                             "--pipeline-model-parallel-size", "2"])
        with pytest.raises(ValueError, match="max-position"):
            parse_args(args=["--seq-length", "64",
                             "--max-position-embeddings", "32"])
        with pytest.raises(ValueError, match="divisible by"):
            parse_args(args=["--micro-batch-size", "3",
                             "--global-batch-size", "8"])
        with pytest.raises(ValueError, match="tensor parallelism"):
            parse_args(args=["--distribute-saved-activations"])
        with pytest.raises(ValueError, match="fp16"):
            parse_args(args=["--fp16-lm-cross-entropy"])

    def test_global_vars_lifecycle(self):
        global_vars.destroy_global_vars()
        with pytest.raises(RuntimeError):
            global_vars.get_args()
        sys_argv = sys.argv
        sys.argv = ["prog"]
        try:
            ns = global_vars.set_global_variables(
                args_defaults={"hidden_size": 96})
        finally:
            sys.argv = sys_argv
        assert global_vars.get_args().hidden_size == 96
        t = global_vars.get_timers()
        t("fwd").start()
        t("fwd").stop()
        assert "fwd" in t.log(["fwd"])
        global_vars.destroy_global_vars()


class TestResNet:
    @pytest.mark.slow
    def test_forward_and_train_smoke(self, rng):
        cfg = ResNetConfig.resnet18ish(num_classes=10, dtype=jnp.float32)
        model = ResNet(cfg)
        x = jnp.asarray(rng.rand(2, 32, 32, 3), jnp.float32)
        variables = model.init(jax.random.PRNGKey(0), x)
        logits, mut = model.apply(variables, x, mutable=["batch_stats"])
        assert logits.shape == (2, 10)

        y = jnp.asarray([1, 2], jnp.int32)
        g = jax.grad(lambda p: cross_entropy_logits(
            model.apply({"params": p,
                         "batch_stats": variables["batch_stats"]},
                        x, train=True, mutable=["batch_stats"])[0], y)
        )(variables["params"])
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree.leaves(g))

    def test_eval_uses_running_stats(self, rng):
        cfg = ResNetConfig.resnet18ish(num_classes=10, dtype=jnp.float32)
        model = ResNet(cfg)
        x = jnp.asarray(rng.rand(2, 32, 32, 3), jnp.float32)
        variables = model.init(jax.random.PRNGKey(0), x)
        out1 = model.apply(variables, x, train=False)
        out2 = model.apply(variables, x, train=False)
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def _load_example(path, name):
    import importlib.util
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod   # flax dataclass transform resolves __module__
    spec.loader.exec_module(mod)
    return mod


class TestExamples:
    """Import-and-run smoke of the example mains (ref tests/L1 runs the
    example trainers across opt-levels and compares)."""

    def test_simple_distributed(self):
        ex = _load_example(
            "examples/simple/distributed/distributed_data_parallel.py",
            "ex_simple_ddp")
        loss = ex.main(["--steps", "25", "--batch-size", "32"])
        assert np.isfinite(loss) and loss < 2.35

    def test_amp_functional_o1(self):
        """The zero-registration O1 port path (amp.F + shipped lists)."""
        from apex_tpu.amp import _amp_state

        ex = _load_example("examples/amp_functional/main.py",
                           "ex_amp_functional")
        prev = _amp_state.get_active()
        try:
            ex.main()          # asserts its own loss improvement
        finally:
            _amp_state.set_active(prev)

    @pytest.mark.parametrize("opt_level", [
        "O1", pytest.param("O5", marks=pytest.mark.slow)])
    def test_imagenet_tiny(self, opt_level, tmp_path):
        ex = _load_example("examples/imagenet/main_amp.py", "ex_imagenet")
        ckpt = str(tmp_path / "ck.npz")
        loss = ex.main(["--arch", "tiny", "--steps", "6",
                        "--batch-size", "16", "--opt-level", opt_level,
                        "--sync-bn", "--save", ckpt])
        assert np.isfinite(loss)
        loss2 = ex.main(["--arch", "tiny", "--steps", "8",
                         "--batch-size", "16", "--opt-level", opt_level,
                         "--resume", ckpt])
        assert np.isfinite(loss2)

    @pytest.mark.slow
    def test_gpt_pretrain(self, tmp_path):
        """The L5 example: tp x pp x dp mesh train loop + orbax resume."""
        ex = _load_example("examples/gpt_pretrain/pretrain_gpt.py",
                           "ex_gpt_pretrain")
        save = str(tmp_path / "ck")
        argv = ["--steps", "4", "--tp", "2", "--pp", "2",
                "--hidden", "64", "--layers", "2", "--seq", "32",
                "--vocab", "128", "--save", save]
        loss = ex.main(argv)
        assert np.isfinite(loss)
        # resume continues from the saved step (same flags, more steps)
        loss2 = ex.main(argv[:1] + ["6"] + argv[2:])
        assert np.isfinite(loss2)

    @pytest.mark.parametrize("attn", [
        pytest.param("ring", marks=pytest.mark.slow), "ulysses"])
    def test_long_context(self, attn):
        """Beyond-reference long-context example: sequence sharded over
        the cp axis, exact causal attention via ring/Ulysses."""
        ex = _load_example("examples/long_context/train_long_context.py",
                           f"ex_long_context_{attn}")
        loss = ex.main(["--seq", "128", "--cp", "4", "--steps", "60",
                        "--hidden", "32", "--vocab", "32",
                        "--lr", "5e-3", "--attn", attn])
        assert np.isfinite(loss) and loss < 2.9   # from ~3.47 at init

    def test_multihead_attn_perf_example(self):
        """ref apex/contrib/examples/multihead_attn: the standalone
        func/perf sweep, flag surface included."""
        ex = _load_example(
            "examples/multihead_attn/perf_test_multihead_attn.py",
            "ex_mha_perf")
        rows = ex.main(["--seq-length", "32", "--num-seqs-start", "4",
                        "--num-seqs-stop", "8", "--num-seqs-inc", "4",
                        "--trials", "2", "--warmup-trials", "1",
                        "--layers", "2", "--hidden-dim", "64",
                        "--heads", "4"])
        assert len(rows) == 2 and all(t > 0 for _, t in rows)
        rows = ex.main(["--seq-length", "32", "--num-seqs-start", "4",
                        "--num-seqs-stop", "4", "--num-seqs-inc", "4",
                        "--trials", "2", "--warmup-trials", "1",
                        "--layers", "1", "--hidden-dim", "64",
                        "--heads", "4", "--encdec-attn", "--ref",
                        "--fwd", "--norm-add", "--biases"])
        assert len(rows) == 1

    @pytest.mark.slow
    def test_dcgan(self):
        ex = _load_example("examples/dcgan/main_amp.py", "ex_dcgan")
        lD, lG = ex.main(["--steps", "4", "--batch-size", "8",
                          "--image-size", "16"])
        assert np.isfinite(lD) and np.isfinite(lG)


class TestMultiproc:
    def test_single_host_noop(self, monkeypatch):
        from apex_tpu.parallel import multiproc
        for var in ("MASTER_ADDR", "WORLD_SIZE", "RANK"):
            monkeypatch.delenv(var, raising=False)
        multiproc.initialize_distributed()  # no cluster env: no-op
        assert multiproc.local_rank() == 0
        assert multiproc.world_size() == 1

    def test_world_size_one_noop(self, monkeypatch):
        from apex_tpu.parallel import multiproc
        monkeypatch.setenv("MASTER_ADDR", "127.0.0.1")
        monkeypatch.setenv("WORLD_SIZE", "1")
        monkeypatch.setenv("RANK", "0")
        multiproc.initialize_distributed()  # world of 1: no-op
        assert multiproc.world_size() == 1


class TestCommonUtils:
    """ref apex/testing/common_utils.py env-gated skips."""

    def test_skip_flaky_honors_env(self, monkeypatch):
        import unittest

        from apex_tpu.testing import common_utils

        calls = []
        monkeypatch.setattr(common_utils, "SKIP_FLAKY_TEST", True)

        @common_utils.skipFlakyTest
        def flaky():
            calls.append(1)

        with pytest.raises(unittest.SkipTest):
            flaky()
        monkeypatch.setattr(common_utils, "SKIP_FLAKY_TEST", False)

        @common_utils.skipFlakyTest
        def fine():
            calls.append(2)

        fine()
        assert calls == [2]

    def test_tpu_gates(self, monkeypatch):
        import unittest

        from apex_tpu.testing import common_utils

        monkeypatch.setattr(common_utils, "TEST_ON_TPU", False)

        @common_utils.skipIfNotTpu
        def needs_tpu():
            pass

        with pytest.raises(unittest.SkipTest):
            needs_tpu()

        @common_utils.skipIfTpu
        def cpu_ok():
            return "ran"

        assert cpu_ok() == "ran"

"""Contrib op tests — each fused op vs a pure-Python reference
(mirrors ref apex/contrib/test/{clip_grad,focal_loss,index_mul_2d,
transducer} test style: numeric parity + gradient checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.clip_grad import clip_grad_norm_
from apex_tpu.contrib.focal_loss import focal_loss
from apex_tpu.contrib.index_mul_2d import index_mul_2d
from apex_tpu.contrib.transducer import (
    TransducerJoint,
    TransducerLoss,
    transducer_loss,
)
from apex_tpu.contrib.xentropy import softmax_cross_entropy


class TestClipGrad:
    def _tree(self, rng):
        return {
            "a": jnp.asarray(rng.randn(17, 5), jnp.float32),
            "b": [jnp.asarray(rng.randn(3), jnp.float32),
                  jnp.asarray(rng.randn(2, 2, 2), jnp.float32)],
        }

    def test_norm_matches_numpy(self, rng, impl):
        g = self._tree(rng)
        _, norm = clip_grad_norm_(g, 1.0, impl=impl)
        ref = np.sqrt(sum(
            float(np.sum(np.asarray(l) ** 2)) for l in jax.tree.leaves(g)))
        np.testing.assert_allclose(float(norm), ref, rtol=1e-5)

    def test_clips_to_max_norm(self, rng):
        g = self._tree(rng)
        clipped, norm = clip_grad_norm_(g, 0.5)
        new_norm = np.sqrt(sum(
            float(np.sum(np.asarray(l) ** 2))
            for l in jax.tree.leaves(clipped)))
        assert float(norm) > 0.5
        np.testing.assert_allclose(new_norm, 0.5, rtol=1e-4)

    def test_no_clip_below_max(self, rng):
        g = jax.tree.map(lambda l: l * 1e-3, self._tree(rng))
        clipped, _ = clip_grad_norm_(g, 10.0)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6),
            clipped, g)

    def test_inf_norm(self, rng):
        g = self._tree(rng)
        _, norm = clip_grad_norm_(g, 1.0, norm_type=float("inf"))
        ref = max(float(np.abs(np.asarray(l)).max())
                  for l in jax.tree.leaves(g))
        np.testing.assert_allclose(float(norm), ref, rtol=1e-6)

    def test_jit(self, rng):
        g = self._tree(rng)
        clipped, norm = jax.jit(
            lambda g: clip_grad_norm_(g, 0.5))(g)
        assert np.isfinite(float(norm))


def _focal_ref(p, y, npos, nreal, alpha, gamma, s):
    """Slow numpy focal loss with the reference kernel's semantics."""
    p = np.asarray(p, np.float64)
    total = 0.0
    N, C = p.shape
    for i in range(N):
        if y[i] == -2:
            continue
        for j in range(min(C, nreal)):
            pos = (y[i] >= 0 and j == y[i])
            q = 1 - s / 2 if pos else s / 2
            sig = 1 / (1 + np.exp(-p[i, j]))
            bce = max(p[i, j], 0) - p[i, j] * q + np.log1p(np.exp(-abs(p[i, j])))
            pt = sig if pos else 1 - sig
            w = (alpha if pos else 1 - alpha) * (1 - pt) ** gamma
            total += w * bce
    return total / npos


class TestFocalLoss:
    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_vs_reference(self, rng, smoothing):
        N, C, nreal = 12, 8, 6
        p = rng.randn(N, C).astype(np.float32)
        y = rng.randint(-2, nreal, N)
        npos = max(float((y >= 0).sum()), 1.0)
        out = focal_loss(jnp.asarray(p), jnp.asarray(y), jnp.asarray(npos),
                         nreal, 0.25, 2.0, smoothing)
        ref = _focal_ref(p, y, npos, nreal, 0.25, 2.0, smoothing)
        np.testing.assert_allclose(float(out), ref, rtol=1e-4)

    def test_grads_zero_for_ignored(self, rng):
        N, C = 4, 4
        p = jnp.asarray(rng.randn(N, C), jnp.float32)
        y = jnp.asarray([0, -2, 1, -1])
        g = jax.grad(lambda p: focal_loss(p, y, jnp.asarray(2.0), C,
                                          0.25, 2.0))(p)
        np.testing.assert_allclose(np.asarray(g[1]), 0.0)  # y=-2 row
        assert float(jnp.abs(g[3]).sum()) > 0  # y=-1 (background) row


class TestXentropyTiling:
    """Mosaic-legality guard: ragged row counts and huge vocabularies
    must fall back to the XLA path instead of emitting illegal
    (tile, cols) blocks (tile not a multiple of 8 / VMEM-busting)."""

    @pytest.mark.parametrize("rows,cols", [(1001, 512), (16, 300_000),
                                           (12, 512)])
    def test_awkward_shapes_match_xla(self, rng, impl, rows, cols):
        from apex_tpu.ops import softmax_cross_entropy_loss

        logits = jnp.asarray(rng.randn(rows, cols).astype(np.float32))
        labels = jnp.asarray(rng.randint(0, cols, (rows,)), jnp.int32)
        got = softmax_cross_entropy_loss(logits, labels, impl=impl)
        want = softmax_cross_entropy_loss(logits, labels, impl="xla")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
        g = jax.grad(lambda x: jnp.sum(softmax_cross_entropy_loss(
            x, labels, impl=impl)))(logits)
        assert np.isfinite(np.asarray(g)).all()


class TestXentropy:
    def test_padding_idx_zeroed(self, rng):
        logits = jnp.asarray(rng.randn(6, 10), jnp.float32)
        labels = jnp.asarray([0, 3, 0, 5, 9, 0], jnp.int32)
        losses = softmax_cross_entropy(logits, labels, padding_idx=0)
        np.testing.assert_allclose(np.asarray(losses)[[0, 2, 5]], 0.0)
        lse = np.log(np.exp(np.asarray(logits)).sum(-1))
        ref = lse[1] - float(logits[1, 3])
        np.testing.assert_allclose(float(losses[1]), ref, rtol=1e-5)

    def test_smoothing(self, rng):
        logits = jnp.asarray(rng.randn(4, 6), jnp.float32)
        labels = jnp.asarray([1, 2, 3, 4], jnp.int32)
        out = softmax_cross_entropy(logits, labels, smoothing=0.1,
                                    padding_idx=-100)
        x = np.asarray(logits, np.float64)
        lse = np.log(np.exp(x).sum(-1))
        ref = lse - 0.9 * x[np.arange(4), np.asarray(labels)] - 0.1 * x.mean(-1)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5)


class TestIndexMul2d:
    def test_forward(self, rng):
        in1 = jnp.asarray(rng.randn(10, 7), jnp.float32)
        in2 = jnp.asarray(rng.randn(5, 7), jnp.float32)
        idx = jnp.asarray([0, 3, 3, 9, 1], jnp.int32)
        out = index_mul_2d(in1, in2, idx)
        ref = np.asarray(in1)[np.asarray(idx)] * np.asarray(in2)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)

    def test_backward_scatter_add(self, rng):
        in1 = jnp.asarray(rng.randn(4, 3), jnp.float32)
        in2 = jnp.asarray(rng.randn(3, 3), jnp.float32)
        idx = jnp.asarray([2, 2, 0], jnp.int32)
        g1 = jax.grad(lambda a: jnp.sum(index_mul_2d(a, in2, idx)))(in1)
        # row 2 referenced twice -> sum of both in2 rows
        np.testing.assert_allclose(
            np.asarray(g1[2]), np.asarray(in2[0] + in2[1]), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(g1[1]), 0.0)


def _rnnt_ref(lp, label, f_len, y_len, blank):
    """Slow numpy alpha-recursion RNN-T loss for one batch element."""
    T, U, V = lp.shape
    t_n, u_n = f_len, y_len + 1
    alpha = np.full((t_n, u_n), -np.inf)
    alpha[0, 0] = 0.0
    for t in range(t_n):
        for u in range(u_n):
            cands = []
            if t > 0:
                cands.append(alpha[t - 1, u] + lp[t - 1, u, blank])
            if u > 0:
                cands.append(alpha[t, u - 1] + lp[t, u - 1, label[u - 1]])
            if cands:
                m = max(cands)
                alpha[t, u] = m + np.log(sum(np.exp(c - m) for c in cands))
    return -(alpha[t_n - 1, u_n - 1] + lp[t_n - 1, u_n - 1, blank])


class TestTransducer:
    def test_joint_dense(self, rng):
        f = jnp.asarray(rng.randn(2, 5, 8), jnp.float32)
        g = jnp.asarray(rng.randn(2, 4, 8), jnp.float32)
        out = TransducerJoint()(f, g)
        assert out.shape == (2, 5, 4, 8)
        np.testing.assert_allclose(
            np.asarray(out[0, 1, 2]),
            np.asarray(f[0, 1]) + np.asarray(g[0, 2]), rtol=1e-6)

    def test_joint_relu_mask(self, rng):
        f = jnp.asarray(rng.randn(1, 3, 4), jnp.float32)
        g = jnp.asarray(rng.randn(1, 2, 4), jnp.float32)
        tj = TransducerJoint(relu=True, probe_mask=True)
        out = tj(f, g)
        assert (np.asarray(out) >= 0).all()
        assert len(tj.mask_probe) == 1

    def test_joint_length_masking(self, rng):
        f = jnp.asarray(rng.randn(2, 5, 4), jnp.float32)
        g = jnp.asarray(rng.randn(2, 4, 4), jnp.float32)
        out = TransducerJoint()(f, g, f_len=jnp.asarray([3, 5]),
                                g_len=jnp.asarray([4, 2]))
        np.testing.assert_allclose(np.asarray(out[0, 3:]), 0.0)
        np.testing.assert_allclose(np.asarray(out[1, :, 2:]), 0.0)

    @pytest.mark.parametrize("blank", [0, 4])
    def test_loss_vs_reference(self, rng, blank):
        B, T, U, V = 3, 6, 4, 5
        x = jnp.asarray(rng.randn(B, T, U, V), jnp.float32)
        label = jnp.asarray(rng.randint(0, V, (B, U - 1)), jnp.int32)
        f_len = jnp.asarray([6, 4, 5], jnp.int32)
        y_len = jnp.asarray([3, 2, 1], jnp.int32)
        out = transducer_loss(x, label, f_len, y_len, blank)
        lp = np.asarray(jax.nn.log_softmax(x, axis=-1))
        for b in range(B):
            ref = _rnnt_ref(lp[b], np.asarray(label[b]),
                            int(f_len[b]), int(y_len[b]), blank)
            np.testing.assert_allclose(float(out[b]), ref, rtol=1e-4,
                                       err_msg=f"batch {b}")

    def test_loss_grads_finite_and_jit(self, rng):
        B, T, U, V = 2, 5, 3, 4
        x = jnp.asarray(rng.randn(B, T, U, V), jnp.float32)
        label = jnp.asarray(rng.randint(0, V, (B, U - 1)), jnp.int32)
        f_len = jnp.asarray([5, 4], jnp.int32)
        y_len = jnp.asarray([2, 1], jnp.int32)

        loss_mod = TransducerLoss()

        @jax.jit
        def loss_fn(x):
            return jnp.sum(loss_mod(x, label, f_len, y_len, 0))

        g = jax.grad(loss_fn)(x)
        assert np.isfinite(np.asarray(g)).all()
        # grads vanish for time steps beyond f_len (batch 1, t=4)
        np.testing.assert_allclose(np.asarray(g[1, 4]), 0.0, atol=1e-6)

"""GPT fixture tests — minimal end-to-end runs.

Mirrors ref tests/L0/run_transformer/run_gpt_minimal_test.py: tiny GPT
forward/backward, TP-vs-dense equivalence, short convergence run on
synthetic data.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt import (
    GPTConfig,
    GPTModel,
    gpt_loss_fn,
    gpt_param_specs,
)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state as ps

TINY = GPTConfig(
    vocab_size=128, max_seq_len=32, hidden_size=64, num_layers=2,
    num_heads=4, dtype=jnp.float32,
)


def synth_batch(rng, b, s, vocab):
    tokens = rng.randint(0, vocab, (b, s + 1))
    return jnp.asarray(tokens[:, :-1], jnp.int32), jnp.asarray(tokens[:, 1:], jnp.int32)


class TestSingleDevice:
    def test_forward_shapes(self, rng):
        model = GPTModel(TINY)
        x, _ = synth_batch(rng, 2, 16, TINY.vocab_size)
        params = model.init(jax.random.PRNGKey(0), x)
        logits = model.apply(params, x)
        assert logits.shape == (16, 2, TINY.vocab_size)

    def test_loss_and_grads(self, rng):
        model = GPTModel(TINY)
        x, y = synth_batch(rng, 2, 16, TINY.vocab_size)
        params = model.init(jax.random.PRNGKey(0), x)

        def loss_fn(p):
            return gpt_loss_fn(model.apply(p, x), y)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(loss))
        # loss near ln(vocab) for random init
        assert abs(float(loss) - np.log(TINY.vocab_size)) < 1.0
        gsum = sum(float(jnp.abs(g).sum()) for g in jax.tree.leaves(grads))
        assert gsum > 0

    def test_tiny_convergence(self, rng):
        """Overfit 1 batch — the reference's minimal convergence check."""
        model = GPTModel(TINY)
        x, y = synth_batch(rng, 4, 16, TINY.vocab_size)
        params = model.init(jax.random.PRNGKey(0), x)
        opt = FusedAdam(lr=1e-3, impl="xla")
        state = opt.init(params)

        @jax.jit
        def step(params, state):
            loss, grads = jax.value_and_grad(
                lambda p: gpt_loss_fn(model.apply(p, x), y)
            )(params)
            params, state = opt.step(state, grads)
            return params, state, loss

        losses = []
        for _ in range(30):
            params, state, loss = step(params, state)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, losses[::10]


class TestGQAWindow:
    """GQA + sliding-window plumbed through GPTConfig (VERDICT r1 #4:
    kernel features must be reachable from the flagship model)."""

    def test_config_validation(self):
        with pytest.raises(ValueError, match="divide"):
            GPTConfig(num_heads=4, num_kv_heads=3)
        with pytest.raises(ValueError, match="flash"):
            GPTConfig(attention_backend="softmax", attention_window=8)
        with pytest.raises(ValueError, match="ring"):
            GPTConfig(attention_backend="ring", num_heads=4, num_kv_heads=2)

    @pytest.mark.parametrize("impl", ["xla", "interpret"])
    @pytest.mark.slow
    def test_gqa_window_forward_matches_mha_shapes(self, rng, impl):
        """GQA + window model runs the flash path end-to-end (the real
        kernel under interpret) and trains: loss finite, grads flow to
        the narrowed QKV slab."""
        cfg = GPTConfig(
            vocab_size=128, max_seq_len=32, hidden_size=64, num_layers=2,
            num_heads=4, num_kv_heads=2, attention_window=8,
            attention_backend="flash", softmax_impl=impl, dtype=jnp.float32,
        )
        model = GPTModel(cfg)
        x, y = synth_batch(rng, 2, 32, cfg.vocab_size)
        params = model.init(jax.random.PRNGKey(0), x)
        # scan_layers stacks layer params: leading axis = layer index
        qkv_kernel = params["params"]["layers"]["layer"][
            "attention"]["qkv"]["kernel"]
        head_dim = cfg.hidden_size // cfg.num_heads
        assert qkv_kernel.shape[0] == cfg.num_layers
        assert qkv_kernel.shape[1] == (cfg.num_heads + 2 * cfg.kv_heads) * head_dim

        loss, grads = jax.value_and_grad(
            lambda p: gpt_loss_fn(model.apply(p, x), y))(params)
        assert np.isfinite(float(loss))
        g = grads["params"]["layers"]["layer"]["attention"]["qkv"]["kernel"]
        assert float(jnp.abs(g).sum()) > 0

    def test_gqa_kernel_matches_xla_in_model(self, rng):
        """Whole-model agreement: interpret-mode Pallas flash vs the XLA
        attention path, same params — pins the GQA/window index maps."""
        base = dict(
            vocab_size=128, max_seq_len=32, hidden_size=64, num_layers=2,
            num_heads=4, num_kv_heads=2, attention_window=8,
            attention_backend="flash", dtype=jnp.float32,
        )
        model_k = GPTModel(GPTConfig(softmax_impl="interpret", **base))
        model_x = GPTModel(GPTConfig(softmax_impl="xla", **base))
        x, y = synth_batch(rng, 2, 32, 128)
        params = model_k.init(jax.random.PRNGKey(0), x)
        lk = gpt_loss_fn(model_k.apply(params, x), y)
        lx = gpt_loss_fn(model_x.apply(params, x), y)
        np.testing.assert_allclose(float(lk), float(lx), rtol=1e-5)
        gk = jax.grad(lambda p: gpt_loss_fn(model_k.apply(p, x), y))(params)
        gx = jax.grad(lambda p: gpt_loss_fn(model_x.apply(p, x), y))(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
            gk, gx)

    @pytest.mark.slow
    def test_tp_sharded_gqa_flash_matches_dense(self, rng):
        """TP=2-sharded flash path with GQA (kv_local=1 per rank) vs the
        dense single-device model (VERDICT r1: 'cover the TP-sharded
        flash path in a test')."""
        m = ps.initialize_model_parallel(2, 1)
        try:
            cfg = GPTConfig(
                vocab_size=64, max_seq_len=16, hidden_size=32, num_layers=2,
                num_heads=4, num_kv_heads=2, attention_window=8,
                attention_backend="flash", dtype=jnp.float32,
            )
            model = GPTModel(cfg)
            x, y = synth_batch(rng, 2, 16, cfg.vocab_size)
            params = model.init(jax.random.PRNGKey(0), x)
            dense_loss = gpt_loss_fn(model.apply(params, x), y)
            specs = gpt_param_specs(params)

            def tp_step(p, x, y):
                return jax.value_and_grad(
                    lambda p: gpt_loss_fn(model.apply(p, x), y))(p)

            step = shard_map(
                tp_step, mesh=m, in_specs=(specs, P(), P()),
                out_specs=(P(), specs), check_vma=False,
            )
            loss_tp, g_tp = jax.jit(step)(params, x, y)
            np.testing.assert_allclose(
                float(loss_tp), float(dense_loss), rtol=2e-4)
            g_dense = jax.grad(
                lambda p: gpt_loss_fn(model.apply(p, x), y))(params)
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5),
                g_tp, g_dense)
        finally:
            ps.destroy_model_parallel()


class TestTensorParallel:
    @pytest.fixture(autouse=True)
    def mesh(self):
        m = ps.initialize_model_parallel(4, 1)
        yield m
        ps.destroy_model_parallel()

    @pytest.mark.parametrize("sequence_parallel", [False, True])
    def test_tp_matches_dense(self, mesh, rng, sequence_parallel):
        cfg = GPTConfig(
            vocab_size=64, max_seq_len=16, hidden_size=32, num_layers=2,
            num_heads=4, dtype=jnp.float32,
            sequence_parallel=sequence_parallel,
        )
        model = GPTModel(cfg)
        x, y = synth_batch(rng, 2, 16, cfg.vocab_size)
        params = model.init(jax.random.PRNGKey(0), x)
        dense_loss = gpt_loss_fn(model.apply(params, x), y)

        specs = gpt_param_specs(params)

        def tp_loss(p, x, y):
            logits = model.apply(p, x)
            return gpt_loss_fn(logits, y)

        loss = jax.jit(
            shard_map(
                tp_loss, mesh=mesh,
                in_specs=(specs, P(), P()),
                out_specs=P(), check_vma=False,
            )
        )(params, x, y)
        np.testing.assert_allclose(float(loss), float(dense_loss), rtol=2e-4)

    def test_tp_grads_match_dense(self, mesh, rng):
        cfg = GPTConfig(
            vocab_size=64, max_seq_len=16, hidden_size=32, num_layers=1,
            num_heads=4, dtype=jnp.float32,
        )
        model = GPTModel(cfg)
        x, y = synth_batch(rng, 2, 16, cfg.vocab_size)
        params = model.init(jax.random.PRNGKey(0), x)
        specs = gpt_param_specs(params)

        def loss_fn(p, x, y):
            return gpt_loss_fn(model.apply(p, x), y)

        # the real train-step pattern: value_and_grad INSIDE shard_map,
        # grads come out with the same sharding as the params — and are
        # numerically identical to the dense model's grads
        step = shard_map(
            lambda p, x, y: jax.value_and_grad(loss_fn)(p, x, y),
            mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=(P(), specs), check_vma=False,
        )
        loss_tp, g_tp = jax.jit(step)(params, x, y)
        g_dense = jax.grad(lambda p: loss_fn(p, x, y))(params)
        np.testing.assert_allclose(
            float(loss_tp), float(loss_fn(params, x, y)), rtol=1e-5
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5
            ),
            g_tp, g_dense,
        )


class TestScanLayersOptOut:
    """scan_layers=False restores per-layer param names ("layer_{i}")
    for name-addressed checkpoints, produces the same function, and
    keeps gpt_param_specs' layer-axis shift from misfiring on the
    un-stacked names."""

    def test_unrolled_matches_scan(self, rng):
        base = dict(vocab_size=128, max_seq_len=32, hidden_size=64,
                    num_layers=2, num_heads=4, dtype=jnp.float32)
        x, y = synth_batch(rng, 2, 32, 128)
        scan_model = GPTModel(GPTConfig(**base))
        loop_model = GPTModel(GPTConfig(scan_layers=False, **base))
        sp = scan_model.init(jax.random.PRNGKey(0), x)
        lp = loop_model.init(jax.random.PRNGKey(0), x)
        assert "layer_0" in lp["params"] and "layers" in sp["params"]

        # copy stacked params into the per-layer tree: same function
        stacked = sp["params"]["layers"]["layer"]
        lp2 = dict(lp["params"])
        for i in range(2):
            lp2[f"layer_{i}"] = jax.tree.map(lambda s, i=i: s[i], stacked)
        for k in sp["params"]:
            if k != "layers":
                lp2[k] = sp["params"][k]
        out_scan = scan_model.apply(sp, x)
        out_loop = loop_model.apply({"params": lp2}, x)
        np.testing.assert_allclose(np.asarray(out_scan),
                                   np.asarray(out_loop), rtol=2e-5,
                                   atol=2e-5)

        # specs: per-layer names must NOT get the leading layer axis
        specs = gpt_param_specs({"params": lp2})
        qkv = specs["params"]["layer_0"]["attention"]["qkv"]["kernel"]
        assert qkv == P("tensor", None)
        sspecs = gpt_param_specs(sp)
        sqkv = sspecs["params"]["layers"]["layer"]["attention"]["qkv"][
            "kernel"]
        assert sqkv == P(None, "tensor", None)


class TestScanMigration:
    """scan_layers checkpoint migration (models/migrate.py): structure
    converts both ways and the converted params drive the OTHER model
    form to identical outputs."""

    def test_gpt_roundtrip_and_equivalence(self, rng):
        import dataclasses

        from apex_tpu.models import stack_scan_params, unstack_scan_params

        cfg_s = dataclasses.replace(TINY, scan_layers=True)
        cfg_u = dataclasses.replace(TINY, scan_layers=False)
        inputs, _ = synth_batch(rng, 2, 16, TINY.vocab_size)
        model_s, model_u = GPTModel(cfg_s), GPTModel(cfg_u)
        params_s = model_s.init(jax.random.PRNGKey(0), inputs)

        params_u = unstack_scan_params(params_s)
        out_s = model_s.apply(params_s, inputs)
        out_u = model_u.apply(params_u, inputs)
        np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_u),
                                   rtol=1e-5, atol=1e-5)

        back = stack_scan_params(params_u)
        for a, b in zip(jax.tree.leaves(params_s), jax.tree.leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_unrolled_checkpoint_loads_into_scan(self, rng):
        import dataclasses

        from apex_tpu.models import stack_scan_params

        cfg_u = dataclasses.replace(TINY, scan_layers=False)
        cfg_s = dataclasses.replace(TINY, scan_layers=True)
        inputs, _ = synth_batch(rng, 2, 16, TINY.vocab_size)
        model_u, model_s = GPTModel(cfg_u), GPTModel(cfg_s)
        params_u = model_u.init(jax.random.PRNGKey(1), inputs)

        params_s = stack_scan_params(params_u)
        out_u = model_u.apply(params_u, inputs)
        out_s = model_s.apply(params_s, inputs)
        np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_s),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.slow
    def test_t5_roundtrip(self, rng):
        import dataclasses

        from apex_tpu.models import stack_scan_params, unstack_scan_params
        from apex_tpu.models.t5 import T5Config, T5Model

        cfg = T5Config(vocab_size=64, max_seq_len=16, hidden_size=32,
                       num_encoder_layers=2, num_decoder_layers=2,
                       num_heads=4, dtype=jnp.float32, scan_layers=True)
        enc = jnp.asarray(rng.randint(0, 64, (2, 12)), jnp.int32)
        enc_mask = jnp.ones((2, 12), jnp.int32)
        dec = jnp.asarray(rng.randint(0, 64, (2, 8)), jnp.int32)
        model_s = T5Model(cfg)
        params_s = model_s.init(jax.random.PRNGKey(0), enc, enc_mask, dec)
        params_u = unstack_scan_params(params_s)
        model_u = T5Model(dataclasses.replace(cfg, scan_layers=False))
        np.testing.assert_allclose(
            np.asarray(model_s.apply(params_s, enc, enc_mask, dec)),
            np.asarray(model_u.apply(params_u, enc, enc_mask, dec)),
            rtol=1e-5, atol=1e-5)
        back = stack_scan_params(params_u)
        for a, b in zip(jax.tree.leaves(params_s), jax.tree.leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestStandaloneAliases:
    def test_standalone_import_paths(self):
        from apex_tpu.transformer.testing import standalone_bert as sb
        from apex_tpu.transformer.testing import standalone_gpt as sg
        from apex_tpu.transformer.testing import standalone_t5 as st

        assert sg.GPTModel is GPTModel
        assert sb.BertModel.__name__ == "BertModel"
        assert st.T5Model.__name__ == "T5Model"
        assert callable(sb.bert_model_provider)
        assert callable(st.t5_model_provider)

"""MoE group-GEMM + expert-parallel tests (BASELINE configs[4]).

group_gemm is pinned against a per-group matmul loop; the dropless
GroupedMLP against a dense per-expert reference; the capacity-based
ExpertParallelMLP sharded over the "expert" axis against its own dense
run (big capacity factor so nothing drops). The PR-19 workload plane
rides below: the mesh-native MoEMLP (both impls, drop accounting,
stats collection, fault poisoning), the MoE GPT config + pretrain step
(aux threading, gauges, the router-collapse latch drill), serving
token identity for an expert-sharded checkpoint, and the telemetry
plane (imbalance detector, fleet merge) — docs/moe.md throughout.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.moe import (
    ExpertParallelMLP,
    GroupedMLP,
    MoEConfig,
    MoEMLP,
    collect_moe_stats,
    expert_load,
    group_gemm,
    load_balancing_loss,
    poison_moe_params,
    router_topk,
)
from apex_tpu.transformer import parallel_state as ps

CFG = MoEConfig(hidden_size=16, ffn_hidden_size=32, num_experts=4,
                top_k=2, dtype=jnp.float32)


class TestGroupGemm:
    def test_vs_loop(self, rng):
        n, h, f, E = 24, 8, 12, 3
        x = jnp.asarray(rng.randn(n, h), jnp.float32)
        w = jnp.asarray(rng.randn(E, h, f), jnp.float32)
        gs = np.array([10, 6, 8], np.int32)
        y = group_gemm(x, w, jnp.asarray(gs))
        off = 0
        refs = []
        for e, g in enumerate(gs):
            refs.append(np.asarray(x[off:off + g]) @ np.asarray(w[e]))
            off += g
        np.testing.assert_allclose(np.asarray(y), np.concatenate(refs),
                                   rtol=1e-5, atol=1e-5)

    def test_empty_group(self, rng):
        x = jnp.asarray(rng.randn(6, 4), jnp.float32)
        w = jnp.asarray(rng.randn(3, 4, 5), jnp.float32)
        y = group_gemm(x, w, jnp.asarray([6, 0, 0], jnp.int32))
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(x) @ np.asarray(w[0]),
            rtol=1e-5, atol=1e-5)

    def test_grads(self, rng):
        x = jnp.asarray(rng.randn(8, 4), jnp.float32)
        w = jnp.asarray(rng.randn(2, 4, 4), jnp.float32)
        gs = jnp.asarray([3, 5], jnp.int32)
        g = jax.grad(lambda x, w: jnp.sum(group_gemm(x, w, gs) ** 2),
                     argnums=(0, 1))(x, w)
        assert np.isfinite(np.asarray(g[0])).all()
        assert np.isfinite(np.asarray(g[1])).all()
        # grad wrt unused weight rows of an empty group is zero
        g2 = jax.grad(
            lambda w: jnp.sum(group_gemm(x, w, jnp.asarray([8, 0], jnp.int32)))
        )(w)
        np.testing.assert_allclose(np.asarray(g2[1]), 0.0)


class TestRouter:
    def test_topk_normalized(self, rng):
        x = jnp.asarray(rng.randn(10, 16), jnp.float32)
        gate = jnp.asarray(rng.randn(16, 4), jnp.float32)
        w, ids, probs = router_topk(x, gate, 2)
        np.testing.assert_allclose(np.asarray(w.sum(-1)), 1.0, rtol=1e-6)
        assert ids.shape == (10, 2)
        assert (np.asarray(ids) < 4).all()
        # aux loss is E when router is uniform-random-ish, >= 1 always
        aux = load_balancing_loss(probs, ids)
        assert float(aux) >= 1.0


def _dense_moe_reference(x, params, cfg):
    """Straightforward per-expert loop with the same routing."""
    gate = params["gate"]
    w1, w2 = params["w1"], params["w2"]
    weights, ids, _ = router_topk(x, gate, cfg.top_k)
    out = np.zeros_like(np.asarray(x))
    for i in range(x.shape[0]):
        for j in range(cfg.top_k):
            e = int(ids[i, j])
            h1 = jax.nn.gelu(np.asarray(x[i]) @ np.asarray(w1[e]),
                             approximate=True)
            out[i] += float(weights[i, j]) * np.asarray(
                h1 @ np.asarray(w2[e]))
    return out


class TestGroupedMLP:
    def test_vs_reference(self, rng):
        x = jnp.asarray(rng.randn(12, CFG.hidden_size), jnp.float32)
        model = GroupedMLP(CFG)
        params = model.init(jax.random.PRNGKey(0), x)
        out = model.apply(params, x)
        ref = _dense_moe_reference(x, params["params"], CFG)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)

    def test_jit_and_grads(self, rng):
        x = jnp.asarray(rng.randn(12, CFG.hidden_size), jnp.float32)
        model = GroupedMLP(CFG)
        params = model.init(jax.random.PRNGKey(0), x)

        @jax.jit
        def loss(p, x):
            return jnp.mean(model.apply(p, x) ** 2)

        g = jax.grad(loss)(params, x)
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree.leaves(g))


class TestExpertParallel:
    @pytest.fixture(autouse=True)
    def mesh(self):
        m = ps.initialize_model_parallel(1, 1, expert_model_parallel_size=4)
        yield m
        ps.destroy_model_parallel()

    def test_ep_matches_dense(self, mesh, rng):
        cfg = MoEConfig(hidden_size=16, ffn_hidden_size=32, num_experts=8,
                        top_k=2, capacity_factor=8.0, dtype=jnp.float32)
        n = 32
        x = jnp.asarray(rng.randn(n, cfg.hidden_size), jnp.float32)
        model = ExpertParallelMLP(cfg)
        params = model.init(jax.random.PRNGKey(0), x)
        dense_out = model.apply(params, x)

        specs = {"params": {"gate": P(), "w1": P(ps.EXPERT_AXIS),
                            "w2": P(ps.EXPERT_AXIS)}}

        def fwd(p, x):
            return model.apply(p, x)

        out = jax.jit(
            shard_map(
                fwd, mesh=mesh,
                in_specs=(specs, P(ps.EXPERT_AXIS)),
                out_specs=P(ps.EXPERT_AXIS), check_vma=False,
            )
        )(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense_out),
                                   rtol=2e-4, atol=2e-4)

    def test_ep_grads_finite(self, mesh, rng):
        cfg = MoEConfig(hidden_size=16, ffn_hidden_size=32, num_experts=8,
                        top_k=2, capacity_factor=4.0, dtype=jnp.float32)
        x = jnp.asarray(rng.randn(32, cfg.hidden_size), jnp.float32)
        model = ExpertParallelMLP(cfg)
        params = model.init(jax.random.PRNGKey(0), x)
        specs = {"params": {"gate": P(), "w1": P(ps.EXPERT_AXIS),
                            "w2": P(ps.EXPERT_AXIS)}}

        def loss(p, x):
            return jnp.mean(model.apply(p, x) ** 2)

        g = jax.jit(
            shard_map(
                lambda p, x: jax.grad(loss)(p, x), mesh=mesh,
                in_specs=(specs, P(ps.EXPERT_AXIS)),
                out_specs=specs, check_vma=False,
            )
        )(params, x)
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree.leaves(g))

    def test_capacity_drops(self, rng):
        """With capacity_factor tiny, some tokens get zero output —
        dropped, not NaN/garbage (Switch semantics)."""
        ps.destroy_model_parallel()
        cfg = MoEConfig(hidden_size=8, ffn_hidden_size=16, num_experts=2,
                        top_k=1, capacity_factor=0.25, dtype=jnp.float32)
        x = jnp.asarray(rng.randn(16, cfg.hidden_size), jnp.float32)
        model = ExpertParallelMLP(cfg)
        params = model.init(jax.random.PRNGKey(0), x)
        out = np.asarray(model.apply(params, x))
        assert np.isfinite(out).all()
        dropped = (np.abs(out).sum(-1) == 0).sum()
        assert dropped >= 16 - 2 * max(1, int(0.25 * 16 / 2))


# -- the PR-19 workload plane ----------------------------------------------


def _moe_tokens(rng, s=8, b=4, h=16):
    return jnp.asarray(rng.randn(s, b, h), jnp.float32)


class TestMoEMLP:
    """The mesh-native GPTLayer drop-in, single device (the sharded
    path is tests/test_mesh-style — the dryrun + check_mesh.sh EP
    drill cover >1-model meshes)."""

    def test_bad_impl_raises(self, rng):
        x = _moe_tokens(rng)
        with pytest.raises(ValueError, match="impl"):
            MoEMLP(CFG, impl="routed").init(jax.random.PRNGKey(0), x)

    def test_dropless_vs_capacity_parity(self, rng):
        """With capacity ample enough that nothing drops, the two
        implementations are the same function of the same params."""
        cfg = MoEConfig(hidden_size=16, ffn_hidden_size=32,
                        num_experts=4, top_k=2, capacity_factor=8.0,
                        dtype=jnp.float32)
        x = _moe_tokens(rng)
        dl = MoEMLP(cfg, impl="dropless")
        params = dl.init(jax.random.PRNGKey(0), x)
        out_dl = dl.apply(params, x)
        out_cap, inter = MoEMLP(cfg, impl="capacity").apply(
            params, x, mutable=["intermediates"])
        np.testing.assert_allclose(np.asarray(out_dl), np.asarray(out_cap),
                                   rtol=1e-5, atol=1e-5)
        stats = collect_moe_stats(inter, num_experts=4)
        assert float(stats["dropped"]) == 0.0

    def test_drop_accounting_golden(self, rng):
        """Dropless never drops; capacity drops exactly the copies
        over each expert's C slots — the sown count matches a numpy
        recount of the routing."""
        x = _moe_tokens(rng)
        n, k, E = 8 * 4, 2, 4
        for impl, cf in (("dropless", 0.25), ("capacity", 0.5)):
            cfg = MoEConfig(hidden_size=16, ffn_hidden_size=32,
                            num_experts=E, top_k=k, capacity_factor=cf,
                            dtype=jnp.float32)
            m = MoEMLP(cfg, impl=impl)
            params = m.init(jax.random.PRNGKey(0), x)
            _, inter = m.apply(params, x, mutable=["intermediates"])
            stats = collect_moe_stats(inter, num_experts=E)
            if impl == "dropless":
                assert float(stats["dropped"]) == 0.0
                continue
            # recount: choice-major stream, first C copies per expert
            gate = params["params"]["gate"]
            toks = np.asarray(x).transpose(1, 0, 2).reshape(n, 16)
            _, ids, _ = router_topk(jnp.asarray(toks), gate, k)
            C = max(1, int(cf * n * k / E))
            flat = np.asarray(ids).T.reshape(-1)   # choice-major
            kept = np.zeros(E, np.int64)
            n_dropped = 0
            for e in flat:
                if kept[e] < C:
                    kept[e] += 1
                else:
                    n_dropped += 1
            assert float(stats["dropped"]) == float(n_dropped)
            assert n_dropped > 0      # cf=0.5 actually exercises drops

    def test_stats_sown_and_collected(self, rng):
        x = _moe_tokens(rng)
        m = MoEMLP(CFG, impl="dropless")
        params = m.init(jax.random.PRNGKey(0), x)
        out, inter = m.apply(params, x, mutable=["intermediates"])
        assert out.shape == x.shape
        stats = collect_moe_stats(inter, num_experts=CFG.num_experts)
        load = np.asarray(stats["expert_load"])
        assert load.shape == (CFG.num_experts,)
        assert load.sum() == 8 * 4 * CFG.top_k   # every routed copy
        assert float(stats["aux_loss"]) >= 1.0
        # non-mutable apply: sows are no-ops, output identical
        out2 = m.apply(params, x)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))

    def test_collect_no_moe_is_zeros(self):
        stats = collect_moe_stats({}, num_experts=4)
        assert float(stats["aux_loss"]) == 0.0
        assert np.asarray(stats["expert_load"]).shape == (4,)
        assert float(stats["dropped"]) == 0.0

    def test_return_stats_layers(self, rng):
        x = jnp.asarray(rng.randn(12, CFG.hidden_size), jnp.float32)
        for cls in (GroupedMLP, ExpertParallelMLP):
            m = cls(CFG)
            params = m.init(jax.random.PRNGKey(0), x)
            out, stats = m.apply(params, x, return_stats=True)
            assert out.shape == x.shape
            assert np.asarray(stats["expert_load"]).sum() == 12 * CFG.top_k
            assert stats["keep"].shape == (12, CFG.top_k)
            if cls is GroupedMLP:
                assert float(stats["dropped"]) == 0.0
                assert bool(np.asarray(stats["keep"]).all())


class TestPoisonMoEParams:
    def test_collapse_zeroes_gates_and_ties_route_low(self, rng):
        x = _moe_tokens(rng)
        m = MoEMLP(CFG, impl="dropless")
        params = m.init(jax.random.PRNGKey(0), x)
        poisoned = poison_moe_params(params, collapse=True)
        np.testing.assert_array_equal(
            np.asarray(poisoned["params"]["gate"]), 0.0)
        # zero gate -> logits tie -> top_k routes every token to
        # experts 0..k-1: the collapse load signature
        _, inter = m.apply(poisoned, x, mutable=["intermediates"])
        load = np.asarray(collect_moe_stats(inter)["expert_load"])
        n = 8 * 4
        np.testing.assert_array_equal(load, [n, n, 0, 0])

    def test_dead_expert_zeroes_w2_slice(self, rng):
        x = _moe_tokens(rng)
        m = MoEMLP(CFG, impl="dropless")
        params = m.init(jax.random.PRNGKey(0), x)
        poisoned = poison_moe_params(params, dead_expert=2)
        w2 = np.asarray(poisoned["params"]["w2"])
        np.testing.assert_array_equal(w2[2], 0.0)
        assert np.abs(w2[[0, 1, 3]]).sum() > 0
        out = m.apply(poisoned, x)
        assert np.isfinite(np.asarray(out)).all()

    def test_noop_off_plan(self, rng):
        x = _moe_tokens(rng)
        params = MoEMLP(CFG).init(jax.random.PRNGKey(0), x)
        assert poison_moe_params(params) is params


class TestMoEGPTConfig:
    def test_knob_validation(self):
        from apex_tpu.models.gpt import GPTConfig

        base = dict(vocab_size=64, max_seq_len=16, hidden_size=32,
                    num_layers=2, num_heads=4)
        with pytest.raises(ValueError, match="moe_top_k"):
            GPTConfig(**base, num_experts=4, moe_top_k=5)
        with pytest.raises(ValueError, match="moe_impl"):
            GPTConfig(**base, num_experts=4, moe_impl="sparse")
        with pytest.raises(ValueError, match="moe_layer_freq"):
            GPTConfig(**base, num_experts=4, moe_layer_freq=0)
        with pytest.raises(ValueError, match="scan_layers"):
            GPTConfig(**base, num_experts=4, moe_layer_freq=2,
                      scan_layers=True)
        with pytest.raises(ValueError, match="num_experts"):
            GPTConfig(**base, num_experts=-1)

    def test_moe_layer_schedule(self):
        from apex_tpu.models.gpt import GPTConfig

        cfg = GPTConfig(vocab_size=64, max_seq_len=16, hidden_size=32,
                        num_layers=4, num_heads=4, num_experts=4,
                        moe_layer_freq=2, scan_layers=False)
        assert [cfg.is_moe_layer(i) for i in range(4)] == \
            [False, True, False, True]
        dense = GPTConfig(vocab_size=64, max_seq_len=16, hidden_size=32,
                          num_layers=4, num_heads=4)
        assert not any(dense.is_moe_layer(i) for i in range(4))

    def test_dense_tree_unchanged(self):
        """num_experts=0 keeps the param tree byte-identical to a
        pre-MoE checkpoint: no gate/w1/w2 leaves anywhere."""
        from apex_tpu.models.gpt import GPTConfig, GPTModel

        cfg = GPTConfig(vocab_size=64, max_seq_len=16, hidden_size=32,
                        num_layers=2, num_heads=4,
                        dtype=jnp.float32, param_dtype=jnp.float32)
        params = GPTModel(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32))
        names = {str(getattr(p[-1], "key", p[-1]))
                 for p, _ in jax.tree_util.tree_flatten_with_path(params)[0]}
        assert "gate" not in names and "w1" not in names

    def test_moe_tree_has_experts(self):
        from apex_tpu.models.gpt import GPTConfig, GPTModel

        cfg = GPTConfig(vocab_size=64, max_seq_len=16, hidden_size=32,
                        num_layers=2, num_heads=4, num_experts=4,
                        dtype=jnp.float32, param_dtype=jnp.float32)
        params = GPTModel(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32))
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        w1 = [leaf for p, leaf in flat
              if str(getattr(p[-1], "key", p[-1])) == "w1"]
        assert w1 and all(l.shape[-3] == 4 for l in w1)


class TestMoEPretrainStep:
    @pytest.fixture(autouse=True)
    def clean(self):
        from apex_tpu import mesh as gmesh
        from apex_tpu import telemetry

        gmesh.destroy_mesh()
        telemetry.reset()
        yield
        gmesh.destroy_mesh()
        telemetry.reset()

    def _cfg(self, **kw):
        from apex_tpu.models.gpt import GPTConfig

        kw.setdefault("vocab_size", 64)
        kw.setdefault("max_seq_len", 16)
        kw.setdefault("hidden_size", 32)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("num_experts", 4)
        kw.setdefault("moe_top_k", 2)
        kw.setdefault("dtype", jnp.float32)
        kw.setdefault("param_dtype", jnp.float32)
        return GPTConfig(**kw)

    def _step(self, cfg):
        from apex_tpu.models.pretrain import (init_gpt_pretrain_params,
                                              make_gpt_pretrain_step)
        from apex_tpu.optimizers import FusedAdam

        params = init_gpt_pretrain_params(cfg, jax.random.PRNGKey(0))
        return make_gpt_pretrain_step(
            cfg, FusedAdam(lr=1e-3, impl="xla"))(params)

    def test_aux_and_gauges(self, rng):
        from apex_tpu.telemetry import metrics as tmetrics

        cfg = self._cfg()
        step, state = self._step(cfg)
        toks = jnp.asarray(rng.randint(0, 64, (4, 17)), jnp.int32)
        state, loss = step(state, toks[:, :-1], toks[:, 1:])
        assert np.isfinite(float(loss))
        aux = step.last_aux
        load = np.asarray(aux["expert_load"])
        assert load.sum() == 4 * 16 * cfg.moe_top_k * cfg.num_layers
        g = tmetrics.registry().snapshot()["gauges"]
        assert g["moe_aux_loss"] == pytest.approx(float(aux["aux_loss"]))
        assert g["moe_dropped_tokens"] == float(aux["dropped"])
        for e in range(4):
            assert g[f'moe_expert_load{{expert="{e}"}}'] == float(load[e])
        assert "moe_imbalance_ratio" in g

    def test_public_signature_unchanged_for_dense(self, rng):
        cfg = self._cfg(num_experts=0)
        step, state = self._step(cfg)
        toks = jnp.asarray(rng.randint(0, 64, (4, 17)), jnp.int32)
        state, loss = step(state, toks[:, :-1], toks[:, 1:])
        assert np.isfinite(float(loss))
        assert step.last_aux is None

    def test_router_collapse_latches_and_bundles(self, rng, tmp_path,
                                                 monkeypatch):
        """The docs/resilience.md collapse drill end to end: fault plan
        -> all load on experts 0..k-1 -> EWMA latch -> ONE flight
        bundle whose extra embeds the histogram."""
        from apex_tpu import records
        from apex_tpu.resilience import faults
        from apex_tpu.telemetry import flight
        from apex_tpu.telemetry import moe as tmoe

        monkeypatch.setattr(records, "RECORDS_DIR", str(tmp_path))
        tmoe._DETECTOR = tmoe.MoEImbalanceDetector(
            factor=1.5, ewma_alpha=1.0, min_samples=1)
        flight.enable(keep=3)
        try:
            cfg = self._cfg()
            step, state = self._step(cfg)
            toks = jnp.asarray(rng.randint(0, 64, (4, 17)), jnp.int32)
            with faults.inject(
                    moe_router_collapse_steps=frozenset(range(8))):
                for _ in range(3):
                    state, loss = step(state, toks[:, :-1], toks[:, 1:])
            n_copies = 4 * 16 * cfg.num_layers   # per chosen expert
            load = np.asarray(step.last_aux["expert_load"])
            np.testing.assert_array_equal(load, [n_copies, n_copies, 0, 0])
            assert np.isfinite(float(loss))
        finally:
            flight.disable()

        import glob
        import json
        import os

        bundles = sorted(glob.glob(os.path.join(str(tmp_path),
                                                "flightrec_*.json")))
        assert len(bundles) == 1      # latched once, not per step
        payload = json.load(open(bundles[0]))["payload"]
        assert payload["trigger"] == "moe_imbalance"
        extra = payload["extra"]
        assert extra["hot_expert"] in (0, 1)
        np.testing.assert_array_equal(
            extra["expert_load"], [n_copies, n_copies, 0, 0])

    def test_dead_expert_finite(self, rng):
        from apex_tpu.resilience import faults

        cfg = self._cfg()
        step, state = self._step(cfg)
        toks = jnp.asarray(rng.randint(0, 64, (4, 17)), jnp.int32)
        with faults.inject(moe_expert_dead=1):
            state, loss = step(state, toks[:, :-1], toks[:, 1:])
        assert np.isfinite(float(loss))
        # the dead expert still RECEIVES traffic: histogram keeps counting
        assert np.asarray(step.last_aux["expert_load"]).sum() == \
            4 * 16 * cfg.moe_top_k * cfg.num_layers

    def test_ep2_mesh_parity_with_single_device(self, rng):
        """dp=4 x ep/tp=2 GSPMD MoE step matches the no-mesh identity
        plan's losses to fp32 tolerance — the one-set-of-model-code
        guarantee extended to expert layers."""
        from apex_tpu import mesh as gmesh

        cfg = self._cfg()
        toks = jnp.asarray(rng.randint(0, 64, (8, 17)), jnp.int32)

        def run(n_steps=3):
            step, state = self._step(cfg)
            losses = []
            for _ in range(n_steps):
                state, loss = step(state, toks[:, :-1], toks[:, 1:])
                losses.append(float(loss))
            return losses

        ref = run()
        gmesh.initialize_mesh(model=2)
        ep = run()
        np.testing.assert_allclose(ep, ref, rtol=2e-5, atol=2e-5)
        assert ep[-1] < ep[0]


class TestMoEServing:
    def test_expert_sharded_decode_token_identical(self):
        """An MoE checkpoint through the REAL serving DecodeStep:
        expert-sharded (model=2 mesh, w1/w2 split on the expert dim via
        gpt_param_specs) produces the same greedy stream as the
        unsharded engine — nothing MoE-specific to call
        (docs/moe.md "Serving")."""
        from apex_tpu import mesh as gmesh
        from apex_tpu.mesh import annotate
        from apex_tpu.models.gpt import GPTConfig, GPTModel
        from apex_tpu.serving import KVCache, make_decode_step

        gmesh.destroy_mesh()
        cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=64,
                        num_layers=2, num_heads=4, num_kv_heads=2,
                        num_experts=4, moe_top_k=2,
                        dtype=jnp.float32, param_dtype=jnp.float32)
        model = GPTModel(cfg)
        prompt = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 8)),
            jnp.int32)
        params = model.init(jax.random.PRNGKey(0), prompt)

        def stream(params, cache_state_sharder):
            cache = KVCache.for_config(cfg, num_blocks=16, block_size=8)
            state = cache_state_sharder(cache.init_state())
            step = make_decode_step(model, cache)
            for i in range(2):
                cache.allocate(i, 8 + 4)
            tables = cache.table_array([0, 1], width=4)
            lengths = np.asarray([8, 8], np.int32)
            out = step.prefill(params, state, prompt, lengths, tables)
            state, tok = out.cache, out.next_token
            toks = [np.asarray(tok)]
            pos = lengths.copy()
            for _ in range(3):
                out = step.decode(params, state, np.asarray(tok), pos,
                                  tables)
                state, tok = out.cache, out.next_token
                pos = pos + 1
                toks.append(np.asarray(tok))
            return np.stack(toks)

        try:
            ref = stream(params, lambda s: s)
            gmesh.initialize_mesh(model=2)
            sharded = stream(annotate.shard_params_for_serving(params),
                             annotate.shard_kv_pool)
        finally:
            gmesh.destroy_mesh()
        np.testing.assert_array_equal(sharded, ref)


class TestMoETelemetry:
    @pytest.fixture(autouse=True)
    def clean(self):
        from apex_tpu import telemetry

        telemetry.reset()
        yield
        telemetry.reset()

    def test_detector_latches_once_and_rearms(self):
        from apex_tpu.telemetry import moe as tmoe

        det = tmoe.MoEImbalanceDetector(factor=2.0, ewma_alpha=1.0,
                                        min_samples=1)
        flat = [25.0, 25.0, 25.0, 25.0]
        hot = [97.0, 1.0, 1.0, 1.0]
        assert not det.observe(flat)
        assert det.observe(hot)          # latch edge
        assert not det.observe(hot)      # stays latched, no re-fire
        assert not det.observe(flat)     # recovery re-arms
        assert det.observe(hot)          # fresh excursion latches again

    def test_detector_validates(self):
        from apex_tpu.telemetry import moe as tmoe

        with pytest.raises(ValueError):
            tmoe.MoEImbalanceDetector(factor=1.0)
        with pytest.raises(ValueError):
            tmoe.MoEImbalanceDetector(ewma_alpha=0.0)

    def test_fleet_expert_load_merges_hosts(self):
        """Each host's gauge is ITS shard's counts: the fleet
        histogram is the cross-host SUM of the merge_snapshots
        per-host entries, not the mean."""
        from apex_tpu.telemetry import fleet, moe as tmoe

        def snap(load):
            return {"registry": {"gauges": {
                f'moe_expert_load{{expert="{e}"}}': v
                for e, v in enumerate(load)} | {"other_gauge": 1.0}}}

        merged = fleet.merge_snapshots([snap([10.0, 5.0]),
                                        snap([30.0, 15.0])])
        assert tmoe.fleet_expert_load(merged) == {"0": 40.0, "1": 20.0}
        assert tmoe.fleet_expert_load({}) == {}

    def test_publish_moe_step_counter_only_on_drops(self):
        from apex_tpu.telemetry import metrics as tmetrics
        from apex_tpu.telemetry import moe as tmoe

        tmoe.publish_moe_step({"aux_loss": 1.0, "dropped": 0.0,
                               "expert_load": [8.0, 8.0]})
        snap = tmetrics.registry().snapshot()
        assert "moe_dropped_tokens_total" not in snap["counters"]
        tmoe.publish_moe_step({"aux_loss": 1.0, "dropped": 3.0,
                               "expert_load": [8.0, 8.0]})
        snap = tmetrics.registry().snapshot()
        assert snap["counters"]["moe_dropped_tokens_total"] == 3.0
        assert snap["gauges"]['moe_expert_load{expert="1"}'] == 8.0

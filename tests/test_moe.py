"""MoE group-GEMM + expert-parallel tests (BASELINE configs[4]).

group_gemm is pinned against a per-group matmul loop; the dropless
GroupedMLP against a dense per-expert reference; the capacity-based
ExpertParallelMLP sharded over the "expert" axis against its own dense
run (big capacity factor so nothing drops).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.moe import (
    ExpertParallelMLP,
    GroupedMLP,
    MoEConfig,
    group_gemm,
    load_balancing_loss,
    router_topk,
)
from apex_tpu.transformer import parallel_state as ps

CFG = MoEConfig(hidden_size=16, ffn_hidden_size=32, num_experts=4,
                top_k=2, dtype=jnp.float32)


class TestGroupGemm:
    def test_vs_loop(self, rng):
        n, h, f, E = 24, 8, 12, 3
        x = jnp.asarray(rng.randn(n, h), jnp.float32)
        w = jnp.asarray(rng.randn(E, h, f), jnp.float32)
        gs = np.array([10, 6, 8], np.int32)
        y = group_gemm(x, w, jnp.asarray(gs))
        off = 0
        refs = []
        for e, g in enumerate(gs):
            refs.append(np.asarray(x[off:off + g]) @ np.asarray(w[e]))
            off += g
        np.testing.assert_allclose(np.asarray(y), np.concatenate(refs),
                                   rtol=1e-5, atol=1e-5)

    def test_empty_group(self, rng):
        x = jnp.asarray(rng.randn(6, 4), jnp.float32)
        w = jnp.asarray(rng.randn(3, 4, 5), jnp.float32)
        y = group_gemm(x, w, jnp.asarray([6, 0, 0], jnp.int32))
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(x) @ np.asarray(w[0]),
            rtol=1e-5, atol=1e-5)

    def test_grads(self, rng):
        x = jnp.asarray(rng.randn(8, 4), jnp.float32)
        w = jnp.asarray(rng.randn(2, 4, 4), jnp.float32)
        gs = jnp.asarray([3, 5], jnp.int32)
        g = jax.grad(lambda x, w: jnp.sum(group_gemm(x, w, gs) ** 2),
                     argnums=(0, 1))(x, w)
        assert np.isfinite(np.asarray(g[0])).all()
        assert np.isfinite(np.asarray(g[1])).all()
        # grad wrt unused weight rows of an empty group is zero
        g2 = jax.grad(
            lambda w: jnp.sum(group_gemm(x, w, jnp.asarray([8, 0], jnp.int32)))
        )(w)
        np.testing.assert_allclose(np.asarray(g2[1]), 0.0)


class TestRouter:
    def test_topk_normalized(self, rng):
        x = jnp.asarray(rng.randn(10, 16), jnp.float32)
        gate = jnp.asarray(rng.randn(16, 4), jnp.float32)
        w, ids, probs = router_topk(x, gate, 2)
        np.testing.assert_allclose(np.asarray(w.sum(-1)), 1.0, rtol=1e-6)
        assert ids.shape == (10, 2)
        assert (np.asarray(ids) < 4).all()
        # aux loss is E when router is uniform-random-ish, >= 1 always
        aux = load_balancing_loss(probs, ids)
        assert float(aux) >= 1.0


def _dense_moe_reference(x, params, cfg):
    """Straightforward per-expert loop with the same routing."""
    gate = params["gate"]
    w1, w2 = params["w1"], params["w2"]
    weights, ids, _ = router_topk(x, gate, cfg.top_k)
    out = np.zeros_like(np.asarray(x))
    for i in range(x.shape[0]):
        for j in range(cfg.top_k):
            e = int(ids[i, j])
            h1 = jax.nn.gelu(np.asarray(x[i]) @ np.asarray(w1[e]),
                             approximate=True)
            out[i] += float(weights[i, j]) * np.asarray(
                h1 @ np.asarray(w2[e]))
    return out


class TestGroupedMLP:
    def test_vs_reference(self, rng):
        x = jnp.asarray(rng.randn(12, CFG.hidden_size), jnp.float32)
        model = GroupedMLP(CFG)
        params = model.init(jax.random.PRNGKey(0), x)
        out = model.apply(params, x)
        ref = _dense_moe_reference(x, params["params"], CFG)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)

    def test_jit_and_grads(self, rng):
        x = jnp.asarray(rng.randn(12, CFG.hidden_size), jnp.float32)
        model = GroupedMLP(CFG)
        params = model.init(jax.random.PRNGKey(0), x)

        @jax.jit
        def loss(p, x):
            return jnp.mean(model.apply(p, x) ** 2)

        g = jax.grad(loss)(params, x)
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree.leaves(g))


class TestExpertParallel:
    @pytest.fixture(autouse=True)
    def mesh(self):
        m = ps.initialize_model_parallel(1, 1, expert_model_parallel_size=4)
        yield m
        ps.destroy_model_parallel()

    def test_ep_matches_dense(self, mesh, rng):
        cfg = MoEConfig(hidden_size=16, ffn_hidden_size=32, num_experts=8,
                        top_k=2, capacity_factor=8.0, dtype=jnp.float32)
        n = 32
        x = jnp.asarray(rng.randn(n, cfg.hidden_size), jnp.float32)
        model = ExpertParallelMLP(cfg)
        params = model.init(jax.random.PRNGKey(0), x)
        dense_out = model.apply(params, x)

        specs = {"params": {"gate": P(), "w1": P(ps.EXPERT_AXIS),
                            "w2": P(ps.EXPERT_AXIS)}}

        def fwd(p, x):
            return model.apply(p, x)

        out = jax.jit(
            shard_map(
                fwd, mesh=mesh,
                in_specs=(specs, P(ps.EXPERT_AXIS)),
                out_specs=P(ps.EXPERT_AXIS), check_vma=False,
            )
        )(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense_out),
                                   rtol=2e-4, atol=2e-4)

    def test_ep_grads_finite(self, mesh, rng):
        cfg = MoEConfig(hidden_size=16, ffn_hidden_size=32, num_experts=8,
                        top_k=2, capacity_factor=4.0, dtype=jnp.float32)
        x = jnp.asarray(rng.randn(32, cfg.hidden_size), jnp.float32)
        model = ExpertParallelMLP(cfg)
        params = model.init(jax.random.PRNGKey(0), x)
        specs = {"params": {"gate": P(), "w1": P(ps.EXPERT_AXIS),
                            "w2": P(ps.EXPERT_AXIS)}}

        def loss(p, x):
            return jnp.mean(model.apply(p, x) ** 2)

        g = jax.jit(
            shard_map(
                lambda p, x: jax.grad(loss)(p, x), mesh=mesh,
                in_specs=(specs, P(ps.EXPERT_AXIS)),
                out_specs=specs, check_vma=False,
            )
        )(params, x)
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree.leaves(g))

    def test_capacity_drops(self, rng):
        """With capacity_factor tiny, some tokens get zero output —
        dropped, not NaN/garbage (Switch semantics)."""
        ps.destroy_model_parallel()
        cfg = MoEConfig(hidden_size=8, ffn_hidden_size=16, num_experts=2,
                        top_k=1, capacity_factor=0.25, dtype=jnp.float32)
        x = jnp.asarray(rng.randn(16, cfg.hidden_size), jnp.float32)
        model = ExpertParallelMLP(cfg)
        params = model.init(jax.random.PRNGKey(0), x)
        out = np.asarray(model.apply(params, x))
        assert np.isfinite(out).all()
        dropped = (np.abs(out).sum(-1) == 0).sum()
        assert dropped >= 16 - 2 * max(1, int(0.25 * 16 / 2))

"""Checkpoint / resume (SURVEY.md §5 "Checkpoint / resume").

The reference's README "Checkpointing" section prescribes a recipe for
bitwise-accurate resume: save model + optimizer + amp (loss-scaler)
state, restore all three, continue. Functional equivalent here: the
whole train state (FlatOptState + ScalerState) is one pytree, saved
with orbax; a restored run must replay the original trajectory
BITWISE. Also covers the reference's O2 master-weight state_dict hook
(_initialize.py:135-144): checkpoints hold fp32 masters regardless of
model compute dtype.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

ocp = pytest.importorskip("orbax.checkpoint")

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam


def _data(step, n=64, d=8):
    rng = np.random.RandomState(step)
    x = rng.randn(n, d).astype(np.float32)
    y = np.tanh(x @ np.linspace(-1, 1, d).astype(np.float32))
    return jnp.asarray(x), jnp.asarray(y)


def _make_step(scaler):
    def loss_fn(params, x, y):
        w, b = params["w"], params["b"]
        pred = jnp.tanh(x @ w + b).sum(-1)
        return jnp.mean((pred - y) ** 2)

    opt = FusedAdam(lr=3e-3, impl="xla")

    @jax.jit
    def step(ostate, sstate, x, y):
        def scaled(p):
            return scaler.scale_loss(loss_fn(p, x, y), sstate)

        params = ostate.space.unpack(ostate.master)
        sloss, grads = jax.value_and_grad(scaled)(params)
        _, ostate = opt.step(ostate, grads, grad_scale=sstate.loss_scale,
                             skip_if_nonfinite=True)
        loss = sloss / sstate.loss_scale   # unscale with the PRE-update scale
        sstate = scaler.update(sstate, ostate.found_inf)
        return ostate, sstate, loss

    return opt, step


class TestOrbaxResume:
    def test_bitwise_resume(self, rng, tmp_path):
        params = {"w": jnp.asarray(rng.randn(8, 4), jnp.float32),
                  "b": jnp.zeros((4,), jnp.float32)}
        scaler = amp.LossScaler(init_scale=2.0**10, scale_window=3)
        opt, step = _make_step(scaler)
        ostate = opt.init(params)
        sstate = scaler.init()

        for i in range(3):
            ostate, sstate, _ = step(ostate, sstate, *_data(i))

        # save the full train state as one pytree
        ckpt = {"opt": opt.state_dict(ostate),
                "scaler": scaler.state_dict(sstate)}
        path = tmp_path / "ckpt"
        with ocp.PyTreeCheckpointer() as cp:
            cp.save(path, ckpt)

        # original run continues
        losses_a = []
        ostate_a, sstate_a = ostate, sstate
        for i in range(3, 6):
            ostate_a, sstate_a, l = step(ostate_a, sstate_a, *_data(i))
            losses_a.append(np.asarray(l))

        # fresh process state: re-init then restore
        ostate_b = opt.init(jax.tree.map(jnp.zeros_like, params))
        with ocp.PyTreeCheckpointer() as cp:
            restored = cp.restore(path)
        ostate_b = opt.load_state_dict(ostate_b, restored["opt"])
        sstate_b = scaler.load_state_dict(restored["scaler"])

        losses_b = []
        for i in range(3, 6):
            ostate_b, sstate_b, l = step(ostate_b, sstate_b, *_data(i))
            losses_b.append(np.asarray(l))

        # bitwise-identical trajectory (ref README "Checkpointing")
        np.testing.assert_array_equal(np.stack(losses_a), np.stack(losses_b))
        np.testing.assert_array_equal(np.asarray(ostate_a.master),
                                      np.asarray(ostate_b.master))
        assert float(sstate_a.loss_scale) == float(sstate_b.loss_scale)
        assert int(sstate_a.unskipped) == int(sstate_b.unskipped)

    def test_masters_fp32_under_bf16_compute(self, rng, tmp_path):
        """O2/O5-style: model weights bf16, checkpoint holds fp32 masters
        (ref O2StateDictHook, _initialize.py:135-144)."""
        params = {"w": jnp.asarray(rng.randn(16, 4), jnp.bfloat16)}
        opt = FusedAdam(lr=1e-3, impl="xla")
        state = opt.init(params)
        sd = opt.state_dict(state)
        assert sd["master"].dtype == jnp.float32
        path = tmp_path / "ckpt"
        with ocp.PyTreeCheckpointer() as cp:
            cp.save(path, sd)
            restored = cp.restore(path)
        assert restored["master"].dtype == np.float32
        # round-trip returns bf16 model params from fp32 masters
        state2 = opt.load_state_dict(state, restored)
        new_params, _ = opt.step(
            state2, {"w": jnp.zeros((16, 4), jnp.float32)}, lr=0.0)
        assert new_params["w"].dtype == jnp.bfloat16

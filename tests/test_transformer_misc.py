"""FusedScaleMaskSoftmax dispatcher + model-parallel grad scaler tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.amp import GradScaler, allreduce_found_inf
from apex_tpu.transformer.functional import AttnMaskType, FusedScaleMaskSoftmax


class TestFusedScaleMaskSoftmax:
    def test_causal_dispatch(self, rng):
        sm = FusedScaleMaskSoftmax(
            attn_mask_type=AttnMaskType.causal, scale=0.5, impl="xla"
        )
        x = jnp.asarray(rng.randn(2, 3, 8, 8), jnp.float32)
        y = sm(x)
        assert y.shape == x.shape
        # causal: strictly-upper entries zero
        assert float(jnp.abs(y[..., 0, 1:]).max()) == 0.0
        np.testing.assert_allclose(
            np.asarray(jnp.sum(y, -1)), np.ones((2, 3, 8)), rtol=1e-5
        )

    def test_padding_dispatch(self, rng):
        sm = FusedScaleMaskSoftmax(impl="xla")
        x = jnp.asarray(rng.randn(2, 3, 4, 16), jnp.float32)
        mask = jnp.asarray(rng.rand(2, 1, 4, 16) > 0.5)
        y = sm(x, mask)
        ref = jax.nn.softmax(jnp.where(mask, x - 10000.0, x), axis=-1)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-3, atol=1e-5)

    def test_no_mask_dispatch(self, rng):
        sm = FusedScaleMaskSoftmax(impl="xla")
        x = jnp.asarray(rng.randn(1, 2, 4, 8), jnp.float32)
        y = sm(x)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(jax.nn.softmax(x, -1)), rtol=1e-5, atol=1e-6
        )

    def test_unfused_fallback(self, rng):
        sm = FusedScaleMaskSoftmax(scaled_masked_softmax_fusion=False)
        x = jnp.asarray(rng.randn(1, 2, 4, 8), jnp.float32)
        y = sm(x)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(jax.nn.softmax(x, -1)), rtol=1e-5, atol=1e-6
        )

    def test_scale_requires_fp32(self):
        with pytest.raises(ValueError):
            FusedScaleMaskSoftmax(scale=2.0, softmax_in_fp32=False)


class TestModelParallelGradScaler:
    @pytest.fixture(autouse=True)
    def mesh(self):
        m = ps.initialize_model_parallel(2, 2)
        yield m
        ps.destroy_model_parallel()

    def test_found_inf_syncs_across_model_axes(self, mesh):
        """One rank overflowing must make ALL tp/pp ranks skip
        (ref apex/transformer/amp/grad_scaler.py:21-61)."""

        def f():
            tp_r = jax.lax.axis_index("tensor")
            pp_r = jax.lax.axis_index("pipe")
            local = jnp.where((tp_r == 1) & (pp_r == 0), 1.0, 0.0)
            return allreduce_found_inf(local)[None]

        out = jax.jit(
            shard_map(
                f, mesh=mesh, in_specs=(),
                out_specs=P(("pipe", "tensor")), check_vma=False,
            )
        )()
        np.testing.assert_array_equal(np.asarray(out), np.ones(4))

    def test_grad_scaler_update_in_mesh(self, mesh):
        scaler = GradScaler(scale_window=100)

        def f(st_scale):
            st = scaler.init()._replace(loss_scale=st_scale)
            tp_r = jax.lax.axis_index("tensor")
            found = jnp.where(tp_r == 0, 1.0, 0.0)  # only rank 0 saw inf
            new = scaler.update(st, found)
            return new.loss_scale[None]

        out = jax.jit(
            shard_map(
                f, mesh=mesh, in_specs=(P(),),
                out_specs=P(("pipe", "tensor")), check_vma=False,
            )
        )(jnp.asarray(2.0 ** 16, jnp.float32))
        # every rank backed off together
        np.testing.assert_allclose(np.asarray(out), 2.0 ** 15 * np.ones(4))


class TestTransformerUtils:
    """ref apex/transformer/utils.py — 1-D chunk scatter/gather round trip."""

    def test_split_gather_roundtrip(self, rng):
        import functools

        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        from apex_tpu.transformer import parallel_state as ps
        from apex_tpu.transformer.utils import (
            gather_split_1d_tensor,
            split_tensor_into_1d_equal_chunks,
        )

        ps.destroy_model_parallel()
        mesh = ps.initialize_model_parallel(tensor_model_parallel_size=4)
        try:
            x = jnp.asarray(rng.randn(8, 16).astype(np.float32))

            def body(x):
                chunk = split_tensor_into_1d_equal_chunks(x)
                assert chunk.shape == (8 * 16 // 4,)
                return gather_split_1d_tensor(chunk).reshape(x.shape)

            run = functools.partial(
                shard_map, mesh=mesh, in_specs=(P(),), out_specs=P(),
                check_vma=False)
            out = jax.jit(run(body))(x)
            np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
        finally:
            ps.destroy_model_parallel()

    def test_log_util(self):
        import logging

        from apex_tpu.transformer.log_util import (
            get_transformer_logger,
            set_logging_level,
        )

        lg = get_transformer_logger("some/module.py")
        assert lg.name == "some/module"
        root = logging.getLogger("apex_tpu")
        before = root.level
        try:
            set_logging_level(logging.DEBUG)
            assert root.level == logging.DEBUG
        finally:
            root.setLevel(before)

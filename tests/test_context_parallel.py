"""Ring attention / Ulysses context parallelism vs single-device attention.

The reference has no CP (SURVEY.md §5 "Long-context"); these tests pin
the TPU-native extension against the dense flash/XLA attention oracle on
the simulated 8-device mesh, including gradients (the backward re-rings
via the scan/ppermute transpose rules).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.attention import flash_attention
from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.context_parallel import (
    ring_attention_sharded,
    ulysses_attention_sharded,
    zigzag_indices,
)


@pytest.fixture
def cp_mesh():
    ps.destroy_model_parallel()
    mesh = ps.initialize_model_parallel(context_parallel_size=4)
    yield mesh
    ps.destroy_model_parallel()


def _qkv(rng, b=2, h=4, s=64, d=16, dtype=np.float32):
    q = jnp.asarray(rng.randn(b, h, s, d), dtype)
    k = jnp.asarray(rng.randn(b, h, s, d), dtype)
    v = jnp.asarray(rng.randn(b, h, s, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("zigzag", [False, True])
def test_ring_matches_dense(rng, cp_mesh, causal, zigzag):
    q, k, v = _qkv(rng)
    ref = flash_attention(q, k, v, causal=causal, impl="xla")
    out = ring_attention_sharded(
        q, k, v, cp_mesh, causal=causal, zigzag=zigzag)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_grads_match(rng, cp_mesh):
    q, k, v = _qkv(rng, b=2, h=2, s=32, d=8)

    def loss_ring(q, k, v):
        o = ring_attention_sharded(q, k, v, cp_mesh, causal=True)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = flash_attention(q, k, v, causal=True, impl="xla")
        return jnp.sum(o * o)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_ring_backward_saves_no_per_step_residuals(rng, cp_mesh):
    """The recompute backward must keep residuals O(s_local): the grad
    jaxpr may not contain any scan-stacked per-ring-step buffer (leading
    dim cp or cp-1 over a (b, h, s_local, d)-shaped chunk) — that is
    the O(S)-per-device AD-through-the-scan failure mode (round-2
    VERDICT weak#5)."""
    b, h, s, d = 2, 2, 64, 8
    cp = 4
    q, k, v = _qkv(rng, b=b, h=h, s=s, d=d)

    def loss(q, k, v):
        o = ring_attention_sharded(q, k, v, cp_mesh, causal=True)
        return jnp.sum(o * o)

    stacked = {(n, b, h, s // cp, d) for n in (cp, cp - 1)}
    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)

    def walk(jx):
        for eqn in jx.eqns:
            for var in eqn.outvars:
                shape = tuple(getattr(var.aval, "shape", ()))
                assert shape not in stacked, (
                    f"{eqn.primitive} stacks per-ring-step residuals "
                    f"{shape}")
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    walk(sub.jaxpr)
                if isinstance(sub, (list, tuple)):
                    for s_ in sub:
                        if hasattr(s_, "jaxpr"):
                            walk(s_.jaxpr)

    walk(jaxpr.jaxpr)


def test_ring_gqa_grads_match(rng, cp_mesh):
    """GQA through the ring: shared kv heads, recompute backward."""
    b, hq, hk, s, d = 2, 4, 2, 32, 8
    q = jnp.asarray(rng.randn(b, hq, s, d), np.float32)
    k = jnp.asarray(rng.randn(b, hk, s, d), np.float32)
    v = jnp.asarray(rng.randn(b, hk, s, d), np.float32)

    def loss_ring(q, k, v):
        o = ring_attention_sharded(q, k, v, cp_mesh, causal=True)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = flash_attention(q, k, v, causal=True, impl="xla")
        return jnp.sum(o * o)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_ring_zigzag_grads_match(rng, cp_mesh):
    """Zig-zag layout + recompute backward: grads must match dense."""
    q, k, v = _qkv(rng, b=2, h=2, s=32, d=8)

    def loss_ring(q, k, v):
        o = ring_attention_sharded(q, k, v, cp_mesh, causal=True,
                                   zigzag=True)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = flash_attention(q, k, v, causal=True, impl="xla")
        return jnp.sum(o * o)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_ring_bf16(rng, cp_mesh):
    q, k, v = _qkv(rng, dtype=jnp.bfloat16)
    ref = flash_attention(q, k, v, causal=True, impl="xla")
    out = ring_attention_sharded(q, k, v, cp_mesh, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=5e-2, atol=5e-2)


def test_zigzag_indices_roundtrip():
    perm, inv = zigzag_indices(32, 4)
    x = np.arange(32)
    np.testing.assert_array_equal(x[perm][inv], x)
    # device 0's shard (first 8 entries of perm) holds chunks 0 and 7
    assert set(perm[:8]) == set(range(0, 4)) | set(range(28, 32))


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(rng, cp_mesh, causal):
    q, k, v = _qkv(rng)  # h=4 divisible by cp=4
    ref = flash_attention(q, k, v, causal=causal, impl="xla")
    out = ulysses_attention_sharded(
        q, k, v, cp_mesh, causal=causal, impl="xla")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_grads_match(rng, cp_mesh):
    q, k, v = _qkv(rng, b=2, h=4, s=32, d=8)

    def loss_u(q, k, v):
        o = ulysses_attention_sharded(q, k, v, cp_mesh, causal=True,
                                      impl="xla")
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = flash_attention(q, k, v, causal=True, impl="xla")
        return jnp.sum(o * o)

    g_u = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_u, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_ulysses_gqa_head_counts(rng, cp_mesh):
    """GQA through Ulysses: kv heads divisible by cp reshard fine; too
    few kv heads raise the informative error (ring is the alternative)."""
    b, s, d = 2, 32, 8
    q = jnp.asarray(rng.randn(b, 8, s, d), np.float32)
    k4 = jnp.asarray(rng.randn(b, 4, s, d), np.float32)
    v4 = jnp.asarray(rng.randn(b, 4, s, d), np.float32)
    out = ulysses_attention_sharded(q, k4, v4, cp_mesh, causal=True,
                                    impl="xla")
    ref = flash_attention(q, k4, v4, causal=True, impl="xla")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    k2 = jnp.asarray(rng.randn(b, 2, s, d), np.float32)
    with pytest.raises(ValueError, match="kv heads"):
        ulysses_attention_sharded(q, k2, k2, cp_mesh, causal=True,
                                  impl="xla")


def test_context_axis_in_state():
    ps.destroy_model_parallel()
    mesh = ps.initialize_model_parallel(
        tensor_model_parallel_size=2, context_parallel_size=2)
    assert ps.get_context_parallel_world_size() == 2
    assert ps.get_tensor_model_parallel_world_size() == 2
    assert ps.get_data_parallel_world_size() == 2
    assert mesh.shape[ps.CONTEXT_AXIS] == 2
    ps.destroy_model_parallel()


class TestContextParallelGPT:
    """GPT with attention_backend="ring": the full model runs
    sequence-sharded over the context axis and matches the dense model
    (the long-context end-to-end path)."""

    def test_cp_gpt_matches_dense(self, rng):
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        from apex_tpu.models.gpt import GPTConfig, GPTModel, gpt_loss_fn

        ps.destroy_model_parallel()
        mesh = ps.initialize_model_parallel(context_parallel_size=4)
        base = dict(vocab_size=64, max_seq_len=32, hidden_size=32,
                    num_layers=2, num_heads=4, dtype=jnp.float32)
        dense_model = GPTModel(GPTConfig(**base))
        ring_model = GPTModel(
            GPTConfig(**base, attention_backend="ring"))

        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 64, (2, 33)), jnp.int32)
        x, y = toks[:, :-1], toks[:, 1:]
        params = dense_model.init(jax.random.PRNGKey(0), x)
        dense_loss = gpt_loss_fn(dense_model.apply(params, x), y)
        positions = jnp.arange(32, dtype=jnp.int32)

        def local_loss(p, x, y, pos):
            logits = ring_model.apply(p, x, positions=pos)
            return gpt_loss_fn(logits, y)[None]

        # tokens sharded along seq; per-shard mean losses averaged on host
        losses = jax.jit(shard_map(
            local_loss, mesh=mesh,
            in_specs=(P(), P(None, "context"), P(None, "context"),
                      P("context")),
            out_specs=P("context"), check_vma=False,
        ))(params, x, y, positions)
        np.testing.assert_allclose(
            float(jnp.mean(losses)), float(dense_loss), rtol=2e-5)
        ps.destroy_model_parallel()

    def test_flash_backend_matches_softmax(self, rng):
        from apex_tpu.models.gpt import GPTConfig, GPTModel

        ps.destroy_model_parallel()
        base = dict(vocab_size=64, max_seq_len=32, hidden_size=32,
                    num_layers=1, num_heads=4, dtype=jnp.float32)
        m1 = GPTModel(GPTConfig(**base))
        m2 = GPTModel(GPTConfig(**base, attention_backend="flash",
                                softmax_impl="xla"))
        toks = jnp.asarray(
            np.random.RandomState(1).randint(0, 64, (2, 16)), jnp.int32)
        params = m1.init(jax.random.PRNGKey(0), toks)
        np.testing.assert_allclose(
            np.asarray(m1.apply(params, toks)),
            np.asarray(m2.apply(params, toks)), rtol=2e-4, atol=2e-4)

"""Ring attention / Ulysses context parallelism vs single-device attention.

The reference has no CP (SURVEY.md §5 "Long-context"); these tests pin
the TPU-native extension against the dense flash/XLA attention oracle on
the simulated 8-device mesh, including gradients (the backward re-rings
via the scan/ppermute transpose rules).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.attention import flash_attention
from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.context_parallel import (
    ring_attention_sharded,
    ulysses_attention_sharded,
    zigzag_indices,
)


@pytest.fixture
def cp_mesh():
    ps.destroy_model_parallel()
    mesh = ps.initialize_model_parallel(context_parallel_size=4)
    yield mesh
    ps.destroy_model_parallel()


def _qkv(rng, b=2, h=4, s=64, d=16, dtype=np.float32):
    q = jnp.asarray(rng.randn(b, h, s, d), dtype)
    k = jnp.asarray(rng.randn(b, h, s, d), dtype)
    v = jnp.asarray(rng.randn(b, h, s, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("zigzag", [False, True])
def test_ring_matches_dense(rng, cp_mesh, causal, zigzag):
    q, k, v = _qkv(rng)
    ref = flash_attention(q, k, v, causal=causal, impl="xla")
    out = ring_attention_sharded(
        q, k, v, cp_mesh, causal=causal, zigzag=zigzag)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_grads_match(rng, cp_mesh):
    q, k, v = _qkv(rng, b=2, h=2, s=32, d=8)

    def loss_ring(q, k, v):
        o = ring_attention_sharded(q, k, v, cp_mesh, causal=True)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = flash_attention(q, k, v, causal=True, impl="xla")
        return jnp.sum(o * o)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_ring_bf16(rng, cp_mesh):
    q, k, v = _qkv(rng, dtype=jnp.bfloat16)
    ref = flash_attention(q, k, v, causal=True, impl="xla")
    out = ring_attention_sharded(q, k, v, cp_mesh, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=5e-2, atol=5e-2)


def test_zigzag_indices_roundtrip():
    perm, inv = zigzag_indices(32, 4)
    x = np.arange(32)
    np.testing.assert_array_equal(x[perm][inv], x)
    # device 0's shard (first 8 entries of perm) holds chunks 0 and 7
    assert set(perm[:8]) == set(range(0, 4)) | set(range(28, 32))


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(rng, cp_mesh, causal):
    q, k, v = _qkv(rng)  # h=4 divisible by cp=4
    ref = flash_attention(q, k, v, causal=causal, impl="xla")
    out = ulysses_attention_sharded(
        q, k, v, cp_mesh, causal=causal, impl="xla")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_grads_match(rng, cp_mesh):
    q, k, v = _qkv(rng, b=2, h=4, s=32, d=8)

    def loss_u(q, k, v):
        o = ulysses_attention_sharded(q, k, v, cp_mesh, causal=True,
                                      impl="xla")
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = flash_attention(q, k, v, causal=True, impl="xla")
        return jnp.sum(o * o)

    g_u = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_u, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_context_axis_in_state():
    ps.destroy_model_parallel()
    mesh = ps.initialize_model_parallel(
        tensor_model_parallel_size=2, context_parallel_size=2)
    assert ps.get_context_parallel_world_size() == 2
    assert ps.get_tensor_model_parallel_world_size() == 2
    assert ps.get_data_parallel_world_size() == 2
    assert mesh.shape[ps.CONTEXT_AXIS] == 2
    ps.destroy_model_parallel()

"""Crash flight recorder (apex_tpu/telemetry/flight.py): bounded
retention rings, the atomic ``flightrec_*.json`` postmortem bundle,
keep-last-k pruning, and the trigger wiring across the runtime
(watchdog escalation, guard divergence, preemption shutdown, fused-step
exception). The two-process real-cluster analog is
``tools/fleet_drill.py`` via tools/check_observability.sh.
"""

import json
import os
import signal
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import records, telemetry
from apex_tpu.optimizers import FusedAdam
from apex_tpu.optimizers.train_step import make_train_step
from apex_tpu.resilience import (
    CheckpointManager,
    ConsistencyGuard,
    FaultInjector,
    LocalCollective,
    NonfiniteWatchdog,
    graceful_shutdown,
)
from apex_tpu.telemetry import flight
from apex_tpu.telemetry.flight import FLIGHT_KIND, FlightRecorder


@pytest.fixture(autouse=True)
def fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()


@pytest.fixture(autouse=True)
def records_dir(tmp_path, monkeypatch):
    path = tmp_path / "records"
    monkeypatch.setattr(records, "RECORDS_DIR", str(path))
    return path


def _params(seed=0):
    r = np.random.RandomState(seed)
    return {"b": jnp.zeros((6,), jnp.float32),
            "w1": jnp.asarray(r.randn(32, 6), jnp.float32),
            "w2": jnp.asarray(r.randn(6, 6), jnp.float32)}


def _small_step(**kw):
    opt = FusedAdam(lr=1e-2, impl="xla")
    state = opt.init(_params())
    r = np.random.RandomState(0)
    g = jnp.asarray(r.randn(state.space.total).astype(np.float32) * 0.01)
    return make_train_step(opt, **kw), state, g


def latest_bundle():
    rec = records.latest_record(FLIGHT_KIND, require_backend=None)
    return None if rec is None else rec["payload"]


class TestRecorder:
    def test_event_ring_is_bounded(self):
        rec = flight.enable(event_capacity=3)
        reg = telemetry.registry()
        for i in range(10):
            reg.event("e", n=i)
        assert [e["n"] for e in rec.events] == [7, 8, 9]

    def test_digest_ring_is_bounded_and_compact(self):
        rec = FlightRecorder(digest_capacity=2)
        for step in range(5):
            rec.record_digest(step, np.arange(6, dtype=np.uint32)
                              .reshape(2, 3) + step)
        assert [d["step"] for d in rec.digests] == [3, 4]
        d = rec.digests[-1]
        assert isinstance(d["xor"], int) and len(d["row_sums"]) == 2
        json.dumps(d)

    def test_dump_bundle_is_self_contained(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_FAULTS", "bit_flip=3")
        tl = telemetry.enable(capacity=64)
        for i in range(5):
            with tl.step_scope():
                with tl.phase("h2d"):
                    pass
        telemetry.registry().counter("steps").inc(5)
        rec = flight.enable(last_steps=2, keep=3)
        telemetry.registry().event("something", n=1)
        rec.record_digest(4, np.ones((2, 3), np.uint32))
        path = rec.dump("watchdog_rollback",
                        error=RuntimeError("boom"), fleet=False,
                        extra={"k": "v"})
        assert path is not None and os.path.exists(path)
        b = latest_bundle()
        assert b["trigger"] == "watchdog_rollback"
        assert b["error"] == "RuntimeError: boom"
        assert b["extra"] == {"k": "v"}
        assert b["faults"] == "bit_flip=3"
        assert b["telemetry"]["registry"]["counters"]["steps"] == 5.0
        assert b["fleet"] is None and "host-local" in b["fleet_unavailable"]
        assert [e["event"] for e in b["recent_events"]] == ["something"]
        assert b["state_digests"][0]["step"] == 4
        # the trace slice honors last_steps: only the 2 newest steps
        steps = {e["args"]["step"] for e in b["trace"]["traceEvents"]
                 if e.get("ph") == "X"}
        assert steps == {3, 4}
        json.dumps(b)

    def test_dump_without_timeline_or_manager(self):
        rec = FlightRecorder()
        path = rec.dump("train_step_exception", fleet=False)
        b = latest_bundle()
        assert path is not None
        assert b["trace"] is None and b["last_checkpoint"] is None

    def test_bundle_carries_devmem_watermark_and_compile_plane(self):
        from apex_tpu.telemetry import compiled, devmem

        class FakeDevice:
            device_kind = "fake"
            in_use = 4000

            def memory_stats(self):
                return {"bytes_in_use": self.in_use,
                        "peak_bytes_in_use": self.in_use,
                        "bytes_limit": 8000}

        dev = FakeDevice()
        led = devmem.enable(device=dev)
        led.poll()
        dev.in_use = 1500
        led.poll()                          # watermark stays at 4000
        rec = flight.enable(keep=2)
        tracker = compiled.enable()
        try:
            tracker.observe("train_step", {"opt": 1})
            tracker.observe("train_step", {"opt": 2})   # one recompile
            path = rec.dump("watchdog_rollback", fleet=False)
        finally:
            compiled.disable()
            devmem.disable()
        assert path is not None
        b = latest_bundle()
        # the devmem watermark survives into the black box
        assert b["devmem"]["watermark_bytes"] == 4000
        assert b["devmem"]["polls"] == 2
        assert b["devmem"]["last"]["bytes_in_use"] == 1500
        # ...and so do the recent recompile events + tracker totals
        cp = b["compile_plane"]
        assert [e["event"] for e in cp["recent_events"]] == ["recompile"]
        assert cp["recent_events"][0]["signature_diff"]["changed"][
            "opt"] == [1, 2]
        assert cp["tracker"]["recompiles"] == 1
        json.dumps(b)

    def test_bundle_devmem_is_null_with_reason_on_cpu(self):
        # nothing armed: the dump takes one direct poll and the CPU
        # backend degrades to the explicit reason, never a missing key
        rec = FlightRecorder()
        rec.dump("train_step_exception", fleet=False)
        b = latest_bundle()
        assert b["devmem"]["watermark_bytes"] is None
        assert b["devmem"]["last"]["bytes_in_use"] is None
        assert "memory_stats" in b["devmem"]["last"]["devmem_reason"]
        assert b["compile_plane"]["tracker"] is None

    def test_dump_names_last_checkpoint(self, tmp_path):
        step, state, g = _small_step()
        mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=3)
        mgr.save(7, state)
        rec = FlightRecorder(manager=mgr)
        rec.dump("watchdog_rollback", fleet=False)
        lc = latest_bundle()["last_checkpoint"]
        assert lc["step"] == 7 and lc["path"] == mgr.path_for(7)

    def test_keep_last_k_pruning(self, records_dir, monkeypatch):
        # distinct (fake) second stamps per dump: pruning never touches
        # the CURRENT second (deleting a same-second record would free
        # its O_EXCL claim name for re-claim with a lower uniquifier)
        tick = iter(range(100))
        monkeypatch.setattr(
            records.time, "strftime",
            lambda *a: f"20260101T0000{next(tick):02d}Z")
        rec = flight.enable(keep=3)
        paths = [rec.dump("watchdog_rollback", fleet=False, extra={"n": i})
                 for i in range(7)]
        on_disk = sorted(n for n in os.listdir(records_dir)
                         if n.startswith(f"{FLIGHT_KIND}_"))
        assert len(on_disk) == 3
        # latest_record finds the newest bundle (the last dump)
        assert latest_bundle()["extra"] == {"n": 6}
        assert os.path.basename(paths[-1]) in on_disk
        assert os.path.basename(paths[0]) not in on_disk

    def test_pruning_skips_current_second(self, records_dir):
        # real clock, all dumps inside (at most) a couple of seconds:
        # nothing stamped "now" is deleted, so a burst can exceed keep
        # transiently, but the newest bundle is always the one
        # latest_record answers with
        rec = flight.enable(keep=2)
        for i in range(5):
            rec.dump("watchdog_rollback", fleet=False, extra={"n": i})
        assert latest_bundle()["extra"] == {"n": 4}

    def test_reset_disarms_global_recorder(self):
        flight.enable(keep=1)
        assert flight.get_recorder() is not None
        telemetry.reset()
        assert flight.get_recorder() is None
        # and notify with nothing armed is a silent no-op
        assert flight.notify("watchdog_rollback", fleet=False) is None

    def test_notify_never_raises(self):
        class Broken(FlightRecorder):
            def dump(self, *a, **kw):
                raise RuntimeError("recorder on fire")

        assert flight.notify("x", recorder=Broken(), fleet=False) is None
        flight.record_digest(1, np.ones((1, 1), np.uint32),
                             recorder=Broken())


class TestTriggers:
    def test_watchdog_escalation_dumps(self):
        from apex_tpu.amp.scaler import LossScaler

        scaler = LossScaler(init_scale=2.0 ** 10)
        opt = FusedAdam(lr=1e-2, impl="xla")
        state = opt.init(_params())
        step = make_train_step(opt, scaler=scaler)
        sstate = scaler.init()
        flight.enable(keep=2)
        wd = NonfiniteWatchdog(step, manager=None, threshold=2)
        bad = jnp.full((state.space.total,), jnp.nan, jnp.float32)
        state, sstate, _ = wd(state, bad, sstate)
        state, sstate, _ = wd(state, bad, sstate)
        b = latest_bundle()
        assert b["trigger"] == "watchdog_rollback"
        assert b["extra"]["event"] == "nonfinite_escalation"
        assert b["extra"]["action"] == "scaler_reset"
        # the escalation's own telemetry event made it into the ring
        assert "nonfinite_escalation" in [e["event"]
                                          for e in b["recent_events"]]

    def test_train_step_exception_dumps_and_reraises(self):
        step, state, g = _small_step()
        flight.enable(keep=2)
        with pytest.raises(Exception):
            step(state, g[: 8])                  # wrong-shaped grads
        b = latest_bundle()
        assert b["trigger"] == "train_step_exception"
        assert b["error"]
        assert "fleet_unavailable" in b

    def test_train_step_without_recorder_raises_plainly(self):
        step, state, g = _small_step()
        with pytest.raises(Exception):
            step(state, g[: 8])
        assert latest_bundle() is None           # nothing armed, no dump

    def test_graceful_shutdown_dumps(self, tmp_path):
        step, state, g = _small_step()
        mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=3)
        rec = FlightRecorder(manager=mgr)
        graceful_shutdown(mgr, 5, state, flight_recorder=rec)
        b = latest_bundle()
        assert b["trigger"] == "preemption_shutdown"
        assert b["extra"]["event"] == "preemption_checkpoint"
        assert b["extra"]["step"] == 5
        # dumped AFTER the final checkpoint: the bundle names it
        assert b["last_checkpoint"]["step"] == 5

    def test_guard_divergence_dumps_fleet_bundle(self):
        """The acceptance scenario in-process: a one-replica bit flip
        -> every simulated host's own recorder dumps a
        replica_divergence bundle whose FLEET snapshot sums the hosts'
        counters and carries the straggler gauges, and whose digest
        ring rode the boundary checksums."""
        opt = FusedAdam(lr=1e-2, impl="xla")
        step = make_train_step(opt, fingerprint_every=2)
        inj = FaultInjector(bit_flip_steps=frozenset({1}),
                            bit_flip_replica=1, bit_flip_leaf=0)
        n = 3
        group = LocalCollective(n)
        handles = group.handles()
        recs = [FlightRecorder(collective=handles[r]) for r in range(n)]
        errs = [None] * n

        def loop(rid):
            try:
                st = opt.init(_params())
                guard = ConsistencyGuard(step, collective=handles[rid],
                                         flight_recorder=recs[rid])
                r = np.random.RandomState(0)
                g = jnp.asarray(
                    r.randn(st.space.total).astype(np.float32) * 0.01)
                for i in range(4):
                    st = st._replace(master=inj.flip_bits(
                        st.master, i, replica=rid, space=st.space))
                    st, _ = guard(st, g)
            except BaseException as e:  # noqa: BLE001
                errs[rid] = e

        ts = [threading.Thread(target=loop, args=(r,), daemon=True)
              for r in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(120)
        assert errs == [None, None, None]
        for rid, rec in enumerate(recs):
            assert rec.dumps == 1
            assert rec.last_trigger == "replica_divergence"
            # boundary checksums fed the digest ring: the divergent
            # count=2 boundary and the post-repair clean count=4 one
            assert [d["step"] for d in rec.digests] == [2, 4]
        # the bundles themselves: pruning kept them all (keep=5 > 3)
        names = [nme for nme in os.listdir(records.RECORDS_DIR)
                 if nme.startswith(f"{FLIGHT_KIND}_")]
        assert len(names) == n
        b = latest_bundle()
        assert b["trigger"] == "replica_divergence"
        assert b["extra"]["event"] == "replica_divergence"
        assert b["extra"]["action"] == "majority_repair"
        fleet = b["fleet"]
        assert fleet is not None and fleet["n_hosts"] == n
        # counters summed across the simulated hosts. The threads here
        # share ONE process-global registry, so each "host" snapshot
        # catches the shared counter mid-flight (each thread sees at
        # least its own increment, at most all n) — the sum is bounded,
        # not pinned; the exact 2-process pin is tools/fleet_drill.py,
        # where every host owns a real private registry
        key = 'resilience_divergence_events{action="majority_repair"}'
        assert n * 1.0 <= fleet["counters"][key] <= n * float(n)
        # straggler gauges present in the bundle's registry snapshot
        gauges = b["telemetry"]["registry"]["gauges"]
        assert any(k.startswith("fleet_straggler_spread")
                   for k in gauges)

    def test_guard_divergence_error_dumps(self):
        opt = FusedAdam(lr=1e-2, impl="xla")
        step = make_train_step(opt, fingerprint_every=2)
        inj = FaultInjector(bit_flip_steps=frozenset({1}),
                            bit_flip_replica=1, bit_flip_leaf=0)
        n = 2                                    # 1v1: no quorum
        group = LocalCollective(n)
        handles = group.handles()
        recs = [FlightRecorder(collective=handles[r]) for r in range(n)]
        errs = [None] * n

        def loop(rid):
            try:
                st = opt.init(_params())
                guard = ConsistencyGuard(step, collective=handles[rid],
                                         flight_recorder=recs[rid])
                r = np.random.RandomState(0)
                g = jnp.asarray(
                    r.randn(st.space.total).astype(np.float32) * 0.01)
                for i in range(4):
                    st = st._replace(master=inj.flip_bits(
                        st.master, i, replica=rid, space=st.space))
                    st, _ = guard(st, g)
            except BaseException as e:  # noqa: BLE001
                errs[rid] = e

        ts = [threading.Thread(target=loop, args=(r,), daemon=True)
              for r in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(120)
        from apex_tpu.resilience import DivergenceError

        for e in errs:
            assert isinstance(e, DivergenceError)
        for rec in recs:
            # replica_divergence first, then the unrecoverable dump
            assert rec.dumps == 2
            assert rec.last_trigger == "divergence_error"


class TestTelemetryDumpCLI:
    def test_prom_and_json_from_flight_bundle(self, capsys):
        from tools import telemetry_dump

        telemetry.registry().counter("demo_total").inc(3, kind="x")
        rec = flight.enable(keep=1)
        path = rec.dump("watchdog_rollback", fleet=False)
        assert telemetry_dump.main([path]) == 0
        out = capsys.readouterr().out
        assert 'demo_total{kind="x"} 3' in out
        assert "# TYPE demo_total counter" in out
        assert telemetry_dump.main([path, "--format", "json"]) == 0
        snap = json.loads(capsys.readouterr().out)
        assert snap["counters"]['demo_total{kind="x"}'] == 3.0

    def test_live_registry_and_bad_file(self, tmp_path, capsys):
        from tools import telemetry_dump

        telemetry.registry().counter("live_total", "help!").inc()
        assert telemetry_dump.main([]) == 0
        out = capsys.readouterr().out
        assert "# HELP live_total help!" in out
        assert "live_total 1" in out
        bad = tmp_path / "nope.json"
        bad.write_text('{"no": "registry"}')
        assert telemetry_dump.main([str(bad)]) == 2

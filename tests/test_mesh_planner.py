"""Layout-planner cost-ordering tests (apex_tpu/mesh/planner.py).

Golden orderings the ISSUE pins: tp-heavy above dp-heavy when per-chip
memory is over budget (dp replicates weights + optimizer; tp shards
them), pure-dp degenerate on 1 device; plus the tiling property —
every emitted plan factorizes the device count exactly.
"""

import json

import pytest

from apex_tpu.mesh import planner


def small_plan(n, **kw):
    kw.setdefault("hidden_size", 256)
    kw.setdefault("num_layers", 4)
    kw.setdefault("vocab_size", 1024)
    kw.setdefault("global_batch", 8)
    kw.setdefault("seq_len", 128)
    kw.setdefault("num_heads", 8)
    return planner.plan_layout(n, **kw)


class TestEnumerate:
    @pytest.mark.parametrize("n", [1, 2, 4, 6, 8, 12, 16])
    def test_every_layout_tiles_device_count(self, n):
        layouts = planner.enumerate_layouts(n)
        assert layouts, f"no layouts for n={n}"
        for dp, tp, pp in layouts:
            assert dp * tp * pp == n
        assert len(set(layouts)) == len(layouts)

    def test_counts(self):
        # 8 = 2^3: ordered factorizations into 3 parts = C(3+2,2) = 10
        assert len(planner.enumerate_layouts(8)) == 10
        assert planner.enumerate_layouts(1) == [(1, 1, 1)]

    def test_invalid(self):
        with pytest.raises(ValueError):
            planner.enumerate_layouts(0)


class TestPlanProperties:
    @pytest.mark.parametrize("n", [1, 2, 4, 8])
    def test_plan_scores_every_tiling_exactly(self, n):
        plan = small_plan(n)
        assert plan.n_devices == n
        assert len(plan.scores) == len(planner.enumerate_layouts(n))
        for s in plan.scores:
            assert s.dp * s.tp * s.pp == n
            assert s.total_ms == pytest.approx(s.compute_ms + s.comm_ms)
            assert s.mem_bytes_per_device > 0

    def test_feasible_rank_above_infeasible(self):
        plan = small_plan(8, mem_budget_bytes=20_000_000)
        feas = [s.feasible for s in plan.scores]
        # once the ranking crosses into infeasible it never comes back
        assert feas == sorted(feas, reverse=True)
        assert plan.best.feasible

    def test_detail_is_json_able(self):
        d = small_plan(8).detail()
        json.dumps(d)
        assert d["best"]["dp"] * d["best"]["tp"] * d["best"]["pp"] == 8
        assert len(d["scores"]) == 10
        assert d["objective"]["peak_source"] in ("table", "fallback",
                                                 "caller")


class TestGoldenOrderings:
    def test_degenerates_to_pure_dp_on_one_device(self):
        plan = small_plan(1)
        assert (plan.best.dp, plan.best.tp, plan.best.pp) == (1, 1, 1)
        assert plan.best.feasible

    def test_dp_heavy_wins_when_memory_fits(self):
        """Unconstrained, the ONE bucketed overlap-hidden gradient
        all-reduce beats 8L per-layer tensor-parallel reductions."""
        plan = small_plan(8)
        assert plan.best.tp == 1
        assert plan.best.dp > 1

    def test_tp_heavy_above_dp_heavy_when_memory_over_budget(self):
        """dp replicates weights + master + Adam slots on every chip;
        a budget below that replicated footprint flips the order."""
        unconstrained = small_plan(8)
        dp_heavy = next(s for s in unconstrained.scores
                        if (s.dp, s.tp, s.pp) == (8, 1, 1))
        # budget between the tp-sharded and fully-replicated footprints
        budget = dp_heavy.mem_bytes_per_device // 2
        plan = small_plan(8, mem_budget_bytes=budget)

        def rank(dp, tp, pp):
            return next(i for i, s in enumerate(plan.scores)
                        if (s.dp, s.tp, s.pp) == (dp, tp, pp))

        assert rank(1, 8, 1) < rank(8, 1, 1)
        dp8 = plan.scores[rank(8, 1, 1)]
        assert not dp8.feasible
        assert "budget" in dp8.reason
        tp8 = plan.scores[rank(1, 8, 1)]
        assert tp8.feasible

    def test_tp_must_divide_heads(self):
        plan = small_plan(8, num_heads=4)
        bad = [s for s in plan.scores if s.tp == 8]
        assert bad and not bad[0].feasible
        assert "num_heads" in bad[0].reason

    def test_pp_bounded_by_layers(self):
        plan = small_plan(8, num_layers=4)
        bad = [s for s in plan.scores if s.pp == 8]
        assert bad and not bad[0].feasible
        assert "num_layers" in bad[0].reason

    def test_dp_bounded_by_global_batch(self):
        plan = small_plan(8, global_batch=4)
        bad = [s for s in plan.scores if s.dp == 8]
        assert bad and not bad[0].feasible
        assert "global_batch" in bad[0].reason


class TestPublishPlan:
    def test_publish_lands_in_snapshot_detail(self):
        from apex_tpu import telemetry
        from apex_tpu.telemetry import metrics as tmetrics

        telemetry.reset()
        try:
            detail0 = telemetry.snapshot_detail()
            assert detail0["layout_plan"] is None
            assert "layout_plan_reason" in detail0

            plan = small_plan(8)
            out = planner.publish_plan(plan)
            assert out == plan.detail()
            g = tmetrics.registry().snapshot()["gauges"]
            assert g['layout_plan_axis{axis="dp"}'] == plan.best.dp
            assert g['layout_plan_axis{axis="tp"}'] == plan.best.tp
            detail = telemetry.snapshot_detail()
            assert detail["layout_plan"]["best"] == plan.detail()["best"]
            assert "layout_plan_reason" not in detail
        finally:
            telemetry.reset()

    def test_plan_for_config(self):
        from apex_tpu.models.gpt import GPTConfig

        cfg = GPTConfig(hidden_size=128, num_layers=4, num_heads=8,
                        max_seq_len=64, vocab_size=512)
        plan = planner.plan_for_config(cfg, 8, global_batch=8,
                                       seq_len=64)
        assert plan.n_devices == 8
        assert plan.objective["model"]["num_heads"] == 8


class TestExpertParallel:
    """MoE pricing (docs/moe.md): EP all-to-all wire on tp>1 tilings,
    active-param FLOPs, tp | num_experts feasibility."""

    def test_ep_wire_on_tp_tilings_only(self):
        plan = small_plan(8, num_experts=4, moe_top_k=2)
        for s in plan.scores:
            if s.tp > 1 and s.feasible:
                assert s.ep_wire_bytes > 0, s
            if s.tp == 1:
                assert s.ep_wire_bytes == 0, s
            assert s.num_experts == 4

    def test_dense_has_no_ep_terms(self):
        plan = small_plan(8)
        assert all(s.ep_wire_bytes == 0 and s.num_experts == 0
                   for s in plan.scores)
        assert "moe" not in plan.objective
        assert "ep_wire_bytes" not in plan.scores[0].detail()

    def test_tp_must_divide_experts(self):
        plan = small_plan(8, num_experts=3, moe_top_k=1)
        bad = [s for s in plan.scores if s.tp == 2]
        assert bad
        assert all(not s.feasible and "num_experts" in s.reason
                   for s in bad)
        # tp=1 tilings stay feasible: EP is optional, not mandatory
        assert any(s.feasible for s in plan.scores if s.tp == 1)

    def test_active_params_in_objective(self):
        """top_k of E experts run per token: the compute term uses
        ACTIVE params (k experts' FFN), strictly below total params,
        and the objective's moe blob says so."""
        plan = small_plan(8, num_experts=8, moe_top_k=2)
        moe = plan.objective["moe"]
        assert moe["num_experts"] == 8 and moe["top_k"] == 2
        assert moe["moe_layers"] == 4
        assert moe["params_active"] < plan.objective["params"]
        dense = small_plan(8)
        assert plan.objective["params"] > dense.objective["params"]

    def test_ep_wire_prices_all_to_all(self):
        """More experts per layer don't change the dispatch payload
        (it's token-count-bound), but a bigger tp slice ships a larger
        all-to-all fraction: (n-1)/n."""
        from apex_tpu.telemetry import comms

        plan = small_plan(8, num_experts=4, moe_top_k=2)
        tp2 = next(s for s in plan.scores
                   if s.tp == 2 and s.pp == 1 and s.feasible)
        tp4 = next(s for s in plan.scores
                   if s.tp == 4 and s.pp == 1 and s.feasible)
        # same per-shard token payload, 4 ops per MoE layer; the wire
        # model is comms.wire_bytes("all_to_all", ...) exactly
        assert comms.wire_bytes("all_to_all", 999, 4) == \
            999 * 3 // 4
        assert tp4.ep_wire_bytes > tp2.ep_wire_bytes

    def test_detail_carries_ep_fields(self):
        plan = small_plan(8, num_experts=4, moe_top_k=2)
        row = next(s for s in plan.scores if s.tp > 1 and s.feasible)
        d = row.detail()
        assert d["ep_wire_bytes"] == row.ep_wire_bytes > 0
        assert d["num_experts"] == 4
        json.dumps(plan.detail())   # the bench record path stays JSON-able

    def test_plan_for_config_reads_moe_knobs(self):
        from apex_tpu.models.gpt import GPTConfig

        cfg = GPTConfig(hidden_size=128, num_layers=4, num_heads=8,
                        max_seq_len=64, vocab_size=512,
                        num_experts=4, moe_top_k=2)
        plan = planner.plan_for_config(cfg, 8, global_batch=8,
                                       seq_len=64)
        assert plan.objective["moe"]["num_experts"] == 4
        assert any(s.ep_wire_bytes > 0 for s in plan.scores)

"""Bisect the bench_bert/bench_gpt Mosaic compile crash.

Both model benches die with `tpu_compile_helper subprocess exit code 1`
(HTTP 500 from the tunnel's remote-compile endpoint) on a healthy chip,
while every microbench kernel compiles. This compiles + runs each
Pallas op AT THE EXACT SHAPES the model benches use, one jit at a time,
so the crashing kernel identifies itself instead of hiding inside a
4000-op model program.

    python tools/tpu_bisect.py            # all kernel candidates
    python tools/tpu_bisect.py xentropy   # substring filter (kernels)
    python tools/tpu_bisect.py bert_full  # exact: whole-model fwd+bwd
    python tools/tpu_bisect.py gpt_full   # exact: whole-model fwd+bwd
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    only = sys.argv[1] if len(sys.argv) > 1 else None

    from apex_tpu.backend_guard import tpu_slot_lock

    with tpu_slot_lock():
        import jax
        import jax.numpy as jnp

        on_cpu = jax.default_backend() == "cpu"
        impl = "interpret" if on_cpu else "pallas"
        rng = np.random.RandomState(0)

        def check(name, fn, *args):
            if only and only not in name:
                return
            try:
                out = jax.jit(fn)(*args)
                jax.device_get(jax.tree.leaves(out)[0].ravel()[:1])
                print(json.dumps({"op": name, "ok": True}), flush=True)
            except Exception as e:  # noqa: BLE001
                msg = str(e).split("\n")[0][:160]
                print(json.dumps({
                    "op": name, "ok": False,
                    "error": f"{type(e).__name__}: {msg}"}), flush=True)

        # ---- bench_bert building blocks (bert_large: hidden 1024,
        # heads 16, seq 512, batch 8, vocab 30528) --------------------
        from apex_tpu.ops.layer_norm import fused_layer_norm
        from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
        from apex_tpu.ops.attention import flash_attention
        from apex_tpu.ops.softmax import scaled_masked_softmax

        rows, hidden = (8 * 512, 1024) if not on_cpu else (64, 128)
        x = jnp.asarray(rng.randn(rows, hidden).astype(np.float32) * 0.1,
                        jnp.bfloat16)
        w = jnp.ones((hidden,), jnp.float32)
        b = jnp.zeros((hidden,), jnp.float32)

        def ln_fwd_bwd(x, w, b):
            def loss(x, w, b):
                return jnp.sum(
                    fused_layer_norm(x, w, b, impl=impl)
                    .astype(jnp.float32) ** 2)
            return jax.value_and_grad(loss, argnums=(0, 1, 2))(x, w, b)

        check("bert_layer_norm_4096x1024_bf16", ln_fwd_bwd, x, w, b)

        for name, vocab in (("bert_xentropy_4096x30528", 30528),
                            ("gpt_xentropy_4096x50257", 50257)):
            vv = vocab if not on_cpu else 512
            logits = jnp.asarray(
                rng.randn(rows, vv).astype(np.float32) * 0.1, jnp.bfloat16)
            labels = jnp.asarray(rng.randint(0, vv, (rows,)), jnp.int32)

            def ce_fwd_bwd(logits, labels):
                def loss(lg):
                    return jnp.sum(softmax_cross_entropy_loss(
                        lg, labels, impl=impl))
                return jax.value_and_grad(loss)(logits)

            check(name, ce_fwd_bwd, logits, labels)

        b_, h_, s_, d_ = (8, 16, 512, 64) if not on_cpu else (1, 2, 64, 32)
        q, k, v = (jnp.asarray(
            rng.randn(b_, h_, s_, d_).astype(np.float32) * 0.1,
            jnp.bfloat16) for _ in range(3))
        seg = jnp.zeros((b_, s_), jnp.int32)

        def attn_seg_fwd_bwd(q, k, v, seg):
            def loss(q, k, v):
                o = flash_attention(q, k, v, segment_ids=seg, impl=impl)
                return jnp.sum(o.astype(jnp.float32) ** 2)
            return jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)

        check("bert_flash_seg_8x16x512x64", attn_seg_fwd_bwd, q, k, v, seg)

        def attn_causal_fwd_bwd(q, k, v):
            def loss(q, k, v):
                o = flash_attention(q, k, v, causal=True, impl=impl)
                return jnp.sum(o.astype(jnp.float32) ** 2)
            return jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)

        check("gpt_flash_causal_4x16x1024x64", attn_causal_fwd_bwd,
              *((q, k, v) if on_cpu else tuple(
                  jnp.asarray(rng.randn(4, 16, 1024, 64)
                              .astype(np.float32) * 0.1, jnp.bfloat16)
                  for _ in range(3))))

        scores = jnp.asarray(
            rng.randn(b_, h_, s_, s_).astype(np.float32), jnp.bfloat16)
        mask = jnp.zeros((b_, 1, s_, s_), jnp.bool_)

        def softmax_fwd_bwd(scores):
            def loss(sc):
                return jnp.sum(scaled_masked_softmax(
                    sc, mask, 0.125, impl=impl).astype(jnp.float32) ** 2)
            return jax.value_and_grad(loss)(scores)

        check("bert_scaled_masked_softmax_8x16x512x512", softmax_fwd_bwd,
              scores)

        # ---- segmented one-pass LAMB at headline scale: the small
        # smoke config compiles tiny segments; the BENCH config runs
        # ~1.25M-element segments with ~10 MB of VMEM scratch — the
        # construct class that produced both round-3 Mosaic crashes
        if not on_cpu:
            from apex_tpu.multi_tensor.flat_buffer import segmented_space
            from apex_tpu.multi_tensor.segmented import (
                fused_lamb_segmented_update,
            )
            from apex_tpu.optimizers import FusedLAMB
            from bench import bert_large_shapes

            import dataclasses as _dc

            for label, okw, shp in (
                ("seg_lamb_41M_auto", {},
                 bert_large_shapes(hidden=512, layers=8)),
                ("seg_lamb_335M_auto", {}, bert_large_shapes()),
                ("seg_lamb_335M_streamp_bf16u",
                 {"seg_stash_p": False, "seg_allow_bf16_u": True,
                  "seg_u_dtype": jnp.bfloat16}, bert_large_shapes()),
            ):
                if only and only not in label:
                    continue
                tree = {f"p{i}": jax.ShapeDtypeStruct(s, jnp.float32)
                        for i, s in enumerate(shp)}
                zeros = jax.tree.map(
                    lambda sd: jnp.zeros(sd.shape, sd.dtype), tree)
                opt = FusedLAMB(lr=1e-3, **okw)
                seg, stash, u_dt = opt._segment_config(zeros)
                sp, meta = segmented_space(zeros, seg_elems=seg)
                meta = _dc.replace(meta, stash_p=bool(stash),
                                   u_dtype_name=jnp.dtype(u_dt).name)
                pbuf = jnp.zeros((sp.total,), jnp.float32)
                gbuf = jnp.full((sp.total,), 1e-3, jnp.float32)

                check(label,
                      lambda p_, g_, sp=sp, meta=meta:
                      fused_lamb_segmented_update(
                          p_, jnp.zeros_like(p_), jnp.zeros_like(p_), g_,
                          sp, meta, lr=1e-3, step=1, weight_decay=0.01,
                          use_nvlamb=True, max_grad_norm=0.0,
                          impl="pallas"),
                      pbuf, gbuf)
                del pbuf, gbuf, zeros

        # the full bert/gpt fwd-bwd jits — exact names, not substrings
        # (slow compiles; request explicitly with `tpu_bisect.py
        # bert_full` / `gpt_full`)
        if only == "bert_full":
            from apex_tpu.models.bert import (BertConfig, BertModel,
                                              bert_loss_fn)

            cfg = BertConfig.bert_large(attention_backend="flash",
                                        dtype=jnp.bfloat16)
            model = BertModel(cfg)
            tokens = jnp.asarray(rng.randint(0, 30000, (8, 512)), jnp.int32)
            amask = jnp.ones((8, 512), jnp.int32)
            lm_labels = jnp.asarray(rng.randint(0, 30000, (8, 512)),
                                    jnp.int32)
            lmask = jnp.ones((8, 512), jnp.float32)
            nsp = jnp.zeros((8,), jnp.int32)
            params = model.init(jax.random.PRNGKey(0), tokens, amask)

            def bert_step(p):
                lm, binary = model.apply(p, tokens, amask,
                                         deterministic=True)
                return bert_loss_fn(lm, binary, lm_labels, lmask, nsp)

            check("bert_full", lambda p: jax.grad(bert_step)(p), params)
        elif only == "gpt_full":

            from apex_tpu.models.gpt import (GPTConfig, GPTModel,
                                             gpt_loss_fn)

            gcfg = GPTConfig.gpt2_345m(attention_backend="flash")
            gmodel = GPTModel(gcfg)
            toks = jnp.asarray(rng.randint(0, 50000, (4, 1025)),
                               jnp.int32)
            gparams = gmodel.init(jax.random.PRNGKey(0), toks[:, :-1])

            def gpt_step(p):
                return gpt_loss_fn(gmodel.apply(p, toks[:, :-1]),
                                   toks[:, 1:])

            check("gpt_full", lambda p: jax.grad(gpt_step)(p), gparams)


if __name__ == "__main__":
    main()

#!/bin/bash
# Round-4 hardware queue, health-gated — priority order from VERDICT r3:
# (1) prove the segmented one-pass LAMB through Mosaic and time it,
# (2) bisect the bench_bert/bench_gpt compile crashers,
# (3) re-validate tile defaults with the fixed chained timer,
# (4) fill every BASELINE row with a TPU-backed bench record.
# Every successful measurement persists to bench_records/ (round-4
# records infrastructure), so evidence survives a dead tunnel.
set -u
cd "$(dirname "$0")/.."
INTERVAL=${INTERVAL:-480}
LOGDIR=${LOGDIR:-/tmp/tpu_queue_r4}
mkdir -p "$LOGDIR"
echo "logs -> $LOGDIR"

healthy() { timeout 240 python tools/tpu_health.py >>"$LOGDIR/health.log" 2>&1; }

run() {  # run <name> <timeout-s> <cmd...>
  local name=$1 to=$2; shift 2
  until healthy; do
    echo "chip unhealthy before $name $(date -u +%H:%M:%S); retry in ${INTERVAL}s"
    sleep "$INTERVAL"
  done
  echo "=== $name ($(date -u +%H:%M:%S)) ==="
  timeout "$to" "$@" >"$LOGDIR/$name.log" 2>&1
  local rc=$?
  tail -4 "$LOGDIR/$name.log"
  echo "--- $name rc=$rc"
}

# 1. the one job above all: does the segmented kernel lower + match?
run smoke_segmented 1200 python tools/tpu_smoke.py --only segmented
# full kernel-zoo parity (regression gate for everything else)
run smoke 2400 python tools/tpu_smoke.py

# 2. optimizer truth with the segmented schedule, 41.5M then 335M
run optdiag_small 2400 python tools/tpu_optdiag.py --small
run optdiag 3000 python tools/tpu_optdiag.py

# 3. bert/gpt Mosaic crasher bisection (evidence for the fix)
run bisect 1800 python tools/tpu_bisect.py

# 3b. engine bandwidth factor ladder (where do the GB/s go?)
run kprobe 1800 python tools/tpu_kprobe.py

# 4. driver-format bench records, headline first
export APEX_TPU_BENCH_PROBE_BUDGET=240
run bench_headline 2400 python bench.py
run bench_attn     1800 python bench.py attn
run bench_bert     2400 python bench.py bert
run bench_gpt      2400 python bench.py gpt
run bench_resnet   2400 python bench.py resnet
run bench_moe      1800 python bench.py moe

# 5. re-validate tile defaults with the fixed chained timer
run tune_attnbwd 2400 python tools/tpu_tune.py attnbwd
run tune_opt     1800 python tools/tpu_tune.py opt
run tune_ln      1200 python tools/tpu_tune.py ln

echo "QUEUE DONE ($(date -u +%H:%M:%S)); logs in $LOGDIR"

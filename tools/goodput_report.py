"""Render the run-ledger attribution table (docs/observability.md
"Run ledger & goodput") from the live process, a checkpoint
directory, or any bundle JSON.

Every second of the run lands in a cause bucket — ``productive``,
``compile``, ``checkpoint_save`` / ``checkpoint_restore``,
``data_wait``, ``rollback``, ``rework``, ``drain_shutdown``,
``straggler_wait`` — with the residual published as ``unattributed``
rather than hidden.  This tool is the postmortem entry point: point it
at whatever the dead run left behind and it prints the table a human
reads first (docs/resilience.md "Postmortem runbook")::

    python tools/goodput_report.py                     # live ledger
    python tools/goodput_report.py ckpts/              # checkpoint dir alone
    python tools/goodput_report.py flightrec_*.json    # bundle / dump / record
    python tools/goodput_report.py --json ckpts/

A directory argument is resolved through
:class:`~apex_tpu.resilience.checkpoint.CheckpointManager` — the
newest checkpoint a resume would actually accept (``latest_valid``),
its manifest ``extra["goodput"]`` pack re-derived into the full table
(fraction, unattributed, effective tok/s are computed here; the pack
stores only raw buckets + wall).  File arguments are resolved by
shape, not name: a flight-recorder bundle (``payload.goodput``), a
telemetry dump (``goodput`` section), a bench record, a serving drain
snapshot, or a bare pack/summary all work.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from apex_tpu.telemetry.goodput import CAUSES  # noqa: E402


def normalize(gp):
    """A checkpoint ``pack()`` (raw buckets + wall) or a live
    ``summary()`` -> one summary-shaped dict with the derived fields
    (attributed / unattributed / overlap / fraction / effective tok/s)
    always present, identity re-derived here so the table sums to wall
    no matter which producer wrote the blob."""
    if not isinstance(gp, dict) or "seconds" not in gp:
        raise ValueError("not a goodput pack/summary (no 'seconds' table)")
    seconds = {c: float(gp["seconds"].get(c, 0.0)) for c in CAUSES}
    wall = float(gp.get("wall_seconds", 0.0))
    attributed = sum(seconds.values())
    unattributed = max(0.0, wall - attributed)
    out = {
        "enabled": True,
        "wall_seconds": round(wall, 6),
        "attributed_seconds": round(attributed, 6),
        "unattributed_seconds": round(unattributed, 6),
        "overlap_seconds": round(max(0.0, attributed - wall), 6),
        "goodput_fraction": (round(seconds["productive"] / wall, 6)
                             if wall > 0 else 0.0),
        "seconds": {**{c: round(v, 6) for c, v in seconds.items()},
                    "unattributed": round(unattributed, 6)},
        "tokens_trained_total": int(gp.get("tokens_trained_total", 0)),
        "effective_tokens_per_sec": (
            round(float(gp.get("tokens_trained_total", 0)) / wall, 3)
            if wall > 0 else 0.0),
        "steps": int(gp.get("steps", 0)),
        "rework_steps": int(gp.get("rework_steps", 0)),
        "restarts": int(gp.get("restarts", 0)),
        "median_step_s": gp.get("median_step_s"),
    }
    for key in ("incarnation", "rollbacks", "step_high_water", "stages",
                "timeline_dropped_span_seconds"):
        if key in gp:
            out[key] = gp[key]
    # summary() carries the series summary under "anomalies"; pack()
    # persists only the episode counters.
    anomalies = gp.get("anomalies")
    episodes = (anomalies or {}).get("episodes") if isinstance(
        anomalies, dict) else None
    if episodes is None:
        episodes = gp.get("anomaly_episodes") or {}
    out["anomaly_episodes"] = dict(episodes)
    return out


def extract(obj):
    """The goodput blob inside any JSON shape this repo writes, or
    None.  Checked shapes: a bare pack/summary, a flight bundle
    (``payload.goodput``), a telemetry dump / snapshot_detail
    (``goodput``), a bench record (``payload.detail.telemetry`` has no
    goodput key, but ``payload.detail.telemetry`` dumps do), a serving
    drain snapshot (``goodput`` pack alongside the request log)."""
    if not isinstance(obj, dict):
        return None
    if "seconds" in obj and "wall_seconds" in obj:
        return obj
    for path in (("goodput",),
                 ("payload", "goodput"),
                 ("telemetry", "goodput"),
                 ("payload", "telemetry", "goodput"),
                 ("detail", "telemetry", "goodput"),
                 ("payload", "detail", "telemetry", "goodput"),
                 ("extra", "goodput")):
        cur = obj
        for key in path:
            cur = cur.get(key) if isinstance(cur, dict) else None
        if isinstance(cur, dict) and "seconds" in cur:
            return cur
    return None


def from_checkpoint_dir(directory):
    """The goodput pack of the newest checkpoint a resume would accept
    in ``directory`` — the same ``latest_valid`` scan
    ``CheckpointManager.restore(None)`` runs, so the report and an
    actual resume always describe the same checkpoint.  Multi-host
    layouts read host 0's shard (each host packs its own ledger)."""
    from apex_tpu.resilience.checkpoint import CheckpointManager, MANIFEST
    mgr = CheckpointManager(directory)
    path = mgr.latest_valid(record_events=False)
    if path is None:
        raise SystemExit(f"no valid checkpoint under {directory!r}")
    leaf = path
    if not os.path.exists(os.path.join(leaf, MANIFEST)):
        hosts = sorted(n for n in os.listdir(path)
                       if os.path.exists(os.path.join(path, n, MANIFEST)))
        if not hosts:
            raise SystemExit(f"checkpoint {path!r} has no manifest")
        leaf = os.path.join(path, hosts[0])
    manifest = mgr.read_manifest(leaf)
    gp = (manifest.get("extra") or {}).get("goodput") \
        if isinstance(manifest.get("extra"), dict) else None
    if not isinstance(gp, dict):
        raise SystemExit(
            f"checkpoint {path!r} carries no goodput pack — was the run "
            "armed via apex_tpu.telemetry.goodput.enable()?")
    return gp, path


def _fmt_tokens(n):
    return f"{int(n):,}"


def render(summary):
    """The human attribution table for one normalized summary."""
    s = summary
    lines = ["== goodput report =="]
    frac = s.get("goodput_fraction") or 0.0
    lines.append(f"wall        {s['wall_seconds']:.3f} s")
    lines.append(f"goodput     {100.0 * frac:.1f} %  (productive / wall)")
    lines.append(
        f"tokens      {_fmt_tokens(s['tokens_trained_total'])} total"
        f" · {s['effective_tokens_per_sec']:,.1f} tok/s effective")
    med = s.get("median_step_s")
    med_txt = f" · median step {1e3 * med:.1f} ms" if med else ""
    lines.append(
        f"steps       {s['steps']} (rework {s['rework_steps']}){med_txt}")
    roll = f" · rollbacks {s['rollbacks']}" if "rollbacks" in s else ""
    lines.append(f"restarts    {s['restarts']}{roll}")
    episodes = {k: v for k, v in (s.get("anomaly_episodes") or {}).items()
                if v}
    if episodes:
        lines.append("anomalies   " + " ".join(
            f"{k}={v}" for k, v in sorted(episodes.items())))
    lines.append("")
    lines.append(f"{'cause':<20}{'seconds':>12}{'%':>8}")
    wall = s["wall_seconds"]
    for cause in (*CAUSES, "unattributed"):
        sec = s["seconds"].get(cause, 0.0)
        pct = 100.0 * sec / wall if wall > 0 else 0.0
        lines.append(f"{cause:<20}{sec:>12.3f}{pct:>8.1f}")
    if s.get("overlap_seconds"):
        lines.append(
            f"(overlap {s['overlap_seconds']:.3f} s — async work counted "
            "in its bucket while steps ran)")
    if s.get("stages"):
        lines.append("")
        lines.append("pipeline stages (diagnostic, outside the identity):")
        for k, v in sorted(s["stages"].items()):
            lines.append(f"  {k:<18}{v:>12.3f}")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Render the run-ledger goodput attribution table.")
    ap.add_argument("source", nargs="?", default=None,
                    help="checkpoint directory or bundle/dump JSON file; "
                         "omit for the live in-process ledger")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the normalized summary as JSON")
    args = ap.parse_args(argv)

    origin = "live"
    if args.source is None:
        from apex_tpu.telemetry import goodput
        sec = goodput.section()
        if not sec.get("enabled"):
            if args.as_json:
                print(json.dumps(sec, indent=2, sort_keys=True))
            else:
                print(f"goodput: disarmed — {sec.get('goodput_reason')}")
            return 0
        gp = sec
    elif os.path.isdir(args.source):
        gp, origin = from_checkpoint_dir(args.source)
    else:
        with open(args.source) as f:
            obj = json.load(f)
        gp = extract(obj)
        origin = args.source
        if gp is None:
            raise SystemExit(
                f"{args.source!r} holds no goodput section in any known "
                "shape (bundle / dump / bench record / snapshot / pack)")

    summary = normalize(gp)
    summary["source"] = origin
    if args.as_json:
        print(json.dumps(summary, indent=2, sort_keys=True))
    else:
        print(render(summary))
        if origin != "live":
            print(f"\nsource: {origin}")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # a downstream `grep -q`/`head` closing the pipe early is a
        # normal way to consume this report, not an error — reopen
        # stdout on devnull so the interpreter's exit flush is quiet
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)

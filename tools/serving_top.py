"""Top-style view of a serving engine: live introspection or a dumped
flight bundle.

The request plane's human surface (docs/serving.md "follow one slow
request"): render what ``ContinuousBatcher.introspect()`` reports —
per-request state/age/deadline headroom/block footprint/chunk
progress, pool + prefix-cache occupancy, the SLO burn-rate window —
as one terminal screenful, from either

- a LIVE engine (``render_live(engine)`` from the serving process —
  the smoke in tools/check_serving.sh does exactly this), or
- a DUMPED bundle: an ``slo_violation`` / ``serving_*`` flight record
  (whose ``extra`` embeds the introspection snapshot and the offending
  requests' traces) or a bare ``introspect()`` JSON you saved
  yourself::

    python tools/serving_top.py bench_records/flightrec_*.json
    python tools/serving_top.py introspect.json

File shapes are resolved by structure, not name (the
telemetry_dump.py discipline): a records wrapper (``payload``), a
flight bundle (``trigger``), a fleet view (``engines`` +
``placement`` — ``FleetRouter.introspect()``, rendered by
``render_fleet`` with per-engine health rows — disaggregation role
and handoff counts included — the KV-handoff/colocated-fallback
summary, the failover log, and each engine's nested screen;
``fleet_engine_lost`` bundles render the
victim's last introspect + the recovery plan), or a bare
single-engine introspection dict (``requests`` + ``pool``) all work.
"""

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _bar(frac: float, width: int = 24) -> str:
    frac = min(max(float(frac), 0.0), 1.0)
    n = int(round(frac * width))
    return "[" + "#" * n + "." * (width - n) + f"] {frac * 100:5.1f}%"


def _fmt(v: Any, nd: int = 1) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def render(intro: Dict[str, Any]) -> str:
    """An ``introspect()`` dict as a top-style text screen."""
    lines: List[str] = []
    pool = intro.get("pool") or {}
    n_blocks = max(int(pool.get("num_blocks") or 1), 1)
    in_use = int(pool.get("blocks_in_use") or 0)
    lines.append(
        f"serving engine  step={intro.get('step')}  "
        f"queued={intro.get('queue_depth')}  "
        f"prefilling={intro.get('prefilling')}  "
        f"decoding={intro.get('in_flight')}"
        + ("  DRAINING" if intro.get("draining") else ""))
    lines.append(f"kv pool  {_bar(in_use / n_blocks)}  "
                 f"{in_use}/{n_blocks} blocks x "
                 f"{pool.get('block_size')} tokens")
    prefix = pool.get("prefix") or {}
    if prefix:
        hits = int(prefix.get("hits") or 0)
        misses = int(prefix.get("misses") or 0)
        rate = hits / max(hits + misses, 1)
        lines.append(
            f"prefix cache  hit rate {rate:.2f} ({hits}/{hits + misses})"
            f"  shared={prefix.get('shared_blocks')}"
            f"  cached={prefix.get('cached_blocks')}"
            f"  tokens_saved={prefix.get('tokens_saved')}")
    slo = intro.get("slo")
    if slo:
        alerting = slo.get("alerting") or []
        lines.append(f"slo  alerts_total={slo.get('alerts_total', 0)}"
                     + (f"  ALERTING: {', '.join(alerting)}"
                        if alerting else "  ok"))
        for name, tgt in sorted((slo.get("targets") or {}).items()):
            burns = "  ".join(
                f"{w['long_s']:g}s/{w['short_s']:g}s="
                f"{_fmt(w.get('burn_long'), 2)}/"
                f"{_fmt(w.get('burn_short'), 2)}"
                for w in tgt.get("windows") or [])
            flag = " !" if tgt.get("alerting") else ""
            lines.append(
                f"  {name:<12} {tgt.get('kind', 'le')} "
                f"{_fmt(tgt.get('objective'), 4)}  "
                f"window={_fmt(tgt.get('window_value'), 4)}  "
                f"burn {burns or '-'}{flag}")
    traces = intro.get("traces")
    if traces:
        lines.append(f"traces  live={traces.get('live')}  "
                     f"completed={traces.get('completed')}  "
                     f"minted={traces.get('minted')}")
    reqs = intro.get("requests") or []
    lines.append("")
    lines.append(f"{'ID':<14}{'STATE':<12}{'AGE_S':>8}{'DEADLN':>8}"
                 f"{'BLKS':>6}{'PREFILL':>10}{'GEN':>8}  TRACE")
    order = {"decoding": 0, "prefilling": 1, "queued": 2}
    for r in sorted(reqs, key=lambda r: (order.get(r.get("state"), 3),
                                         -float(r.get("age_s") or 0))):
        left = r.get("deadline_left_ms")
        lines.append(
            f"{str(r.get('id'))[:13]:<14}{r.get('state'):<12}"
            f"{_fmt(r.get('age_s'), 2):>8}"
            f"{(_fmt(left, 0) if left is not None else '-'):>8}"
            f"{r.get('blocks', 0):>6}"
            f"{str(r.get('prefilled')) + '/' + str(r.get('prompt_tokens')):>10}"
            f"{str(r.get('generated')) + '/' + str(r.get('max_new_tokens')):>8}"
            f"  {r.get('trace_id') or '-'}")
    if not reqs:
        lines.append("(no requests in flight)")
    return "\n".join(lines) + "\n"


def render_fleet(intro: Dict[str, Any]) -> str:
    """A ``FleetRouter.introspect()`` dict as a fleet screen: one
    health row per engine (state, disaggregation role, heartbeat age,
    last step, failures, hedges, handoffs, queue/prefill/decode load,
    shed flag), the KV-handoff/fallback summary, the failover log,
    then each live engine's own screen nested below."""
    lines: List[str] = []
    engines = intro.get("engines") or {}
    ho = intro.get("handoff") or {}
    fb = ho.get("fallback") or {}
    head = (
        f"serving fleet  step={intro.get('step')}  "
        f"placement={intro.get('placement')}  "
        f"engines={len(engines)}  orphans={intro.get('orphans')}  "
        f"refused_pending={intro.get('refused_pending')}")
    if fb.get("latched"):
        head += (f"  COLOCATED-FALLBACK(since step "
                 f"{fb.get('since_step')})")
    lines.append(head)
    if ho:
        lines.append(
            f"handoffs  ok={ho.get('ok', 0)}  "
            f"failed={ho.get('failed', 0)}  "
            f"orphan={ho.get('orphan', 0)}  "
            f"dst_crash={ho.get('dst_crash', 0)}  "
            f"retries={ho.get('retries', 0)}  "
            f"bytes={ho.get('bytes', 0)}")
    lines.append(f"{'ENGINE':<12}{'STATE':<10}{'ROLE':<11}"
                 f"{'BEAT_S':>8}{'STEP_S':>8}"
                 f"{'FAILS':>6}{'HEDGED':>7}{'HO>':>5}{'>HO':>5}"
                 f"{'Q':>4}{'PRE':>5}{'DEC':>5}"
                 "  FLAGS")
    for name in sorted(engines):
        e = engines[name]
        nested = e.get("engine") or {}
        flags = []
        if e.get("shedding"):
            flags.append("SHED")
        if e.get("error"):
            flags.append(str(e["error"])[:40])
        lines.append(
            f"{name[:11]:<12}{str(e.get('status')):<10}"
            f"{str(e.get('role', '-')):<11}"
            f"{_fmt(e.get('heartbeat_age_s'), 2):>8}"
            f"{_fmt(e.get('last_step_s'), 3):>8}"
            f"{e.get('step_failures', 0):>6}{e.get('hedged', 0):>7}"
            f"{e.get('handoffs_out', 0):>5}{e.get('handoffs_in', 0):>5}"
            f"{_fmt(nested.get('queue_depth')):>4}"
            f"{_fmt(nested.get('prefilling')):>5}"
            f"{_fmt(nested.get('in_flight')):>5}"
            f"  {' '.join(flags) or '-'}")
    failovers = intro.get("failovers") or []
    if failovers:
        lines.append("")
        lines.append(f"{'FAILOVER':<12}{'CAUSE':<9}{'SOURCE':<10}"
                     f"{'STEP':>6}{'RECOV_MS':>10}  RECOVERED")
        for f in failovers:
            rec = f.get("recovered") or []
            lines.append(
                f"{str(f.get('engine'))[:11]:<12}"
                f"{str(f.get('cause')):<9}{str(f.get('source')):<10}"
                f"{_fmt(f.get('router_step')):>6}"
                f"{_fmt((f.get('recover_s') or 0) * 1e3, 1):>10}"
                f"  {', '.join(map(str, rec)) or '-'}")
    out = "\n".join(lines) + "\n"
    for name in sorted(engines):
        nested = engines[name].get("engine")
        if isinstance(nested, dict):
            out += f"\n--- {name} ---\n" + render(nested)
    return out


def _trace_table(traces: List[Dict[str, Any]]) -> str:
    lines = [f"{'REQUEST':<14}{'TRACE':<22}{'OUTCOME':<18}"
             f"{'SPANS':>6}{'CHUNKS':>7}{'TTFT_S':>9}{'WALL_S':>9}"
             "  RESUMED_FROM"]
    for t in traces:
        first = next((m["t"] for m in t.get("marks") or []
                      if m["name"] == "first_token"), None)
        ttft = (first - t["t_submit"]) if first is not None else None
        wall = ((t["t_finish"] - t["t_submit"])
                if t.get("t_finish") is not None else None)
        chunks = sum(1 for s in t.get("spans") or []
                     if s["name"].startswith("prefill_chunk"))
        lines.append(
            f"{str(t.get('request_id'))[:13]:<14}"
            f"{str(t.get('trace_id'))[:21]:<22}"
            f"{str(t.get('outcome') or t.get('state'))[:17]:<18}"
            f"{len(t.get('spans') or []):>6}{chunks:>7}"
            f"{_fmt(ttft, 4):>9}{_fmt(wall, 4):>9}"
            f"  {t.get('resumed_from') or '-'}")
    return "\n".join(lines) + "\n"


def render_bundle(obj: Dict[str, Any]) -> str:
    """A flight-recorder bundle (`slo_violation` or any serving
    trigger): header + the embedded introspection snapshot and/or
    offending-request traces from ``extra``."""
    bundle = obj.get("payload") if isinstance(obj.get("payload"),
                                              dict) else obj
    lines = [f"flight bundle  trigger={bundle.get('trigger')}  "
             f"pid={bundle.get('pid')}"]
    if bundle.get("error"):
        lines.append(f"error: {bundle['error']}")
    extra = bundle.get("extra") or {}
    if extra.get("slo"):
        offenders = ", ".join(map(str, extra.get("requests") or []))
        lines.append(f"slo: {extra['slo']}  "
                     f"offending requests: {offenders or '-'}")
    out = "\n".join(lines) + "\n"
    intro = extra.get("introspect")
    if isinstance(intro, dict):
        out += "\n" + render(intro)
    # fleet_engine_lost: the victim's final state + the recovery plan
    last = extra.get("last_introspect")
    if isinstance(last, dict):
        out += (f"\nlost engine {extra.get('engine')} "
                f"(cause={extra.get('cause')}) — last introspect:\n")
        out += render(last)
    plan = extra.get("plan")
    if isinstance(plan, dict):
        targets = plan.get("targets") or {}
        out += (f"\nrecovery plan  source={plan.get('source')}  "
                f"snapshot={plan.get('snapshot') or '-'}\n")
        for rid, tgt in targets.items():
            out += f"  {rid} -> {tgt or 'ORPHANED'}\n"
    traces = extra.get("traces")
    if traces:
        out += "\n" + _trace_table(traces)
    return out


def render_live(engine) -> str:
    """The live view: ``render(engine.introspect())``."""
    return render(engine.introspect())


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="top-style view of a serving engine introspection "
                    "dump or flight bundle")
    parser.add_argument("path", help="JSON file: flight-recorder "
                                     "bundle or introspect() dump")
    args = parser.parse_args(argv)
    try:
        with open(args.path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        print(f"error: cannot read {args.path}: {e}", file=sys.stderr)
        return 2
    payload = (obj.get("payload")
               if isinstance(obj, dict) and isinstance(obj.get("payload"),
                                                       dict) else obj)
    if not isinstance(payload, dict):
        print(f"error: {args.path} holds no renderable dict",
              file=sys.stderr)
        return 2
    if "trigger" in payload:
        sys.stdout.write(render_bundle(payload))
    elif "engines" in payload and "placement" in payload:
        sys.stdout.write(render_fleet(payload))
    elif "requests" in payload and "pool" in payload:
        sys.stdout.write(render(payload))
    else:
        print(f"error: {args.path} is neither a flight bundle nor an "
              "introspect() dump", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())

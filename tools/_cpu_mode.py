"""Force the hardware tools onto the plain CPU backend.

The axon TPU-tunnel plugin hooks jax backend lookup at interpreter
start; on a dead tunnel any `jax.default_backend()` call sleeps in the
plugin's retry loop — which, inside a tool that has already taken the
TPU slot lock, wedges every other client behind a process that will
never run (observed round 4). `tests/conftest.py` strips the plugin for
the test suite; this is the same strip as a callable, used by the
tools' ``--cpu`` flags for CPU logic-validation runs (CI, interpret
parity) that must never touch the tunnel.

Call BEFORE the first jax import in the process.
"""

import os
import sys


def force_cpu():
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "XLA_FLAGS",
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8")
    sys.path = [p for p in sys.path if ".axon_site" not in p]
    os.environ.pop("PYTHONPATH", None)
    import jax
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    hook = _xb._get_backend_uncached
    if getattr(hook, "__name__", "") == "_axon_get_backend_uncached":
        for cell in hook.__closure__ or ():
            if callable(cell.cell_contents):
                _xb._get_backend_uncached = cell.cell_contents
    jax.config.update("jax_platforms", "cpu")


__all__ = ["force_cpu"]

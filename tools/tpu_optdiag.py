"""Fused-optimizer step breakdown on the real chip.

The round-3 headline artifact showed FusedLAMB at 4.3x optax (84 ms vs
19 ms at 335M params, ~160 GB/s effective) — far from the <=1.1x
north-star. This tool decomposes the step so the fix lands where the
time actually goes. Measurement phases are ordered to stage memory on a
16 GB chip (each drops its buffers before the next allocates) and each
is fault-isolated so one failure never loses the rest:

  1. chip identity + raw HBM streaming bandwidth (natural-feed copy)
  2. optax.lamb on the param tree, state threaded (the baseline)
  3. the FULL FusedLAMB.step as the bench runs it (pack + kernel +
     unpack + per-leaf probe), both impls
  4. kernel-only fused_lamb/adam on pre-flat buffers, both impls
     (full minus kernel = the plumbing the flat design pays)

    python tools/tpu_optdiag.py            # BERT-large-class shapes
    python tools/tpu_optdiag.py --small    # ~40M quick pass

One JSON line per measurement; all timing via the feed-threaded chained
loop (tunnel round-trips never inside the sample; every measurement
has a REAL iteration-to-iteration data dependence, see tpu_smoke._time).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from tpu_smoke import opt_feed  # noqa: E402
from tpu_longctx import _time_adaptive  # noqa: E402


_LINES = []


def rec(**kw):
    _LINES.append(kw)
    print(json.dumps(kw), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true")
    args = ap.parse_args()

    from apex_tpu.backend_guard import tpu_slot_lock

    with tpu_slot_lock():
        import jax
        import jax.numpy as jnp
        import optax

        import apex_tpu.multi_tensor as mt
        from apex_tpu.optimizers import FusedLAMB
        from bench import bert_large_shapes

        d = jax.devices()[0]
        rec(what="device", kind=str(d.device_kind),
            platform=str(d.platform),
            backend=str(jax.default_backend()))

        # interpret-mode pallas at these sizes is not a measurement;
        # on CPU only the xla impl is timed (the chip times both)
        impls = (("xla",) if jax.default_backend() == "cpu"
                 else ("pallas", "xla"))

        def make_trees():
            # regenerable (same seed) so later phases can rebuild the
            # trees after dropping them for chip-memory headroom
            r = np.random.RandomState(0)
            shapes = (bert_large_shapes(hidden=512, layers=8)
                      if args.small else bert_large_shapes())
            ps = {
                f"p{i}": jnp.asarray(
                    r.randn(*s).astype(np.float32) * 0.02)
                for i, s in enumerate(shapes)
            }
            gs = {
                k: jnp.asarray(
                    r.randn(*v.shape).astype(np.float32) * 1e-3)
                for k, v in ps.items()
            }
            return shapes, ps, gs

        # 1. raw streaming bandwidth: out-of-place scale of a 1 GiB
        # buffer, output fed back as next input (zero harness traffic)
        try:
            n_raw = 1 << 28   # 268M fp32 = 1 GiB
            buf = jnp.asarray(
                np.random.RandomState(1).randn(n_raw).astype(np.float32))
            t = _time_adaptive(lambda b: (b * 1.0000001,), buf,
                               feed=lambda out, carry: out)
            rec(what="raw_copy_scale", gib=1.0, ms=round(t * 1e3, 3),
                gb_per_sec=round(2 * n_raw * 4 / t / 1e9, 1))
            del buf
        except Exception as e:  # noqa: BLE001
            rec(what="raw_copy_scale",
                error=f"{type(e).__name__}: {str(e)[:120]}")

        shapes, params, grads = make_trees()
        space = mt.FlatSpace.create(params)
        n = int(space.total)
        gb = n * 4 / 1e9
        rec(what="workload", n_params=n, n_tensors=len(shapes),
            fp32_gb=round(gb, 3))

        # 2. optax.lamb on the tree, state threaded (the baseline,
        # measured with the same chained discipline as everything else)
        try:
            tx = optax.lamb(1e-3, weight_decay=0.01)
            ostate = tx.init(params)
            ps_leaves, ps_def = jax.tree.flatten((params, ostate))
            n_ps = len(ps_leaves)
            g_leaves, g_def = jax.tree.flatten(grads)

            def optax_step(*leaves):
                p, s = jax.tree.unflatten(ps_def, leaves[:n_ps])
                g = jax.tree.unflatten(g_def, leaves[n_ps:])
                upd, s2 = tx.update(g, s, p)
                p2 = optax.apply_updates(p, upd)
                probe = sum(jnp.sum(l) for l in jax.tree.leaves(p2))
                return (*jax.tree.leaves((p2, s2)), probe)

            t = _time_adaptive(
                optax_step, *ps_leaves, *g_leaves,
                feed=lambda out, carry: (*out[:n_ps], *carry[n_ps:]))
            rec(what="optax_lamb_tree", ms=round(t * 1e3, 3),
                gb_per_sec=round(10 * gb / t, 1))
            del ostate, ps_leaves
        except Exception as e:  # noqa: BLE001
            rec(what="optax_lamb_tree",
                error=f"{type(e).__name__}: {str(e)[:120]}")

        # 3. the FULL FusedLAMB.step exactly as bench.py's headline runs
        # it: pack(grad tree) + kernel + unpack + per-leaf probe fold.
        # Each impl's 3-buffer state (4 GB at BERT-large scale) is
        # dropped before the next allocates — two live states OOM the
        # 16 GB chip and a chip-side OOM degrades the tunnel for
        # everyone after (docs/HARDWARE_NOTES.md).
        for impl in impls:
            state0 = None
            try:
                opt = FusedLAMB(lr=1e-3, weight_decay=0.01,
                                max_grad_norm=0.0, use_nvlamb=True,
                                impl=impl)
                state0 = opt.init(params)

                def full_step(master, m_, v_, count, *gleaves,
                              opt=opt, state0=state0):
                    gtree = dict(zip(sorted(grads), gleaves))
                    st = state0._replace(
                        master=master,
                        slots={"m": m_, "v": v_}, count=count)
                    new_params, st2 = opt.step(st, gtree)
                    probe = sum(jnp.sum(l)
                                for l in jax.tree.leaves(new_params))
                    return (st2.master, st2.slots["m"], st2.slots["v"],
                            st2.count, probe)

                t = _time_adaptive(
                    full_step, state0.master, state0.slots["m"],
                    state0.slots["v"], state0.count,
                    *[grads[k] for k in sorted(grads)],
                    feed=lambda out, carry: (*out[:4], *carry[4:]))
                rec(what="full_step_pack_kernel_unpack", impl=impl,
                    ms=round(t * 1e3, 3))
            except Exception as e:  # noqa: BLE001
                rec(what="full_step_pack_kernel_unpack", impl=impl,
                    error=f"{type(e).__name__}: {str(e)[:120]}")
            finally:
                del state0

        # 4. kernel-only updates on pre-flat buffers; the param/grad
        # trees are dropped first so the chained loop has headroom for
        # its in-flight outputs (carry + new state + update term)
        try:
            flat_g = space.pack(grads, dtype=jnp.float32)
            flat_p = space.pack(params, dtype=jnp.float32)
            m = jnp.zeros_like(flat_p)
            v = jnp.zeros_like(flat_p)
            del params, grads
        except Exception as e:  # noqa: BLE001
            rec(what="kernel_only_setup",
                error=f"{type(e).__name__}: {str(e)[:120]}")
            return

        for name, fn in (
            ("lamb", lambda p_, m_, v_, g_, impl: mt.fused_lamb_update(
                p_, m_, v_, g_, space, lr=1e-3, step=2, weight_decay=0.01,
                use_nvlamb=True, max_grad_norm=0.0, impl=impl)[:3]),
            ("adam", lambda p_, m_, v_, g_, impl: mt.fused_adam_update(
                p_, m_, v_, g_, lr=1e-3, step=2, weight_decay=0.01,
                impl=impl)[:3]),
        ):
            # traffic: lamb r(p,m,v,g)+w(u,m,v) stage1, r(p,u)+w(p)
            # stage2 = 10x n*4; adam r(p,m,v,g)+w(p,m,v) = 7x
            acc = 10 if name == "lamb" else 7
            for impl in impls:
                try:
                    t = _time_adaptive(
                        lambda p_, m_, v_, g_, fn=fn, impl=impl:
                        fn(p_, m_, v_, g_, impl), flat_p, m, v, flat_g,
                        feed=opt_feed)
                    rec(what=f"fused_{name}_update_flat", impl=impl,
                        ms=round(t * 1e3, 3),
                        gb_per_sec=round(acc * gb / t, 1))
                except Exception as e:  # noqa: BLE001
                    rec(what=f"fused_{name}_update_flat", impl=impl,
                        error=f"{type(e).__name__}: {str(e)[:120]}")

        # 5. the segment-resident ONE-PASS LAMB (multi_tensor/
        # segmented.py) — the round-3 redesign that answers optax's
        # per-leaf fusion; never measured on chip before round 4. The
        # plain flat buffers are dropped first and the trees rebuilt
        # (different layout padding), keeping peak memory at one
        # workload set.
        del flat_p, flat_g, m, v
        from apex_tpu.multi_tensor.segmented import (
            fused_lamb_segmented_update,
        )

        for label, kw in (
            ("stash_p", {}),
            ("stream_p", {"seg_stash_p": False}),
            ("stream_p_bf16u", {"seg_stash_p": False,
                                "seg_allow_bf16_u": True,
                                "seg_u_dtype": jnp.bfloat16}),
        ):
            seg_p = None
            try:
                _, params, grads = make_trees()
                opt = FusedLAMB(lr=1e-3, weight_decay=0.01,
                                max_grad_norm=0.0, use_nvlamb=True, **kw)
                seg, stash, u_dt = opt._segment_config(params)
                from apex_tpu.multi_tensor.flat_buffer import (
                    segmented_space,
                )

                seg_space, seg_meta = segmented_space(params,
                                                      seg_elems=seg)
                import dataclasses as _dc

                seg_meta = _dc.replace(
                    seg_meta, stash_p=bool(stash),
                    u_dtype_name=jnp.dtype(u_dt).name)
                seg_p = seg_space.pack(params, dtype=jnp.float32)
                seg_g = seg_space.pack(grads, dtype=jnp.float32)
                del params, grads
                sm = jnp.zeros_like(seg_p)
                sv = jnp.zeros_like(seg_p)
                seg_gb = int(seg_space.total) * 4 / 1e9
                covered = 1.0 - sum(
                    pl for (_, _, pl) in seg_meta.large
                ) / max(int(seg_space.total), 1)
                acc = 7 if seg_meta.stash_p else 8

                seg_impl = ("xla" if jax.default_backend() == "cpu"
                            else "pallas")

                def seg_fn(p_, m_, v_, g_, seg_impl=seg_impl):
                    return fused_lamb_segmented_update(
                        p_, m_, v_, g_, seg_space, seg_meta, lr=1e-3,
                        step=2, weight_decay=0.01, use_nvlamb=True,
                        max_grad_norm=0.0, impl=seg_impl)[:3]

                t = _time_adaptive(
                    seg_fn, seg_p, sm, sv, seg_g,
                    feed=lambda out, carry: (*out, carry[3]))
                rec(what="fused_lamb_segmented_onepass", config=label,
                    seg_elems=int(seg_meta.seg_elems),
                    stash_p=bool(seg_meta.stash_p),
                    u_dtype=seg_meta.u_dtype_name,
                    covered_frac=round(covered, 4),
                    ms=round(t * 1e3, 3),
                    gb_per_sec_at_small_acc=round(acc * seg_gb / t, 1))
                del sm, sv, seg_g
            except Exception as e:  # noqa: BLE001
                rec(what="fused_lamb_segmented_onepass", config=label,
                    error=f"{type(e).__name__}: {str(e)[:200]}")
            finally:
                del seg_p

        if jax.default_backend() == "tpu":
            from apex_tpu.records import write_record

            path = write_record(
                "optdiag",
                {"small": bool(args.small), "lines": _LINES},
                backend="tpu")
            if path:
                print(f"# record: {path}", file=sys.stderr)


if __name__ == "__main__":
    main()

"""Chip health gauge: raw streaming bandwidth probe.

The axon tunnel degrades after OOM'd/killed clients — everything still
*runs*, just 5-10x slower (observed 574 -> 99 GB/s raw copy within an
hour, docs/HARDWARE_NOTES.md round-3 log), which silently poisons every
measurement taken in the window. Gate hardware measurement queues on
this: exit 0 iff the chip streams above ``--min-gbps``.

    python tools/tpu_health.py             # probe, print JSON, gate at 300
    python tools/tpu_health.py --min-gbps 400
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from tpu_longctx import _time_adaptive  # noqa: E402


def probe_gbps(n=1 << 26):
    """Streaming GB/s of an out-of-place scale over a 256 MB buffer."""
    import jax.numpy as jnp

    buf = jnp.asarray(np.random.RandomState(0).randn(n).astype(np.float32))
    t = _time_adaptive(lambda b: (b * 1.0000001,), buf, target_s=1.0,
                       feed=lambda out, carry: out)
    return 2 * n * 4 / t / 1e9


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-gbps", type=float, default=300.0)
    args = ap.parse_args()

    from apex_tpu.backend_guard import tpu_slot_lock

    with tpu_slot_lock():
        import jax

        backend = str(jax.default_backend())
        gbps = probe_gbps()
        healthy = backend == "tpu" and gbps >= args.min_gbps
        out = {
            "backend": backend,
            "raw_copy_gb_per_sec": round(gbps, 1),
            "healthy": bool(healthy),
            "min_gbps": args.min_gbps,
        }
        print(json.dumps(out))
        if backend == "tpu":
            from apex_tpu.records import write_record

            write_record("health", out, backend="tpu")
        sys.exit(0 if healthy else 1)


if __name__ == "__main__":
    main()

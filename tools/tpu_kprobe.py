"""Pallas fused-engine factor isolation: where do the GB/s go?

Healthy-chip facts (tools/tpu_optdiag.py, 2026-07-31): raw streaming
574 GB/s, engine pallas adam 133-137 GB/s (tile-size-INsensitive),
engine xla impl 236 GB/s, optax-on-trees ~480+. This probe times a
ladder of kernels from a pure copy up to the real engine call, each
step adding ONE suspect factor, so the slowdown attributes to a
mechanism instead of a guess:

  copy1          1-in/1-out pallas copy            (pallas ceiling)
  multi7         4-in/3-out passthrough            (stream count)
  adam_math      + real Adam arithmetic            (VPU cost)
  adam_found     + found_inf SMEM accumulator      (revisited output)
  adam_alias     + input_output_aliases, undonated (defensive
                 copies; NOTE a donated rung is impossible here —
                 donation inside _time's traced loop is a no-op, and
                 the loop's threaded carry already gives XLA
                 steady-state buffer reuse)
  engine         mt.fused_adam_update as shipped
  jnp_fused      one fused jnp expression, no engine machinery

    python tools/tpu_kprobe.py             # n=64M, tile 512
    python tools/tpu_kprobe.py --n 16777216 --tile-rows 1024
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from tpu_smoke import opt_feed  # noqa: E402
from tpu_longctx import _time_adaptive  # noqa: E402

LANES = 128


def rec(**kw):
    print(json.dumps(kw), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=64_000_000)
    ap.add_argument("--tile-rows", type=int, default=512)
    args = ap.parse_args()

    from apex_tpu.backend_guard import tpu_slot_lock

    with tpu_slot_lock():
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        import apex_tpu.multi_tensor as mt

        on_cpu = jax.default_backend() == "cpu"
        n = 1 << 20 if on_cpu else args.n
        tr = args.tile_rows
        tile = tr * LANES
        padded = ((n + tile - 1) // tile) * tile
        num_tiles = padded // tile
        rng = np.random.RandomState(0)
        p = jnp.asarray(rng.randn(padded).astype(np.float32))
        g = jnp.asarray(rng.randn(padded).astype(np.float32) * 1e-3)
        m = jnp.zeros_like(p)
        v = jnp.zeros_like(p)
        gb = padded * 4 / 1e9
        interp = on_cpu
        rec(what="config", n=padded, tile_rows=tr, backend=str(
            jax.default_backend()), fp32_gb=round(gb, 3))

        spec = pl.BlockSpec((tr, LANES), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
        r2 = lambda b: b.reshape(padded // LANES, LANES)   # noqa: E731

        def timed(name, fn, *bufs, acc, feed):
            try:
                t = _time_adaptive(fn, *bufs, feed=feed)
                rec(what=name, ms=round(t * 1e3, 3),
                    gb_per_sec=round(acc * gb / t, 1))
            except Exception as e:  # noqa: BLE001
                rec(what=name, error=f"{type(e).__name__}: {str(e)[:110]}")

        # -- copy1: the pallas streaming ceiling -------------------------
        def copy_kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 1.0000001

        copy_call = pl.pallas_call(
            copy_kernel, grid=(num_tiles,), in_specs=[spec],
            out_specs=spec,
            out_shape=jax.ShapeDtypeStruct((padded // LANES, LANES),
                                           jnp.float32),
            interpret=interp)
        timed("copy1", lambda x: (copy_call(r2(x)).reshape(-1),), p,
              acc=2, feed=lambda out, carry: out)

        # -- multi7: 4 streams in, 3 out, no math ------------------------
        def multi_kernel(p_ref, m_ref, v_ref, g_ref, po, mo, vo):
            po[...] = p_ref[...] * 1.0000001
            mo[...] = m_ref[...] * 1.0000001
            vo[...] = v_ref[...] + g_ref[...]

        multi_call = pl.pallas_call(
            multi_kernel, grid=(num_tiles,), in_specs=[spec] * 4,
            out_specs=[spec] * 3,
            out_shape=[jax.ShapeDtypeStruct((padded // LANES, LANES),
                                            jnp.float32)] * 3,
            interpret=interp)
        timed("multi7",
              lambda p_, m_, v_, g_: tuple(
                  o.reshape(-1) for o in multi_call(
                      r2(p_), r2(m_), r2(v_), r2(g_))),
              p, m, v, g, acc=7, feed=opt_feed)

        # -- adam math (no found, no alias) ------------------------------
        def adam_body(p_, m_, v_, g_):
            m2 = 0.9 * m_ + 0.1 * g_
            v2 = 0.999 * v_ + 0.001 * g_ * g_
            up = m2 / (jnp.sqrt(v2) + 1e-8) + 0.01 * p_
            return p_ - 1e-3 * up, m2, v2

        def adam_kernel(p_ref, m_ref, v_ref, g_ref, po, mo, vo):
            p2, m2, v2 = adam_body(p_ref[...], m_ref[...], v_ref[...],
                                   g_ref[...])
            po[...] = p2
            mo[...] = m2
            vo[...] = v2

        adam_call = pl.pallas_call(
            adam_kernel, grid=(num_tiles,), in_specs=[spec] * 4,
            out_specs=[spec] * 3,
            out_shape=[jax.ShapeDtypeStruct((padded // LANES, LANES),
                                            jnp.float32)] * 3,
            interpret=interp)
        timed("adam_math",
              lambda p_, m_, v_, g_: tuple(
                  o.reshape(-1) for o in adam_call(
                      r2(p_), r2(m_), r2(v_), r2(g_))),
              p, m, v, g, acc=7, feed=opt_feed)

        # -- + found_inf SMEM accumulator --------------------------------
        def adamf_kernel(p_ref, m_ref, v_ref, g_ref, po, mo, vo, fo):
            i = pl.program_id(0)

            @pl.when(i == 0)
            def _():
                fo[0, 0] = jnp.float32(0.0)

            gv = g_ref[...]
            ok = jnp.all(jnp.isfinite(gv))
            fo[0, 0] = jnp.maximum(
                fo[0, 0], jnp.where(ok, 0.0, 1.0).astype(jnp.float32))
            p2, m2, v2 = adam_body(p_ref[...], m_ref[...], v_ref[...], gv)
            po[...] = p2
            mo[...] = m2
            vo[...] = v2

        adamf_call = pl.pallas_call(
            adamf_kernel, grid=(num_tiles,), in_specs=[spec] * 4,
            out_specs=[spec] * 3 + [
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM)],
            out_shape=[jax.ShapeDtypeStruct((padded // LANES, LANES),
                                            jnp.float32)] * 3
            + [jax.ShapeDtypeStruct((1, 1), jnp.float32)],
            interpret=interp)
        timed("adam_found",
              lambda p_, m_, v_, g_: tuple(
                  o.reshape(-1) if o.ndim > 1 and o.shape[-1] == LANES
                  else o
                  for o in adamf_call(r2(p_), r2(m_), r2(v_), r2(g_)))[:3],
              p, m, v, g, acc=7, feed=opt_feed)

        # -- + aliases, UNdonated (XLA inserts defensive copies) ---------
        adama_call = pl.pallas_call(
            adam_kernel, grid=(num_tiles,), in_specs=[spec] * 4,
            out_specs=[spec] * 3,
            out_shape=[jax.ShapeDtypeStruct((padded // LANES, LANES),
                                            jnp.float32)] * 3,
            input_output_aliases={0: 0, 1: 1, 2: 2},
            interpret=interp)
        timed("adam_alias_undonated",
              lambda p_, m_, v_, g_: tuple(
                  o.reshape(-1) for o in adama_call(
                      r2(p_), r2(m_), r2(v_), r2(g_))),
              p, m, v, g, acc=7, feed=opt_feed)

        # -- the engine as shipped ---------------------------------------
        timed("engine_fused_adam",
              lambda p_, m_, v_, g_: mt.fused_adam_update(
                  p_, m_, v_, g_, lr=1e-3, step=2, weight_decay=0.01,
                  impl="xla" if on_cpu else "pallas")[:3],
              p, m, v, g, acc=7, feed=opt_feed)

        # -- one fused jnp expression (XLA on the flat buffer) -----------
        timed("jnp_fused",
              lambda p_, m_, v_, g_: adam_body(p_, m_, v_, g_),
              p, m, v, g, acc=7, feed=opt_feed)


if __name__ == "__main__":
    main()

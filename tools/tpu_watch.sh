#!/bin/bash
# Tunnel watcher: probe the single-slot TPU tunnel until it answers,
# then run the full measurement runbook ONCE and exit.
#
# The tunnel wedges for long stretches after killed/OOM'd clients
# (docs/HARDWARE_NOTES.md "Known tunnel behaviors"); this keeps a
# session's hardware queue alive without a human re-trying. Each probe
# is a 120 s-timeout subprocess (apex_tpu.backend_guard), so a wedged
# tunnel can never hang the watcher itself.
set -u
cd "$(dirname "$0")/.."
INTERVAL=${INTERVAL:-480}
while true; do
  if timeout 150 python -c "
from apex_tpu.backend_guard import probe_default_backend as p
import sys
r = p()
print(r, flush=True)
sys.exit(0 if r.get('ok') and r.get('platform') == 'tpu' else 1)
"; then
    echo "tunnel up $(date -u +%H:%M:%S); launching runbook"
    LOGDIR=${LOGDIR:-/tmp/tpu_runbook_auto} exec bash tools/tpu_runbook.sh
  fi
  echo "tunnel down $(date -u +%H:%M:%S); retry in ${INTERVAL}s"
  sleep "$INTERVAL"
done

#!/usr/bin/env bash
# Serving smoke (CI / pre-merge, next to check_telemetry.sh): the
# serving unit tier, then a 200-request continuous-batching run under
# JAX_PLATFORMS=cpu with the compile tracker ARMED, asserting
#  - continuous batching beats the naive static-batch baseline on
#    generated tokens/sec (same jitted programs, same cache — the win
#    is pure scheduling),
#  - exactly the expected decode-bucket compile count (ONE program:
#    decode pads to max_batch over one table-width bucket), and
#  - ZERO decode recompile events after warmup (no recompile storm in
#    the hot loop — docs/serving.md "compile plane").
# Extra args pass through to pytest.
set -uo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu

rc=0

python -m pytest tests/test_serving.py "$@" -q \
    -p no:cacheprovider || rc=1

echo "== 200-request smoke: continuous batching vs static batch =="
python - <<'PY' || rc=1
import time

import numpy as np
import jax
import jax.numpy as jnp

from apex_tpu import serving, telemetry
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.telemetry import compiled as _compiled

cfg = GPTConfig(vocab_size=512, max_seq_len=128, hidden_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2,
                dtype=jnp.float32, param_dtype=jnp.float32)
model = GPTModel(cfg)
rng = np.random.RandomState(0)
params = model.init(jax.random.PRNGKey(0),
                    jnp.asarray(rng.randint(0, 512, (1, 8)), jnp.int32))
MAX_BATCH = 8
cache = serving.KVCache.for_config(cfg, num_blocks=MAX_BATCH * 8,
                                   block_size=16)
step_fn = serving.make_decode_step(model, cache)

N = 200
def make_requests(tag):
    return [serving.Request(
        id=f"{tag}{i}",
        prompt=rng.randint(0, 512, (int(rng.randint(4, 25)),)),
        max_new_tokens=int(rng.randint(4, 41))) for i in range(N)]

reg = telemetry.MetricsRegistry()
sink = telemetry.InMemorySink()
reg.add_sink(sink)
tracker = _compiled.enable(registry=reg)
try:
    eng = serving.ContinuousBatcher(model, params, cache,
                                    max_batch=MAX_BATCH, step_fn=step_fn,
                                    min_seq_bucket=32, registry=reg)
    state = eng.warmup(cache.init_state())
    out = step_fn.prefill(        # the static loop's full-batch bucket
        params, state, np.zeros((MAX_BATCH, 32), np.int32),
        np.zeros((MAX_BATCH,), np.int32),
        np.zeros((MAX_BATCH, eng.min_width_bucket), np.int32))
    state = out.cache
    jax.block_until_ready(out.next_token)
    del state

    warm_decode_sigs = tracker.summary()["signatures"]["decode_step"]
    warm_events = [e["event"] for e in sink.events
                   if "decode_step" in str(e.get("fn", ""))]
    # warmup deliberately mints every bucketed program back-to-back —
    # storms there are by construction; the contract is the HOT LOOP
    n_warm_storms = sum(e["event"] == "recompile_storm"
                        for e in sink.events)

    # static baseline first (burst arrivals: the barrier cost is the
    # whole story), then continuous batching on the same workload
    state = cache.init_state()
    t0 = time.perf_counter()
    state, st_res = serving.static_batch_generate(
        model, params, cache, state, make_requests("s"),
        batch_size=MAX_BATCH, step_fn=step_fn, min_seq_bucket=32)
    st_wall = time.perf_counter() - t0
    st_toks = sum(len(r.tokens) for r in st_res)
    del state

    state = cache.init_state()
    t0 = time.perf_counter()
    state, cb_res = serving.serve_loop(eng, state, make_requests("c"))
    cb_wall = time.perf_counter() - t0
    cb_toks = sum(len(r.tokens) for r in cb_res)

    st_tps = st_toks / st_wall
    cb_tps = cb_toks / cb_wall
    ttft = sorted(r.ttft_s for r in cb_res)
    print(f"static : {st_toks} tokens in {st_wall:.2f}s = {st_tps:.0f} tok/s")
    print(f"contin.: {cb_toks} tokens in {cb_wall:.2f}s = {cb_tps:.0f} tok/s "
          f"({cb_tps / st_tps:.2f}x)  ttft p50 "
          f"{ttft[len(ttft)//2]*1e3:.1f}ms")
    assert len(cb_res) == N and len(st_res) == N
    assert all(r.finish_reason == "length" for r in cb_res), \
        "continuous run had non-length finishes"
    assert cb_tps > st_tps, (
        f"continuous batching ({cb_tps:.0f} tok/s) must beat the "
        f"static-batch baseline ({st_tps:.0f} tok/s)")

    # compile plane: decode = exactly ONE bucketed program, and the
    # 200-request hot loop minted no new decode signatures (zero
    # recompile events after warmup — no storm)
    keys = step_fn.compile_keys()
    assert keys["decode_step"] == 1, keys
    sigs = tracker.summary()["signatures"]["decode_step"]
    assert sigs == warm_decode_sigs == 1, (sigs, warm_decode_sigs)
    hot_decode_events = [
        e["event"] for e in sink.events
        if "decode_step" in str(e.get("fn", ""))]
    assert hot_decode_events == warm_events, (
        f"decode recompile events after warmup: {hot_decode_events}")
    storms = [e for e in sink.events if e["event"] == "recompile_storm"]
    assert len(storms) == n_warm_storms, (
        f"recompile storm escalated in the hot loop: "
        f"{storms[n_warm_storms:]}")
    print(f"compile plane OK: {keys}, decode signatures={sigs}, "
          f"zero hot-loop recompiles, no storms")
finally:
    _compiled.disable()
PY

if [ "$rc" -ne 0 ]; then
    echo "check_serving: FAILED" >&2
else
    echo "check_serving: OK"
fi
exit "$rc"

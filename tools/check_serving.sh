#!/usr/bin/env bash
# Serving smoke (CI / pre-merge, next to check_telemetry.sh): the
# serving unit tier, then a 200-request continuous-batching run under
# JAX_PLATFORMS=cpu with the compile tracker ARMED, asserting
#  - continuous batching beats the naive static-batch baseline on
#    generated tokens/sec (same jitted programs, same cache — the win
#    is pure scheduling),
#  - exactly the expected decode-bucket compile count (ONE program:
#    decode pads to max_batch over one table-width bucket), and
#  - ZERO decode recompile events after warmup (no recompile storm in
#    the hot loop — docs/serving.md "compile plane"),
# then the serving hot path (docs/serving.md "Chunked prefill"):
#  - the LONG-PROMPT smoke: a sustained decode workload with
#    max-seq-scale prompts arriving mid-run, chunked — concurrent
#    long prefill must not degrade the in-flight decode MEAN TPOT by
#    more than 25% (p99 guarded at 4x) vs a decode-only run of the
#    same short workload, asserted from the recorded
#    serving_tpot_seconds histograms, best of 3 paired trials,
# then the resilience tier (docs/serving.md "Failure modes &
# recovery"):
#  - the APEX_TPU_FAULTS env-knob matrix: every serving clause parses
#    from the env grammar and forces its degradation path
#    (serving_pool_exhausted / decode_step_exception /
#    prefill_chunk_exception / decode_nonfinite /
#    serving_snapshot_corrupt / weight_swap_mismatch), and
#  - the CHAOS smoke: 200 requests with decode_nonfinite injected AND
#    a real mid-run SIGTERM — the engine must quarantine ONLY the
#    poisoned sequence, drain with a committed serving snapshot (zero
#    admitted requests silently dropped), resume on a fresh engine
#    with bitwise-identical token streams, and land >= 90% of the
#    fault-free goodput,
# then the request plane (docs/observability.md "Request plane"):
#  - the TRACING smoke: a 200-request traced run must export a
#    perfetto trace with ONE TRACK PER REQUEST (prefill/prefill-chunk
#    + decode spans on every track), and armed tracing+SLO must stay
#    within the steady-state engine-step overhead budget vs disabled
#    (the `disabled is step` discipline), and
#  - the SLO smoke: a clean run stays alert-free (zero slo_alert
#    events, zero slo_violation bundles); a run with decode_nonfinite
#    injected AND an artificial decode stall must commit EXACTLY ONE
#    slo_violation flight bundle embedding the offending requests'
#    complete traces — and tools/serving_top.py must render both the
#    bundle and the live engine,
# then the fleet plane (docs/serving.md "Fleet"):
#  - the ROUTER chaos smoke: 300 requests across 3 engines behind
#    FleetRouter with engine_crash injected mid-load and one
#    add_engine replacement joining after the kill — goodput >= 0.95
#    of the no-kill run, fleet prefix hit-rate within 10 points of the
#    no-kill run, ZERO dropped or duplicated streams (every recovered
#    stream bitwise-identical), traces continuous across engines (same
#    trace id, resumed_from set), and tools/serving_top.py must render
#    the fleet introspection, and
#  - the DISAGG chaos soak: 300 requests through a 1-prefill/2-decode
#    fleet with engine_crash + engine_stall_ms + kv_transfer_corrupt
#    injected in ONE run — goodput >= 0.99 of the no-fault disagg run,
#    ZERO dropped or duplicated streams, every stream bitwise-identical
#    to the no-fault baseline, corrupt wire payloads absorbed by
#    verified re-send (handoff retries > 0, nothing corrupt installed),
#    and one continuous perfetto track per request across the handoff.
# Extra args pass through to pytest.
set -uo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu

rc=0

python -m pytest tests/test_serving.py tests/test_serving_resilience.py \
    tests/test_serving_hotpath.py tests/test_serving_request_plane.py \
    tests/test_fleet_router.py tests/test_fleet_disagg.py \
    "$@" -q -p no:cacheprovider || rc=1

echo "== 200-request smoke: continuous batching vs static batch =="
python - <<'PY' || rc=1
import time

import numpy as np
import jax
import jax.numpy as jnp

from apex_tpu import serving, telemetry
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.telemetry import compiled as _compiled

cfg = GPTConfig(vocab_size=512, max_seq_len=128, hidden_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2,
                dtype=jnp.float32, param_dtype=jnp.float32)
model = GPTModel(cfg)
rng = np.random.RandomState(0)
params = model.init(jax.random.PRNGKey(0),
                    jnp.asarray(rng.randint(0, 512, (1, 8)), jnp.int32))
MAX_BATCH = 8
cache = serving.KVCache.for_config(cfg, num_blocks=MAX_BATCH * 8,
                                   block_size=16)
step_fn = serving.make_decode_step(model, cache)

N = 200
def make_requests(tag):
    return [serving.Request(
        id=f"{tag}{i}",
        prompt=rng.randint(0, 512, (int(rng.randint(4, 25)),)),
        max_new_tokens=int(rng.randint(4, 41))) for i in range(N)]

reg = telemetry.MetricsRegistry()
sink = telemetry.InMemorySink()
reg.add_sink(sink)
tracker = _compiled.enable(registry=reg)
try:
    eng = serving.ContinuousBatcher(model, params, cache,
                                    max_batch=MAX_BATCH, step_fn=step_fn,
                                    min_seq_bucket=32, registry=reg)
    state = eng.warmup(cache.init_state())
    out = step_fn.prefill(        # the static loop's full-batch bucket
        params, state, np.zeros((MAX_BATCH, 32), np.int32),
        np.zeros((MAX_BATCH,), np.int32),
        np.zeros((MAX_BATCH, eng.min_width_bucket), np.int32))
    state = out.cache
    jax.block_until_ready(out.next_token)
    del state

    warm_decode_sigs = tracker.summary()["signatures"]["decode_step"]
    warm_events = [e["event"] for e in sink.events
                   if "decode_step" in str(e.get("fn", ""))]
    # warmup deliberately mints every bucketed program back-to-back —
    # storms there are by construction; the contract is the HOT LOOP
    n_warm_storms = sum(e["event"] == "recompile_storm"
                        for e in sink.events)

    # static baseline first (burst arrivals: the barrier cost is the
    # whole story), then continuous batching on the same workload.
    # BEST OF 3 trials each side: single-shot CPU wall time swings
    # +/-15% run to run (host noise only ever INFLATES wall), which
    # made a one-shot cb>st assert a coin flip — min wall per side is
    # the noise-robust estimator of what each scheduler can do
    st_tps = cb_tps = 0.0
    for trial in range(3):
        state = cache.init_state()
        t0 = time.perf_counter()
        state, st_res = serving.static_batch_generate(
            model, params, cache, state, make_requests(f"s{trial}"),
            batch_size=MAX_BATCH, step_fn=step_fn, min_seq_bucket=32)
        st_wall = time.perf_counter() - t0
        st_toks = sum(len(r.tokens) for r in st_res)
        del state

        state = cache.init_state()
        t0 = time.perf_counter()
        state, cb_res = serving.serve_loop(
            eng, state, make_requests(f"c{trial}"))
        cb_wall = time.perf_counter() - t0
        cb_toks = sum(len(r.tokens) for r in cb_res)
        del state
        assert len(cb_res) == N and len(st_res) == N
        st_tps = max(st_tps, st_toks / st_wall)
        cb_tps = max(cb_tps, cb_toks / cb_wall)
    ttft = sorted(r.ttft_s for r in cb_res)
    print(f"static : {st_toks} tokens, best of 3 = {st_tps:.0f} tok/s")
    print(f"contin.: {cb_toks} tokens, best of 3 = {cb_tps:.0f} tok/s "
          f"({cb_tps / st_tps:.2f}x)  ttft p50 "
          f"{ttft[len(ttft)//2]*1e3:.1f}ms")
    assert all(r.finish_reason == "length" for r in cb_res), \
        "continuous run had non-length finishes"
    assert cb_tps > st_tps, (
        f"continuous batching ({cb_tps:.0f} tok/s) must beat the "
        f"static-batch baseline ({st_tps:.0f} tok/s)")

    # compile plane: decode = exactly ONE bucketed program, and the
    # 200-request hot loop minted no new decode signatures (zero
    # recompile events after warmup — no storm)
    keys = step_fn.compile_keys()
    assert keys["decode_step"] == 1, keys
    sigs = tracker.summary()["signatures"]["decode_step"]
    assert sigs == warm_decode_sigs == 1, (sigs, warm_decode_sigs)
    hot_decode_events = [
        e["event"] for e in sink.events
        if "decode_step" in str(e.get("fn", ""))]
    assert hot_decode_events == warm_events, (
        f"decode recompile events after warmup: {hot_decode_events}")
    storms = [e for e in sink.events if e["event"] == "recompile_storm"]
    assert len(storms) == n_warm_storms, (
        f"recompile storm escalated in the hot loop: "
        f"{storms[n_warm_storms:]}")
    print(f"compile plane OK: {keys}, decode signatures={sigs}, "
          f"zero hot-loop recompiles, no storms")
finally:
    _compiled.disable()
PY

echo "== long-prompt smoke: chunked prefill must not starve in-flight decode =="
python - <<'PY' || rc=1
import time

import numpy as np
import jax
import jax.numpy as jnp

from apex_tpu import serving, telemetry
from apex_tpu.models.gpt import GPTConfig, GPTModel

cfg = GPTConfig(vocab_size=512, max_seq_len=512, hidden_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2,
                dtype=jnp.float32, param_dtype=jnp.float32)
model = GPTModel(cfg)
rng = np.random.RandomState(0)
params = model.init(jax.random.PRNGKey(0),
                    jnp.asarray(rng.randint(0, 512, (1, 8)), jnp.int32))
MAX_BATCH = 8
# pool fits several full long spans: 448 prompt + 8 new = 29 blocks
cache = serving.KVCache.for_config(cfg, num_blocks=MAX_BATCH * 31,
                                   block_size=16)
step_fn = serving.make_decode_step(model, cache)


def hist_p99(reg, name):
    """p99 from the recorded histogram (linear interpolation inside
    the bucket) — the smoke asserts from telemetry, not raw lists."""
    h = reg.histogram(name).series()[name]
    buckets, total = h["buckets"], h["count"]
    target = 0.99 * total
    prev_le, prev_cum = 0.0, 0
    for le, cum in buckets.items():
        ub = float("inf") if le == "+Inf" else float(le)
        if cum >= target:
            if ub == float("inf"):
                return prev_le
            frac = (target - prev_cum) / max(cum - prev_cum, 1)
            return prev_le + frac * (ub - prev_le)
        prev_le, prev_cum = ub, cum
    return prev_le


def workload(tag, with_long, gap):
    r = np.random.RandomState(3)
    reqs, arr = [], []
    t = 0.0
    for i in range(40):
        t += float(r.exponential(gap))
        reqs.append(serving.Request(
            id=f"{tag}{i}",
            prompt=r.randint(0, 512, (int(r.randint(4, 13)),)),
            max_new_tokens=int(r.randint(24, 41))))
        arr.append(t)
    if with_long:
        # max-seq-scale prompts (the CPU stand-in for 4k tokens)
        # arriving while decodes are in flight
        for j in range(4):
            reqs.append(serving.Request(
                id=f"{tag}L{j}",
                prompt=np.random.RandomState(7 + j).randint(
                    0, 512, (448,)),
                max_new_tokens=8))
            arr.append(arr[39] * (j + 1) / 5.0)
    return reqs, arr


def run(tag, with_long, gap):
    cache.reset_prefix_cache()
    reg = telemetry.MetricsRegistry()
    eng = serving.ContinuousBatcher(
        model, params, cache, step_fn=step_fn, max_batch=MAX_BATCH,
        min_seq_bucket=16, min_width_bucket=32, prefill_chunk=64,
        prefill_interval=2, registry=reg)
    state = eng.warmup(cache.init_state(), seq_buckets=[16],
                       chunk_buckets=[64])
    reqs, arr = workload(tag, with_long, gap)
    state, res = serving.serve_loop(eng, state, reqs, arrivals=arr)
    del state
    assert len(res) == len(reqs)
    assert all(r.finish_reason == "length" for r in res), tag
    p99 = hist_p99(reg, "serving_tpot_seconds") * 1e3
    h = reg.histogram("serving_tpot_seconds").series()[
        "serving_tpot_seconds"]
    mean = h["sum"] / max(h["count"], 1) * 1e3
    chunks = reg.counter("serving_prefill_chunks").value()
    print(f"  {tag}: mean TPOT {mean:.2f}ms / p99 {p99:.2f}ms "
          f"(histogram), {int(chunks)} prefill chunks")
    return mean, p99


# calibrate ~60% decode load so queueing happens, collapse doesn't
state = cache.init_state()
tab = np.zeros((MAX_BATCH, 32), np.int32)
out = step_fn.decode(params, state, np.zeros(MAX_BATCH, np.int32),
                     np.zeros(MAX_BATCH, np.int32), tab)
state = out.cache
jax.block_until_ready(out.next_token)
t0 = time.perf_counter()
for _ in range(10):
    out = step_fn.decode(params, state, np.zeros(MAX_BATCH, np.int32),
                         np.zeros(MAX_BATCH, np.int32), tab)
    state = out.cache
    jax.block_until_ready(out.next_token)
t_decode = (time.perf_counter() - t0) / 10
del state
gap = 32 / (0.6 * MAX_BATCH / t_decode)

# BEST OF 3 PAIRED trials: each trial runs decode-only then
# with-long-prompts back to back and scores their ratio, so slow
# patches of host time hit both sides of a pair and cancel — a
# single-shot (or unpaired best-of-N) ratio was a coin flip whenever
# the host drifted between the two runs. The 1.25x bound rides the
# MEAN (sum/count — quantization-free): the interpolated p99 steps in
# ~2x increments whenever the tail straddles a log-spaced bucket edge
# on this tiny CPU model. p99 keeps a loose 4x guard — past one
# adjacent-bucket step — so a real tail collapse still fails.
ratio, p99_ratio = float("inf"), float("inf")
for t in range(3):
    base_mean, base_p99 = run(f"decode-only/{t}", False, gap)
    conc_mean, conc_p99 = run(f"with-long-prompts/{t}", True, gap)
    ratio = min(ratio, conc_mean / base_mean)
    p99_ratio = min(p99_ratio, conc_p99 / base_p99)
print(f"long-prompt smoke: mean TPOT ratio {ratio:.3f}x (bound 1.25x),"
      f" p99 ratio {p99_ratio:.3f}x (guard 4x)")
assert ratio <= 1.25, (
    f"concurrent chunked prefill degraded decode mean TPOT {ratio:.3f}x"
    f" (> 1.25x) vs the decode-only run")
assert p99_ratio <= 4.0, (
    f"decode p99 TPOT collapsed {p99_ratio:.3f}x (> 4x) under "
    f"concurrent chunked prefill")
PY

echo "== env-knob matrix: every serving fault clause, via APEX_TPU_FAULTS =="
python - <<'PY' || rc=1
import os

import numpy as np
import jax
import jax.numpy as jnp

from apex_tpu import serving, telemetry
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.resilience import faults
from apex_tpu.serving import resilience as sresil

cfg = GPTConfig(vocab_size=64, max_seq_len=64, hidden_size=32,
                num_layers=2, num_heads=4, num_kv_heads=2,
                dtype=jnp.float32, param_dtype=jnp.float32)
model = GPTModel(cfg)
rng = np.random.RandomState(0)
params = model.init(jax.random.PRNGKey(0),
                    jnp.asarray(rng.randint(0, 64, (1, 8)), jnp.int32))
cache = serving.KVCache(2, 2, 8, num_blocks=16, block_size=4)
step_fn = serving.make_decode_step(model, cache)


def engine(**kw):
    reg = telemetry.MetricsRegistry()
    eng = serving.ContinuousBatcher(model, params, cache, step_fn=step_fn,
                                    max_batch=4, registry=reg, **kw)
    return eng, reg


def drill(knob, fn):
    os.environ[faults.ENV_KNOB] = knob
    try:
        fn()
    finally:
        os.environ.pop(faults.ENV_KNOB, None)
    print(f"  clause OK: {knob}")


def d_pool():
    eng, reg = engine()
    eng.submit(serving.Request(id=0, prompt=[1] * 4, max_new_tokens=2))
    state, rep = eng.step(cache.init_state())
    assert rep["admitted"] == [] and rep["queued"] == 1, rep
    while not eng.idle():
        state, _ = eng.step(state)
    assert eng.drain()[0].finish_reason == "length"


def d_exc():
    eng, reg = engine()
    eng.submit(serving.Request(id=0, prompt=[1] * 4, max_new_tokens=4))
    state, rep = eng.step(cache.init_state())
    assert rep["quarantined"] == [0], rep
    assert reg.counter("serving_quarantined").value(reason="exception") == 1


def d_chunk_exc():
    eng, reg = engine(prefill_chunk=4)
    eng.submit(serving.Request(id=0, prompt=[1] * 10, max_new_tokens=4))
    state, rep = eng.step(cache.init_state())
    assert rep["quarantined"] == [0], rep
    assert reg.counter("serving_quarantined").value(reason="exception") == 1
    assert cache.blocks_in_use == 0


def d_nonfinite():
    eng, reg = engine()
    for i in range(2):
        eng.submit(serving.Request(id=i, prompt=[1 + i] * 4,
                                   max_new_tokens=4))
    state, _ = eng.step(cache.init_state())
    state, rep = eng.step(state)           # decode_nonfinite=1, lane 1
    assert rep["quarantined"] == [1], rep
    assert rep["decoded"] == [0], rep
    while not eng.idle():
        state, _ = eng.step(state)
    res = {r.id: r for r in eng.drain()}
    assert res[0].finish_reason == "length"
    assert "nonfinite" in res[1].error
    assert reg.counter("serving_quarantined").value(reason="nonfinite") == 1


def d_snap(tmp="/tmp/apex_tpu_check_serving_snap"):
    import shutil
    shutil.rmtree(tmp, ignore_errors=True)
    eng, reg = engine()
    eng.submit(serving.Request(id=0, prompt=[2] * 4, max_new_tokens=2))
    path = sresil.save_snapshot(eng, tmp, step=0)
    ok, reason = sresil.validate_snapshot(path)
    assert not ok and "truncated" in reason, (ok, reason)
    assert sresil.latest_snapshot(tmp) is None
    shutil.rmtree(tmp, ignore_errors=True)


def d_swap():
    eng, reg = engine()
    try:
        serving.swap_weights(eng, params)
    except serving.WeightSwapError as e:
        assert e.mismatches
    else:
        raise AssertionError("injected weight_swap_mismatch not raised")
    assert reg.counter("serving_weight_swap_rejected").value() == 1


drill("serving_pool_exhausted=0", d_pool)
drill("decode_step_exception=0", d_exc)
drill("prefill_chunk_exception=0", d_chunk_exc)
drill("decode_nonfinite=1;decode_nonfinite_lane=1", d_nonfinite)
drill("serving_snapshot_corrupt=0", d_snap)
drill("weight_swap_mismatch=0", d_swap)
print("env-knob matrix OK: 6 serving clauses")
PY

echo "== chaos smoke: 200 requests, decode_nonfinite + mid-run SIGTERM =="
python - <<'PY' || rc=1
import os
import shutil
import tempfile

import numpy as np
import jax
import jax.numpy as jnp

from apex_tpu import serving
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.resilience import faults
from apex_tpu.resilience.guard import PreemptionHandler
from apex_tpu.serving import resilience as sresil

cfg = GPTConfig(vocab_size=512, max_seq_len=128, hidden_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2,
                dtype=jnp.float32, param_dtype=jnp.float32)
model = GPTModel(cfg)
rng = np.random.RandomState(0)
params = model.init(jax.random.PRNGKey(0),
                    jnp.asarray(rng.randint(0, 512, (1, 8)), jnp.int32))
MAX_BATCH = 8
N = 200


def make_requests():
    r = np.random.RandomState(7)
    return [serving.Request(
        id=i, prompt=r.randint(0, 512, (int(r.randint(4, 25)),)),
        max_new_tokens=int(r.randint(4, 41))) for i in range(N)]


def fresh():
    cache = serving.KVCache.for_config(cfg, num_blocks=MAX_BATCH * 8,
                                       block_size=16)
    return cache, serving.make_decode_step(model, cache)


# fault-free baseline: the bitwise reference and the goodput bar
cache, step_fn = fresh()
eng = serving.ContinuousBatcher(model, params, cache, step_fn=step_fn,
                                max_batch=MAX_BATCH, min_seq_bucket=32)
_, base = serving.serve_loop(eng, cache.init_state(), make_requests())
baseline = {r.id: r.tokens for r in base}
base_toks = sum(len(t) for t in baseline.values())
assert len(baseline) == N

# chaos run: NaN-poison one lane at step 40, REAL SIGTERM at step 80
snapdir = tempfile.mkdtemp(prefix="apex_tpu_chaos_")
os.environ[faults.ENV_KNOB] = "decode_nonfinite=40;sigterm=80"
handler = PreemptionHandler().install()
try:
    cache, step_fn = fresh()
    eng = serving.ContinuousBatcher(
        model, params, cache, step_fn=step_fn, max_batch=MAX_BATCH,
        min_seq_bucket=32, preemption=handler, snapshot_dir=snapdir)
    _, phase1 = serving.serve_loop(eng, cache.init_state(),
                                   make_requests())
finally:
    handler.uninstall()
    os.environ.pop(faults.ENV_KNOB, None)

assert eng.draining and eng.drained_snapshot, "engine did not drain"
assert handler.requested, "SIGTERM was not delivered/latched"
quarantined = [r for r in phase1 if r.finish_reason == "error"]
assert len(quarantined) == 1, (
    f"expected exactly the poisoned sequence quarantined, got "
    f"{[(r.id, r.error) for r in quarantined]}")
assert "nonfinite" in quarantined[0].error

# zero silently dropped: finished + snapshotted == admitted/submitted
snap = sresil.load_snapshot(eng.drained_snapshot)
snap_ids = {e["id"] for e in snap["requests"]}
done_ids = {r.id for r in phase1}
assert done_ids | snap_ids == set(range(N)), "requests vanished"
assert done_ids.isdisjoint(snap_ids)

# resume on a fresh engine; merged streams must be bitwise identical
resumed, prior = sresil.resume_requests(snap)
cache2, step2 = fresh()
eng2 = serving.ContinuousBatcher(model, params, cache2, step_fn=step2,
                                 max_batch=MAX_BATCH, min_seq_bucket=32)
_, phase2 = serving.serve_loop(eng2, cache2.init_state(), resumed)
merged = sresil.merge_results(phase2, prior)
got = {r.id: r.tokens for r in merged}
got.update({r.id: r.tokens for r in phase1
            if r.finish_reason != "error"})
bad_id = quarantined[0].id
mismatch = [i for i in got if i != bad_id and got[i] != baseline[i]]
assert not mismatch, f"non-bitwise replay for ids {mismatch[:5]}"
assert len(got) == N - 1 + (1 if bad_id in got else 0)

ok_toks = sum(len(t) for i, t in got.items() if i != bad_id)
goodput = ok_toks / base_toks
n_resumed_inflight = sum(1 for e in snap["requests"]
                         if e["state"] == "in_flight")
print(f"chaos OK: quarantined only id {bad_id}, snapshot carried "
      f"{len(snap_ids)} requests ({n_resumed_inflight} in-flight), "
      f"resume bitwise, goodput {goodput:.3f} of fault-free")
assert goodput >= 0.90, f"goodput {goodput:.3f} < 0.90"
shutil.rmtree(snapdir, ignore_errors=True)
PY

echo "== request plane smoke: tracing tracks + overhead, SLO burn-rate monitor =="
python - <<'PY' || rc=1
import json
import shutil
import tempfile
import time

import numpy as np
import jax
import jax.numpy as jnp

from apex_tpu import records, serving, telemetry
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.resilience import faults
from apex_tpu.telemetry import flight
from apex_tpu.telemetry.slo import SLOMonitor, SLOTarget

import sys, os
sys.path.insert(0, os.path.join(os.getcwd(), "tools"))
import serving_top

cfg = GPTConfig(vocab_size=512, max_seq_len=128, hidden_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2,
                dtype=jnp.float32, param_dtype=jnp.float32)
model = GPTModel(cfg)
rng = np.random.RandomState(0)
params = model.init(jax.random.PRNGKey(0),
                    jnp.asarray(rng.randint(0, 512, (1, 8)), jnp.int32))
MAX_BATCH = 8
cache = serving.KVCache.for_config(cfg, num_blocks=MAX_BATCH * 8,
                                   block_size=16)
step_fn = serving.make_decode_step(model, cache)
N = 200


def make_requests(tag, n=N):
    r = np.random.RandomState(7)
    return [serving.Request(
        id=f"{tag}{i}", prompt=r.randint(0, 512, (int(r.randint(4, 25)),)),
        max_new_tokens=int(r.randint(4, 41))) for i in range(n)]


# -- tracing smoke: 200 requests, one perfetto track per request ------------
tracer = serving.RequestTracer(keep=N)
eng = serving.ContinuousBatcher(model, params, cache, step_fn=step_fn,
                                max_batch=MAX_BATCH, min_seq_bucket=32,
                                prefill_chunk=16, tracer=tracer)
state = eng.warmup(cache.init_state())
state, res = serving.serve_loop(eng, state, make_requests("t"))
del state
assert len(res) == N
trace = tracer.export_trace()
tracks = [e for e in trace["traceEvents"] if e["ph"] == "M"]
assert len(tracks) == N, f"expected {N} request tracks, got {len(tracks)}"
by_tid = {}
for e in trace["traceEvents"]:
    if e["ph"] == "X":
        by_tid.setdefault(e["tid"], set()).add(e["name"])
for t in tracks:
    names = by_tid[t["tid"]]
    assert "decode" in names, f"track {t['args']['name']}: no decode span"
    assert "prefill" in names or any(n.startswith("prefill_chunk")
                                     for n in names), (
        f"track {t['args']['name']}: no prefill span")
print(f"tracing OK: {N} requests -> {len(tracks)} perfetto tracks, "
      f"{sum(len(v) for v in by_tid.values())} distinct span names total")

# -- overhead: armed tracing+SLO vs disabled on a steady decode loop --------
# the budget is 2% on a quiet machine; at ~2ms/step CI noise swamps
# that, so measurements INTERLEAVE (ABAB), take the min per config,
# and assert a noise-tolerant 25% ceiling while printing the real
# number — a request plane that actually cost its 18%-style bug
# (per-step label sorting, json mirrors) fails this loudly
def steady_step_ms(tracer, slo):
    eng = serving.ContinuousBatcher(
        model, params, cache, step_fn=step_fn, max_batch=MAX_BATCH,
        min_seq_bucket=32, tracer=tracer, slo=slo)
    state = cache.init_state()
    for i in range(MAX_BATCH):
        eng.submit(serving.Request(id=f"o{i}", prompt=[1 + i] * 8,
                                   max_new_tokens=100))
    state, _ = eng.step(state)          # admission + prefill
    t0 = time.perf_counter()
    steps = 0
    while not eng.idle():
        state, _ = eng.step(state)
        steps += 1
    ms = (time.perf_counter() - t0) / steps * 1e3
    del state
    eng.drain()
    return ms


armed_slo = SLOMonitor.serving_default(
    ttft_p99_s=60.0, tpot_p99_s=60.0, queue_depth=10000,
    registry=telemetry.MetricsRegistry())
base_ms, armed_ms = None, None
for _ in range(4):
    b = steady_step_ms(None, None)
    a = steady_step_ms(serving.RequestTracer(), armed_slo)
    base_ms = b if base_ms is None else min(base_ms, b)
    armed_ms = a if armed_ms is None else min(armed_ms, a)
ratio = armed_ms / base_ms
print(f"overhead: disabled {base_ms:.3f}ms/step, armed {armed_ms:.3f}"
      f"ms/step = {100 * (ratio - 1):+.2f}% (budget 2% quiet-machine, "
      f"CI bound 25%)")
assert ratio < 1.25, (
    f"armed request plane cost {100 * (ratio - 1):.1f}% per step")

# -- SLO smoke: clean run alert-free ----------------------------------------
records.RECORDS_DIR = tempfile.mkdtemp(prefix="apex_tpu_slo_smoke_")


def slo_monitor(reg, tpot_objective_s):
    # goodput budget 0.9: armed, but one quarantined lane must not
    # alert — the bundle count pins tpot_p99 as the only episode
    return SLOMonitor(
        [SLOTarget("tpot_p99", tpot_objective_s, budget=0.05),
         SLOTarget("goodput", 1.0, kind="ge", budget=0.9)],
        windows=((6.0, 2.0, 1.5),), min_samples=2, registry=reg)


def slo_bundles():
    out = []
    for name in sorted(os.listdir(records.RECORDS_DIR)):
        if name.startswith("flightrec"):
            with open(os.path.join(records.RECORDS_DIR, name)) as f:
                b = json.load(f)["payload"]
            if b["trigger"] == "slo_violation":
                out.append(b)
    return out


# calibrate a clean-decode tpot so the objective separates stall from noise
t0 = time.perf_counter()
state = cache.init_state()
tab = np.zeros((MAX_BATCH, 4), np.int32)
for _ in range(10):
    out = step_fn.decode(params, state, np.zeros(MAX_BATCH, np.int32),
                         np.zeros(MAX_BATCH, np.int32), tab)
    state = out.cache
    jax.block_until_ready(out.next_token)
t_decode = (time.perf_counter() - t0) / 10
del state
objective = max(t_decode * 8, 0.02)
stall_s = max(t_decode * 40, 0.05)

recorder = flight.enable(keep=20)
try:
    reg = telemetry.MetricsRegistry()
    sink = telemetry.InMemorySink()
    reg.add_sink(sink)
    tracer = serving.RequestTracer(keep=64)
    eng = serving.ContinuousBatcher(
        model, params, cache, step_fn=step_fn, max_batch=MAX_BATCH,
        min_seq_bucket=32, registry=reg, tracer=tracer,
        slo=slo_monitor(reg, objective))
    state = eng.warmup(cache.init_state())
    state, res = serving.serve_loop(eng, state, make_requests("c", 60))
    del state
    assert all(r.finish_reason == "length" for r in res)
    assert not [e for e in sink.events if e["event"] == "slo_alert"], \
        "clean run fired an SLO alert"
    assert not slo_bundles(), "clean run committed an slo_violation bundle"
    assert reg.counter("serving_slo_shed").value() == 0
    clean_top = serving_top.render_live(eng)
    assert "serving engine" in clean_top and "tpot_p99" in clean_top
    print(f"clean run OK: zero alerts, zero bundles "
          f"(objective {objective * 1e3:.1f}ms)")

    # -- faulted run: decode_nonfinite + an artificial decode stall ---------
    # every request is admitted BEFORE the stall bites (max_batch >=
    # N, burst arrivals), so the violation is ONE episode: the alert
    # latches once, everyone in flight finishes under it, and exactly
    # one slo_violation bundle commits — a shed/starve/recover cycle
    # would legitimately fire once per episode instead
    class StallingStep:
        """Proxy step_fn: decode calls past `after` sleep `stall_s` —
        the artificial stall that must burn the TPOT error budget."""
        def __init__(self, inner, after, stall_s):
            self.inner, self.after, self.stall_s = inner, after, stall_s
            self.calls = 0
        def prefill(self, *a, **kw):
            return self.inner.prefill(*a, **kw)
        def prefill_chunk(self, *a, **kw):
            return self.inner.prefill_chunk(*a, **kw)
        def decode(self, *a, **kw):
            self.calls += 1
            if self.calls > self.after:
                time.sleep(self.stall_s)
            return self.inner.decode(*a, **kw)

    reg = telemetry.MetricsRegistry()
    sink = telemetry.InMemorySink()
    reg.add_sink(sink)
    tracer = serving.RequestTracer(keep=64)
    eng = serving.ContinuousBatcher(
        model, params, cache, step_fn=StallingStep(step_fn, 8, stall_s),
        max_batch=16, min_seq_bucket=32, registry=reg,
        tracer=tracer, slo=slo_monitor(reg, objective))
    state = cache.init_state()
    with faults.inject(decode_nonfinite_steps=frozenset({10})):
        state, res = serving.serve_loop(eng, state,
                                        make_requests("f", 12))
    del state
    quarantined = [r for r in res if r.finish_reason == "error"]
    assert len(quarantined) == 1, "nonfinite lane not quarantined"
    alerts = [e for e in sink.events if e["event"] == "slo_alert"]
    assert alerts, "stalled run fired no SLO alert"
    bundles = slo_bundles()
    assert len(bundles) == 1, (
        f"expected exactly one slo_violation bundle, got {len(bundles)}")
    extra = bundles[0]["extra"]
    assert extra["slo"] == "tpot_p99" and extra["requests"]
    traces = {t["request_id"]: t for t in extra["traces"]}
    for rid in extra["requests"]:
        t = traces[str(rid)]
        assert t["outcome"] is not None and t["spans"], (
            f"offending trace {rid} incomplete")
    assert extra["introspect"]["slo"]["alerting"] == ["tpot_p99"]
    shed = reg.counter("serving_slo_shed").value()
    # serving_top renders the committed bundle file itself
    rendered = 0
    for name in sorted(os.listdir(records.RECORDS_DIR)):
        if not name.startswith("flightrec"):
            continue
        p = os.path.join(records.RECORDS_DIR, name)
        with open(p) as f:
            if json.load(f)["payload"]["trigger"] != "slo_violation":
                continue
        assert serving_top.main([p]) == 0
        rendered += 1
    assert rendered == 1, "serving_top could not render the slo bundle"
    print(f"slo smoke OK: 1 slo_violation bundle, "
          f"{len(extra['requests'])} offending traces embedded, "
          f"{int(shed)} admission passes shed, quarantine isolated "
          f"{quarantined[0].id}")
finally:
    flight.disable()
    shutil.rmtree(records.RECORDS_DIR, ignore_errors=True)
PY

echo "== router chaos smoke: 300 requests, 3 engines, engine_crash mid-load + replacement =="
python - <<'PY' || rc=1
import json
import os
import shutil
import tempfile

import numpy as np
import jax
import jax.numpy as jnp

from apex_tpu import serving, telemetry
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.resilience import faults

import sys
sys.path.insert(0, os.path.join(os.getcwd(), "tools"))
import serving_top

cfg = GPTConfig(vocab_size=512, max_seq_len=128, hidden_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2,
                dtype=jnp.float32, param_dtype=jnp.float32)
model = GPTModel(cfg)
rng = np.random.RandomState(0)
params = model.init(jax.random.PRNGKey(0),
                    jnp.asarray(rng.randint(0, 512, (1, 8)), jnp.int32))
MAX_BATCH = 8
N = 300
# one step_fn: geometry-bound, cache-instance-independent — every
# engine shares it, so programs compile once fleet-wide
_geom = serving.KVCache.for_config(cfg, num_blocks=MAX_BATCH * 8,
                                   block_size=16)
step_fn = serving.make_decode_step(model, _geom)

# half the workload shares one of three 32-token prefix families —
# the affinity placement's raw material for the hit-rate bar
FAMILIES = [list(np.random.RandomState(100 + f).randint(0, 512, (32,)))
            for f in range(3)]


def make_requests():
    r = np.random.RandomState(7)
    reqs = []
    for i in range(N):
        if r.rand() < 0.5:
            prompt = (FAMILIES[int(r.randint(3))]
                      + list(r.randint(0, 512, (int(r.randint(2, 9)),))))
        else:
            prompt = list(r.randint(0, 512, (int(r.randint(4, 25)),)))
        reqs.append(serving.Request(
            id=i, prompt=prompt, max_new_tokens=int(r.randint(4, 25))))
    return reqs


def engine(reg):
    cache = serving.KVCache.for_config(cfg, num_blocks=MAX_BATCH * 8,
                                       block_size=16)
    b = serving.ContinuousBatcher(model, params, cache, step_fn=step_fn,
                                  max_batch=MAX_BATCH, min_seq_bucket=32,
                                  registry=reg)
    return b, cache.init_state()


def hit_rate(reg):
    c = reg.counter("serving_prefix_cache_hits")
    h, m = c.value(outcome="hit"), c.value(outcome="miss")
    return h / max(h + m, 1)


def drive(router, reqs, *, replace_with=None):
    for r in reqs:
        router.submit(r)
    results, added = [], False
    while not router.idle():
        router.step()
        results.extend(router.merge_results())
        if replace_with is not None and router.failovers and not added:
            b, st = replace_with()
            router.add_engine("e3", b, st, warm=True)
            added = True
    results.extend(router.merge_results())
    return results


_snapdirs = []


def fleet(reg, tracer):
    _snapdirs.append(tempfile.mkdtemp(prefix="apex_tpu_fleet_"))
    router = serving.FleetRouter(
        registry=reg, tracer=tracer, stall_after_s=30.0,
        snapshot_dir=_snapdirs[-1])
    for i in range(3):
        b, st = engine(reg)
        router.add_engine(f"e{i}", b, st, warm=(i == 0))
    return router


# no-kill reference: the bitwise baseline, the goodput bar, and the
# prefix hit-rate bar
reg0 = telemetry.MetricsRegistry()
tr0 = serving.RequestTracer(keep=2 * N)
router0 = fleet(reg0, tr0)
base = drive(router0, make_requests())
baseline = {r.id: r.tokens for r in base}
assert len(baseline) == N
base_toks = sum(len(t) for t in baseline.values())
rate0 = hit_rate(reg0)

# kill run: engine 1 dies mid-load; a warmed replacement joins
reg1 = telemetry.MetricsRegistry()
tr1 = serving.RequestTracer(keep=2 * N)
router1 = fleet(reg1, tr1)
with faults.inject(engine_crash_steps=frozenset({12}),
                   engine_crash_engine=1):
    got_res = drive(router1, make_requests(),
                    replace_with=lambda: engine(reg1))

# zero dropped, zero duplicated
ids = [r.id for r in got_res]
assert sorted(ids) == list(range(N)), (
    f"dropped={set(range(N)) - set(ids)} dup={len(ids) - len(set(ids))}")
[fo] = router1.failovers
assert fo["engine"] == "e1" and fo["cause"] == "crash"
assert any(h.name == "e3" for h in router1.engines()), "no replacement"

# every stream bitwise-identical to the no-kill run
by_res = {r.id: r for r in got_res}
got = {i: r.tokens for i, r in by_res.items()}
mismatch = [i for i in got if got[i] != baseline[i]]
assert not mismatch, f"non-bitwise recovery for ids {mismatch[:5]}"
ok_toks = sum(len(r.tokens) for r in by_res.values()
              if r.finish_reason in ("length", "eos"))
goodput = ok_toks / base_toks
assert goodput >= 0.95, f"goodput {goodput:.3f} < 0.95"

# prefix hit-rate within 10 points of the no-kill run
rate1 = hit_rate(reg1)
assert abs(rate1 - rate0) <= 0.10, (
    f"kill-run prefix hit rate {rate1:.3f} vs no-kill {rate0:.3f}")

# traces continuous across engines: same trace id, resumed_from set,
# and ONE perfetto track per trace id
recovered = fo["recovered"]
assert recovered
dicts = tr1.trace_dicts(request_ids=recovered)
by_id = {}
for d in dicts:
    by_id.setdefault(d["request_id"], []).append(d)
for rid, segs in by_id.items():
    assert len({d["trace_id"] for d in segs}) == 1, rid
    assert any(d["outcome"] == "drained" for d in segs), rid
    assert any(d["resumed_from"] for d in segs), rid
trace = tr1.export_trace()
metas = [e for e in trace["traceEvents"] if e["ph"] == "M"]
assert len(metas) == N, f"expected {N} tracks, got {len(metas)}"
resumed_tracks = [m for m in metas
                  if "resumed_from=" in m["args"]["name"]]
assert len(resumed_tracks) == len(by_id)

# serving_top renders the fleet introspection
tmp = tempfile.mkdtemp(prefix="apex_tpu_fleet_top_")
p = os.path.join(tmp, "fleet.json")
with open(p, "w") as f:
    json.dump(router1.introspect(), f)
assert serving_top.main([p]) == 0
shutil.rmtree(tmp, ignore_errors=True)

for d in _snapdirs:
    shutil.rmtree(d, ignore_errors=True)
print(f"router chaos OK: killed e1 at step {fo['router_step']}, "
      f"recovered {len(recovered)} requests from {fo['source']} onto "
      f"survivors, replacement e3 joined warm; goodput {goodput:.3f}, "
      f"prefix hit-rate {rate1:.3f} vs {rate0:.3f} no-kill, "
      f"{len(metas)} continuous tracks")
PY

echo "== disagg chaos soak: 300 requests, 1 prefill + 2 decode, crash + stall + corrupt wire =="
python - <<'PY' || rc=1
import tempfile
import shutil

import numpy as np
import jax
import jax.numpy as jnp

from apex_tpu import serving, telemetry
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.resilience import faults

cfg = GPTConfig(vocab_size=512, max_seq_len=128, hidden_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2,
                dtype=jnp.float32, param_dtype=jnp.float32)
model = GPTModel(cfg)
rng = np.random.RandomState(0)
params = model.init(jax.random.PRNGKey(0),
                    jnp.asarray(rng.randint(0, 512, (1, 8)), jnp.int32))
MAX_BATCH = 8
N = 300
_geom = serving.KVCache.for_config(cfg, num_blocks=MAX_BATCH * 8,
                                   block_size=16)
step_fn = serving.make_decode_step(model, _geom)


def make_requests():
    r = np.random.RandomState(7)
    return [serving.Request(
        id=i, prompt=list(r.randint(0, 512, (int(r.randint(4, 25)),))),
        max_new_tokens=int(r.randint(4, 25))) for i in range(N)]


def fleet(reg, tracer):
    snapdir = tempfile.mkdtemp(prefix="apex_tpu_disagg_")
    router = serving.FleetRouter(
        registry=reg, tracer=tracer, stall_after_s=30.0,
        snapshot_dir=snapdir)
    for i, role in enumerate(["prefill", "decode", "decode"]):
        cache = serving.KVCache.for_config(cfg, num_blocks=MAX_BATCH * 8,
                                           block_size=16)
        b = serving.ContinuousBatcher(
            model, params, cache, step_fn=step_fn, max_batch=MAX_BATCH,
            min_seq_bucket=32, registry=reg)
        router.add_engine(f"{role[0]}{i}", b, cache.init_state(),
                          warm=(i == 0), role=role)
    return router, snapdir


def drive(router, reqs):
    for r in reqs:
        router.submit(r)
    results = []
    while not router.idle():
        router.step()
        results.extend(router.merge_results())
    results.extend(router.merge_results())
    return results


# no-fault disagg reference: the bitwise baseline and the goodput bar
reg0 = telemetry.MetricsRegistry()
router0, snap0 = fleet(reg0, serving.RequestTracer(keep=2 * N))
base = {r.id: r.tokens for r in drive(router0, make_requests())}
assert len(base) == N
assert router0.handoff_stats["ok"] > 0, "no handoffs in clean disagg run"
base_toks = sum(len(t) for t in base.values())

# combined-fault run, everything in ONE injection: a decode engine
# crashes mid-load, the other decode engine stalls for a stretch, and
# the first six handoff wire transfers arrive corrupt — the first
# handoff exhausts its retries (decodes locally on the prefill seat),
# the second absorbs two corrupt sends and lands on the third attempt
reg1 = telemetry.MetricsRegistry()
tr1 = serving.RequestTracer(keep=2 * N)
router1, snap1 = fleet(reg1, tr1)
with faults.inject(engine_crash_steps=frozenset({14}),
                   engine_crash_engine=2,
                   engine_stall_ms=40.0, engine_stall_engine=1,
                   engine_stall_at=frozenset({5, 6, 7}),
                   kv_transfer_corrupt=frozenset(range(6))):
    got_res = drive(router1, make_requests())

# zero dropped, zero duplicated
ids = [r.id for r in got_res]
assert sorted(ids) == list(range(N)), (
    f"dropped={set(range(N)) - set(ids)} dup={len(ids) - len(set(ids))}")
assert router1.failovers and router1.failovers[0]["cause"] == "crash"

# every stream bitwise-identical to the no-fault run: corrupt payloads
# were refused before install, crash victims re-prefilled exactly
got = {r.id: r.tokens for r in got_res}
mismatch = [i for i in got if got[i] != base[i]]
assert not mismatch, f"non-bitwise recovery for ids {mismatch[:5]}"
ok_toks = sum(len(r.tokens) for r in got_res
              if r.finish_reason in ("length", "eos"))
goodput = ok_toks / base_toks
assert goodput >= 0.99, f"goodput {goodput:.3f} < 0.99"

ho = router1.handoff_stats
assert ho["ok"] > 0, "no successful handoffs under fault load"
assert ho["retries"] > 0, "corrupt wire never re-sent"

# one continuous perfetto track per request — handoffs keep the live
# segment, crash replays continue the same trace id
trace = tr1.export_trace()
metas = [e for e in trace["traceEvents"] if e["ph"] == "M"]
assert len(metas) == N, f"expected {N} tracks, got {len(metas)}"
spans = [e for e in trace["traceEvents"]
         if e.get("ph") == "X" and e.get("name") == "handoff"]
assert spans, "no handoff spans in the exported trace"

shutil.rmtree(snap0, ignore_errors=True)
shutil.rmtree(snap1, ignore_errors=True)
print(f"disagg chaos OK: {ho['ok']} handoffs ({ho['retries']} re-sends, "
      f"{ho['failed']} fell back to local decode), crash on d2 replayed "
      f"{len(router1.failovers[0]['recovered'])} streams; goodput "
      f"{goodput:.3f}, {len(metas)} continuous tracks, all bitwise")
PY

if [ "$rc" -ne 0 ]; then
    echo "check_serving: FAILED" >&2
else
    echo "check_serving: OK"
fi
exit "$rc"

"""Elastic resharding drill (one invocation = one "host").

The acceptance scenario of ISSUE 7 / docs/resilience.md "Elastic
resume", run with REAL processes over a real ``jax.distributed``
cluster on CPU (the in-process ``LocalCollective`` simulation lives in
tests/test_elastic.py): kill an N-process run and resume on N−1 and
N+1 processes with the restored state bitwise-identical to an
uninterrupted run.

phase ``train``  — WORLD_SIZE=2: both hosts run a deterministic
    fused-step loop, elastic-checkpointing every 2 steps. The
    orchestrator (tools/check_resilience.sh) sets
    ``APEX_TPU_FAULTS=sigterm=5`` on host 0 ONLY: a real SIGTERM lands
    at step 5, ``should_stop`` spreads it to the fleet by agreement,
    and ``graceful_shutdown`` writes the priority final checkpoint —
    which, through the elastic manager, commits a range-sharded bundle
    WITH a layout manifest. Both hosts exit 0.

phase ``resume`` — ANY world (the orchestrator runs it once with 1
    process and once with 3): every host restores ``latest_valid()``
    through the :class:`ElasticRestorePlanner` (disk reads for its own
    assignment, peer fetches over the collective for the rest),
    proves the reassembled state against the layout fingerprint AND
    across replicas (``ConsistencyGuard.verify_restore``), replays to
    the end, and verifies the final master is bitwise identical to an
    uninterrupted golden run computed locally.

Usage (see check_resilience.sh for the orchestration)::

    MASTER_ADDR=127.0.0.1 MASTER_PORT=29871 WORLD_SIZE=<n> RANK=<r> \\
        python tools/elastic_drill.py {train|resume} <workdir>
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _cpu_mode import force_cpu  # noqa: E402

force_cpu()

import numpy as np  # noqa: E402

STEPS = 9
CKPT_EVERY = 2
SIGTERM_STEP = 5


def _make(opt):
    import jax.numpy as jnp

    r = np.random.RandomState(0)
    params = {"w": jnp.asarray(r.randn(64, 8), jnp.float32),
              "b": jnp.zeros((8,), jnp.float32)}
    return opt.init(params)


def _grad(space, i):
    import jax.numpy as jnp

    r = np.random.RandomState(1000 + i)
    return jnp.asarray(r.randn(space.total).astype(np.float32) * 0.01)


def _run(step, state, start, stop):
    for i in range(start, stop):
        state, _ = step(state, _grad(state.space, i))
    return state


def main() -> int:
    phase, workdir = sys.argv[1], sys.argv[2]

    from apex_tpu import records
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.optimizers.train_step import make_train_step
    from apex_tpu.parallel import multiproc
    from apex_tpu.resilience import (ConsistencyGuard, faults,
                                     graceful_shutdown,
                                     install_preemption_handler)

    records.RECORDS_DIR = os.path.join(workdir, "records")
    multiproc.initialize_distributed()          # env-driven, the ref way
    rank, world = multiproc.process_index(), multiproc.world_size()
    col = multiproc.process_collective()
    tag = f"[elastic_drill host {rank}/{world}]"

    opt = FusedAdam(lr=1e-2, impl="xla")
    step = make_train_step(opt)
    state = _make(opt)
    mgr = multiproc.elastic_checkpoint_manager(
        os.path.join(workdir, "ckpt"), keep=4, quorum_timeout=10.0)

    if phase == "train":
        assert world == 2, f"train phase expects WORLD_SIZE=2, got {world}"
        handler = install_preemption_handler()
        for i in range(STEPS):
            state, _ = step(state, _grad(state.space, i))
            if (i + 1) % CKPT_EVERY == 0:
                mgr.save(i + 1, state)
            faults.maybe_sigterm(i + 1)         # host 0's planned SIGTERM
            if handler.should_stop(col):        # agreement: all hosts stop
                graceful_shutdown(mgr, i + 1, state, collective=col,
                                  handler=handler)
                commit = mgr.read_commit(mgr.path_for(i + 1))
                assert commit.get("layout") is not None, (
                    f"{tag} graceful_shutdown committed WITHOUT a layout "
                    "manifest — the elastic wiring is broken")
                assert i + 1 == SIGTERM_STEP, (tag, i + 1)
                print(f"{tag} preempted at step {i + 1}, elastic bundle "
                      f"committed (world {commit['layout']['world']})",
                      flush=True)
                return 0
        raise SystemExit(f"{tag} survived a drill that SIGTERMs host 0")

    assert phase == "resume", phase
    path = mgr.latest_valid()
    assert path == mgr.path_for(SIGTERM_STEP), (
        f"{tag} resumed from {path}, wanted the elastic step-"
        f"{SIGTERM_STEP} bundle")
    restored = mgr.restore(path, template=state, collective=col)
    assert restored.step == SIGTERM_STEP
    guard = ConsistencyGuard(step, collective=col, fingerprint_every=2)
    guard.verify_restore(restored.opt_state,
                         baseline=restored.fingerprint)
    state = _run(step, restored.opt_state, restored.step, STEPS)

    golden = _run(step, _make(opt), 0, STEPS)
    if not np.array_equal(np.asarray(state.master),
                          np.asarray(golden.master)):
        raise SystemExit(f"{tag} resumed trajectory diverged from golden")
    fetched = sum(1 for s in restored.plan["ranges"]
                  if str(s.get("source", "")).startswith("peer_"))
    print(f"{tag} resumed saved-world {restored.plan['saved_world']} on "
          f"world {world} ({fetched} ranges fetched over the "
          "collective), replay bitwise-identical: OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

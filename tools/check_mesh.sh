#!/usr/bin/env bash
# Mesh-substrate smoke (CI / pre-merge, next to check_serving.sh and
# check_telemetry.sh): the mesh unit tier (tests/test_mesh.py +
# tests/test_mesh_planner.py + tests/test_mesh_pipeline.py), then four
# fresh-process drills on a FORCED 8-device CPU backend proving
# docs/mesh.md's contracts:
#  - PARITY: the same GPT train step, no mesh (single-device identity
#    plan) vs dp=8 GSPMD, produces loss curves identical to fp32
#    tolerance — the "one set of model code" guarantee,
#  - SERVING: a model-sharded checkpoint + kv_heads-sharded paged pool
#    through the real serving DecodeStep is TOKEN-IDENTICAL to the
#    unsharded engine on the same greedy stream, and
#  - COMPILE PLANE: with the PR-6 CompileTracker armed, the mesh train
#    step and the sharded decode loop each mint exactly their warmup
#    programs and hit ZERO hot-loop recompiles, and the train step
#    publishes its layouts (sharding_devices{fn="mesh_train_step"}),
#  - PIPELINE: a pp=2 interleaved-1F1B schedule on the pipe axis
#    matches the dp-only loss curve to fp32 tolerance, mints ONE
#    program with zero hot-loop recompiles, and publishes its
#    per-stage bubble_fraction gauges,
#  - EXPERT PARALLEL (docs/moe.md): a 4-expert MoE train step on a
#    dp=4 x ep/tp=2 mesh mints ONE program, hits ZERO hot-loop
#    recompiles, and the moe_expert_load gauges read back EQUAL to the
#    load measured from the step's own aux (and sum to tokens x top_k
#    x moe_layers — every routed copy accounted for).
# Extra args pass through to pytest.
set -uo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

rc=0

python -m pytest tests/test_mesh.py tests/test_mesh_planner.py \
    tests/test_mesh_pipeline.py \
    "$@" -q -p no:cacheprovider || rc=1

echo "== parity: no-mesh reference vs dp=8 GSPMD train step =="
python - <<'PY' || rc=1
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import mesh as gmesh
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.optimizers import FusedAdam

assert jax.device_count() == 8, jax.device_count()
cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=64,
                num_layers=2, num_heads=4,
                dtype=jnp.float32, param_dtype=jnp.float32)
rng = np.random.RandomState(0)
toks = jnp.asarray(rng.randint(0, 128, (8, 16)), jnp.int32)
labels = jnp.asarray(rng.randint(0, 128, (8, 16)), jnp.int32)


def run(n_steps=4):
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0), toks)
    if gmesh.mesh_initialized():
        plan = gmesh.plan_gpt(params)
    else:
        from jax.sharding import Mesh
        one = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1, 1),
                   gmesh.MESH_AXES)
        plan = gmesh.plan_gpt(params, mesh=one)
        assert plan.is_identity()
    step = gmesh.make_mesh_train_step(
        model, FusedAdam(lr=1e-3, impl="xla"), plan)
    state = step.init(params)
    losses = []
    for _ in range(n_steps):
        state, loss = step(state, toks, labels)
        losses.append(float(loss))
    return losses


ref = run()                                # identity plan, one device
gmesh.initialize_mesh()                    # pure dp=8 over all devices
try:
    assert gmesh.axis_sizes() == {"batch": 8, "pipe": 1, "model": 1}
    dp = run()
finally:
    gmesh.destroy_mesh()
np.testing.assert_allclose(dp, ref, rtol=2e-5, atol=2e-5)
assert dp[-1] < dp[0], "loss did not decrease"
print(f"parity OK: 4 steps, ref {ref[0]:.6f}->{ref[-1]:.6f}, "
      f"dp=8 matches to fp32 tolerance")
PY

echo "== serving: model-sharded decode vs unsharded, token identity =="
python - <<'PY' || rc=1
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import mesh as gmesh
from apex_tpu.mesh import annotate
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.serving import KVCache, make_decode_step

cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=64,
                num_layers=2, num_heads=4, num_kv_heads=2,
                dtype=jnp.float32, param_dtype=jnp.float32)
model = GPTModel(cfg)
prompt = jnp.asarray(
    np.random.RandomState(0).randint(0, 128, (2, 8)), jnp.int32)
params = model.init(jax.random.PRNGKey(0), prompt)


def stream(params, shard_state, n_decode=8):
    cache = KVCache.for_config(cfg, num_blocks=16, block_size=8)
    state = shard_state(cache.init_state())
    step = make_decode_step(model, cache)
    for i in range(2):
        cache.allocate(i, 8 + n_decode)
    tables = cache.table_array([0, 1], width=4)
    lengths = np.asarray([8, 8], np.int32)
    out = step.prefill(params, state, prompt, lengths, tables)
    state, tok = out.cache, out.next_token
    toks = [np.asarray(tok)]
    pos = lengths.copy()
    for _ in range(n_decode - 1):
        out = step.decode(params, state, np.asarray(tok), pos, tables)
        state, tok = out.cache, out.next_token
        pos = pos + 1
        toks.append(np.asarray(tok))
    return np.stack(toks)


ref = stream(params, lambda s: s)
gmesh.initialize_mesh(model=2)             # 4-way batch x 2-way model
try:
    sharded = stream(annotate.shard_params_for_serving(params),
                     annotate.shard_kv_pool)
finally:
    gmesh.destroy_mesh()
np.testing.assert_array_equal(sharded, ref)
print(f"serving OK: {ref.shape[0]} greedy decode steps x "
      f"{ref.shape[1]} sequences, model-sharded stream token-identical")
PY

echo "== compile plane: zero hot-loop recompiles, layouts published =="
python - <<'PY' || rc=1
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import mesh as gmesh, telemetry
from apex_tpu.mesh import annotate
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.serving import KVCache, make_decode_step
from apex_tpu.telemetry import compiled as tcompiled
from apex_tpu.telemetry import metrics as tmetrics

cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=64,
                num_layers=2, num_heads=4, num_kv_heads=2,
                dtype=jnp.float32, param_dtype=jnp.float32)
model = GPTModel(cfg)
rng = np.random.RandomState(0)
toks = jnp.asarray(rng.randint(0, 128, (8, 16)), jnp.int32)
labels = jnp.asarray(rng.randint(0, 128, (8, 16)), jnp.int32)

telemetry.reset()
gmesh.initialize_mesh()                    # dp=8
tracker = tcompiled.enable()
try:
    params = model.init(jax.random.PRNGKey(0), toks)
    step = gmesh.make_mesh_train_step(
        model, FusedAdam(lr=1e-3, impl="xla"), gmesh.plan_gpt(params))
    state = step.init(params)
    state, _ = step(state, toks, labels)   # warmup: the one compile
    for _ in range(10):                    # hot loop
        state, loss = step(state, toks, labels)
    del state

    gmesh.destroy_mesh()
    gmesh.initialize_mesh(model=2)         # sharded decode hot loop
    cache = KVCache.for_config(cfg, num_blocks=16, block_size=8)
    cstate = annotate.shard_kv_pool(cache.init_state())
    sparams = annotate.shard_params_for_serving(params)
    dstep = make_decode_step(model, cache)
    for i in range(2):
        cache.allocate(i, 8 + 12)
    tables = cache.table_array([0, 1], width=4)
    prompt = jnp.asarray(rng.randint(0, 128, (2, 8)), jnp.int32)
    lengths = np.asarray([8, 8], np.int32)
    out = dstep.prefill(sparams, cstate, prompt, lengths, tables)
    cstate, tok = out.cache, out.next_token
    pos = lengths.copy()
    out = dstep.decode(sparams, cstate, np.asarray(tok), pos, tables)
    cstate, tok = out.cache, out.next_token   # warmup: mints decode
    pos = pos + 1
    warm = dict(tracker.summary()["signatures"])
    for _ in range(10):                    # hot loop: no new programs
        out = dstep.decode(sparams, cstate, np.asarray(tok), pos, tables)
        cstate, tok = out.cache, out.next_token
        pos = pos + 1
    jax.block_until_ready(out.next_token)

    s = tracker.summary()
    assert s["signatures"].get("mesh_train_step") == 1, s["signatures"]
    assert s["signatures"].get("decode_step") == \
        warm.get("decode_step"), (s["signatures"], warm)
    assert s["recompiles"] == 0, f"hot-loop recompiles: {s}"
    assert s["storms"] == 0, s
    g = tmetrics.registry().snapshot()["gauges"]
    assert g.get('sharding_devices{fn="mesh_train_step"}') == 8, \
        {k: v for k, v in g.items() if "sharding" in k}
    detail = telemetry.snapshot_detail()
    assert "mesh_train_step" in (detail["sharding"] or {}), \
        detail.get("sharding")
    print(f"compile plane OK: signatures {s['signatures']}, "
          f"{s['compiles']} compiles all warmup, zero recompiles, "
          f"sharding_devices published for mesh_train_step")
finally:
    tcompiled.disable()
    gmesh.destroy_mesh()
    telemetry.reset()
PY

echo "== pipeline: pp=2 interleaved-1F1B parity, zero recompiles, bubble =="
python - <<'PY' || rc=1
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import mesh as gmesh, telemetry
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.telemetry import compiled as tcompiled
from apex_tpu.telemetry import metrics as tmetrics

assert jax.device_count() == 8, jax.device_count()
cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=64,
                num_layers=4, num_heads=4,
                dtype=jnp.float32, param_dtype=jnp.float32)
rng = np.random.RandomState(0)
toks = jnp.asarray(rng.randint(0, 128, (8, 16)), jnp.int32)
labels = jnp.asarray(rng.randint(0, 128, (8, 16)), jnp.int32)
model = GPTModel(cfg)


def run(pipe, n_steps=6):
    gmesh.initialize_mesh(pipe=pipe)
    try:
        params = model.init(jax.random.PRNGKey(0), toks)
        plan = gmesh.plan_gpt(params)
        opt = FusedAdam(lr=1e-3, impl="xla")
        if pipe > 1:
            spec = gmesh.PipelineSpec(
                schedule="interleaved_1f1b", num_stages=pipe,
                num_microbatches=4, num_model_chunks=2)
            step = gmesh.make_mesh_pipeline_train_step(
                model, opt, plan, spec)
        else:
            step = gmesh.make_mesh_train_step(model, opt, plan)
        state = step.init(params)
        losses = []
        for _ in range(n_steps):
            state, loss = step(state, toks, labels)
            losses.append(float(loss))
        return losses, step
    finally:
        gmesh.destroy_mesh()


ref, _ = run(1)                          # dp=8, the no-pipeline curve
telemetry.reset()
tracker = tcompiled.enable()
try:
    pipe, step = run(2)                  # dp=4 x pp=2, V=2 interleaved
    np.testing.assert_allclose(pipe, ref, rtol=2e-5, atol=2e-5)
    assert pipe[-1] < pipe[0], "loss did not decrease"

    s = tracker.summary()
    assert s["signatures"].get("mesh_pipeline_step") == 1, s["signatures"]
    assert s["recompiles"] == 0, f"hot-loop recompiles: {s}"

    bubble = step.last_bubble_fraction
    assert bubble == step.spec.bubble, (bubble, step.spec.bubble)
    g = tmetrics.registry().snapshot()["gauges"]
    for stage in range(2):
        key = ('pipeline_bubble_fraction{schedule="interleaved_1f1b"'
               f',stage="{stage}"}}')
        assert g.get(key) == bubble, {k: v for k, v in g.items()
                                      if "pipeline" in k}
    print(f"pipeline OK: 6 steps dp=4 x pp=2 interleaved-1F1B match "
          f"dp=8 to fp32 tolerance, 1 program, zero recompiles, "
          f"bubble_fraction={bubble:.4f} published per stage")
finally:
    tcompiled.disable()
    telemetry.reset()
PY

echo "== expert parallel: ep=2 MoE step, zero recompiles, gauge == load =="
python - <<'PY' || rc=1
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import mesh as gmesh, telemetry
from apex_tpu.models.gpt import GPTConfig
from apex_tpu.models.pretrain import (init_gpt_pretrain_params,
                                      make_gpt_pretrain_step)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.telemetry import compiled as tcompiled
from apex_tpu.telemetry import metrics as tmetrics

assert jax.device_count() == 8, jax.device_count()
cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=64,
                num_layers=2, num_heads=4,
                num_experts=4, moe_top_k=2,
                dtype=jnp.float32, param_dtype=jnp.float32)
rng = np.random.RandomState(0)
toks = jnp.asarray(rng.randint(0, 128, (8, 33)), jnp.int32)

telemetry.reset()
gmesh.initialize_mesh(model=2)             # dp=4 x ep/tp=2
tracker = tcompiled.enable()
try:
    params = init_gpt_pretrain_params(cfg, jax.random.PRNGKey(0))
    step, state = make_gpt_pretrain_step(
        cfg, FusedAdam(lr=1e-3, impl="xla"))(params)
    state, loss = step(state, toks[:, :-1], toks[:, 1:])  # warmup
    for _ in range(10):                    # hot loop
        state, loss = step(state, toks[:, :-1], toks[:, 1:])
    jax.block_until_ready(loss)
    assert np.isfinite(float(loss)), loss

    s = tracker.summary()
    assert s["signatures"].get("mesh_train_step") == 1, s["signatures"]
    assert s["recompiles"] == 0, f"hot-loop recompiles: {s}"

    # gauge == measured: the per-expert gauges must equal the load in
    # the step's own aux, and sum to every routed token copy
    load = np.asarray(step.last_aux["expert_load"], np.float64)
    g = tmetrics.registry().snapshot()["gauges"]
    for e in range(cfg.num_experts):
        key = f'moe_expert_load{{expert="{e}"}}'
        assert g.get(key) == float(load[e]), (key, g.get(key), load)
    n_copies = 8 * 32 * cfg.moe_top_k * cfg.num_layers
    assert load.sum() == n_copies, (load, n_copies)
    print(f"expert parallel OK: 11 steps dp=4 x ep=2, E=4 top_k=2, "
          f"1 program, zero recompiles, gauges == aux load "
          f"{load.tolist()} (sum {int(load.sum())} == {n_copies})")
finally:
    tcompiled.disable()
    gmesh.destroy_mesh()
    telemetry.reset()
PY

if [ "$rc" -ne 0 ]; then
    echo "check_mesh: FAILED" >&2
else
    echo "check_mesh: OK"
fi
exit "$rc"

# makes tools/ importable so pytest -p tools._marker_audit resolves

"""Two-process fleet-observability drill (one invocation = one "host").

The flight-recorder acceptance scenario of docs/observability.md run
with REAL processes over a real ``jax.distributed`` cluster on CPU
(pattern of tools/quorum_drill.py; the in-process threaded analog
lives in tests/test_flight.py): the orchestrator
(tools/check_observability.sh) injects a one-replica ``bit_flip``
fault on host 1 via ``APEX_TPU_FAULTS``, both hosts run a
guard-wrapped fused-step loop with the global timeline on and the
global flight recorder armed with a ``ProcessCollective``, and the
divergence boundary must:

1. detect the flip and repair it — with TWO hosts a 1v1 split has no
   majority, so the guard takes the no-quorum path: both hosts roll
   back to the last QUORUM checkpoint (the PR-3 contract), AND
2. dump a committed ``flightrec_*.json`` black box on EVERY host whose
   - ``trigger`` is ``replica_divergence``,
   - fleet snapshot sums both hosts' counters (pinned against this
     host's own registry snapshot in the same bundle),
   - straggler gauges are present (host 1 carries an injected per-step
     sleep so the spread is real),
   - perfetto trace slice parses as well-formed Chrome-trace JSON.

After the loop both hosts verify the repair end state is bitwise
identical across the fleet (an all-gather of the master buffer).

The drill then exercises the COMMS plane (docs/observability.md
"Comms & sharding plane"): the loop above ran with the comms tracer
armed, so every guard gather/agree and quorum barrier crossed the
instrumented ``KVStoreCollective`` — both hosts assert
``collective_ops{...impl="KVStoreCollective"}`` counters and
``collective:*`` timeline spans, warm the barrier EWMA and latch a
``collective_slow`` escalation through the documented
``collective_slow=<ms>`` fault clause, and merge both hosts'
timelines into ONE offset-corrected perfetto trace
(``fleet.export_fleet_trace``; host 0 commits it to
``<workdir>/merged_trace.json`` for the orchestrator to validate).

Usage (see check_observability.sh for the orchestration)::

    MASTER_ADDR=127.0.0.1 MASTER_PORT=29881 WORLD_SIZE=2 RANK=<r> \\
        [APEX_TPU_FAULTS="bit_flip=3;bit_flip_replica=1;bit_flip_leaf=0"] \\
        python tools/fleet_drill.py <workdir>
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _cpu_mode import force_cpu  # noqa: E402

force_cpu()

import numpy as np  # noqa: E402

STEPS = 8
FP_EVERY = 2
FLIP_STEP = 3          # strictly inside a fingerprint window
STRAGGLER_RANK = 1
STRAGGLE_S = 0.04    # big enough to dominate OS sleep granularity


def main() -> int:
    workdir = sys.argv[1]

    import jax.numpy as jnp

    from apex_tpu import records, telemetry
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.optimizers.train_step import make_train_step
    from apex_tpu.parallel import multiproc
    from apex_tpu.resilience import (CheckpointManager, ConsistencyGuard,
                                     faults)
    from apex_tpu.telemetry import comms, flight
    from apex_tpu.telemetry import fleet as fleet_mod

    multiproc.initialize_distributed()          # env-driven, the ref way
    rank, world = multiproc.process_index(), multiproc.world_size()
    assert world == 2, f"drill expects WORLD_SIZE=2, got {world}"
    tag = f"[fleet_drill host {rank}]"
    # per-host records dir: each host's black box is asserted against
    # its own registry, and O_EXCL claims never race across hosts
    records.RECORDS_DIR = os.path.join(workdir, f"records_{rank}")

    # arm the comms tracer BEFORE the collective is built, so
    # process_collective() hands back the instrumented wrapper
    comms.enable()
    col = multiproc.process_collective()
    assert col.n_replicas == 2
    assert isinstance(col, comms.InstrumentedCollective), type(col)
    assert col.impl_name() == "KVStoreCollective", col.impl_name()

    tl = telemetry.enable(capacity=512)
    mgr = CheckpointManager(os.path.join(workdir, "ckpt"), keep=4,
                            process_id=rank, n_processes=world,
                            quorum_timeout=30.0)
    recorder = flight.enable(collective=col, manager=mgr, keep=3,
                             last_steps=STEPS)

    opt = FusedAdam(lr=1e-2, impl="xla")
    step = make_train_step(opt, fingerprint_every=FP_EVERY, telemetry=tl)
    guard = ConsistencyGuard(step, collective=col, manager=mgr)

    r = np.random.RandomState(0)
    params = {"w": jnp.asarray(r.randn(64, 8), jnp.float32),
              "b": jnp.zeros((8,), jnp.float32)}
    st = opt.init(params)
    reg = telemetry.registry()

    for i in range(STEPS):
        reg.counter("drill_steps", "fused steps this host ran").inc()
        with tl.step_scope():
            with tl.phase("data_wait"):
                # a deterministic straggle on host 1 so the fleet
                # data_wait spread is real, not timing noise
                time.sleep(STRAGGLE_S if rank == STRAGGLER_RANK
                           else STRAGGLE_S / 8)
            st = st._replace(master=faults.flip_bits(
                st.master, i, replica=rank, space=st.space))
            r2 = np.random.RandomState(1000 + i)
            g = jnp.asarray(r2.randn(st.space.total).astype(np.float32)
                            * 0.01)
            st, _aux = guard(st, g)
        if (i + 1) % FP_EVERY == 0:
            mgr.save(i + 1, st)                 # quorum checkpoints

    # -- detection resolved by rollback (1v1: no majority to repair
    # from) and the fleet left the run bit-identical
    assert guard.rollbacks == 1, \
        f"{tag} expected 1 rollback, saw {guard.rollbacks}"
    masters = col.all_gather(np.asarray(st.master))
    if not np.array_equal(masters[0], masters[1]):
        raise SystemExit(f"{tag} post-repair masters differ across hosts")

    # -- the black box landed, committed, with the divergence trigger
    assert recorder.dumps >= 1, f"{tag} flight recorder never dumped"
    rec = records.latest_record("flightrec", require_backend=None)
    assert rec is not None, f"{tag} no flightrec record on disk"
    bundle = rec["payload"]
    assert bundle["trigger"] == "replica_divergence", bundle["trigger"]
    assert bundle["n_replicas"] == 2 and bundle["replica_id"] == rank
    assert bundle["faults"] == os.environ.get("APEX_TPU_FAULTS"), \
        f"{tag} bundle lost the faults config"
    # the bundle names the checkpoint a resume would use: at dump time
    # (inside the divergence boundary, before the rollback restore)
    # that is the step-2 quorum checkpoint
    lc = bundle["last_checkpoint"]
    assert lc and lc.get("step") == FLIP_STEP - 1, \
        f"{tag} bundle last_checkpoint {lc} != quorum step {FLIP_STEP - 1}"

    # fleet snapshot sums host counters: pinned against this host's own
    # registry snapshot carried in the SAME bundle (both hosts were at
    # the same loop point when their snapshots were gathered)
    fleet = bundle["fleet"]
    assert fleet is not None and fleet["n_hosts"] == 2, \
        f"{tag} bundle has no fleet snapshot"
    local_steps = bundle["telemetry"]["registry"]["counters"]["drill_steps"]
    fleet_steps = fleet["counters"]["drill_steps"]
    assert fleet_steps == world * local_steps, (
        f"{tag} fleet counter {fleet_steps} != {world} x local "
        f"{local_steps}")

    # straggler gauges present (published by the dump's aggregation
    # BEFORE the local snapshot was taken) and the spread is real
    gauges = bundle["telemetry"]["registry"]["gauges"]
    spread_keys = [k for k in gauges
                   if k.startswith("fleet_straggler_spread")]
    assert spread_keys, f"{tag} no fleet_straggler_spread gauge in bundle"
    strag = fleet["straggler"]["phases"]
    assert "step" in strag and strag["step"].get("spread") is not None, \
        f"{tag} fleet snapshot carries no step-phase spread"
    # the injected data_wait straggle shows in the fleet spread
    dw_spread = strag["data_wait"].get("spread")
    assert dw_spread is not None and dw_spread > 2.0, \
        f"{tag} injected data_wait straggle invisible (spread={dw_spread})"

    # the perfetto slice parses: well-formed Chrome-trace JSON
    trace = bundle["trace"]
    assert trace is not None, f"{tag} bundle has no trace slice"
    json.loads(json.dumps(trace))               # round-trips as JSON
    events = trace["traceEvents"]
    complete = [e for e in events if e.get("ph") == "X"]
    assert complete, f"{tag} trace slice has no complete events"
    for e in complete:
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
    assert any(e["name"] == "host_step" for e in complete)

    # state digests rode the boundary checksums
    assert bundle["state_digests"], f"{tag} no state digests retained"
    assert all("xor" in d and "step" in d for d in bundle["state_digests"])

    # -- comms plane: the loop's gathers/agrees/barriers all crossed
    # the instrumented collective on this host
    counters = reg.snapshot()["counters"]
    kv_ops = {k: v for k, v in counters.items()
              if k.startswith("collective_ops")
              and 'impl="KVStoreCollective"' in k}
    assert kv_ops and sum(kv_ops.values()) > 0, \
        f"{tag} no traced collective ops on this host"
    c_spans = [s for s in tl.spans() if s.category == "collective"]
    assert c_spans and all(s.name.startswith("collective:")
                           for s in c_spans), \
        f"{tag} no collective:* spans in the timeline"
    # the bundle carried the comms section (armed -> the full summary)
    assert bundle["comms"]["enabled"] is True, \
        f"{tag} flight bundle lost the comms section"
    assert any(r["op"] == "all_gather" and r["calls"] > 0
               for r in bundle["comms"]["ledger"]), \
        f"{tag} bundle ledger has no all_gather row"

    # escalation drill: warm the barrier EWMA past min_samples, then
    # inject a delay through the DOCUMENTED clause grammar on both
    # hosts — the next barrier must latch one collective_slow event
    tr = comms.get_tracer()
    for _ in range(tr.min_samples + 1):
        col.barrier()
    ewma = tr.op_stats()["barrier"]["ewma_ms"]
    delay_ms = max(60.0, tr.slow_factor * 2.0 * ewma)
    faults.install(faults.FaultInjector.from_env(
        f"collective_slow={delay_ms:.3f}"))
    try:
        col.barrier()
    finally:
        faults.install(None)        # back to the env-driven plan
    counters = reg.snapshot()["counters"]
    assert counters.get('collective_slow_total{op="barrier"}', 0) >= 1, \
        f"{tag} injected {delay_ms:.1f}ms barrier delay never escalated"
    assert counters.get('telemetry_events{event="collective_slow"}',
                        0) >= 1, f"{tag} no collective_slow event"
    assert any(e.get("event") == "collective_slow"
               for e in recorder.events), \
        f"{tag} collective_slow missing from the flight ring"

    # merged fleet trace: one offset-corrected perfetto timeline, both
    # hosts' spans + the escalation instants; host 0 commits the file
    trace_path = (os.path.join(workdir, "merged_trace.json")
                  if rank == 0 else None)
    merged = fleet_mod.export_fleet_trace(col, path=trace_path)
    evs = merged["traceEvents"]
    complete_pids = {e["pid"] for e in evs if e.get("ph") == "X"}
    assert complete_pids == {0, 1}, \
        f"{tag} merged trace pids {complete_pids} != both hosts"
    for r in (0, 1):
        c_evs = [e for e in evs if e.get("ph") == "X" and e["pid"] == r
                 and e["name"].startswith("collective:")]
        assert c_evs, \
            f"{tag} merged trace has no collective spans for host {r}"
        # every collective span carries its bytes/ms attribution
        assert all("payload_bytes" in e["args"] and e["dur"] >= 0
                   for e in c_evs), \
            f"{tag} host {r} collective spans lost bytes attribution"
        assert any(e.get("ph") == "M" and e["name"] == "process_name"
                   and e["pid"] == r for e in evs), \
            f"{tag} merged trace lacks host {r} process_name track"
    assert any(e.get("ph") == "i" and e["name"] == "collective_slow"
               for e in evs), \
        f"{tag} merged trace lacks the collective_slow instant"
    assert all(e["ts"] >= 0 for e in evs if "ts" in e), \
        f"{tag} merged trace has negative ts after normalization"
    n_hosts_merged = merged["otherData"]["n_hosts"]
    assert n_hosts_merged == 2, f"{tag} merged {n_hosts_merged} hosts"

    print(f"{tag} comms plane OK: {int(sum(kv_ops.values()))} traced "
          f"ops, {len(c_spans)} collective spans, clock spread="
          f"{merged['otherData']['clock_offset_spread_ms']}ms, "
          f"{len(evs)} merged trace events", flush=True)
    print(f"{tag} divergence black box OK: trigger="
          f"{bundle['trigger']}, fleet drill_steps={fleet_steps}, "
          f"straggler spread={strag['step']['spread']}, "
          f"{len(complete)} trace events", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

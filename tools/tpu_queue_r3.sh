#!/bin/bash
# Round-3 remaining hardware measurements, health-gated.
#
# Probes chip health (tools/tpu_health.py: raw streaming >= 300 GB/s)
# every INTERVAL seconds; when healthy, runs the queue ONCE, serially,
# re-checking health between stages — a stage that OOMs degrades the
# tunnel for every stage after it (docs/HARDWARE_NOTES.md), so the gate
# keeps poisoned numbers out of the logs.
set -u
cd "$(dirname "$0")/.."
INTERVAL=${INTERVAL:-480}
LOGDIR=${LOGDIR:-/tmp/tpu_queue_r3}
mkdir -p "$LOGDIR"
echo "logs -> $LOGDIR"

healthy() { timeout 240 python tools/tpu_health.py >>"$LOGDIR/health.log" 2>&1; }

run() {  # run <name> <timeout-s> <cmd...>
  local name=$1 to=$2; shift 2
  until healthy; do
    echo "chip unhealthy before $name $(date -u +%H:%M:%S); retry in ${INTERVAL}s"
    sleep "$INTERVAL"
  done
  echo "=== $name ($(date -u +%H:%M:%S)) ==="
  timeout "$to" "$@" >"$LOGDIR/$name.log" 2>&1
  local rc=$?
  tail -3 "$LOGDIR/$name.log"
  echo "--- $name rc=$rc"
}

run bisect    1800 python tools/tpu_bisect.py
run kprobe    1800 python tools/tpu_kprobe.py
run bench_resnet 2400 python bench.py resnet

echo "QUEUE DONE ($(date -u +%H:%M:%S)); logs in $LOGDIR"

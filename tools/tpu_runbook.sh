#!/bin/bash
# Serial TPU measurement sequence for the single-slot tunnel.
# Run when the chip answers (tools/../tpu probe or the watcher says so);
# every stage is strictly sequential — two TPU clients deadlock the
# tunnel (docs/HARDWARE_NOTES.md). Logs land in $LOGDIR.
set -u
cd "$(dirname "$0")/.."
LOGDIR=${LOGDIR:-/tmp/tpu_runbook_$(date +%H%M)}
mkdir -p "$LOGDIR"
echo "logs -> $LOGDIR"

run() {  # run <name> <timeout-s> <cmd...>
  local name=$1 to=$2; shift 2
  echo "=== $name ($(date +%H:%M:%S)) ==="
  timeout "$to" "$@" >"$LOGDIR/$name.log" 2>&1
  local rc=$?
  tail -3 "$LOGDIR/$name.log"
  echo "--- $name rc=$rc"
}

# kernel parity + Mosaic lowering across the whole op zoo first: if
# this fails nothing else is trustworthy
run smoke 1800 python tools/tpu_smoke.py

# bench modes, headline first (the driver-scored artifact)
export APEX_TPU_BENCH_PROBE_BUDGET=240
run bench_headline 2400 python bench.py
run bench_attn     1800 python bench.py attn
run bench_bert     2400 python bench.py bert
run bench_gpt      2400 python bench.py gpt
run bench_resnet   2400 python bench.py resnet
run bench_moe      1800 python bench.py moe

# tuning sweeps (feed winners back into kernel defaults)
run tune_attnbwd 2400 python tools/tpu_tune.py attnbwd
run tune_attn    2400 python tools/tpu_tune.py attn
run tune_opt     1800 python tools/tpu_tune.py opt
run tune_ln      1200 python tools/tpu_tune.py ln

echo "ALL DONE ($(date +%H:%M:%S)); logs in $LOGDIR"

"""Hardware smoke + parity sweep for every Pallas kernel.

The test suite runs kernels in interpreter mode on CPU (tests/conftest.py);
this tool runs the SAME kernel-vs-XLA comparisons compiled for the real
backend (TPU via Mosaic), mirroring how the reference validates its CUDA
exts on-device (ref: tests/L0/run_amp/test_multi_tensor_scale.py style).

    python tools/tpu_smoke.py          # parity PASS/FAIL per op + timing
    python tools/tpu_smoke.py --perf   # adds a perf table (pallas vs xla)

Exit code is the number of failing ops.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _time(fn, *args, iters=30, warmup=2, chain=20, feed=None):
    """Per-call device time of ``fn``: ``chain`` iterations run inside
    ONE jitted fori_loop, amortizing host dispatch — which costs ~ms
    through the axon tunnel and would otherwise dominate every sub-ms
    kernel. The outer loop then queues all calls and syncs once
    (block_until_ready alone is async through the tunnel; device_get of
    a scalar is the fence).

    ``feed(out, args) -> next_args`` threads each iteration's outputs
    into the next iteration's inputs. THIS IS LOAD-BEARING: without a
    real data dependence XLA hoists the loop-invariant ``fn(*args)``
    out of the fori_loop and the "chain" measures ONE call (verified
    empirically — an optimization_barrier on a discarded output does
    NOT stop it; a 1024x1024 matmul "sped up" 50x at chain=50). When
    no natural feed exists, every output leaf is folded into a probe
    scalar that scales the first input — a multiply by a runtime value
    the compiler cannot fold away.
    """
    import jax
    import jax.numpy as jnp

    def chained(*a):
        def body(_, c):
            carry, probe = c
            out = fn(*carry)
            if feed is not None:
                nxt = feed(out, carry)
                # leaves the feed threads forward stay live through the
                # loop carry; only the DEAD leaves (e.g. the loss in a
                # (loss, *grads) tuple) need folding into the probe —
                # summing live ones would add full-array reductions to
                # every timed iteration
                live = {id(l) for l in jax.tree.leaves(nxt)}
                dead = [l for l in jax.tree.leaves(out)
                        if id(l) not in live]
            else:
                # no natural output->input feed: every output leaf is
                # dead, and EVERY input must be made iteration-variant
                # (scaling only one would let XLA hoist sub-computations
                # that read the others) — scale by a runtime-dependent
                # 1.0 (isnan of a runtime value can't be constant-
                # folded). This costs a read+write of the inputs plus
                # the probe reductions per iteration; prefer a real
                # `feed` for bandwidth-sensitive measurements.
                dead = list(jax.tree.leaves(out))
                one = jnp.where(jnp.isnan(probe), probe, 1.0)
                nxt = jax.tree.map(
                    lambda l: (l * one.astype(l.dtype))
                    if hasattr(l, "dtype")
                    and jnp.issubdtype(l.dtype, jnp.floating) else l,
                    carry)
            probe = probe + sum(
                jnp.sum(l).astype(jnp.float32) for l in dead)
            return (tuple(nxt), probe)

        final, probe = jax.lax.fori_loop(0, chain, body,
                                         (a, jnp.float32(0.0)))
        # tap one element of each final carry leaf: the chain's last
        # outputs are consumed, so no iteration can be pruned, while the
        # host transfer stays scalar. (Element-0 slices can't reach back
        # through the loop: carries are full arrays every iteration.)
        return probe + sum(
            l.ravel()[0].astype(jnp.float32)
            for l in jax.tree.leaves(final))

    f = jax.jit(chained)
    for _ in range(warmup):
        jax.device_get(f(*args))
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = f(*args)
    jax.device_get(out)
    return (time.perf_counter() - t0) / (iters * chain)


def grad_feed(out, carry):
    """Natural feed for ``(loss, *grads)`` outputs: grads become the
    next iteration's inputs (shapes/dtypes match their primals)."""
    return out[1:]


def opt_feed(out, carry):
    """Natural feed for optimizer steps ``(p,m,v,g) -> (p2,m2,v2)``:
    thread the state, reuse the grad."""
    return (*out, carry[3])


def run(perf=False, kimpl="pallas", only=None):
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    results = []

    def check(name, fn, *args, tol=2e-2, grad_wrt=None):
        """Compare impl='pallas' vs impl='xla' outputs (and grads)."""
        import functools

        if only and only not in name:
            return
        try:
            f_p = jax.jit(functools.partial(fn, impl=kimpl))
            f_x = jax.jit(functools.partial(fn, impl="xla"))
            out_p = jax.tree.leaves(f_p(*args))
            out_x = jax.tree.leaves(f_x(*args))
            def rel_err(pairs):
                # max relative error, absolute below unit scale
                return max(
                    float(jnp.max(
                        jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))
                        / (1.0 + jnp.abs(b.astype(jnp.float32)))))
                    for a, b in zip(*pairs) if hasattr(a, "dtype"))

            err = rel_err((out_p, out_x))
            ok = err < tol
            if grad_wrt is not None and ok:
                def loss(impl_):
                    def g(*a):
                        out = fn(*a, impl=impl_)
                        lv = jax.tree.leaves(out)[0]
                        return jnp.sum(lv.astype(jnp.float32) ** 2)
                    return g
                gp = jax.tree.leaves(
                    jax.jit(jax.grad(loss(kimpl), argnums=grad_wrt))(*args))
                gx = jax.tree.leaves(
                    jax.jit(jax.grad(loss("xla"), argnums=grad_wrt))(*args))
                gerr = rel_err((gp, gx))
                ok = gerr < tol * 10
                err = max(err, gerr)
            t_p = t_x = None
            if perf and ok:
                t_p = _time(f_p, *args)
                t_x = _time(f_x, *args)
            results.append((name, ok, err, t_p, t_x))
            mark = "PASS" if ok else "FAIL"
            extra = ""
            if t_p is not None:
                extra = f"  pallas {t_p*1e3:8.3f} ms  xla {t_x*1e3:8.3f} ms  ({t_x/t_p:4.2f}x)"
            print(f"  [{mark}] {name:42s} max_err {err:.2e}{extra}")
        except Exception as e:  # noqa: BLE001 — report, keep sweeping
            results.append((name, False, float("inf"), None, None))
            msg = str(e).split("\n")[0][:140]
            print(f"  [FAIL] {name:42s} {type(e).__name__}: {msg}")

    print(f"backend: {jax.default_backend()}  devices: {len(jax.devices())}")
    if perf:
        print("# perf note: timings use _time's no-feed fallback, which "
              "adds fixed per-iteration probe traffic (one input "
              "read+write + output reductions). Common-mode for both "
              "impls, so the (Nx) column UNDERSTATES bandwidth-bound "
              "kernel speedups; tools/tpu_tune.py carries the "
              "feed-threaded numbers that count.")

    # ---- multi_tensor engine ops over a flat buffer -------------------
    from apex_tpu import multi_tensor as mt

    tree = {f"p{i}": jnp.asarray(rng.randn(*s).astype(np.float32))
            for i, s in enumerate([(1024, 1024), (4096,), (513, 255), (7,)])}
    space = mt.FlatSpace.create(tree)
    buf = space.pack(tree)
    gbuf = space.pack(jax.tree.map(
        lambda v: jnp.asarray(rng.randn(*v.shape).astype(np.float32)), tree))

    check("multi_tensor_scale", lambda b, impl: mt.multi_tensor_scale(b, 0.5, impl=impl), buf)
    check("multi_tensor_axpby", lambda b, g, impl: mt.multi_tensor_axpby(b, g, 2.0, -0.5, impl=impl), buf, gbuf)
    check("multi_tensor_l2norm", lambda b, impl: mt.multi_tensor_l2norm(b, impl=impl), buf)
    check("per_tensor_l2norm", lambda b, impl: mt.per_tensor_l2norm(b, space, impl=impl), buf, tol=1e-1)

    m = jnp.zeros_like(buf)
    v = jnp.zeros_like(buf)
    check("fused_adam_update",
          lambda p, g, m_, v_, impl: mt.fused_adam_update(
              p, m_, v_, g, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
              step=1, weight_decay=0.01, impl=impl),
          buf, gbuf, m, v, tol=1e-4)
    check("fused_sgd_update",
          lambda p, g, m_, impl: mt.fused_sgd_update(
              p, g, m_, lr=1e-2, momentum=0.9, weight_decay=1e-4,
              nesterov=True, impl=impl),
          buf, gbuf, m, tol=1e-4)
    check("fused_lamb_update",
          lambda p, g, m_, v_, impl: mt.fused_lamb_update(
              p, m_, v_, g, space, lr=1e-3, beta1=0.9, beta2=0.999,
              eps=1e-6, step=1, weight_decay=0.01, impl=impl),
          buf, gbuf, m, v, tol=1e-4)
    # segment-resident single-pass LAMB vs its two-stage reference on
    # the SAME segmented layout — the round-3 schedule that brings
    # LAMB to ~7 HBM accesses/element (multi_tensor/segmented.py).
    # New Mosaic surface: (seg, phase, chunk) grid with resident
    # phase-1 blocks, VMEM scratch persisting across grid steps, and
    # in-kernel one-hot dot_generals.
    from apex_tpu.multi_tensor.flat_buffer import segmented_space
    from apex_tpu.multi_tensor.segmented import (
        CHUNK as SEG_CHUNK,
        fused_lamb_segmented_update,
    )

    seg_tree = {
        "w0": jnp.asarray(rng.randn(600, 700).astype(np.float32)),
        "b0": jnp.asarray(rng.randn(700).astype(np.float32)),
        "w1": jnp.asarray(rng.randn(3 * SEG_CHUNK + 777)
                          .astype(np.float32)),   # large leaf
        "w2": jnp.asarray(rng.randn(512, 512).astype(np.float32)),
    }
    seg_space, seg_meta = segmented_space(seg_tree,
                                          seg_elems=2 * SEG_CHUNK)
    seg_pk = lambda t: seg_space.pack(t, dtype=jnp.float32)  # noqa: E731
    seg_p = seg_pk(seg_tree)
    seg_g = seg_pk(jax.tree.map(
        lambda x: jnp.asarray(
            np.random.RandomState(7).randn(*x.shape).astype(np.float32)
            * 1e-2), seg_tree))
    seg_m = jnp.zeros_like(seg_p)
    seg_v = jnp.zeros_like(seg_p)

    check("fused_lamb_segmented (one-pass)",
          lambda p, g, m_, v_, impl: fused_lamb_segmented_update(
              p, m_, v_, g, seg_space, seg_meta, lr=1e-3,
              weight_decay=0.01, use_nvlamb=True, step=1,
              max_grad_norm=0.0, impl=impl),
          seg_p, seg_g, seg_m, seg_v, tol=1e-4)

    # segmented + in-kernel SR: the counter-hash bits make the stream
    # impl-independent (tests/test_multi_tensor.py pins the interpret
    # schedule); this chip check proves the SAME schedule lowers
    # through Mosaic and stays unbiased: a tiny constant update must
    # round up/down ~50/50 and be unbiased in the mean
    name = "fused_lamb_segmented SR bf16 (in-kernel prng)"
    if kimpl == "pallas" and not (only and only not in name):
        try:
            sr_tree = {"w": jnp.full((2 * SEG_CHUNK,), 1.0, jnp.bfloat16)}
            sr_space, sr_meta = segmented_space(sr_tree,
                                                seg_elems=2 * SEG_CHUNK)
            sr_p = sr_space.pack(sr_tree, dtype=jnp.bfloat16)
            # grads sized so the LAMB update lands well below one bf16
            # ulp of 1.0 (2^-8): SR must preserve it in expectation
            sr_g = jnp.full((sr_space.total,), 1.0, jnp.float32)
            sr_m = jnp.zeros((sr_space.total,), jnp.float32)
            sr_v = jnp.zeros((sr_space.total,), jnp.float32)
            p2s, *_ = jax.jit(
                lambda p_, m_, v_, g_: fused_lamb_segmented_update(
                    p_, m_, v_, g_, sr_space, sr_meta, lr=2.0 ** -11,
                    weight_decay=0.0, use_nvlamb=False, step=1,
                    max_grad_norm=0.0, bias_correction=True,
                    impl=kimpl, sr_seed=11))(sr_p, sr_m, sr_v, sr_g)
            vals = np.asarray(jax.device_get(p2s), np.float32)
            # exact update: 1 - 2^-11 (trust ratio 1: wd=0, nvlamb off);
            # bf16 neighbors are 1.0 and 1-2^-8 -> frac_hi ~ 1-2^-3/...
            exp = 1.0 - 2.0 ** -11
            mean_err = abs(float(vals.mean()) - exp)
            uniq = np.unique(vals)
            ok = mean_err < 2e-4 and 1 < uniq.size <= 3
            results.append((name, ok, mean_err, None, None))
            print(f"  [{'PASS' if ok else 'FAIL'}] {name:42s} "
                  f"mean_err {mean_err:.2e} uniq {uniq.size}")
        except Exception as e:  # noqa: BLE001 — report, keep sweeping
            results.append((name, False, float("inf"), None, None))
            msg = str(e).split("\n")[0][:140]
            print(f"  [FAIL] {name:42s} {type(e).__name__}: {msg}")

    # the VMEM-budget variants must also lower: p-streaming (half the
    # scratch) and the bf16 u-stash
    check("fused_lamb_segmented stream_p",
          lambda p, g, m_, v_, impl: fused_lamb_segmented_update(
              p, m_, v_, g, seg_space, seg_meta, lr=1e-3,
              weight_decay=0.01, use_nvlamb=True, step=1,
              max_grad_norm=0.0, stash_p=False, impl=impl),
          seg_p, seg_g, seg_m, seg_v, tol=1e-4)
    check("fused_lamb_segmented bf16-u",
          lambda p, g, m_, v_, impl: fused_lamb_segmented_update(
              p, m_, v_, g, seg_space, seg_meta, lr=1e-3,
              weight_decay=0.01, use_nvlamb=True, step=1,
              max_grad_norm=0.0, stash_p=False, u_dtype=jnp.bfloat16,
              impl=impl),
          seg_p, seg_g, seg_m, seg_v, tol=1e-2)

    check("fused_novograd_update",
          lambda p, g, m_, impl: mt.fused_novograd_update(
              p, m_, jnp.zeros((space.num_leaves,), jnp.float32), g, space,
              lr=1e-3, beta1=0.95, beta2=0.98, eps=1e-8, step=1,
              weight_decay=0.01, impl=impl),
          buf, gbuf, m, tol=1e-4)
    check("fused_lars_update",
          lambda p, g, m_, impl: mt.fused_lars_update(
              p, m_, g, space, lr=1e-2, momentum=0.9, weight_decay=1e-4,
              trust_coefficient=0.02, impl=impl),
          buf, gbuf, m, tol=1e-4)

    # stochastic rounding: the in-kernel pltpu.prng path has NO CPU
    # lowering, so this statistics check (not parity — streams differ
    # from the xla emulation by design) is its only validation surface
    name = "stochastic_round bf16 (in-kernel prng)"
    if not (only and only not in name):
        try:
            nsr = 1 << 14
            psr = jnp.full((nsr,), 1.0, jnp.bfloat16)
            gsr = jnp.full((nsr,), 2.0 ** -9, jnp.float32)
            p2sr, _, _ = jax.jit(
                lambda p_, g_: mt.fused_sgd_update(
                    p_, jnp.zeros((nsr,), jnp.float32), g_, lr=1.0,
                    impl=kimpl, sr_seed=7))(psr, gsr)
            vals = np.asarray(jax.device_get(p2sr), np.float32)
            frac_hi = float((vals == 1.0).mean())
            mean_err = abs(float(vals.mean()) - (1.0 - 2.0 ** -9))
            ok = abs(frac_hi - 0.5) < 0.05 and mean_err < 2e-4
            results.append((name, ok, mean_err, None, None))
            print(f"  [{'PASS' if ok else 'FAIL'}] {name:42s} "
                  f"mean_err {mean_err:.2e} frac_hi {frac_hi:.3f}")
        except Exception as e:  # noqa: BLE001 — report, keep sweeping
            results.append((name, False, float("inf"), None, None))
            msg = str(e).split("\n")[0][:140]
            print(f"  [FAIL] {name:42s} {type(e).__name__}: {msg}")

    # ---- layer norm / rms norm ---------------------------------------
    from apex_tpu import ops

    x = jnp.asarray(rng.randn(8 * 512, 1024).astype(np.float32))
    w = jnp.asarray(rng.randn(1024).astype(np.float32))
    b = jnp.asarray(rng.randn(1024).astype(np.float32))
    check("fused_layer_norm (fwd+bwd)",
          lambda x_, w_, b_, impl: ops.fused_layer_norm(x_, w_, b_, impl=impl),
          x, w, b, grad_wrt=(0, 1, 2), tol=1e-3)
    check("fused_rms_norm (fwd+bwd)",
          lambda x_, w_, impl: ops.fused_rms_norm(x_, w_, impl=impl),
          x, w, grad_wrt=(0, 1), tol=1e-3)
    xb = x.astype(jnp.bfloat16)
    check("fused_layer_norm bf16",
          lambda x_, w_, b_, impl: ops.fused_layer_norm(x_, w_, b_, impl=impl),
          xb, w, b, tol=1e-1)

    # ---- softmax family ----------------------------------------------
    s4 = jnp.asarray(rng.randn(4, 8, 512, 512).astype(np.float32))
    mask = jnp.asarray(rng.rand(4, 1, 512, 512) < 0.2)
    check("scaled_softmax (fwd+bwd)",
          lambda a, impl: ops.scaled_softmax(a, 0.5, impl=impl),
          s4, grad_wrt=(0,), tol=1e-3)
    s3 = s4.reshape(32, 512, 512)  # (attn_batches, sq, sk)
    check("scaled_upper_triang_masked_softmax",
          lambda a, impl: ops.scaled_upper_triang_masked_softmax(a, 0.5, impl=impl),
          s3, grad_wrt=(0,), tol=1e-3)
    check("scaled_masked_softmax",
          lambda a, m_, impl: ops.scaled_masked_softmax(a, m_, 0.5, impl=impl),
          s4, mask, tol=1e-3)
    s4b = s4.astype(jnp.bfloat16)
    check("scaled_softmax bf16",
          lambda a, impl: ops.scaled_softmax(a, 0.5, impl=impl), s4b, tol=1e-2)

    # ---- rope ---------------------------------------------------------
    t = jnp.asarray(rng.randn(512, 4, 8, 128).astype(np.float32))
    freqs = jnp.asarray(rng.randn(512, 1, 1, 128).astype(np.float32))
    check("fused_apply_rotary_pos_emb (fwd+bwd)",
          lambda t_, f_, impl: ops.fused_apply_rotary_pos_emb(t_, f_, impl=impl),
          t, freqs, grad_wrt=(0,), tol=1e-3)

    # ---- xentropy -----------------------------------------------------
    logits = jnp.asarray(rng.randn(4096, 32000).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 32000, (4096,)), jnp.int32)
    check("softmax_cross_entropy_loss (fwd+bwd)",
          lambda lg, lb, impl: ops.softmax_cross_entropy_loss(
              lg, lb, smoothing=0.1, impl=impl),
          logits, labels, grad_wrt=(0,), tol=1e-3)

    # ---- flash attention ---------------------------------------------
    q = jnp.asarray(rng.randn(2, 8, 1024, 128).astype(np.float32) * 0.1)
    k = jnp.asarray(rng.randn(2, 8, 1024, 128).astype(np.float32) * 0.1)
    v_ = jnp.asarray(rng.randn(2, 8, 1024, 128).astype(np.float32) * 0.1)
    check("flash_attention causal (fwd+bwd)",
          lambda q_, k_, vv, impl: ops.flash_attention(
              q_, k_, vv, causal=True, impl=impl),
          q, k, v_, grad_wrt=(0, 1, 2), tol=2e-2)
    seg = jnp.asarray(
        np.repeat(np.arange(4), 256)[None, :].repeat(2, 0), jnp.int32)
    check("flash_attention packed-varlen",
          lambda q_, k_, vv, impl: ops.flash_attention(
              q_, k_, vv, segment_ids=seg, impl=impl),
          q, k, v_, tol=2e-2)
    qb, kb, vb = (a.astype(jnp.bfloat16) for a in (q, k, v_))
    check("flash_attention bf16 causal",
          lambda q_, k_, vv, impl: ops.flash_attention(
              q_, k_, vv, causal=True, impl=impl),
          qb, kb, vb, tol=5e-2)
    check("flash_attention sliding-window",
          lambda q_, k_, vv, impl: ops.flash_attention(
              q_, k_, vv, causal=True, window_size=256, impl=impl),
          q, k, v_, grad_wrt=(0, 1, 2), tol=2e-2)
    kg = jnp.asarray(rng.randn(2, 2, 1024, 128).astype(np.float32) * 0.1)
    vg = jnp.asarray(rng.randn(2, 2, 1024, 128).astype(np.float32) * 0.1)
    check("flash_attention GQA (8q/2kv, fwd+bwd)",
          lambda q_, k_, vv, impl: ops.flash_attention(
              q_, k_, vv, causal=True, impl=impl),
          q, kg, vg, grad_wrt=(0, 1, 2), tol=2e-2)
    check("flash_attention dropout (fwd+bwd)",
          lambda q_, k_, vv, impl: ops.flash_attention(
              q_, k_, vv, causal=True, dropout_rate=0.1,
              dropout_rng=jax.random.PRNGKey(0), impl=impl),
          q, k, v_, grad_wrt=(0, 1, 2), tol=2e-2)
    check("flash_attention return_lse (fwd+bwd)",
          lambda q_, k_, vv, impl: ops.flash_attention(
              q_, k_, vv, causal=True, return_lse=True, impl=impl),
          q, k, v_, grad_wrt=(0, 1, 2), tol=2e-2)
    pos = jnp.arange(1024, dtype=jnp.int32)
    check("flash_attention positions causal",
          lambda q_, k_, vv, impl: ops.flash_attention(
              q_, k_, vv, causal=True, q_positions=pos, kv_positions=pos,
              impl=impl),
          q, k, v_, grad_wrt=(0, 1, 2), tol=2e-2)

    # ---- ring attention chunk math (single-chunk degenerate ring:
    # flash with positions + lse-merge identity) --------------------
    def chunk_merge(q_, k_, vv, impl):
        o1, l1 = ops.flash_attention(
            q_, k_[:, :, :512], vv[:, :, :512], causal=True,
            q_positions=pos, kv_positions=pos[:512],
            return_lse=True, impl=impl)
        o2, l2 = ops.flash_attention(
            q_, k_[:, :, 512:], vv[:, :, 512:], causal=True,
            q_positions=pos, kv_positions=pos[512:],
            return_lse=True, impl=impl)
        lse = jnp.logaddexp(l1, l2)
        return (o1.astype(jnp.float32) * jnp.exp(l1 - lse)[..., None]
                + o2.astype(jnp.float32) * jnp.exp(l2 - lse)[..., None])

    check("flash chunked lse-merge == full", chunk_merge, q, k, v_,
          tol=2e-2)

    # separately-tuned backward blocks (new bwd_block_q/bwd_block_k
    # threading) must lower through Mosaic and match the XLA grads
    check("flash_attention bwd blocks 512x512",
          lambda q_, k_, vv, impl: ops.flash_attention(
              q_, k_, vv, causal=True, bwd_block_q=512, bwd_block_k=512,
              impl=impl),
          q, k, v_, grad_wrt=(0, 1, 2), tol=2e-2)

    # ring-attention recompute backward's per-chunk kernel path:
    # _flash_bwd_pallas evaluated against GLOBAL (lse, delta) statistics
    # must reproduce the XLA chunk-grads (context_parallel._chunk_grads)
    from apex_tpu.transformer.context_parallel import _chunk_grads

    def ring_chunk_grads(q_, k_, vv, impl):
        half = k_.shape[2] // 2
        out, lse = ops.flash_attention(
            q_, k_, vv, causal=True, return_lse=True, impl="xla")
        g = out.astype(jnp.float32) * 2.0     # d(sum out^2)/d out
        delta = jnp.sum(out.astype(jnp.float32) * g, axis=-1)
        return _chunk_grads(
            q_, k_[:, :, :half], vv[:, :, :half],
            pos, pos[:half], g, lse, delta, q_.shape[-1] ** -0.5, True,
            impl)

    check("ring chunk-grads (global lse) kernel", ring_chunk_grads,
          q, k, v_, tol=2e-2)

    n_fail = sum(1 for _, ok, *_ in results if not ok)
    print(f"\n{len(results) - n_fail}/{len(results)} ops pass on "
          f"{jax.default_backend()}")
    if jax.default_backend() == "tpu":
        from apex_tpu.records import write_record

        path = write_record("smoke", {
            "passed": len(results) - n_fail,
            "total": len(results),
            "impl": kimpl,
            "only": only,
            "perf": bool(perf),
            "results": [
                {"name": n, "ok": bool(ok),
                 "max_err": (float(err) if np.isfinite(err) else None),
                 **({"pallas_ms": round(tp * 1e3, 3),
                     "xla_ms": round(tx * 1e3, 3)}
                    if tp is not None and tx is not None else {})}
                for n, ok, err, tp, tx in results
            ],
        }, backend="tpu")
        if path:
            print(f"# record: {path}", file=sys.stderr)
    return n_fail


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--perf", action="store_true")
    ap.add_argument("--impl", default="pallas",
                    choices=("pallas", "interpret"),
                    help="kernel impl to compare against the XLA path "
                         "(interpret = CPU logic check)")
    ap.add_argument("--only", default=None,
                    help="substring filter: run only configs whose name "
                         "contains this (targeted hardware re-checks)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the plain CPU backend (strips the "
                         "tunnel plugin) — for interpret-mode logic "
                         "validation without touching the chip slot")
    args = ap.parse_args()
    if args.cpu:
        from _cpu_mode import force_cpu

        force_cpu()
    from apex_tpu.backend_guard import tpu_slot_lock

    # the tunnel serves ONE client; serialize against bench/tune runs
    # (the lock warns on stderr itself if it can't be acquired)
    with tpu_slot_lock():
        sys.exit(run(perf=args.perf, kimpl=args.impl, only=args.only))

"""Empirical probe: which BlockSpec shapes does Mosaic accept on this chip?

Run on real TPU to pin down the tiling rules the interpreter never checks.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def probe(name, arr_shape, block_shape, index_map, grid):
    x = jnp.asarray(np.random.RandomState(0).rand(*arr_shape), jnp.float32)

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    try:
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[pl.BlockSpec(block_shape, index_map)],
            out_specs=pl.BlockSpec(block_shape, index_map),
            out_shape=jax.ShapeDtypeStruct(arr_shape, x.dtype),
        )(x)
        ok = bool(jnp.allclose(out, x * 2.0))
        print(f"  [{'PASS' if ok else 'WRONG'}] {name}")
    except Exception as e:
        msg = str(e).split("\n")[0][:110]
        print(f"  [FAIL] {name}: {msg}")


print("backend:", jax.default_backend())
# 2D array, (1, 128) block — the lse/segment pattern
probe("2d (1,128) of (16,256)", (16, 256), (1, 128),
      lambda i, j: (i, j), (16, 2))
# 2D array, full trailing dim
probe("2d (1,256) of (16,256)", (16, 256), (1, 256),
      lambda i: (i, 0), (16,))
# 3D array, (1,1,128) block
probe("3d (1,1,128) of (4,4,256)", (4, 4, 256), (1, 1, 128),
      lambda i, j, k: (i, j, k), (4, 4, 2))
# 2D (8,128) block
probe("2d (8,128) of (16,256)", (16, 256), (8, 128),
      lambda i, j: (i, j), (2, 2))
# 2D (1,1) scalar block
probe("2d (1,1) of (16,16)", (16, 16), (1, 1),
      lambda i, j: (i, j), (16, 16))
# 2D (tile,1) partials
probe("2d (128,1) of (256,4)", (256, 4), (128, 1),
      lambda i, j: (i, j), (2, 4))
# 3D q-style (1, 128, 64) where 64 == full dim
probe("3d (1,128,64) of (8,256,64)", (8, 256, 64), (1, 128, 64),
      lambda i, j: (i, j, 0), (8, 2))
# 2D block (1, 512) == full row
probe("2d (1,512) of (8,512)", (8, 512), (1, 512),
      lambda i: (i, 0), (8,))
# grid-index-arithmetic index map (banded pattern)
probe("3d banded index map", (8, 256, 128), (1, 128, 128),
      lambda i, j: (i, jnp.minimum(j, 1), 0), (8, 2))
# row-stat layouts: trailing singleton vs middle singleton
probe("3d (1,128,1) of (8,256,1)", (8, 256, 1), (1, 128, 1),
      lambda i, j: (i, j, 0), (8, 2))
probe("3d (1,1,128) of (8,1,256)", (8, 1, 256), (1, 1, 128),
      lambda i, j: (i, 0, j), (8, 2))
# int32 segment-id style
probe("2d (8,128) int-ish of (64,256)", (64, 256), (8, 128),
      lambda i, j: (i, j), (8, 2))
# scalar output (1,1) of (1,1)
probe("2d (1,1) of (1,1)", (1, 1), (1, 1), lambda: (0, 0), ())
# (bq,128) scratch-like full-dim equality: (16,128) of (16,128)
probe("2d (16,128) of (16,128)", (16, 128), (16, 128), lambda: (0, 0), ())

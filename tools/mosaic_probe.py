"""Empirical probe: which BlockSpec shapes does Mosaic accept on this chip?

Run on real TPU to pin down the tiling rules the interpreter never checks.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def probe(name, arr_shape, block_shape, index_map, grid):
    x = jnp.asarray(np.random.RandomState(0).rand(*arr_shape), jnp.float32)

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    try:
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[pl.BlockSpec(block_shape, index_map)],
            out_specs=pl.BlockSpec(block_shape, index_map),
            out_shape=jax.ShapeDtypeStruct(arr_shape, x.dtype),
        )(x)
        ok = bool(jnp.allclose(out, x * 2.0))
        print(f"  [{'PASS' if ok else 'WRONG'}] {name}")
    except Exception as e:
        msg = str(e).split("\n")[0][:110]
        print(f"  [FAIL] {name}: {msg}")


print("backend:", jax.default_backend())
# 2D array, (1, 128) block — the lse/segment pattern
probe("2d (1,128) of (16,256)", (16, 256), (1, 128),
      lambda i, j: (i, j), (16, 2))
# 2D array, full trailing dim
probe("2d (1,256) of (16,256)", (16, 256), (1, 256),
      lambda i: (i, 0), (16,))
# 3D array, (1,1,128) block
probe("3d (1,1,128) of (4,4,256)", (4, 4, 256), (1, 1, 128),
      lambda i, j, k: (i, j, k), (4, 4, 2))
# 2D (8,128) block
probe("2d (8,128) of (16,256)", (16, 256), (8, 128),
      lambda i, j: (i, j), (2, 2))
# 2D (1,1) scalar block
probe("2d (1,1) of (16,16)", (16, 16), (1, 1),
      lambda i, j: (i, j), (16, 16))
# 2D (tile,1) partials
probe("2d (128,1) of (256,4)", (256, 4), (128, 1),
      lambda i, j: (i, j), (2, 4))
# 3D q-style (1, 128, 64) where 64 == full dim
probe("3d (1,128,64) of (8,256,64)", (8, 256, 64), (1, 128, 64),
      lambda i, j: (i, j, 0), (8, 2))
# 2D block (1, 512) == full row
probe("2d (1,512) of (8,512)", (8, 512), (1, 512),
      lambda i: (i, 0), (8,))
# grid-index-arithmetic index map (banded pattern)
probe("3d banded index map", (8, 256, 128), (1, 128, 128),
      lambda i, j: (i, jnp.minimum(j, 1), 0), (8, 2))
# row-stat layouts: trailing singleton vs middle singleton
probe("3d (1,128,1) of (8,256,1)", (8, 256, 1), (1, 128, 1),
      lambda i, j: (i, j, 0), (8, 2))
probe("3d (1,1,128) of (8,1,256)", (8, 1, 256), (1, 1, 128),
      lambda i, j: (i, 0, j), (8, 2))
# int32 segment-id style
probe("2d (8,128) int-ish of (64,256)", (64, 256), (8, 128),
      lambda i, j: (i, j), (8, 2))
# scalar output (1,1) of (1,1)
probe("2d (1,1) of (1,1)", (1, 1), (1, 1), lambda: (0, 0), ())
# (bq,128) scratch-like full-dim equality: (16,128) of (16,128)
probe("2d (16,128) of (16,128)", (16, 128), (16, 128), lambda: (0, 0), ())


def probe_subtile_gather():
    """The engine's big-tile per-tensor pattern (multi_tensor/engine.py):
    gather `sub` leaf ids from a scalar-prefetch SMEM array, stack the
    per-leaf values, broadcast each over its subtile's rows. Probing it
    in isolation triages a Mosaic rejection without compiling the whole
    LAMB kernel."""
    tile_rows, lanes, sub = 512, 128, 32
    n_tiles = 2
    ids = jnp.asarray(np.arange(n_tiles * sub) % 5, jnp.int32)
    vals = jnp.arange(5, dtype=jnp.float32) + 1.0
    x = jnp.ones((n_tiles * tile_rows, lanes), jnp.float32)

    def kernel(ids_ref, vals_ref, x_ref, o_ref):
        i = pl.program_id(0)
        tids = [ids_ref[i * sub + j] for j in range(sub)]
        v = jnp.stack([vals_ref[t] for t in tids])
        v = jnp.broadcast_to(
            v.reshape(sub, 1, 1), (sub, tile_rows // sub, 1)
        ).reshape(tile_rows, 1)
        o_ref[...] = x_ref[...] * v

    try:
        out = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(n_tiles,),
                in_specs=[pl.BlockSpec((tile_rows, lanes),
                                       lambda i, *_: (i, 0))],
                out_specs=pl.BlockSpec((tile_rows, lanes),
                                       lambda i, *_: (i, 0)),
            ),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        )(ids, vals, x)
        want = np.repeat(
            np.asarray(vals)[np.asarray(ids)], tile_rows // sub
        )[:, None] * np.ones((1, lanes), np.float32)
        ok = bool(jnp.allclose(out, want))
        print(f"  [{'PASS' if ok else 'WRONG'}] subtile gather "
              f"(stack of {sub} SMEM scalar reads + broadcast)")
    except Exception as e:  # noqa: BLE001
        msg = str(e).split("\n")[0][:110]
        print(f"  [FAIL] subtile gather: {msg}")


probe_subtile_gather()

"""Print a telemetry snapshot — Prometheus text or JSON — from the
live process registry, a flight-recorder bundle, or a bench record.

The scrape-shaped view of the observability layer
(docs/observability.md): the same ``to_prometheus_text()`` rendering a
node-exporter-style endpoint would serve, runnable against the black
box a dead run left behind::

    python tools/telemetry_dump.py                      # live registry
    python tools/telemetry_dump.py --format json
    python tools/telemetry_dump.py bench_records/flightrec_*.json
    python tools/telemetry_dump.py --format json some_headline.json

File arguments are resolved by shape, not by name: a flight-recorder
bundle (``payload.telemetry.registry``), a bench record
(``payload.detail.telemetry.registry``), a raw emitted bench line
(``detail.telemetry.registry``), or a bare registry snapshot all work.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def extract_registry_snapshot(obj):
    """The registry snapshot inside any of the JSON shapes this repo
    writes (flight bundle, bench record, emitted line, bare snapshot);
    None when the object holds no registry."""
    if not isinstance(obj, dict):
        return None
    # bare snapshot: has the three section keys
    if {"counters", "gauges", "histograms"} <= set(obj):
        return obj
    for path in (("payload", "telemetry", "registry"),
                 ("payload", "detail", "telemetry", "registry"),
                 ("detail", "telemetry", "registry"),
                 ("telemetry", "registry"),
                 ("registry",)):
        node = obj
        for key in path:
            node = node.get(key) if isinstance(node, dict) else None
            if node is None:
                break
        if isinstance(node, dict) and {"counters", "gauges",
                                       "histograms"} <= set(node):
            return node
    return None


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="print a telemetry snapshot (live registry, "
                    "flight-recorder bundle, or bench record)")
    parser.add_argument("path", nargs="?", default=None,
                        help="JSON file holding a registry snapshot "
                             "(flightrec bundle / bench record); "
                             "default: the live process registry")
    parser.add_argument("--format", choices=("prom", "json"),
                        default="prom",
                        help="prom = Prometheus text exposition "
                             "(default), json = the snapshot dict")
    args = parser.parse_args(argv)

    from apex_tpu.telemetry import metrics

    if args.path is None:
        snap = metrics.registry().snapshot()
        if args.format == "json":
            print(json.dumps(snap, indent=1, sort_keys=True))
        else:
            # live path: the registry renders with its HELP text
            sys.stdout.write(metrics.registry().to_prometheus_text())
        return 0

    try:
        with open(args.path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        print(f"error: cannot read {args.path}: {e}", file=sys.stderr)
        return 2
    snap = extract_registry_snapshot(obj)
    if snap is None:
        print(f"error: no telemetry registry snapshot found in "
              f"{args.path}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps(snap, indent=1, sort_keys=True))
    else:
        sys.stdout.write(metrics.prometheus_text_from_snapshot(snap))
    return 0


if __name__ == "__main__":
    sys.exit(main())

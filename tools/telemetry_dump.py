"""Print a telemetry snapshot — Prometheus text or JSON — from the
live process registry, a flight-recorder bundle, or a bench record.

The scrape-shaped view of the observability layer
(docs/observability.md): the same ``to_prometheus_text()`` rendering a
node-exporter-style endpoint would serve, runnable against the black
box a dead run left behind::

    python tools/telemetry_dump.py                      # live registry
    python tools/telemetry_dump.py --format json
    python tools/telemetry_dump.py bench_records/flightrec_*.json
    python tools/telemetry_dump.py --format json some_headline.json

File arguments are resolved by shape, not by name: a flight-recorder
bundle (``payload.telemetry.registry``), a bench record
(``payload.detail.telemetry.registry``), a raw emitted bench line
(``detail.telemetry.registry``), or a bare registry snapshot all work.

Both formats carry the COMPILE and DEVMEM planes
(docs/observability.md "compile & memory plane"): JSON output appends
``compile`` / ``devmem`` sections (the plane's series pulled out of
the snapshot, with the explicit ``devmem_reason`` when the backend has
no stats); Prometheus output renders every ``compile_*`` /
``recompile*`` / ``devmem_*`` series through the standard exposition
and appends one summary comment line per plane.

The SERVING plane rides the same way (docs/observability.md "Request
plane"): JSON output appends a ``serving`` section — every
``serving_*`` / ``slo_*`` series by kind, the computed prefix-cache
hit rate, and the SLO window summary the monitor mirrored into
``info["slo_window"]`` — and Prometheus output adds one serving
summary comment line (requests by outcome, tokens, queue depth, hit
rate, SLO alerts).

So does the COMMS plane (docs/observability.md "Comms & sharding
plane"): JSON output appends a ``comms`` section — every
``collective_*`` series plus the ``fleet_clock_offset*`` gauges, with
the per-op payload bandwidth recomputed from the bytes/ms histogram
sums (the measured column of the ledger) — and Prometheus output adds
one comms summary comment line (op count, slow events, per-op
bandwidth, clock spread). A snapshot whose comms plane never armed
reports the explicit ``comms_reason`` instead.

And the MESH plane (docs/mesh.md): JSON output appends a ``mesh``
section — the ``sharding_devices{fn=}`` / ``sharding_bytes_per_device``
gauges the GSPMD train step and mesh-armed serving decode publish,
the ``layout_plan_*`` gauges, and the planner's full ranked
``layout_plan`` info blob — and Prometheus output adds one mesh
summary comment line (chosen layout + publishing fns). A snapshot
with neither published layouts nor a plan reports ``mesh_reason``.

And the PIPELINE plane (docs/mesh.md "Pipeline schedules on the pipe
axis"): JSON output appends a ``pipeline`` section — the per-stage
``pipeline_bubble_fraction{schedule=,stage=}`` / ``pipeline_ticks``
gauges and the ``pipeline`` info blob the mesh pipeline train step
publishes (schedule, microbatches, per-stage activity windows, step
wall time) — and Prometheus output adds one pipeline summary comment
line. A snapshot where no schedule ran reports ``pipeline_reason``.

And the MOE plane (docs/moe.md): JSON output appends a ``moe``
section — every ``moe_*`` series plus the per-expert load histogram
folded out of the ``moe_expert_load{expert=}`` gauges — and
Prometheus output adds one MoE summary comment line (aux loss,
dropped tokens, imbalance EWMA, hottest expert). A snapshot from a
dense run reports ``moe_reason``.

And the GOODPUT plane (docs/observability.md "Run ledger & goodput"):
JSON output appends a ``goodput`` section — the
``goodput_seconds{cause=}`` attribution gauges, the fraction /
token-rate / ``mfu_ewma`` gauges, and the full ``info["goodput"]``
summary blob the ledger publishes (buckets, unattributed residual,
rework, restarts, anomaly episodes) — and Prometheus output adds one
goodput summary comment line. A snapshot whose ledger never armed
reports the explicit ``goodput_reason``; see
``tools/goodput_report.py`` for the human attribution table.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def extract_registry_snapshot(obj):
    """The registry snapshot inside any of the JSON shapes this repo
    writes (flight bundle, bench record, emitted line, bare snapshot);
    None when the object holds no registry."""
    if not isinstance(obj, dict):
        return None
    # bare snapshot: has the three section keys
    if {"counters", "gauges", "histograms"} <= set(obj):
        return obj
    for path in (("payload", "telemetry", "registry"),
                 ("payload", "detail", "telemetry", "registry"),
                 ("detail", "telemetry", "registry"),
                 ("telemetry", "registry"),
                 ("registry",)):
        node = obj
        for key in path:
            node = node.get(key) if isinstance(node, dict) else None
            if node is None:
                break
        if isinstance(node, dict) and {"counters", "gauges",
                                       "histograms"} <= set(node):
            return node
    return None


_COMPILE_PREFIXES = ("compile_", "compiled_signatures", "recompile")
_DEVMEM_PREFIX = "devmem_"


def _series_base(series: str) -> str:
    return series.split("{", 1)[0]


def _plane(snap, match):
    out = {}
    for kind in ("counters", "gauges", "histograms"):
        sel = {k: v for k, v in (snap.get(kind) or {}).items()
               if match(_series_base(k))}
        if sel:
            out[kind] = sel
    return out


def compile_section(snap):
    """The compile plane of a registry snapshot: every ``compile_*`` /
    ``compiled_signatures`` / ``recompile*`` series, by kind."""
    return _plane(snap, lambda base: base.startswith(_COMPILE_PREFIXES))


def devmem_section(snap):
    """The memory plane of a registry snapshot: every ``devmem_*``
    series — or, when no poll ever landed a gauge, the explicit
    ``devmem_reason`` (the mfu_reason contract: null sections always
    say why)."""
    out = _plane(snap, lambda base: base.startswith(_DEVMEM_PREFIX))
    if not out.get("gauges"):
        out["devmem_reason"] = ((snap.get("info") or {}).get(
            "devmem_reason") or "no device-memory poll in this snapshot")
    return out


_SERVING_PREFIXES = ("serving_", "slo_")


def _counter_total(snap, base):
    return sum(v for k, v in (snap.get("counters") or {}).items()
               if _series_base(k) == base)


def _counter_label(snap, base, **labels):
    # snapshot series names carry sorted labels (metrics._series_name)
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return (snap.get("counters") or {}).get(f"{base}{{{inner}}}", 0.0)


def serving_section(snap):
    """The serving plane of a registry snapshot: every ``serving_*``
    and ``slo_*`` series by kind, plus the computed prefix-cache hit
    rate and the SLO window summary the monitor mirrors into
    ``info["slo_window"]`` (absent = no monitor armed, reported
    explicitly — the null-with-reason contract)."""
    out = _plane(snap, lambda base: base.startswith(_SERVING_PREFIXES))
    hits = _counter_label(snap, "serving_prefix_cache_hits",
                          outcome="hit")
    misses = _counter_label(snap, "serving_prefix_cache_hits",
                            outcome="miss")
    out["prefix_cache_hit_rate"] = (
        round(hits / (hits + misses), 4) if hits + misses else None)
    slo = (snap.get("info") or {}).get("slo_window")
    if slo is not None:
        out["slo_window"] = slo
    else:
        out["slo_reason"] = "no SLO monitor armed in this snapshot"
    return out


_COMMS_PREFIXES = ("collective_", "fleet_clock_offset")


def _series_labels(series: str):
    """The label dict out of a snapshot series name
    (``base{k="v",...}`` — metrics._series_name sorts and quotes)."""
    if "{" not in series:
        return {}
    inner = series.split("{", 1)[1].rstrip("}")
    out = {}
    for part in inner.split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            out[k] = v.strip('"')
    return out


def comms_section(snap):
    """The comms plane of a registry snapshot: every ``collective_*``
    series plus the ``fleet_clock_offset*`` gauges, with the per-op
    payload bandwidth recomputed from the bytes/ms histogram sums —
    the measured column of the tracer's ledger, recoverable from any
    scrape. A snapshot whose comms plane never armed gets the explicit
    ``comms_reason`` (the null-with-reason contract)."""
    out = _plane(snap, lambda base: base.startswith(_COMMS_PREFIXES))
    hists = snap.get("histograms") or {}
    bw = {}
    for series, h in hists.items():
        if _series_base(series) != "collective_bytes":
            continue
        op = _series_labels(series).get("op")
        if not op:
            continue
        ms = (hists.get(f'collective_ms{{op="{op}"}}') or {}).get(
            "sum", 0.0)
        payload = (h or {}).get("sum", 0.0)
        bw[op] = (round(payload / (ms / 1e3) / 1e6, 4)
                  if ms and payload else None)
    if any(out.get(k) for k in ("counters", "gauges", "histograms")):
        out["collective_bandwidth_mbps"] = bw or None
    else:
        out["comms_reason"] = (
            "no collective tracing in this snapshot "
            "(telemetry.comms.enable() / APEX_TPU_COMMS=1)")
    return out


_MESH_PREFIXES = ("sharding_", "layout_plan")


def mesh_section(snap):
    """The mesh/sharding plane of a registry snapshot (docs/mesh.md):
    the ``sharding_devices{fn=}`` / ``sharding_bytes_per_device``
    gauges next to the ``layout_plan_*`` gauges and the planner's
    ranked ``layout_plan`` info blob — what the compiler DID beside
    what the planner ASKED for. Null-with-``mesh_reason`` when the
    snapshot holds neither."""
    out = _plane(snap, lambda base: base.startswith(_MESH_PREFIXES))
    plan = (snap.get("info") or {}).get("layout_plan")
    if plan is not None:
        out["layout_plan"] = plan
    if not out.get("gauges") and plan is None:
        out["mesh_reason"] = (
            "no sharding layouts or layout plan published in this "
            "snapshot (mesh.publish_plan / publish_shardings)")
    return out


_PIPELINE_PREFIX = "pipeline_"


def pipeline_section(snap):
    """The pipeline plane of a registry snapshot (docs/mesh.md
    "Pipeline schedules on the pipe axis"): the per-stage
    ``pipeline_bubble_fraction{schedule=,stage=}`` / ``pipeline_ticks``
    gauges next to the ``pipeline`` info blob (the PipelineSpec plus
    the last step's wall time and per-stage activity windows) the mesh
    pipeline train step publishes each step.
    Null-with-``pipeline_reason`` when no schedule ran."""
    out = _plane(snap, lambda base: base.startswith(_PIPELINE_PREFIX))
    blob = (snap.get("info") or {}).get("pipeline")
    if blob is not None:
        out["pipeline"] = blob
    if not out.get("gauges") and blob is None:
        out["pipeline_reason"] = (
            "no pipeline schedule ran in this snapshot "
            "(mesh.make_mesh_pipeline_train_step)")
    return out


_MOE_PREFIX = "moe_"


def moe_section(snap):
    """The MoE workload plane of a registry snapshot (docs/moe.md):
    every ``moe_*`` series — the ``moe_aux_loss`` /
    ``moe_dropped_tokens`` / ``moe_imbalance_ratio`` gauges and the
    drop counter — plus ``expert_load``, the per-expert histogram
    folded out of the ``moe_expert_load{expert=}`` gauges.
    Null-with-``moe_reason`` when the snapshot is from a dense run
    (the mfu_reason contract)."""
    out = _plane(snap, lambda base: base.startswith(_MOE_PREFIX))
    load = {}
    for series, v in (out.get("gauges") or {}).items():
        if _series_base(series) == "moe_expert_load":
            expert = _series_labels(series).get("expert")
            if expert is not None:
                load[expert] = v
    if load:
        out["expert_load"] = {e: load[e]
                              for e in sorted(load, key=int)}
    if not any(out.get(k) for k in ("counters", "gauges", "histograms")):
        out["moe_reason"] = (
            "no MoE gauges in this snapshot (dense run, or "
            "telemetry.moe.publish_moe_step never called)")
    return out


_GOODPUT_PREFIXES = ("goodput_", "tokens_trained", "effective_tokens",
                     "mfu_ewma")


def goodput_section(snap):
    """The run-ledger plane of a registry snapshot
    (docs/observability.md "Run ledger & goodput"): the
    ``goodput_seconds{cause=}`` attribution gauges next to the
    ``goodput_fraction`` / ``tokens_trained_total`` /
    ``effective_tokens_per_sec`` / ``mfu_ewma`` gauges, plus the full
    ``info["goodput"]`` summary blob the ledger publishes (buckets,
    unattributed residual, rework, restarts, anomaly episodes).
    Null-with-``goodput_reason`` when the ledger never armed in the
    process that wrote the snapshot."""
    out = _plane(snap, lambda base: base.startswith(_GOODPUT_PREFIXES))
    blob = (snap.get("info") or {}).get("goodput")
    if blob is not None:
        out["goodput"] = blob
    if not out.get("gauges") and blob is None:
        out["goodput_reason"] = (
            "goodput ledger not armed in this snapshot "
            "(telemetry.goodput.enable)")
    return out


def plane_comments(snap) -> str:
    """One summary comment line per plane, appended to the Prometheus
    text (comments are legal exposition; the series themselves render
    through the standard format above them)."""
    comp = compile_section(snap)
    counters = comp.get("counters", {})

    def _total(prefix):
        return sum(v for k, v in counters.items()
                   if _series_base(k) == prefix)

    lines = [f"# compile plane: {int(_total('compile_count'))} "
             f"compiles, {int(_total('recompile_count'))} recompiles, "
             f"{int(_total('recompile_storms'))} storms"]
    dm = devmem_section(snap)
    gauges = dm.get("gauges", {})
    if gauges:
        in_use = gauges.get("devmem_bytes_in_use")
        mark = gauges.get("devmem_watermark_bytes")
        lines.append(f"# devmem: bytes_in_use={in_use} "
                     f"watermark={mark}")
    else:
        lines.append(f"# devmem: unavailable ({dm['devmem_reason']})")
    sv = serving_section(snap)
    if sv.get("counters") or sv.get("gauges") or sv.get("histograms"):
        n_req = int(_counter_total(snap, "serving_requests"))
        n_tok = int(_counter_total(snap, "serving_tokens"))
        depth = (sv.get("gauges") or {}).get("serving_queue_depth")
        rate = sv.get("prefix_cache_hit_rate")
        slo = sv.get("slo_window")
        alerts = (slo or {}).get("alerts_total")
        alerting = ",".join((slo or {}).get("alerting") or []) or "none"
        lines.append(
            f"# serving: {n_req} requests, {n_tok} tokens, "
            f"queue_depth={depth} prefix_hit_rate={rate} "
            + (f"slo_alerts={alerts} alerting={alerting}"
               if slo is not None else f"slo={sv.get('slo_reason')}"))
    cm = comms_section(snap)
    if "comms_reason" in cm:
        lines.append(f"# comms: unavailable ({cm['comms_reason']})")
    else:
        n_ops = int(_counter_total(snap, "collective_ops"))
        slow = int(_counter_total(snap, "collective_slow_total"))
        bw = cm.get("collective_bandwidth_mbps") or {}
        bw_s = " ".join(f"{op}={v}MB/s"
                        for op, v in sorted(bw.items())
                        if v is not None) or "n/a"
        spread = (cm.get("gauges") or {}).get(
            "fleet_clock_offset_spread_ms")
        lines.append(f"# comms: {n_ops} collective ops, "
                     f"slow_events={slow} bandwidth[{bw_s}] "
                     f"clock_spread_ms={spread}")
    ms = mesh_section(snap)
    if "mesh_reason" in ms:
        lines.append(f"# mesh: unavailable ({ms['mesh_reason']})")
    else:
        best = (ms.get("layout_plan") or {}).get("best")
        fns = sorted({_series_labels(k).get("fn")
                      for k in (ms.get("gauges") or {})
                      if _series_base(k) == "sharding_devices"}
                     - {None})
        lines.append(f"# mesh: plan={best} "
                     f"sharding_fns=[{','.join(fns)}]")
    pl = pipeline_section(snap)
    if "pipeline_reason" in pl:
        lines.append(f"# pipeline: none ({pl['pipeline_reason']})")
    else:
        blob = pl.get("pipeline") or {}
        bub = {_series_labels(k).get("stage"): v
               for k, v in (pl.get("gauges") or {}).items()
               if _series_base(k) == "pipeline_bubble_fraction"}
        bub_s = " ".join(f"s{s}={bub[s]}" for s in sorted(bub)) or "n/a"
        lines.append(
            f"# pipeline: schedule={blob.get('schedule')} "
            f"stages={blob.get('num_stages')} "
            f"microbatches={blob.get('num_microbatches')} "
            f"step_ms={blob.get('step_ms')} bubble[{bub_s}]")
    mo = moe_section(snap)
    if "moe_reason" in mo:
        lines.append(f"# moe: none ({mo['moe_reason']})")
    else:
        g = mo.get("gauges") or {}
        load = mo.get("expert_load") or {}
        hot = (max(load, key=load.get) if load else None)
        lines.append(
            f"# moe: aux_loss={g.get('moe_aux_loss')} "
            f"dropped={g.get('moe_dropped_tokens')} "
            f"imbalance_ewma={g.get('moe_imbalance_ratio')} "
            f"hot_expert={hot} experts={len(load)}")
    gp = goodput_section(snap)
    if "goodput_reason" in gp:
        lines.append(f"# goodput: none ({gp['goodput_reason']})")
    else:
        blob = gp.get("goodput") or {}
        gauges = gp.get("gauges") or {}
        secs = blob.get("seconds") or {}
        frac = blob.get("goodput_fraction",
                        gauges.get("goodput_fraction"))
        lines.append(
            f"# goodput: fraction={frac} "
            f"productive={secs.get('productive')}s "
            f"unattributed={blob.get('unattributed_seconds')}s "
            f"restarts={blob.get('restarts')} "
            f"rework_steps={blob.get('rework_steps')} "
            f"eff_tok_per_s={blob.get('effective_tokens_per_sec')}")
    return "\n".join(lines) + "\n"


def _emit(snap, fmt, help_source=None) -> None:
    from apex_tpu.telemetry import metrics

    if fmt == "json":
        out = dict(snap)
        out["compile"] = compile_section(snap)
        out["devmem"] = devmem_section(snap)
        out["serving"] = serving_section(snap)
        out["comms"] = comms_section(snap)
        out["mesh"] = mesh_section(snap)
        out["pipeline"] = pipeline_section(snap)
        out["moe"] = moe_section(snap)
        out["goodput"] = goodput_section(snap)
        print(json.dumps(out, indent=1, sort_keys=True))
        return
    if help_source is not None:
        text = help_source.to_prometheus_text()
    else:
        text = metrics.prometheus_text_from_snapshot(snap)
    sys.stdout.write(text + plane_comments(snap))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="print a telemetry snapshot (live registry, "
                    "flight-recorder bundle, or bench record)")
    parser.add_argument("path", nargs="?", default=None,
                        help="JSON file holding a registry snapshot "
                             "(flightrec bundle / bench record); "
                             "default: the live process registry")
    parser.add_argument("--format", choices=("prom", "json"),
                        default="prom",
                        help="prom = Prometheus text exposition "
                             "(default), json = the snapshot dict")
    args = parser.parse_args(argv)

    from apex_tpu.telemetry import metrics

    if args.path is None:
        # live path: the registry renders with its HELP text
        _emit(metrics.registry().snapshot(), args.format,
              help_source=metrics.registry())
        return 0

    try:
        with open(args.path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        print(f"error: cannot read {args.path}: {e}", file=sys.stderr)
        return 2
    snap = extract_registry_snapshot(obj)
    if snap is None:
        print(f"error: no telemetry registry snapshot found in "
              f"{args.path}", file=sys.stderr)
        return 2
    _emit(snap, args.format)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Two-process quorum-checkpoint drill (one invocation = one "host").

The distributed acceptance scenario of docs/resilience.md run with
REAL processes over a real ``jax.distributed`` cluster on CPU — the
in-process threaded simulation lives in tests/test_quorum_checkpoint.py;
this drill proves the same protocol across actual process boundaries,
driven purely by the launcher env conventions
(MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK -> multiproc.
initialize_distributed) and the ``APEX_TPU_FAULTS`` env knob:

phase ``train``  — both hosts run a deterministic fused-step loop,
    quorum-checkpointing every 2 steps. The orchestrator (tools/
    check_resilience.sh) sets ``APEX_TPU_FAULTS=crash_before_commit=6``
    on host 1 ONLY: host 1 dies inside its step-6 save before its
    shard lands (exit 42, the expected death), and host 0's
    coordinator commit times out (``CheckpointError``, exit 0 after
    verifying the step-6 set stayed uncommitted).

phase ``resume`` — both hosts come back, restore
    ``latest_valid()`` — which MUST be the step-4 QUORUM checkpoint,
    never the partial step-6 host-set — replay to the end, and verify
    the final master is bitwise identical to an uninterrupted golden
    run computed locally.

Usage (see check_resilience.sh for the orchestration)::

    MASTER_ADDR=127.0.0.1 MASTER_PORT=29871 WORLD_SIZE=2 RANK=<r> \\
        python tools/quorum_drill.py {train|resume} <workdir>
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _cpu_mode import force_cpu  # noqa: E402

force_cpu()

import numpy as np  # noqa: E402

STEPS = 9
CKPT_EVERY = 2
CRASH_STEP = 6
QUORUM_STEP = 4


def _make(opt):
    import jax.numpy as jnp

    r = np.random.RandomState(0)
    params = {"w": jnp.asarray(r.randn(64, 8), jnp.float32),
              "b": jnp.zeros((8,), jnp.float32)}
    return opt.init(params)


def _grad(space, i):
    import jax.numpy as jnp

    r = np.random.RandomState(1000 + i)
    return jnp.asarray(r.randn(space.total).astype(np.float32) * 0.01)


def _run(step, state, start, stop):
    for i in range(start, stop):
        state, _ = step(state, _grad(state.space, i))
    return state


def main() -> int:
    phase, workdir = sys.argv[1], sys.argv[2]

    from apex_tpu import records
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.optimizers.train_step import make_train_step
    from apex_tpu.parallel import multiproc
    from apex_tpu.resilience import (CheckpointError, CheckpointManager,
                                     SimulatedCrash)

    records.RECORDS_DIR = os.path.join(workdir, "records")
    multiproc.initialize_distributed()          # env-driven, the ref way
    rank, world = multiproc.process_index(), multiproc.world_size()
    assert world == 2, f"drill expects WORLD_SIZE=2, got {world}"
    tag = f"[quorum_drill host {rank}]"

    opt = FusedAdam(lr=1e-2, impl="xla")
    step = make_train_step(opt)
    state = _make(opt)
    mgr = CheckpointManager(os.path.join(workdir, "ckpt"), keep=4,
                            process_id=rank, n_processes=world,
                            quorum_timeout=10.0)

    if phase == "train":
        try:
            for i in range(STEPS):
                state, _ = step(state, _grad(state.space, i))
                if (i + 1) % CKPT_EVERY == 0:
                    mgr.save(i + 1, state)
        except SimulatedCrash as e:
            print(f"{tag} died as planned: {e}", flush=True)
            return 42                           # the expected death
        except CheckpointError as e:
            assert "quorum timeout" in str(e), e
            ok, reason = mgr.validate(mgr.path_for(CRASH_STEP))
            assert not ok and "commit" in reason, (ok, reason)
            print(f"{tag} coordinator refused the partial host-set: "
                  f"{reason}", flush=True)
            return 0
        raise SystemExit(f"{tag} survived a drill that kills host 1")

    assert phase == "resume", phase
    path = mgr.latest_valid()
    assert path == mgr.path_for(QUORUM_STEP), (
        f"{tag} resumed from {path}, wanted the step-{QUORUM_STEP} "
        "QUORUM checkpoint")
    restored = mgr.restore(path, template=state)
    assert restored.step == QUORUM_STEP
    state = _run(step, restored.opt_state, restored.step, STEPS)

    golden = _run(step, _make(opt), 0, STEPS)
    if not np.array_equal(np.asarray(state.master),
                          np.asarray(golden.master)):
        raise SystemExit(f"{tag} resumed trajectory diverged from golden")
    print(f"{tag} resumed from quorum step {restored.step}, replay "
          "bitwise-identical: OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Pytest plugin: slow-marker audit.

Tier-1 runs ``-m 'not slow'`` under a hard 870 s budget (ROADMAP.md);
a long test that forgets the ``slow`` marker silently eats that budget
for every future round. This plugin asserts the invariant over
whatever selection it runs with: any test whose call phase exceeds
``APEX_TPU_SLOW_BUDGET_S`` seconds (default 20) and does NOT carry the
``slow`` marker is reported and fails the session.

Usage (tools/check_resilience.sh wires it up)::

    python -m pytest tests/ -p tools._marker_audit ...

The summary line is machine-grepable: ``marker-audit: OK`` or
``marker-audit: FAILED (<n> unmarked slow tests)``.
"""

import os

BUDGET_S = float(os.environ.get("APEX_TPU_SLOW_BUDGET_S", "20"))

_offenders = []


def pytest_runtest_logreport(report):
    if report.when != "call":
        return
    if report.duration > BUDGET_S and "slow" not in report.keywords:
        _offenders.append((report.nodeid, report.duration))


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    tr = terminalreporter
    if not _offenders:
        tr.write_line(f"marker-audit: OK (budget {BUDGET_S:g}s)")
        return
    tr.write_line(
        f"marker-audit: FAILED ({len(_offenders)} unmarked slow tests)")
    for nodeid, dur in sorted(_offenders, key=lambda t: -t[1]):
        tr.write_line(
            f"  {dur:7.1f}s  {nodeid}  — add @pytest.mark.slow or "
            "shrink it under the tier-1 budget")


def pytest_sessionfinish(session, exitstatus):
    # flip the process exit code; the grep on the summary line is the
    # belt to this suspender (pytest versions differ on whether a
    # plugin may mutate exitstatus here)
    if _offenders and exitstatus == 0:
        session.exitstatus = 1
        session.testsfailed += 1

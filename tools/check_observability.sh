#!/usr/bin/env bash
# Observability smoke (CI / pre-merge, next to check_telemetry.sh and
# check_resilience.sh): the fleet-aggregation / flight-recorder /
# compile-tracker / devmem / bench-baseline unit tier, the
# disabled-telemetry structural guarantee (the disabled path IS the
# cached raw step object), the COMPILE-TRACKER smoke (one forced
# re-trace of the train step must emit exactly ONE `recompile` event
# with a signature diff, cache hits must publish nothing, and the
# armed tracker must hold the <1% steady-state overhead budget), and
# the two-process jax.distributed FLEET DRILL (tools/fleet_drill.py):
# a one-replica bit_flip injected via APEX_TPU_FAULTS must produce a
# committed flightrec_*.json black box on every host — trigger
# replica_divergence, fleet snapshot summing both hosts' counters,
# straggler gauges present, perfetto slice well-formed — plus the
# COMMS-PLANE smoke (docs/observability.md "Comms & sharding plane"):
# disabled means instrument(col) IS col (zero wrapper), and the drill
# must assert collective spans on both hosts, latch a collective_slow
# escalation from the injected-delay fault clause, and commit ONE
# offset-corrected merged perfetto trace this script structure-
# validates. Extra args pass through to pytest.
set -uo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu

rc=0

python -m pytest tests/test_telemetry.py tests/test_fleet.py \
    tests/test_flight.py tests/test_bench_baseline.py \
    tests/test_records.py tests/test_compiled.py tests/test_devmem.py \
    tests/test_comms.py tests/test_goodput.py \
    "$@" -q -p no:cacheprovider || rc=1

echo "== compile-tracker smoke: one forced retrace =="
python - <<'PY' || rc=1
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import telemetry
from apex_tpu.telemetry import compiled
from apex_tpu.optimizers import FusedAdam
from apex_tpu.optimizers.train_step import make_train_step

telemetry.reset()
sink = telemetry.InMemorySink()
telemetry.registry().add_sink(sink)
compiled.enable()

rng = np.random.RandomState(0)
params = {f"p{i}": jnp.asarray(rng.randn(512).astype(np.float32) * 0.02)
          for i in range(12)}
opt = FusedAdam(lr=1e-3)
state = opt.init(params)
g = jnp.asarray(rng.randn(state.space.total).astype(np.float32) * 1e-3)

step = make_train_step(opt)
state, _ = step(state, g)                 # first trace+compile
assert not [e for e in sink.events if e["event"] == "recompile"], \
    "the FIRST signature is a compile, not a recompile"
compiles = telemetry.registry().counter("compile_count").value(
    fn="train_step")
assert compiles >= 1, "labeled compile not recorded"
state, _ = step(state, g)                 # layout cache hit
assert telemetry.registry().counter("compile_count").value(
    fn="train_step") == compiles, "a cache hit must publish no compile"

# forced re-trace: ONE changed static option on the same fn
sibling = step.with_options(with_grad_norm=True)
state, _ = sibling(state, g)
rec = [e for e in sink.events if e["event"] == "recompile"]
assert len(rec) == 1, f"expected exactly one recompile event, got {rec}"
assert rec[0]["fn"] == "train_step"
assert "with_grad_norm" in rec[0]["signature_diff"]["changed"], rec[0]
state, _ = sibling(state, g)              # hit on the sibling: still one
assert len([e for e in sink.events if e["event"] == "recompile"]) == 1

# re-assert the structural guarantees with the tracker ARMED: the
# disabled-telemetry path is still the raw cached step object...
assert make_train_step(opt, telemetry=None) is step
assert make_train_step(
    opt, telemetry=telemetry.StepTimeline(enabled=False)) is step

# ...and the armed tracker adds <1% to the steady-state host loop
# (layout hits never reach the tracker; this measures exactly that)
STEPS = 20

def loop(s, st):
    for _ in range(STEPS):
        st, _aux = s(st, g)
    jax.block_until_ready(st.master)
    return st

state = loop(step, state)                 # warm
t_on = t_off = float("inf")
for _ in range(11):                       # interleaved best-of
    compiled.enable()
    t0 = time.perf_counter()
    state = loop(step, state)
    t_on = min(t_on, time.perf_counter() - t0)
    compiled.disable()
    t0 = time.perf_counter()
    state = loop(step, state)
    t_off = min(t_off, time.perf_counter() - t0)
overhead = t_on / t_off - 1.0
print(f"tracker-armed={t_on * 1e3:.3f}ms disarmed={t_off * 1e3:.3f}ms "
      f"overhead={overhead * 100:+.3f}%")
assert overhead < 0.01, (
    f"armed compile-tracker steady-state overhead "
    f"{overhead * 100:.3f}% >= 1%")
compiled.disable()
telemetry.reset()
print("compile-tracker smoke: OK")
PY

echo "== disabled-telemetry structural guarantee =="
python - <<'PY' || rc=1
from apex_tpu import telemetry
from apex_tpu.optimizers import FusedAdam
from apex_tpu.optimizers.train_step import make_train_step

import jax.numpy as jnp
import numpy as np

rng = np.random.RandomState(0)
params = {"w": jnp.asarray(rng.randn(256).astype(np.float32))}
opt = FusedAdam(lr=1e-3)
step = make_train_step(opt)
disabled = make_train_step(
    opt, telemetry=telemetry.StepTimeline(enabled=False))
# the <1% overhead budget of check_telemetry.sh rests on this identity:
# with telemetry disabled there is NO instrumented code to be slow —
# the flight-recorder / fleet wiring must not have broken it
assert disabled is step, "disabled telemetry must be the raw step object"
assert make_train_step(opt, telemetry=None) is step
# and an armed-then-disarmed flight recorder leaves it intact
telemetry.flight.enable(keep=1)
telemetry.flight.disable()
assert make_train_step(opt, telemetry=None) is step
print("disabled-is-step: OK")
PY

echo "== comms-plane structural guarantee =="
python - <<'PY' || rc=1
import numpy as np

from apex_tpu import telemetry
from apex_tpu.telemetry import comms
from apex_tpu.resilience.guard import NullCollective

telemetry.reset()
# disabled means UNTOUCHED: the raw object, no wrapper in the path —
# the make_train_step disabled-is-step discipline applied to the wire
col = NullCollective()
assert comms.instrument(col) is col, \
    "disarmed instrument() must return the exact object passed in"
assert not comms.enabled()

# armed: the same call wraps, ops land on the registry, and the
# bundle section flips from reason to summary
tracer = comms.enable()
wrapped = comms.instrument(col)
assert isinstance(wrapped, comms.InstrumentedCollective)
assert comms.instrument(wrapped) is wrapped, "re-wrap must be idempotent"
out = wrapped.all_gather(np.ones(256, np.float32))
assert np.array_equal(np.asarray(out)[0], np.ones(256, np.float32))
wrapped.barrier()
snap = telemetry.registry().snapshot()["counters"]
key = 'collective_ops{impl="NullCollective",op="all_gather"}'
assert snap.get(key) == 1.0, snap
assert comms.section()["enabled"] is True
ledger = {r["op"]: r for r in tracer.ledger()}
assert ledger["all_gather"]["payload_bytes"] == 1024
assert ledger["all_gather"]["wire_bytes"] == 1024  # n_replicas == 1
telemetry.reset()
assert comms.section()["enabled"] is False, \
    "reset must disarm the comms plane"
print("comms structural guarantees: OK")
PY

# Goodput kill-and-resume drill (docs/observability.md "Run ledger &
# goodput"): a 30-step run with injected data stalls (the
# data_stall_ms fault clause), one forced watchdog rollback, and a
# real SIGTERM -> graceful drain; invocation 2 resumes from the
# drained checkpoint (the packed ledger rides the manifest extra),
# asserts every exercised bucket is nonzero, the attribution identity
# holds, and the unattributed residual stays under 5% of wall — then
# the report CLI renders the table from the checkpoint dir ALONE (the
# dead-run postmortem path, docs/resilience.md "Postmortem runbook").
echo "== goodput kill-and-resume drill =="
gp_dir="$(mktemp -d)"
cat > "$gp_dir/goodput_drill.py" <<'PY'
import json
import os
import sys

sys.path.insert(0, os.getcwd())   # invoked from the repo root

import jax.numpy as jnp
import numpy as np

from apex_tpu import telemetry
from apex_tpu.amp.scaler import LossScaler
from apex_tpu.optimizers import FusedAdam
from apex_tpu.optimizers.train_step import make_train_step
from apex_tpu.resilience import CheckpointManager, NonfiniteWatchdog, faults
from apex_tpu.resilience.guard import (graceful_shutdown,
                                       install_preemption_handler)
from apex_tpu.runtime import PrefetchLoader

ckpt_dir, phase = sys.argv[1], sys.argv[2]

telemetry.reset()
goodput = telemetry.goodput

rng = np.random.RandomState(0)
params = {"w1": jnp.asarray(rng.randn(64, 32).astype(np.float32) * 0.02),
          "b": jnp.zeros((32,), jnp.float32)}
opt = FusedAdam(lr=1e-3, impl="xla")
scaler = LossScaler(init_scale=2.0 ** 8, scale_window=100)
step_fn = make_train_step(
    opt, scaler=scaler,
    # sync=True: the span covers device execution, not just dispatch,
    # so the per-step compute lands in productive instead of leaking
    # into unattributed at the watchdog's found_inf sync
    telemetry=telemetry.StepTimeline(enabled=True, sync=True))
state = opt.init(params)
sstate = scaler.init()
mgr = CheckpointManager(f"{ckpt_dir}", keep=8)
wd = NonfiniteWatchdog(step_fn, manager=mgr, threshold=1)
base_g = jnp.asarray(rng.randn(state.space.total).astype(np.float32) * 1e-3)
nan_g = jnp.asarray(base_g).at[0].set(float("nan"))  # pre-built: the
# scatter's compile is drill scaffolding, not run time to attribute
handler = install_preemption_handler()

# arm AFTER setup: the ledger's wall starts here, so import/init time
# (not part of any run) stays out of the unattributed residual
goodput.enable(publish_every=10)

start = 0
if phase == "resume":
    restored = mgr.restore(template=state)   # absorbs the packed ledger
    state, sstate = restored.opt_state, restored.scaler_state
    start = restored.step + 1
n_steps = 10 if phase == "resume" else 30


def batches(n):
    for _ in range(n):
        yield rng.randn(128).astype(np.float32)


for j, b in enumerate(PrefetchLoader(batches(n_steps), depth=2)):
    i = start + j
    g = base_g
    if phase == "first" and i == 8:
        g = nan_g                            # -> threshold=1 rollback
    state, sstate, aux = wd(state, g, sstate)
    goodput.observe_step(step=i, loss=1.0 / (i + 1.0), tokens=2048)
    if i and i % 5 == 0:
        mgr.save(i, state, scaler_state=sstate)
    faults.maybe_sigterm(i)                  # sigterm=20 in phase one
    if handler.should_stop():
        graceful_shutdown(mgr, i, state, scaler_state=sstate,
                          handler=handler)
        print("phase1 drained at step", i)
        sys.exit(0)

if phase == "first":
    sys.exit("phase one must end in the SIGTERM drain, not fall through")

mgr.save(start + n_steps - 1, state, scaler_state=sstate)
s = goodput.get_ledger().summary()
sec = s["seconds"]
assert s["restarts"] == 1, s
assert s["rollbacks"] == 0, "the rollback happened in phase one"
for cause in ("productive", "data_wait", "checkpoint_save",
              "checkpoint_restore", "rollback", "rework",
              "drain_shutdown"):
    assert sec[cause] > 0.0, (cause, sec)
assert s["rework_steps"] > 0, s
attributed = sum(v for c, v in sec.items() if c != "unattributed")
wall = s["wall_seconds"]
# the identity: buckets + residual == wall (or == buckets themselves
# when async overlap pushed attribution past wall and residual is 0)
assert abs(attributed + sec["unattributed"] - max(wall, attributed)) < 1e-3, s
assert sec["unattributed"] < 0.05 * wall, (
    f"unattributed {sec['unattributed']:.3f}s >= 5% of wall {wall:.3f}s")
print("resume summary:", json.dumps(
    {k: s[k] for k in ("restarts", "rework_steps", "goodput_fraction",
                       "unattributed_seconds", "wall_seconds")}))
PY
if env APEX_TPU_FAULTS="data_stall_ms=4;sigterm=20" \
        python "$gp_dir/goodput_drill.py" "$gp_dir/ckpt" first \
        && python "$gp_dir/goodput_drill.py" "$gp_dir/ckpt" resume; then
    # the postmortem path: the table renders from the dir ALONE, and
    # carries the restart the resumed incarnation recorded (captured,
    # not piped into grep -q: an early-exiting reader would SIGPIPE
    # the report under pipefail even on a match)
    gp_report="$(python tools/goodput_report.py "$gp_dir/ckpt")"
    if grep -q "^restarts    1" <<<"$gp_report"; then
        echo "goodput kill-and-resume drill: OK"
    else
        echo "goodput drill FAILED: report from checkpoint dir lacks" \
             "the resumed restart" >&2
        printf '%s\n' "$gp_report" >&2
        rc=1
    fi
else
    echo "goodput drill FAILED" >&2
    rc=1
fi
rm -rf "$gp_dir"

echo "== goodput ledger overhead budget =="
python - <<'PY' || rc=1
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import telemetry
from apex_tpu.optimizers import FusedAdam
from apex_tpu.optimizers.train_step import make_train_step

telemetry.reset()
rng = np.random.RandomState(0)
# ~2ms CPU step — the granularity the <1% budget is stated against
# (docs/observability.md "Run ledger & goodput")
params = {f"p{i}": jnp.asarray(rng.randn(24576).astype(np.float32) * 0.02)
          for i in range(12)}
opt = FusedAdam(lr=1e-3)
state = opt.init(params)
g = jnp.asarray(rng.randn(state.space.total).astype(np.float32) * 1e-3)
# the SAME instrumented step both ways: armed-vs-disarmed measures
# exactly the ledger's span observer + per-step feed, nothing else.
# sync=True: each step blocks, so the comparison isolates the
# ledger's host work instead of the CPU backend's GIL/thread
# scheduling interaction with async dispatch
step = make_train_step(
    opt, telemetry=telemetry.StepTimeline(enabled=True, sync=True))
STEPS = 20

def loop(s, st):
    for k in range(STEPS):
        st, _aux = s(st, g)
        telemetry.goodput.observe_step(step=k, loss=1.0, tokens=512)
    jax.block_until_ready(st.master)
    return st

state = loop(step, state)                 # warm
t_on = t_off = float("inf")
for _ in range(11):                       # interleaved best-of
    telemetry.goodput.enable(publish_every=10 ** 9)
    t0 = time.perf_counter()
    state = loop(step, state)
    t_on = min(t_on, time.perf_counter() - t0)
    telemetry.goodput.disable()
    t0 = time.perf_counter()
    state = loop(step, state)
    t_off = min(t_off, time.perf_counter() - t0)
overhead = t_on / t_off - 1.0
print(f"ledger-armed={t_on * 1e3:.3f}ms disarmed={t_off * 1e3:.3f}ms "
      f"overhead={overhead * 100:+.3f}%")
assert overhead < 0.01, (
    f"armed goodput-ledger steady-state overhead "
    f"{overhead * 100:.3f}% >= 1%")
telemetry.reset()
print("goodput overhead budget: OK")
PY

# Two-process jax.distributed fleet drill: rank 1 carries the bit_flip
# fault; both hosts must leave a committed flight bundle (see
# tools/fleet_drill.py for every asserted property).
echo "== two-process fleet drill =="
drill_dir="$(mktemp -d)"
drill_port=$(( 20000 + RANDOM % 20000 ))
drill_env=(MASTER_ADDR=127.0.0.1 "MASTER_PORT=$drill_port" WORLD_SIZE=2)
env "${drill_env[@]}" RANK=0 python tools/fleet_drill.py "$drill_dir" &
h0=$!
env "${drill_env[@]}" RANK=1 \
    APEX_TPU_FAULTS="bit_flip=3;bit_flip_replica=1;bit_flip_leaf=0" \
    python tools/fleet_drill.py "$drill_dir" &
h1=$!
wait $h0; rc0=$?
wait $h1; rc1=$?
if [ "$rc0" -ne 0 ] || [ "$rc1" -ne 0 ]; then
    echo "fleet drill FAILED (host0 rc=$rc0, host1 rc=$rc1)" >&2
    rc=1
else
    # the bundle's perfetto slice + registry snapshot feed the dump CLI
    bundle="$(ls "$drill_dir"/records_0/flightrec_*.json | head -1)"
    if python tools/telemetry_dump.py "$bundle" | grep -q "drill_steps"; then
        echo "two-process fleet drill: OK"
    else
        echo "fleet drill FAILED: telemetry_dump found no drill_steps" \
             "in $bundle" >&2
        rc=1
    fi
    # the armed comms plane rode the same bundle: the dump CLI's prom
    # view must render collective_ops series + the comms summary line
    dump="$(python tools/telemetry_dump.py "$bundle")"
    if echo "$dump" | grep -q '^collective_ops{' \
            && echo "$dump" | grep -Eq '^# comms: [0-9]+ collective ops'; then
        echo "bundle comms section: OK"
    else
        echo "fleet drill FAILED: bundle dump carries no comms plane" >&2
        rc=1
    fi
    # host 0 committed the offset-corrected merged perfetto trace;
    # hold it to the structure the drill promised
    python - "$drill_dir/merged_trace.json" <<'PY' || rc=1
import json
import sys

with open(sys.argv[1]) as f:
    trace = json.load(f)
evs = trace["traceEvents"]
pids = {e["pid"] for e in evs if e.get("ph") == "X"}
assert pids == {0, 1}, f"merged trace pids {pids}: want both hosts"
for r in (0, 1):
    c_evs = [e for e in evs if e.get("ph") == "X" and e["pid"] == r
             and e["name"].startswith("collective:")]
    assert c_evs, f"no collective spans for host {r}"
    assert all("payload_bytes" in e["args"] and e["dur"] >= 0
               for e in c_evs), f"host {r} spans lack bytes attribution"
    names = [e for e in evs if e.get("ph") == "M"
             and e["name"] == "process_name" and e.get("pid") == r]
    assert names, f"no process_name track for host {r}"
assert any(e.get("ph") == "i" and e["name"] == "collective_slow"
           for e in evs), "no collective_slow instant in merged trace"
assert all(e["ts"] >= 0 for e in evs if "ts" in e), "negative ts"
od = trace["otherData"]
assert od["n_hosts"] == 2 and "clock_offsets_ms" in od
print(f"merged fleet trace: OK ({len(evs)} events, "
      f"clock spread {od['clock_offset_spread_ms']}ms)")
PY
fi
rm -rf "$drill_dir"

if [ "$rc" -eq 0 ]; then
    echo "check_observability: OK"
else
    echo "check_observability: FAILED (rc=$rc)" >&2
fi
exit $rc

#!/usr/bin/env bash
# Observability smoke (CI / pre-merge, next to check_telemetry.sh and
# check_resilience.sh): the fleet-aggregation / flight-recorder /
# bench-baseline unit tier, the disabled-telemetry structural guarantee
# (the disabled path IS the cached raw step object), and the
# two-process jax.distributed FLEET DRILL (tools/fleet_drill.py): a
# one-replica bit_flip injected via APEX_TPU_FAULTS must produce a
# committed flightrec_*.json black box on every host — trigger
# replica_divergence, fleet snapshot summing both hosts' counters,
# straggler gauges present, perfetto slice well-formed. Extra args
# pass through to pytest.
set -uo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu

rc=0

python -m pytest tests/test_telemetry.py tests/test_fleet.py \
    tests/test_flight.py tests/test_bench_baseline.py \
    tests/test_records.py "$@" -q -p no:cacheprovider || rc=1

echo "== disabled-telemetry structural guarantee =="
python - <<'PY' || rc=1
from apex_tpu import telemetry
from apex_tpu.optimizers import FusedAdam
from apex_tpu.optimizers.train_step import make_train_step

import jax.numpy as jnp
import numpy as np

rng = np.random.RandomState(0)
params = {"w": jnp.asarray(rng.randn(256).astype(np.float32))}
opt = FusedAdam(lr=1e-3)
step = make_train_step(opt)
disabled = make_train_step(
    opt, telemetry=telemetry.StepTimeline(enabled=False))
# the <1% overhead budget of check_telemetry.sh rests on this identity:
# with telemetry disabled there is NO instrumented code to be slow —
# the flight-recorder / fleet wiring must not have broken it
assert disabled is step, "disabled telemetry must be the raw step object"
assert make_train_step(opt, telemetry=None) is step
# and an armed-then-disarmed flight recorder leaves it intact
telemetry.flight.enable(keep=1)
telemetry.flight.disable()
assert make_train_step(opt, telemetry=None) is step
print("disabled-is-step: OK")
PY

# Two-process jax.distributed fleet drill: rank 1 carries the bit_flip
# fault; both hosts must leave a committed flight bundle (see
# tools/fleet_drill.py for every asserted property).
echo "== two-process fleet drill =="
drill_dir="$(mktemp -d)"
drill_port=$(( 20000 + RANDOM % 20000 ))
drill_env=(MASTER_ADDR=127.0.0.1 "MASTER_PORT=$drill_port" WORLD_SIZE=2)
env "${drill_env[@]}" RANK=0 python tools/fleet_drill.py "$drill_dir" &
h0=$!
env "${drill_env[@]}" RANK=1 \
    APEX_TPU_FAULTS="bit_flip=3;bit_flip_replica=1;bit_flip_leaf=0" \
    python tools/fleet_drill.py "$drill_dir" &
h1=$!
wait $h0; rc0=$?
wait $h1; rc1=$?
if [ "$rc0" -ne 0 ] || [ "$rc1" -ne 0 ]; then
    echo "fleet drill FAILED (host0 rc=$rc0, host1 rc=$rc1)" >&2
    rc=1
else
    # the bundle's perfetto slice + registry snapshot feed the dump CLI
    bundle="$(ls "$drill_dir"/records_0/flightrec_*.json | head -1)"
    if python tools/telemetry_dump.py "$bundle" | grep -q "drill_steps"; then
        echo "two-process fleet drill: OK"
    else
        echo "fleet drill FAILED: telemetry_dump found no drill_steps" \
             "in $bundle" >&2
        rc=1
    fi
fi
rm -rf "$drill_dir"

if [ "$rc" -eq 0 ]; then
    echo "check_observability: OK"
else
    echo "check_observability: FAILED (rc=$rc)" >&2
fi
exit $rc

#!/usr/bin/env bash
# Resilience smoke (CI / pre-merge): the kill-and-resume acceptance
# test, the watchdog escalation ladder, and the fault-injection matrix
# under JAX_PLATFORMS=cpu — with the slow-marker audit active (every
# test over APEX_TPU_SLOW_BUDGET_S seconds must carry @pytest.mark.slow,
# tools/_marker_audit.py). Extra args are passed through to pytest,
# e.g.:  tools/check_resilience.sh tests/  (audit the whole suite).
set -uo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu

log="$(mktemp)"
trap 'rm -f "$log"' EXIT
rc=0

targets=(tests/test_resilience.py tests/test_watchdog.py)
if [ "$#" -gt 0 ]; then targets=(); fi
python -m pytest "${targets[@]}" "$@" -q \
    -p no:cacheprovider -p tools._marker_audit 2>&1 | tee "$log"
prc=${PIPESTATUS[0]}
[ "$prc" -ne 0 ] && rc=$prc
if grep -q "marker-audit: FAILED" "$log"; then
    echo "check_resilience: slow-marker audit failed" >&2
    rc=1
fi

# Fault-injection matrix via the APEX_TPU_FAULTS env knob: the same
# plans the tests install programmatically must work from the
# environment, with no code edits (docs/resilience.md "knobs").
echo "== env-knob fault matrix =="
APEX_TPU_FAULTS="nan_grads=2,3;nan_leaf=0;io:record_write=0;io:device_put=0,2" \
python - <<'PY'
import tempfile

import numpy as np

from apex_tpu import records
from apex_tpu.resilience import faults

inj = faults.active()
assert inj is not None, "env knob did not activate"
assert inj.should_poison(2) and inj.should_poison(3)
assert not inj.should_poison(1)

# nan_grads: poisons exactly the planned steps
import jax.numpy as jnp
g = jnp.zeros((16,), jnp.float32)
assert np.isfinite(np.asarray(faults.poison_grads(g, 1))).all()
assert np.isnan(np.asarray(faults.poison_grads(g, 2))).any()

# io:record_write transient fault absorbed by the retry path
records.RECORDS_DIR = tempfile.mkdtemp()
path = records.write_record("resil_smoke", {"ok": 1})
assert path is not None, "retry did not absorb the injected write fault"

# io:device_put transient faults: the prefetch pipeline delivers every
# batch, in order, without degrading
from apex_tpu.runtime import PrefetchLoader
batches = [np.full((2,), i, np.float32) for i in range(4)]
loader = PrefetchLoader(iter(batches), depth=2, retry_base_delay=0.001)
out = list(loader)
assert len(out) == 4 and not loader.degraded, (len(out), loader.degraded)
for i, b in enumerate(out):
    np.testing.assert_array_equal(np.asarray(b), batches[i])
print("env-knob fault matrix: OK")
PY
[ $? -ne 0 ] && rc=1

# Permanent-death degrade: repeated worker deaths must fall back to
# synchronous loading, not fail the epoch.
APEX_TPU_FAULTS="io:device_put=0,1,2,3" python - <<'PY'
import numpy as np

from apex_tpu.runtime import PrefetchLoader

batches = [np.full((2,), i, np.float32) for i in range(4)]
loader = PrefetchLoader(iter(batches), depth=2, transfer_retries=1,
                        max_worker_restarts=1, retry_base_delay=0.001)
out = list(loader)
assert loader.degraded and len(out) == 4, (loader.degraded, len(out))
print("synchronous degrade: OK")
PY
[ $? -ne 0 ] && rc=1

if [ "$rc" -eq 0 ]; then
    echo "check_resilience: OK"
else
    echo "check_resilience: FAILED (rc=$rc)" >&2
fi
exit $rc

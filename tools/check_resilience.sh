#!/usr/bin/env bash
# Resilience smoke (CI / pre-merge): the kill-and-resume acceptance
# test, the watchdog escalation ladder, and the fault-injection matrix
# under JAX_PLATFORMS=cpu — with the slow-marker audit active (every
# test over APEX_TPU_SLOW_BUDGET_S seconds must carry @pytest.mark.slow,
# tools/_marker_audit.py). Extra args are passed through to pytest,
# e.g.:  tools/check_resilience.sh tests/  (audit the whole suite).
set -uo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu

log="$(mktemp)"
trap 'rm -f "$log"' EXIT
rc=0

targets=(tests/test_resilience.py tests/test_watchdog.py
         tests/test_guard.py tests/test_quorum_checkpoint.py)
if [ "$#" -gt 0 ]; then targets=(); fi
python -m pytest "${targets[@]}" "$@" -q \
    -p no:cacheprovider -p tools._marker_audit 2>&1 | tee "$log"
prc=${PIPESTATUS[0]}
[ "$prc" -ne 0 ] && rc=$prc
if grep -q "marker-audit: FAILED" "$log"; then
    echo "check_resilience: slow-marker audit failed" >&2
    rc=1
fi

# Fault-injection matrix via the APEX_TPU_FAULTS env knob: the same
# plans the tests install programmatically must work from the
# environment, with no code edits (docs/resilience.md "knobs").
echo "== env-knob fault matrix =="
APEX_TPU_FAULTS="nan_grads=2,3;nan_leaf=0;io:record_write=0;io:device_put=0,2" \
python - <<'PY'
import tempfile

import numpy as np

from apex_tpu import records
from apex_tpu.resilience import faults

inj = faults.active()
assert inj is not None, "env knob did not activate"
assert inj.should_poison(2) and inj.should_poison(3)
assert not inj.should_poison(1)

# nan_grads: poisons exactly the planned steps
import jax.numpy as jnp
g = jnp.zeros((16,), jnp.float32)
assert np.isfinite(np.asarray(faults.poison_grads(g, 1))).all()
assert np.isnan(np.asarray(faults.poison_grads(g, 2))).any()

# io:record_write transient fault absorbed by the retry path
records.RECORDS_DIR = tempfile.mkdtemp()
path = records.write_record("resil_smoke", {"ok": 1})
assert path is not None, "retry did not absorb the injected write fault"

# io:device_put transient faults: the prefetch pipeline delivers every
# batch, in order, without degrading
from apex_tpu.runtime import PrefetchLoader
batches = [np.full((2,), i, np.float32) for i in range(4)]
loader = PrefetchLoader(iter(batches), depth=2, retry_base_delay=0.001)
out = list(loader)
assert len(out) == 4 and not loader.degraded, (len(out), loader.degraded)
for i, b in enumerate(out):
    np.testing.assert_array_equal(np.asarray(b), batches[i])
print("env-knob fault matrix: OK")
PY
[ $? -ne 0 ] && rc=1

# Permanent-death degrade: repeated worker deaths must fall back to
# synchronous loading, not fail the epoch.
APEX_TPU_FAULTS="io:device_put=0,1,2,3" python - <<'PY'
import numpy as np

from apex_tpu.runtime import PrefetchLoader

batches = [np.full((2,), i, np.float32) for i in range(4)]
loader = PrefetchLoader(iter(batches), depth=2, transfer_retries=1,
                        max_worker_restarts=1, retry_base_delay=0.001)
out = list(loader)
assert loader.degraded and len(out) == 4, (loader.degraded, len(out))
print("synchronous degrade: OK")
PY
[ $? -ne 0 ] && rc=1

# Distributed-site env-knob matrix: the guard/quorum clauses must parse
# and fire from the environment exactly like the classic ones.
echo "== distributed env-knob matrix =="
APEX_TPU_FAULTS="bit_flip=3;bit_flip_replica=1;bit_flip_leaf=0;crash_before_commit=6;sigterm=9;shard_truncate=4;shard_truncate_host=1;world_mismatch=8;range_fetch_timeout=0,2" \
python - <<'PY'
import signal

import numpy as np

from apex_tpu.resilience import faults
from apex_tpu.resilience.guard import PreemptionHandler

inj = faults.active()
assert inj is not None, "env knob did not activate"
assert inj.should_bit_flip(3, replica=1)
assert not inj.should_bit_flip(3, replica=0)     # targeted replica only
assert not inj.should_bit_flip(2, replica=1)

import jax.numpy as jnp
buf = jnp.zeros((16,), jnp.float32) + 1.0
flipped = np.asarray(faults.flip_bits(buf, 3, replica=1))
assert (flipped != np.asarray(buf)).sum() == 1   # exactly one element
assert np.isfinite(flipped).all()                # SDC, not a NaN bomb

try:
    faults.maybe_crash_before_commit(6)
    raise SystemExit("crash_before_commit did not fire")
except faults.SimulatedCrash:
    pass

# elastic clauses: all three parse and fire from the env
assert faults.shard_truncate_target(4) == 1      # the configured host
assert faults.shard_truncate_target(3) is None
assert faults.should_world_mismatch(8)
assert not faults.should_world_mismatch(7)
assert faults.should_range_timeout(0) and faults.should_range_timeout(2)
assert not faults.should_range_timeout(1)

with PreemptionHandler(signals=(signal.SIGTERM,)) as h:
    faults.maybe_sigterm(8)
    assert not h.requested
    faults.maybe_sigterm(9)                      # a REAL SIGTERM to self
    assert h.requested and h.signum == signal.SIGTERM
print("distributed env-knob matrix: OK")
PY
[ $? -ne 0 ] && rc=1

# Two-process jax.distributed drill: kill host 1 before the quorum
# commit, then resume BOTH hosts from the last quorum checkpoint
# (tools/quorum_drill.py; the in-process analog is
# tests/test_quorum_checkpoint.py).
echo "== two-process quorum drill =="
drill_dir="$(mktemp -d)"
drill_port=$(( 20000 + RANDOM % 20000 ))
drill_env=(MASTER_ADDR=127.0.0.1 "MASTER_PORT=$drill_port" WORLD_SIZE=2)
env "${drill_env[@]}" RANK=0 python tools/quorum_drill.py train "$drill_dir" &
h0=$!
env "${drill_env[@]}" RANK=1 APEX_TPU_FAULTS="crash_before_commit=6" \
    python tools/quorum_drill.py train "$drill_dir" &
h1=$!
wait $h0; rc0=$?
wait $h1; rc1=$?
if [ "$rc0" -ne 0 ] || [ "$rc1" -ne 42 ]; then
    echo "quorum drill train phase FAILED (host0 rc=$rc0, host1 rc=$rc1," \
         "expected 0/42)" >&2
    rc=1
else
    drill_port=$(( 20000 + RANDOM % 20000 ))
    drill_env=(MASTER_ADDR=127.0.0.1 "MASTER_PORT=$drill_port" WORLD_SIZE=2)
    env "${drill_env[@]}" RANK=0 python tools/quorum_drill.py resume "$drill_dir" &
    h0=$!
    env "${drill_env[@]}" RANK=1 python tools/quorum_drill.py resume "$drill_dir" &
    h1=$!
    wait $h0; rc0=$?
    wait $h1; rc1=$?
    if [ "$rc0" -ne 0 ] || [ "$rc1" -ne 0 ]; then
        echo "quorum drill resume phase FAILED (rc=$rc0/$rc1)" >&2
        rc=1
    else
        echo "two-process quorum drill: OK"
    fi
fi
rm -rf "$drill_dir"

# Elastic resharding drill: save on 2 jax.distributed processes,
# SIGTERM host 0 (graceful elastic commit), then resume once on 1
# process and once on 3 — both must reassemble the exact bits
# (tools/elastic_drill.py; the in-process analog is
# tests/test_elastic.py).
echo "== elastic resharding drill =="
el_dir="$(mktemp -d)"
el_port=$(( 20000 + RANDOM % 20000 ))
el_env=(MASTER_ADDR=127.0.0.1 "MASTER_PORT=$el_port" WORLD_SIZE=2)
env "${el_env[@]}" RANK=0 APEX_TPU_FAULTS="sigterm=5" \
    python tools/elastic_drill.py train "$el_dir" &
h0=$!
env "${el_env[@]}" RANK=1 python tools/elastic_drill.py train "$el_dir" &
h1=$!
wait $h0; rc0=$?
wait $h1; rc1=$?
if [ "$rc0" -ne 0 ] || [ "$rc1" -ne 0 ]; then
    echo "elastic drill train phase FAILED (rc=$rc0/$rc1)" >&2
    rc=1
else
    # resume on 1 process (shrink): no cluster, every range from disk
    if ! python tools/elastic_drill.py resume "$el_dir"; then
        echo "elastic drill resume-on-1 FAILED" >&2
        rc=1
    else
        # resume on 3 processes (grow): ranges served over the collective
        el_port=$(( 20000 + RANDOM % 20000 ))
        el_env=(MASTER_ADDR=127.0.0.1 "MASTER_PORT=$el_port" WORLD_SIZE=3)
        env "${el_env[@]}" RANK=0 python tools/elastic_drill.py resume "$el_dir" &
        h0=$!
        env "${el_env[@]}" RANK=1 python tools/elastic_drill.py resume "$el_dir" &
        h1=$!
        env "${el_env[@]}" RANK=2 python tools/elastic_drill.py resume "$el_dir" &
        h2=$!
        wait $h0; rc0=$?
        wait $h1; rc1=$?
        wait $h2; rc2=$?
        if [ "$rc0" -ne 0 ] || [ "$rc1" -ne 0 ] || [ "$rc2" -ne 0 ]; then
            echo "elastic drill resume-on-3 FAILED (rc=$rc0/$rc1/$rc2)" >&2
            rc=1
        else
            echo "elastic resharding drill: OK"
        fi
    fi
fi
rm -rf "$el_dir"

if [ "$rc" -eq 0 ]; then
    echo "check_resilience: OK"
else
    echo "check_resilience: FAILED (rc=$rc)" >&2
fi
exit $rc

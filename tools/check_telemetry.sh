#!/usr/bin/env bash
# Telemetry smoke (CI / pre-merge, next to check_resilience.sh): the
# telemetry unit tier, then a 20-step smoke train loop run twice —
# once with telemetry disabled (must add <1% host-loop overhead vs the
# raw step: the disabled path IS the raw step object) and once with a
# StepTimeline attached (must export well-formed Chrome-trace/perfetto
# JSON with the expected phases). Extra args pass through to pytest.
set -uo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu

rc=0

python -m pytest tests/test_telemetry.py tests/test_profiler.py "$@" -q \
    -p no:cacheprovider || rc=1

echo "== 20-step smoke loop: disabled-telemetry overhead + trace export =="
python - <<'PY' || rc=1
import json
import os
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import telemetry
from apex_tpu.optimizers import FusedAdam
from apex_tpu.optimizers.train_step import make_train_step

rng = np.random.RandomState(0)
params = {f"p{i}": jnp.asarray(rng.randn(512).astype(np.float32) * 0.02)
          for i in range(12)}
opt = FusedAdam(lr=1e-3)
state = opt.init(params)
g = jnp.asarray(rng.randn(state.space.total).astype(np.float32) * 1e-3)
host_g = np.asarray(g)

step = make_train_step(opt)
disabled = make_train_step(
    opt, telemetry=telemetry.StepTimeline(enabled=False))
# the structural guarantee behind the <1% budget: None and a disabled
# timeline return the SAME cached object — there is no instrumented
# code on the disabled path to be slow
assert disabled is step, "disabled telemetry must be the raw step object"
assert make_train_step(opt, telemetry=None) is step

STEPS = 20

def loop(s, st):
    for _ in range(STEPS):
        st, _aux = s(st, g)
    jax.block_until_ready(st.master)
    return st

state = loop(step, state)                     # compile + warm
t_raw = t_off = float("inf")
for _ in range(11):                           # interleaved best-of
    t0 = time.perf_counter()
    state = loop(step, state)
    t_raw = min(t_raw, time.perf_counter() - t0)
    t0 = time.perf_counter()
    state = loop(disabled, state)
    t_off = min(t_off, time.perf_counter() - t0)
overhead = t_off / t_raw - 1.0
print(f"raw={t_raw * 1e3:.3f}ms disabled={t_off * 1e3:.3f}ms "
      f"overhead={overhead * 100:+.3f}%")
assert overhead < 0.01, (
    f"disabled-telemetry host-loop overhead {overhead * 100:.3f}% >= 1%")

# enabled path: phase spans + a loadable Chrome-trace export
tl = telemetry.StepTimeline(capacity=1024, sync=True)
inst = make_train_step(opt, telemetry=tl)
assert inst is not step and inst._jitted is step._jitted
for _ in range(STEPS):
    with tl.step_scope():
        with tl.phase("h2d"):
            gd = jax.device_put(host_g)
            jax.block_until_ready(gd)
        state, _aux = inst(state, gd)
summ = tl.summary()
assert summ["phases"]["step"]["count"] == STEPS, summ
assert summ["phases"]["h2d"]["count"] == STEPS, summ

path = os.path.join(tempfile.mkdtemp(prefix="apex_tpu_tele_"),
                    "trace.json")
tl.export_trace(path)
with open(path) as f:
    trace = json.load(f)                      # well-formed JSON
events = trace["traceEvents"]
complete = [e for e in events if e.get("ph") == "X"]
assert {e["name"] for e in complete} >= {"h2d", "step", "host_step"}
for e in complete:
    assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
    assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
    assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
assert len(complete) == 3 * STEPS, len(complete)
print(f"perfetto trace OK: {len(complete)} complete events, "
      f"{len(events) - len(complete)} metadata rows -> {path}")
print("20-step smoke loop: OK")
PY

if [ "$rc" -eq 0 ]; then
    echo "check_telemetry: OK"
else
    echo "check_telemetry: FAILED (rc=$rc)" >&2
fi
exit $rc

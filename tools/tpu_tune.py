"""Kernel tuning sweep — run on the real chip to pick tile/block sizes.

Chained-iteration timing (see tpu_smoke._time): each candidate config
runs K iterations inside one jitted fori_loop, so per-op numbers are
kernel time, not tunnel dispatch. Prints a table per op family; the
winner feeds the defaults in the op modules.

    python tools/tpu_tune.py            # everything
    python tools/tpu_tune.py attn ln    # subset
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from tpu_smoke import _time  # noqa: E402  (chained timer)
from tpu_smoke import grad_feed as _grad_feed  # noqa: E402
from tpu_smoke import opt_feed as _opt_feed  # noqa: E402

from apex_tpu.ops.mosaic_limits import block_ok  # noqa: E402

_LINES = []
_print = print


def print(*args, **kw):  # noqa: A001 — tee stdout into the record
    _LINES.append(" ".join(str(a) for a in args))
    _print(*args, **kw)

def tune_attn():
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.attention import flash_attention

    rng = np.random.RandomState(0)
    for (b, h, s, d), dt in [((4, 16, 2048, 128), jnp.bfloat16),
                             ((2, 16, 4096, 128), jnp.bfloat16),
                             ((8, 16, 512, 64), jnp.bfloat16)]:
        q, k, v = (jnp.asarray(
            rng.randn(b, h, s, d).astype(np.float32) * 0.1, dt)
            for _ in range(3))
        print(f"flash fwd+bwd bhsd={(b, h, s, d)} {dt.__name__}")
        base = None
        for bq, bk in [(256, 256), (512, 512), (512, 1024), (1024, 512),
                       (1024, 1024), (2048, 1024), (1024, 2048)]:
            if bq > s or bk > s:
                continue
            isz = jnp.dtype(dt).itemsize
            if not (block_ok(bq, d, isz) and block_ok(bk, d, isz)):
                print(f"  bq={bq:5d} bk={bk:5d}  SKIP (Mosaic crash "
                      "region, docs/HARDWARE_NOTES.md)")
                continue

            def fwd_bwd(q, k, v, bq=bq, bk=bk):
                def loss(q, k, v):
                    o = flash_attention(q, k, v, causal=True, impl="pallas",
                                        block_q=bq, block_k=bk)
                    return jnp.sum(o.astype(jnp.float32) ** 2)
                l, g = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
                return (l, *g)

            try:
                t = _time(fwd_bwd, q, k, v, iters=3, chain=10,
                          feed=_grad_feed)
                base = base or t
                print(f"  bq={bq:5d} bk={bk:5d}  {t*1e3:8.3f} ms "
                      f"({base/t:4.2f}x)")
            except Exception as e:  # noqa: BLE001
                print(f"  bq={bq:5d} bk={bk:5d}  FAIL {str(e)[:60]}")

        def xla_fb(q, k, v):
            def loss(q, k, v):
                o = flash_attention(q, k, v, causal=True, impl="xla")
                return jnp.sum(o.astype(jnp.float32) ** 2)
            l, g = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
            return (l, *g)

        try:
            t = _time(xla_fb, q, k, v, iters=3, chain=10, feed=_grad_feed)
            print(f"  xla reference   {t*1e3:8.3f} ms")
        except Exception as e:  # noqa: BLE001
            print(f"  xla reference   FAIL {str(e)[:60]}")


def tune_attn_bwd():
    """Sweep the BACKWARD dq/dkv blocks independently of the forward's
    (fixed at the round-2 winner 1024x1024): the dq and dkv kernels have
    different reuse patterns than the fwd, so their best block shape can
    differ. Winner feeds flash_attention's bwd_block_q/bwd_block_k
    defaults."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.attention import flash_attention

    rng = np.random.RandomState(0)
    for (b, h, s, d), dt in [((4, 16, 2048, 128), jnp.bfloat16),
                             ((2, 16, 4096, 128), jnp.bfloat16)]:
        q, k, v = (jnp.asarray(
            rng.randn(b, h, s, d).astype(np.float32) * 0.1, dt)
            for _ in range(3))
        print(f"flash BWD blocks (fwd fixed 1024x1024) "
              f"bhsd={(b, h, s, d)} {dt.__name__}")
        base = None
        for bbq, bbk in [(256, 256), (512, 512), (512, 1024), (1024, 512),
                         (1024, 1024), (2048, 1024), (1024, 2048),
                         (2048, 2048), (256, 1024), (1024, 256)]:
            if bbq > s or bbk > s:
                continue
            isz = jnp.dtype(dt).itemsize
            if not (block_ok(bbq, d, isz) and block_ok(bbk, d, isz)):
                print(f"  bbq={bbq:5d} bbk={bbk:5d}  SKIP (Mosaic crash "
                      "region, docs/HARDWARE_NOTES.md)")
                continue

            def fwd_bwd(q, k, v, bbq=bbq, bbk=bbk):
                def loss(q, k, v):
                    o = flash_attention(q, k, v, causal=True,
                                        impl="pallas",
                                        block_q=1024, block_k=1024,
                                        bwd_block_q=bbq, bwd_block_k=bbk)
                    return jnp.sum(o.astype(jnp.float32) ** 2)
                l, g = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
                return (l, *g)

            try:
                t = _time(fwd_bwd, q, k, v, iters=3, chain=10,
                          feed=_grad_feed)
                base = base or t
                print(f"  bbq={bbq:5d} bbk={bbk:5d}  {t*1e3:8.3f} ms "
                      f"({base/t:4.2f}x)")
            except Exception as e:  # noqa: BLE001
                print(f"  bbq={bbq:5d} bbk={bbk:5d}  FAIL {str(e)[:60]}")


def tune_ln():
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops import layer_norm as ln_mod
    from apex_tpu.ops.layer_norm import fused_layer_norm

    rng = np.random.RandomState(0)
    rows, hidden = 8192, 4096
    x = jnp.asarray(rng.randn(rows, hidden).astype(np.float32),
                    jnp.bfloat16)
    w = jnp.asarray(rng.randn(hidden).astype(np.float32))
    b = jnp.asarray(rng.randn(hidden).astype(np.float32))

    def fwd_bwd(x, w, b, impl):
        def loss(x, w, b):
            return jnp.sum(
                fused_layer_norm(x, w, b, impl=impl).astype(jnp.float32)
                ** 2)
        l, g = jax.value_and_grad(loss, argnums=(0, 1, 2))(x, w, b)
        return (l, *g)

    print(f"layer_norm fwd+bwd rows={rows} hidden={hidden} bf16 x")
    orig = ln_mod._DEF_ROWS
    for tile_rows in (64, 128, 256, 512, 1024):
        if not block_ok(tile_rows, hidden, 2):
            print(f"  tile_rows={tile_rows:5d}  SKIP (Mosaic crash "
                  "region, docs/HARDWARE_NOTES.md)")
            continue
        ln_mod._DEF_ROWS = tile_rows
        try:
            t = _time(lambda x, w, b: fwd_bwd(x, w, b, "pallas"),
                      x, w, b, iters=3, chain=20, feed=_grad_feed)
            print(f"  tile_rows={tile_rows:5d}  {t*1e3:8.3f} ms")
        except Exception as e:  # noqa: BLE001
            print(f"  tile_rows={tile_rows:5d}  FAIL {str(e)[:60]}")
    ln_mod._DEF_ROWS = orig
    t = _time(lambda x, w, b: fwd_bwd(x, w, b, "xla"), x, w, b,
              iters=3, chain=20, feed=_grad_feed)
    print(f"  xla reference     {t*1e3:8.3f} ms")


def tune_softmax():
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.softmax import scaled_upper_triang_masked_softmax

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(32, 1024, 1024).astype(np.float32),
                    jnp.bfloat16)

    def fwd_bwd(x, impl):
        def loss(x):
            return jnp.sum(
                scaled_upper_triang_masked_softmax(x, 0.5, impl=impl)
                .astype(jnp.float32) ** 2)
        return jax.value_and_grad(loss)(x)

    print("causal softmax fwd+bwd (32,1024,1024) bf16")
    for impl in ("pallas", "xla"):
        try:
            t = _time(lambda x: fwd_bwd(x, impl), x, iters=3, chain=20,
                      feed=_grad_feed)
            print(f"  {impl:8s}  {t*1e3:8.3f} ms")
        except Exception as e:  # noqa: BLE001
            print(f"  {impl:8s}  FAIL {str(e)[:60]}")


def _sweep_tile_rows(label, step_fn, args, n, accesses_per_elem):
    """Sweep engine.DEFAULT_TILE_ROWS for one fused-update step.

    ``accesses_per_elem`` = fp32 reads+writes per element (drives the
    achieved-GB/s column; keep it in sync with the op's actual traffic).
    """
    from apex_tpu.multi_tensor import engine

    print(f"{label} n={n}")
    orig = engine.DEFAULT_TILE_ROWS
    for tile_rows in (128, 256, 512, 1024, 2048):
        if not block_ok(tile_rows, 128, 4):
            print(f"  tile_rows={tile_rows:5d}  SKIP (Mosaic crash "
                  "region, docs/HARDWARE_NOTES.md)")
            continue
        engine.DEFAULT_TILE_ROWS = tile_rows
        try:
            t = _time(step_fn, *args, iters=3, chain=5, feed=_opt_feed)
            gbps = accesses_per_elem * n * 4 / t / 1e9
            print(f"  tile_rows={tile_rows:5d}  {t*1e3:8.3f} ms "
                  f"({gbps:6.1f} GB/s)")
        except Exception as e:  # noqa: BLE001
            print(f"  tile_rows={tile_rows:5d}  FAIL {str(e)[:60]}")
    engine.DEFAULT_TILE_ROWS = orig


def tune_opt():
    import jax
    import jax.numpy as jnp

    import apex_tpu.multi_tensor as mt

    rng = np.random.RandomState(0)
    n = 64_000_000   # ~BERT-large scale flat buffer
    p = jnp.asarray(rng.randn(n).astype(np.float32))
    g = jnp.asarray(rng.randn(n).astype(np.float32) * 1e-3)
    m = jnp.zeros_like(p)
    v = jnp.zeros_like(p)

    def adam_step(p, m, v, g, impl="pallas"):
        p2, m2, v2, f = mt.fused_adam_update(
            p, m, v, g, lr=1e-3, step=2, weight_decay=0.01, impl=impl)
        return (p2, m2, v2)

    # adam: reads p/m/v/g + writes p/m/v = 7 accesses per element
    _sweep_tile_rows("fused adam update", adam_step, (p, m, v, g), n, 7)
    t = _time(lambda *a: adam_step(*a, impl="xla"), p, m, v, g,
              iters=3, chain=5, feed=_opt_feed)
    print(f"  xla reference     {t*1e3:8.3f} ms ({7*n*4/t/1e9:6.1f} GB/s)")

    # LAMB with the stage-1-fused per-tensor norm partials: sweep the
    # stage-1 tile (read via DEFAULT_TILE_ROWS at call time). Layout
    # only needs shapes/dtypes — no device zeros materialized.
    tree = {f"p{i}": jax.ShapeDtypeStruct((4096, 1024), jnp.float32)
            for i in range(16)}
    space = mt.FlatSpace.create(tree)
    pL = jnp.asarray(rng.randn(space.total).astype(np.float32))
    gL = jnp.asarray(rng.randn(space.total).astype(np.float32) * 1e-3)
    mL = jnp.zeros_like(pL)
    vL = jnp.zeros_like(pL)

    def lamb_step(p, m_, v_, g_):
        p2, m2, v2, f = mt.fused_lamb_update(
            p, m_, v_, g_, space, lr=1e-3, step=2, weight_decay=0.01,
            impl="pallas")
        return (p2, m2, v2)

    # stage 1: 4 reads + 3 writes; stage 2: 2 reads + 1 write = 10
    _sweep_tile_rows("fused lamb update (stage-1-fused norms)",
                     lamb_step, (pL, mL, vL, gL), space.total, 10)


def tune_segmented():
    """Sweep the segmented one-pass LAMB's knobs: segment size
    (VMEM-scratch bound) x scratch config (stash_p / p-stream /
    bf16-u). This is the production headline impl — its winner feeds
    flat_buffer.default_seg_elems / DEFAULT_SEG_VMEM_BUDGET."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.multi_tensor.flat_buffer import (
        default_seg_elems,
        segmented_space,
    )
    from apex_tpu.multi_tensor.segmented import (
        CHUNK,
        fused_lamb_segmented_update,
    )

    rng = np.random.RandomState(0)
    # optdiag's 41.5M-param tensor mix: many smalls + a few large leaves
    tree = {}
    for i in range(48):
        tree[f"w{i}"] = jax.ShapeDtypeStruct((512, 1024), jnp.float32)
    for i in range(8):
        tree[f"b{i}"] = jax.ShapeDtypeStruct((1024,), jnp.float32)
    for i in range(4):
        tree[f"W{i}"] = jax.ShapeDtypeStruct((4096, 1024), jnp.float32)

    est = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(tree))
    base_seg = default_seg_elems(est)
    configs = [("stash_p", dict(stash_p=True)),
               ("p-stream", dict(stash_p=False)),
               ("bf16-u", dict(stash_p=False, u_dtype=jnp.bfloat16))]
    for seg_mult in (0.5, 1.0, 2.0):
        seg = max(CHUNK, int(base_seg * seg_mult) // CHUNK * CHUNK)
        space, meta = segmented_space(tree, seg_elems=seg)
        p = jnp.asarray(rng.randn(space.total).astype(np.float32))
        g = jnp.asarray(
            rng.randn(space.total).astype(np.float32) * 1e-3)
        m = jnp.zeros_like(p)
        v = jnp.zeros_like(p)

        for label, kw in configs:
            def step(p_, m_, v_, g_, kw=kw):
                p2, m2, v2, f = fused_lamb_segmented_update(
                    p_, m_, v_, g_, space, meta, lr=1e-3, step=2,
                    weight_decay=0.01, use_nvlamb=True,
                    max_grad_norm=0.0, impl="pallas", **kw)
                return (p2, m2, v2)

            # traffic model: small segments ride the one-pass kernel
            # (7 accesses/elem, 8 with p-stream); leaves larger than a
            # segment take the two-stage path (~10). Weight by the
            # actual split so the GB/s is comparable with tune_opt's.
            acc_small = 8 if not kw.get("stash_p", True) else 7
            large_elems = sum(plen for _, _, plen in meta.large)
            small_elems = space.total - large_elems
            traffic = (acc_small * small_elems + 10 * large_elems) * 4
            try:
                t = _time(step, p, m, v, g, iters=3, chain=5,
                          feed=_opt_feed)
                gbps = traffic / t / 1e9
                print(f"  seg={seg:>9} ({seg_mult:3.1f}x) {label:9s} "
                      f"{t*1e3:8.3f} ms ({gbps:6.1f} GB/s, "
                      f"{small_elems/space.total:4.0%} one-pass)")
            except Exception as e:  # noqa: BLE001 — sweep must finish
                msg = str(e).split("\n")[0][:100]
                print(f"  seg={seg:>9} ({seg_mult:3.1f}x) {label:9s} "
                      f"FAILED {type(e).__name__}: {msg}")
        del p, g, m, v


ALL = {"attn": tune_attn, "attnbwd": tune_attn_bwd, "ln": tune_ln,
       "softmax": tune_softmax, "opt": tune_opt,
       "segmented": tune_segmented}

if __name__ == "__main__":
    import jax

    from apex_tpu.backend_guard import tpu_slot_lock

    # the tunnel serves ONE client; serialize against bench/smoke runs
    # (the lock warns on stderr itself if it can't be acquired)
    with tpu_slot_lock():
        print("backend:", jax.default_backend())
        which = sys.argv[1:] or list(ALL)
        for name in which:
            ALL[name]()
        if jax.default_backend() == "tpu":
            from apex_tpu.records import write_record

            path = write_record(
                "tune", {"modes": which, "lines": _LINES},
                backend="tpu")
            if path:
                _print(f"# record: {path}", file=sys.stderr)

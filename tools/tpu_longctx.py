"""Long-context scaling measurement — sliding-window DMA banding on chip.

The banded flash kernel walks only the k-blocks inside each query's
sliding window (apex_tpu/ops/attention.py `_band`), so fwd+bwd cost for
a fixed window should scale ~linearly in sequence length where full
causal attention scales quadratically. This records that claim on real
hardware at S = 4k/8k/16k (queued in docs/HARDWARE_NOTES.md "Pending
next chip session"); nothing in the reference reaches these lengths
(its fmha caps at seqlen 512, ref apex/contrib/fmha/fmha.py:33-74).

    python tools/tpu_longctx.py            # full sweep
    python tools/tpu_longctx.py --max-s 8192

Emits one JSON line per (S, variant) with absolute time, achieved
TFLOP/s, and the linear-scaling ratio vs the previous S.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from tpu_smoke import _time, grad_feed  # noqa: E402  (chained timer)

WINDOW = 1024


def _time_adaptive(fn, *args, target_s=2.0, max_chain=400, feed=None):
    """Chained timing sized so total wall >= ``target_s``.

    The axon tunnel's host round-trip costs ~2.5-135 ms
    (docs/HARDWARE_NOTES.md); a fixed small chain measures that floor,
    not the kernel. Estimate with a short chain, then rerun with the
    chain length that amortizes the fence below ~1% of the total.
    """
    t = _time(fn, *args, iters=1, warmup=1, chain=4, feed=feed)
    chain = int(min(max_chain, max(4, target_s / max(t, 1e-6) / 2)))
    if chain <= 4:
        return t
    return _time(fn, *args, iters=2, warmup=1, chain=chain, feed=feed)


def band_flops(b, h, s, d, window):
    """fwd matmul FLOPs of the banded computation: each query row sees
    ~min(window, its causal span) keys; fwd = 2 matmuls of 2*keys*d per
    row; fwd+bwd = 3.5x fwd (bwd recomputes scores + 5 s^2-scale
    matmuls), matching bench.py's attention accounting."""
    rows = np.arange(s, dtype=np.float64)
    keys = np.minimum(rows + 1, window).sum()
    fwd = 2 * (2 * b * h * keys * d)
    return fwd * 3.5


def causal_flops(b, h, s, d):
    fwd = 0.5 * 2 * (2 * b * h * s * s * d)
    return fwd * 3.5


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-s", type=int, default=16384)
    ap.add_argument("--causal-max-s", type=int, default=8192,
                    help="largest S to also time full-causal at (the "
                    "quadratic baseline gets slow/large fast)")
    args = ap.parse_args()

    from apex_tpu.backend_guard import tpu_slot_lock, chip_peak_tflops

    with tpu_slot_lock():
        import jax
        import jax.numpy as jnp

        from apex_tpu.ops.attention import flash_attention

        backend = jax.default_backend()
        on_cpu = backend == "cpu"
        impl = "interpret" if on_cpu else "pallas"
        peak = chip_peak_tflops(str(jax.devices()[0].device_kind)) \
            if not on_cpu else None

        b, h, d = (1, 2, 64) if on_cpu else (1, 16, 128)
        seqs = [512, 1024] if on_cpu else \
            [s for s in (4096, 8192, 16384) if s <= args.max_s]
        dt = jnp.float32 if on_cpu else jnp.bfloat16
        rng = np.random.RandomState(0)

        prev = {}
        for s in seqs:
            q, k, v = (jnp.asarray(
                rng.randn(b, h, s, d).astype(np.float32) * 0.1, dt)
                for _ in range(3))
            variants = [("window", dict(causal=True, window_size=WINDOW))]
            if s <= args.causal_max_s:
                variants.append(("causal", dict(causal=True)))
            for name, kw in variants:
                def fwd_bwd(q, k, v, kw=kw):
                    def loss(q, k, v):
                        o = flash_attention(q, k, v, impl=impl, **kw)
                        return jnp.sum(o.astype(jnp.float32) ** 2)
                    l, g = jax.value_and_grad(loss, argnums=(0, 1, 2))(
                        q, k, v)
                    return (l, *g)

                try:
                    if on_cpu:
                        t = _time(fwd_bwd, q, k, v, iters=2, warmup=1,
                                  chain=2, feed=grad_feed)
                    else:
                        t = _time_adaptive(fwd_bwd, q, k, v,
                                           feed=grad_feed)
                except Exception as e:  # noqa: BLE001
                    print(json.dumps({
                        "s": s, "variant": name, "error":
                        f"{type(e).__name__}: {str(e)[:120]}"}))
                    continue
                fl = (band_flops(b, h, s, d, WINDOW) if name == "window"
                      else causal_flops(b, h, s, d))
                tf = fl / t / 1e12
                rec = {
                    "s": s, "variant": name, "ms": round(t * 1e3, 3),
                    "tflops_per_sec": round(tf, 2),
                    "mfu": round(tf / peak, 4) if peak else None,
                    "backend": backend, "window": WINDOW,
                    "shape_bhd": [b, h, d],
                }
                if name in prev:
                    ps, pt = prev[name]
                    # window should track s (ratio ~ s/ps); causal ~ (s/ps)^2
                    rec["time_ratio_vs_prev_s"] = round(t / pt, 2)
                    rec["s_ratio"] = round(s / ps, 2)
                prev[name] = (s, t)
                print(json.dumps(rec))
                if backend == "tpu":
                    from apex_tpu.records import write_record

                    write_record("longctx", rec, backend="tpu")


if __name__ == "__main__":
    main()

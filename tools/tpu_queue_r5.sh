#!/bin/bash
# Round-5 hardware queue, health-gated — priority order from VERDICT r4:
# (1) the only must-win: prove the segmented one-pass LAMB through
#     Mosaic (all scratch configs + SR) and time it vs optax,
# (2) BERT/GPT model benches (scan_layers fix verification / bisect),
# (3) resnet + moe BASELINE rows,
# (4) re-sweep LN/engine/opt tile defaults with the fixed timer.
# Every successful measurement persists to bench_records/ so evidence
# survives a dead tunnel; the driver-format BENCH payload comes from
# bench.py at the end of the round.
set -u
cd "$(dirname "$0")/.."
INTERVAL=${INTERVAL:-480}
LOGDIR=${LOGDIR:-/tmp/tpu_queue_r5}
mkdir -p "$LOGDIR"
echo "logs -> $LOGDIR"

healthy() { timeout 240 python tools/tpu_health.py >>"$LOGDIR/health.log" 2>&1; }

run() {  # run <name> <timeout-s> <cmd...>
  local name=$1 to=$2; shift 2
  until healthy; do
    echo "chip unhealthy before $name $(date -u +%H:%M:%S); retry in ${INTERVAL}s"
    sleep "$INTERVAL"
  done
  echo "=== $name ($(date -u +%H:%M:%S)) ==="
  timeout "$to" "$@" >"$LOGDIR/$name.log" 2>&1
  local rc=$?
  tail -4 "$LOGDIR/$name.log"
  echo "--- $name rc=$rc"
}

# 1. the one job above all: does the segmented kernel lower + match?
run smoke_segmented 1200 python tools/tpu_smoke.py --only segmented
run smoke 2400 python tools/tpu_smoke.py

# 2. optimizer truth with the segmented schedule, 41.5M then 335M
run optdiag_small 2400 python tools/tpu_optdiag.py --small
run optdiag 3000 python tools/tpu_optdiag.py

# 3. driver-format bench records, headline first (segmented is the
#    production impl on tpu as of round 5)
export APEX_TPU_BENCH_PROBE_BUDGET=240
run bench_headline 2400 python bench.py
run bench_gpt      2400 python bench.py gpt
run bench_bert     2400 python bench.py bert
run bench_attn     1800 python bench.py attn
run bench_resnet   2400 python bench.py resnet
run bench_moe      1800 python bench.py moe

# 4. crasher bisection + bandwidth ladder (diagnostics if 2/3 failed)
run bisect 1800 python tools/tpu_bisect.py
run kprobe 1800 python tools/tpu_kprobe.py

# 5. re-validate tile defaults with the fixed chained timer; the
#    segmented sweep tunes the production headline impl's knobs
run tune_opt       1800 python tools/tpu_tune.py opt
run tune_segmented 1800 python tools/tpu_tune.py segmented
run tune_ln        1200 python tools/tpu_tune.py ln
run tune_attnbwd   2400 python tools/tpu_tune.py attnbwd

echo "QUEUE DONE ($(date -u +%H:%M:%S)); logs in $LOGDIR"
